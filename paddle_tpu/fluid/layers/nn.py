"""Declarative NN layers — build ops into the default main program.

Parity: reference ``python/paddle/fluid/layers/nn.py`` (146 functions; SURVEY
Appendix A). Layer functions validate args, create parameters via
LayerHelper, and append ops; all math happens in the lowered XLA program.
"""

import numpy as np

from .. import framework
from ..framework import Variable
from ..layer_helper import LayerHelper

__all__ = [
    "fc", "embedding", "conv2d", "conv3d", "conv2d_transpose", "conv3d_transpose",
    "softmax", "pool2d", "pool3d", "adaptive_pool2d", "batch_norm", "instance_norm",
    "layer_norm", "group_norm", "spectral_norm", "data_norm",
    "reduce_sum", "reduce_mean", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_all", "reduce_any", "dropout", "split", "l2_normalize", "matmul", "topk",
    "transpose", "im2sequence", "row_conv", "multiplex", "one_hot", "reshape",
    "squeeze", "unsqueeze", "lrn", "pad", "pad2d", "pad_constant_like", "label_smooth",
    "image_resize", "resize_bilinear", "resize_nearest", "resize_trilinear",
    "gather", "gather_nd", "scatter", "scatter_nd_add", "random_crop", "mean_iou",
    "relu", "selu", "log", "crop", "elu", "relu6", "pow", "stanh", "hard_sigmoid",
    "swish", "prelu", "brelu", "leaky_relu", "soft_relu", "flatten", "stack",
    "unstack", "expand", "expand_as", "scale", "elementwise_add", "elementwise_div",
    "elementwise_sub", "elementwise_mul", "elementwise_max", "elementwise_min",
    "elementwise_pow", "elementwise_mod", "elementwise_floordiv",
    "uniform_random_batch_size_like", "gaussian_random", "sampling_id",
    "gaussian_random_batch_size_like", "sum", "slice", "strided_slice", "shape",
    "rank", "size", "logical_and", "logical_or", "logical_xor", "logical_not",
    "clip", "clip_by_norm", "mean", "mul", "maxout", "space_to_depth",
    "affine_grid", "affine_channel", "hash", "grid_sampler", "log_loss",
    "add_position_encoding", "bilinear_tensor_product", "shuffle_channel",
    "temporal_shift", "pixel_shuffle", "where", "sign", "unfold", "shard_index",
    "hard_swish", "uniform_random", "gelu", "erf", "topk", "unique",
    "autoincreased_step_counter", "smooth_l1", "dice_loss", "py_func",
    "linear_chain_crf", "crf_decoding", "ctc_greedy_decoder",
    "shard_tensor", "fused_attention", "fused_attention_packed",
    "einsum",
]


def _data_type(x):
    return framework.dtype_str(x.dtype)


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None):
    """Fully-connected layer (reference ``layers/nn.py`` fc): flattens input
    to 2-D, matmuls against a (in, size) weight — MXU-friendly — adds bias,
    applies activation."""
    helper = LayerHelper("fc", **locals())
    inputs = input if isinstance(input, (list, tuple)) else [input]
    mul_results = []
    for inp in inputs:
        in_features = int(np.prod(inp.shape[num_flatten_dims:]))
        w = helper.create_parameter(param_attr, [in_features, size], _data_type(inp))
        out = helper.create_variable_for_type_inference(inp.dtype)
        helper.append_op(
            type="mul",
            inputs={"X": [inp], "Y": [w]},
            outputs={"Out": [out]},
            attrs={"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1},
        )
        mul_results.append(out)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(inputs[0].dtype)
        helper.append_op(type="sum", inputs={"X": mul_results}, outputs={"Out": [pre_bias]})
    pre_act = _append_bias(helper, pre_bias, bias_attr, dim_start=num_flatten_dims)
    return helper.append_activation(pre_act, act)


def _append_bias(helper, x, bias_attr, dim_start=1, channel_dim=None):
    if bias_attr is False:
        return x
    if channel_dim is not None:
        bias_size = [x.shape[channel_dim]] if x.shape and len(x.shape) > channel_dim else [1]
        axis = channel_dim
    else:
        bias_size = [int(np.prod(x.shape[dim_start:]))] if x.shape else [1]
        axis = dim_start
    b = helper.create_parameter(bias_attr, bias_size, _data_type(x), is_bias=True)
    if b is None:
        return x
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="elementwise_add",
        inputs={"X": [x], "Y": [b]},
        outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32",
              table_lr=0.01, table_optimizer="sgd", residence=None):
    """Embedding lookup. ``is_sparse=True`` routes onto the sparse
    embedding engine (paddle_tpu.embedding): the device tier's
    dedup-gather ``embedding_lookup`` op with a SelectedRows backward and
    fused row-sparse optimizer updates. ``residence`` picks the tier
    explicitly ("device" | "host"); by default a lookup whose param name
    has a registered ``HostEmbeddingTable`` goes to the host tier (table
    in host RAM behind a fixed HBM cache). ``is_distributed=True`` stays
    the legacy parameter-server shim."""
    helper = LayerHelper("embedding", **locals())
    if is_distributed:
        # PS tier (reference distributed_lookup_table_op.cc): the table is a
        # host-resident sharded store, NOT a device Parameter. Rows are
        # pulled via host callback; grads are pushed to the host optimizer
        # (table_lr/table_optimizer) by a distributed_push op appended in
        # append_backward. A distributed_table_init op in the STARTUP
        # program resets the host store like device params.
        from ...distributed import ps

        from .. import unique_name

        name = (param_attr.name if param_attr is not None
                and getattr(param_attr, "name", None) else
                unique_name.generate("dist_emb"))
        ps.ensure_table(name, size[0], size[1])
        helper.startup_program.global_block().append_op(
            "distributed_table_init", attrs={"table_name": name})
        out = helper.create_variable_for_type_inference(dtype)
        helper.append_op(
            type="distributed_lookup_table",
            inputs={"Ids": [input]},
            outputs={"Out": [out]},
            attrs={"table_name": name, "dim": int(size[1]),
                   "lr": float(table_lr), "optimizer": table_optimizer,
                   "padding_idx": -1 if padding_idx is None else padding_idx,
                   "dtype": dtype},
        )
        return out
    pname = (param_attr.name if param_attr is not None
             and getattr(param_attr, "name", None) else None)
    if residence not in (None, "device", "host"):
        raise ValueError(
            "embedding residence must be None, 'device' or 'host', got %r"
            % (residence,))
    if residence is None and pname is not None:
        from ... import embedding as _embedding

        if _embedding.has_host_table(pname):
            residence = "host"
    if residence == "host":
        if pname is None:
            raise ValueError(
                "residence='host' needs param_attr with a name matching a "
                "registered HostEmbeddingTable")
        from ... import embedding as _embedding
        from ...embedding.host import append_host_lookup

        return append_host_lookup(helper, input, size,
                                  _embedding.get_host_table(pname),
                                  padding_idx, dtype)
    w = helper.create_parameter(param_attr, size, dtype)
    out = helper.create_variable_for_type_inference(dtype)
    if is_sparse:
        # engine device tier: dedup-gather lookup; backward stays the
        # SelectedRows pair, the optimizer applies the fused row update
        helper.append_op(
            type="embedding_lookup",
            inputs={"W": [w], "Ids": [input]},
            outputs={"Out": [out]},
            attrs={
                "is_sparse": True,
                "dedup": True,
                "padding_idx": -1 if padding_idx is None else padding_idx,
            },
        )
        return out
    helper.append_op(
        type="lookup_table",
        inputs={"W": [w], "Ids": [input]},
        outputs={"Out": [out]},
        attrs={
            "is_sparse": is_sparse,
            "is_distributed": is_distributed,
            "padding_idx": -1 if padding_idx is None else padding_idx,
        },
    )
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCHW"):
    helper = LayerHelper("conv2d", **locals())
    groups = groups or 1
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    num_channels = (input.shape[-1] if data_format == "NHWC"
                    else input.shape[1])
    filter_shape = [num_filters, num_channels // groups] + list(filter_size)
    from ..initializer import Normal

    fan = num_channels * filter_size[0] * filter_size[1] // groups
    w = helper.create_parameter(
        param_attr, filter_shape, _data_type(input),
        default_initializer=Normal(0.0, (2.0 / fan) ** 0.5),
    )
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="conv2d",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={
            "strides": [stride, stride] if isinstance(stride, int) else list(stride),
            "paddings": [padding, padding] if isinstance(padding, int) else list(padding),
            "dilations": [dilation, dilation] if isinstance(dilation, int) else list(dilation),
            "groups": groups,
            "data_format": data_format,
        },
    )
    out = _append_bias(helper, out, bias_attr,
                       channel_dim=-1 if data_format == "NHWC" else 1)
    return helper.append_activation(out, act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("conv3d", **locals())
    groups = groups or 1
    if isinstance(filter_size, int):
        filter_size = [filter_size] * 3
    num_channels = input.shape[1]
    w = helper.create_parameter(
        param_attr, [num_filters, num_channels // groups] + list(filter_size),
        _data_type(input),
    )
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="conv3d",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={
            "strides": [stride] * 3 if isinstance(stride, int) else list(stride),
            "paddings": [padding] * 3 if isinstance(padding, int) else list(padding),
            "dilations": [dilation] * 3 if isinstance(dilation, int) else list(dilation),
            "groups": groups,
        },
    )
    out = _append_bias(helper, out, bias_attr, channel_dim=1)
    return helper.append_activation(out, act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("conv2d_transpose", **locals())
    groups = groups or 1
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    num_channels = input.shape[1]
    w = helper.create_parameter(
        param_attr, [num_channels, num_filters // groups] + list(filter_size),
        _data_type(input),
    )
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="conv2d_transpose",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={
            "strides": [stride, stride] if isinstance(stride, int) else list(stride),
            "paddings": [padding, padding] if isinstance(padding, int) else list(padding),
            "dilations": [dilation, dilation] if isinstance(dilation, int) else list(dilation),
            "groups": groups,
        },
    )
    out = _append_bias(helper, out, bias_attr, channel_dim=1)
    return helper.append_activation(out, act)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("conv3d_transpose", **locals())
    groups = groups or 1
    if isinstance(filter_size, int):
        filter_size = [filter_size] * 3
    num_channels = input.shape[1]
    w = helper.create_parameter(
        param_attr,
        [num_channels, num_filters // groups] + list(filter_size),
        _data_type(input),
    )
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="conv3d_transpose",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={
            "strides": [stride] * 3 if isinstance(stride, int) else list(stride),
            "paddings": [padding] * 3 if isinstance(padding, int) else list(padding),
            "dilations": [dilation] * 3 if isinstance(dilation, int)
            else list(dilation),
            "groups": groups,
        },
    )
    out = _append_bias(helper, out, bias_attr, channel_dim=1)
    return helper.append_activation(out, act)


def softmax(input, use_cudnn=False, name=None, axis=-1):
    helper = LayerHelper("softmax", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="softmax", inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"axis": axis})
    return out


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1, pool_padding=0,
           global_pooling=False, use_cudnn=True, ceil_mode=False, name=None,
           exclusive=True, adaptive=False, data_format="NCHW"):
    helper = LayerHelper("pool2d", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="pool2d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "pooling_type": pool_type,
            "ksize": [pool_size, pool_size] if isinstance(pool_size, int) else list(pool_size),
            "strides": [pool_stride, pool_stride] if isinstance(pool_stride, int) else list(pool_stride),
            "paddings": [pool_padding, pool_padding] if isinstance(pool_padding, int) else list(pool_padding),
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
            "adaptive": adaptive,
            "data_format": data_format,
        },
    )
    return out


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1, pool_padding=0,
           global_pooling=False, ceil_mode=False, name=None, exclusive=True):
    helper = LayerHelper("pool3d", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="pool3d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "pooling_type": pool_type,
            "ksize": [pool_size] * 3 if isinstance(pool_size, int) else list(pool_size),
            "strides": [pool_stride] * 3 if isinstance(pool_stride, int) else list(pool_stride),
            "paddings": [pool_padding] * 3 if isinstance(pool_padding, int) else list(pool_padding),
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
        },
    )
    return out


def adaptive_pool2d(input, pool_size, pool_type="max", require_index=False, name=None):
    return pool2d(input, pool_size=pool_size, pool_type=pool_type, adaptive=True)


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=False,
               use_global_stats=False):
    helper = LayerHelper("batch_norm", **locals())
    dtype = _data_type(input)
    ch = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    from ..initializer import Constant

    scale = helper.create_parameter(param_attr, [ch], dtype,
                                    default_initializer=Constant(1.0))
    bias = helper.create_parameter(bias_attr, [ch], dtype, is_bias=True)
    # running stats: persistable, non-trainable
    mean = _create_persistable_stat(helper, moving_mean_name, [ch], dtype, 0.0)
    var = _create_persistable_stat(helper, moving_variance_name, [ch], dtype, 1.0)
    out = helper.create_variable_for_type_inference(input.dtype)
    saved_mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    saved_var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op(
        type="batch_norm",
        inputs={"X": [input], "Scale": [scale], "Bias": [bias],
                "Mean": [mean], "Variance": [var]},
        outputs={"Y": [out], "MeanOut": [mean], "VarianceOut": [var],
                 "SavedMean": [saved_mean], "SavedVariance": [saved_var]},
        attrs={"momentum": momentum, "epsilon": epsilon, "is_test": is_test,
               "data_layout": data_layout, "use_global_stats": use_global_stats},
    )
    return helper.append_activation(out, act)


def _create_persistable_stat(helper, name, shape, dtype, init_val):
    from .. import unique_name as un
    from ..initializer import Constant

    name = name or un.generate(helper.name_prefix + ".stat")
    var = helper.main_program.global_block().create_var(
        name=name, shape=shape, dtype=dtype, persistable=True, stop_gradient=True
    )
    sb = helper.startup_program.global_block()
    sv = sb.create_var(name=name, shape=shape, dtype=dtype, persistable=True,
                       stop_gradient=True)
    Constant(init_val)(sv, sb)
    return var


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None, name=None):
    helper = LayerHelper("instance_norm", **locals())
    dtype = _data_type(input)
    ch = input.shape[1]
    from ..initializer import Constant

    scale = helper.create_parameter(param_attr, [ch], dtype,
                                    default_initializer=Constant(1.0))
    bias = helper.create_parameter(bias_attr, [ch], dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="instance_norm",
        inputs={"X": [input], "Scale": [scale], "Bias": [bias]},
        outputs={"Y": [out]},
        attrs={"epsilon": epsilon},
    )
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1, epsilon=1e-5,
               param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("layer_norm", **locals())
    dtype = _data_type(input)
    norm_shape = [int(np.prod(input.shape[begin_norm_axis:]))]
    from ..initializer import Constant

    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(param_attr, norm_shape, dtype,
                                    default_initializer=Constant(1.0))
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(bias_attr, norm_shape, dtype, is_bias=True)
        inputs["Bias"] = [b]
    out = helper.create_variable_for_type_inference(input.dtype)
    mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op(
        type="layer_norm",
        inputs=inputs,
        outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
        attrs={"epsilon": epsilon, "begin_norm_axis": begin_norm_axis},
    )
    return helper.append_activation(out, act)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    helper = LayerHelper("group_norm", **locals())
    dtype = _data_type(input)
    ch = input.shape[1]
    from ..initializer import Constant

    scale = helper.create_parameter(param_attr, [ch], dtype,
                                    default_initializer=Constant(1.0))
    bias = helper.create_parameter(bias_attr, [ch], dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(input.dtype)
    mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op(
        type="group_norm",
        inputs={"X": [input], "Scale": [scale], "Bias": [bias]},
        outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
        attrs={"epsilon": epsilon, "groups": groups},
    )
    return helper.append_activation(out, act)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    helper = LayerHelper("spectral_norm", **locals())
    dtype = _data_type(weight)
    h = weight.shape[dim]
    w = int(np.prod(weight.shape)) // h
    from ..initializer import Normal

    u = helper.create_parameter(None, [h], dtype, default_initializer=Normal(0.0, 1.0))
    v = helper.create_parameter(None, [w], dtype, default_initializer=Normal(0.0, 1.0))
    u.stop_gradient = True
    v.stop_gradient = True
    out = helper.create_variable_for_type_inference(weight.dtype)
    helper.append_op(
        type="spectral_norm",
        inputs={"Weight": [weight], "U": [u], "V": [v]},
        outputs={"Out": [out]},
        attrs={"dim": dim, "power_iters": power_iters, "eps": eps},
    )
    return out


def data_norm(input, act=None, epsilon=1e-5, param_attr=None, name=None):
    helper = LayerHelper("data_norm", **locals())
    dtype = _data_type(input)
    ch = input.shape[-1]
    from ..initializer import Constant

    batch_size = _create_persistable_stat(helper, None, [ch], dtype, 1e4)
    batch_sum = _create_persistable_stat(helper, None, [ch], dtype, 0.0)
    batch_square = _create_persistable_stat(helper, None, [ch], dtype, 1e4)
    out = helper.create_variable_for_type_inference(input.dtype)
    means = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    scales = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op(
        type="data_norm",
        inputs={"X": [input], "BatchSize": [batch_size], "BatchSum": [batch_sum],
                "BatchSquareSum": [batch_square]},
        outputs={"Y": [out], "Means": [means], "Scales": [scales]},
        attrs={"epsilon": epsilon},
    )
    return helper.append_activation(out, act)


def _reduce_layer(op_type, input, dim=None, keep_dim=False, name=None):
    helper = LayerHelper(op_type, **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    if dim is None:
        attrs = {"reduce_all": True, "dim": [0], "keep_dim": keep_dim}
    else:
        attrs = {"reduce_all": False,
                 "dim": dim if isinstance(dim, (list, tuple)) else [dim],
                 "keep_dim": keep_dim}
    helper.append_op(type=op_type, inputs={"X": [input]}, outputs={"Out": [out]}, attrs=attrs)
    return out


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer("reduce_sum", input, dim, keep_dim, name)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer("reduce_mean", input, dim, keep_dim, name)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer("reduce_max", input, dim, keep_dim, name)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer("reduce_min", input, dim, keep_dim, name)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer("reduce_prod", input, dim, keep_dim, name)


def reduce_all(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer("reduce_all", input, dim, keep_dim, name)


def reduce_any(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer("reduce_any", input, dim, keep_dim, name)


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    helper = LayerHelper("dropout", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    mask = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        type="dropout",
        inputs={"X": [x]},
        outputs={"Out": [out], "Mask": [mask]},
        attrs={"dropout_prob": dropout_prob, "is_test": is_test,
               "dropout_implementation": dropout_implementation},
    )
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", **locals())
    dim = dim if dim >= 0 else dim + len(input.shape)
    if isinstance(num_or_sections, int):
        n = num_or_sections
        attrs = {"num": n, "sections": [], "axis": dim}
    else:
        n = len(num_or_sections)
        attrs = {"num": 0, "sections": list(num_or_sections), "axis": dim}
    outs = [helper.create_variable_for_type_inference(input.dtype) for _ in range(n)]
    helper.append_op(type="split", inputs={"X": [input]}, outputs={"Out": outs}, attrs=attrs)
    return outs


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    norm = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="l2_normalize",
        inputs={"X": [x]},
        outputs={"Out": [out], "Norm": [norm]},
        attrs={"axis": axis, "epsilon": epsilon},
    )
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="matmul",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"transpose_X": transpose_x, "transpose_Y": transpose_y,
               "alpha": float(alpha)},
    )
    return out


def topk(input, k, name=None):
    helper = LayerHelper("top_k", **locals())
    values = helper.create_variable_for_type_inference(input.dtype)
    indices = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="top_k",
        inputs={"X": [input]},
        outputs={"Out": [values], "Indices": [indices]},
        attrs={"k": k},
    )
    return values, indices


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="transpose", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"axis": list(perm)})
    return out


def im2sequence(input, filter_size=1, stride=1, padding=0, input_image_size=None,
                out_stride=1, name=None):
    helper = LayerHelper("im2sequence", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="im2sequence",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "kernels": [filter_size, filter_size] if isinstance(filter_size, int) else list(filter_size),
            "strides": [stride, stride] if isinstance(stride, int) else list(stride),
            "paddings": [padding] * 4 if isinstance(padding, int) else list(padding),
        },
    )
    return out


def row_conv(input, future_context_size, param_attr=None, act=None):
    helper = LayerHelper("row_conv", **locals())
    w = helper.create_parameter(
        param_attr, [future_context_size + 1, input.shape[-1]], _data_type(input)
    )
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="row_conv", inputs={"X": [input], "Filter": [w]},
                     outputs={"Out": [out]})
    return helper.append_activation(out, act)


def multiplex(inputs, index):
    helper = LayerHelper("multiplex", **locals())
    out = helper.create_variable_for_type_inference(inputs[0].dtype)
    helper.append_op(type="multiplex", inputs={"X": inputs, "Ids": [index]},
                     outputs={"Out": [out]})
    return out


def one_hot(input, depth, allow_out_of_range=False):
    helper = LayerHelper("one_hot", **locals())
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(type="one_hot", inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"depth": depth})
    return out


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="reshape", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"shape": list(shape)})
    return helper.append_activation(out, act)


def squeeze(input, axes, name=None):
    helper = LayerHelper("squeeze", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="squeeze", inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"axes": list(axes)})
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="unsqueeze", inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"axes": list(axes)})
    return out


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="lrn", inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"n": n, "k": k, "alpha": alpha, "beta": beta})
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="pad", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"paddings": list(paddings), "pad_value": float(pad_value)})
    return out


def pad2d(input, paddings=[0, 0, 0, 0], mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    helper = LayerHelper("pad2d", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="pad2d", inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"paddings": list(paddings), "mode": mode,
                            "pad_value": float(pad_value)})
    return out


def pad_constant_like(x, y, pad_value=0.0, name=None):
    helper = LayerHelper("pad_constant_like", **locals())
    out = helper.create_variable_for_type_inference(y.dtype)
    helper.append_op(type="pad_constant_like", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]}, attrs={"pad_value": float(pad_value)})
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32", name=None):
    helper = LayerHelper("label_smooth", **locals())
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="label_smooth", inputs={"X": [label]}, outputs={"Out": [out]},
                     attrs={"epsilon": float(epsilon)})
    return out


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR", actual_shape=None, align_corners=True,
                 align_mode=1):
    op_type = {"BILINEAR": "bilinear_interp", "NEAREST": "nearest_interp",
               "TRILINEAR": "trilinear_interp"}[resample]
    helper = LayerHelper(op_type, **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    attrs = {"align_corners": align_corners, "align_mode": align_mode}
    if out_shape is not None:
        if op_type == "trilinear_interp":
            attrs["out_d"], attrs["out_h"], attrs["out_w"] = out_shape
        else:
            attrs["out_h"], attrs["out_w"] = out_shape
    if scale is not None:
        attrs["scale"] = float(scale)
    helper.append_op(type=op_type, inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs=attrs)
    return out


def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    actual_shape=None, align_corners=True, align_mode=1):
    return image_resize(input, out_shape, scale, name, "BILINEAR", actual_shape,
                        align_corners, align_mode)


def resize_nearest(input, out_shape=None, scale=None, name=None,
                   actual_shape=None, align_corners=True):
    return image_resize(input, out_shape, scale, name, "NEAREST", actual_shape,
                        align_corners)


def resize_trilinear(input, out_shape=None, scale=None, name=None,
                     actual_shape=None, align_corners=True, align_mode=1):
    return image_resize(input, out_shape, scale, name, "TRILINEAR", actual_shape,
                        align_corners, align_mode)


def gather(input, index, overwrite=True):
    helper = LayerHelper("gather", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="gather", inputs={"X": [input], "Index": [index]},
                     outputs={"Out": [out]})
    return out


def gather_nd(input, index, name=None):
    helper = LayerHelper("gather_nd", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="gather_nd", inputs={"X": [input], "Index": [index]},
                     outputs={"Out": [out]})
    return out


def scatter(input, index, updates, name=None, overwrite=True):
    helper = LayerHelper("scatter", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="scatter",
                     inputs={"X": [input], "Ids": [index], "Updates": [updates]},
                     outputs={"Out": [out]}, attrs={"overwrite": overwrite})
    return out


def scatter_nd_add(ref, index, updates, name=None):
    helper = LayerHelper("scatter_nd_add", **locals())
    out = helper.create_variable_for_type_inference(ref.dtype)
    helper.append_op(type="scatter_nd_add",
                     inputs={"X": [ref], "Index": [index], "Updates": [updates]},
                     outputs={"Out": [out]})
    return out


def random_crop(x, shape, seed=None):
    helper = LayerHelper("random_crop", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="random_crop", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"shape": list(shape)})
    return out


def mean_iou(input, label, num_classes):
    helper = LayerHelper("mean_iou", **locals())
    iou = helper.create_variable_for_type_inference("float32")
    wrong = helper.create_variable_for_type_inference("int32")
    correct = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="mean_iou",
        inputs={"Predictions": [input], "Labels": [label]},
        outputs={"OutMeanIou": [iou], "OutWrong": [wrong], "OutCorrect": [correct]},
        attrs={"num_classes": num_classes},
    )
    return iou, wrong, correct


def _unary_layer(op_type, x, name=None, **attrs):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type=op_type, inputs={"X": [x]}, outputs={"Out": [out]}, attrs=attrs)
    return out


def relu(x, name=None):
    return _unary_layer("relu", x, name)


def selu(x, scale=None, alpha=None, name=None):
    attrs = {}
    if scale is not None:
        attrs["scale"] = scale
    if alpha is not None:
        attrs["alpha"] = alpha
    return _unary_layer("selu", x, name, **attrs)


def log(x, name=None):
    return _unary_layer("log", x, name)


def crop(x, shape=None, offsets=None, name=None):
    helper = LayerHelper("crop", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    if isinstance(shape, Variable):
        raise NotImplementedError("dynamic crop shape unsupported (XLA static shapes)")
    offsets = offsets or [0] * len(x.shape)
    helper.append_op(
        type="slice",
        inputs={"Input": [x]},
        outputs={"Out": [out]},
        attrs={"axes": list(range(len(x.shape))),
               "starts": list(offsets),
               "ends": [o + s for o, s in zip(offsets, shape)]},
    )
    return out


crop_tensor = crop


def elu(x, alpha=1.0, name=None):
    return _unary_layer("elu", x, name, alpha=alpha)


def relu6(x, threshold=6.0, name=None):
    return _unary_layer("relu6", x, name, threshold=threshold)


def pow(x, factor=1.0, name=None):
    return _unary_layer("pow", x, name, factor=factor)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _unary_layer("stanh", x, name, scale_a=scale_a, scale_b=scale_b)


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    return _unary_layer("hard_sigmoid", x, name, slope=slope, offset=offset)


def swish(x, beta=1.0, name=None):
    return _unary_layer("swish", x, name, beta=beta)


def prelu(x, mode, param_attr=None, name=None):
    helper = LayerHelper("prelu", **locals())
    alpha_shape = [1]
    if mode == "channel":
        alpha_shape = [x.shape[1]]
    elif mode == "element":
        alpha_shape = list(x.shape[1:])
    from ..initializer import Constant

    alpha = helper.create_parameter(param_attr, alpha_shape, _data_type(x),
                                    default_initializer=Constant(0.25))
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="prelu", inputs={"X": [x], "Alpha": [alpha]},
                     outputs={"Out": [out]}, attrs={"mode": mode})
    return out


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    return _unary_layer("brelu", x, name, t_min=t_min, t_max=t_max)


def leaky_relu(x, alpha=0.02, name=None):
    return _unary_layer("leaky_relu", x, name, alpha=alpha)


def soft_relu(x, threshold=40.0, name=None):
    return _unary_layer("soft_relu", x, name, threshold=threshold)


def gelu(x, approximate=False, name=None):
    return _unary_layer("gelu", x, name, approximate=approximate)


def erf(x, name=None):
    return _unary_layer("erf", x, name)


def flatten(x, axis=1, name=None):
    helper = LayerHelper("flatten", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="flatten", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"axis": axis})
    return out


def stack(x, axis=0):
    helper = LayerHelper("stack", **locals())
    x = x if isinstance(x, (list, tuple)) else [x]
    out = helper.create_variable_for_type_inference(x[0].dtype)
    helper.append_op(type="stack", inputs={"X": x}, outputs={"Y": [out]},
                     attrs={"axis": axis})
    return out


def unstack(x, axis=0, num=None):
    helper = LayerHelper("unstack", **locals())
    num = num or x.shape[axis]
    outs = [helper.create_variable_for_type_inference(x.dtype) for _ in range(num)]
    helper.append_op(type="unstack", inputs={"X": [x]}, outputs={"Y": outs},
                     attrs={"axis": axis})
    return outs


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="expand", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"expand_times": list(expand_times)})
    return out


def expand_as(x, target_tensor, name=None):
    helper = LayerHelper("expand_as", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="expand_as",
                     inputs={"X": [x], "target_tensor": [target_tensor]},
                     outputs={"Out": [out]})
    return out


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="scale", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"scale": float(scale), "bias": float(bias),
                            "bias_after_scale": bias_after_scale})
    return helper.append_activation(out, act)


def _elementwise_layer(op_type, x, y, axis=-1, act=None, name=None):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return helper.append_activation(out, act)


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return _elementwise_layer("elementwise_add", x, y, axis, act, name)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return _elementwise_layer("elementwise_sub", x, y, axis, act, name)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return _elementwise_layer("elementwise_mul", x, y, axis, act, name)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return _elementwise_layer("elementwise_div", x, y, axis, act, name)


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return _elementwise_layer("elementwise_max", x, y, axis, act, name)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return _elementwise_layer("elementwise_min", x, y, axis, act, name)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return _elementwise_layer("elementwise_pow", x, y, axis, act, name)


def elementwise_mod(x, y, axis=-1, act=None, name=None):
    return _elementwise_layer("elementwise_mod", x, y, axis, act, name)


def elementwise_floordiv(x, y, axis=-1, act=None, name=None):
    return _elementwise_layer("elementwise_floordiv", x, y, axis, act, name)


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random", **locals())
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="uniform_random", outputs={"Out": [out]},
                     attrs={"shape": list(shape), "dtype": dtype, "min": min,
                            "max": max, "seed": seed})
    return out


def uniform_random_batch_size_like(input, shape, dtype="float32",
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random_batch_size_like", **locals())
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="uniform_random_batch_size_like",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": dtype, "min": min, "max": max,
               "input_dim_idx": input_dim_idx, "output_dim_idx": output_dim_idx},
    )
    return out


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random", **locals())
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="gaussian_random", outputs={"Out": [out]},
                     attrs={"shape": list(shape), "dtype": dtype, "mean": mean,
                            "std": std, "seed": seed})
    return out


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random_batch_size_like", **locals())
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="gaussian_random_batch_size_like",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": dtype, "mean": mean, "std": std,
               "input_dim_idx": input_dim_idx, "output_dim_idx": output_dim_idx},
    )
    return out


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("sampling_id", **locals())
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(type="sampling_id", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def sum(x):
    helper = LayerHelper("sum", **locals())
    x = x if isinstance(x, (list, tuple)) else [x]
    out = helper.create_variable_for_type_inference(x[0].dtype)
    helper.append_op(type="sum", inputs={"X": x}, outputs={"Out": [out]})
    return out


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="slice", inputs={"Input": [input]}, outputs={"Out": [out]},
                     attrs={"axes": list(axes), "starts": list(starts),
                            "ends": list(ends)})
    return out


def strided_slice(input, axes, starts, ends, strides):
    helper = LayerHelper("strided_slice", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="strided_slice", inputs={"Input": [input]},
                     outputs={"Out": [out]},
                     attrs={"axes": list(axes), "starts": list(starts),
                            "ends": list(ends), "strides": list(strides)})
    return out


def shape(input):
    helper = LayerHelper("shape", **locals())
    out = helper.create_variable_for_type_inference("int32")
    helper.append_op(type="shape", inputs={"Input": [input]}, outputs={"Out": [out]})
    return out


def rank(input):
    from .tensor import fill_constant

    return fill_constant([1], "int32", len(input.shape))


def size(input):
    from .tensor import fill_constant

    return fill_constant([1], "int64", int(np.prod(input.shape)))


def _logical_layer(op_type, x, y=None, out=None, name=None):
    helper = LayerHelper(op_type, name=name)
    if out is None:
        out = helper.create_variable_for_type_inference("bool")
    inputs = {"X": [x]}
    if y is not None:
        inputs["Y"] = [y]
    helper.append_op(type=op_type, inputs=inputs, outputs={"Out": [out]})
    return out


def logical_and(x, y, out=None, name=None):
    return _logical_layer("logical_and", x, y, out, name)


def logical_or(x, y, out=None, name=None):
    return _logical_layer("logical_or", x, y, out, name)


def logical_xor(x, y, out=None, name=None):
    return _logical_layer("logical_xor", x, y, out, name)


def logical_not(x, out=None, name=None):
    return _logical_layer("logical_not", x, None, out, name)


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="clip", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"min": float(min), "max": float(max)})
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="clip_by_norm", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"max_norm": float(max_norm)})
    return out


def mean(x, name=None):
    helper = LayerHelper("mean", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="mean", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="mul", inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]},
                     attrs={"x_num_col_dims": x_num_col_dims,
                            "y_num_col_dims": y_num_col_dims})
    return out


def maxout(x, groups, name=None):
    helper = LayerHelper("maxout", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="maxout", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"groups": groups})
    return out


def space_to_depth(x, blocksize, name=None):
    helper = LayerHelper("space_to_depth", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="space_to_depth", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"blocksize": blocksize})
    return out


def affine_grid(theta, out_shape, name=None):
    helper = LayerHelper("affine_grid", **locals())
    out = helper.create_variable_for_type_inference(theta.dtype)
    inputs = {"Theta": [theta]}
    attrs = {}
    if isinstance(out_shape, Variable):
        inputs["OutputShape"] = [out_shape]
    else:
        attrs["output_shape"] = list(out_shape)
    helper.append_op(type="affine_grid", inputs=inputs, outputs={"Output": [out]},
                     attrs=attrs)
    return out


def affine_channel(x, scale=None, bias=None, data_layout="NCHW", name=None,
                   act=None):
    helper = LayerHelper("affine_channel", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="affine_channel",
                     inputs={"X": [x], "Scale": [scale], "Bias": [bias]},
                     outputs={"Out": [out]})
    return helper.append_activation(out, act)


def hash(input, hash_size, num_hash=1, name=None):
    helper = LayerHelper("hash", **locals())
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(type="hash", inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"mod_by": hash_size, "num_hash": num_hash})
    return out


def grid_sampler(x, grid, name=None):
    helper = LayerHelper("grid_sampler", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="grid_sampler", inputs={"X": [x], "Grid": [grid]},
                     outputs={"Output": [out]})
    return out


def log_loss(input, label, epsilon=1e-4, name=None):
    helper = LayerHelper("log_loss", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="log_loss",
                     inputs={"Predicted": [input], "Labels": [label]},
                     outputs={"Loss": [out]}, attrs={"epsilon": epsilon})
    return out


def add_position_encoding(input, alpha, beta, name=None):
    helper = LayerHelper("add_position_encoding", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="add_position_encoding", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs={"alpha": alpha, "beta": beta})
    return out


def bilinear_tensor_product(x, y, size, act=None, name=None, param_attr=None,
                            bias_attr=None):
    helper = LayerHelper("bilinear_tensor_product", **locals())
    w = helper.create_parameter(param_attr, [size, x.shape[1], y.shape[1]],
                                _data_type(x))
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x], "Y": [y], "Weight": [w]}
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, [1, size], _data_type(x), is_bias=True)
        inputs["Bias"] = [b]
    helper.append_op(type="bilinear_tensor_product", inputs=inputs,
                     outputs={"Out": [out]})
    return helper.append_activation(out, act)


def shuffle_channel(x, group, name=None):
    helper = LayerHelper("shuffle_channel", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="shuffle_channel", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"group": group})
    return out


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    helper = LayerHelper("temporal_shift", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="temporal_shift", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"seg_num": seg_num, "shift_ratio": shift_ratio})
    return out


def pixel_shuffle(x, upscale_factor):
    helper = LayerHelper("pixel_shuffle", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="pixel_shuffle", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"upscale_factor": upscale_factor})
    return out


def where(condition):
    """Returns indices of true elements — dynamic output; trace-time only."""
    raise NotImplementedError(
        "where(condition) has a dynamic output shape; use layers.cond or "
        "masked arithmetic instead (XLA requires static shapes)"
    )


def sign(x):
    return _unary_layer("sign", x)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    helper = LayerHelper("unfold", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="unfold",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={
            "kernel_sizes": kernel_sizes if isinstance(kernel_sizes, list) else [kernel_sizes] * 2,
            "strides": strides if isinstance(strides, list) else [strides] * 2,
            "paddings": paddings if isinstance(paddings, list) else [paddings] * 4,
            "dilations": dilations if isinstance(dilations, list) else [dilations] * 2,
        },
    )
    return out


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    helper = LayerHelper("shard_index", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="shard_index", inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"index_num": index_num, "nshards": nshards,
                            "shard_id": shard_id, "ignore_value": ignore_value})
    return out


def hard_swish(x, threshold=6.0, scale=6.0, offset=3.0, name=None):
    return _unary_layer("hard_swish", x, name, threshold=threshold, scale=scale,
                        offset=offset)


def unique(x, dtype="int32"):
    helper = LayerHelper("unique", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    index = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="unique", inputs={"X": [x]},
                     outputs={"Out": [out], "Index": [index]})
    return out, index


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    helper = LayerHelper("global_step_counter")
    name = counter_name or "@STEP_COUNTER@"
    counter = helper.main_program.global_block().create_var(
        name=name, shape=(1,), dtype="int64", persistable=True, stop_gradient=True
    )
    sb = helper.startup_program.global_block()
    sv = sb.create_var(name=name, shape=(1,), dtype="int64", persistable=True)
    from ..initializer import Constant

    Constant(begin - step)(sv, sb)
    helper.append_op(type="increment", inputs={"X": [counter]},
                     outputs={"Out": [counter]}, attrs={"step": float(step)})
    counter.stop_gradient = True
    return counter


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1_loss", **locals())
    diff = helper.create_variable_for_type_inference(x.dtype)
    loss = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x], "Y": [y]}
    if inside_weight is not None:
        inputs["InsideWeight"] = [inside_weight]
    if outside_weight is not None:
        inputs["OutsideWeight"] = [outside_weight]
    helper.append_op(type="smooth_l1_loss", inputs=inputs,
                     outputs={"Diff": [diff], "Out": [loss]},
                     attrs={"sigma": sigma or 1.0})
    return loss


def dice_loss(input, label, epsilon=1e-5):
    from . import tensor as t

    label = one_hot(label, depth=input.shape[-1])
    reduce_dims = list(range(1, len(input.shape)))
    inse = reduce_sum(input * label, dim=reduce_dims)
    dice_denominator = reduce_sum(input, dim=reduce_dims) + reduce_sum(
        label, dim=reduce_dims
    )
    dice_score = 1 - inse * 2 / (dice_denominator + epsilon)
    return reduce_mean(dice_score)


_PYFUNC_TABLE = {}


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host-Python op with optional custom backward (reference
    ``operators/py_func_op.cc`` / ``layers/nn.py`` py_func). ``func``
    maps numpy inputs to numpy outputs matching ``out``'s declared
    shapes/dtypes (out vars must carry static shapes — create them with
    ``program.current_block().create_var(...)``); ``backward_func``
    receives (x..., out..., dout...) minus ``skip_vars_in_backward_input``
    and returns grads for each x (None for non-differentiable inputs).
    Lowering: ``jax.pure_callback`` forward wrapped in ``jax.custom_vjp``
    whose backward is a second host callback — the same mechanism the
    distributed_lookup_table lowerings use (ops/distributed_ops.py).
    Callables live in an in-process table keyed by an op attr; a Program
    serialized via proto_io keeps the op but needs the same Python
    process (or re-registration) to execute it — host code cannot ride
    the proto, exactly like the reference's pybind-registered callables."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    skip = set(id(v) for v in (skip_vars_in_backward_input or []))
    for o in outs:
        if o.shape is None or any(int(s) < 0 for s in o.shape):
            raise ValueError(
                "py_func out var %r needs a fully static shape" % o.name)
    func_id = len(_PYFUNC_TABLE)
    _PYFUNC_TABLE[func_id] = (
        func, backward_func,
        [id(v) in skip for v in xs],       # skip flags for x slots
        [id(v) in skip for v in outs],     # skip flags for out slots
    )
    helper = LayerHelper("py_func")
    helper.append_op(
        type="py_func",
        inputs={"X": list(xs)},
        outputs={"Out": list(outs)},
        attrs={"func_id": func_id,
               "out_shapes": [[int(s) for s in o.shape] for o in outs],
               "out_dtypes": [str(o.dtype) for o in outs]})
    return out


# -- extra ops used by models ------------------------------------------------

def _register_extra_ops():
    from ..registry import register as reg

    @reg("add_position_encoding")
    def _ape(ctx, op):
        import jax.numpy as jnp

        x = ctx.get_input(op, "X")  # (B, T, D)
        alpha = op.attr("alpha", 1.0)
        beta = op.attr("beta", 1.0)
        b, t, d = x.shape
        half = d // 2
        pos = jnp.arange(t, dtype=x.dtype)[:, None]
        div = jnp.power(10000.0, jnp.arange(half, dtype=x.dtype) / half)
        enc = jnp.concatenate([jnp.sin(pos / div), jnp.cos(pos / div)], axis=1)
        ctx.set_output(op, "Out", alpha * x + beta * enc[None, :, :])

    @reg("hash")
    def _hash(ctx, op):
        import jax.numpy as jnp

        x = ctx.get_input(op, "X").astype(jnp.uint32)
        mod_by = op.attr("mod_by")
        num_hash = op.attr("num_hash", 1)
        outs = []
        for i in range(num_hash):
            h = (x * jnp.uint32(2654435761) + jnp.uint32(i * 97)) % jnp.uint32(mod_by)
            outs.append(h)
        out = jnp.stack(outs, axis=-2) if num_hash > 1 else outs[0]
        ctx.set_output(op, "Out", out.astype(jnp.int64))

    @reg("shard_index")
    def _shard_index(ctx, op):
        import jax.numpy as jnp

        x = ctx.get_input(op, "X")
        index_num = op.attr("index_num")
        nshards = op.attr("nshards")
        shard_id = op.attr("shard_id")
        ignore = op.attr("ignore_value", -1)
        shard_size = (index_num + nshards - 1) // nshards
        in_shard = (x // shard_size) == shard_id
        ctx.set_output(op, "Out", jnp.where(in_shard, x % shard_size, ignore))

    @reg("random_crop", has_state=True)
    def _random_crop(ctx, op):
        import jax

        x = ctx.get_input(op, "X")
        shape = op.attr("shape")
        starts = []
        key = ctx.next_rng()
        keys = jax.random.split(key, len(shape))
        ndim = x.ndim
        offs = []
        for i, target in enumerate(shape):
            dim = ndim - len(shape) + i
            max_off = x.shape[dim] - target
            off = jax.random.randint(keys[i], (), 0, max_off + 1)
            offs.append(off)
        start_indices = [0] * (ndim - len(shape)) + offs
        sizes = list(x.shape[: ndim - len(shape)]) + list(shape)
        out = jax.lax.dynamic_slice(x, start_indices, sizes)
        ctx.set_output(op, "Out", out)


_register_extra_ops()


def linear_chain_crf(input, label, param_attr=None, length=None):
    """CRF negative-log-likelihood layer (reference layers/nn.py
    linear_chain_crf / linear_chain_crf_op.cc). Returns per-sequence
    log-likelihood; transition param rows 0/1 are start/end weights."""
    helper = LayerHelper("linear_chain_crf", **locals())
    num_tags = int(input.shape[-1])
    trans = helper.create_parameter(param_attr, [num_tags + 2, num_tags],
                                    "float32")
    ll = helper.create_variable_for_type_inference("float32")
    alpha = helper.create_variable_for_type_inference("float32")
    eexp = helper.create_variable_for_type_inference("float32")
    texp = helper.create_variable_for_type_inference("float32")
    ll.shape = (-1, 1)
    helper.append_op(
        type="linear_chain_crf",
        inputs={"Emission": [input], "Transition": [trans],
                "Label": [label]},
        outputs={"LogLikelihood": [ll], "Alpha": [alpha],
                 "EmissionExps": [eexp], "TransitionExps": [texp]})
    return ll


def crf_decoding(input, param_attr, label=None, length=None):
    """Viterbi decode with a trained CRF transition (reference
    crf_decoding_op.cc). ``param_attr`` must name the transition param
    created by linear_chain_crf."""
    helper = LayerHelper("crf_decoding", **locals())
    name = param_attr.name if hasattr(param_attr, "name") else str(param_attr)
    block = helper.main_program.global_block()
    if block._find_var_recursive(name) is not None:
        trans = block.var(name)
    else:
        # standalone decode program: declare the named transition param so
        # it resolves from scope (trained by linear_chain_crf elsewhere)
        num_tags = int(input.shape[-1])
        trans = helper.create_parameter(param_attr,
                                        [num_tags + 2, num_tags], "float32")
    path = helper.create_variable_for_type_inference("int64")
    path.shape = (-1, 1)
    path.lod_level = 1
    helper.append_op(
        type="crf_decoding",
        inputs={"Emission": [input], "Transition": [trans]},
        outputs={"ViterbiPath": [path]})
    return path


def ctc_greedy_decoder(input, blank, name=None):
    """Greedy CTC decode: per-step argmax, collapse repeats, drop blanks
    (reference ctc_greedy_decoder = top_k + ctc_align)."""
    helper = LayerHelper("ctc_greedy_decoder", **locals())
    idx = helper.create_variable_for_type_inference("int64")
    idx.shape = (-1, 1)
    idx.lod_level = 1
    helper.append_op(type="arg_max", inputs={"X": [input]},
                     outputs={"Out": [idx]},
                     attrs={"axis": -1, "keepdims": True})
    out = helper.create_variable_for_type_inference("int64")
    out.shape = (-1, 1)
    out.lod_level = 1
    helper.append_op(type="ctc_align", inputs={"Input": [idx]},
                     outputs={"Output": [out]},
                     attrs={"blank": int(blank)})
    return out


def shard_tensor(x, spec, name=None):
    """Annotate an activation with a mesh layout (TPU-native analogue of
    the reference's manual collective placement): ``spec`` is one mesh
    axis name (or None) per dim, e.g. ["dp", None, "sp"] shards batch over
    dp and sequence over sp. Lowering: lax.with_sharding_constraint."""
    helper = LayerHelper("shard_tensor", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = tuple(x.shape)
    helper.append_op(type="shard_tensor", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"spec": ["" if s is None else str(s)
                                     for s in spec]})
    return out


def fused_attention(q, k, v, attn_bias=None, scale=None, dropout_prob=0.0,
                    is_test=False, name=None):
    """Fused softmax(q·kᵀ·scale + bias)·v over [B, H, S, d] heads — a
    single Pallas TPU kernel per (batch, head) with in-kernel dropout;
    falls back to the unfused jnp math off-TPU (kernels/attention.py)."""
    helper = LayerHelper("fused_multihead_attention", **locals())
    out = helper.create_variable_for_type_inference(q.dtype)
    inputs = {"Q": [q], "K": [k], "V": [v]}
    if attn_bias is not None:
        inputs["Bias"] = [attn_bias]
    attrs = {"dropout_prob": float(dropout_prob), "is_test": is_test}
    if scale is not None:
        attrs["scale"] = float(scale)
    helper.append_op(type="fused_multihead_attention", inputs=inputs,
                     outputs={"Out": [out]}, attrs=attrs)
    return out


def einsum(equation, *operands, name=None):
    """Tensor contraction by equation (``paddle.einsum`` capability,
    lowered to jnp.einsum — XLA chooses fused layouts, so e.g. attention
    scores contract straight out of the [B, S, H, d] projection layout
    with no materialized transpose)."""
    helper = LayerHelper("einsum", name=name)
    out = helper.create_variable_for_type_inference(operands[0].dtype)
    helper.append_op(type="einsum",
                     inputs={"Operands": list(operands)},
                     outputs={"Out": [out]},
                     attrs={"equation": equation})
    return out


def fused_attention_packed(q, k, v, n_heads, attn_bias=None, scale=None,
                           dropout_prob=0.0, is_test=False, name=None):
    """Multi-head attention on PACKED [B, S, H*d] q/k/v — consumes the
    QKV projections' native layout so the graph carries no head
    split/merge transposes (those layout copies dominate small-S
    attention cost); heads are strided inside one Pallas kernel per
    batch block (kernels/attention.py packed tier). Returns
    [B, S, H*d]."""
    helper = LayerHelper("fused_multihead_attention_packed", **locals())
    out = helper.create_variable_for_type_inference(q.dtype)
    inputs = {"Q": [q], "K": [k], "V": [v]}
    if attn_bias is not None:
        inputs["Bias"] = [attn_bias]
    attrs = {"dropout_prob": float(dropout_prob), "is_test": is_test,
             "n_heads": int(n_heads)}
    if scale is not None:
        attrs["scale"] = float(scale)
    helper.append_op(type="fused_multihead_attention_packed",
                     inputs=inputs, outputs={"Out": [out]}, attrs=attrs)
    return out


def kv_cache_update(cache, new, cache_len, name=None):
    """Write ``new`` [B, H, T, d] into the KV ring buffer ``cache``
    [B, H, C, d] at per-sequence slot ``cache_len % C``; returns
    ``(updated_cache, cache_len + T)``. A single write must not cross
    the ring boundary (T=1 decode always holds; prefill needs prompt
    length <= C). See kernels/attention.py kv_cache_update."""
    helper = LayerHelper("kv_cache_update", name=name)
    out = helper.create_variable_for_type_inference(cache.dtype)
    out_len = helper.create_variable_for_type_inference("int32")
    helper.append_op(type="kv_cache_update",
                     inputs={"Cache": [cache], "New": [new],
                             "CacheLen": [cache_len]},
                     outputs={"Out": [out], "OutLen": [out_len]})
    return out, out_len


def fused_attention_cache(q, k_cache, v_cache, cache_len, scale=None,
                          name=None):
    """Decode-step attention of q [B, H, Q, d] against a KV ring buffer
    [B, H, C, d] with per-sequence valid lengths ``cache_len`` [B]
    (post-update token counts). Dispatches to the Pallas decode tier at
    large capacities, masked-length fp32 fallback otherwise
    (kernels/attention.py attention_with_cache). Inference-only: no
    gradient."""
    helper = LayerHelper("fused_multihead_attention_cache", name=name)
    out = helper.create_variable_for_type_inference(q.dtype)
    attrs = {}
    if scale is not None:
        attrs["scale"] = float(scale)
    helper.append_op(type="fused_multihead_attention_cache",
                     inputs={"Q": [q], "KCache": [k_cache],
                             "VCache": [v_cache], "CacheLen": [cache_len]},
                     outputs={"Out": [out]}, attrs=attrs)
    return out
