"""Loss layers (reference ``layers/loss.py``)."""

from ..layer_helper import LayerHelper

__all__ = [
    "center_loss", "bpr_loss", "cross_entropy", "square_error_cost",
    "softmax_with_cross_entropy", "rank_loss", "margin_rank_loss",
    "sigmoid_cross_entropy_with_logits", "teacher_student_sigmoid_loss",
    "huber_loss", "kldiv_loss", "npair_loss", "mse_loss", "hinge_loss",
]


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="cross_entropy",
                     inputs={"X": [input], "Label": [label]},
                     outputs={"Y": [out]},
                     attrs={"soft_label": soft_label, "ignore_index": ignore_index})
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    helper = LayerHelper("softmax_with_cross_entropy", **locals())
    softmax = helper.create_variable_for_type_inference(logits.dtype)
    loss = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op(
        type="softmax_with_cross_entropy",
        inputs={"Logits": [logits], "Label": [label]},
        outputs={"Softmax": [softmax], "Loss": [loss]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index, "axis": axis},
    )
    if return_softmax:
        return loss, softmax
    return loss


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="square_error_cost",
                     inputs={"X": [input], "Y": [label]}, outputs={"Out": [out]})
    return out


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, name=None,
                                      normalize=False):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="sigmoid_cross_entropy_with_logits",
        inputs={"X": [x], "Label": [label]},
        outputs={"Out": [out]},
        attrs={"ignore_index": ignore_index, "normalize": normalize},
    )
    return out


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    residual = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="huber_loss", inputs={"X": [input], "Y": [label]},
                     outputs={"Out": [out], "Residual": [residual]},
                     attrs={"delta": float(delta)})
    return out


def kldiv_loss(x, target, reduction="mean", name=None):
    helper = LayerHelper("kldiv_loss", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="kldiv_loss", inputs={"X": [x], "Target": [target]},
                     outputs={"Loss": [out]}, attrs={"reduction": reduction})
    return out


def bpr_loss(input, label, name=None):
    helper = LayerHelper("bpr_loss", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="bpr_loss", inputs={"X": [input], "Label": [label]},
                     outputs={"Y": [out]})
    return out


def rank_loss(label, left, right, name=None):
    helper = LayerHelper("rank_loss", **locals())
    out = helper.create_variable_for_type_inference(left.dtype)
    helper.append_op(type="rank_loss",
                     inputs={"Label": [label], "Left": [left], "Right": [right]},
                     outputs={"Out": [out]})
    return out


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    helper = LayerHelper("margin_rank_loss", **locals())
    out = helper.create_variable_for_type_inference(left.dtype)
    act = helper.create_variable_for_type_inference(left.dtype)
    helper.append_op(type="margin_rank_loss",
                     inputs={"Label": [label], "X1": [left], "X2": [right]},
                     outputs={"Out": [out], "Activated": [act]},
                     attrs={"margin": float(margin)})
    return out


def hinge_loss(input, label, name=None):
    helper = LayerHelper("hinge_loss", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="hinge_loss",
                     inputs={"Logits": [input], "Labels": [label]},
                     outputs={"Loss": [out]})
    return out


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    helper = LayerHelper("teacher_student_sigmoid_loss", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="teacher_student_sigmoid_loss",
                     inputs={"X": [input], "Label": [label]},
                     outputs={"Y": [out]})
    return out


def center_loss(input, label, num_classes, alpha, param_attr=None,
                update_center=True):
    helper = LayerHelper("center_loss", **locals())
    from ..initializer import Constant
    from .tensor import fill_constant

    dtype = "float32"
    centers = helper.create_parameter(param_attr, [num_classes, input.shape[1]],
                                      dtype, default_initializer=Constant(0.0))
    centers.stop_gradient = True
    alpha_var = fill_constant([1], dtype, alpha)
    loss = helper.create_variable_for_type_inference(dtype)
    diff = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="center_loss",
        inputs={"X": [input], "Label": [label], "Centers": [centers],
                "CenterUpdateRate": [alpha_var]},
        outputs={"Loss": [loss], "SampleCenterDiff": [diff], "CentersOut": [centers]},
        attrs={"need_update": update_center},
    )
    return loss


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    helper = LayerHelper("npair_loss", **locals())
    out = helper.create_variable_for_type_inference(anchor.dtype)
    helper.append_op(type="npair_loss",
                     inputs={"Anchor": [anchor], "Positive": [positive],
                             "Labels": [labels]},
                     outputs={"Out": [out]}, attrs={"l2_reg": l2_reg})
    return out


def mse_loss(input, label):
    helper = LayerHelper("mse_loss", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="mse_loss", inputs={"X": [input], "Y": [label]},
                     outputs={"Out": [out]})
    return out
