"""Loss layers (reference ``layers/loss.py``)."""

from ..layer_helper import LayerHelper

__all__ = [
    "center_loss", "bpr_loss", "cross_entropy", "square_error_cost",
    "softmax_with_cross_entropy", "rank_loss", "margin_rank_loss",
    "sigmoid_cross_entropy_with_logits", "teacher_student_sigmoid_loss",
    "huber_loss", "kldiv_loss", "npair_loss", "mse_loss", "hinge_loss",
    "warpctc", "edit_distance", "nce", "hsigmoid",
    "sampled_softmax_with_cross_entropy",
]


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="cross_entropy",
                     inputs={"X": [input], "Label": [label]},
                     outputs={"Y": [out]},
                     attrs={"soft_label": soft_label, "ignore_index": ignore_index})
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    helper = LayerHelper("softmax_with_cross_entropy", **locals())
    softmax = helper.create_variable_for_type_inference(logits.dtype)
    loss = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op(
        type="softmax_with_cross_entropy",
        inputs={"Logits": [logits], "Label": [label]},
        outputs={"Softmax": [softmax], "Loss": [loss]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index, "axis": axis},
    )
    if return_softmax:
        return loss, softmax
    return loss


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="square_error_cost",
                     inputs={"X": [input], "Y": [label]}, outputs={"Out": [out]})
    return out


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, name=None,
                                      normalize=False):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="sigmoid_cross_entropy_with_logits",
        inputs={"X": [x], "Label": [label]},
        outputs={"Out": [out]},
        attrs={"ignore_index": ignore_index, "normalize": normalize},
    )
    return out


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    residual = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="huber_loss", inputs={"X": [input], "Y": [label]},
                     outputs={"Out": [out], "Residual": [residual]},
                     attrs={"delta": float(delta)})
    return out


def kldiv_loss(x, target, reduction="mean", name=None):
    helper = LayerHelper("kldiv_loss", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="kldiv_loss", inputs={"X": [x], "Target": [target]},
                     outputs={"Loss": [out]}, attrs={"reduction": reduction})
    return out


def bpr_loss(input, label, name=None):
    helper = LayerHelper("bpr_loss", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="bpr_loss", inputs={"X": [input], "Label": [label]},
                     outputs={"Y": [out]})
    return out


def rank_loss(label, left, right, name=None):
    helper = LayerHelper("rank_loss", **locals())
    out = helper.create_variable_for_type_inference(left.dtype)
    helper.append_op(type="rank_loss",
                     inputs={"Label": [label], "Left": [left], "Right": [right]},
                     outputs={"Out": [out]})
    return out


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    helper = LayerHelper("margin_rank_loss", **locals())
    out = helper.create_variable_for_type_inference(left.dtype)
    act = helper.create_variable_for_type_inference(left.dtype)
    helper.append_op(type="margin_rank_loss",
                     inputs={"Label": [label], "X1": [left], "X2": [right]},
                     outputs={"Out": [out], "Activated": [act]},
                     attrs={"margin": float(margin)})
    return out


def hinge_loss(input, label, name=None):
    helper = LayerHelper("hinge_loss", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="hinge_loss",
                     inputs={"Logits": [input], "Labels": [label]},
                     outputs={"Loss": [out]})
    return out


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    helper = LayerHelper("teacher_student_sigmoid_loss", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="teacher_student_sigmoid_loss",
                     inputs={"X": [input], "Label": [label]},
                     outputs={"Y": [out]})
    return out


def center_loss(input, label, num_classes, alpha, param_attr=None,
                update_center=True):
    helper = LayerHelper("center_loss", **locals())
    from ..initializer import Constant
    from .tensor import fill_constant

    dtype = "float32"
    centers = helper.create_parameter(param_attr, [num_classes, input.shape[1]],
                                      dtype, default_initializer=Constant(0.0))
    centers.stop_gradient = True
    alpha_var = fill_constant([1], dtype, alpha)
    loss = helper.create_variable_for_type_inference(dtype)
    diff = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="center_loss",
        inputs={"X": [input], "Label": [label], "Centers": [centers],
                "CenterUpdateRate": [alpha_var]},
        outputs={"Loss": [loss], "SampleCenterDiff": [diff], "CentersOut": [centers]},
        attrs={"need_update": update_center},
    )
    return loss


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    helper = LayerHelper("npair_loss", **locals())
    out = helper.create_variable_for_type_inference(anchor.dtype)
    helper.append_op(type="npair_loss",
                     inputs={"Anchor": [anchor], "Positive": [positive],
                             "Labels": [labels]},
                     outputs={"Out": [out]}, attrs={"l2_reg": l2_reg})
    return out


def mse_loss(input, label):
    helper = LayerHelper("mse_loss", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="mse_loss", inputs={"X": [input], "Y": [label]},
                     outputs={"Out": [out]})
    return out


def warpctc(input, label, blank=0, norm_by_times=False,
            input_length=None, label_length=None):
    """CTC loss over bounded-LoD logits/labels (reference warpctc_op.cc,
    lowered to optax.ctc_loss — see ops/structured_loss_ops.py)."""
    helper = LayerHelper("warpctc", **locals())
    loss = helper.create_variable_for_type_inference("float32")
    grad = helper.create_variable_for_type_inference("float32")
    loss.shape = (-1, 1)
    inputs = {"Logits": [input], "Label": [label]}
    padded = input_length is not None and label_length is not None
    if padded:
        # padded-tensor API: Logits [B, T, V], Label [B, N] + lengths
        inputs["LogitsLength"] = [input_length]
        inputs["LabelLength"] = [label_length]
    helper.append_op(
        type="warpctc", inputs=inputs,
        outputs={"Loss": [loss], "WarpCTCGrad": [grad]},
        attrs={"blank": int(blank), "norm_by_times": bool(norm_by_times),
               "padded": padded})
    return loss


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    """Levenshtein distance per sequence pair (reference
    edit_distance_op.cc). ``ignored_tokens`` are erased first."""
    from . import sequence_lod

    padded = input_length is not None and label_length is not None
    if ignored_tokens:
        if padded:
            raise NotImplementedError(
                "ignored_tokens with the padded API is not supported")
        input = sequence_lod.sequence_erase(input, ignored_tokens)
        label = sequence_lod.sequence_erase(label, ignored_tokens)
    helper = LayerHelper("edit_distance", **locals())
    out = helper.create_variable_for_type_inference("float32")
    seq_num = helper.create_variable_for_type_inference("int64")
    out.shape = (-1, 1)
    inputs = {"Hyps": [input], "Refs": [label]}
    if padded:
        inputs["HypsLength"] = [input_length]
        inputs["RefsLength"] = [label_length]
    helper.append_op(
        type="edit_distance", inputs=inputs,
        outputs={"Out": [out], "SequenceNum": [seq_num]},
        attrs={"normalized": bool(normalized), "padded": padded})
    return out, seq_num


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=10, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation (reference nce_op.cc). TPU path
    samples uniformly from the threaded PRNG; other samplers are not
    implemented."""
    if sampler != "uniform" or custom_dist is not None:
        raise NotImplementedError(
            "nce on TPU supports sampler='uniform' only (got %r)" % sampler)
    if sample_weight is not None:
        raise NotImplementedError("nce sample_weight is not supported")
    helper = LayerHelper("nce", **locals())
    dim = int(input.shape[-1])
    w = helper.create_parameter(param_attr, [num_total_classes, dim],
                                "float32")
    b = helper.create_parameter(bias_attr, [num_total_classes],
                                "float32", is_bias=True)
    cost = helper.create_variable_for_type_inference("float32")
    slog = helper.create_variable_for_type_inference("float32")
    slab = helper.create_variable_for_type_inference("int64")
    cost.shape = (-1, 1)
    inputs = {"Input": [input], "Label": [label], "Weight": [w]}
    if b is not None:
        inputs["Bias"] = [b]
    helper.append_op(
        type="nce", inputs=inputs,
        outputs={"Cost": [cost], "SampleLogits": [slog],
                 "SampleLabels": [slab]},
        attrs={"num_total_classes": int(num_total_classes),
               "num_neg_samples": int(num_neg_samples)})
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, path_table=None, path_code=None,
             is_custom=False, is_sparse=False):
    """Hierarchical sigmoid over the complete binary tree (reference
    hierarchical_sigmoid_op.cc); custom trees are not supported on TPU —
    the default heap coding covers the reference's main mode."""
    if is_custom or path_table is not None:
        raise NotImplementedError(
            "hsigmoid custom trees (path_table/path_code) not supported")
    helper = LayerHelper("hsigmoid", **locals())
    dim = int(input.shape[-1])
    w = helper.create_parameter(param_attr, [num_classes - 1, dim],
                                "float32")
    b = helper.create_parameter(bias_attr, [num_classes - 1, 1], "float32",
                                is_bias=True)
    out = helper.create_variable_for_type_inference("float32")
    pre = helper.create_variable_for_type_inference("float32")
    out.shape = (-1, 1)
    inputs = {"X": [input], "Label": [label], "W": [w]}
    if b is not None:
        inputs["Bias"] = [b]
    helper.append_op(
        type="hierarchical_sigmoid", inputs=inputs,
        outputs={"Out": [out], "PreOut": [pre]},
        attrs={"num_classes": int(num_classes)})
    return out


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       num_true=1, remove_accidental_hits=True,
                                       use_customized_samples=False,
                                       customized_samples=None,
                                       customized_probabilities=None,
                                       seed=0):
    """Softmax CE over {true, sampled} classes with logQ correction
    (reference sample_logits_op.cc Python wrapper). TPU path: uniform
    proposal, accidental hits always masked."""
    if use_customized_samples or customized_samples is not None:
        raise NotImplementedError(
            "sampled_softmax customized samples are not supported on TPU")
    if num_true != 1:
        raise NotImplementedError("num_true != 1 is not supported")
    if not remove_accidental_hits:
        raise NotImplementedError(
            "remove_accidental_hits=False is not supported (hits are "
            "always masked)")
    helper = LayerHelper("sampled_softmax", **locals())
    loss = helper.create_variable_for_type_inference("float32")
    samples = helper.create_variable_for_type_inference("int64")
    loss.shape = (-1, 1)
    helper.append_op(
        type="sampled_softmax_with_cross_entropy",
        inputs={"Logits": [logits], "Label": [label]},
        outputs={"Loss": [loss], "Samples": [samples]},
        attrs={"num_samples": int(num_samples)})
    return loss
