"""py_reader — the reference's in-graph feeding queue
(``layers/io.py:py_reader`` / ``create_py_reader_by_data`` +
``reader_op_registry``): the training loop runs ``reader.start()`` then
``exe.run(program)`` WITHOUT a feed until ``core.EOFException``.

TPU-native redesign: the reference's C++ blocking queue + read op become
a host-side Python queue the EXECUTOR drains — ``exe.run`` pulls the
next batch before dispatching the step and injects it through the
normal feed path, so the dequeue op lowers to identity, works under
GSPMD/shard_map unchanged, donation stays on, and EOF raises
``fluid.core.EOFException`` BEFORE any step runs (no sentinel step to
discard). Shapes/dtypes are declared up front (XLA needs static
shapes); ``reset()`` re-arms the queue for the next epoch.
``DataLoader.from_generator`` (fluid/reader.py) remains the recommended
API — this exists so reference py_reader training loops run unchanged.
"""

import logging
import time as _time
import weakref

import numpy as np

from .. import monitor as _monitor
from ..layer_helper import LayerHelper

_LOG = logging.getLogger(__name__)

_M_BATCHES = _monitor.counter(
    "py_reader_batches_total",
    help="batches the executor pulled from py_reader queues")
_M_EOF = _monitor.counter(
    "py_reader_eof_total", help="end-of-pass events (EOFException raised)")
_M_FEED_SECONDS = _monitor.histogram(
    "py_reader_feed_seconds",
    help="host time to pull + normalize one py_reader batch")

__all__ = ["py_reader", "create_py_reader_by_data", "read_file",
           "double_buffer"]


class _PyReader:
    """Host-side state: the provider function and the live iterator."""

    def __init__(self, names, shapes, dtypes):
        self.names = list(names)
        self.shapes = [tuple(int(d) for d in s) for s in shapes]
        self.dtypes = [np.dtype(d) for d in dtypes]
        self._provider = None
        self._it = None
        # batches consumed since start() — checkpointed by
        # fluid.io.CheckpointManager so a resumed run can fast-forward
        # the provider to the batch after the checkpoint
        self._pos = 0
        self._resume_to = 0

    # -- decoration (reference py_reader surface) -------------------------
    def decorate_paddle_reader(self, reader, places=None):
        """reader() yields per-sample tuples; samples are batched by the
        caller's reader decorators (fluid.io.batch), so each yielded item
        here is one BATCH (list of sample tuples) or an ndarray tuple."""
        self._provider = reader
        return self

    decorate_sample_list_generator = decorate_paddle_reader

    def decorate_tensor_provider(self, reader, places=None):
        self._provider = reader
        return self

    decorate_batch_generator = decorate_tensor_provider

    # -- run control -------------------------------------------------------
    def start(self):
        if self._provider is None:
            raise RuntimeError(
                "py_reader.start(): decorate a reader first "
                "(decorate_paddle_reader / decorate_tensor_provider)")
        self._it = iter(self._provider())
        self._pos = 0
        if self._resume_to:
            # checkpoint resume: burn the batches the checkpointed run
            # already consumed this pass, so training continues with the
            # batch AFTER the checkpoint (requires a deterministic
            # provider, which resumable pipelines need anyway)
            skip, self._resume_to = self._resume_to, 0
            for _ in range(skip):
                if self._next() is None:
                    break

    def reset(self):
        self._it = None
        self._pos = 0
        self._resume_to = 0

    @property
    def position(self):
        """Batches consumed since start() (the checkpointed cursor)."""
        return self._pos

    def resume_at(self, n):
        """Arm a fast-forward: the next start() skips the first ``n``
        batches. Applied immediately when the pass is already live."""
        n = int(n)
        if n < 0:
            raise ValueError("resume_at: n must be >= 0, got %d" % n)
        if self._it is not None:
            while self._pos < n:
                if self._next() is None:
                    break
        else:
            self._resume_to = n

    def _to_arrays(self, item):
        if isinstance(item, dict):
            vals = [item[n] for n in self.names]
        else:
            vals = list(item)
        if vals and not isinstance(vals[0], np.ndarray) \
                and isinstance(vals[0], (list, tuple)):
            # a batch of per-sample tuples -> stack per slot
            vals = [np.stack([np.asarray(s[i]) for s in vals])
                    for i in range(len(self.names))]
        out = []
        for v, dt, shp in zip(vals, self.dtypes, self.shapes):
            a = np.ascontiguousarray(np.asarray(v, dtype=dt))
            if a.shape == shp:
                pass
            elif a.shape[0] == shp[0] and a.size == int(np.prod(shp)):
                a = a.reshape(shp)        # e.g. (B,) label -> (B, 1)
            elif 0 < a.shape[0] < shp[0] and \
                    a.size == a.shape[0] * int(np.prod(shp[1:])):
                # a trailing PARTIAL batch (paddle.batch
                # drop_last=False) cannot fill the declared static
                # shape: drop it, like drop_last, and end the pass
                _LOG.warning(
                    "py_reader: dropping a partial final batch of shape "
                    "%s (declared %s) — use fluid.io.batch(..., "
                    "drop_last=True) to silence", a.shape, shp)
                raise StopIteration
            else:
                raise ValueError(
                    "py_reader batch shape %s does not match the "
                    "declared slot shape %s" % (a.shape, shp))
            out.append(a)
        return tuple(out)

    def _next(self):
        """Called by Executor.run BEFORE dispatching the step; returns
        the batch, or None at end-of-pass (the executor then raises
        core.EOFException without running anything)."""
        if self._it is None:
            raise RuntimeError("py_reader: call start() before exe.run()")
        t0 = _time.perf_counter()
        try:
            # _to_arrays raises StopIteration itself on a partial final
            # batch (drop_last semantics)
            out = self._to_arrays(next(self._it))
        except StopIteration:
            _M_EOF.inc()
            return None
        _M_FEED_SECONDS.observe(_time.perf_counter() - t0)
        _M_BATCHES.inc()
        self._pos += 1
        return out


def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    """Reference ``layers/io.py`` py_reader: declares the queue and
    returns the reader object; ``read_file(reader)`` yields the data
    vars. ``capacity``/``use_double_buffer`` are accepted for parity —
    buffering is the XLA async-dispatch pipeline's job here. Batch dims
    must be static (XLA), so pass concrete shapes."""
    for s in shapes:
        if any(int(d) < 0 for d in s):
            raise ValueError(
                "py_reader shapes must be fully static (XLA), got %r — "
                "pass the concrete batch size (fluid.layers.data vars "
                "prepend -1; build with append_batch_size=False)"
                % (list(s),))
    helper = LayerHelper(name or "py_reader")
    prefix = helper.name_prefix
    names = ["%s.slot%d" % (prefix, i) for i in range(len(shapes))]
    reader = _PyReader(names, shapes, dtypes)
    blk = helper.main_program.current_block()
    out_vars = []
    for n, s, d in zip(names, reader.shapes, reader.dtypes):
        out_vars.append(blk.create_var(name=n, shape=s, dtype=str(d)))
    blk.append_op(
        "py_reader_dequeue", inputs={},
        outputs={"Out": out_vars},
        attrs={"reader_id": _register(reader),
               "shapes": [list(s) for s in reader.shapes],
               "dtypes": [str(d) for d in reader.dtypes]})
    reader._out_vars = out_vars
    return reader


def create_py_reader_by_data(capacity, feed_list, name=None,
                             use_double_buffer=True):
    """Reference variant taking data vars instead of shapes."""
    return py_reader(capacity,
                     shapes=[v.shape for v in feed_list],
                     dtypes=[v.dtype for v in feed_list],
                     name=name, use_double_buffer=use_double_buffer)


def read_file(reader):
    """Reference ``layers/io.py`` read_file: the data vars the dequeue op
    produces (one per declared slot)."""
    vs = reader._out_vars
    return vs[0] if len(vs) == 1 else vs


def double_buffer(reader, place=None, name=None):
    """Buffering is the runtime's (async dispatch + DataLoader staging);
    identity for parity."""
    return reader


# -- lowering ----------------------------------------------------------------

# weak registry: the program only records the id, the USER's reader
# object keeps the entry alive — dropping the reader frees its provider,
# iterator, and any cached trace values
_READERS = weakref.WeakValueDictionary()
_NEXT_ID = [0]


def _register(reader):
    rid = _NEXT_ID[0]
    _NEXT_ID[0] += 1
    _READERS[rid] = reader
    return rid


def _register_dequeue_op():
    from ..registry import register

    @register("py_reader_dequeue")
    def _dequeue(ctx, op):
        # Executor.run already injected this step's batch into the env
        # under the slot names (identical to the out var names) — the op
        # is an identity marker binding them as this op's outputs. The
        # autodiff replay re-lowers it against the same env values, so
        # no batch is ever consumed twice.
        for n in op.output("Out"):
            ctx.set(n, ctx.get(n))


_register_dequeue_op()
