"""Sequence (LoD) layers — reference ``python/paddle/fluid/layers/
sequence_lod.py`` (16 public fns). Each appends one sequence op whose
TPU-native lowering does static-shape segment arithmetic over bounded-LoD
pairs (``fluid/ops/sequence_ops.py``; design in ``fluid/lod.py``).
"""

import numpy as np

from ..layer_helper import LayerHelper

__all__ = [
    "sequence_conv", "sequence_softmax", "sequence_pool", "sequence_concat",
    "sequence_first_step", "sequence_last_step", "sequence_slice",
    "sequence_expand", "sequence_expand_as", "sequence_pad", "sequence_unpad",
    "sequence_reshape", "sequence_scatter", "sequence_enumerate",
    "sequence_mask", "sequence_reverse", "sequence_erase",
]


def _out(helper, x, dtype=None, lod_level=1, shape=None):
    v = helper.create_variable_for_type_inference(dtype or x.dtype)
    v.lod_level = lod_level
    # static shapes are set here, not via eval_shape: sequence lowerings
    # need an @LOD binding that does not exist at build time
    v.shape = tuple(shape) if shape is not None else \
        (-1,) + tuple(x.shape[1:] if len(x.shape) > 1 else ())
    return v


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, name=None):
    helper = LayerHelper("sequence_conv", **locals())
    d = int(np.prod([s for s in input.shape[1:]])) if len(input.shape) > 1 else 1
    filter_shape = [filter_size * d, num_filters]
    w = helper.create_parameter(param_attr, filter_shape, input.dtype)
    out = _out(helper, input, shape=(-1, num_filters))
    if padding_start is None:
        padding_start = -int(filter_size // 2)
    helper.append_op(
        type="sequence_conv",
        inputs={"X": [input], "Filter": [w]},
        outputs={"Out": [out]},
        attrs={"contextStart": int(padding_start),
               "contextLength": int(filter_size),
               "contextStride": int(filter_stride)})
    b = helper.create_parameter(bias_attr, [num_filters], input.dtype,
                                is_bias=True)
    if b is not None:
        tmp = _out(helper, input, shape=(-1, num_filters))
        helper.append_op(type="elementwise_add",
                         inputs={"X": [out], "Y": [b]},
                         outputs={"Out": [tmp]}, attrs={"axis": -1})
        out = tmp
    return helper.append_activation(out, act)


def sequence_softmax(input, use_cudnn=False, name=None):
    helper = LayerHelper("sequence_softmax", **locals())
    out = _out(helper, input)
    helper.append_op(type="sequence_softmax", inputs={"X": [input]},
                     outputs={"Out": [out]})
    return out


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0):
    helper = LayerHelper("sequence_pool", **locals())
    out = _out(helper, input, lod_level=0)
    max_index = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="sequence_pool",
        inputs={"X": [input]},
        outputs={"Out": [out], "MaxIndex": [max_index]},
        attrs={"pooltype": pool_type.upper(), "is_test": is_test,
               "pad_value": float(pad_value)})
    return out


def sequence_first_step(input):
    return sequence_pool(input, "first")


def sequence_last_step(input):
    return sequence_pool(input, "last")


def sequence_concat(input, name=None):
    helper = LayerHelper("sequence_concat", **locals())
    out = _out(helper, input[0])
    helper.append_op(type="sequence_concat",
                     inputs={"X": [x for x in input]},
                     outputs={"Out": [out]})
    return out


def sequence_slice(input, offset, length, name=None):
    helper = LayerHelper("sequence_slice", **locals())
    out = _out(helper, input)
    helper.append_op(
        type="sequence_slice",
        inputs={"X": [input], "Offset": [offset], "Length": [length]},
        outputs={"Out": [out]})
    return out


def sequence_expand(x, y, ref_level=-1, name=None):
    helper = LayerHelper("sequence_expand", **locals())
    out = _out(helper, x)
    helper.append_op(type="sequence_expand", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"ref_level": int(ref_level)})
    return out


def sequence_expand_as(x, y, name=None):
    helper = LayerHelper("sequence_expand_as", **locals())
    out = _out(helper, x)
    helper.append_op(type="sequence_expand_as", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    return out


def sequence_pad(x, pad_value, maxlen=None, name=None):
    helper = LayerHelper("sequence_pad", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    length = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="sequence_pad",
        inputs={"X": [x], "PadValue": [pad_value]},
        outputs={"Out": [out], "Length": [length]},
        attrs={"padded_length": -1 if maxlen is None else int(maxlen)})
    return out, length


def sequence_unpad(x, length, name=None):
    helper = LayerHelper("sequence_unpad", **locals())
    out = _out(helper, x)
    helper.append_op(type="sequence_unpad",
                     inputs={"X": [x], "Length": [length]},
                     outputs={"Out": [out]})
    return out


def sequence_reshape(input, new_dim):
    helper = LayerHelper("sequence_reshape", **locals())
    out = _out(helper, input, shape=(-1, int(new_dim)))
    helper.append_op(type="sequence_reshape", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"new_dim": int(new_dim)})
    return out


def sequence_scatter(input, index, updates, name=None):
    helper = LayerHelper("sequence_scatter", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="sequence_scatter",
        inputs={"X": [input], "Ids": [index], "Updates": [updates]},
        outputs={"Out": [out]})
    return out


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    helper = LayerHelper("sequence_enumerate", **locals())
    out = _out(helper, input, dtype=input.dtype)
    helper.append_op(type="sequence_enumerate", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"win_size": int(win_size),
                            "pad_value": pad_value})
    return out


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    helper = LayerHelper("sequence_mask", **locals())
    out = helper.create_variable_for_type_inference(dtype)
    inputs = {"X": [x]}
    attrs = {"out_dtype": dtype}
    if maxlen is not None and hasattr(maxlen, "name"):
        inputs["MaxLenTensor"] = [maxlen]
        attrs["maxlen"] = -1
    else:
        attrs["maxlen"] = -1 if maxlen is None else int(maxlen)
    helper.append_op(type="sequence_mask", inputs=inputs,
                     outputs={"Out": [out]}, attrs=attrs)
    return out


def sequence_reverse(x, name=None):
    helper = LayerHelper("sequence_reverse", **locals())
    out = _out(helper, x)
    helper.append_op(type="sequence_reverse", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


def sequence_erase(x, tokens, name=None):
    helper = LayerHelper("sequence_erase", **locals())
    out = _out(helper, x)
    helper.append_op(type="sequence_erase", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"tokens": list(tokens)})
    return out
