"""Unique name generation for IR variables and parameters.

Capability parity: reference ``python/paddle/fluid/unique_name.py`` — a
per-prefix counter with nestable guards so cloned programs can re-generate
identical names.
"""

import contextlib
import threading


class UniqueNameGenerator:
    def __init__(self, prefix=""):
        self.prefix = prefix
        self.ids = {}

    def __call__(self, key):
        if key not in self.ids:
            self.ids[key] = 0
        else:
            self.ids[key] += 1
        return "%s%s_%d" % (self.prefix, key, self.ids[key])


_local = threading.local()


def _generator():
    if not hasattr(_local, "generator"):
        _local.generator = UniqueNameGenerator()
    return _local.generator


def generate(key):
    return _generator()(key)


def switch(new_generator=None):
    old = _generator()
    _local.generator = new_generator or UniqueNameGenerator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    if isinstance(new_generator, str):
        new_generator = UniqueNameGenerator(new_generator)
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
