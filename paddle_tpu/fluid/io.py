"""Checkpointing & model export.

Parity: reference ``python/paddle/fluid/io.py`` — ``save_params:273`` /
``save_persistables:523`` / ``load_persistables:801`` /
``save_inference_model:1011`` / ``load_inference_model:1215`` and the
unified ``save:1493``/``load:1547``.

Storage format: one file per var (like the reference's per-var ``save`` op
files) or a combined ``.npz``; the program goes as protobuf (``__model__``).
"""

import os

import numpy as np

from . import framework
from .executor import global_scope
from .framework import Program, Variable

from ..reader.decorator import batch, shuffle  # noqa: F401  (io.batch parity)

__all__ = [
    "batch", "shuffle",
    "save_vars", "save_params", "save_persistables", "load_vars",
    "load_params", "load_persistables", "save_inference_model",
    "load_inference_model", "save", "load",
]


def _is_persistable(var):
    return var.persistable


def _is_param(var):
    return isinstance(var, framework.Parameter)


def save_vars(executor, dirname, main_program=None, vars=None, predicate=None,
              filename=None):
    main_program = main_program or framework.default_main_program()
    if vars is None:
        vars = [v for v in main_program.list_vars() if predicate(v)]
    scope = global_scope()
    os.makedirs(dirname, exist_ok=True)
    if filename is not None:
        from .core import tensor_io

        arrays = {}
        for v in vars:
            val = scope.find_var(v.name)
            if val is not None:
                arrays[v.name] = np.asarray(val)
        tensor_io.save_combine(os.path.join(dirname, filename), arrays)
    else:
        for v in vars:
            val = scope.find_var(v.name)
            if val is not None:
                np.save(os.path.join(dirname, v.name + ".npy"), np.asarray(val))


def _load_combined(path):
    """Read a combined tensor file: PTC1 (native serde) or legacy npz."""
    with open(path, "rb") as f:
        magic = f.read(4)
    if magic == b"PTC1":
        from .core import tensor_io

        return tensor_io.load_combine(path)
    data = np.load(path, allow_pickle=False)
    return {name: data[name] for name in data.files}


def save_params(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program, predicate=_is_param,
                     filename=filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program,
                     predicate=_is_persistable, filename=filename)


def load_vars(executor, dirname, main_program=None, vars=None, predicate=None,
              filename=None):
    main_program = main_program or framework.default_main_program()
    if vars is None:
        vars = [v for v in main_program.list_vars() if predicate(v)]
    scope = global_scope()
    if filename is not None:
        data = _load_combined(os.path.join(dirname, filename))
        for v in vars:
            if v.name in data:
                scope.set_var(v.name, data[v.name])
    else:
        for v in vars:
            path = os.path.join(dirname, v.name + ".npy")
            if os.path.exists(path):
                scope.set_var(v.name, np.load(path))


def load_params(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program, predicate=_is_param,
                     filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program,
                     predicate=_is_persistable, filename=filename)


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True,
                         program_only=False):
    """Prunes to the inference subgraph and saves program + params
    (reference ``io.py:1011``). ``export_for_deployment=False`` keeps the
    full (unpruned) program so it can be re-optimized later;
    ``program_only=True`` writes ``__model__`` without parameter files.
    """
    main_program = main_program or framework.default_main_program()
    if export_for_deployment:
        pruned = main_program._prune(target_vars)
    else:
        # keep the program EXACTLY as built — no for_test flip — so a
        # reloaded program still trains (dropout active, batch-norm
        # updating running stats); only deployment exports go eval-mode
        pruned = main_program.clone(for_test=False)
    pruned._feed_names = list(feeded_var_names)
    pruned._fetch_names = [
        v.name if isinstance(v, Variable) else v for v in target_vars
    ]
    os.makedirs(dirname, exist_ok=True)
    model_path = os.path.join(dirname, model_filename or "__model__")
    desc = pruned.to_desc()
    desc["feed_names"] = pruned._feed_names
    desc["fetch_names"] = pruned._fetch_names
    from .core import proto_io

    model_bytes = proto_io.program_to_bytes(desc)
    # Structural cross-check of the pruned program through the native IR
    # (program_graph.cc lint — the reference validates saved descs on
    # its native side too). Advisory when the toolchain is absent.
    try:
        from .native_program import NativeProgram

        native_prog = NativeProgram.from_bytes(model_bytes)
        if native_prog is not None:
            defects = [i for i in native_prog.lint() if i.startswith("E: ")]
            if defects:
                raise RuntimeError(
                    "save_inference_model produced a structurally broken "
                    "program:\n" + "\n".join(defects))
    except ImportError:
        pass
    with open(model_path, "wb") as f:
        f.write(model_bytes)
    if not program_only:
        # only save params the pruned program still references
        needed = {n for blk in pruned.blocks for op in blk.ops
                  for n in op.input_arg_names()}
        vars = [v for v in main_program.list_vars()
                if v.persistable and v.name in needed]
        save_vars(executor, dirname, main_program, vars=vars,
                  filename=params_filename)
    return pruned._fetch_names


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    from .core import proto_io

    model_path = os.path.join(dirname, model_filename or "__model__")
    with open(model_path, "rb") as f:
        # program_from_bytes enforces check_program_compatible itself
        desc = proto_io.program_from_bytes(f.read())
    program = Program.from_desc(desc)
    feed_names = desc.get("feed_names", [])
    fetch_names = desc.get("fetch_names", [])
    load_vars(executor, dirname, program, predicate=_is_persistable,
              filename=params_filename)
    fetch_vars = [program.global_block().var(n) for n in fetch_names]
    return program, feed_names, fetch_vars


def save(program, model_path):
    """Unified save (reference ``io.py:1493``): params + opt state + program."""
    base = model_path
    os.makedirs(os.path.dirname(base) or ".", exist_ok=True)
    scope = global_scope()
    params = {}
    opt = {}
    for v in program.list_vars():
        if not v.persistable:
            continue
        val = scope.find_var(v.name)
        if val is None:
            continue
        (params if _is_param(v) else opt)[v.name] = np.asarray(val)
    from .core import tensor_io

    tensor_io.save_combine(base + ".pdparams", params)
    tensor_io.save_combine(base + ".pdopt", opt)
    with open(base + ".pdmodel", "wb") as f:
        f.write(program.serialize_to_string())


def load(program, model_path, executor=None, var_list=None):
    scope = global_scope()
    for suffix in (".pdparams", ".pdopt"):
        path = model_path + suffix
        if not os.path.exists(path):
            continue
        for name, arr in _load_combined(path).items():
            scope.set_var(name, arr)
