"""Checkpointing & model export.

Parity: reference ``python/paddle/fluid/io.py`` — ``save_params:273`` /
``save_persistables:523`` / ``load_persistables:801`` /
``save_inference_model:1011`` / ``load_inference_model:1215`` and the
unified ``save:1493``/``load:1547``.

Storage format: one file per var (like the reference's per-var ``save`` op
files) or a combined ``.npz``; the program goes as protobuf (``__model__``).
"""

import hashlib
import json
import os
import threading
import time

import numpy as np

from . import framework
from . import monitor as _monitor
from . import resilience as _resilience
from .executor import RNG_STATE_VAR, global_scope
from .framework import Program, Variable

from ..reader.decorator import batch, shuffle  # noqa: F401  (io.batch parity)

__all__ = [
    "batch", "shuffle",
    "save_vars", "save_params", "save_persistables", "load_vars",
    "load_params", "load_persistables", "save_inference_model",
    "load_inference_model", "save", "load", "CheckpointManager",
]

ENV_CHECKPOINT_DIR = "PADDLE_CHECKPOINT_DIR"
ENV_RESTART_ATTEMPT = "PADDLE_RESTART_ATTEMPT"

_M_CKPT_SAVES = _monitor.counter(
    "checkpoint_saves_total", help="checkpoint versions committed")
_M_CKPT_SECONDS = _monitor.histogram(
    "checkpoint_save_seconds",
    help="wall time to snapshot + write + commit one checkpoint version "
         "(the write side only for background saves)")
_M_CKPT_RESTORES = _monitor.counter(
    "checkpoint_restores_total", help="successful CheckpointManager restores")
_M_CKPT_CORRUPT = _monitor.counter(
    "checkpoint_corrupt_total",
    help="checkpoint versions rejected by manifest/checksum validation "
         "(torn writes, truncation, bit rot)")
_M_CKPT_FALLBACK = _monitor.counter(
    "checkpoint_latest_fallback_total",
    help="latest() calls that skipped a torn newest version and fell "
         "back to an older intact one")
_M_CKPT_RESHARDS = _monitor.counter(
    "checkpoint_reshards_total",
    help="state arrays re-laid-out (device_put) onto the current mesh "
         "during restore — the elastic-reformation reshard path")
_M_CKPT_RESHARD_SECONDS = _monitor.histogram(
    "checkpoint_reshard_seconds",
    help="wall time of the reshard-on-restore pass (all state arrays "
         "of one restore)")

# a crashed reader's leftover .reading-* guard stops blocking rotation
# after this long
_GUARD_TTL = 300.0


def _atomic_write_bytes(path, data):
    """tmp-file + fsync + rename: the file at ``path`` is always either
    the old version or the new one, never a prefix of the new one."""
    tmp = "%s.tmp-%d" % (path, os.getpid())
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        from . import faults as _faults

        _faults.check("io.write")  # simulated crash between write and rename
        os.replace(tmp, path)
    except BaseException:  # crash-consistency: surfaced errors must not leave tmp litter
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def _is_persistable(var):
    return var.persistable


def _is_param(var):
    return isinstance(var, framework.Parameter)


def save_vars(executor, dirname, main_program=None, vars=None, predicate=None,
              filename=None):
    main_program = main_program or framework.default_main_program()
    if vars is None:
        vars = [v for v in main_program.list_vars() if predicate(v)]
    scope = global_scope()
    os.makedirs(dirname, exist_ok=True)
    if filename is not None:
        from .core import tensor_io

        arrays = {}
        for v in vars:
            val = scope.find_var(v.name)
            if val is not None:
                arrays[v.name] = np.asarray(val)
        tensor_io.save_combine(os.path.join(dirname, filename), arrays)
    else:
        for v in vars:
            val = scope.find_var(v.name)
            if val is not None:
                import io as _io

                buf = _io.BytesIO()
                np.save(buf, np.asarray(val))
                _atomic_write_bytes(
                    os.path.join(dirname, v.name + ".npy"), buf.getvalue())


def _load_combined(path):
    """Read a combined tensor file: PTC1 (native serde) or legacy npz."""
    with open(path, "rb") as f:
        magic = f.read(4)
    if magic == b"PTC1":
        from .core import tensor_io

        return tensor_io.load_combine(path)
    data = np.load(path, allow_pickle=False)
    return {name: data[name] for name in data.files}


def save_params(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program, predicate=_is_param,
                     filename=filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program,
                     predicate=_is_persistable, filename=filename)


def load_vars(executor, dirname, main_program=None, vars=None, predicate=None,
              filename=None):
    main_program = main_program or framework.default_main_program()
    if vars is None:
        vars = [v for v in main_program.list_vars() if predicate(v)]
    scope = global_scope()
    if filename is not None:
        data = _load_combined(os.path.join(dirname, filename))
        for v in vars:
            if v.name in data:
                scope.set_var(v.name, data[v.name])
    else:
        for v in vars:
            path = os.path.join(dirname, v.name + ".npy")
            if os.path.exists(path):
                scope.set_var(v.name, np.load(path))


def load_params(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program, predicate=_is_param,
                     filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program,
                     predicate=_is_persistable, filename=filename)


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True,
                         program_only=False, prelower=False,
                         prelower_batch_sizes=(1,)):
    """Prunes to the inference subgraph and saves program + params
    (reference ``io.py:1011``). ``export_for_deployment=False`` keeps the
    full (unpruned) program so it can be re-optimized later;
    ``program_only=True`` writes ``__model__`` without parameter files.

    ``prelower=True`` additionally AOT-compiles the pruned program (one
    executable per batch size in ``prelower_batch_sizes``; dynamic
    non-batch dims fill with 1) and serializes the executables into
    ``<dirname>/__prelowered__`` via ``fluid.compile_cache`` — a
    ``Predictor`` opening this model then cold-starts by deserializing
    instead of tracing+compiling, no ``PADDLE_COMPILE_CACHE_DIR``
    needed. Batch sizes not in the list still compile live as usual.
    """
    main_program = main_program or framework.default_main_program()
    if export_for_deployment:
        pruned = main_program._prune(target_vars)
    else:
        # keep the program EXACTLY as built — no for_test flip — so a
        # reloaded program still trains (dropout active, batch-norm
        # updating running stats); only deployment exports go eval-mode
        pruned = main_program.clone(for_test=False)
    pruned._feed_names = list(feeded_var_names)
    pruned._fetch_names = [
        v.name if isinstance(v, Variable) else v for v in target_vars
    ]
    os.makedirs(dirname, exist_ok=True)
    model_path = os.path.join(dirname, model_filename or "__model__")
    desc = pruned.to_desc()
    desc["feed_names"] = pruned._feed_names
    desc["fetch_names"] = pruned._fetch_names
    from .core import proto_io

    model_bytes = proto_io.program_to_bytes(desc)
    # Structural cross-check of the pruned program through the native IR
    # (program_graph.cc lint — the reference validates saved descs on
    # its native side too). Advisory when the toolchain is absent.
    try:
        from .native_program import NativeProgram

        native_prog = NativeProgram.from_bytes(model_bytes)
        if native_prog is not None:
            defects = [i for i in native_prog.lint() if i.startswith("E: ")]
            if defects:
                raise RuntimeError(
                    "save_inference_model produced a structurally broken "
                    "program:\n" + "\n".join(defects))
    except ImportError:
        pass
    _atomic_write_bytes(model_path, model_bytes)
    if not program_only:
        # only save params the pruned program still references
        needed = {n for blk in pruned.blocks for op in blk.ops
                  for n in op.input_arg_names()}
        vars = [v for v in main_program.list_vars()
                if v.persistable and v.name in needed]
        save_vars(executor, dirname, main_program, vars=vars,
                  filename=params_filename)
    if prelower:
        _prelower_executables(dirname, model_bytes, prelower_batch_sizes)
    return pruned._fetch_names


def _prelower_executables(dirname, model_bytes, batch_sizes):
    """AOT-compile + serialize the saved inference program into
    ``<dirname>/__prelowered__``.

    The program is re-parsed from the exact ``__model__`` bytes just
    written (not the in-memory pruned object) so the content digest in
    the cache key matches what ``load_inference_model`` will compute at
    cold start; params come from the calling scope (they were just
    saved from it). Exemplar feeds are zeros in the declared shapes —
    the first dynamic (-1) dim takes the batch size, any other dynamic
    dim takes 1."""
    from . import compile_cache as _compile_cache
    from .core import proto_io
    from .executor import Executor

    desc = proto_io.program_from_bytes(model_bytes)
    program = Program.from_desc(desc)
    block = program.global_block()
    feed_names = list(desc.get("feed_names", []))
    fetch_names = list(desc.get("fetch_names", []))
    out_dir = os.path.join(dirname, _compile_cache.PRELOWERED_DIRNAME)
    exe = Executor()
    # inference executables are serialized WITHOUT state donation: a
    # donated AOT executable runs in-place over param buffers, which
    # corrupts served values once a cold process serves through the
    # deserialized copy (see Executor._donate_state). The Predictor's
    # executor flips the same bit, so reader keys match these entries.
    exe._donate_state = False
    # a child scope keeps the exemplar run's state commits (and the rng
    # var) out of the caller's scope while params resolve through it
    scope = global_scope().new_scope()
    with _compile_cache.override_dir(out_dir):
        for b in batch_sizes:
            feed = {}
            for name in feed_names:
                var = block._find_var_recursive(name)
                if var is None or var.shape is None:
                    raise ValueError(
                        "prelower: feed var %r has no declared shape — "
                        "pass explicit exemplar batches through the "
                        "serving warm-up instead" % name)
                shape, batch_dim_used = [], False
                for d in var.shape:
                    if int(d) < 0:
                        shape.append(1 if batch_dim_used else int(b))
                        batch_dim_used = True
                    else:
                        shape.append(int(d))
                feed[name] = np.zeros(shape, dtype=np.dtype(var.dtype))
            exe.run(program, feed=feed, fetch_list=fetch_names,
                    scope=scope)


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    from .core import proto_io

    model_path = os.path.join(dirname, model_filename or "__model__")
    with open(model_path, "rb") as f:
        # program_from_bytes enforces check_program_compatible itself
        desc = proto_io.program_from_bytes(f.read())
    program = Program.from_desc(desc)
    feed_names = desc.get("feed_names", [])
    fetch_names = desc.get("fetch_names", [])
    load_vars(executor, dirname, program, predicate=_is_persistable,
              filename=params_filename)
    fetch_vars = [program.global_block().var(n) for n in fetch_names]
    return program, feed_names, fetch_vars


def save(program, model_path):
    """Unified save (reference ``io.py:1493``): params + opt state + program."""
    base = model_path
    os.makedirs(os.path.dirname(base) or ".", exist_ok=True)
    scope = global_scope()
    params = {}
    opt = {}
    for v in program.list_vars():
        if not v.persistable:
            continue
        val = scope.find_var(v.name)
        if val is None:
            continue
        (params if _is_param(v) else opt)[v.name] = np.asarray(val)
    from .core import tensor_io

    tensor_io.save_combine(base + ".pdparams", params)
    tensor_io.save_combine(base + ".pdopt", opt)
    with open(base + ".pdmodel", "wb") as f:
        f.write(program.serialize_to_string())


def load(program, model_path, executor=None, var_list=None, strict=True):
    """Unified load. ``strict=True`` (default) raises ``FileNotFoundError``
    when NEITHER ``<model_path>.pdparams`` nor ``.pdopt`` exists — the
    old behavior silently "loaded" a typo'd path and trained from
    uninitialized weights. ``strict=False`` is the escape hatch for
    callers probing an optional checkpoint."""
    scope = global_scope()
    found = False
    for suffix in (".pdparams", ".pdopt"):
        path = model_path + suffix
        if not os.path.exists(path):
            continue
        found = True
        for name, arr in _load_combined(path).items():
            scope.set_var(name, arr)
    if not found and strict:
        raise FileNotFoundError(
            "fluid.io.load: neither %s.pdparams nor %s.pdopt exists — "
            "pass strict=False to tolerate a missing checkpoint"
            % (model_path, model_path))
    return found


# ---------------------------------------------------------------------------
# Crash-consistent versioned checkpointing
# ---------------------------------------------------------------------------

_MANIFEST = "manifest.json"
_CKPT_PREFIX = "ckpt-"


def _sha256_file(path, chunk=1 << 20):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def _fsync_dir(path):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _program_py_readers(program):
    """(key, reader) for every live py_reader feeding ``program`` — key
    is the reader's first slot name (stable across restarts because slot
    names come from the deterministic LayerHelper counter)."""
    from .layers.py_reader import _READERS

    out = []
    for blk in program.blocks:
        for op in blk.ops:
            if op.type == "py_reader_dequeue":
                r = _READERS.get(int(op.attr("reader_id")))
                if r is not None:
                    out.append((r.names[0], r))
    return out


class CheckpointManager:
    """Versioned, crash-consistent training checkpoints with one-line
    auto-resume (the piece ``launch(max_restarts=...)`` always assumed
    existed: SURVEY §5.3's "workers resume from their own checkpoints").

    Each ``save`` writes ``<dir>/ckpt-<step>/`` containing
    ``params.pdparams`` + ``opt.pdopt`` (which also carries the
    executor's rng state, so dropout streams resume mid-epoch) and a
    ``manifest.json`` with the step, per-file sha256 checksums, and any
    py_reader epoch positions. The version is assembled in a hidden tmp
    dir, every file fsync'd, and committed by ONE atomic directory
    rename — a crash at any instant leaves only whole versions.
    ``latest()``/``restore()`` validate checksums and silently fall back
    to the newest INTACT version, so a torn write (or bit rot) costs at
    most one checkpoint interval, never a poisoned run.

    ``dirname=None`` reads ``PADDLE_CHECKPOINT_DIR`` — the launcher
    exports it (``launch(checkpoint_dir=...)``) so a restarted worker
    finds the manifests with zero script plumbing:

        mgr = fluid.io.CheckpointManager(max_to_keep=3)
        exe.run(startup)
        start = mgr.restore_on_restart(exe, main) or 0
        for step in range(start, total):
            exe.run(main, feed=..., checkpoint=(mgr, 50))

    ``background=True`` snapshots the scope synchronously (host copies)
    but writes off the critical path on a worker thread; ``wait()``
    joins it (``close()``/pending-save joins it too — the thread is
    non-daemon on purpose, a leaked writer is a bug).

    All checkpoint I/O goes through a shared ``resilience.Retry``
    (transient filesystem errors are retried with backoff and counted
    in ``monitor``; corrupt data is never retried, it's skipped).
    """

    def __init__(self, dirname=None, max_to_keep=3, background=False,
                 retry=None):
        dirname = dirname or os.environ.get(ENV_CHECKPOINT_DIR)
        if not dirname:
            raise ValueError(
                "CheckpointManager needs a directory: pass dirname= or "
                "set %s (distributed.launch(checkpoint_dir=...) exports "
                "it to workers)" % ENV_CHECKPOINT_DIR)
        self.dirname = dirname
        self.max_to_keep = max(1, int(max_to_keep))
        self.background = bool(background)
        self._step = 0
        self._writer = None  # in-flight background save thread
        self._writer_err = None
        self._lock = threading.Lock()
        self._retry = retry if retry is not None else _resilience.Retry(
            max_attempts=3, base_delay=0.05, max_delay=2.0,
            name="checkpoint.io")
        os.makedirs(dirname, exist_ok=True)

    # -- version enumeration / validation --------------------------------
    def _path(self, step):
        return os.path.join(self.dirname, "%s%08d" % (_CKPT_PREFIX, step))

    def steps(self):
        """All committed version steps, ascending (no validation)."""
        out = []
        try:
            names = os.listdir(self.dirname)
        except OSError:
            return out
        for n in names:
            if n.startswith(_CKPT_PREFIX):
                try:
                    out.append(int(n[len(_CKPT_PREFIX):]))
                except ValueError:
                    pass
        return sorted(out)

    def manifest(self, step):
        """Parsed manifest of version ``step`` (no checksum pass);
        raises on a missing/corrupt manifest file."""
        with open(os.path.join(self._path(step), _MANIFEST)) as f:
            return json.load(f)

    def validate(self, step):
        """True if version ``step`` is intact: manifest parses and every
        listed file matches its recorded sha256 and size."""
        d = self._path(step)
        try:
            m = self.manifest(step)
            for fname, meta in m["files"].items():
                p = os.path.join(d, fname)
                if os.path.getsize(p) != meta["bytes"]:
                    return False
                if _sha256_file(p) != meta["sha256"]:
                    return False
            return True
        except (OSError, ValueError, KeyError):
            return False

    def latest(self):
        """Step of the newest INTACT version, or None. Corrupt versions
        (torn by a crash mid-write on a non-atomic filesystem, truncated
        by an operator, rotted) are counted and skipped — restore falls
        back to the previous good one."""
        fell_back = False
        for step in reversed(self.steps()):
            if self.validate(step):
                if fell_back:
                    _M_CKPT_FALLBACK.inc()
                return step
            _M_CKPT_CORRUPT.inc()
            fell_back = True
        return None

    # -- save -------------------------------------------------------------
    def _snapshot(self, program, scope):
        """Host-side copies of every persistable the program can see,
        split params/opt like ``fluid.io.save`` — taken on the CALLER's
        thread so a background write never races the training loop's
        scope mutations."""
        scope = scope or global_scope()
        params, opt = {}, {}
        for v in program.list_vars():
            if not v.persistable:
                continue
            val = scope.find_var(v.name)
            if val is None:
                continue
            (params if _is_param(v) else opt)[v.name] = np.asarray(val)
        rng = scope.find_var(RNG_STATE_VAR)
        if rng is not None:
            opt[RNG_STATE_VAR] = np.asarray(rng)
        readers = {key: r.position for key, r in
                   _program_py_readers(program)}
        return params, opt, readers

    def save(self, program, scope=None, step=None, background=None):
        """Write one version. ``step`` defaults to the manager's
        internal counter (advanced by ``Executor.run(checkpoint=...)``
        or ``restore``). ``background`` overrides the constructor
        default; a background save returns immediately after the
        host-side snapshot — call ``wait()`` before reading
        ``latest()`` or exiting."""
        if step is None:
            step = self._step
        step = int(step)
        background = self.background if background is None else background
        self.wait()  # one writer at a time; surfaces a prior bg failure
        params, opt, readers = self._snapshot(program, scope)
        if background:
            self._writer = threading.Thread(
                target=self._write_guarded,
                args=(step, params, opt, readers),
                name="paddle-checkpoint-writer", daemon=False)
            self._writer.start()
        else:
            self._retry.call(self._write_version, step, params, opt,
                             readers)
        return step

    def _write_guarded(self, step, params, opt, readers):
        try:
            self._retry.call(self._write_version, step, params, opt,
                             readers)
        except BaseException as e:  # re-raised on the training thread at the next wait()/save()
            self._writer_err = e

    def _write_version(self, step, params, opt, readers):
        from .core import tensor_io

        with _M_CKPT_SECONDS.time():
            final = self._path(step)
            tmp = os.path.join(
                self.dirname, ".tmp-%s%08d-%d" % (_CKPT_PREFIX, step,
                                                  os.getpid()))
            if os.path.exists(tmp):
                import shutil

                shutil.rmtree(tmp)
            os.makedirs(tmp)
            files = {}
            for fname, arrays in (("params.pdparams", params),
                                  ("opt.pdopt", opt)):
                p = os.path.join(tmp, fname)
                # atomic=False: the enclosing tmp-dir + rename IS the
                # atomicity here; fsync still required before commit
                tensor_io.save_combine(p, arrays, atomic=False)
                tensor_io._fsync_path(p)
                files[fname] = {"sha256": _sha256_file(p),
                                "bytes": os.path.getsize(p)}
            from . import faults as _faults

            _faults.check("io.write")  # simulated crash before the commit rename
            manifest = {"step": step, "files": files,
                        "reader_positions": readers,
                        # gang size at save time: restore into a
                        # different world (elastic reformation) reshards
                        "world_size": int(os.environ.get(
                            "PADDLE_TRAINERS_NUM", "1") or 1),
                        "time": time.time()}
            mpath = os.path.join(tmp, _MANIFEST)
            with open(mpath, "w") as f:
                json.dump(manifest, f, indent=1)
                f.flush()
                os.fsync(f.fileno())
            if os.path.exists(final):
                import shutil

                shutil.rmtree(final)  # re-saving the same step replaces it
            os.rename(tmp, final)
            _fsync_dir(self.dirname)
        _M_CKPT_SAVES.inc()
        self._prune()

    def _guard_path(self, step):
        return os.path.join(self.dirname,
                            ".reading-%08d-%d" % (int(step), os.getpid()))

    def _guarded_steps(self):
        """Versions some live reader pinned with a ``.reading-*`` guard
        file — rotation must not delete them out from under a
        concurrent ``restore()``. Guards older than ``_GUARD_TTL``
        belong to crashed readers and are swept."""
        guarded = set()
        try:
            names = os.listdir(self.dirname)
        except OSError:
            return guarded
        now = time.time()
        for n in names:
            if not n.startswith(".reading-"):
                continue
            p = os.path.join(self.dirname, n)
            try:
                if now - os.path.getmtime(p) > _GUARD_TTL:
                    os.remove(p)
                    continue
                guarded.add(int(n[len(".reading-"):].split("-")[0]))
            except (OSError, ValueError):
                pass
        return guarded

    def _prune(self):
        import shutil

        steps = self.steps()
        guarded = self._guarded_steps()
        for step in steps[:-self.max_to_keep]:
            if step in guarded:
                continue  # a concurrent restore() is reading it
            shutil.rmtree(self._path(step), ignore_errors=True)
        # abandoned tmp dirs from crashed writers
        try:
            for n in os.listdir(self.dirname):
                if n.startswith(".tmp-%s" % _CKPT_PREFIX) and \
                        not n.endswith("-%d" % os.getpid()):
                    shutil.rmtree(os.path.join(self.dirname, n),
                                  ignore_errors=True)
        except OSError:
            pass

    def wait(self):
        """Join an in-flight background save; re-raise its failure (a
        checkpoint that silently never landed is the one failure mode
        this class exists to kill)."""
        w, self._writer = self._writer, None
        if w is not None:
            w.join()
        if self._writer_err is not None:
            e, self._writer_err = self._writer_err, None
            raise e

    close = wait

    # -- restore ----------------------------------------------------------
    def restore(self, executor=None, program=None, scope=None, step=None,
                strategy=None):
        """Load version ``step`` (default: ``latest()`` intact one) into
        the scope: params, optimizer state, executor rng, and py_reader
        positions (live readers fast-forward on their next ``start()``).
        Returns the restored step; raises ``FileNotFoundError`` when no
        intact version exists.

        ``strategy`` (a ``CompiledProgram``): reshard-on-restore — every
        restored array is ``device_put`` with the layout
        ``strategy.state_sharding`` derives on the CURRENT mesh, so a
        checkpoint written by a world-size-N gang restores cleanly into
        the N-k survivors of an elastic reformation (specs that no
        longer fit the shrunk mesh degrade to replicated). The version
        being read is pinned with a ``.reading-*`` guard file so a
        concurrent background save's ``max_to_keep`` rotation can never
        delete it mid-read."""
        self.wait()
        if program is None:
            program = framework.default_main_program()
        from . import compiler as _compiler

        if isinstance(program, _compiler.CompiledProgram):
            # callers may hand the CompiledProgram straight in: it IS
            # the strategy, and carries the underlying Program
            if strategy is None:
                strategy = program
            program = program._program
        if step is None:
            step = self.latest()
            if step is None:
                raise FileNotFoundError(
                    "no intact checkpoint under %r" % self.dirname)
        elif not self.validate(step):
            raise IOError("checkpoint step %d under %r failed checksum "
                          "validation" % (step, self.dirname))
        scope = scope or global_scope()
        from .core import tensor_io

        d = self._path(step)
        guard = self._guard_path(step)
        try:
            with open(guard, "w") as f:
                f.write(str(time.time()))
        except OSError:
            guard = None  # unwritable dir: read unguarded, best effort
        try:
            block = program.global_block() if (
                strategy is not None and program is not None) else None
            resharded = 0
            t0 = time.monotonic()
            for fname in ("params.pdparams", "opt.pdopt"):
                data = self._retry.call(
                    tensor_io.load_combine, os.path.join(d, fname))
                for name, arr in data.items():
                    if strategy is not None:
                        sh = strategy.state_sharding(block, name, arr)
                        if sh is not None:
                            import jax

                            arr = jax.device_put(arr, sh)
                            resharded += 1
                    scope.set_var(name, arr)
            manifest = self.manifest(step)
        finally:
            if guard:
                try:
                    os.remove(guard)
                except OSError:
                    pass
        if resharded:
            _M_CKPT_RESHARDS.inc(resharded)
            _M_CKPT_RESHARD_SECONDS.observe(time.monotonic() - t0)
            saved_world = manifest.get("world_size")
            cur_world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1")
                            or 1)
            if saved_world and int(saved_world) != cur_world:
                import logging

                logging.getLogger(__name__).info(
                    "checkpoint step %d was saved by a world-size-%s "
                    "gang; resharded %d state arrays onto the current "
                    "world-size-%d mesh", step, saved_world, resharded,
                    cur_world)
        positions = manifest.get("reader_positions", {})
        if positions and program is not None:
            for key, r in _program_py_readers(program):
                if key in positions:
                    r.resume_at(int(positions[key]))
        self._step = step
        _M_CKPT_RESTORES.inc()
        return step

    def restore_on_restart(self, executor=None, program=None, scope=None,
                           strategy=None):
        """Auto-resume for launcher-restarted workers: when
        ``PADDLE_RESTART_ATTEMPT`` > 0 (set by ``distributed.launch`` on
        every respawn) and an intact version exists, restore it and
        return its step; otherwise return None (fresh start — attempt 0,
        an empty/garbage checkpoint dir, or the crash predated the first
        checkpoint). ``strategy`` enables reshard-on-restore (see
        ``restore``) — pass the ``CompiledProgram`` when running under
        an elastic launcher whose gang may have been re-formed at a
        different world size."""
        attempt = int(os.environ.get(ENV_RESTART_ATTEMPT, "0") or 0)
        if attempt <= 0:
            return None
        # restarted worker: page in + validate the persistent compile
        # cache now, so the first step deserializes instead of
        # recompiling (no-op when PADDLE_COMPILE_CACHE_DIR is unset)
        from . import compile_cache as _compile_cache

        _compile_cache.prewarm()
        if self.latest() is None:
            return None
        return self.restore(executor, program, scope, strategy=strategy)

    # -- executor integration ---------------------------------------------
    def step_completed(self, program, scope, iters, every_n_steps):
        """Called by ``Executor.run(..., checkpoint=(mgr, n))`` after
        each committed step (or ``iters=k`` window): advances the step
        counter and saves whenever it crosses a multiple of
        ``every_n_steps``."""
        every = int(every_n_steps)
        if every < 1:
            raise ValueError(
                "checkpoint every_n_steps must be >= 1, got %r"
                % (every_n_steps,))
        before = self._step
        self._step = before + int(iters)
        if self._step // every > before // every:
            self.save(program, scope, step=self._step)
