"""Op registry: op type -> lowering rule.

Plays the role of the reference's ``OpInfoMap`` + ``REGISTER_OPERATOR``
(``paddle/fluid/framework/op_registry.h:199``, ``op_info.h:115``) but instead
of per-device kernel dispatch, each op has a single *lowering rule* that emits
JAX/XLA (or Pallas) computation when a Block is traced into one compiled
function. This is the TPU-native analogue of the kernel layer: XLA does the
tiling/fusion that per-op CUDA kernels hand-coded.

A lowering rule has signature ``lower(ctx, op)`` where ``ctx`` is a
``LowerCtx`` giving read/write access to the symbolic environment, and ``op``
is the ``framework.Operator``. Rules read inputs with ``ctx.get`` and bind
outputs with ``ctx.set``.
"""

import numpy as np


class OpInfo:
    def __init__(self, type, lower, has_state=False):
        self.type = type
        self.lower = lower
        # has_state: op reads/advances the RNG stream (dropout, random init)
        self.has_state = has_state


class OpRegistry:
    def __init__(self):
        self._ops = {}

    def register(self, type, lower=None, **kw):
        if lower is None:  # decorator form
            def deco(fn):
                self._ops[type] = OpInfo(type, fn, **kw)
                return fn

            return deco
        self._ops[type] = OpInfo(type, lower, **kw)
        return lower

    def get(self, type):
        info = self._ops.get(type)
        if info is None:
            raise NotImplementedError(
                "Op %r has no lowering rule registered (see paddle_tpu/fluid/ops/)" % type
            )
        return info

    def has(self, type):
        return type in self._ops

    def types(self):
        return sorted(self._ops)


registry = OpRegistry()
register = registry.register


class LowerCtx:
    """Symbolic environment threaded through a block lowering.

    - ``env``: name -> jax value (tracers during jit trace).
    - ``written``: persistable names assigned during the trace (optimizer
      updates, BN running stats, step counters) — the executor commits these
      back to the Scope, the analogue of the reference's in-place scope
      mutation under XLA's functional model.
    - RNG: a single threaded PRNG key. Each stateful op calls ``next_rng``.
      During autodiff replay (``replay_keys``) the recorded keys are reused so
      the recomputed forward matches bit-for-bit (reference analogue: fixed
      dropout masks saved for backward).
    """

    def __init__(self, block, env, rng_key, mesh=None, replay_keys=None):
        self.block = block
        self.program = block.program
        self.env = env
        self.rng_key = rng_key
        self.mesh = mesh
        self.used_keys = []
        self._replay_keys = list(replay_keys) if replay_keys is not None else None
        self.written = set()
        # per-op [start, end) spans into used_keys, recorded by lower_block —
        # the autodiff recompute path slices keys per checkpoint segment
        self.op_key_spans = {}
        # snapshots for autodiff replay (see ops/autodiff.py)
        self.initial_env = dict(env)
        self.initial_rng = rng_key

    def get(self, name):
        if name not in self.env:
            raise KeyError(
                "Var %r not materialized; it must be fed, persistable, or "
                "produced by an earlier op" % name
            )
        return self.env[name]

    def get_input(self, op, slot, default=None):
        names = op.input(slot)
        if not names:
            return default
        return self.get(names[0])

    def get_inputs(self, op, slot):
        return [self.get(n) for n in op.input(slot)]

    def set(self, name, value):
        self.env[name] = value
        v = self.block._find_var_recursive(name)
        if v is not None and v.persistable:
            self.written.add(name)

    def set_output(self, op, slot, value):
        names = op.output(slot)
        if names:
            self.set(names[0], value)

    def var(self, name):
        return self.block._find_var_recursive(name)

    def next_rng(self):
        import jax

        if self._replay_keys is not None:
            key = self._replay_keys.pop(0)
        else:
            self.rng_key, key = jax.random.split(self.rng_key)
        self.used_keys.append(key)
        return key

    def var_dtype(self, name):
        v = self.var(name)
        return np.dtype(v.dtype) if v is not None else np.dtype("float32")


def propagate_lod(ctx, op):
    """Dataflow LoD propagation: if exactly one input carries an @LOD
    lengths binding and an output has the same (static) token dimension,
    the output inherits it — the analogue of the reference's ShareLoD in
    per-op InferShape, done generically on the lowered values."""
    in_lods = []
    for name in op.input_arg_names():
        key = name + "@LOD"
        if key in ctx.env and name in ctx.env:
            in_lods.append((name, ctx.env[key]))
    if not in_lods:
        return
    # several LoD inputs (e.g. concat along features) may share one
    # segmentation. Propagate the first input's lengths only when every
    # LoD input agrees on sequence count and token dim — values can't be
    # compared at trace time; like the reference's ShareLoD, equal-shape
    # disagreement is the caller's contract violation. Disagreeing shapes
    # propagate nothing, so downstream sequence ops raise loudly.
    first_len = in_lods[0][1]
    leads = set()
    for name, lv in in_lods:
        v = ctx.env[name]
        if not np.ndim(v):
            return
        leads.add(np.shape(v)[0])
        if np.shape(lv) != np.shape(first_len):
            return
    if len(leads) != 1:
        return
    lengths = first_len
    lead = leads.pop()
    for out in op.output_arg_names():
        key = out + "@LOD"
        if key in ctx.env or out not in ctx.env:
            continue
        v = ctx.env[out]
        if np.ndim(v) and np.shape(v)[0] == lead:
            ctx.env[key] = lengths


class EnforceError(RuntimeError):
    """Op-attributed error (reference PADDLE_ENFORCE + op_call_stack.cc):
    carries which op failed and where user code created it."""


def attribute_op_error(op, exc):
    """Re-raise ``exc`` wrapped with the op's identity + creation site."""
    lines = ["op %r failed during lowering: %s: %s"
             % (op.type, type(exc).__name__, exc)]
    ins = {k: v for k, v in op.inputs.items() if v}
    outs = {k: v for k, v in op.outputs.items() if v}
    lines.append("  inputs: %r  outputs: %r" % (ins, outs))
    stack = getattr(op, "callstack", None)
    if stack:
        lines.append("  created at (most recent user frame first):")
        lines.extend("    " + s for s in stack)
    raise EnforceError("\n".join(lines)) from exc


# Op types whose lowering actually ran in this process — the
# execution-based coverage gate (tests/test_zz_coverage_gate.py) asserts
# every registered type lands here during the full suite, so a lowering
# that is merely *mentioned* in test text can no longer pass the gate.
EXECUTED_OP_TYPES = set()


def lower_op(ctx, op):
    """Lower ONE op with error attribution + LoD propagation — the single
    entry every lowering loop (block, sub-block, replay, pipeline stage)
    must use so failures name the failing op and its creation site."""
    EXECUTED_OP_TYPES.add(op.type)
    try:
        registry.get(op.type).lower(ctx, op)
    except EnforceError:
        raise
    except Exception as e:  # noqa: B902 — attribute, then re-raise
        attribute_op_error(op, e)
    propagate_lod(ctx, op)


def lower_block(ctx, block):
    """Run every op's lowering rule in order (the `Executor::RunPreparedContext`
    hot-loop analogue, reference executor.cc:411 — but traced once, compiled
    by XLA, not interpreted per step)."""
    for op in block.ops:
        start = len(ctx.used_keys)
        lower_op(ctx, op)
        ctx.op_key_spans[id(op)] = (start, len(ctx.used_keys))
