"""DistributeTranspiler — the parameter-server training facade (reference
``transpiler/distribute_transpiler.py:495``: split a single-process program
into trainer programs that send/recv and pserver programs that
listen_and_serv).

TPU-native mapping (SURVEY §2.6): dense parameters stay on-device and
synchronize through mesh collectives (GSPMD DP — no RPC round-trip per
step), so only the SPARSE embedding tables move to the pserver tier.
``transpile`` scans the program for ``distributed_lookup_table`` ops;
``get_trainer_program`` swaps their host tables for ``ShardedRemoteTable``
proxies over the pserver endpoints (the existing pull/push op lowerings
then train over TCP unchanged); ``get_pserver_program`` returns a Program
holding one ``listen_and_serv`` op — running it with an Executor blocks
and serves that endpoint's row shards, exactly like the reference's
pserver loop."""

import logging
import os

from .. import framework
from ..framework import Program

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig"]


class DistributeTranspilerConfig:
    """Reference ``distribute_transpiler.py:131``. ``slice_var_up`` /
    ``min_block_size`` / ``split_method`` tuned dense-var splitting and
    placement in the reference; dense vars don't ride the PS tier here
    (they synchronize through mesh collectives) and sparse rows always
    shard by ``id % n_endpoints``, so all three are accepted for
    parity and not consulted."""

    def __init__(self):
        self.slice_var_up = True
        self.split_method = None  # default: modulo row sharding
        self.min_block_size = 8192
        self.sync_mode = True
        # GeoSgdTranspiler cadence: deltas ship every this many pushes
        self.geo_sgd_need_push_nums = 100


class DistributeTranspiler:
    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()
        self._tables = {}      # name -> (vocab, dim)
        self._eps = []
        self._trainer_id = 0
        self._trainers = 1
        self._program = None
        self.sync_mode = True

    # -- analysis -----------------------------------------------------------
    def transpile(self, trainer_id, program=None, pservers="", trainers=1,
                  sync_mode=True, startup_program=None):
        from ...distributed import ps

        self._trainer_id = int(trainer_id)
        self._trainers = int(trainers)
        self._eps = [e for e in pservers.split(",") if e]
        if not self._eps:
            raise ValueError("transpile needs at least one pserver endpoint")
        self._program = program or framework.default_main_program()
        self.sync_mode = sync_mode
        for blk in self._program.blocks:
            for op in blk.ops:
                if op.type == "distributed_lookup_table":
                    name = op.attr("table_name")
                    t = ps.get_table(name)
                    self._tables[name] = (t.vocab, t.dim)
        if not self._tables:
            raise ValueError(
                "no distributed_lookup_table ops found — build embeddings "
                "with fluid.layers.embedding(..., is_distributed=True)")

    # -- trainer side -------------------------------------------------------
    def get_trainer_program(self, wait_port=True, push_init=True):
        """Swap local host tables for ShardedRemoteTable proxies. With
        ``push_init`` (default), trainer 0 ships its LOCAL tables'
        initial values to UNTOUCHED pservers first — fresh-start PS
        training then begins from exactly the single-process init (the
        reference ships init through the split startup program; ADVICE
        r3 #2). Shards that already saw a push or a checkpoint load
        report themselves touched and are never overwritten, so resume
        flows keep their restored state even through fleet.init_worker.
        Trainers 1..N-1 BLOCK here (up to PADDLE_PS_INIT_WAIT_SECS,
        default 60) until every shard reports touched, so they cannot
        pull placeholder-seeded rows before trainer 0's init lands
        (ADVICE r4 #3); on timeout they log and proceed."""
        from ...distributed import ps
        from ...distributed.ps_server import ShardedRemoteTable

        if wait_port:
            from ...distributed import wait_server_ready

            wait_server_ready(self._eps)
        for name, (vocab, dim) in self._tables.items():
            local = ps.get_table(name)
            remote = ShardedRemoteTable(self._eps, name, vocab, dim)
            if push_init and self._trainer_id == 0 and local is not None \
                    and hasattr(local, "dump"):
                # PER-SHARD: only untouched shards receive init, so a
                # partially-restarted cluster gets its fresh shard
                # initialized while restored shards keep their state
                full = None
                for k, shard in enumerate(remote._shards):
                    if shard.touched:
                        continue
                    if full is None:
                        full = local.dump()
                    shard.load(full[k::remote._n])
            elif push_init and self._trainer_id != 0:
                wait = float(os.environ.get(
                    "PADDLE_PS_INIT_WAIT_SECS", "60"))
                if not remote.wait_touched(timeout=wait):
                    logging.getLogger(__name__).warning(
                        "table %s: trainer 0's init did not land within "
                        "%.0fs — proceeding on server-side init (set "
                        "PADDLE_PS_INIT_WAIT_SECS to wait longer)",
                        name, wait)
            ps.register_table(name, remote)
        return self._program

    # -- pserver side -------------------------------------------------------
    def get_pserver_program(self, endpoint):
        """A Program whose single ``listen_and_serv`` op serves this
        endpoint's row shards when run (Executor blocks, like the
        reference's RunSyncLoop)."""
        shard_idx = self._eps.index(endpoint)
        prog = Program()
        blk = prog.global_block()
        blk.append_op(
            "listen_and_serv", inputs={}, outputs={},
            attrs={"endpoint": endpoint,
                   "shard_idx": shard_idx,
                   "n_shards": len(self._eps),
                   "table_names": sorted(self._tables),
                   "table_vocabs": [int(self._tables[n][0])
                                    for n in sorted(self._tables)],
                   "table_dims": [int(self._tables[n][1])
                                  for n in sorted(self._tables)],
                   "sync_mode": bool(self.sync_mode)})
        return prog

    def get_pserver_programs(self, endpoint):
        return self.get_pserver_program(endpoint), self.get_startup_program(
            endpoint)

    def get_startup_program(self, endpoint=None, pserver_program=None,
                            startup_program=None):
        """Pserver-side init is carried by the serve op (shards initialize
        when the server builds its tables); an empty program keeps the
        reference's exe.run(startup) call shape working."""
        return Program()


def build_server_from_attrs(attrs):
    """listen_and_serv runtime: construct the TableServer for one
    endpoint's shards (consumed by the Executor's serve path)."""
    from ...distributed import ps
    from ...distributed.ps_server import TableServer, shard_vocab

    host, port = attrs["endpoint"].rsplit(":", 1)
    k, n = int(attrs["shard_idx"]), int(attrs["n_shards"])
    tables = {}
    for name, vocab, dim in zip(attrs["table_names"],
                                attrs["table_vocabs"],
                                attrs["table_dims"]):
        rows = shard_vocab(vocab, n, k)
        # shard-local seed only shapes the placeholder rows: the real
        # initial values arrive from trainer 0's push_init load (or an
        # explicit restore) before training pulls them
        tables[name] = ps.EmbeddingTable(rows, dim, seed=1000 + k)
    return TableServer(host=host, port=int(port), tables=tables)
