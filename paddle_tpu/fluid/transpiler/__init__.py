"""Program transpilers (reference ``python/paddle/fluid/transpiler/``)."""

from . import collective  # noqa: F401
from .collective import Collective, GradAllReduce, LocalSGD  # noqa: F401
