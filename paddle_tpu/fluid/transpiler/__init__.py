"""Program transpilers (reference ``python/paddle/fluid/transpiler/``)."""

from . import (collective, geo_sgd_transpiler,  # noqa: F401
               memory_optimization_transpiler, ps_dispatcher)
from .collective import Collective, GradAllReduce, LocalSGD  # noqa: F401
from .distribute_transpiler import (  # noqa: F401
    DistributeTranspiler,
    DistributeTranspilerConfig,
)
from .geo_sgd_transpiler import GeoSgdTranspiler  # noqa: F401
from .memory_optimization_transpiler import (  # noqa: F401
    memory_optimize,
    release_memory,
)
from .ps_dispatcher import HashName, RoundRobin  # noqa: F401
