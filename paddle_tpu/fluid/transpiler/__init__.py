"""Program transpilers (reference ``python/paddle/fluid/transpiler/``)."""

from . import collective, ps_dispatcher  # noqa: F401
from .collective import Collective, GradAllReduce, LocalSGD  # noqa: F401
from .distribute_transpiler import (  # noqa: F401
    DistributeTranspiler,
    DistributeTranspilerConfig,
)
from .ps_dispatcher import HashName, RoundRobin  # noqa: F401
