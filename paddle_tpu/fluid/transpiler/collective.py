"""Collective-mode program transpilers.

Parity: reference ``python/paddle/fluid/transpiler/collective.py`` —
``GradAllReduce`` (scale loss 1/nranks, insert c_allreduce_sum per grad,
``:178,208``) and ``LocalSGD`` (periodic parameter averaging, ``:269``).

TPU-native: the rewritten program executes under
``CompiledProgram.with_explicit_collectives`` (shard_map), where the inserted
c_allreduce ops lower to XLA psum over the 'dp' mesh axis on ICI. Comm-init
ops (c_gen_nccl_id/c_comm_init) are unnecessary — the JAX coordination
service owns bootstrap — but we keep no-op markers for program parity.
"""

from .. import framework
from ..framework import default_main_program


class Collective:
    def __init__(self, nranks=None):
        self.nranks = nranks

    def transpile(self, startup_program, main_program, rank=0, endpoints=None,
                  current_endpoint=None, wait_port=True):
        self.startup_program = startup_program or framework.default_startup_program()
        self.main_program = main_program or default_main_program()
        if self.nranks is None:
            self.nranks = len(endpoints) if endpoints else 1
        self._transpile_startup_program()
        self._transpile_main_program()
        return self.main_program

    def _transpile_startup_program(self):
        # bootstrap marker (reference inserts c_gen_nccl_id + c_comm_init)
        self.startup_program.global_block().append_op(
            "c_comm_init_all", attrs={"ring_id": 0})

    def _transpile_main_program(self):
        raise NotImplementedError


class GradAllReduce(Collective):
    """Insert grad allreduce after backward (reference ``collective.py:178``)."""

    def __init__(self, nranks=None):
        super().__init__(nranks)

    def _transpile_main_program(self):
        block = self.main_program.global_block()
        new_ops = []
        for op in block.ops:
            new_ops.append(op)
            if op.type in ("autodiff",):
                # scale loss gradient by 1/nranks (reference :189)
                op.attrs["loss_scale"] = op.attrs.get("loss_scale", 1.0) / self.nranks
                for gname in op.attr("grad_names"):
                    ar = framework.Operator(
                        block, "c_allreduce_sum",
                        inputs={"X": [gname]}, outputs={"Out": [gname]},
                        attrs={"ring_id": 0, "use_calc_stream": True})
                    new_ops.append(ar)
        block.ops = new_ops
        self.main_program._bump()


class LocalSGD(Collective):
    """Periodic parameter averaging (reference ``collective.py:269``):
    every k steps, params = pmean(params)."""

    def __init__(self, nranks=None, k_steps=1):
        super().__init__(nranks)
        self.k_steps = k_steps

    def _transpile_main_program(self):
        block = self.main_program.global_block()
        # every-step averaging when k_steps == 1; otherwise gated averaging
        for param in self.main_program.all_parameters():
            block.append_op(
                "c_allreduce_avg",
                inputs={"X": [param.name]}, outputs={"Out": [param.name]},
                attrs={"ring_id": 0})
        self.main_program._bump()
