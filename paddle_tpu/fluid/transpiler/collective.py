"""Collective-mode program transpilers.

Parity: reference ``python/paddle/fluid/transpiler/collective.py`` —
``GradAllReduce`` (scale loss 1/nranks, insert c_allreduce_sum per grad,
``:178,208``) and ``LocalSGD`` (periodic parameter averaging, ``:269``).

TPU-native: the rewritten program executes under
``CompiledProgram.with_explicit_collectives`` (shard_map), where the inserted
c_allreduce ops lower to XLA psum over the 'dp' mesh axis on ICI. Comm-init
ops (c_gen_nccl_id/c_comm_init) are unnecessary — the JAX coordination
service owns bootstrap — but we keep no-op markers for program parity.
"""

from .. import framework
from ..framework import default_main_program


class Collective:
    def __init__(self, nranks=None):
        self.nranks = nranks

    def transpile(self, startup_program, main_program, rank=0, endpoints=None,
                  current_endpoint=None, wait_port=True):
        self.startup_program = startup_program or framework.default_startup_program()
        self.main_program = main_program or default_main_program()
        if self.nranks is None:
            self.nranks = len(endpoints) if endpoints else 1
        self._transpile_startup_program()
        self._transpile_main_program()
        return self.main_program

    def _transpile_startup_program(self):
        # bootstrap marker (reference inserts c_gen_nccl_id + c_comm_init)
        self.startup_program.global_block().append_op(
            "c_comm_init_all", attrs={"ring_id": 0})

    def _transpile_main_program(self):
        raise NotImplementedError


class GradAllReduce(Collective):
    """Insert grad allreduce after backward (reference ``collective.py:178``)."""

    def __init__(self, nranks=None):
        super().__init__(nranks)

    def _transpile_main_program(self):
        block = self.main_program.global_block()
        # DGC grads are allreduced AFTER compression (the reference's
        # sparse_all_reduce_op_handle): the dense grad skips the autodiff
        # allreduce and the masked-dense compressed grad gets one instead.
        dgc_grads = set()
        for op in block.ops:
            if op.type == "dgc":
                dgc_grads.update(op.input("Grad"))
        new_ops = []
        for op in block.ops:
            new_ops.append(op)
            if op.type == "autodiff":
                # scale loss gradient by 1/nranks (reference :189)
                op.attrs["loss_scale"] = op.attrs.get("loss_scale", 1.0) / self.nranks
                for gname in op.attr("grad_names"):
                    if gname in dgc_grads:
                        continue
                    gvar = block.vars.get(gname)
                    if gvar is not None and getattr(
                            gvar, "type", "lod_tensor") == "selected_rows":
                        # A positional c_allreduce_sum over SelectedRows
                        # values would mix gradients of DIFFERENT rows (each
                        # rank looked up different ids). Gather every rank's
                        # (rows, values) instead; the optimizer's scatter-add
                        # sums duplicates, which IS the cross-rank reduction
                        # (reference densifies before allreduce — this keeps
                        # the grad sparse and rides one all-gather on ICI).
                        for name in (gname, gname + "@ROWS"):
                            ar = framework.Operator(
                                block, "c_allgather",
                                inputs={"X": [name]}, outputs={"Out": [name]},
                                attrs={"ring_id": 0, "use_calc_stream": True})
                            new_ops.append(ar)
                        continue
                    ar = framework.Operator(
                        block, "c_allreduce_sum",
                        inputs={"X": [gname]}, outputs={"Out": [gname]},
                        attrs={"ring_id": 0, "use_calc_stream": True})
                    new_ops.append(ar)
            elif op.type == "dgc":
                for cname in op.output("GradOut"):
                    ar = framework.Operator(
                        block, "c_allreduce_sum",
                        inputs={"X": [cname]}, outputs={"Out": [cname]},
                        attrs={"ring_id": 0, "use_calc_stream": True})
                    new_ops.append(ar)
        block.ops = new_ops
        self.main_program._bump()


class HierarchicalGradAllReduce(GradAllReduce):
    """GradAllReduce for a 2-level ``(host, device)`` mesh (reference
    ``use_hierarchical_allreduce``): dense grads get one
    ``c_hierarchical_allreduce`` — reduce-scatter/all-gather over the
    in-host ICI axis, allreduce of the 1/D shard over the DCN axis.
    DGC-compressed grads ride a two-phase split instead: the DENSE
    gradient allreduces in-host first (ring 1 -> axes[1], ICI — cheap,
    and it feeds the compressor the host-summed signal), then the
    masked-dense compressed output crosses hosts (ring 0 -> axes[0],
    DCN) — compression spends exactly where the bandwidth gap pays,
    never on ICI. SelectedRows grads all-gather over ICI then DCN.
    On a single-axis mesh every emitted op degrades to the flat
    collective (``_axis_for`` clamps the ring index), so programs
    transpiled here run unchanged on one host."""

    def _transpile_main_program(self):
        block = self.main_program.global_block()
        dgc_grads = set()
        for op in block.ops:
            if op.type == "dgc":
                dgc_grads.update(op.input("Grad"))
        new_ops = []
        for op in block.ops:
            new_ops.append(op)
            if op.type == "autodiff":
                op.attrs["loss_scale"] = \
                    op.attrs.get("loss_scale", 1.0) / self.nranks
                for gname in op.attr("grad_names"):
                    if gname in dgc_grads:
                        # in-host dense reduction feeding the compressor
                        # (ring 1 = the ICI/device axis)
                        new_ops.append(framework.Operator(
                            block, "c_allreduce_sum",
                            inputs={"X": [gname]},
                            outputs={"Out": [gname]},
                            attrs={"ring_id": 1, "use_calc_stream": True}))
                        continue
                    gvar = block.vars.get(gname)
                    if gvar is not None and getattr(
                            gvar, "type", "lod_tensor") == "selected_rows":
                        # sparse grads: gather rows/values in-host first,
                        # then across hosts (see GradAllReduce for why
                        # gather-not-reduce)
                        for ring in (1, 0):
                            for name in (gname, gname + "@ROWS"):
                                new_ops.append(framework.Operator(
                                    block, "c_allgather",
                                    inputs={"X": [name]},
                                    outputs={"Out": [name]},
                                    attrs={"ring_id": ring,
                                           "use_calc_stream": True}))
                        continue
                    new_ops.append(framework.Operator(
                        block, "c_hierarchical_allreduce",
                        inputs={"X": [gname]}, outputs={"Out": [gname]},
                        attrs={"ring_id": 0, "use_calc_stream": True}))
            elif op.type == "dgc":
                for cname in op.output("GradOut"):
                    # only the compressed payload crosses DCN (ring 0)
                    new_ops.append(framework.Operator(
                        block, "c_allreduce_sum",
                        inputs={"X": [cname]}, outputs={"Out": [cname]},
                        attrs={"ring_id": 0, "use_calc_stream": True}))
        block.ops = new_ops
        self.main_program._bump()


class LocalSGD(Collective):
    """Periodic parameter averaging (reference ``collective.py:269``):
    every k steps, params = pmean(params). The emitted
    ``c_allreduce_avg`` rides ring 0 — on a 2-level ``(host, device)``
    mesh that is the DCN/host axis, so LocalSGD syncs ONLY across
    hosts (devices inside a host already share gradients every step);
    on a flat mesh ring 0 is the one axis and behavior is unchanged."""

    def __init__(self, nranks=None, k_steps=1):
        super().__init__(nranks)
        self.k_steps = k_steps

    def _transpile_main_program(self):
        block = self.main_program.global_block()
        if self.k_steps <= 1:
            for param in self.main_program.all_parameters():
                block.append_op(
                    "c_allreduce_avg",
                    inputs={"X": [param.name]}, outputs={"Out": [param.name]},
                    attrs={"ring_id": 0})
            self.main_program._bump()
            return
        # Gated averaging every k steps. The collective itself always runs
        # (SPMD collectives cannot be skipped per-step without divergent
        # control flow); the *application* is gated in-graph:
        #   p' = sync ? pmean(p) : p,  sync = (step % k == 0)
        from ..framework import program_guard
        from ..layers import nn, tensor

        with program_guard(self.main_program, self.startup_program):
            step = nn.autoincreased_step_counter(
                counter_name="@LOCALSGD_STEP@", begin=1)
            k = tensor.fill_constant([1], "int64", self.k_steps)
            mod = nn.elementwise_sub(
                step, nn.elementwise_mul(nn.elementwise_floordiv(step, k), k))
            sync = tensor.cast(
                nn.elementwise_sub(tensor.ones([1], "int64"),
                                   tensor.cast(mod > 0, "int64")), "float32")
        for param in self.main_program.all_parameters():
            avg = block.create_var(
                name=param.name + ".localsgd_avg", shape=param.shape,
                dtype=param.dtype, stop_gradient=True)
            block.append_op(
                "c_allreduce_avg",
                inputs={"X": [param.name]}, outputs={"Out": [avg.name]},
                attrs={"ring_id": 0})
            # p' = p + sync * (avg - p)
            with program_guard(self.main_program, self.startup_program):
                delta = nn.elementwise_mul(
                    nn.elementwise_sub(avg, param), sync, axis=-1)
                newp = nn.elementwise_add(param, delta)
            block.append_op("assign", inputs={"X": [newp]},
                            outputs={"Out": [param.name]})
        self.main_program._bump()
