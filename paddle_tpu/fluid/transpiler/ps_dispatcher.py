"""Parameter-to-pserver dispatchers (reference
``transpiler/ps_dispatcher.py``: RoundRobin / HashName decide which
endpoint owns each parameter block)."""

import zlib

__all__ = ["PSDispatcher", "RoundRobin", "HashName"]


class PSDispatcher:
    def __init__(self, pserver_endpoints):
        self._eps = list(pserver_endpoints)
        self._step = 0

    @property
    def eps(self):
        return self._eps

    def reset(self):
        self._step = 0

    def dispatch(self, varlist):
        raise NotImplementedError


class RoundRobin(PSDispatcher):
    """Endpoints assigned in rotation (reference ``ps_dispatcher.py``
    RoundRobin)."""

    def dispatch(self, varlist):
        out = []
        for _v in varlist:
            out.append(self._eps[self._step % len(self._eps)])
            self._step += 1
        return out


class HashName(PSDispatcher):
    """Endpoint chosen by name hash — deterministic across trainers
    without coordination (reference ``ps_dispatcher.py`` HashName)."""

    def dispatch(self, varlist):
        out = []
        for v in varlist:
            name = v if isinstance(v, str) else v.name
            # crc32, not builtin hash(): per-process hash salting would
            # send different trainers to different endpoints
            out.append(self._eps[zlib.crc32(name.encode())
                                 % len(self._eps)])
        return out
