"""Geo-SGD distributed transpiler.

Parity: reference ``transpiler/geo_sgd_transpiler.py:48``
``GeoSgdTranspiler`` — parameter-server training where workers train
against a LOCAL parameter copy and ship accumulated DELTAS every
``geo_sgd_need_push_nums`` updates, instead of per-step push/pull.

Built over this repo's tiers: the pserver side is identical to
``DistributeTranspiler`` (the delta arrives as a gradient with lr = -1,
an additive apply); the trainer side interposes the geo table proxy
(``fluid/communicator.py`` ``_GeoTableProxy``) in front of every
distributed table, so program pulls serve the local mirror and pushes
update it, with ``GeoCommunicator`` shipping/rebasing on cadence.
"""

from .distribute_transpiler import (DistributeTranspiler,
                                    DistributeTranspilerConfig)

__all__ = ["GeoSgdTranspiler"]


class GeoSgdTranspiler(DistributeTranspiler):
    def __init__(self, config=None):
        if config is None:
            config = DistributeTranspilerConfig()
        super(GeoSgdTranspiler, self).__init__(config)
        self._geo_k = int(getattr(config, "geo_sgd_need_push_nums", 100))
        self._geo_comms = {}

    def transpile(self, trainer_id, program=None, pservers="", trainers=1,
                  sync_mode=False, startup_program=None):
        # geo is an async mode by definition
        super(GeoSgdTranspiler, self).transpile(
            trainer_id, program, pservers, trainers, sync_mode=False,
            startup_program=startup_program)

    def get_trainer_program(self, wait_port=True, push_init=True):
        from ...distributed import ps
        from ..communicator import _GeoTableProxy

        program = super(GeoSgdTranspiler, self).get_trainer_program(
            wait_port=wait_port, push_init=push_init)
        # swap the remote proxies for geo views: local-mirror training,
        # delta push every _geo_k updates. Idempotent: a second
        # get_trainer_program call must not wrap the proxy in another
        # proxy (the delta would land in the first mirror, never the PS)
        for name in self._tables:
            if name in self._geo_comms:
                continue
            remote = ps.get_table(name)
            comm = ps.GeoCommunicator(remote, k_steps=self._geo_k)
            self._geo_comms[name] = comm
            ps.register_table(name, _GeoTableProxy(remote, comm))
        return program

    def sync(self):
        """Force-ship all pending deltas (end-of-pass; the reference's
        final geo push on barrier)."""
        for comm in self._geo_comms.values():
            comm.maybe_sync(force=True)
