"""Legacy memory-optimization entry points.

Parity: reference ``transpiler/memory_optimization_transpiler.py:18`` —
since 1.6 these are deprecation warnings, not rewrites (the runtime's
default strategies replaced them). The same is true here, more so: XLA's
buffer assignment plus donation (``enable_inplace``) owns reuse, and the
eager-deletion analysis survives as the native last-use plan
(``native/program_graph.cc``), which ``memory_optimize`` reports when
available so callers still get the visibility the old pass printed.
"""

import logging

__all__ = ["memory_optimize", "release_memory"]


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=True):
    """Deprecated no-op (reference behavior since 1.6). Logs where the
    equivalent machinery lives now; with ``print_log`` also reports the
    native last-use (eager-deletion) plan size for the program."""
    logging.warning(
        "paddle.fluid.memory_optimize() is deprecated and takes no "
        "effect: XLA buffer assignment + donation "
        "(build_strategy.enable_inplace, on by default) own buffer "
        "reuse on this backend.")
    if print_log:
        try:
            from ..native_program import NativeProgram

            np_ = NativeProgram.from_program(input_program)
            if np_ is not None:
                plan = np_.last_use(0)
                logging.warning(
                    "last-use plan: %d vars become dead across %d ops "
                    "(advisory; XLA already frees at these points)",
                    sum(len(v) for v in plan.values()), len(plan))
        except Exception:
            pass
    return None


def release_memory(input_program, skip_opt_set=None):
    """Deprecated no-op (reference behavior since 1.6)."""
    logging.warning(
        "paddle.fluid.release_memory() is deprecated and takes no "
        "effect on this backend.")
    return None
