"""Executor: runs Programs by lowering blocks to compiled XLA computations.

Capability parity: reference ``python/paddle/fluid/executor.py:418`` and C++
``framework/executor.cc`` — feed/fetch, scope-held persistable state, startup
program execution, compile caching.

TPU-first redesign: instead of an op-by-op interpreter hot loop
(``executor.cc:445``), the whole block is traced ONCE into a pure function

    step(state_dict, feed_dict, rng_key) -> (fetches, new_state, new_key)

jit-compiled with buffer donation on ``state`` (the XLA analogue of the
reference's in-place scope mutation + eager GC: donation lets XLA reuse
parameter buffers for their updated values, so an optimizer step is
allocation-free). Recompilation is avoided via a cache keyed on
(program identity, mutation counter, feed signature, fetch list).

Data-parallel / model-parallel execution reuses the same lowered function
under a ``jax.sharding.Mesh`` with GSPMD shardings supplied by
``CompiledProgram`` (see ``compiler.py``) — the reference's multi-device
SSA-graph executor (``details/fast_threaded_ssa_graph_executor.cc``) is
replaced by XLA partitioning + ICI collectives.
"""

import os

import numpy as np

from . import compile_cache as _compile_cache
from . import framework
from . import monitor as _monitor
from . import rng as _rng
from .framework import Program, Variable, convert_dtype
from .registry import LowerCtx, lower_block

__all__ = ["Executor", "Scope", "global_scope", "scope_guard",
           "register_run_hook", "unregister_run_hook"]

# -- monitor series (process-wide; see fluid/monitor.py) ----------------------
_M_RUN_SECONDS = _monitor.histogram(
    "executor_run_seconds",
    help="Executor.run wall time (feed normalization + compile-cache "
         "lookup + dispatch; includes device sync only while profiling)")
_M_RUNS = _monitor.counter(
    "executor_run_total", help="completed Executor.run calls")
_M_CACHE_HIT = _monitor.counter(
    "executor_compile_cache_hit_total",
    help="Executor.run served by an already-jitted step")
_M_CACHE_MISS = _monitor.counter(
    "executor_compile_cache_miss_total",
    help="Executor.run that traced+jitted a new step "
         "(program/feed-signature/fetch-list/sharding change)")
# tier-labeled views of the same series (the unlabeled legacy counters
# keep their exact semantics): tier=memory is this process's dict,
# tier=disk (owned by fluid/compile_cache.py) is the persistent tier a
# restart hits
_M_CACHE_HIT_MEM = _monitor.counter(
    "executor_compile_cache_hit_total",
    help="compile-cache hits by tier",
    labels={"tier": "memory"})
_M_CACHE_MISS_MEM = _monitor.counter(
    "executor_compile_cache_miss_total",
    help="compile-cache misses by tier",
    labels={"tier": "memory"})
_M_BATCHED_RUNS = _monitor.counter(
    "executor_batched_run_total",
    help="Executor.run calls that lowered iters>1 steps into one "
         "device-side loop (lax.scan) dispatch")
_M_BATCHED_ITERS = _monitor.counter(
    "executor_batched_iters_total",
    help="device-side training steps executed inside batched runs "
         "(sum of iters over executor_batched_run_total)")
_M_FETCH_SYNC = _monitor.histogram(
    "executor_fetch_sync_seconds",
    help="device->host fetch materialization (the blocking sync): "
         "return_numpy=True observes once per fetch at run time, "
         "fetch_mode='async' only when FetchHandle.numpy()/indexing "
         "forces the value — zero samples means no host sync happened")
_M_WINDOW_STALL = _monitor.histogram(
    "executor_window_stall_seconds",
    help="host wait for a prefetched iters=k window to finish its "
         "drain+stack+stage (0 when the window was already staged — "
         "the prefetch fully hid the host-side feed work)")
_M_OVERLAP_HIT = _monitor.counter(
    "executor_window_overlap_hit_total",
    help="batched runs served by an already-prefetched window "
         "(drain/stack/stage overlapped the previous window's compute)")
_M_OVERLAP_MISS = _monitor.counter(
    "executor_window_overlap_miss_total",
    help="prefetch-requested batched runs that drained inline "
         "(first window of a pass, or the pass just restarted after EOF)")
_M_PREFETCH_INFLIGHT = _monitor.gauge(
    "executor_window_prefetch_inflight",
    help="window prefetches currently draining/staging in the "
         "background (0 or 1 per Executor)")
_M_ANOMALY = _monitor.counter(
    "executor_anomaly_nonfinite_total",
    help="steps whose fetches/updated state contained non-finite values "
         "(or an injected step.nonfinite fault)")
_M_ANOMALY_SKIPPED = _monitor.counter(
    "executor_anomaly_skipped_steps_total",
    help="training steps discarded (state not committed) by the "
         "skip_step anomaly policy")
_M_ANOMALY_ROLLBACKS = _monitor.counter(
    "executor_anomaly_rollbacks_total",
    help="rollback-policy restores to the last intact checkpoint after "
         "a non-finite step")

# -- run hooks ----------------------------------------------------------------
_RUN_HOOKS = []


def register_run_hook(fn):
    """Register ``fn(record)`` to fire once after every completed
    ``Executor.run`` (the compiled-step path; server loops and EOF'd
    py_reader runs never complete a step). ``record`` keys:
    ``program_id`` (Program._uid), ``fetch_names``, ``wall_time``
    (seconds), ``cache_hit``, ``profiler_enabled``. A step-batched run
    (``Executor.run(..., iters=k)`` with k >= 2) still fires the hook
    ONCE for the whole device-side loop and adds an ``iters`` key
    (``record["iters"] == k``); single-step runs carry no ``iters`` key
    (read ``record.get("iters", 1)``). Hook exceptions are
    logged and swallowed — observability must not fail training.
    Returns ``fn`` so it composes as a decorator."""
    _RUN_HOOKS.append(fn)
    return fn


def unregister_run_hook(fn):
    """Remove a previously registered run hook (no-op if absent)."""
    try:
        _RUN_HOOKS.remove(fn)
    except ValueError:
        pass


def _fire_run_hooks(record):
    for fn in list(_RUN_HOOKS):
        try:
            fn(record)
        except Exception:
            import logging

            logging.getLogger(__name__).exception(
                "executor run hook %r failed", fn)


class Scope:
    """name -> device array store (reference ``framework/scope.h:46``)."""

    def __init__(self, parent=None):
        self.vars = {}
        self.parent = parent
        self.kids = []

    def new_scope(self):
        s = Scope(self)
        self.kids.append(s)
        return s

    def find_var(self, name):
        s = self
        while s is not None:
            if name in s.vars:
                return s.vars[name]
            s = s.parent
        return None

    def has_var(self, name):
        return self.find_var(name) is not None

    def set_var(self, name, value):
        self.vars[name] = value

    def erase(self, name):
        self.vars.pop(name, None)

    def drop_kids(self):
        self.kids = []

    def local_var_names(self):
        return list(self.vars)

    def var_names(self):
        """All visible names: this scope plus ancestors (find_var order;
        shadowed ancestor names appear once)."""
        seen, s = [], self
        while s is not None:
            for n in s.vars:
                if n not in seen:
                    seen.append(n)
            s = s.parent
        return seen


_global_scope = Scope()
_scope_stack = [_global_scope]


def global_scope():
    return _scope_stack[-1]


import contextlib


@contextlib.contextmanager
def scope_guard(scope):
    _scope_stack.append(scope)
    try:
        yield
    finally:
        _scope_stack.pop()


RNG_STATE_VAR = "@rng_state@"


def _feed_signature(feed, block):
    sig = []
    for name in sorted(feed):
        arr = feed[name]
        dt = getattr(arr, "dtype", None)  # avoid np.asarray on device arrays
        if dt is None:
            dt = np.asarray(arr).dtype
        sig.append((name, tuple(np.shape(arr)), str(dt)))
    return tuple(sig)


def _split_batched_feed(feed, block, iters, batch_factor=1):
    """Classify each ``iters=k`` feed as per-iteration STACKED
    (``[k, ...]``, sliced by the device-side loop) or loop-INVARIANT
    (the per-step shape, reused every iteration).

    Vars with a fully static declared shape are validated exactly;
    when the declared shape has dynamic (-1) batch dims, the leading
    axis decides: ``shape[0] == k`` means one slice per iteration.
    Ambiguity (a per-step shape whose own leading dim equals k)
    resolves to the declared/per-step reading for static vars and the
    stacked reading for dynamic ones — stack explicitly to be safe.

    ``batch_factor > 1`` (manual pipeline mode): programs traced at the
    per-shard microbatch size take per-step feeds at the FULL batch —
    leading dim scaled by ``M * data * host`` — so that scaled shape is
    accepted alongside the declared one (batch-invariant feeds like an
    attention bias still arrive at their declared shape)."""
    stacked, invariant = {}, {}
    for name, arr in feed.items():
        shape = tuple(np.shape(arr))
        var = block._find_var_recursive(name)
        declared = tuple(int(d) for d in var.shape) \
            if var is not None and var.shape is not None else None
        static = declared is not None and all(d >= 0 for d in declared)
        if static:
            per_step = {declared}
            if batch_factor > 1 and declared and declared[0] > 0:
                per_step.add((declared[0] * batch_factor,) + declared[1:])
            if shape in per_step:
                invariant[name] = arr
            elif shape[:1] == (iters,) and shape[1:] in per_step:
                stacked[name] = arr
            elif shape[:1] == (iters,):
                raise ValueError(
                    "iters=%d: stacked feed %r has per-step shape %s "
                    "but var %r declares shape %s"
                    % (iters, name, list(shape[1:]), name,
                       list(declared)))
            else:
                raise ValueError(
                    "iters=%d: feed %r has shape %s — pass either the "
                    "per-step shape %s (reused every iteration) or a "
                    "leading-axis stack %s (one slice per iteration)"
                    % (iters, name, list(shape), list(declared),
                       [iters] + list(declared)))
        else:
            if shape[:1] == (iters,):
                stacked[name] = arr
            else:
                invariant[name] = arr
    return stacked, invariant


def _local_view(x):
    """Host-readable numpy view of a possibly multi-process array: a
    non-fully-addressable array (replicated or sharded across processes)
    is read through its first LOCAL shard — the shard-local view every
    SPMD process can materialize without a cross-host gather. The one
    conversion helper shared by the sync fetch path, the async
    ``FetchHandle``, save ops, and the nan/inf debug checks."""
    if hasattr(x, "is_fully_addressable") and not x.is_fully_addressable:
        return np.asarray(x.addressable_shards[0].data)
    return np.asarray(x)


def _fetch_numpy(x):
    """Materialize one fetch on the host (THE blocking device sync —
    observed by ``executor_fetch_sync_seconds``), multiprocess-safe: a
    replicated global array reads its local replica; a SHARDED global
    fetch has no complete local value, so fail loudly rather than
    return a slice."""
    if hasattr(x, "is_fully_addressable") and not x.is_fully_addressable \
            and not getattr(x.sharding, "is_fully_replicated", False):
        raise ValueError(
            "fetch is sharded across processes (%s); fetch with "
            "return_numpy=False and gather explicitly (e.g. "
            "multihost_utils.process_allgather)" % (x.sharding,))
    with _M_FETCH_SYNC.time():
        return _local_view(x)


class FetchHandle:
    """A fetch result still in flight on the device
    (``Executor.run(..., fetch_mode="async")``).

    JAX dispatch is asynchronous: ``run`` returns as soon as the step is
    enqueued, and the handle wraps the resulting ``jax.Array`` WITHOUT
    forcing a device->host sync — back-to-back windows keep the device
    busy. The sync happens exactly when you ask for host data:
    ``.numpy()``, indexing, ``np.asarray(handle)``, or ``float(handle)``
    (each observes ``executor_fetch_sync_seconds``). ``.value`` exposes
    the raw in-flight array and ``shape``/``dtype``/``repr`` never
    sync."""

    __slots__ = ("_value", "name")

    def __init__(self, value, name=None):
        self._value = value
        self.name = name

    @property
    def value(self):
        """The underlying (possibly in-flight) array — no sync."""
        return self._value

    @property
    def shape(self):
        return tuple(np.shape(self._value))

    @property
    def dtype(self):
        return getattr(self._value, "dtype", None)

    def block_until_ready(self):
        """Wait for the device computation, keep data on device (no
        transfer). Returns self for chaining."""
        import jax

        jax.block_until_ready(self._value)
        return self

    def numpy(self):
        """Materialize on the host (blocking sync)."""
        return _fetch_numpy(self._value)

    def __getitem__(self, idx):
        return self.numpy()[idx]

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def __float__(self):
        return float(self.numpy())

    def __repr__(self):
        return "FetchHandle(name=%r, shape=%s, dtype=%s)" % (
            self.name, self.shape, self.dtype)


class _CompiledStep:
    """One jit-compiled (program block, feed-sig, fetch-list) entry."""

    def __init__(self, fn, state_names, fetch_names):
        self.fn = fn
        self.state_names = state_names
        self.fetch_names = fetch_names


class _WindowPrefetch:
    """One in-flight background drain+stack+stage of the NEXT ``iters=k``
    py_reader window (``Executor.run(..., iters=k, prefetch=True)``).

    While the device executes window i, this thread pulls the k batches
    of window i+1 from the py_reader queues, stacks them ``[k, ...]``
    (``_to_arrays`` already normalized dtype/shape to the declared
    slots), and ``jax.device_put``s the stacks with the program's GSPMD
    feed sharding (``CompiledProgram.feed_sharding`` at ``batch_dim=1``
    — axis 0 is the iteration axis) — so when window i's dispatch
    returns, window i+1's feeds are already device-resident and
    pre-sharded. EOF is detected here but ACTED ON at consume time: the
    consuming run resets the readers and raises ``EOFException`` before
    any step executes, preserving the inline path's
    EOF-before-step contract.

    The thread is NON-daemon (tests/conftest.py fails tests that leak
    one); ``consume()``/``discard()`` join it. ``_next()`` only blocks
    as long as the user's generator takes to yield, so the join is
    bounded by one window of host feed work."""

    def __init__(self, py_readers, iters, sharding_fn=None):
        import threading

        self.key = (tuple(id(r) for r in py_readers), iters)
        self.readers = list(py_readers)
        self.iters = iters
        self._sharding_fn = sharding_fn
        self._result = ("error", RuntimeError("prefetch never ran"))
        self._thread = threading.Thread(
            target=self._drain, name="paddle-window-prefetch",
            daemon=False)
        self._thread.start()

    def _drain(self):
        import jax

        try:
            with _M_PREFETCH_INFLIGHT.track():
                pulled = {r: [] for r in self.readers}
                for i in range(self.iters):
                    step_vals = [(r, r._next()) for r in self.readers]
                    if any(v is None for _, v in step_vals):
                        partial = bool(i) or any(v is not None
                                                 for _, v in step_vals)
                        self._result = ("eof", i, partial)
                        return
                    for r, vals in step_vals:
                        pulled[r].append(vals)
                feed = {}
                for r, items in pulled.items():
                    for j, name in enumerate(r.names):
                        arr = np.stack([vals[j] for vals in items])
                        s = self._sharding_fn(name, arr) \
                            if self._sharding_fn is not None else None
                        feed[name] = jax.device_put(arr, s) \
                            if s is not None else jax.device_put(arr)
                self._result = ("ok", feed)
        except BaseException as e:  # background thread: stored and re-raised on the consuming run
            self._result = ("error", e)

    def consume(self):
        """Join the drain and return ``("ok", feed)``, ``("eof",
        n_pulled, partial)`` or ``("error", exc)``. The join time IS
        the window stall — 0 when the prefetch finished during the
        previous window's compute."""
        import time as _time

        t0 = _time.perf_counter()
        self._thread.join()
        _M_WINDOW_STALL.observe(_time.perf_counter() - t0)
        return self._result

    def discard(self):
        """Join and drop the result (Executor.close / abandoned loop).
        Already-pulled batches are lost, like any abandoned pass."""
        self._thread.join()
        self._result = ("error", RuntimeError("prefetch discarded"))


class Executor:
    """Reference ``executor.py:418``. ``place`` is advisory — JAX device
    placement is controlled by the default backend / shardings."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}
        # extra read-only disk-cache tiers consulted on a memory miss
        # (e.g. a Predictor's model-adjacent __prelowered__ directory);
        # the env-configured PADDLE_COMPILE_CACHE_DIR joins implicitly
        self._cache_read_dirs = []
        # (reader ids, iters) -> in-flight _WindowPrefetch; one entry
        # per distinct prefetching batched loop (close() reaps them all)
        self._window_prefetch = {}
        # consecutive steps discarded by the skip_step/rollback anomaly
        # policy; a clean step resets it, exceeding the budget raises
        self._anomaly_skips = 0
        # donate the state dict to the step executable (training wants
        # the buffer reuse). Inference-path executors (Predictor,
        # prelower export) set this False: donation bakes input->output
        # aliasing into AOT-compiled executables — the ones the
        # persistent cache serializes — and on CPU those run IN-PLACE
        # over buffers that serving still exposes through zero-copy
        # numpy views, corrupting served results after a cache restore.
        # (A plain jit dispatch drops donation on CPU, which is why
        # only the deserialized/AOT path was exposed.) The bit joins
        # the disk cache key, so writer and reader must agree.
        self._donate_state = True

    # -- anomaly policy (nan/inf) --------------------------------------
    def _scan_anomaly(self, fetch_names, fetches, new_state):
        """First non-finite (kind, var name) among fetches and updated
        state, or None. Runs when FLAGS_check_nan_inf is on, when the
        anomaly policy is not 'raise', or when a step.nonfinite fault is
        armed; costs one host sync by design. Shard-local on
        multi-process arrays (every SPMD process scans its shard)."""
        from . import faults as _faults
        from . import flags as _flags

        enabled = (_flags.check_nan_inf_enabled()
                   or _flags.anomaly_policy() != "raise"
                   or _faults.is_armed("step.nonfinite"))
        if not enabled:
            return None
        if _faults.take("step.nonfinite"):
            return ("injected", "step.nonfinite")
        for label, vals in (("fetch", zip(fetch_names, fetches)),
                            ("state", new_state.items())):
            for n, v in vals:
                arr = _local_view(v)
                if np.issubdtype(arr.dtype, np.floating) and \
                        not np.isfinite(arr).all():
                    return (label, n)
        return None

    def _handle_anomaly(self, where, program, scope, checkpoint, iters):
        """Apply the configured anomaly policy to a non-finite step.
        Returns True when the step (or whole ``iters=k`` window) must be
        DISCARDED — the caller then commits neither state nor rng.

        ``raise``: legacy behavior, FloatingPointError names the var.
        ``skip_step``: drop this step's updates, keep training on the
        previous weights; after ``FLAGS_anomaly_skip_budget`` CONSECUTIVE
        anomalous steps it raises anyway (a persistently diverged run
        must not spin forever). ``rollback``: additionally restore the
        last intact checkpoint (requires ``checkpoint=(manager, n)`` on
        this run call), rewinding optimizer state and rng with the
        params. Skip/rollback keep the PRE-step scope arrays live, so
        they need XLA buffer donation off (the executor builds its plain
        jit undonated for these policies automatically; sharded runs set
        ``build_strategy.enable_inplace = False``)."""
        from . import flags as _flags

        _M_ANOMALY.inc()
        policy = _flags.anomaly_policy()
        msg = ("non-finite values in %s var %r after running program"
               % where)
        if policy == "raise":
            raise FloatingPointError("FLAGS_check_nan_inf: " + msg)
        self._anomaly_skips += 1
        budget = _flags.anomaly_skip_budget()
        if self._anomaly_skips > budget:
            raise FloatingPointError(
                "anomaly policy %r: %s — %d consecutive anomalous steps "
                "exceeded FLAGS_anomaly_skip_budget=%d"
                % (policy, msg, self._anomaly_skips, budget))
        import logging

        log = logging.getLogger(__name__)
        if policy == "rollback":
            if checkpoint is None:
                raise RuntimeError(
                    "anomaly policy 'rollback' needs a checkpoint to "
                    "roll back to — call Executor.run(..., "
                    "checkpoint=(CheckpointManager, every_n_steps))")
            step = checkpoint[0].restore(self, program, scope=scope)
            _M_ANOMALY_ROLLBACKS.inc()
            log.warning("anomaly policy rollback: %s; restored "
                        "checkpoint step %d (%d/%d consecutive)",
                        msg, step, self._anomaly_skips, budget)
        else:
            _M_ANOMALY_SKIPPED.inc(iters)
            log.warning("anomaly policy skip_step: %s; discarding the "
                        "step's updates (%d/%d consecutive)",
                        msg, self._anomaly_skips, budget)
        return True

    @staticmethod
    def _check_checkpoint_arg(checkpoint):
        if checkpoint is None:
            return None
        try:
            mgr, every = checkpoint
        except (TypeError, ValueError):
            raise ValueError(
                "checkpoint must be a (CheckpointManager, every_n_steps) "
                "pair, got %r" % (checkpoint,))
        if not hasattr(mgr, "step_completed") or int(every) < 1:
            raise ValueError(
                "checkpoint must be a (CheckpointManager, every_n_steps "
                ">= 1) pair, got %r" % (checkpoint,))
        return mgr, int(every)

    # ------------------------------------------------------------------
    def run(
        self,
        program=None,
        feed=None,
        fetch_list=None,
        scope=None,
        return_numpy=True,
        iters=1,
        fetch_mode=None,
        prefetch=False,
        checkpoint=None,
    ):
        """``iters=1`` (default): one feed/fetch step, the legacy path.

        ``iters=k`` (k >= 2): step-batched execution — the program's step
        function is compiled ONCE and ``k`` steps run inside a single
        jitted dispatch (``jax.lax.scan`` carrying ``(state, rng)`` with
        buffer donation), amortizing the per-step Python + PJRT round
        trip the way the reference's C++ hot loop (``executor.cc:445``)
        amortizes op dispatch. Feed contract: each feed is either a
        leading-axis stack ``[k, ...]`` (one slice per iteration) or the
        plain per-step shape (loop-invariant, reused every iteration);
        py_reader-fed programs instead drain exactly ``k`` batches up
        front. Each fetch returns the per-iteration trajectory, stacked
        ``[k, ...]``. See ``_run_batched`` and README "Step-batched
        execution".

        ``fetch_mode="async"``: return ``FetchHandle`` objects instead
        of numpy — the step is dispatched but run() never blocks on a
        device->host sync; each handle syncs only when ``.numpy()`` /
        indexing forces it. ``fetch_mode="sync"`` (or None) is the
        legacy behavior, where ``return_numpy`` decides between numpy
        (blocking per fetch) and raw in-flight ``jax.Array``s.

        ``prefetch=True`` (needs ``iters=k`` and a py_reader-fed
        program): after dispatching this window, a background thread
        drains + stacks + device-stages window i+1's batches while the
        device executes window i, so the next ``run`` finds its feeds
        already staged (``executor_window_overlap_hit_total``).
        EOF-before-step semantics are preserved. See README "Async
        execution".

        ``checkpoint=(manager, every_n_steps)``: after every committed
        step (``iters=k`` counts k), the ``fluid.io.CheckpointManager``
        advances its step counter and writes a crash-consistent
        checkpoint each time it crosses a multiple of ``every_n_steps``
        — pair with ``manager.restore_on_restart`` for auto-resume under
        ``distributed.launch(max_restarts=...)``. Also the rollback
        target for the ``rollback`` anomaly policy (README "Fault
        tolerance")."""
        checkpoint = self._check_checkpoint_arg(checkpoint)
        if fetch_mode not in (None, "sync", "async"):
            raise ValueError(
                "fetch_mode must be None, 'sync' or 'async', got %r"
                % (fetch_mode,))
        iters = int(iters)
        if iters < 1:
            raise ValueError("iters must be >= 1, got %d" % iters)
        if prefetch and iters == 1:
            raise ValueError(
                "prefetch=True needs iters>=2: window prefetch overlaps "
                "the NEXT step-batched window with this one's compute — "
                "single steps already overlap via async dispatch "
                "(fetch_mode='async')")
        if iters > 1:
            return self._run_batched(program, feed, fetch_list, scope,
                                     return_numpy, iters, fetch_mode,
                                     prefetch, checkpoint)
        import time as _time

        import jax

        _t_run0 = _time.perf_counter()
        scope = scope or global_scope()
        feed = dict(feed or {})
        fetch_list = list(fetch_list or [])
        fetch_names = [v.name if isinstance(v, Variable) else str(v) for v in fetch_list]

        # CompiledProgram carries sharding strategy; plain Program runs single-device.
        from . import compiler

        strategy = None
        if isinstance(program, compiler.CompiledProgram):
            strategy = program
            program = strategy._program
        if program is None:
            program = framework.default_main_program()

        block = program.global_block()

        # graceful preemption (distributed.preemption): launched workers
        # have PADDLE_PREEMPT_DRAIN=1, so the first run() installs the
        # SIGTERM drain handlers; a signal that already arrived drains
        # HERE — before the step — through the active CheckpointManager
        # and exits 0 (drain_exit does not return).
        from ..distributed import preemption as _preemption

        _preemption.maybe_install_from_env()
        _preemption.check_drain(checkpoint[0] if checkpoint else None,
                                program, scope)

        # pserver programs don't compile — their listen_and_serv op is a
        # host serving loop; running one blocks, like the reference's
        # pserver Executor (listen_and_serv_op.cc RunSyncLoop). The same
        # scan collects py_reader queues so EOF can surface after the step.
        py_readers = []
        # save ops write once per run, after commit — which is only
        # truthful at the top level. Inside control flow (a cond branch
        # that may not run, a While body that may run 0 or N times) a
        # host file write cannot follow the predicate from within one
        # compiled step, so refuse rather than silently firing.
        save_ops = [(op.input("X")[0], op.attr("file_path"))
                    for op in block.ops if op.type == "save"]
        for blk in program.blocks:
            if blk is not block and any(op.type == "save"
                                        for op in blk.ops):
                raise RuntimeError(
                    "a save op inside a control-flow sub-block is not "
                    "supported: the compiled step cannot conditionally "
                    "write host files — move the save op to the global "
                    "block or checkpoint from the host loop "
                    "(fluid.io.save)")
        for op in block.ops:
            if op.type == "listen_and_serv":
                from .transpiler.distribute_transpiler import (
                    build_server_from_attrs)

                build_server_from_attrs(op.attrs).serve_forever()
                return []
            if op.type == "fl_listen_and_serv":
                # federated variant (reference fl_listen_and_serv_op):
                # initial params come from this scope's vars by name
                from ..distributed.fl_server import FLServer

                params = {}
                for name in op.attr("param_names"):
                    val = scope.find_var(name)
                    if val is None:
                        raise RuntimeError(
                            "fl_listen_and_serv param %r not in scope — "
                            "run the startup program first" % name)
                    params[name] = np.asarray(val)
                from ..distributed import fl_server as _fl

                configured = op.attr("endpoint")
                host, port = configured.rsplit(":", 1)
                srv = FLServer(params, op.attr("n_trainers"),
                               host=host, port=int(port))
                # register under BOTH the endpoint the program named and
                # the socket's resolved one (getsockname may differ,
                # e.g. localhost vs 127.0.0.1)
                for key in {configured, srv.endpoint}:
                    _fl.SERVING[key] = srv
                try:
                    srv.serve_forever()
                finally:
                    srv.stop()
                    for key in {configured, srv.endpoint}:
                        _fl.SERVING.pop(key, None)
                return []
            if op.type == "host_embedding_init":
                # host-side residency reset, synchronous with this run —
                # the in-program op is a no-op (an io_callback there fires
                # on a runtime thread after the async dispatch returns,
                # racing the next step's residency prepare and wiping the
                # LUT it just admitted)
                from .. import embedding as _embedding

                _embedding.get_host_table(
                    op.attr("table_name")).reset_residency()
            if op.type == "py_reader_dequeue":
                from .layers.py_reader import _READERS

                r = _READERS.get(int(op.attr("reader_id")))
                if r is None:
                    raise RuntimeError(
                        "the py_reader feeding this program was "
                        "garbage-collected — keep the object returned "
                        "by layers.py_reader() alive and start() it")
                py_readers.append(r)
        if py_readers:
            rids = {id(r) for r in py_readers}
            for pf in self._window_prefetch.values():
                if set(pf.key[0]) & rids:
                    raise RuntimeError(
                        "a prefetched iters=%d window is pending on "
                        "this program's py_reader(s) — a single-step "
                        "run would race it for batches. Finish the "
                        "batched loop (run with iters=%d until EOF) or "
                        "exe.close() first." % (pf.iters, pf.iters))
        if py_readers:
            # pull every reader's batch on the host BEFORE dispatch and
            # ride the normal feed path (works under any sharding
            # strategy); any empty queue raises EOF with no step run —
            # nothing to discard, donation stays on. All batches are
            # pulled before deciding, so uneven readers lose at most
            # the final ragged step (logged), exactly one epoch ends.
            pulled = [(r, r._next()) for r in py_readers]
            if any(v is None for _, v in pulled):
                from . import core as _core

                dropped = [r.names[0] for r, v in pulled if v is not None]
                if dropped:
                    import logging

                    logging.getLogger(__name__).warning(
                        "py_reader EOF: discarding the already-pulled "
                        "batch of %s (readers have unequal lengths)",
                        dropped)
                for r in py_readers:
                    r.reset()
                raise _core.EOFException(
                    "py_reader queue exhausted — reader.reset() and "
                    "re-start() for the next pass")
            for r, vals in pulled:
                feed.update(zip(r.names, vals))

        # host-tier embedding tables: translate this batch's raw ids into
        # resident-cache slots (admitting missing rows) and inject the
        # <table>@SLOTS feed — BEFORE normalization so the slots array is
        # part of the feed signature like any other input
        if getattr(program, "_embedding_bindings", None):
            from .. import embedding as _embedding

            _embedding.prepare_feed(program, feed, scope)

        # normalize feeds to declared dtype; device-resident jax Arrays pass
        # through untouched (the DataLoader/buffered-reader path pre-stages
        # H2D transfers — critical when the chip sits behind a slow link)
        from .lod import LoDTensor, lod_name

        for name in list(feed):
            if isinstance(feed[name], LoDTensor):
                # decompose: data under the name, int32 lengths under @LOD
                # (the bounded-LoD device encoding, see fluid/lod.py)
                feed[lod_name(name)] = feed[name].lengths()
                feed[name] = feed[name].data()
            if isinstance(feed[name], jax.Array):
                continue
            var = block._find_var_recursive(name)
            arr = np.asarray(feed[name])
            if var is not None and arr.dtype != var.dtype:
                arr = arr.astype(var.dtype)
            feed[name] = arr

        # persistable state visible to this program
        state_names = sorted(
            v.name
            for v in program.list_vars()
            if v.persistable and scope.has_var(v.name)
        )

        from . import flags as _flags

        # program._uid (a monotonic token) rather than id(program): a GC'd
        # Program's id can be reused, which would serve a stale compiled step.
        # The anomaly-policy bit joins the key because it flips buffer
        # donation (skip_step/rollback must keep pre-step buffers alive).
        key = (
            program._uid,
            program._mutation,
            _feed_signature(feed, block),
            tuple(fetch_names),
            tuple(state_names),
            strategy._uid if strategy is not None else 0,
            _flags.anomaly_policy() != "raise",
        )

        step = self._cache.get(key)
        cache_hit = step is not None
        (_M_CACHE_HIT if cache_hit else _M_CACHE_MISS).inc()
        (_M_CACHE_HIT_MEM if cache_hit else _M_CACHE_MISS_MEM).inc()
        if step is None:
            if _flags.check_program_enabled():
                # debug mode (reference multi_devices_check_pass): validate
                # well-formedness once per compiled signature
                from .passes import apply_pass

                apply_pass(program, "program_check",
                           feed_names=list(feed))
            step = self._build(program, block, feed, fetch_names, state_names, strategy)
            self._cache[key] = step

        # rng state: persists across runs in the scope
        rng = scope.find_var(RNG_STATE_VAR)
        if rng is None:
            seed = program.random_seed or 0
            rng = _rng.key_data(_rng.root_key(seed))
            scope.set_var(RNG_STATE_VAR, rng)

        state = {n: scope.find_var(n) for n in state_names}
        from . import profiler as _prof

        from .. import telemetry as _telemetry

        profiling = _prof.is_profiler_enabled()
        t0 = _prof.now() if profiling else None
        try:
            if _telemetry.enabled() and _telemetry.current() is not None:
                # traced request (serving batch ctx is ambient): the
                # device-dispatch interval joins the request's trace
                with _telemetry.span("executor.run",
                                     attrs={"program": program._uid,
                                            "cache_hit": cache_hit}):
                    fetches, new_state, new_rng = step.fn(state, feed,
                                                          rng)
            else:
                fetches, new_state, new_rng = step.fn(state, feed, rng)
        except Exception:
            # flight-recorder trigger: capture the ring (open spans show
            # the in-flight request) before the failure unwinds
            _telemetry.flight.dump(reason="executor_exception")
            raise
        if profiling:
            jax.block_until_ready(fetches)
            # the #p<uid> suffix keeps distinct programs with the same
            # leading fetches from colliding in the summary table
            _prof._record("executor_run[%s#p%d]" % (
                ",".join(fetch_names[:3]), program._uid),
                _prof.now() - t0)
        # nan/inf anomaly scan BEFORE commit (reference
        # FLAGS_check_nan_inf / nan_inf_utils, grown into a policy): a
        # non-finite step is handled per FLAGS_anomaly_policy — raise
        # (legacy, default), skip_step (discard the update), or rollback
        # (restore the last checkpoint). Discarded steps commit nothing.
        anomaly = self._scan_anomaly(fetch_names, fetches, new_state)
        discarded = False
        if anomaly is not None:
            discarded = self._handle_anomaly(anomaly, program, scope,
                                             checkpoint, iters=1)
        else:
            self._anomaly_skips = 0
        if not discarded:
            scope.set_var(RNG_STATE_VAR, new_rng)
            for n, v in new_state.items():
                scope.set_var(n, v)

        if save_ops and not discarded:
            # TPU deviation from save_op.cc (which executes at its
            # program-order position): the whole block runs as ONE
            # compiled step, so saves always record the POST-step
            # committed value, and only persistable (scope-held) vars
            # are saveable. One PTC1 entry per file — exactly what
            # layers.load reads back.
            from .core import tensor_io

            for name, path in save_ops:
                val = scope.find_var(name)
                if val is None:
                    raise RuntimeError(
                        "save op: var %r is not in the scope — only "
                        "PERSISTABLE vars can be saved (the step "
                        "commits those; intermediates are fused away "
                        "by XLA). fetch_list the value instead." % name)
                os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
                tensor_io.save_combine(path, {name: _fetch_numpy(val)})

        if checkpoint is not None and not discarded:
            checkpoint[0].step_completed(program, scope, 1, checkpoint[1])

        # a preemption signal that landed DURING the step drains now,
        # after the state committed — the step is never torn in half
        _preemption.check_drain(checkpoint[0] if checkpoint else None,
                                program, scope)

        wall = _time.perf_counter() - _t_run0
        _M_RUN_SECONDS.observe(wall)
        _M_RUNS.inc()
        if _RUN_HOOKS:
            record = {
                "program_id": program._uid,
                "fetch_names": list(fetch_names),
                "wall_time": wall,
                "cache_hit": cache_hit,
                "profiler_enabled": profiling,
            }
            if fetch_mode == "async":
                # omit-when-default, like iters: legacy records keep
                # their exact key set (read record.get("async", False))
                record["async"] = True
            _fire_run_hooks(record)

        if fetch_mode == "async":
            return [FetchHandle(x, name=n)
                    for n, x in zip(fetch_names, fetches)]
        if return_numpy:
            return [_fetch_numpy(x) for x in fetches]
        return list(fetches)

    # ------------------------------------------------------------------
    def _build(self, program, block, feed, fetch_names, state_names, strategy):
        import jax

        mesh = strategy.mesh if strategy is not None else None

        def step(state, feed_vals, rng_key):
            env = {}
            env.update(state)
            env.update(feed_vals)
            ctx = LowerCtx(block, env, _rng.wrap_key_data(rng_key),
                           mesh=mesh)
            if strategy is not None:
                strategy._on_trace_begin(ctx)
            lower_block(ctx, block)
            fetches = [ctx.get(n) for n in fetch_names]
            # Return ALL state (unchanged entries pass through as aliased
            # buffers under donation — returning them keeps the donated
            # buffers alive for the scope), plus vars that became
            # persistable during this program (startup init).
            new_state = {n: env[n] for n in state if n in env}
            new_state.update({n: env[n] for n in ctx.written if n in env})
            for name, var in block.vars.items():
                if var.persistable and name in env and name not in state:
                    new_state[name] = env[name]
            return fetches, new_state, _rng.key_data(ctx.rng_key)

        from . import flags as _flags

        donate = ((0,) if self._donate_state
                  and _flags.anomaly_policy() == "raise" else ())
        cache_key = None
        if _compile_cache.active(self._cache_read_dirs):
            cache_key = _compile_cache.step_key(
                program, _feed_signature(feed, block), fetch_names,
                state_names, strategy, 1, bool(donate))

        # Startup-style programs create new persistables -> output structure
        # depends on trace; jit handles that fine since structure is fixed
        # per cache entry.
        if strategy is not None and mesh is not None:
            return _CompiledStep(
                strategy.wrap_step(step, program, block, feed, fetch_names,
                                   state_names, cache_key=cache_key,
                                   cache_read_dirs=self._cache_read_dirs),
                state_names,
                fetch_names,
            )

        # skip_step/rollback re-commit the PRE-step scope arrays after a
        # discarded step; donation would have handed those buffers to XLA
        # (a no-op on CPU but fatal on TPU), so those policies compile
        # undonated (donate computed above joins the disk key). The
        # policy sits in the compile-cache key, so flipping
        # FLAGS_anomaly_policy recompiles rather than reusing a
        # mismatched executable.
        jfn = _compile_cache.wrap_jit(
            jax.jit(step, donate_argnums=donate), cache_key,
            read_dirs=self._cache_read_dirs,
            label="step#%s" % ",".join(fetch_names[:3]))
        return _CompiledStep(jfn, state_names, fetch_names)

    # -- step-batched execution (iters=k) ------------------------------
    def _run_batched(self, program, feed, fetch_list, scope, return_numpy,
                     iters, fetch_mode=None, prefetch=False,
                     checkpoint=None):
        """``Executor.run(..., iters=k)`` for k >= 2: one compiled
        executable drives k steps device-side. Kept separate from the
        single-step ``run`` body so ``iters=1`` stays byte-for-byte the
        legacy path (semantics, hook payloads, profiler events).
        ``prefetch=True`` overlaps the NEXT window's py_reader
        drain+stack+stage with this window's device compute
        (``_WindowPrefetch``); ``fetch_mode="async"`` returns
        ``FetchHandle``s, so a prefetching loop issues no host sync at
        all between windows."""
        import time as _time

        import jax

        _t_run0 = _time.perf_counter()
        scope = scope or global_scope()
        feed = dict(feed or {})
        fetch_list = list(fetch_list or [])
        fetch_names = [v.name if isinstance(v, Variable) else str(v)
                       for v in fetch_list]

        from . import compiler

        strategy = None
        if isinstance(program, compiler.CompiledProgram):
            strategy = program
            program = strategy._program
        if program is None:
            program = framework.default_main_program()
        block = program.global_block()

        # same drain hook as the single-step path: check between
        # windows, never inside one (the k-step device loop is the
        # commit unit)
        from ..distributed import preemption as _preemption

        _preemption.maybe_install_from_env()
        _preemption.check_drain(checkpoint[0] if checkpoint else None,
                                program, scope)

        py_readers = []
        for op in block.ops:
            if op.type in ("listen_and_serv", "fl_listen_and_serv"):
                raise RuntimeError(
                    "iters>1 cannot drive a server program (%s op): the "
                    "serving loop runs on the host — call exe.run "
                    "without iters" % op.type)
            if op.type == "host_embedding_init":
                from .. import embedding as _embedding

                _embedding.get_host_table(
                    op.attr("table_name")).reset_residency()
            if op.type == "py_reader_dequeue":
                from .layers.py_reader import _READERS

                r = _READERS.get(int(op.attr("reader_id")))
                if r is None:
                    raise RuntimeError(
                        "the py_reader feeding this program was "
                        "garbage-collected — keep the object returned "
                        "by layers.py_reader() alive and start() it")
                py_readers.append(r)
        save_ops = [(op.input("X")[0], op.attr("file_path"))
                    for op in block.ops if op.type == "save"]
        for blk in program.blocks:
            if blk is not block and any(op.type == "save"
                                        for op in blk.ops):
                raise RuntimeError(
                    "a save op inside a control-flow sub-block is not "
                    "supported: the compiled step cannot conditionally "
                    "write host files — move the save op to the global "
                    "block or checkpoint from the host loop "
                    "(fluid.io.save)")

        if prefetch and not py_readers:
            raise ValueError(
                "prefetch=True needs a py_reader-fed program — explicit "
                "feeds are the caller's to stage ahead of time "
                "(DataLoader use_double_buffer / fluid.reader.stage_feed)")

        rkey = (tuple(id(r) for r in py_readers), iters)
        pending = self._window_prefetch.get(rkey) if py_readers else None
        for k in list(self._window_prefetch):
            if k != rkey and set(k[0]) & set(rkey[0]):
                pf = self._window_prefetch[k]
                raise RuntimeError(
                    "a prefetched window (iters=%d) is pending on "
                    "py_reader(s) this run (iters=%d) also reads — the "
                    "prefetched batches would be mis-windowed. Keep a "
                    "prefetching batched loop's iters uniform, or "
                    "exe.close() between loops." % (pf.iters, iters))
        if pending is not None:
            # overlap hit: the window was drained+stacked+staged in the
            # background while the previous window computed
            del self._window_prefetch[rkey]
            status = pending.consume()
            if status[0] == "error":
                raise status[1]
            if status[0] == "eof":
                # EOF-before-step, exactly like the inline drain: reset,
                # raise, no step ran, partial pulls discarded (logged)
                from . import core as _core

                if status[2]:
                    import logging

                    logging.getLogger(__name__).warning(
                        "py_reader EOF during a prefetched batched run: "
                        "discarding %d already-pulled batch(es) of a "
                        "requested window of %d", status[1], iters)
                for r in py_readers:
                    r.reset()
                raise _core.EOFException(
                    "py_reader queue exhausted before %d batches — "
                    "reader.reset() and re-start() for the next pass"
                    % iters)
            _M_OVERLAP_HIT.inc()
            feed.update(status[1])
        elif py_readers:
            if prefetch:
                # first window of a pass (or the pass just restarted
                # after EOF): nothing staged yet, drain inline
                _M_OVERLAP_MISS.inc()
            # drain exactly `iters` batches per reader up front and stack
            # them [k, ...]; EOF before k batches ends the pass like the
            # single-step path (readers reset, EOFException, no step ran —
            # already-pulled batches of this window are discarded, so size
            # the pass to a multiple of k to lose nothing)
            pulled = {r: [] for r in py_readers}
            for i in range(iters):
                step_vals = [(r, r._next()) for r in py_readers]
                if any(v is None for _, v in step_vals):
                    from . import core as _core

                    if i or any(v is not None for _, v in step_vals):
                        import logging

                        logging.getLogger(__name__).warning(
                            "py_reader EOF during a batched run: "
                            "discarding %d already-pulled batch(es) of a "
                            "requested window of %d", i, iters)
                    for r in py_readers:
                        r.reset()
                    raise _core.EOFException(
                        "py_reader queue exhausted before %d batches — "
                        "reader.reset() and re-start() for the next pass"
                        % iters)
                for r, vals in step_vals:
                    pulled[r].append(vals)
            for r, items in pulled.items():
                for j, name in enumerate(r.names):
                    feed[name] = np.stack([vals[j] for vals in items])

        # host-tier embeddings: one residency transaction covers the whole
        # [k, ...] window — ids across all k steps are admitted together so
        # the scanned body only ever gathers resident slots
        if getattr(program, "_embedding_bindings", None):
            from .. import embedding as _embedding

            _embedding.prepare_feed(program, feed, scope, iters=iters)

        from .lod import LoDTensor

        for name in list(feed):
            if isinstance(feed[name], LoDTensor):
                raise ValueError(
                    "iters>1 does not take LoDTensor feeds — feed dense "
                    "arrays (plus explicit length arrays) stacked "
                    "[k, ...], or loop exe.run from the host")
            if isinstance(feed[name], jax.Array):
                continue
            var = block._find_var_recursive(name)
            arr = np.asarray(feed[name])
            if var is not None and arr.dtype != var.dtype:
                arr = arr.astype(var.dtype)
            feed[name] = arr

        batch_factor = 1
        if strategy is not None and \
                getattr(strategy, "_mode", "") == "pipeline":
            batch_factor = int(strategy._num_microbatches)
            mesh = strategy.mesh
            for ax in ("host", "data"):
                if mesh is not None and ax in mesh.shape:
                    batch_factor *= int(mesh.shape[ax])
        stacked, invariant = _split_batched_feed(feed, block, iters,
                                                 batch_factor)

        state_names = sorted(
            v.name
            for v in program.list_vars()
            if v.persistable and scope.has_var(v.name)
        )

        from . import flags as _flags

        # iters joins the key: a k-step executable is a different
        # program than a single step (8-tuple — never collides with the
        # single-step path's 7-tuple keys in the same cache); the
        # anomaly-policy bit flips buffer donation, like the single-step
        # path
        key = (
            program._uid,
            program._mutation,
            _feed_signature(feed, block),
            tuple(fetch_names),
            tuple(state_names),
            strategy._uid if strategy is not None else 0,
            iters,
            _flags.anomaly_policy() != "raise",
        )

        step = self._cache.get(key)
        cache_hit = step is not None
        (_M_CACHE_HIT if cache_hit else _M_CACHE_MISS).inc()
        (_M_CACHE_HIT_MEM if cache_hit else _M_CACHE_MISS_MEM).inc()
        if step is None:
            if _flags.check_program_enabled():
                from .passes import apply_pass

                apply_pass(program, "program_check",
                           feed_names=list(feed))
            step = self._build_batched(program, block, stacked, invariant,
                                       fetch_names, state_names, strategy,
                                       iters)
            self._cache[key] = step

        rng = scope.find_var(RNG_STATE_VAR)
        if rng is None:
            seed = program.random_seed or 0
            rng = _rng.key_data(_rng.root_key(seed))
            scope.set_var(RNG_STATE_VAR, rng)

        state = {n: scope.find_var(n) for n in state_names}
        from . import profiler as _prof

        from .. import telemetry as _telemetry

        profiling = _prof.is_profiler_enabled()
        t0 = _prof.now() if profiling else None
        try:
            if _telemetry.enabled() and _telemetry.current() is not None:
                with _telemetry.span("executor.run_batched",
                                     attrs={"program": program._uid,
                                            "iters": iters}):
                    fetches, new_state, new_rng = step.fn(
                        state, stacked, invariant, rng)
            else:
                fetches, new_state, new_rng = step.fn(state, stacked,
                                                      invariant, rng)
        except Exception:
            _telemetry.flight.dump(reason="executor_exception")
            raise
        if profiling:
            jax.block_until_ready(fetches)
            _prof._record("executor_batched_run[%s#p%d;k=%d]" % (
                ",".join(fetch_names[:3]), program._uid, iters),
                _prof.now() - t0)
        if prefetch:
            # dispatch is asynchronous — window i is still executing on
            # device; start draining + staging window i+1 right now so
            # the next run finds it ready (overlap hit). Pre-shard with
            # the program's GSPMD feed sharding (iteration axis is 0,
            # so the dp'd batch axis sits at 1).
            sharding_fn = None
            if strategy is not None and strategy.mesh is not None:
                sharding_fn = (lambda name, v:
                               strategy.feed_sharding(v, batch_dim=1))
            self._window_prefetch[rkey] = _WindowPrefetch(
                py_readers, iters, sharding_fn)
        # anomaly scan BEFORE commit, same policy as the single-step
        # path. Granularity is the WINDOW: a non-finite value anywhere in
        # the k-step trajectory (fetches are stacked [k, ...]) or the
        # final state discards all k steps — the device-side loop cannot
        # partially commit.
        anomaly = self._scan_anomaly(fetch_names, fetches, new_state)
        discarded = False
        if anomaly is not None:
            discarded = self._handle_anomaly(anomaly, program, scope,
                                             checkpoint, iters=iters)
        else:
            self._anomaly_skips = 0
        if not discarded:
            scope.set_var(RNG_STATE_VAR, new_rng)
            for n, v in new_state.items():
                scope.set_var(n, v)

        if save_ops and not discarded:
            # same contract as the single-step path, applied to the whole
            # window: ONE write per save op, recording the value committed
            # after step k (running k single-step runs against the same
            # file path leaves exactly this value too)
            from .core import tensor_io

            for name, path in save_ops:
                val = scope.find_var(name)
                if val is None:
                    raise RuntimeError(
                        "save op: var %r is not in the scope — only "
                        "PERSISTABLE vars can be saved (the step "
                        "commits those; intermediates are fused away "
                        "by XLA). fetch_list the value instead." % name)
                os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
                tensor_io.save_combine(path, {name: _fetch_numpy(val)})

        if checkpoint is not None and not discarded:
            checkpoint[0].step_completed(program, scope, iters,
                                         checkpoint[1])

        # drain between windows: a signal that landed mid-window exits
        # here, after all k steps committed
        _preemption.check_drain(checkpoint[0] if checkpoint else None,
                                program, scope)

        wall = _time.perf_counter() - _t_run0
        _M_RUN_SECONDS.observe(wall)
        _M_RUNS.inc()
        _M_BATCHED_RUNS.inc()
        _M_BATCHED_ITERS.inc(iters)
        if _RUN_HOOKS:
            record = {
                "program_id": program._uid,
                "fetch_names": list(fetch_names),
                "wall_time": wall,
                "cache_hit": cache_hit,
                "profiler_enabled": profiling,
                "iters": iters,
            }
            if fetch_mode == "async":
                record["async"] = True
            _fire_run_hooks(record)

        if fetch_mode == "async":
            return [FetchHandle(x, name=n)
                    for n, x in zip(fetch_names, fetches)]
        if return_numpy:
            return [_fetch_numpy(x) for x in fetches]
        return list(fetches)

    def _build_batched(self, program, block, stacked, invariant,
                       fetch_names, state_names, strategy, iters):
        """Trace the block once into ``step`` and wrap it in a
        ``lax.scan`` over the iteration axis: stacked feeds are sliced
        per step, invariant feeds close over the loop, ``(state, rng)``
        is the carry, and the initial state is donated — the whole
        k-step window is allocation-free on device."""
        import jax

        mesh = strategy.mesh if strategy is not None else None

        def step(state, feed_vals, rng_key):
            env = {}
            env.update(state)
            env.update(feed_vals)
            ctx = LowerCtx(block, env, _rng.wrap_key_data(rng_key),
                           mesh=mesh)
            if strategy is not None:
                strategy._on_trace_begin(ctx)
            lower_block(ctx, block)
            fetches = [ctx.get(n) for n in fetch_names]
            new_state = {n: env[n] for n in state if n in env}
            # a scan carry has a FIXED structure: a program that creates
            # new persistables mid-step (startup-style init) cannot be
            # step-batched — fail with the remedy, not a tracer error
            grown = sorted(
                set(n for n in ctx.written
                    if n in env and n not in new_state) |
                set(name for name, var in block.vars.items()
                    if var.persistable and name in env
                    and name not in state))
            if grown:
                raise RuntimeError(
                    "iters>1 needs loop-invariant state, but this "
                    "program creates new persistable vars %s during "
                    "the step — run the startup program (iters=1) "
                    "first so they exist in the scope" % (grown,))
            return fetches, new_state, _rng.key_data(ctx.rng_key)

        def batched(state, stacked_feeds, invariant_feeds, rng_key):
            def body(carry, feed_i):
                st, rk = carry
                fv = dict(invariant_feeds)
                fv.update(feed_i)
                fetches, new_st, new_rk = step(st, fv, rk)
                return (new_st, new_rk), fetches

            (final_state, final_rng), traj = jax.lax.scan(
                body, (state, rng_key), stacked_feeds, length=iters)
            return traj, final_state, final_rng

        from . import flags as _flags

        donate = ((0,) if self._donate_state
                  and _flags.anomaly_policy() == "raise" else ())
        cache_key = None
        if _compile_cache.active(self._cache_read_dirs):
            merged = dict(stacked)
            merged.update(invariant)
            cache_key = _compile_cache.step_key(
                program, _feed_signature(merged, block), fetch_names,
                state_names, strategy, iters, bool(donate))

        if strategy is not None and mesh is not None:
            return _CompiledStep(
                strategy.wrap_batched_step(batched, block, stacked,
                                           invariant, fetch_names,
                                           state_names,
                                           cache_key=cache_key,
                                           cache_read_dirs=self._cache_read_dirs,
                                           program=program, iters=iters),
                state_names,
                fetch_names,
            )

        # see _build: donation off under skip_step/rollback (so a
        # discarded window's pre-step state stays valid) and for
        # inference-path executors; donate computed above joins the key
        jfn = _compile_cache.wrap_jit(
            jax.jit(batched, donate_argnums=donate), cache_key,
            read_dirs=self._cache_read_dirs,
            label="batched#k=%d" % iters)
        return _CompiledStep(jfn, state_names, fetch_names)

    # convenience ------------------------------------------------------
    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """One pass over ``dataset`` (reference ``executor.py:920`` +
        trainer/DeviceWorker stack). The reference spawns per-thread C++
        workers over dataset channels; here each batch runs through the
        same compile-cached XLA step ``run()`` uses — thread-level
        parallelism lives in the dataset's parsing/prefetch side, device
        parallelism in the compiled step's shardings."""
        if dataset is None:
            raise ValueError("dataset is required")
        if thread:
            dataset.set_thread(thread)
        fetch_list = list(fetch_list or [])
        fetch_info = list(fetch_info or
                          [getattr(v, "name", str(v)) for v in fetch_list])
        n_batches = 0
        # double-buffer ahead-dispatch (the fluid/reader.py staging trick;
        # reference buffered_reader.h ReadAsync semantics): step i is
        # dispatched asynchronously (return_numpy=False keeps it
        # in-flight), then a background DeviceStager parses batch i+1 on
        # host and stages it H2D while the device executes — host prep
        # and device step overlap. A CompiledProgram's GSPMD feed
        # sharding is applied AT the stage, so data-parallel feeds land
        # pre-sharded across the mesh instead of funneling through
        # device 0.
        import numpy as _np

        from . import compiler as _compiler
        from .reader import DeviceStager, _as_sharding_fn, stage_feed

        sharding_fn = None
        if isinstance(program, _compiler.CompiledProgram) and \
                program.mesh is not None:
            sharding_fn = _as_sharding_fn(program)

        stager = DeviceStager(
            dataset.batch_reader()(),
            transform=lambda feed: stage_feed(feed, sharding_fn),
            capacity=2, name="dataset")
        try:
            for staged in stager:
                res = self.run(program, feed=staged,
                               fetch_list=fetch_list, scope=scope,
                               return_numpy=False)
                n_batches += 1
                if debug and fetch_list and n_batches % print_period == 0:
                    msg = ", ".join(
                        "%s=%s" % (info, _np.asarray(val).ravel()[:4])
                        for info, val in zip(fetch_info, res))
                    print("batch %d: %s" % (n_batches, msg))
        finally:
            stager.close()
        return n_batches

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """Reference ``executor.py:847``: identical drive, inference
        program (no optimizer ops — the program decides, not the call)."""
        return self.train_from_dataset(program, dataset, scope, thread,
                                       debug, fetch_list, fetch_info,
                                       print_period)

    def as_function(self, program, feed_specs, fetch_list, scope=None):
        """Exposes a Program block as a pure jittable function
        ``fn(state_dict, feed_dict, rng_key) -> (fetches, new_state, key)``
        plus example args. ``feed_specs``: {name: example ndarray}."""
        import jax

        scope = scope or global_scope()
        block = program.global_block()
        fetch_names = [v.name if isinstance(v, Variable) else str(v) for v in fetch_list]
        state_names = sorted(
            v.name
            for v in program.list_vars()
            if v.persistable and scope.has_var(v.name)
        )

        def step(state, feed_vals, rng_key):
            env = {}
            env.update(state)
            env.update(feed_vals)
            ctx = LowerCtx(block, env, _rng.wrap_key_data(rng_key))
            lower_block(ctx, block)
            fetches = [ctx.get(n) for n in fetch_names]
            new_state = {n: env[n] for n in state if n in env}
            new_state.update({n: env[n] for n in ctx.written if n in env})
            return fetches, new_state, _rng.key_data(ctx.rng_key)

        state = {n: scope.find_var(n) for n in state_names}
        rng = scope.find_var(RNG_STATE_VAR)
        if rng is None:
            rng = _rng.key_data(_rng.root_key(program.random_seed or 0))
        return step, (state, dict(feed_specs), rng)

    def close(self):
        """Release compiled steps and reap any in-flight window
        prefetch (joining its non-daemon thread; already-pulled batches
        of an abandoned pass are dropped)."""
        pending = list(self._window_prefetch.values())
        self._window_prefetch.clear()
        for pf in pending:
            pf.discard()
        self._cache.clear()


def _as_lodtensor(data, place=None):
    return np.asarray(data)
