"""Shared resilience primitives: retry-with-backoff and circuit breaking.

Before this module every layer re-invented its own failure handling —
the pserver client hand-rolled a reconnect loop (``ps_server._Conn``),
the launcher respawned a crashed gang immediately, checkpoint writes
had no retry at all. ``Retry`` and ``CircuitBreaker`` centralize the
policy (attempt budget, deadline, exponential backoff + jitter, a
retryable-exception predicate) and the observability (every attempt,
exhaustion, and breaker trip is counted in ``monitor`` under the
call-site's name), so "how does this subsystem behave under transient
failure" has one answer instead of five.

Exception taxonomy: ``TransientError`` marks failures worth retrying by
default (network blips, queue hiccups, injected faults from
``fluid/faults.py``); anything else is considered a programming error
and surfaces immediately unless the call site widens ``retryable``.

No jax / framework imports: like ``monitor``, this must be importable
from every layer (io, reader, launcher, pserver) without cycles.
"""

import random
import threading
import time

from . import monitor as _monitor

__all__ = ["TransientError", "CircuitOpenError", "Overloaded", "Closed",
           "Retry", "CircuitBreaker", "RestartBackoff", "backoff_delay"]

def _site_counters(site):
    return (
        _monitor.counter(
            "resilience_retry_attempts_total",
            help="failed attempts that were retried (per site label)",
            labels={"site": site}),
        _monitor.counter(
            "resilience_retry_exhausted_total",
            help="Retry.call gave up: attempts/deadline exhausted or "
                 "non-retryable error",
            labels={"site": site}),
    )


class TransientError(Exception):
    """Marker base class: an operation failed in a way that is expected
    to succeed on retry (connection reset, queue hiccup, injected
    fault). ``Retry``'s default predicate retries these plus
    ``OSError``/``ConnectionError``."""


class CircuitOpenError(RuntimeError):
    """The circuit breaker is open: calls are short-circuited without
    touching the protected resource until the reset timeout elapses."""


class Overloaded(RuntimeError):
    """Admission control shed this request: the protected queue is at
    its depth bound (or the admission breaker is open after consecutive
    over-bound submissions). Unlike ``TransientError`` this is NOT
    retried blindly by ``Retry`` defaults — the correct client response
    is to back off, not to hammer an already-saturated server. Raised
    by ``inference.serving`` ``submit``; carries no partial state."""


class Closed(RuntimeError):
    """The target was shut down deliberately: ``Server.close()`` ran (or
    a fleet replica is draining) and this operation arrived after the
    fact. NOT a ``TransientError`` — retrying against the same instance
    can never succeed; the caller should fail over to another replica
    (what the fleet ``Router`` does) or surface the shutdown. Subclasses
    ``RuntimeError`` so pre-typed ``except RuntimeError`` call sites
    keep working."""


def backoff_delay(attempt, base=0.1, factor=2.0, max_delay=30.0,
                  jitter=0.5, rand=random.random):
    """Exponential backoff with decorrelating jitter: attempt 0 waits
    ~``base``, each further attempt multiplies by ``factor``, capped at
    ``max_delay``; ``jitter`` adds up to that fraction of the delay on
    top (0 disables — deterministic, used by tests)."""
    d = min(float(max_delay), float(base) * float(factor) ** int(attempt))
    if jitter:
        d += d * float(jitter) * rand()
    return d


class Retry:
    """Bounded retry policy: ``retry.call(fn, *args)`` runs ``fn`` up to
    ``max_attempts`` times (or until ``deadline`` seconds have elapsed),
    sleeping ``backoff_delay`` between failures. On exhaustion the LAST
    exception re-raises unchanged, so callers' ``except`` clauses keep
    working. Also usable as a decorator: ``@Retry(name="io")``.

    ``retryable`` is an exception class, a tuple of classes, or a
    predicate ``fn(exc) -> bool``; the default retries
    ``TransientError`` / ``OSError`` / ``ConnectionError``. A
    non-retryable exception surfaces immediately (counted as
    exhaustion, not as an attempt burned).

    Instances are stateless between calls and therefore thread-safe —
    one module-level Retry can guard every call site of a subsystem.
    """

    DEFAULT_RETRYABLE = (TransientError, OSError, ConnectionError)

    def __init__(self, max_attempts=3, base_delay=0.1, factor=2.0,
                 max_delay=30.0, deadline=None, jitter=0.5,
                 retryable=None, name="retry", sleep=time.sleep,
                 clock=time.monotonic):
        if int(max_attempts) < 1:
            raise ValueError("max_attempts must be >= 1, got %r"
                             % (max_attempts,))
        self.max_attempts = int(max_attempts)
        self.base_delay = float(base_delay)
        self.factor = float(factor)
        self.max_delay = float(max_delay)
        self.deadline = None if deadline is None else float(deadline)
        self.jitter = float(jitter)
        self.name = name
        self._sleep = sleep
        self._clock = clock
        if retryable is None:
            retryable = self.DEFAULT_RETRYABLE
        if isinstance(retryable, type) and issubclass(retryable,
                                                      BaseException):
            retryable = (retryable,)
        if isinstance(retryable, tuple):
            classes = retryable
            self._retryable = lambda e: isinstance(e, classes)
        elif callable(retryable):
            self._retryable = retryable
        else:
            raise TypeError(
                "retryable must be an exception class, a tuple of them, "
                "or a predicate fn(exc) -> bool; got %r" % (retryable,))
        self._m_attempts, self._m_exhausted = _site_counters(name)

    def delay(self, attempt):
        """Seconds to sleep after failed attempt number ``attempt``
        (0-based)."""
        return backoff_delay(attempt, self.base_delay, self.factor,
                             self.max_delay, self.jitter)

    def call(self, fn, *args, **kwargs):
        t0 = self._clock()
        for attempt in range(self.max_attempts):
            try:
                return fn(*args, **kwargs)
            except BaseException as e:  # classified below: re-raised unless the predicate marks it retryable
                if not self._retryable(e):
                    self._m_exhausted.inc()
                    raise
                last = attempt == self.max_attempts - 1
                if not last:
                    d = self.delay(attempt)
                    over = (self.deadline is not None and
                            self._clock() - t0 + d > self.deadline)
                    last = over
                if last:
                    self._m_exhausted.inc()
                    raise
                self._m_attempts.inc()
                self._sleep(d)
        raise AssertionError("unreachable")  # loop always returns/raises

    def __call__(self, fn):
        def wrapped(*args, **kwargs):
            return self.call(fn, *args, **kwargs)

        wrapped.__name__ = getattr(fn, "__name__", "wrapped")
        wrapped.__doc__ = fn.__doc__
        wrapped.__wrapped__ = fn
        return wrapped


class CircuitBreaker:
    """Classic three-state breaker guarding a flaky dependency.

    CLOSED: calls pass through; ``failure_threshold`` CONSECUTIVE
    failures trip it OPEN. OPEN: calls raise ``CircuitOpenError``
    immediately (no load on the dependency) until ``reset_timeout``
    seconds pass. HALF_OPEN: one probe call is let through — success
    closes the breaker, failure re-opens it for another timeout.

    Use ``breaker.call(fn, ...)`` or the ``allow()`` /
    ``record_success()`` / ``record_failure()`` trio when the protected
    operation isn't a single callable. Thread-safe.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, failure_threshold=5, reset_timeout=30.0,
                 name="breaker", clock=time.monotonic):
        if int(failure_threshold) < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout = float(reset_timeout)
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0
        self._opened_at = None
        self._probing = False
        self._m_trips = _monitor.counter(
            "resilience_breaker_trips_total",
            help="breaker transitions into the open state",
            labels={"site": name})
        self._m_rejected = _monitor.counter(
            "resilience_breaker_rejected_total",
            help="calls short-circuited while the breaker was open",
            labels={"site": name})

    @property
    def state(self):
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _maybe_half_open(self):
        # caller holds the lock
        if self._state == self.OPEN and \
                self._clock() - self._opened_at >= self.reset_timeout:
            self._state = self.HALF_OPEN

    def allow(self):
        """True if a call may proceed (transitions OPEN -> HALF_OPEN
        after the reset timeout; the HALF_OPEN probe is single-shot —
        a second concurrent caller is rejected until it resolves)."""
        with self._lock:
            self._maybe_half_open()
            if self._state == self.CLOSED:
                return True
            if self._state == self.HALF_OPEN and not self._probing:
                self._probing = True
                return True
            self._m_rejected.inc()
            return False

    def record_success(self):
        with self._lock:
            self._state = self.CLOSED
            self._failures = 0
            self._probing = False

    def record_failure(self):
        with self._lock:
            self._failures += 1
            self._probing = False
            if self._state == self.HALF_OPEN or \
                    self._failures >= self.failure_threshold:
                if self._state != self.OPEN:
                    self._m_trips.inc()
                self._state = self.OPEN
                self._opened_at = self._clock()

    def call(self, fn, *args, **kwargs):
        if not self.allow():
            raise CircuitOpenError(
                "circuit %r is open (%d consecutive failures); retrying "
                "after %.1fs" % (self.name, self._failures,
                                 self.reset_timeout))
        try:
            result = fn(*args, **kwargs)
        except BaseException:  # any failure counts against the breaker; always re-raised
            self.record_failure()
            raise
        self.record_success()
        return result


class RestartBackoff:
    """Backoff series for restart loops, with a healthy-run reset: each
    consecutive failure grows the delay exponentially, but a run that
    stayed healthy for at least ``reset_after`` seconds before failing
    resets the series — a crash hours into training must not inherit
    the max backoff accumulated by startup flakes.

    Usage (``distributed.launch``):

        bo = RestartBackoff(base=0.5, reset_after=60.0)
        ...gang fails after running healthy_secs...
        time.sleep(bo.next_delay(healthy_secs))
    """

    def __init__(self, base=0.5, factor=2.0, max_delay=30.0,
                 jitter=0.25, reset_after=60.0):
        self.base = float(base)
        self.factor = float(factor)
        self.max_delay = float(max_delay)
        self.jitter = float(jitter)
        self.reset_after = float(reset_after)
        self.attempt = 0
        self._m_resets = _monitor.counter(
            "restart_backoff_resets_total",
            help="backoff series reset after a healthy run "
                 "(>= reset_after seconds before the failure)")

    def next_delay(self, healthy_seconds):
        """Delay before the next restart, given how long the failed run
        stayed healthy. Advances the attempt counter."""
        if self.attempt and float(healthy_seconds) >= self.reset_after:
            self.attempt = 0
            self._m_resets.inc()
        d = backoff_delay(self.attempt, base=self.base,
                          factor=self.factor, max_delay=self.max_delay,
                          jitter=self.jitter)
        self.attempt += 1
        return d
