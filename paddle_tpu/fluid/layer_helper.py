"""LayerHelper: shared machinery for layer functions — parameter creation
(+ init op into the startup program), output var creation, activation append.

Parity: reference ``python/paddle/fluid/layer_helper.py``.
"""

import numpy as np

from . import framework, initializer, unique_name
from .framework import Variable, default_main_program, default_startup_program
from .param_attr import ParamAttr


class LayerHelper:
    def __init__(self, layer_type, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        self.name_prefix = name if name else unique_name.generate(layer_type)

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    @property
    def block(self):
        return self.main_program.current_block()

    def create_parameter(self, attr, shape, dtype="float32", is_bias=False,
                         default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        if default_initializer is None:
            if is_bias:
                default_initializer = initializer.Constant(0.0)
            else:
                default_initializer = initializer.Xavier()
        init = attr.initializer or default_initializer
        name = attr.name or unique_name.generate(self.name_prefix + (".b" if is_bias else ".w"))

        shape = [int(s) for s in shape]
        param = self.block.create_parameter(
            shape=shape,
            dtype=dtype,
            name=name,
            trainable=attr.trainable,
            regularizer=attr.regularizer,
            learning_rate=attr.learning_rate,
            do_model_average=attr.do_model_average,
        )
        if getattr(attr, "shard", None) is not None:
            if len(attr.shard) != len(shape):
                raise ValueError(
                    "ParamAttr(shard=%r) rank does not match param shape %r"
                    % (attr.shard, shape))
            param.shard_spec = tuple(attr.shard)
        # mirror the parameter + its init op into the startup program
        startup_block = self.startup_program.global_block()
        sp = framework.Parameter(
            startup_block, shape=shape, dtype=dtype, name=name, trainable=attr.trainable
        )
        startup_block.vars[sp.name] = sp
        init(sp, startup_block)
        return param

    def create_variable_for_type_inference(self, dtype="float32", stop_gradient=False):
        return self.block.create_var(
            name=unique_name.generate(self.name_prefix + ".tmp"),
            shape=(),
            dtype=dtype,
            stop_gradient=stop_gradient,
        )

    def create_global_variable(self, shape, dtype="float32", persistable=False, name=None):
        return self.main_program.global_block().create_var(
            name=name or unique_name.generate(self.name_prefix + ".gvar"),
            shape=shape,
            dtype=dtype,
            persistable=persistable,
        )

    def append_op(self, **kwargs):
        op = self.block.append_op(
            kwargs["type"],
            inputs=kwargs.get("inputs"),
            outputs=kwargs.get("outputs"),
            attrs=kwargs.get("attrs"),
        )
        self._infer_shapes(op)
        return op

    def _infer_shapes(self, op):
        """Best-effort static shape inference via the op's lowering rule on
        abstract values (single source of truth — no per-op InferShape)."""
        from .shape_inference import infer_op_shapes

        try:
            infer_op_shapes(op)
        except Exception:
            pass  # shapes stay advisory; execution uses concrete shapes

    def append_activation(self, out_var, act=None):
        act = act or self.kwargs.get("act")
        if act is None:
            return out_var
        if isinstance(act, str):
            act = {"type": act}
        act_type = act.pop("type")
        tmp = self.create_variable_for_type_inference(out_var.dtype)
        self.append_op(type=act_type, inputs={"X": [out_var]}, outputs={"Out": [tmp]}, attrs=act)
        return tmp

    def input_dtype(self, var):
        return framework.dtype_str(var.dtype)
