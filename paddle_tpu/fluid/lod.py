"""LoD (level-of-detail / ragged sequence) tensors, TPU-native.

Reference: ``paddle/fluid/framework/lod_tensor.h:52,104`` — a dense tensor
plus nested offset tables describing variable-length sequences, threaded
through ~16 sequence_* ops and the RNN/beam stack.

TPU-native redesign ("bounded LoD"): XLA requires static shapes, so a LoD
tensor is a *flattened* ``[total_bound, ...]`` array whose first dimension is
a static physical bound, paired with a device-resident int32 ``lengths``
vector bound to ``name + "@LOD"`` in the lowering environment (the same
side-binding convention as SelectedRows' ``@ROWS``). The *logical* total is
``sum(lengths)`` — rows past it are padding that every sequence op masks out
via segment arithmetic (``searchsorted(cumsum(lengths), arange(total))``),
so lengths can change batch-to-batch without recompilation while every
intermediate keeps a fixed shape for the compiler.

Only level-1 LoD is carried on-device (one lengths vector). The host-side
``LoDTensor`` accepts recursive (nested) lengths for API parity and flattens
the innermost level for device use.
"""

import numpy as np

__all__ = ["LoDTensor", "create_lod_tensor", "LOD_SUFFIX", "lod_name"]

LOD_SUFFIX = "@LOD"


def lod_name(name):
    return name + LOD_SUFFIX


class LoDTensor:
    """Host-side (data, recursive lengths) pair accepted by ``feed={}``.

    The Executor decomposes it into two device arrays: ``name`` gets the
    flattened data, ``name@LOD`` gets the innermost-level lengths.
    """

    def __init__(self, data, recursive_seq_lens=None):
        self._data = np.asarray(data)
        if recursive_seq_lens is None:
            recursive_seq_lens = [[self._data.shape[0]]]
        if recursive_seq_lens and not isinstance(
                recursive_seq_lens[0], (list, tuple, np.ndarray)):
            recursive_seq_lens = [recursive_seq_lens]
        self._rsl = [list(int(x) for x in lvl) for lvl in recursive_seq_lens]
        total = int(sum(self._rsl[-1]))
        if total > self._data.shape[0]:
            raise ValueError(
                "sum(lengths)=%d exceeds data rows %d"
                % (total, self._data.shape[0]))

    def recursive_sequence_lengths(self):
        return [list(lvl) for lvl in self._rsl]

    def lod(self):
        """Offset form (reference ``LoD``): prefix sums per level."""
        out = []
        for lvl in self._rsl:
            offs = [0]
            for x in lvl:
                offs.append(offs[-1] + x)
            out.append(offs)
        return out

    def lengths(self):
        """Innermost-level lengths as int32 (the device-side binding)."""
        return np.asarray(self._rsl[-1], np.int32)

    def data(self):
        return self._data

    def __array__(self, dtype=None):
        return self._data if dtype is None else self._data.astype(dtype)

    @property
    def shape(self):
        return self._data.shape

    def __repr__(self):
        return "LoDTensor(shape=%s, recursive_seq_lens=%s)" % (
            self._data.shape, self._rsl)


def create_lod_tensor(data, recursive_seq_lens, place=None):
    """Reference ``fluid.create_lod_tensor``; ``place`` is advisory."""
    return LoDTensor(data, recursive_seq_lens)


class LoDTensorArray(list):
    """Ordered container of LoDTensors (reference ``core.LoDTensorArray``
    — a bound ``vector<LoDTensor>`` with ``append``). The in-graph
    analogue is the bounded TensorArray (``layers.create_array`` +
    ``array_write``/``array_read``); this host-side type carries arrays
    between runs, e.g. beam-search outputs. Every insertion path
    coerces plain arrays, so elements always honor the LoDTensor API."""

    @staticmethod
    def _coerce(value):
        return value if isinstance(value, LoDTensor) else LoDTensor(value,
                                                                    None)

    def __init__(self, iterable=()):
        super().__init__(self._coerce(v) for v in iterable)

    def append(self, value):
        super().append(self._coerce(value))

    def extend(self, iterable):
        super().extend(self._coerce(v) for v in iterable)

    def insert(self, index, value):
        super().insert(index, self._coerce(value))

    def __setitem__(self, index, value):
        if isinstance(index, slice):
            value = [self._coerce(v) for v in value]
        else:
            value = self._coerce(value)
        super().__setitem__(index, value)
