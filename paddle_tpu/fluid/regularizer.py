"""Weight-decay regularizers (reference ``python/paddle/fluid/regularizer.py``)."""

from .framework import Variable

__all__ = ["L1Decay", "L2Decay", "L1DecayRegularizer", "L2DecayRegularizer",
           "append_regularization_ops"]


class WeightDecayRegularizer:
    def __call__(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        from .layers import nn

        decay = nn.scale(param, scale=self._coeff)
        return nn.elementwise_add(grad, decay)


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        from .layers import nn

        decay = nn.scale(nn.sign(param), scale=self._coeff)
        return nn.elementwise_add(grad, decay)


def append_regularization_ops(params_grads, regularization=None):
    out = []
    for param, grad in params_grads:
        regular = getattr(param, "regularizer", None) or regularization
        if regular is None or grad is None:
            out.append((param, grad))
            continue
        if getattr(grad, "type", "lod_tensor") == "selected_rows":
            # reference regularizer.py skips SelectedRows grads too (sparse
            # update + decay of untouched rows would densify the gradient)
            import warnings

            warnings.warn("regularization skipped for sparse gradient of %r"
                          % param.name)
            out.append((param, grad))
            continue
        new_grad = regular(param, grad, grad.block)
        out.append((param, new_grad))
    return out


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer
