"""Version bridges for the jax surface this codebase targets.

``shard_map`` moved twice across jax releases: old builds expose it only
as ``jax.experimental.shard_map.shard_map`` (replication check kwarg
``check_rep``), newer ones promote it to ``jax.shard_map`` and rename
the kwarg ``check_vma``. Every internal call site goes through
:func:`shard_map` below so the rest of the tree can use the modern
spelling unconditionally.
"""

import jax

__all__ = ["shard_map", "axis_size", "SHARD_MAP_DONATION_OK"]

# The pre-promotion shard_map miscomputes jit donation aliases for
# replicated operands (size-mismatched input/output pairing at run
# time); donation must be skipped when running on that fallback.
SHARD_MAP_DONATION_OK = hasattr(jax, "shard_map")


def shard_map(f, mesh, in_specs, out_specs, check_vma=True):
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def axis_size(axis_name):
    """``jax.lax.axis_size`` appeared after the oldest supported jax;
    inside a mapped region the psum of 1 over the axis is the same
    number on every build."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)
