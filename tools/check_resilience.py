#!/usr/bin/env python
"""Resilience lint: no silent catch-alls in the runtime.

A bare ``except:`` or ``except BaseException`` swallows
KeyboardInterrupt, SystemExit, and injected faults alike — in a
fault-tolerant runtime every such site must either not exist or carry
an inline justification (a trailing ``#`` comment on the ``except``
line saying WHY the catch-all is correct there: stored-and-reraised on
a consumer thread, crash-consistency cleanup, etc.). This checker
fails on any unjustified site; it runs inside the test suite
(tests/test_resilience.py) so a new one can't land unnoticed.

Usage: python tools/check_resilience.py [root]   (default: repo root)
Exit code 0 = clean, 1 = violations (one per line on stdout).
"""

import io
import os
import re
import sys
import tokenize

# an except line we care about: bare `except:` or naming BaseException
# (possibly `except BaseException as e:`); `except (A, BaseException)`
# tuples count too
_EXCEPT_RE = re.compile(r"^\s*except\s*(:|[^:]*\bBaseException\b)")

# directories that are not runtime code
_SKIP_DIRS = {".git", "__pycache__", ".pytest_cache", "build", "dist",
              ".eggs", "node_modules"}


def _line_has_justification(line):
    """True when the except line carries a real trailing comment
    (tokenize-accurate: a '#' inside a string literal is not a
    comment)."""
    try:
        toks = list(tokenize.generate_tokens(
            io.StringIO(line).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # a lone `except ...:` line is not valid standalone Python;
        # fall back to a textual scan outside quotes
        toks = []
    for t in toks:
        if t.type == tokenize.COMMENT:
            return len(t.string.lstrip("#").strip()) >= 8
    # fallback: rfind a '#' not inside quotes (good enough for source
    # lines, which the repo style keeps simple)
    in_s = None
    for i, ch in enumerate(line):
        if in_s:
            if ch == in_s:
                in_s = None
        elif ch in "\"'":
            in_s = ch
        elif ch == "#":
            return len(line[i:].lstrip("#").strip()) >= 8
    return False


def check_file(path):
    """Violations in one file: list of (lineno, line)."""
    out = []
    with open(path, encoding="utf-8", errors="replace") as f:
        for lineno, line in enumerate(f, 1):
            if not _EXCEPT_RE.match(line):
                continue
            if not _line_has_justification(line.rstrip("\n")):
                out.append((lineno, line.strip()))
    return out


def check_tree(root):
    """Violations under ``root``: list of (path, lineno, line)."""
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            for lineno, line in check_file(path):
                out.append((os.path.relpath(path, root), lineno, line))
    return out


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else \
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    violations = check_tree(root)
    for path, lineno, line in violations:
        print("%s:%d: unjustified catch-all: %s" % (path, lineno, line))
    if violations:
        print("%d unjustified bare-except/BaseException site(s) — add a "
              "trailing comment explaining why the catch-all is safe, "
              "or narrow the exception" % len(violations))
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
