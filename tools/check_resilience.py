#!/usr/bin/env python
"""Resilience lint: no silent catch-alls, no rogue signal handlers.

A bare ``except:`` or ``except BaseException`` swallows
KeyboardInterrupt, SystemExit, and injected faults alike — in a
fault-tolerant runtime every such site must either not exist or carry
an inline justification (a trailing ``#`` comment on the ``except``
line saying WHY the catch-all is correct there: stored-and-reraised on
a consumer thread, crash-consistency cleanup, etc.). This checker
fails on any unjustified site; it runs inside the test suite
(tests/test_resilience.py) so a new one can't land unnoticed.

The same discipline applies to raw ``signal.signal`` registration and
raw ``os._exit`` calls: ``distributed/preemption.py`` is the ONE
sanctioned home for signal handlers (a second registration site would
clobber the drain handler), and a raw exit skips the drain/checkpoint
machinery entirely. Both are detected at the AST level (a docstring
MENTIONING os._exit is fine; a call needs a trailing justification
comment or must move into preemption.py).

``pickle.load``/``pickle.loads`` gets the same treatment:
``fluid/compile_cache.py`` is the single sanctioned deserialization
site for persistent compile-cache entries (it quarantines on ANY
failure instead of crashing); any other call site needs a trailing
comment saying why its input is trusted. Likewise, ``open()`` with a
``.xc`` literal (a cache entry) outside compile_cache.py bypasses the
quarantine/atomic-write discipline and is flagged.

Usage: python tools/check_resilience.py [root]   (default: repo root)
Exit code 0 = clean, 1 = violations (one per line on stdout).
"""

import ast
import io
import os
import re
import sys
import tokenize

# an except line we care about: bare `except:` or naming BaseException
# (possibly `except BaseException as e:`); `except (A, BaseException)`
# tuples count too
_EXCEPT_RE = re.compile(r"^\s*except\s*(:|[^:]*\bBaseException\b)")

# directories that are not runtime code
_SKIP_DIRS = {".git", "__pycache__", ".pytest_cache", "build", "dist",
              ".eggs", "node_modules"}

# the sanctioned home for raw signal.signal / os._exit (see module doc)
_RAW_CALL_EXEMPT = ("distributed/preemption.py",)

# module.attr calls that need a justification (or to live in an exempt
# file): rogue handler registration / raw process exits
_RAW_CALLS = {("signal", "signal"), ("os", "_exit")}

# the single sanctioned home for deserializing compile-cache entries
# (quarantine-on-failure; see fluid/compile_cache.py module doc)
_PICKLE_EXEMPT = ("fluid/compile_cache.py",)
_PICKLE_CALLS = {("pickle", "load"), ("pickle", "loads")}

# compile-cache entry suffix: open()ing one of these anywhere else
# bypasses the quarantine/atomic-write discipline
_CACHE_ENTRY_SUFFIX = ".xc"

# the single sanctioned home for raw socket construction:
# distributed/wire.py owns listener setup (SO_REUSEADDR, close-on-
# failure) and framed client connections (handshake, retry/backoff,
# frame caps). A raw socket.socket — or socket.create_connection, the
# bypass the serving fleet would otherwise reach for — elsewhere grows
# an unframed, un-retried, token-less protocol the fault injector
# can't see.
_SOCKET_EXEMPT = ("distributed/wire.py",)
_SOCKET_CALLS = {("socket", "socket"), ("socket", "create_connection")}

# distributed-tracing discipline (telemetry/): every opcode-dispatch
# site in the serving fleet and the coordination service must keep the
# trace header flowing — a handler that drops it silently truncates
# every fleet trace at that hop. A function that compares a request
# byte against an OP_*/opcode constant passes when it mentions "trace"
# anywhere (it decodes/forwards the header, or a comment says why not);
# otherwise each dispatch line needs a trailing `# trace: ...`
# justification.
_TRACE_FILES = ("paddle_tpu/serving/", "paddle_tpu/distributed/"
                "coordination.py")
_TRACE_OP_RE = re.compile(r"^OP_[A-Z_0-9]+$")

# durable-coordination discipline: every coordination-service request
# handler (``CoordServer._do_*``) either journals its effect to the
# WAL (``self._journal(...)``) BEFORE the ack is sent — a crash
# between ack and disk would otherwise silently rewind acknowledged
# state on recovery — or declares itself read-only with a trailing
# ``# wal: ...`` justification on its ``def`` line.
_WAL_FILE = "paddle_tpu/distributed/coordination.py"
_WAL_CLASS = "CoordServer"


def _line_has_justification(line):
    """True when the except line carries a real trailing comment
    (tokenize-accurate: a '#' inside a string literal is not a
    comment)."""
    try:
        toks = list(tokenize.generate_tokens(
            io.StringIO(line).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # a lone `except ...:` line is not valid standalone Python;
        # fall back to a textual scan outside quotes
        toks = []
    for t in toks:
        if t.type == tokenize.COMMENT:
            return len(t.string.lstrip("#").strip()) >= 8
    # fallback: rfind a '#' not inside quotes (good enough for source
    # lines, which the repo style keeps simple)
    in_s = None
    for i, ch in enumerate(line):
        if in_s:
            if ch == in_s:
                in_s = None
        elif ch in "\"'":
            in_s = ch
        elif ch == "#":
            return len(line[i:].lstrip("#").strip()) >= 8
    return False


def _call_violations(source, calls):
    """(lineno, line) for ``module.attr(...)`` CALLS from ``calls``
    without a trailing justification comment. AST-based on purpose:
    prose or docstrings mentioning the names must not trip the lint,
    only actual call sites."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    lines = source.splitlines()
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and (f.value.id, f.attr) in calls):
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if not _line_has_justification(line):
            out.append((node.lineno, line.strip()))
    return out


def _cache_open_violations(source):
    """(lineno, line) for ``open(...)`` calls whose arguments carry a
    ``.xc`` string literal — a compile-cache entry touched outside the
    sanctioned module skips quarantine-on-corruption on the read side
    and atomic tmp+fsync+rename on the write side."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    lines = source.splitlines()
    out = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "open"):
            continue
        literal = any(
            isinstance(sub, ast.Constant) and isinstance(sub.value, str)
            and _CACHE_ENTRY_SUFFIX in sub.value
            for arg in node.args for sub in ast.walk(arg))
        if not literal:
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if not _line_has_justification(line):
            out.append((node.lineno, line.strip()))
    return out


def _local_opcode_names(tree):
    """Module-level _ALL_CAPS integer constants — the coordination
    service's private opcode set (``_PUT = 2`` style). Collected from
    the AST so a new opcode is linted the moment it's declared. The
    leading underscore is deliberate: public ALL-CAPS ints (status
    codes like ``ST_OK``) ride the RESPONSE path, where there is no
    header to propagate."""
    names = set()
    for node in tree.body:
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, int)):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name) \
                    and re.match(r"^_[A-Z][A-Z_0-9]*$", t.id):
                names.add(t.id)
    return names


def _trace_violations(source):
    """(lineno, line) for opcode-dispatch Compare sites in a wire
    handler whose enclosing function neither mentions "trace" nor
    justifies the site on the dispatch line itself."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    lines = source.splitlines()
    local_ops = _local_opcode_names(tree)

    def is_opcode(expr):
        if isinstance(expr, ast.Attribute):
            return bool(_TRACE_OP_RE.match(expr.attr))
        if isinstance(expr, ast.Name):
            return bool(_TRACE_OP_RE.match(expr.id)) \
                or expr.id in local_ops
        return False

    out = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        fn_src = "\n".join(
            lines[fn.lineno - 1:fn.end_lineno]).lower()
        if "trace" in fn_src:
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Compare):
                continue
            if not (is_opcode(node.left)
                    or any(is_opcode(c) for c in node.comparators)):
                continue
            line = lines[node.lineno - 1] \
                if node.lineno <= len(lines) else ""
            out.append((node.lineno, line.strip()))
    return out


def _wal_violations(source):
    """(lineno, line) for ``CoordServer._do_*`` handlers that neither
    call ``self._journal(...)`` anywhere in their body nor carry a
    ``# wal:`` read-only justification on the ``def`` line. A new
    mutating opcode is linted the moment its handler is written."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    lines = source.splitlines()
    out = []
    for cls in tree.body:
        if not isinstance(cls, ast.ClassDef) or cls.name != _WAL_CLASS:
            continue
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)) \
                    or not fn.name.startswith("_do_"):
                continue
            journals = any(
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "_journal"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"
                for node in ast.walk(fn))
            if journals:
                continue
            line = lines[fn.lineno - 1] if fn.lineno <= len(lines) \
                else ""
            if "# wal:" in line and _line_has_justification(line):
                continue
            out.append((fn.lineno, line.strip()))
    return out


def check_file(path):
    """Violations in one file: list of (lineno, line)."""
    out = []
    with open(path, encoding="utf-8", errors="replace") as f:
        source = f.read()
    for lineno, line in enumerate(source.splitlines(), 1):
        if not _EXCEPT_RE.match(line):
            continue
        if not _line_has_justification(line):
            out.append((lineno, line.strip()))
    norm = path.replace(os.sep, "/")
    if not any(norm.endswith(suffix) for suffix in _RAW_CALL_EXEMPT):
        out.extend(_call_violations(source, _RAW_CALLS))
    if not any(norm.endswith(suffix) for suffix in _PICKLE_EXEMPT):
        out.extend(_call_violations(source, _PICKLE_CALLS))
        out.extend(_cache_open_violations(source))
    if not any(norm.endswith(suffix) for suffix in _SOCKET_EXEMPT):
        out.extend(_call_violations(source, _SOCKET_CALLS))
    if any(pat in norm for pat in _TRACE_FILES):
        out.extend(_trace_violations(source))
    if norm.endswith(_WAL_FILE):
        out.extend(_wal_violations(source))
    return sorted(set(out))  # nested fns can report a site twice


def check_tree(root):
    """Violations under ``root``: list of (path, lineno, line)."""
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            for lineno, line in check_file(path):
                out.append((os.path.relpath(path, root), lineno, line))
    return out


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else \
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    violations = check_tree(root)
    for path, lineno, line in violations:
        print("%s:%d: unjustified resilience hazard: %s"
              % (path, lineno, line))
    if violations:
        print("%d unjustified site(s): bare-except/BaseException, raw "
              "signal.signal, raw os._exit, raw pickle.load(s), a "
              ".xc cache entry opened outside fluid/compile_cache, "
              "a raw socket.socket/socket.create_connection outside "
              "distributed/wire, or an opcode handler in "
              "serving/coordination that drops the trace header, or a "
              "mutating CoordServer._do_ handler that skips the WAL "
              "journal — "
              "add a trailing comment explaining why the site is safe, "
              "narrow the exception, or route the access through the "
              "sanctioned module" % len(violations))
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
