#!/usr/bin/env python
"""Per-stage memory accounting for a candidate pipeline cut.

``PipelineOptimizer(cut_list=...)`` decides which ops land on which
stage rank; a bad cut starves some ranks and blows the memory budget of
others. This tool audits a candidate cut BEFORE committing devices to
it, using the exact segmentation the compiled schedule will run
(``fluid.compiler.pipeline_segments``) and the same static liveness
walk the long-context tier uses (``utils.liveness.peak_live_bytes``):

  * ``param_bytes``     — parameters consumed by the stage's forward
    ops. Gradients and optimizer slots live on the same rank, so the
    training-state footprint scales with this number.
  * ``peak_act_bytes``  — peak live bytes of the stage's forward
    segment at microbatch shape (born at the defining eqn, dead after
    the last use — an estimate of logical buffers, not an XLA
    allocation model; compare stages against each other).
  * ``boundary_bytes``  — the activation bundle ppermuted to the next
    stage each schedule tick.

Library use: ``stage_report(program, feed)`` with a feed dict at
MICROBATCH batch size. CLI (builds the demo EncoderTower LM):

  PYTHONPATH=. python tools/stagebalance.py --stages 2 --layers 4 \
      --mb-rows 4 --seq 32 [--json]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def _var_nbytes(var):
    shape = [int(s) for s in var.shape]
    if any(s < 0 for s in shape):
        return 0
    return int(np.prod(shape, dtype=np.int64)) * np.dtype(var.dtype).itemsize


def stage_report(program, feed):
    """Audit the recorded pipeline cut of ``program``.

    ``feed``: {name: array} at MICROBATCH batch size (shapes/dtypes are
    what matter — nothing executes). Returns a list of per-stage dicts
    ``{stage, ops, param_bytes, peak_act_bytes, boundary_bytes}``.
    Raises ValueError when a non-cut var crosses a stage boundary — the
    same GPipe contract violation the compiled schedule would reject,
    surfaced with the leaking names.
    """
    import jax

    from paddle_tpu.fluid import rng as _rng
    from paddle_tpu.fluid.compiler import pipeline_segments
    from paddle_tpu.fluid.registry import LowerCtx, lower_op
    from paddle_tpu.utils.liveness import peak_live_bytes

    block = program.global_block()
    segments, cut_groups, _ = pipeline_segments(program, block)

    feed_sds = {n: jax.ShapeDtypeStruct(np.asarray(v).shape,
                                        np.asarray(v).dtype)
                for n, v in feed.items()}

    def _is_param(name):
        try:
            v = block.var(name)
        except Exception:
            return False
        return bool(getattr(v, "persistable", False))

    report = []
    boundary_sds = {}   # incoming activations for the current stage
    for r, seg in enumerate(segments):
        produced = set()
        needed = []
        for op in seg:
            for nm in op.input_arg_names():
                if nm not in produced and nm not in needed:
                    needed.append(nm)
            produced.update(op.output_arg_names())

        params, env_tmpl, leaked = [], {}, []
        for nm in needed:
            if nm in boundary_sds:
                env_tmpl[nm] = boundary_sds[nm]
            elif nm in feed_sds:
                env_tmpl[nm] = feed_sds[nm]
            elif _is_param(nm):
                params.append(nm)
                v = block.var(nm)
                env_tmpl[nm] = jax.ShapeDtypeStruct(
                    tuple(int(s) for s in v.shape), np.dtype(v.dtype))
            else:
                leaked.append(nm)
        if leaked:
            raise ValueError(
                "stage %d consumes %r which earlier stages produce but "
                "the cut does not carry — add them to the cut bundle "
                "(PipelineOptimizer cut_list entries may be lists)"
                % (r, leaked))

        out_names = list(cut_groups[r]) if r < len(cut_groups) else [
            nm for op in seg for nm in op.output_arg_names()][-1:]

        def _seg_fn(env):
            ctx = LowerCtx(block, dict(env), _rng.root_key(0))
            for op in seg:
                lower_op(ctx, op)
            return [ctx.get(nm) for nm in out_names]

        closed = jax.make_jaxpr(_seg_fn)(env_tmpl)
        outs = jax.eval_shape(_seg_fn, env_tmpl)
        boundary_sds = dict(zip(out_names, outs))
        boundary_bytes = sum(
            int(np.prod(o.shape, dtype=np.int64)) * o.dtype.itemsize
            for o in outs) if r < len(cut_groups) else 0

        report.append({
            "stage": r,
            "ops": len(seg),
            "param_bytes": sum(_var_nbytes(block.var(nm)) for nm in params),
            "peak_act_bytes": int(peak_live_bytes(closed)),
            "boundary_bytes": int(boundary_bytes),
        })
    return report


def _build_demo(n_layers, n_stages, mb_rows, seq_len, vocab):
    """Tiny EncoderTower LM with uniform layer cuts — the same model
    ``bench.py``'s BENCH_PIPELINE leg times."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import dygraph, layers, optimizer
    from paddle_tpu.models import transformer

    with dygraph.guard():
        model = transformer.EncoderTower(
            vocab, d_model=64, n_heads=4, d_inner=128, n_layers=n_layers,
            max_len=seq_len, dropout_rate=0.0)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, vocab, size=(mb_rows, seq_len)).astype("int64")
        pos = np.tile(np.arange(seq_len, dtype="int64"), (mb_rows, 1))
        args = [dygraph.to_variable(v) for v in (ids, pos)]
        _, traced = dygraph.jit.trace(model, args)
    startup = fluid.Program()
    with fluid.program_guard(traced.program, startup):
        blk = traced.program.global_block()
        logits = blk.var(traced._fetch_names[0])
        label = layers.data("sb_lbl", [seq_len, 1], dtype="int64")
        ce = layers.softmax_with_cross_entropy(
            layers.reshape(logits, [-1, vocab]),
            layers.reshape(label, [-1, 1]))
        loss = layers.mean(ce)
        opt = optimizer.SGD(learning_rate=0.1)
        if n_stages > 1:
            per = n_layers // n_stages
            cuts = [blk.var(model.last_checkpoints[per * (i + 1) - 1])
                    for i in range(n_stages - 1)]
            opt = optimizer.PipelineOptimizer(opt, cut_list=cuts)
        opt.minimize(loss)
    feed = dict(zip(traced._feed_names, (ids, pos)))
    feed["sb_lbl"] = rng.randint(0, vocab,
                                 size=(mb_rows, seq_len, 1)).astype("int64")
    return traced.program, feed


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="per-stage param/activation bytes for a pipeline cut")
    ap.add_argument("--stages", type=int, default=2)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--mb-rows", type=int, default=4,
                    help="microbatch rows (per-shard batch)")
    ap.add_argument("--seq", type=int, default=32)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    if args.layers % args.stages:
        ap.error("--layers must divide evenly into --stages")
    program, feed = _build_demo(args.layers, args.stages, args.mb_rows,
                                args.seq, args.vocab)
    rows = stage_report(program, feed)
    if args.json:
        print(json.dumps(rows))
        return 0
    hdr = "%-6s %-5s %14s %16s %15s" % (
        "stage", "ops", "param_bytes", "peak_act_bytes", "boundary_bytes")
    print(hdr)
    print("-" * len(hdr))
    for row in rows:
        print("%-6d %-5d %14d %16d %15d" % (
            row["stage"], row["ops"], row["param_bytes"],
            row["peak_act_bytes"], row["boundary_bytes"]))
    pb = [r["param_bytes"] for r in rows]
    print("param imbalance (max/min): %.2f" % (max(pb) / max(min(pb), 1)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
