#!/usr/bin/env python
"""Chaos driver for the durable coordination service.

Each scenario SIGKILLs a standalone coordinator
(``python -m paddle_tpu.distributed.coordination``) at the worst
possible moment and restarts it on the SAME port against the SAME
``--wal-dir``, then asserts the system on top of it never noticed
beyond a bounded stall:

  barrier  kill between the two arrivals of a world-2 barrier — the
           journaled arrival survives, the blocked waiter re-dials,
           and both ranks are released with the SAME generation.
  lease    kill while a lease keeper renews a fleet-style membership
           key — the WAL-persisted wall deadline plus the keeper's
           post-reconnect replay keep the member live well past the
           TTL it held when the server died.
  fleet    delegate to ``bench.bench_coord_recovery(smoke=True)``:
           coordinator crash + recovery under closed-loop serving
           traffic (every request accounted, stale-routing window
           observed, zero lost).

Usage: python tools/chaos.py [barrier|lease|fleet|all]
Exit code 0 = every scenario held its invariant; one JSON line per
scenario on stdout.
"""

import argparse
import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

# printed by coordination.main() once the socket is bound
_BANNER = re.compile(r"coordination service at ([^\s:]+):(\d+) "
                     r"epoch=(\d+)")


def _spawn(wal_dir, port=0, timeout=120.0):
    """Start a coordinator subprocess; block until its stdout banner
    names the bound endpoint. Returns (proc, addr, port, epoch)."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(
        [sys.executable, "-u", "-m",
         "paddle_tpu.distributed.coordination",
         "--port", str(port), "--wal-dir", wal_dir],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        cwd=_REPO, env=env, text=True)
    # watchdog: a coordinator that never prints (import wedge, port
    # clash) would park readline() forever — kill it at the deadline
    # so the read returns EOF and we can raise with context
    watchdog = threading.Timer(timeout, proc.kill)
    watchdog.daemon = True
    watchdog.start()
    try:
        line = proc.stdout.readline()
    finally:
        watchdog.cancel()
    m = _BANNER.search(line or "")
    if not m:
        proc.kill()
        proc.wait()
        raise RuntimeError(
            "coordinator subprocess never announced its endpoint "
            "(got %r)" % (line,))
    return (proc, "%s:%s" % (m.group(1), m.group(2)),
            int(m.group(2)), int(m.group(3)))


def _kill9(proc):
    """Simulated power cut: SIGKILL, no drain, no final snapshot."""
    if proc.poll() is None:
        proc.send_signal(signal.SIGKILL)
    proc.wait()


def scenario_barrier():
    """Kill -9 between the two arrivals of a world-2 barrier."""
    from paddle_tpu.distributed.coordination import CoordClient

    wal = tempfile.mkdtemp(prefix="chaos_barrier_")
    proc, addr, port, epoch0 = _spawn(wal)
    a = CoordClient(addr, grace=120.0)
    b = CoordClient(addr, grace=120.0)
    got = {}
    try:
        t = threading.Thread(
            target=lambda: got.__setitem__(
                "a", a.barrier("chaos/bar", 2, "rank-a", timeout=240)),
            daemon=True)
        t.start()
        time.sleep(1.0)      # rank-a's arrival is journaled; it blocks
        _kill9(proc)
        proc, _, _, epoch1 = _spawn(wal, port=port)
        assert epoch1 == epoch0 + 1, (epoch0, epoch1)
        got["b"] = b.barrier("chaos/bar", 2, "rank-b", timeout=240)
        t.join(240)
        assert not t.is_alive(), "rank-a never released"
        assert got.get("a") == got["b"], got
        # the blocked waiter crossed the restart: its client saw the
        # new epoch in the re-dial handshake
        assert a.server_epoch == epoch1, (a.server_epoch, epoch1)
        return {"scenario": "barrier", "ok": True,
                "generation": got["b"],
                "epochs": [epoch0, epoch1]}
    finally:
        a.close()
        b.close()
        _kill9(proc)


def scenario_lease():
    """Kill -9 while a lease keeper renews a membership key."""
    from paddle_tpu.distributed.coordination import CoordClient

    wal = tempfile.mkdtemp(prefix="chaos_lease_")
    proc, addr, port, epoch0 = _spawn(wal)
    cli = CoordClient(addr, grace=120.0)
    key = "chaos/members/m0"
    try:
        cli.put(key, b"alive")
        cli.start_lease_keeper(key, ttl=4.0, interval=0.5)
        assert cli.live_members("chaos/members/") == [key]
        t_kill = time.monotonic()
        _kill9(proc)
        proc, _, _, epoch1 = _spawn(wal, port=port)
        # let a post-restart beat land, and stand well past the TTL
        # the member held when the server died
        time.sleep(max(3.0, t_kill + 6.0 - time.monotonic()))
        live = cli.live_members("chaos/members/")
        held_s = time.monotonic() - t_kill
        assert key in live, (live, held_s)
        assert cli.get(key) == b"alive"
        assert cli.server_epoch == epoch1, (cli.server_epoch, epoch1)
        return {"scenario": "lease", "ok": True,
                "held_through_outage_s": round(held_s, 2),
                "epochs": [epoch0, epoch1]}
    finally:
        cli.close()
        _kill9(proc)


def scenario_fleet():
    """Coordinator crash + recovery under closed-loop fleet traffic."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import bench

    out = bench.bench_coord_recovery(smoke=True)
    return dict({"scenario": "fleet", "ok": True}, **out)


_SCENARIOS = {"barrier": scenario_barrier,
              "lease": scenario_lease,
              "fleet": scenario_fleet}


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python tools/chaos.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("scenario", nargs="?", default="all",
                   choices=sorted(_SCENARIOS) + ["all"])
    args = p.parse_args(argv)
    names = sorted(_SCENARIOS) if args.scenario == "all" \
        else [args.scenario]
    for name in names:
        res = _SCENARIOS[name]()
        print(json.dumps(res))
    return 0


if __name__ == "__main__":
    sys.exit(main())
