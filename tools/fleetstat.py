#!/usr/bin/env python
"""fleetstat: one terminal view of a live fleet's merged telemetry.

Reads every live publisher's ``monitor.snapshot()`` from the
coordination KV (``telemetry/metrics/<proc>``, TTL-leased — dead
processes age out on their own) and renders either:

  * the default **fleet table** — one row per live publisher (name,
    pid, snapshot age, metric count) followed by the fleet-MERGED
    registry: counters summed, gauges last-write-wins, histograms as
    exact merged quantiles (p50/p99 over the union of observations,
    see telemetry/aggregate.py);
  * ``--prom`` — the merged registry as Prometheus text exposition
    (scrape-file or debugging dump), via ``aggregate.merged_prometheus``
    so it is rendered by the one canonical ``dump_prometheus``;
  * ``--watch N`` — re-render the table every N seconds.

Usage:
    python tools/fleetstat.py --coord HOST:PORT [--token T]
    python tools/fleetstat.py --coord HOST:PORT --prom [--out FILE]
    python tools/fleetstat.py --coord HOST:PORT --watch 2
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from paddle_tpu.fluid import monitor as _monitor            # noqa: E402
from paddle_tpu.telemetry import aggregate as _aggregate    # noqa: E402
from paddle_tpu.telemetry import pusher as _pusher          # noqa: E402


def _fmt_labels(labels):
    if not labels:
        return ""
    return "{%s}" % ",".join("%s=%s" % kv for kv in sorted(labels.items()))


def _fmt(v):
    if isinstance(v, float) and not v.is_integer():
        return "%.6g" % v
    return "%d" % v


def render_table(snapshots, out=sys.stdout):
    """The human view: publishers, then the merged registry."""
    now = time.time()
    out.write("%-28s %8s %8s %8s\n"
              % ("PROC", "PID", "AGE_S", "METRICS"))
    for snap in sorted(snapshots, key=lambda s: str(s.get("proc"))):
        out.write("%-28s %8s %8.1f %8d\n"
                  % (snap.get("proc") or "?", snap.get("pid", "?"),
                     max(now - float(snap.get("ts", now)), 0.0),
                     len(snap.get("metrics", ()))))
    if not snapshots:
        out.write("(no live publishers)\n")
        return
    metrics, _kinds = _aggregate.merge(snapshots)
    out.write("\n%-44s %-10s %s\n" % ("METRIC", "KIND", "VALUE"))
    for m in sorted(metrics, key=lambda m: (m.name,
                                            tuple(m.labels.items()))):
        label = m.name + _fmt_labels(m.labels)
        if isinstance(m, _monitor.Histogram):
            p50, p99 = m.quantile(0.5), m.quantile(0.99)
            out.write("%-44s %-10s count=%d sum=%s p50=%s p99=%s\n"
                      % (label, m.kind, m._count, _fmt(m._sum),
                         "-" if p50 is None else _fmt(p50),
                         "-" if p99 is None else _fmt(p99)))
        else:
            out.write("%-44s %-10s %s\n" % (label, m.kind, _fmt(m.value)))


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="live fleet telemetry: merged metrics from the "
                    "coordination KV")
    parser.add_argument("--coord", required=True,
                        help="coordination service host:port")
    parser.add_argument("--token", default=None,
                        help="coordination auth token "
                             "(default $PADDLE_COORD_TOKEN)")
    parser.add_argument("--prefix", default="telemetry/",
                        help="KV key prefix the pushers publish under")
    parser.add_argument("--prom", action="store_true",
                        help="dump merged Prometheus text instead of "
                             "the table")
    parser.add_argument("--out", default=None,
                        help="write to this file instead of stdout")
    parser.add_argument("--watch", type=float, default=None,
                        metavar="SECS",
                        help="re-render the table every SECS seconds")
    args = parser.parse_args(argv)

    def once(out):
        snapshots = _pusher.collect_metrics(
            args.coord, prefix=args.prefix, token=args.token)
        if args.prom:
            out.write(_aggregate.merged_prometheus(snapshots))
        else:
            render_table(snapshots, out=out)
        return len(snapshots)

    if args.watch and not args.prom:
        try:
            while True:
                sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
                once(sys.stdout)
                sys.stdout.flush()
                time.sleep(args.watch)
        except KeyboardInterrupt:
            return 0
    if args.out:
        with open(args.out, "w") as f:
            n = once(f)
    else:
        n = once(sys.stdout)
    return 0 if n else 2   # 2 = reachable but nobody publishing


if __name__ == "__main__":
    sys.exit(main())
