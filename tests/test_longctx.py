"""Long-context tier (kernels/attention.py sequence-parallel section):
ring attention (KV rotation over ICI neighbors, online-softmax fold
across hops) and DeepSpeed-Ulysses (all-to-all head<->sequence swap) over
the 'sp' mesh axis, plus the recompute memory knob and the
sequence-sharded decode session. Numerics are pinned against the
single-device oracle — the SAME op with n=1, and the jnp reference —
including the causal and dropout paths; dropout masks are keyed on
GLOBAL (batch, head, tile) coordinates, so the sharded outputs must be
bit-compatible with the unsharded ones, not just statistically alike."""

import os

import numpy as np
import pytest

os.environ.setdefault("PADDLE_TPU_PALLAS_INTERPRET", "1")

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import paddle_tpu.kernels.attention as A
from paddle_tpu.fluid import monitor
from paddle_tpu.kernels.attention import sequence_parallel_attention

pytestmark = pytest.mark.longctx

RTOL, ATOL = 2e-5, 2e-5
B, H, S, D = 2, 4, 256, 16
RNG = np.random.RandomState(5)
Q3 = (RNG.randn(B, S, H * D) * 0.5).astype(np.float32)
K3 = (RNG.randn(B, S, H * D) * 0.5).astype(np.float32)
V3 = (RNG.randn(B, S, H * D) * 0.5).astype(np.float32)
BIAS = np.zeros((B, 1, 1, S), np.float32)
BIAS[0, 0, 0, -17:] = -1e4
SCALE = 1.0 / np.sqrt(D)


def _mesh(n_sp, n_dp=1):
    devs = np.array(jax.devices()[:n_dp * n_sp]).reshape(n_dp, n_sp)
    return Mesh(devs, ("dp", "sp"))


def _split(x3):
    x = x3.reshape(B, S, H, D)
    return jnp.asarray(np.transpose(x, (0, 2, 1, 3)))


def _oracle(bias, causal, p_drop=0.0, rng_key=None):
    """The op itself at n=1 — fixes the dropout masks AND the math."""
    return sequence_parallel_attention(
        jnp.asarray(Q3), jnp.asarray(K3), jnp.asarray(V3), H,
        bias=None if bias is None else jnp.asarray(bias), mesh=None,
        causal=causal, dropout_prob=p_drop, rng_key=rng_key)


def _run(strategy, n_sp, bias, causal, p_drop=0.0, rng_key=None, n_dp=1):
    return sequence_parallel_attention(
        jnp.asarray(Q3), jnp.asarray(K3), jnp.asarray(V3), H,
        bias=None if bias is None else jnp.asarray(bias),
        mesh=_mesh(n_sp, n_dp), causal=causal, dropout_prob=p_drop,
        rng_key=rng_key, strategy=strategy)


# -- dispatch: every advertised PADDLE_TPU_ATTN_FORCE value ---------------
class TestAttnForceDispatch:
    def test_bogus_value_enumerates_all(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_ATTN_FORCE", "warp")
        with pytest.raises(ValueError) as ei:
            A._attn_force()
        for v in A._ATTN_FORCE_VALUES:
            assert v in str(ei.value)

    def test_flash_skips_long_tier(self, monkeypatch):
        q = jnp.zeros((1, 2, 2048, 64), jnp.float32)
        bias = jnp.zeros((1, 1, 1, 2048), jnp.float32)
        assert A._use_long_kernel(q, 0.0, bias)
        monkeypatch.setenv("PADDLE_TPU_ATTN_FORCE", "flash")
        assert not A._use_long_kernel(q, 0.0, bias)

    def test_packed_skips_res_tier(self, monkeypatch):
        q3 = jnp.zeros((2, 256, 4 * 64), jnp.float32)
        bias = jnp.zeros((2, 1, 1, 256), jnp.float32)
        assert A._use_res_kernel(q3, 4, 0.0, bias)
        monkeypatch.setenv("PADDLE_TPU_ATTN_FORCE", "packed")
        assert not A._use_res_kernel(q3, 4, 0.0, bias)
        assert A._use_packed_kernel(q3, 4, 0.0, bias)

    def test_decode_forces_kernel_at_any_capacity(self, monkeypatch):
        small = jnp.zeros((1, 2, 64, 16), jnp.float32)
        assert not A._use_decode_kernel(small)
        monkeypatch.setenv("PADDLE_TPU_ATTN_FORCE", "decode")
        assert A._use_decode_kernel(small)

    def test_ring_forced_over_auto_ulysses(self, monkeypatch):
        # H=4 divides n=4, so auto would pick ulysses; the force must
        # route to ring — observable as ring hops on the counter
        monkeypatch.setenv("PADDLE_TPU_ATTN_FORCE", "ring")
        hops = monitor.counter("attn_ring_hops_total")
        before = hops.value
        _run("auto", 4, None, False)
        assert hops.value == before + 3    # n - 1 hops per ring pass

    def test_ulysses_forced_over_ring_arg(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_ATTN_FORCE", "ulysses")
        hops = monitor.counter("attn_ring_hops_total")
        before = hops.value
        _run("ring", 4, None, False)       # force beats the argument
        assert hops.value == before        # no ring pass traced
        assert monitor.gauge("attn_seq_shards").value == 4

    def test_forced_ulysses_rejects_indivisible_heads(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_ATTN_FORCE", "ulysses")
        with pytest.raises(ValueError, match="divide"):
            _run("auto", 3, None, False)   # H=4, n=3


# -- numerics: sharded vs single-device ----------------------------------
@pytest.mark.parametrize("strategy", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [False, True])
def test_matches_reference_no_dropout(strategy, causal):
    s = jnp.einsum("bhqd,bhkd->bhqk", _split(Q3), _split(K3)) * SCALE
    s = s + jnp.asarray(BIAS)
    if causal:
        rows = jnp.arange(S)[:, None]
        s = jnp.where((jnp.arange(S)[None, :] <= rows)[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    ref4 = jnp.einsum("bhqk,bhkd->bhqd", p, _split(V3))
    ref = np.transpose(np.asarray(ref4), (0, 2, 1, 3)).reshape(B, S, H * D)
    got = np.asarray(_run(strategy, 4, BIAS, causal))
    np.testing.assert_allclose(got, ref, rtol=RTOL, atol=ATOL)


@pytest.mark.parametrize("strategy", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [False, True])
def test_sharded_matches_single_device_with_dropout(strategy, causal):
    """The pinned-closeness claim on the dropout path: the n=1 op run is
    the oracle (same global tile-keyed masks), the 4-shard run must
    reproduce it."""
    key = jax.random.PRNGKey(42)
    ref = np.asarray(_oracle(BIAS, causal, 0.2, key))
    got = np.asarray(_run(strategy, 4, BIAS, causal, 0.2, key))
    np.testing.assert_allclose(got, ref, rtol=RTOL, atol=ATOL)


@pytest.mark.parametrize("strategy", ["ring", "ulysses"])
def test_gradients_match_single_device(strategy):
    key = jax.random.PRNGKey(7)

    def loss(fn):
        def f(q, k, v):
            out = fn(q, k, v)
            return jnp.sum(out * out)
        return jax.grad(f, argnums=(0, 1, 2))

    ref_fn = lambda q, k, v: sequence_parallel_attention(
        q, k, v, H, bias=jnp.asarray(BIAS), mesh=None, causal=True,
        dropout_prob=0.2, rng_key=key)
    got_fn = lambda q, k, v: sequence_parallel_attention(
        q, k, v, H, bias=jnp.asarray(BIAS), mesh=_mesh(4), causal=True,
        dropout_prob=0.2, rng_key=key, strategy=strategy)
    gr = loss(ref_fn)(jnp.asarray(Q3), jnp.asarray(K3), jnp.asarray(V3))
    gg = loss(got_fn)(jnp.asarray(Q3), jnp.asarray(K3), jnp.asarray(V3))
    for r, g in zip(gr, gg):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=5e-5, atol=5e-5)


def test_batch_axis_composes():
    """dp=2 x sp=2: batch shards over 'dp' while the sequence shards
    over 'sp'; dropout masks keyed on GLOBAL batch ids keep the result
    identical to the unsharded run."""
    key = jax.random.PRNGKey(9)
    ref = np.asarray(_oracle(BIAS, True, 0.2, key))
    got = np.asarray(_run("ring", 2, BIAS, True, 0.2, key, n_dp=2))
    np.testing.assert_allclose(got, ref, rtol=RTOL, atol=ATOL)


def test_seq_not_divisible_raises():
    with pytest.raises(ValueError, match="divisible"):
        _run("ring", 3, None, False)    # S=256, n=3


def test_dropout_chunk_tile_guard():
    q = jnp.zeros((1, 128, H * D), jnp.float32)
    with pytest.raises(ValueError, match="tile"):
        sequence_parallel_attention(
            q, q, q, H, mesh=_mesh(4), dropout_prob=0.1,
            rng_key=jax.random.PRNGKey(0))    # S/n = 32 < 64-wide tile


# -- model layer: train step + recompute + decode session ----------------
def _trace_tiny(seq_parallel, strategy="auto", V=64, Bm=4, Sm=32,
                drop=0.0, seed=7):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import dygraph
    from paddle_tpu.models import transformer

    with dygraph.guard():
        model = transformer.Transformer(
            V, V, d_model=32, n_heads=4, d_inner=64, n_layers=2,
            max_len=max(64, Sm), dropout_rate=drop,
            seq_parallel=seq_parallel, attn_strategy=strategy)
        rng = np.random.RandomState(seed)
        for _, p in model.named_parameters():
            p.set_value(rng.uniform(-0.1, 0.1, p.shape).astype(np.float32))
        src, tgt, labels, pos = transformer.synthetic_batch(V, V, Bm, Sm)
        bias = transformer.make_causal_bias(Sm)
        args = [dygraph.to_variable(v)
                for v in (src, tgt, pos, pos, bias)]
        _, tl = dygraph.jit.trace(model, args)
    return model, tl, (src, tgt, pos, bias, labels)


def _train_losses(model, tl, data, V=64, Sm=32, compiledfn=None,
                  recompute=False, steps=3):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers, optimizer
    from paddle_tpu.fluid.executor import scope_guard

    src, tgt, pos, bias, labels = data
    startup = fluid.Program()
    with fluid.program_guard(tl.program, startup):
        logits = tl.program.global_block().var(tl._fetch_names[0])
        label = layers.data("lc_label", [Sm, 1], dtype="int64")
        ce = layers.softmax_with_cross_entropy(
            layers.reshape(logits, [-1, V]),
            layers.reshape(label, [-1, 1]))
        loss = layers.mean(ce)
        opt = optimizer.SGD(learning_rate=0.1)
        if recompute:
            opt = optimizer.RecomputeOptimizer(opt)
            opt._set_checkpoints(model.checkpoint_vars(tl.program))
        opt.minimize(loss)
    tl._materialize_scope()
    exe = fluid.Executor()
    prog = tl.program
    if compiledfn:
        prog = compiledfn(fluid.CompiledProgram(prog))
    feed = dict(zip(tl._feed_names, (src, tgt, pos, pos, bias)))
    feed["lc_label"] = labels
    losses = []
    with scope_guard(tl._scope):
        exe.run(startup)
        for _ in range(steps):
            (lv,) = exe.run(prog, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(lv)))
    return losses, tl, feed, loss.name


_SP_MESH = lambda cp: cp.with_data_parallel(
    mesh_axes=("dp", "sp"), mesh_shape={"dp": 2, "sp": 4}, places=8)


@pytest.mark.parametrize("strategy", ["ring", "ulysses"])
def test_train_step_matches_single_device(strategy):
    """Full traced train step (loss + SGD) on a dp=2 x sp=4 mesh vs the
    plain single-device program — loss trajectories pinned to fp32
    closeness."""
    m0, tl0, data = _trace_tiny(False)
    ref, _, _, _ = _train_losses(m0, tl0, data)
    m1, tl1, _ = _trace_tiny(True, strategy)
    got, _, _, _ = _train_losses(m1, tl1, data, compiledfn=_SP_MESH)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_recompute_lowers_peak_memory_same_losses():
    """RecomputeOptimizer over the per-block checkpoint vars: the loss
    trajectory must be unchanged while the statically-estimated peak
    live bytes drop (the activations live only inside remat segments)."""
    from paddle_tpu.utils import liveness

    m0, tl0, data = _trace_tiny(True, "ring", Sm=64)
    base, tl0, feed0, l0 = _train_losses(m0, tl0, data, Sm=64)
    m1, tl1, _ = _trace_tiny(True, "ring", Sm=64)
    rec, tl1, feed1, l1 = _train_losses(m1, tl1, data, Sm=64,
                                        recompute=True)
    np.testing.assert_allclose(rec, base, rtol=1e-5, atol=1e-6)
    p0 = liveness.program_peak_bytes(tl0.program, feed0, tl0._scope, [l0])
    p1 = liveness.program_peak_bytes(tl1.program, feed1, tl1._scope, [l1])
    assert p1 < p0, "recompute did not lower peak live bytes: %d >= %d" \
        % (p1, p0)


@pytest.mark.decode
def test_seq_sharded_decode_token_identical():
    """seq_shards=4 decode session (KV ring caches + cross K/V sharded
    on the sequence dim over 'sp') vs the unsharded session — token
    stream and finished mask identical, INCLUDING a generation that
    wraps the ring capacity (prompt 8 + 12 new > capacity 16)."""
    from paddle_tpu.fluid import dygraph
    from paddle_tpu.models import transformer

    V, Bd, SRC, PROMPT, CAP = 64, 2, 16, 8, 16
    rng = np.random.RandomState(3)
    src = rng.randint(2, V, (Bd, SRC)).astype(np.int64)
    prompt = rng.randint(2, V, (Bd, PROMPT)).astype(np.int64)
    plens = np.array([PROMPT, PROMPT - 2], np.int64)

    def gen(seq_shards):
        with dygraph.guard():
            model = transformer.Transformer.tiny(V, V)
            prng = np.random.RandomState(11)
            for _, p in model.named_parameters():
                p.set_value(prng.uniform(-0.3, 0.3,
                                         p.shape).astype(np.float32))
            sess = transformer.build_decode_session(
                model, Bd, SRC, PROMPT, CAP, end_id=1,
                seq_shards=seq_shards)
        return sess.generate(src, prompt, plens, 12)

    toks1, fin1 = gen(1)
    toks4, fin4 = gen(4)
    assert np.array_equal(toks1, toks4)
    assert np.array_equal(fin1, fin4)


def test_seq_shards_validates_divisibility():
    from paddle_tpu.fluid import dygraph
    from paddle_tpu.models import transformer

    with dygraph.guard():
        model = transformer.Transformer.tiny()
        with pytest.raises(ValueError, match="divide"):
            transformer.build_decode_session(model, 1, 10, 8, 18,
                                             seq_shards=4)


# -- heavy: S >= 1024 over the full 8-device ring ------------------------
@pytest.mark.slow
@pytest.mark.parametrize("strategy,n", [("ring", 8), ("ulysses", 4)])
def test_long_sequence_8_shards(strategy, n):
    # ulysses needs n | H (H=4); ring takes the full 8-device axis
    S_big = 1024
    rng = np.random.RandomState(13)
    q = (rng.randn(1, S_big, H * D) * 0.5).astype(np.float32)
    k = (rng.randn(1, S_big, H * D) * 0.5).astype(np.float32)
    v = (rng.randn(1, S_big, H * D) * 0.5).astype(np.float32)
    devs = np.array(jax.devices()[:n]).reshape(1, n)
    mesh = Mesh(devs, ("dp", "sp"))
    ref = np.asarray(sequence_parallel_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), H, mesh=None,
        causal=True))
    got = np.asarray(sequence_parallel_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), H, mesh=mesh,
        causal=True, strategy=strategy))
    np.testing.assert_allclose(got, ref, rtol=RTOL, atol=1e-4)
