"""Seq2seq NMT book model: teacher-forced training then beam-search decode
in the SAME scope (shared parameter names) — the reference
test_machine_translation flow end to end."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.models import seq2seq


def test_seq2seq_trains_and_beam_decodes_echo():
    rng = np.random.RandomState(0)
    V, L = 16, 5
    main, startup, loss = seq2seq.build_train_program(
        src_vocab=V, tgt_vocab=V, src_len=L, tgt_len=L, lr=1e-2)
    infer, infer_startup, seqs = seq2seq.build_infer_program(
        src_vocab=V, tgt_vocab=V, src_len=L, max_tgt_len=L, beam_size=3)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        losses = []
        for _ in range(150):
            feed = seq2seq.synthetic_pairs(rng, 32, V, L)
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(lv).ravel()[0]))
        assert losses[-1] < losses[0] * 0.2, (losses[0], losses[-1])

        # infer program resolves the SAME persistable params from scope
        feed = seq2seq.synthetic_pairs(rng, 4, V, L)
        (sv,) = exe.run(infer, feed={"s2s_src": feed["s2s_src"]},
                        fetch_list=[seqs])
        sv = np.asarray(sv)  # [T, B*beam]
        assert sv.shape[1] == 4 * 3
        # top beam of each example echoes the last source token
        want = feed["s2s_src"][:, -1]
        got_first_step = sv[0].reshape(4, 3)[:, 0]
        assert (got_first_step == want).mean() >= 0.75, (got_first_step,
                                                         want)


@pytest.mark.slow
def test_crf_tagger_trains_and_decodes():
    from paddle_tpu.models import tagger

    rng = np.random.RandomState(2)
    main, startup, loss = tagger.build_train_program(vocab=32, num_tags=4)
    dec, _, path = tagger.build_decode_program(vocab=32, num_tags=4)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        losses = []
        for _ in range(120):
            feed, _l = tagger.synthetic_tagging(rng, 16, 32, 4)
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(lv).ravel()[0]))
        assert losses[-1] < losses[0] * 0.3, (losses[0], losses[-1])

        feed, lens = tagger.synthetic_tagging(rng, 8, 32, 4)
        (pv,) = exe.run(dec, feed={"tg_words": feed["tg_words"]},
                        fetch_list=[path])
        pv = np.asarray(pv).ravel()
        want = np.asarray(feed["tg_tags"]._data).ravel()
        n = sum(lens)
        acc = (pv[:n] == want[:n]).mean()
        assert acc > 0.8, acc
