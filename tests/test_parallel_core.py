"""paddle_tpu.parallel: ring attention, Ulysses, TP linears, pipeline —
numerics vs single-device references on the 8-device CPU mesh (the
spawn-local-fake-cluster strategy of the reference's TestDistBase, SURVEY §4,
without processes)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from paddle_tpu import parallel as pl
from paddle_tpu import jax_compat


@pytest.fixture(scope="module")
def sp_mesh():
    return pl.make_mesh({"sp": 4})


@pytest.fixture(scope="module")
def tp_mesh():
    return pl.make_mesh({"tp": 4})


@pytest.fixture(scope="module")
def pp_mesh():
    return pl.make_mesh({"pp": 4})


def _qkv(b=2, s=32, h=4, d=8, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.slow
def test_ring_attention_matches_reference(sp_mesh, causal):
    q, k, v = _qkv()
    ref = pl.attention_reference(q, k, v, causal=causal)
    out = pl.ring_attention(q, k, v, sp_mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_reference(sp_mesh, causal):
    q, k, v = _qkv(h=8)
    ref = pl.attention_reference(q, k, v, causal=causal)
    out = pl.ulysses_attention(q, k, v, sp_mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_ring_attention_grads(sp_mesh):
    q, k, v = _qkv(b=1, s=16, h=2, d=4)

    def loss_ring(q, k, v):
        return jnp.sum(pl.ring_attention(q, k, v, sp_mesh, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(pl.attention_reference(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_tp_column_then_row_linear(tp_mesh):
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(8, 16).astype(np.float32))
    w1 = jnp.asarray(rng.randn(16, 32).astype(np.float32))
    b1 = jnp.asarray(rng.randn(32).astype(np.float32))
    w2 = jnp.asarray(rng.randn(32, 16).astype(np.float32))
    b2 = jnp.asarray(rng.randn(16).astype(np.float32))
    ref = jax.nn.relu(x @ w1 + b1) @ w2 + b2

    def mlp(x, w1, b1, w2, b2):
        h = pl.column_parallel_linear(x, w1, b1)
        h = jax.nn.relu(h)
        return pl.row_parallel_linear(h, w2, b2)

    out = jax_compat.shard_map(
        mlp, mesh=tp_mesh,
        in_specs=(P(), P(None, "tp"), P("tp"), P("tp", None), P()),
        out_specs=P(),
        check_vma=False,
    )(x, w1, b1, w2, b2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_vocab_parallel_embedding(tp_mesh):
    rng = np.random.RandomState(2)
    table = jnp.asarray(rng.randn(64, 8).astype(np.float32))
    ids = jnp.asarray(rng.randint(0, 64, (4, 7)))
    ref = jnp.take(table, ids, axis=0)
    out = jax_compat.shard_map(
        functools.partial(pl.vocab_parallel_embedding),
        mesh=tp_mesh,
        in_specs=(P(), P("tp", None)),
        out_specs=P(),
        check_vma=False,
    )(ids, table)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


def test_pipeline_matches_sequential(pp_mesh):
    rng = np.random.RandomState(3)
    n_stage, m, bsz, dim = 4, 6, 3, 8
    ws = jnp.asarray(rng.randn(n_stage, dim, dim).astype(np.float32) * 0.3)
    bs = jnp.asarray(rng.randn(n_stage, dim).astype(np.float32) * 0.1)
    mbs = jnp.asarray(rng.randn(m, bsz, dim).astype(np.float32))

    def stage(params, x):
        w, b = params
        return jnp.tanh(x @ w + b)

    ref = mbs
    for i in range(n_stage):
        ref = stage((ws[i], bs[i]), ref)

    out = pl.pipeline(stage, (ws, bs), mbs, pp_mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_pipeline_differentiable(pp_mesh):
    rng = np.random.RandomState(4)
    n_stage, m, bsz, dim = 4, 4, 2, 4
    ws = jnp.asarray(rng.randn(n_stage, dim, dim).astype(np.float32) * 0.3)
    bs = jnp.zeros((n_stage, dim), jnp.float32)
    mbs = jnp.asarray(rng.randn(m, bsz, dim).astype(np.float32))

    def stage(params, x):
        w, b = params
        return jnp.tanh(x @ w + b)

    def loss_pl(ws, bs):
        return jnp.sum(pl.pipeline(stage, (ws, bs), mbs, pp_mesh) ** 2)

    def loss_ref(ws, bs):
        y = mbs
        for i in range(n_stage):
            y = stage((ws[i], bs[i]), y)
        return jnp.sum(y ** 2)

    gw_pl, gb_pl = jax.grad(loss_pl, argnums=(0, 1))(ws, bs)
    gw_rf, gb_rf = jax.grad(loss_ref, argnums=(0, 1))(ws, bs)
    np.testing.assert_allclose(np.asarray(gw_pl), np.asarray(gw_rf),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gb_pl), np.asarray(gb_rf),
                               rtol=1e-4, atol=1e-4)


def test_parallel_executor_api_trains_dp():
    """fluid.ParallelExecutor (reference parallel_executor.py:28): the
    pre-CompiledProgram multi-device API drives GSPMD DP over the
    8-device mesh; loss decreases and a test-PE shares its weights."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("pe_x", [8], dtype="float32")
        y = layers.data("pe_y", [1], dtype="float32")
        pred = layers.fc(x, 1, name="pe_fc")
        loss = layers.reduce_mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    test_prog = main._prune([loss])

    rng = np.random.RandomState(0)
    xs = rng.rand(16, 8).astype(np.float32)
    ys = (xs.sum(1, keepdims=True) * 0.5).astype(np.float32)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        fluid.Executor().run(startup)
        pe = fluid.ParallelExecutor(use_cuda=False, loss_name=loss.name,
                                    main_program=main, scope=scope)
        assert pe.device_count == 8
        losses = [float(np.asarray(pe.run([loss.name],
                                          feed={"pe_x": xs, "pe_y": ys})[0]
                                    ).ravel()[0])
                  for _ in range(6)]
        assert losses[-1] < losses[0], losses
        # share_vars_from: a test PE reads the trained weights
        pe_test = fluid.ParallelExecutor(use_cuda=False,
                                         main_program=test_prog,
                                         share_vars_from=pe)
        (lv,) = pe_test.run([loss.name], feed={"pe_x": xs, "pe_y": ys})
        np.testing.assert_allclose(float(np.asarray(lv).ravel()[0]),
                                   losses[-1], rtol=0.2)


def test_parallel_executor_per_device_feed_and_guards():
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("pd_x", [4], dtype="float32")
        s = layers.reduce_sum(x)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        fluid.Executor().run(startup)
        pe = fluid.ParallelExecutor(main_program=main, scope=scope)
        # reference-style per-device feed: list of dicts concatenates
        halves = [{"pd_x": np.ones((2, 4), np.float32)},
                  {"pd_x": np.full((2, 4), 2.0, np.float32)}]
        (sv,) = pe.run([s.name], feed=halves)
        np.testing.assert_allclose(float(np.asarray(sv).ravel()[0]), 24.0)
    # the refusal must point at the working multi-process path (fleet
    # collective / paddle_tpu.distributed — whose single-vs-multi
    # equivalence tests/test_fleet_collective.py pins)
    with pytest.raises(ValueError, match="fleet collective"):
        fluid.ParallelExecutor(main_program=main, num_trainers=4)
