"""Fleet collective mode: GradAllReduce transpile + shard_map execution with
explicit XLA collectives over the 8-device mesh.

Reference analogue: test_dist_mnist.py NCCL2 mode — trainer losses must match
the single-device baseline (SURVEY §4)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, optimizer
from paddle_tpu.fluid.incubate.fleet.base.role_maker import (
    UserDefinedCollectiveRoleMaker,
)
from paddle_tpu.fluid.incubate.fleet.collective import (
    DistributedStrategy,
    fleet,
)


def _model(seed):
    main = fluid.Program()
    startup = fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[8], dtype="float32")
        label = layers.data(name="label", shape=[1], dtype="int64")
        h = layers.fc(x, size=16, act="relu")
        logits = layers.fc(h, size=4)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    return main, startup, loss


@pytest.mark.slow
def test_fleet_collective_matches_baseline():
    rng = np.random.RandomState(0)
    xv = rng.rand(16, 8).astype(np.float32)
    yv = rng.randint(0, 4, (16, 1)).astype(np.int64)

    # single-device baseline
    main, startup, loss = _model(11)
    with fluid.program_guard(main, startup):
        optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor()
    base_losses = []
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(4):
            (lv,) = exe.run(main, feed={"x": xv, "label": yv}, fetch_list=[loss])
            base_losses.append(float(lv))

    # fleet collective (explicit allreduce under shard_map)
    main2, startup2, loss2 = _model(11)
    fleet.init(UserDefinedCollectiveRoleMaker(current_id=0))
    with fluid.program_guard(main2, startup2):
        dopt = fleet.distributed_optimizer(optimizer.SGD(0.1),
                                           DistributedStrategy())
        dopt.minimize(loss2)
    # program now contains explicit collective ops
    types = [op.type for op in fleet.main_program.global_block().ops]
    assert "c_allreduce_sum" in types

    fleet._compiled = None
    compiled = fleet.compiled_program(loss_name=loss2.name)
    exe2 = fluid.Executor()
    dp_losses = []
    with fluid.scope_guard(fluid.Scope()):
        exe2.run(fleet.startup_program)
        for _ in range(4):
            (lv,) = exe2.run(compiled, feed={"x": xv, "label": yv},
                             fetch_list=[loss2])
            dp_losses.append(float(lv))

    np.testing.assert_allclose(base_losses, dp_losses, rtol=1e-4)


def test_collective_ops_single_rank_identity():
    """Outside any mesh, collectives are identity (1-rank world)."""
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = layers.data(name="x", shape=[4], dtype="float32")
        out = main.global_block().create_var(name="ar_out", shape=(-1, 4),
                                             dtype="float32")
        main.global_block().append_op(
            "c_allreduce_sum", inputs={"X": [x]}, outputs={"Out": [out]},
            attrs={"ring_id": 0})
    exe = fluid.Executor()
    xv = np.random.rand(2, 4).astype(np.float32)
    with fluid.scope_guard(fluid.Scope()):
        (r,) = exe.run(main, feed={"x": xv}, fetch_list=["ar_out"])
    np.testing.assert_allclose(r, xv)


def test_localsgd_transpile():
    main, startup, loss = _model(13)
    fleet.init(UserDefinedCollectiveRoleMaker(current_id=0))
    strategy = DistributedStrategy()
    strategy.use_local_sgd = True
    with fluid.program_guard(main, startup):
        dopt = fleet.distributed_optimizer(optimizer.SGD(0.1), strategy)
        dopt.minimize(loss)
    types = [op.type for op in fleet.main_program.global_block().ops]
    assert "c_allreduce_avg" in types
