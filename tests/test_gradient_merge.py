"""GradientMergeOptimizer: k-step gradient accumulation matches a plain
optimizer fed the combined batch (capability of the reference's
``ir/multi_batch_merge_pass.cc``)."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import initializer, layers, optimizer


def _build(opt_factory, merge_k=None):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("gm_x", [4])
        y = layers.data("gm_y", [1])
        pred = layers.fc(x, 1, param_attr=fluid.ParamAttr(
            name="gm_w", initializer=initializer.Constant(0.25)),
            bias_attr=fluid.ParamAttr(
                name="gm_b", initializer=initializer.Constant(0.0)))
        loss = layers.reduce_mean(layers.square(pred - y))
        opt = opt_factory()
        if merge_k:
            opt = optimizer.GradientMergeOptimizer(opt, k_steps=merge_k,
                                                   avg=True)
        opt.minimize(loss)
    return main, startup, loss


def _param(scope, name):
    return np.asarray(scope.find_var(name))


def _run_merge_vs_dense(opt_factory, n_merge_rounds, k=2, seed=0):
    rng = np.random.RandomState(seed)
    micro = [
        (rng.rand(8, 4).astype(np.float32), rng.rand(8, 1).astype(np.float32))
        for _ in range(n_merge_rounds * k)]

    # merged: k micro-batches per applied update
    scope_m = fluid.Scope()
    with fluid.scope_guard(scope_m):
        main, startup, loss = _build(opt_factory, merge_k=k)
        exe = fluid.Executor()
        exe.run(startup)
        for xb, yb in micro:
            exe.run(main, feed={"gm_x": xb, "gm_y": yb}, fetch_list=[])
        w_m, b_m = _param(scope_m, "gm_w"), _param(scope_m, "gm_b")

    # dense: one step per combined batch
    scope_d = fluid.Scope()
    with fluid.scope_guard(scope_d):
        main, startup, loss = _build(opt_factory)
        exe = fluid.Executor()
        exe.run(startup)
        for i in range(n_merge_rounds):
            xs = np.concatenate([micro[i * k + j][0] for j in range(k)])
            ys = np.concatenate([micro[i * k + j][1] for j in range(k)])
            exe.run(main, feed={"gm_x": xs, "gm_y": ys}, fetch_list=[])
        w_d, b_d = _param(scope_d, "gm_w"), _param(scope_d, "gm_b")

    np.testing.assert_allclose(w_m, w_d, rtol=2e-5, atol=1e-6)
    np.testing.assert_allclose(b_m, b_d, rtol=2e-5, atol=1e-6)


def test_sgd_merge_matches_big_batch():
    _run_merge_vs_dense(lambda: optimizer.SGD(learning_rate=0.1),
                        n_merge_rounds=3)


def test_adam_merge_matches_big_batch():
    # state (moments, beta powers) must advance once per merge, not per
    # micro step — this fails if gating leaks into optimizer state.
    _run_merge_vs_dense(lambda: optimizer.Adam(learning_rate=0.05),
                        n_merge_rounds=3)


def test_momentum_merge_matches_big_batch():
    _run_merge_vs_dense(
        lambda: optimizer.Momentum(learning_rate=0.1, momentum=0.9),
        n_merge_rounds=3)


def test_params_frozen_between_syncs():
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        main, startup, loss = _build(
            lambda: optimizer.SGD(learning_rate=0.1), merge_k=3)
        exe = fluid.Executor()
        exe.run(startup)
        w0 = _param(scope, "gm_w").copy()
        rng = np.random.RandomState(1)
        feed = {"gm_x": rng.rand(8, 4).astype(np.float32),
                "gm_y": rng.rand(8, 1).astype(np.float32)}
        exe.run(main, feed=feed, fetch_list=[])
        np.testing.assert_allclose(_param(scope, "gm_w"), w0)  # step 1: hold
        exe.run(main, feed=feed, fetch_list=[])
        np.testing.assert_allclose(_param(scope, "gm_w"), w0)  # step 2: hold
        exe.run(main, feed=feed, fetch_list=[])
        assert not np.allclose(_param(scope, "gm_w"), w0)      # step 3: apply
