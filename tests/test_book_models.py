"""Book-suite e2e models beyond MNIST (reference
``python/paddle/fluid/tests/book/``): word2vec, sentiment (conv +
stacked-LSTM), VGG16. Each trains on synthetic separable data and must
reduce its loss."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.models import sentiment, vgg, word2vec


def _train(main, startup, loss, feeder, steps, fetch=None):
    exe = fluid.Executor()
    losses = []
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for i in range(steps):
            out = exe.run(main, feed=feeder(i),
                          fetch_list=[loss] + list(fetch or []))
            losses.append(float(np.asarray(out[0]).ravel()[0]))
    return losses


def test_word2vec_learns_ngram_language():
    rng = np.random.RandomState(0)
    main, startup, loss, _ = word2vec.build_train_program(vocab_size=32,
                                                          lr=5e-3)
    batches = [word2vec.synthetic_ngrams(rng, 64, 32) for _ in range(8)]
    losses = _train(main, startup, loss, lambda i: batches[i % 8], 60)
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


def test_sentiment_conv_net_separates():
    rng = np.random.RandomState(1)
    main, startup, loss, acc = sentiment.build_train_program(net="conv",
                                                             input_dim=64)
    batches = [sentiment.synthetic_reviews(rng, 32, 64) for _ in range(6)]
    losses = _train(main, startup, loss, lambda i: batches[i % 6], 36)
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])


def test_sentiment_stacked_lstm_runs_and_learns():
    rng = np.random.RandomState(2)
    main, startup, loss, acc = sentiment.build_train_program(net="lstm",
                                                             input_dim=64)
    batches = [sentiment.synthetic_reviews(rng, 16, 64) for _ in range(4)]
    losses = _train(main, startup, loss, lambda i: batches[i % 4], 24)
    assert losses[-1] < losses[0] * 0.85, (losses[0], losses[-1])


def test_vgg16_smoke_trains():
    rng = np.random.RandomState(3)
    main, startup, loss, acc = vgg.build_train_program(width_mult=0.125,
                                                       lr=2e-3)
    batches = [vgg.synthetic_cifar(rng, 16) for _ in range(3)]
    losses = _train(main, startup, loss, lambda i: batches[i % 3], 9)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 1.05, (losses[0], losses[-1])


def test_recommender_system_trains():
    """Book recommender (reference test_recommender_system.py): dual
    embedding towers + cos_sim*5 regression; loss decreases on a fixed
    synthetic batch, ragged movie fields riding bounded-LoD feeds."""
    from paddle_tpu.models import recommender

    main, startup, loss, feeds = recommender.build_train_program(lr=0.2)
    assert set(feeds) >= {"user_id", "movie_title", "score"}
    exe = fluid.Executor()
    batch = recommender.synthetic_batch(16)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        losses = []
        for _ in range(8):
            (lv,) = exe.run(main, feed=batch, fetch_list=[loss])
            losses.append(float(np.asarray(lv).ravel()[0]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses


def test_fit_a_line_book():
    """Book hello-world (reference tests/book/test_fit_a_line.py): one
    fc over the 13 uci_housing features, SGD on square error — loss
    decreases over epochs of the real reader pipeline."""
    from paddle_tpu import dataset

    reader = fluid.io.batch(
        fluid.io.shuffle(dataset.uci_housing.train(), buf_size=128),
        batch_size=20)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("house_x", [13], dtype="float32")
        y = layers.data("house_y", [1], dtype="float32")
        pred = layers.fc(x, 1)
        loss = layers.reduce_mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        epoch_losses = []
        for _ in range(3):
            vals = []
            for batch in reader():
                xs = np.stack([b[0] for b in batch]).astype(np.float32)
                ys = np.stack([b[1] for b in batch]).astype(
                    np.float32).reshape(-1, 1)
                (lv,) = exe.run(main, feed={"house_x": xs, "house_y": ys},
                                fetch_list=[loss])
                vals.append(float(np.asarray(lv).ravel()[0]))
            epoch_losses.append(np.mean(vals))
    assert all(np.isfinite(epoch_losses))
    assert epoch_losses[-1] < epoch_losses[0], epoch_losses
