"""Tensor parallelism through the fluid Program surface (VERDICT item 5):
ParamAttr(shard=...) -> CompiledProgram GSPMD layouts -> XLA inserts the
Megatron collectives. Correctness bar: dp x tp training matches the
single-device loss trajectory exactly (same math, different layout).
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, optimizer
from paddle_tpu.models import bert


def test_param_shard_spec_recorded():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        y = layers.fc(x, size=16,
                      param_attr=fluid.ParamAttr(name="w_tp",
                                                 shard=(None, "tp")))
        optimizer.Adam(0.1).minimize(layers.mean(y))
    w = main.global_block().var("w_tp")
    assert w.shard_spec == (None, "tp")
    # adam moments inherit the layout
    moments = [v for v in main.list_vars()
               if v.name.startswith("w_tp_moment")]
    assert moments and all(
        getattr(m, "shard_spec", None) == (None, "tp") for m in moments)


def test_shard_spec_rank_mismatch_raises():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        with pytest.raises(ValueError):
            layers.fc(x, size=16,
                      param_attr=fluid.ParamAttr(shard=("tp",)))


def _mlp(seed, tp):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    attr = (lambda kind: fluid.ParamAttr(
        shard=(None, "tp") if kind == "col" else ("tp", None))) if tp \
        else (lambda kind: None)
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[16], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(x, size=32, act="relu", param_attr=attr("col"))
        h = layers.fc(h, size=16, act="relu", param_attr=attr("row"))
        logits = layers.fc(h, size=4)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


@pytest.mark.slow
def test_tp_mlp_matches_single_device():
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(16, 16).astype(np.float32),
            "label": rng.randint(0, 4, (16, 1)).astype(np.int64)}

    main, startup, loss = _mlp(31, tp=False)
    exe = fluid.Executor()
    base = []
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(4):
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
            base.append(float(np.asarray(lv)))

    main2, startup2, loss2 = _mlp(31, tp=True)
    compiled = fluid.CompiledProgram(main2).with_data_parallel(
        loss_name=loss2.name, mesh_axes=("dp", "tp"),
        mesh_shape={"dp": 2, "tp": 4})
    got = []
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup2)
        for _ in range(4):
            (lv,) = exe.run(compiled, feed=feed, fetch_list=[loss2])
            got.append(float(np.asarray(lv)))
    np.testing.assert_allclose(base, got, rtol=1e-4)


@pytest.mark.slow
def test_bert_tiny_dp_tp_matches_single_device():
    """The flagship path: a fluid BERT Program with tp>1 trains on the
    8-device mesh and reproduces the single-device loss curve."""
    seq = 16
    batch = bert.synthetic_batch(bert.BertConfig.tiny(), 8, seq)

    def run(tp):
        cfg = bert.BertConfig.tiny()
        cfg.hidden_dropout = 0.0
        cfg.attn_dropout = 0.0
        if tp:
            cfg.tp_axis = "tp"
        main, startup, loss = bert.build_pretrain_program(
            cfg, seq_len=seq, lr=1e-3, seed=41)
        exe = fluid.Executor()
        target = main
        if tp:
            target = fluid.CompiledProgram(main).with_data_parallel(
                loss_name=loss.name, mesh_axes=("dp", "tp"),
                mesh_shape={"dp": 2, "tp": 4})
        out = []
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            for _ in range(3):
                (lv,) = exe.run(target, feed=batch, fetch_list=[loss])
                out.append(float(np.asarray(lv)))
        return out

    base = run(False)
    got = run(True)
    assert got[-1] < got[0]
    np.testing.assert_allclose(base, got, rtol=2e-3)


def test_shard_tensor_annotation():
    """layers.shard_tensor annotates activations; single-device it is the
    identity, under a mesh it constrains the layout (still exact math)."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 5
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        h = layers.fc(x, size=8, act="relu")
        h = layers.shard_tensor(h, ["dp", None])
        loss = layers.mean(h)
        optimizer.SGD(0.1).minimize(loss)
    feed = {"x": np.random.RandomState(1).rand(8, 8).astype(np.float32)}
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (a,) = exe.run(main, feed=feed, fetch_list=[loss])
    compiled = fluid.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (b,) = exe.run(compiled, feed=feed, fetch_list=[loss])
    np.testing.assert_allclose(float(np.asarray(a)), float(np.asarray(b)),
                               rtol=1e-5)


def test_unknown_mesh_axis_raises():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 6
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.fc(x, size=4,
                      param_attr=fluid.ParamAttr(shard=(None, "nope")))
        loss = layers.mean(y)
        optimizer.SGD(0.1).minimize(loss)
    compiled = fluid.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        with pytest.raises(ValueError):
            exe.run(compiled,
                    feed={"x": np.ones((8, 4), np.float32)},
                    fetch_list=[loss])
