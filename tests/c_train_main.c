/* C embedder TRAINING driver: loads a fluid.save'd train program
 * through the trn_* ABI (libpredictor.so), runs N optimizer steps on a
 * deterministic synthetic batch (float32 features + int64 labels), and
 * checkpoints back out — no Python in this translation unit.
 * Usage: c_train_main <model_path> <out_model_path> <steps>
 * Prints "first_loss <f> last_loss <f>"; exits nonzero on any error or
 * if the loss failed to decrease. */

#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>

#include "../paddle_tpu/native/c_api.h"

#define BATCH 16
#define DIM 4

int main(int argc, char** argv) {
  if (argc < 4) {
    fprintf(stderr, "usage: %s model_path out_model_path steps\n",
            argv[0]);
    return 2;
  }
  int steps = atoi(argv[3]);

  int64_t h = trn_create(argv[1]);
  if (!h) {
    fprintf(stderr, "trn_create failed\n");
    return 3;
  }

  /* deterministic batch: x[i][j] ramp, label = j-index of max feature */
  float x[BATCH * DIM];
  int64_t label[BATCH];
  for (int i = 0; i < BATCH; ++i) {
    for (int j = 0; j < DIM; ++j)
      x[i * DIM + j] = (float)((i * 7 + j * 3) % 11) / 11.0f;
    int best = 0;
    for (int j = 1; j < DIM; ++j)
      if (x[i * DIM + j] > x[i * DIM + best]) best = j;
    label[i] = best % 3;
  }

  const char* names[2] = {"x", "label"};
  const void* bufs[2] = {x, label};
  int64_t shapes[4] = {BATCH, DIM, BATCH, 1};
  int64_t ranks[2] = {2, 2};
  int32_t dtypes[2] = {0, 1};

  float first = 0.0f, last = 0.0f;
  for (int s = 0; s < steps; ++s) {
    float out[16];
    int64_t out_shape[8];
    int64_t out_rank = 0;
    int rc = trn_step(h, names, bufs, shapes, ranks, dtypes, 2, "loss",
                      out, 16, out_shape, &out_rank);
    if (rc != 0) {
      fprintf(stderr, "trn_step rc=%d at step %d\n", rc, s);
      return 4;
    }
    if (s == 0) first = out[0];
    last = out[0];
  }
  printf("first_loss %.6f last_loss %.6f\n", first, last);
  if (!(last < first)) {
    fprintf(stderr, "loss did not decrease\n");
    return 5;
  }
  if (trn_save(h, argv[2]) != 0) {
    fprintf(stderr, "trn_save failed\n");
    return 6;
  }
  return trn_destroy(h) == 0 ? 0 : 7;
}
