"""Autoregressive KV-cache decode fast path: ring-buffer cache updates,
cache-aware attention (masked-length fallback + Pallas decode tier), the
traced (prefill, decode) program pair, and the generative Predictor
routing. The load-bearing invariants: greedy decode through the cache is
TOKEN-IDENTICAL to full re-encode, and an N-token generation costs
exactly TWO executor compiles."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import monitor

pytestmark = pytest.mark.decode


# -- PADDLE_TPU_ATTN_FORCE centralization ----------------------------------
def test_attn_force_rejects_unknown_values(monkeypatch):
    from paddle_tpu.kernels import attention

    monkeypatch.setenv("PADDLE_TPU_ATTN_FORCE", "banana")
    with pytest.raises(ValueError, match="banana"):
        attention._attn_force()
    for ok in ("flash", "packed", "decode"):
        monkeypatch.setenv("PADDLE_TPU_ATTN_FORCE", ok)
        assert attention._attn_force() == ok
    monkeypatch.delenv("PADDLE_TPU_ATTN_FORCE")
    assert attention._attn_force() == ""


# -- ring-buffer cache update ----------------------------------------------
def test_kv_cache_update_ring_wraparound():
    from paddle_tpu.kernels.attention import kv_cache_update

    B, H, C, d = 2, 1, 5, 3
    cache = np.zeros((B, H, C, d), np.float32)
    new = np.arange(B * H * d, dtype=np.float32).reshape(B, H, 1, d) + 1
    # slot = len % C: sequence 0 writes slot 0, sequence 1 (len 7) wraps
    # to slot 2
    lens = np.array([0, 7], np.int32)
    out, out_len = kv_cache_update(cache, new, lens)
    out = np.asarray(out)
    assert np.asarray(out_len).tolist() == [1, 8]
    assert (out[0, 0, 0] == new[0, 0, 0]).all()
    assert (out[1, 0, 2] == new[1, 0, 0]).all()
    assert out[0, 0, 1:].sum() == 0 and out[1, 0, 0:2].sum() == 0


def test_cache_attention_masked_slots_are_exactly_dead():
    """fp32-exact masking: garbage in slots beyond cache_len must not
    perturb the output by even one ulp."""
    from paddle_tpu.kernels.attention import attention_with_cache

    rng = np.random.RandomState(0)
    B, H, C, d, n = 2, 2, 8, 4, 5
    q = rng.randn(B, H, 1, d).astype(np.float32)
    k = rng.randn(B, H, C, d).astype(np.float32)
    v = rng.randn(B, H, C, d).astype(np.float32)
    lens = np.full((B,), n, np.int32)
    base = np.asarray(attention_with_cache(q, k, v, lens))
    k2, v2 = k.copy(), v.copy()
    k2[:, :, n:] = 1e9
    v2[:, :, n:] = -1e9
    poisoned = np.asarray(attention_with_cache(q, k2, v2, lens))
    assert (base == poisoned).all()


def test_cache_attention_matches_full_recompute():
    """Feeding tokens one at a time through the ring (including PAST the
    capacity) attends over exactly the last min(len, C) tokens — the
    same probabilities a full recompute over that window produces."""
    from paddle_tpu.kernels.attention import (attention_with_cache,
                                              kv_cache_update)

    rng = np.random.RandomState(1)
    B, H, C, d, steps = 1, 2, 4, 8, 7  # wraps the ring twice
    kc = np.zeros((B, H, C, d), np.float32)
    vc = np.zeros((B, H, C, d), np.float32)
    lens = np.zeros((B,), np.int32)
    ks = rng.randn(steps, B, H, 1, d).astype(np.float32)
    vs = rng.randn(steps, B, H, 1, d).astype(np.float32)
    qs = rng.randn(steps, B, H, 1, d).astype(np.float32)
    for t in range(steps):
        kc, new_len = kv_cache_update(kc, ks[t], lens)
        vc, _ = kv_cache_update(vc, vs[t], lens)
        lens = new_len
        got = np.asarray(attention_with_cache(qs[t], kc, vc, lens))
        lo = max(0, t + 1 - C)
        kw = np.concatenate(list(ks[lo:t + 1]), axis=2)
        vw = np.concatenate(list(vs[lo:t + 1]), axis=2)
        s = np.einsum("bhqd,bhkd->bhqk", qs[t], kw) / np.sqrt(d)
        w = np.exp(s - s.max(-1, keepdims=True))
        w /= w.sum(-1, keepdims=True)
        want = np.einsum("bhqk,bhkd->bhqd", w, vw)
        np.testing.assert_allclose(got, want, rtol=0, atol=1e-5)
    assert np.asarray(lens).tolist() == [steps]


@pytest.mark.parametrize("C", [7, 13, 128, 256])
def test_pallas_decode_kernel_matches_fallback(monkeypatch, C):
    """PADDLE_TPU_ATTN_FORCE=decode + PALLAS_INTERPRET=1 exercises the
    Pallas decode tier on CPU — including prime/odd capacities, which
    take the pad-to-128 path."""
    from paddle_tpu.kernels import attention

    monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
    rng = np.random.RandomState(C)
    B, H, d = 2, 2, 8
    q = rng.randn(B, H, 1, d).astype(np.float32)
    k = rng.randn(B, H, C, d).astype(np.float32)
    v = rng.randn(B, H, C, d).astype(np.float32)
    # one partially-filled sequence, one wrapped past capacity
    lens = np.array([max(1, C // 2), C + 3], np.int32)
    want = np.asarray(attention.attention_with_cache(q, k, v, lens))
    monkeypatch.setenv("PADDLE_TPU_ATTN_FORCE", "decode")
    got = np.asarray(attention.attention_with_cache(q, k, v, lens))
    np.testing.assert_allclose(got, want, rtol=0, atol=1e-5)


# -- traced (prefill, decode) pair ----------------------------------------
def test_greedy_decode_token_identical_and_two_traces():
    """THE acceptance pair: KV-cache greedy decode emits the same tokens
    as full re-encode decode from the same weights, and the whole
    N-token generation costs exactly two executor compiles (one
    prefill, one decode) — zero on a repeat generation."""
    from paddle_tpu.models.transformer import (Transformer,
                                               build_decode_session,
                                               make_causal_bias)

    B, S, P, C, NEW = 2, 6, 4, 16, 6
    with fluid.dygraph.guard():
        np.random.seed(0)
        model = Transformer.tiny()
        model.eval()
        sess = build_decode_session(model, B, S, P, C, end_id=1)

        rng = np.random.RandomState(7)
        src = rng.randint(2, 512, (B, S)).astype(np.int64)
        prompt = rng.randint(2, 512, (B, P)).astype(np.int64)
        plens = np.full((B,), P, np.int64)

        m0 = monitor.counter("executor_compile_cache_miss_total").value
        steps0 = monitor.counter("decode_steps_total").value
        toks, fin = sess.generate(src, prompt, plens, NEW)
        m1 = monitor.counter("executor_compile_cache_miss_total").value
        assert m1 - m0 == 2, "want exactly (prefill, decode) compiles"
        assert monitor.counter("decode_steps_total").value - steps0 \
            == NEW - 1
        assert toks.shape == (B, NEW) and fin.shape == (B,)

        toks2, _ = sess.generate(src, prompt, plens, NEW)
        assert monitor.counter(
            "executor_compile_cache_miss_total").value == m1, \
            "repeat generation retraced"
        assert (toks == toks2).all()

        # full re-encode greedy baseline off the SAME eager weights
        def var(x):
            return fluid.dygraph.to_variable(x)

        cur = prompt.copy()
        base = []
        pos_src = np.tile(np.arange(S, dtype=np.int64), (B, 1))
        for _ in range(NEW):
            T = cur.shape[1]
            pos = np.tile(np.arange(T, dtype=np.int64), (B, 1))
            logits = model(var(src), var(cur), var(pos_src), var(pos),
                           var(make_causal_bias(T)))
            nxt = np.asarray(logits._ivar)[:, -1, :].argmax(-1)
            base.append(nxt)
            cur = np.concatenate([cur, nxt[:, None].astype(np.int64)],
                                 axis=1)
        assert (toks == np.stack(base, axis=1)).all(), (
            toks.tolist(), [b.tolist() for b in base])


def test_decode_session_validates_inputs():
    from paddle_tpu.models.transformer import (Transformer,
                                               build_decode_session)

    with fluid.dygraph.guard():
        model = Transformer.tiny()
        with pytest.raises(ValueError, match="ring boundary"):
            build_decode_session(model, 1, 4, 8, cache_capacity=4)
        sess = build_decode_session(model, 1, 4, 2, cache_capacity=8)
        src = np.zeros((1, 4), np.int64)
        with pytest.raises(ValueError, match="shape mismatch"):
            sess.generate(src, np.zeros((1, 3), np.int64), [2], 2)
        with pytest.raises(ValueError, match="prompt_lens"):
            sess.generate(src, np.zeros((1, 2), np.int64), [3], 2)
        with pytest.raises(ValueError, match="max_new_tokens"):
            sess.generate(src, np.zeros((1, 2), np.int64), [2], 0)


# -- seq2seq encoder hoist --------------------------------------------------
def test_seq2seq_split_infer_bit_identical():
    """The encoder hoisted out of beam search (encoder program once +
    decode-from-state program) reproduces the monolithic infer program
    BIT-identically from the same trained scope."""
    from paddle_tpu.models import seq2seq

    rng = np.random.RandomState(0)
    V, L = 16, 5
    main, startup, loss = seq2seq.build_train_program(
        src_vocab=V, tgt_vocab=V, src_len=L, tgt_len=L, lr=1e-2)
    infer, _, seqs = seq2seq.build_infer_program(
        src_vocab=V, tgt_vocab=V, src_len=L, max_tgt_len=L, beam_size=3)
    enc_p, _, enc_state = seq2seq.build_encoder_program(
        src_vocab=V, src_len=L)
    dec_p, _, seqs2 = seq2seq.build_decode_program(
        tgt_vocab=V, max_tgt_len=L, beam_size=3)
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(20):
            feed = seq2seq.synthetic_pairs(rng, 16, V, L)
            exe.run(main, feed=feed, fetch_list=[loss])
        feed = seq2seq.synthetic_pairs(rng, 4, V, L)
        (sv,) = exe.run(infer, feed={"s2s_src": feed["s2s_src"]},
                        fetch_list=[seqs])
        sv2 = seq2seq.run_split_infer(exe, scope, enc_p, enc_state,
                                      dec_p, seqs2, feed["s2s_src"])
    assert (np.asarray(sv) == np.asarray(sv2)).all()


# -- generative Predictor routing ------------------------------------------
def test_generative_predictor_no_shape_recompiles():
    """Growing output length through the plain Predictor re-feeds a
    longer sequence every call (a recompile per length). The decode
    routing is shape-closed: one prefill + one decode compile serve
    every max_new_tokens, and predictor_shape_recompile_total stays 0."""
    from paddle_tpu import inference
    from paddle_tpu.models.transformer import Transformer

    with fluid.dygraph.guard():
        model = Transformer.tiny()
        p = inference.GenerativePredictor(
            model, batch_size=1, src_len=6, prompt_len=4,
            cache_capacity=32, end_id=1)
    rng = np.random.RandomState(3)
    feed = {"src": rng.randint(2, 512, (1, 6)).astype(np.int64),
            "prompt": rng.randint(2, 512, (1, 4)).astype(np.int64)}
    rec0 = monitor.counter("predictor_shape_recompile_total").value
    m0 = monitor.counter("executor_compile_cache_miss_total").value
    outs = [p.run(feed, max_new_tokens=n)[0] for n in (2, 5, 9)]
    m1 = monitor.counter("executor_compile_cache_miss_total").value
    assert m1 - m0 == 2, (
        "generative serving cost %d compiles for 3 growing-length "
        "requests, want 2 (one prefill + one decode)" % (m1 - m0))
    assert monitor.counter(
        "predictor_shape_recompile_total").value == rec0
    assert [o.shape for o in outs] == [(1, 2), (1, 5), (1, 9)]
    # growing max_new_tokens extends, never rewrites, the trajectory
    assert (outs[2][:, :5] == outs[1]).all()
    assert (outs[1][:, :2] == outs[0]).all()
    assert p.get_input_names() == ["src", "prompt", "prompt_lens"]
    with pytest.raises(ValueError, match="missing generative feeds"):
        p.run({"src": feed["src"]}, max_new_tokens=2)
