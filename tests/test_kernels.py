"""Pallas kernels (kernels/attention.py) — run through the pallas
interpreter on CPU so the real kernel bodies execute in CI; numerics are
checked against the jnp reference path and fp64 truth."""

import os

import numpy as np
import pytest

os.environ.setdefault("PADDLE_TPU_PALLAS_INTERPRET", "1")

import jax
import jax.numpy as jnp

from paddle_tpu.kernels.attention import (_ref_attention, _supports_pallas,
                                          fused_attention)

RNG = np.random.RandomState(3)
B, H, S, D = 2, 3, 16, 8
SCALE = 1.0 / np.sqrt(D)
Q = (RNG.randn(B, H, S, D) * 0.5).astype(np.float32)
K = (RNG.randn(B, H, S, D) * 0.5).astype(np.float32)
V = (RNG.randn(B, H, S, D) * 0.5).astype(np.float32)
BIAS = np.zeros((B, 1, 1, S), np.float32)
BIAS[0, 0, 0, -4:] = -1e4
Z = np.zeros(1, np.int32)


def _f64_attention(q, k, v, bias):
    s = np.einsum("bhqd,bhkd->bhqk", q.astype(np.float64),
                  k.astype(np.float64)) * SCALE
    s = s + bias.astype(np.float64)
    e = np.exp(s - s.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    return np.einsum("bhqk,bhkd->bhqd", p, v.astype(np.float64))


def test_interpret_mode_active():
    assert _supports_pallas(), "interpret mode should force the kernel path"


def test_forward_matches_fp64():
    out = fused_attention(jnp.asarray(Q), jnp.asarray(K), jnp.asarray(V),
                          jnp.asarray(BIAS))
    ref = _f64_attention(Q, K, V, BIAS)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-5)


def test_forward_mask_zeroes_attention():
    """Masked key columns must receive zero attention weight: make the
    masked V rows huge; the output must not move."""
    v2 = V.copy()
    v2[0, :, -4:, :] = 1e6
    out1 = fused_attention(jnp.asarray(Q), jnp.asarray(K), jnp.asarray(V),
                           jnp.asarray(BIAS))
    out2 = fused_attention(jnp.asarray(Q), jnp.asarray(K), jnp.asarray(v2),
                           jnp.asarray(BIAS))
    np.testing.assert_allclose(np.asarray(out1)[0], np.asarray(out2)[0],
                               rtol=1e-5)


def test_gradients_match_fp64():
    """The hand-written backward kernel against fp64 finite truth (the
    jnp autodiff path itself carries ~1e-2 fp32 noise here, so fp64 is
    the only fair oracle)."""
    def f64_loss_grads():
        q = Q.astype(np.float64)
        k = K.astype(np.float64)
        v = V.astype(np.float64)
        s = np.einsum("bhqd,bhkd->bhqk", q, k) * SCALE + BIAS
        e = np.exp(s - s.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        o = np.einsum("bhqk,bhkd->bhqd", p, v)
        do = 2 * o
        dv = np.einsum("bhqk,bhqd->bhkd", p, do)
        dp = np.einsum("bhqd,bhkd->bhqk", do, v)
        ds = p * (dp - (dp * p).sum(-1, keepdims=True))
        dq = np.einsum("bhqk,bhkd->bhqd", ds, k) * SCALE
        dk = np.einsum("bhqk,bhqd->bhkd", ds, q) * SCALE
        return dq, dk, dv

    def loss(q, k, v):
        return jnp.sum(fused_attention(q, k, v, jnp.asarray(BIAS)) ** 2)

    got = jax.grad(loss, argnums=(0, 1, 2))(
        jnp.asarray(Q), jnp.asarray(K), jnp.asarray(V))
    want = f64_loss_grads()
    for g, w, name in zip(got, want, "qkv"):
        scale = max(np.abs(w).max(), 1e-9)
        err = np.abs(np.asarray(g) - w).max() / scale
        assert err < 5e-3, (name, err)


def test_dropout_statistics_and_determinism():
    key = jax.random.PRNGKey(11)
    out1 = fused_attention(jnp.asarray(Q), jnp.asarray(K), jnp.asarray(V),
                           jnp.asarray(BIAS), dropout_prob=0.5, rng_key=key)
    out2 = fused_attention(jnp.asarray(Q), jnp.asarray(K), jnp.asarray(V),
                           jnp.asarray(BIAS), dropout_prob=0.5, rng_key=key)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2))  # same key
    out3 = fused_attention(jnp.asarray(Q), jnp.asarray(K), jnp.asarray(V),
                           jnp.asarray(BIAS), dropout_prob=0.5,
                           rng_key=jax.random.PRNGKey(12))
    assert np.abs(np.asarray(out1) - np.asarray(out3)).max() > 1e-4
    # dropout keeps the output mean roughly unbiased (upscale_in_train)
    base = fused_attention(jnp.asarray(Q), jnp.asarray(K), jnp.asarray(V),
                           jnp.asarray(BIAS))
    outs = [np.asarray(fused_attention(
        jnp.asarray(Q), jnp.asarray(K), jnp.asarray(V), jnp.asarray(BIAS),
        dropout_prob=0.5, rng_key=jax.random.PRNGKey(s)))
        for s in range(24)]
    mean = np.mean(outs, axis=0)
    denom = np.abs(np.asarray(base)).mean() + 1e-6
    assert np.abs(mean - np.asarray(base)).mean() / denom < 0.35


def test_dropout_gradient_uses_same_mask():
    """grad through the dropped forward: zeroed probability cells must
    contribute zero gradient; check grads are finite and nonzero."""
    key = jax.random.PRNGKey(5)

    def loss(q):
        return jnp.sum(fused_attention(q, jnp.asarray(K), jnp.asarray(V),
                                       jnp.asarray(BIAS), dropout_prob=0.3,
                                       rng_key=key) ** 2)

    g = np.asarray(jax.grad(loss)(jnp.asarray(Q)))
    assert np.isfinite(g).all() and np.abs(g).max() > 0


def test_fluid_layer_path():
    """layers.fused_attention drives the op through a Program, grads flow
    into q/k/v producers."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [S, D * H], append_batch_size=False)
        qkv = layers.fc(x, 3 * H * D, name="qkv")
        q = layers.reshape(layers.slice(qkv, [1], [0], [H * D]),
                           [1, S, H, D])
        kk = layers.reshape(layers.slice(qkv, [1], [H * D], [2 * H * D]),
                            [1, S, H, D])
        vv = layers.reshape(layers.slice(qkv, [1], [2 * H * D],
                                         [3 * H * D]), [1, S, H, D])
        q = layers.transpose(q, [0, 2, 1, 3])
        kk = layers.transpose(kk, [0, 2, 1, 3])
        vv = layers.transpose(vv, [0, 2, 1, 3])
        out = layers.fused_attention(q, kk, vv)
        loss = layers.reduce_mean(layers.square(out))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    assert any(op.type == "fused_multihead_attention"
               for op in main.global_block().ops)
    exe = fluid.Executor()
    feed = {"x": RNG.rand(S, D * H).astype(np.float32)}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        vals = [float(np.asarray(exe.run(main, feed=feed,
                                         fetch_list=[loss])[0]))
                for _ in range(6)]
    assert vals[-1] < vals[0]  # minimizing the mean square moves weights


def test_bias_gradient_reduced_in_kernel():
    """dbias comes back already reduced to the broadcast [B,1,1,S] shape
    and matches the jnp-autodiff reference."""
    def loss_fused(b):
        return jnp.sum(fused_attention(jnp.asarray(Q), jnp.asarray(K),
                                       jnp.asarray(V), b) ** 2)

    def loss_ref(b):
        return jnp.sum(_ref_attention(jnp.asarray(Q), jnp.asarray(K),
                                      jnp.asarray(V), b, SCALE, 0.0,
                                      Z) ** 2)

    g1 = np.asarray(jax.grad(loss_fused)(jnp.asarray(BIAS)))
    g2 = np.asarray(jax.grad(loss_ref)(jnp.asarray(BIAS)))
    assert g1.shape == BIAS.shape
    denom = max(np.abs(g2).max(), 1e-9)
    assert np.abs(g1 - g2).max() / denom < 5e-3


def test_blockwise_attention_matches_reference():
    """Long-seq fallback (online softmax over K blocks) must match the
    one-pass reference numerically, fwd and grad."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.kernels import attention as A

    rng = np.random.RandomState(0)
    B, H, S, d = 2, 3, 256, 8
    q = jnp.asarray(rng.randn(B, H, S, d).astype(np.float32) * 0.3)
    k = jnp.asarray(rng.randn(B, H, S, d).astype(np.float32) * 0.3)
    v = jnp.asarray(rng.randn(B, H, S, d).astype(np.float32) * 0.3)
    bias = jnp.asarray(
        np.where(rng.rand(B, 1, 1, S) < 0.2, -1e4, 0.0).astype(np.float32))
    seed = jnp.zeros((1,), jnp.int32)
    scale = d ** -0.5

    out_blk = A._blockwise_attention(q, k, v, bias, scale, 0.0, seed)
    out_ref = A._ref_attention(q, k, v, bias, scale, 0.0, seed)
    np.testing.assert_allclose(np.asarray(out_blk), np.asarray(out_ref),
                               rtol=2e-5, atol=2e-6)

    def loss_blk(q_, k_, v_):
        return A._blockwise_attention(q_, k_, v_, bias, scale, 0.0,
                                      seed).sum()

    def loss_ref(q_, k_, v_):
        return A._ref_attention(q_, k_, v_, bias, scale, 0.0, seed).sum()

    g_blk = jax.grad(loss_blk, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_blk, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-5, atol=3e-6)


def test_fallback_dispatches_blockwise_past_vmem_bound(monkeypatch):
    from paddle_tpu.kernels import attention as A

    calls = []
    real = A._blockwise_attention
    monkeypatch.setattr(A, "_blockwise_attention",
                        lambda *a, **k: calls.append(1) or real(*a, **k))
    monkeypatch.setattr(A, "_MAX_FUSED_SEQ", 64)
    rng = np.random.RandomState(1)
    import jax.numpy as jnp

    q = jnp.asarray(rng.randn(1, 2, 128, 8).astype(np.float32))
    bias = jnp.zeros((1, 1, 1, 128), jnp.float32)
    seed = jnp.zeros((1,), jnp.int32)
    A._fallback_attention(q, q, q, bias, 0.35, 0.0, seed)
    assert calls, "blockwise path not taken past the bound"


def test_blockwise_dropout_normalizes_like_one_pass():
    """Denominator uses undropped weights: E[out] ~ one-pass output."""
    import jax.numpy as jnp

    from paddle_tpu.kernels import attention as A

    rng = np.random.RandomState(2)
    B, H, S, d = 1, 2, 128, 4
    q = jnp.asarray(rng.randn(B, H, S, d).astype(np.float32) * 0.2)
    bias = jnp.zeros((B, 1, 1, S), jnp.float32)
    outs = []
    for s in range(8):
        seed = jnp.asarray([s], jnp.int32)
        outs.append(np.asarray(A._blockwise_attention(
            q, q, q, bias, 0.5, 0.3, seed)))
    mean = np.mean(outs, axis=0)
    ref = np.asarray(A._ref_attention(q, q, q, bias, 0.5, 0.0,
                                      jnp.zeros((1,), jnp.int32)))
    np.testing.assert_allclose(mean, ref, rtol=0.35, atol=0.05)


def test_blockwise_attention_prime_seq_pads():
    import jax.numpy as jnp

    from paddle_tpu.kernels import attention as A

    rng = np.random.RandomState(3)
    B, H, S, d = 1, 2, 131, 4  # prime S: must pad, not degrade to block=1
    q = jnp.asarray(rng.randn(B, H, S, d).astype(np.float32) * 0.3)
    k = jnp.asarray(rng.randn(B, H, S, d).astype(np.float32) * 0.3)
    v = jnp.asarray(rng.randn(B, H, S, d).astype(np.float32) * 0.3)
    bias = jnp.zeros((B, 1, 1, S), jnp.float32)
    seed = jnp.zeros((1,), jnp.int32)
    out = A._blockwise_attention(q, k, v, bias, 0.5, 0.0, seed)
    ref = A._ref_attention(q, k, v, bias, 0.5, 0.0, seed)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


class TestLongKernel:
    """Q-tiled long-seq kernels: fwd + custom-vjp bwd vs the reference,
    exercised through the interpreter with _MAX_FUSED_SEQ patched below S
    so the long path engages (S % _QB_LONG == 0)."""

    def _setup(self, monkeypatch, bias_shape):
        from paddle_tpu.kernels import attention as A

        monkeypatch.setattr(A, "_MAX_FUSED_SEQ", 128)
        rng = np.random.RandomState(7)
        b, h, s, d = 1, 2, 256, 8
        q = jnp.asarray((rng.randn(b, h, s, d) * 0.4).astype(np.float32))
        k = jnp.asarray((rng.randn(b, h, s, d) * 0.4).astype(np.float32))
        v = jnp.asarray((rng.randn(b, h, s, d) * 0.4).astype(np.float32))
        bias = np.zeros(bias_shape, np.float32)
        bias[..., -5:] = -1e4
        return A, q, k, v, jnp.asarray(bias), 1.0 / np.sqrt(d)

    def test_long_path_taken(self, monkeypatch):
        A, q, k, v, bias, scale = self._setup(monkeypatch, (1, 1, 1, 256))
        assert A._use_long_kernel(q, 0.0, bias)
        assert not A._use_kernel(q, 0.0)

    def test_head_broadcast_per_row_bias_takes_blockwise(self, monkeypatch):
        # [B,1,S,S] bias with H>1: dbias would need non-consecutive
        # revisit accumulation — must decline the long kernel
        A, q, k, v, bias, scale = self._setup(monkeypatch, (1, 1, 256, 256))
        assert not A._use_long_kernel(q, 0.0, bias)
        seed = jnp.zeros((1,), jnp.int32)
        out = A._fused(q, k, v, bias, scale, 0.0, seed)
        ref = A._ref_attention(q, k, v, bias, scale, 0.0, seed)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize("bias_shape", [(1, 1, 1, 256), (1, 2, 256, 256)])
    def test_forward_matches_reference(self, monkeypatch, bias_shape):
        A, q, k, v, bias, scale = self._setup(monkeypatch, bias_shape)
        seed = jnp.zeros((1,), jnp.int32)
        out = A._pallas_attention_long(q, k, v, bias, scale, 0.0, seed)
        ref = A._ref_attention(q, k, v, bias, scale, 0.0, seed)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize("bias_shape", [(1, 1, 1, 256), (1, 2, 256, 256)])
    def test_grads_match_reference(self, monkeypatch, bias_shape):
        A, q, k, v, bias, scale = self._setup(monkeypatch, bias_shape)
        seed = jnp.zeros((1,), jnp.int32)

        def loss_fused(q_, k_, v_, b_):
            return (A._fused(q_, k_, v_, b_, scale, 0.0, seed) ** 2).sum()

        def loss_ref(q_, k_, v_, b_):
            return (A._ref_attention(q_, k_, v_, b_, scale, 0.0,
                                     seed) ** 2).sum()

        g_f = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(q, k, v, bias)
        g_r = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(q, k, v, bias)
        for a, b in zip(g_f, g_r):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=3e-4, atol=3e-5)


@pytest.mark.skipif(not _supports_pallas(), reason="no pallas")
class TestFlashKernels:
    """Flash tier (_flash_* kernels): online-softmax forward + split
    dq / dk·dv backward pair, exercised through the interpreter with the
    lower tiers patched off and a small tile edge so multi-tile online
    accumulation runs (S=256 at Tb=64 -> 4x4 tiles)."""

    def _setup(self, monkeypatch, bias_shape):
        from paddle_tpu.kernels import attention as A

        monkeypatch.setattr(A, "_MAX_FUSED_SEQ", 64)
        monkeypatch.setattr(A, "_MAX_LONG_SEQ", 0)
        monkeypatch.setattr(A, "_FLASH_BLOCK_CANDIDATES", (64,))
        rng = np.random.RandomState(11)
        b, h, s, d = 1, 2, 256, 8
        q = jnp.asarray((rng.randn(b, h, s, d) * 0.4).astype(np.float32))
        k = jnp.asarray((rng.randn(b, h, s, d) * 0.4).astype(np.float32))
        v = jnp.asarray((rng.randn(b, h, s, d) * 0.4).astype(np.float32))
        bias = np.zeros(bias_shape, np.float32)
        bias[..., -7:] = -1e4
        return A, q, k, v, jnp.asarray(bias), 1.0 / np.sqrt(d)

    def test_flash_path_taken(self, monkeypatch):
        A, q, k, v, bias, scale = self._setup(monkeypatch, (1, 1, 1, 256))
        assert A._use_flash_kernel(q, 0.0, bias)
        assert not A._use_kernel(q, 0.0)
        assert not A._use_long_kernel(q, 0.0, bias)

    def test_per_row_bias_declines(self, monkeypatch):
        # per-row bias would need [B,H,S,S] dbias partials — blockwise
        # path takes it and still matches the reference
        A, q, k, v, bias, scale = self._setup(monkeypatch, (1, 1, 256, 256))
        assert not A._use_flash_kernel(q, 0.0, bias)
        seed = jnp.zeros((1,), jnp.int32)
        out = A._fused(q, k, v, bias, scale, 0.0, seed)
        ref = A._ref_attention(q, k, v, bias, scale, 0.0, seed)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_indivisible_seq_declines(self, monkeypatch):
        A, _, _, _, _, _ = self._setup(monkeypatch, (1, 1, 1, 256))
        assert A._flash_block(250) is None

    @pytest.mark.parametrize("bias_shape", [(1, 1, 1, 256), (1, 2, 1, 256)])
    def test_forward_matches_reference(self, monkeypatch, bias_shape):
        A, q, k, v, bias, scale = self._setup(monkeypatch, bias_shape)
        seed = jnp.zeros((1,), jnp.int32)
        out, lse = A._pallas_attention_flash(q, k, v, bias, scale, 0.0,
                                             seed)
        ref = A._ref_attention(q, k, v, bias, scale, 0.0, seed)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)
        # the saved logsumexp must be the true row logsumexp
        s = np.einsum("bhqd,bhkd->bhqk", np.asarray(q),
                      np.asarray(k)) * scale
        s = s + np.broadcast_to(np.asarray(bias),
                                (1, bias.shape[1], 1, 256))
        ref_lse = np.log(np.exp(s - s.max(-1, keepdims=True))
                         .sum(-1)) + s.max(-1)
        got_lse = np.asarray(lse)[..., 0]
        if bias.shape[1] == 1:
            ref_lse = np.broadcast_to(ref_lse, got_lse.shape)
        np.testing.assert_allclose(got_lse, ref_lse, rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("bias_shape", [(1, 1, 1, 256), (1, 2, 1, 256)])
    def test_grads_match_reference(self, monkeypatch, bias_shape):
        A, q, k, v, bias, scale = self._setup(monkeypatch, bias_shape)
        seed = jnp.zeros((1,), jnp.int32)

        def loss_fused(q_, k_, v_, b_):
            return (A._fused(q_, k_, v_, b_, scale, 0.0, seed) ** 2).sum()

        def loss_ref(q_, k_, v_, b_):
            return (A._ref_attention(q_, k_, v_, b_, scale, 0.0,
                                     seed) ** 2).sum()

        g_f = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(q, k, v, bias)
        g_r = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(q, k, v, bias)
        for a, b in zip(g_f, g_r):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=3e-4, atol=3e-5)

    def test_grads_match_blockwise_production_tile_picker(self, monkeypatch):
        """Same check against the blockwise oracle with the production
        tile picker (Tb=128 via candidates) and uneven value scales."""
        from paddle_tpu.kernels import attention as A

        monkeypatch.setattr(A, "_MAX_FUSED_SEQ", 64)
        monkeypatch.setattr(A, "_MAX_LONG_SEQ", 0)
        rng = np.random.RandomState(5)
        b, h, s, d = 1, 1, 384, 8
        q = jnp.asarray((rng.randn(b, h, s, d)).astype(np.float32))
        k = jnp.asarray((rng.randn(b, h, s, d)).astype(np.float32))
        v = jnp.asarray((rng.randn(b, h, s, d) * 2.0).astype(np.float32))
        bias = np.zeros((b, 1, 1, s), np.float32)
        bias[..., :11] = -1e4
        bias = jnp.asarray(bias)
        assert A._flash_block(s) == 128
        seed = jnp.zeros((1,), jnp.int32)
        scale = 1.0 / np.sqrt(d)

        def loss_fused(q_, k_, v_, b_):
            return (A._fused(q_, k_, v_, b_, scale, 0.0, seed) ** 2).sum()

        def loss_blk(q_, k_, v_, b_):
            return (A._blockwise_attention(q_, k_, v_, b_, scale, 0.0,
                                           seed) ** 2).sum()

        g_f = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(q, k, v, bias)
        g_b = jax.grad(loss_blk, argnums=(0, 1, 2, 3))(q, k, v, bias)
        for a, b_ in zip(g_f, g_b):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=3e-4, atol=3e-5)


@pytest.mark.skipif(not _supports_pallas(), reason="no pallas")
class TestPackedKernels:
    """Packed-layout tier (fused_attention_packed): [B, S, H*d] q/k/v,
    heads split/merged inside the kernel (interpret mode runs the real
    body; off-TPU the wrapper falls back through the per-head dispatch)."""

    def _setup(self, bias_shape):
        from paddle_tpu.kernels import attention as A

        rng = np.random.RandomState(13)
        b, s, h, d = 4, 64, 3, 8
        hd = h * d
        q = jnp.asarray((rng.randn(b, s, hd) * 0.4).astype(np.float32))
        k = jnp.asarray((rng.randn(b, s, hd) * 0.4).astype(np.float32))
        v = jnp.asarray((rng.randn(b, s, hd) * 0.4).astype(np.float32))
        bias = np.zeros(bias_shape, np.float32)
        bias[..., -5:] = -1e4
        return A, q, k, v, jnp.asarray(bias), h, d

    def _ref(self, A, q, k, v, bias, h, d):
        B, S, HD = q.shape

        def split(t):
            return jnp.transpose(t.reshape(B, S, h, d), (0, 2, 1, 3))

        o = A._ref_attention(split(q), split(k), split(v), bias,
                             1.0 / np.sqrt(d), 0.0,
                             jnp.zeros((1,), jnp.int32))
        return jnp.transpose(o, (0, 2, 1, 3)).reshape(B, S, HD)

    @pytest.mark.parametrize("bias_shape", [(4, 1, 1, 64), (4, 3, 1, 64)])
    def test_forward_and_grads_match_reference(self, bias_shape):
        A, q, k, v, bias, h, d = self._setup(bias_shape)
        assert A._use_packed_kernel(q, h, 0.0, bias)
        out = A.fused_attention_packed(q, k, v, bias, n_heads=h)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(self._ref(A, q, k, v, bias, h, d)),
            rtol=2e-4, atol=2e-5)

        gp = jax.grad(lambda *a: (A.fused_attention_packed(
            *a, n_heads=h) ** 2).sum(), argnums=(0, 1, 2, 3))(q, k, v, bias)
        gr = jax.grad(lambda *a: (self._ref(A, *a, h, d) ** 2).sum(),
                      argnums=(0, 1, 2, 3))(q, k, v, bias)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=3e-4, atol=1e-4)

    def test_layer_through_program(self):
        """fused_multihead_attention_packed drives through a Program and
        its grads flow (packed layout end to end, no transposes)."""
        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid import layers

        b, s, h, d = 2, 32, 2, 8
        rng = np.random.RandomState(5)
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            q = layers.data("q", shape=[b, s, h * d], dtype="float32")
            w = layers.create_parameter(
                [h * d], "float32",
                default_initializer=fluid.initializer.Constant(1.0))
            out = layers.fused_attention_packed(q, q, q * w, h)
            loss = layers.reduce_mean(out * out)
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        assert any(op.type == "fused_multihead_attention_packed"
                   for op in main.blocks[0].ops)
        exe = fluid.Executor()
        feed = {"q": rng.randn(b, s, h * d).astype(np.float32)}
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            (l0,) = exe.run(main, feed=feed, fetch_list=[loss])
            assert np.isfinite(np.asarray(l0)).all()


@pytest.mark.skipif(not _supports_pallas(), reason="no pallas")
class TestResidentKernels:
    """Resident tier: fc-native [B, S, H*d] operands, head-PAIR grid
    (128-lane-aligned dynamic slices, static half splits in VMEM).
    Gate needs even H and 2d % 128 == 0."""

    def _setup(self, bias_shape):
        from paddle_tpu.kernels import attention as A

        rng = np.random.RandomState(29)
        b, s, h, d = 4, 64, 4, 64
        hd = h * d
        mk = lambda: jnp.asarray((rng.randn(b, s, hd) * 0.4)
                                 .astype(np.float32))
        bias = np.zeros(bias_shape, np.float32)
        bias[..., -5:] = -1e4
        return A, mk(), mk(), mk(), jnp.asarray(bias), h, d

    def _ref(self, A, q, k, v, bias, h, d):
        B, S, HD = q.shape

        def split(t):
            return jnp.transpose(t.reshape(B, S, h, d), (0, 2, 1, 3))

        o = A._ref_attention(split(q), split(k), split(v), bias,
                             1.0 / np.sqrt(d), 0.0,
                             jnp.zeros((1,), jnp.int32))
        return jnp.transpose(o, (0, 2, 1, 3)).reshape(B, S, HD)

    @pytest.mark.parametrize("bias_shape", [(4, 1, 1, 64), (4, 4, 1, 64)])
    def test_matches_reference(self, bias_shape):
        A, q, k, v, bias, h, d = self._setup(bias_shape)
        assert A._use_res_kernel(q, h, 0.0, bias)
        out = A.fused_attention_packed(q, k, v, bias, n_heads=h)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(self._ref(A, q, k, v, bias, h, d)),
            rtol=2e-4, atol=2e-5)
        gp = jax.grad(lambda *a: (A.fused_attention_packed(
            *a, n_heads=h) ** 2).sum(), argnums=(0, 1, 2, 3))(q, k, v, bias)
        gr = jax.grad(lambda *a: (self._ref(A, *a, h, d) ** 2).sum(),
                      argnums=(0, 1, 2, 3))(q, k, v, bias)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=3e-4, atol=1e-4)
