"""Elastic, preemption-aware training: the SIGTERM drain path
(distributed/preemption), shrink-to-survivors gang reformation
(distributed/rendezvous + launch), the hung-step deadline watchdog
(distributed/heartbeat), and the checkpoint machinery underneath them
(rotation guard, latest-fallback, reshard-on-restore)."""

import json
import os
import re
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import paddle_tpu.fluid as fluid  # noqa: E402
from paddle_tpu.fluid import faults, layers, monitor, optimizer  # noqa: E402
from paddle_tpu.fluid.resilience import RestartBackoff  # noqa: E402
from paddle_tpu.distributed import preemption, rendezvous  # noqa: E402
from paddle_tpu.distributed.env import trainer_env  # noqa: E402
from paddle_tpu.distributed.heartbeat import Watchdog  # noqa: E402
from paddle_tpu.distributed.launch import launch  # noqa: E402
from paddle_tpu.distributed.rendezvous import (  # noqa: E402
    Rendezvous, plan_next_world)

HERE = os.path.dirname(os.path.abspath(__file__))
RUNNER = os.path.join(HERE, "dist_runner_elastic.py")


@pytest.fixture(autouse=True)
def _clean_elastic_state(monkeypatch):
    faults.reset()
    preemption.reset()
    for k in ("PADDLE_RESTART_ATTEMPT", "PADDLE_HEARTBEAT_DIR",
              "PADDLE_CHECKPOINT_DIR", "PADDLE_RENDEZVOUS_DIR",
              "PADDLE_COORD_ADDR", "PADDLE_COORD_BACKEND",
              "PADDLE_TRAINER_ID", "PADDLE_TRAINERS_NUM",
              preemption.ENV_DRAIN, faults.ENV):
        monkeypatch.delenv(k, raising=False)
    yield
    faults.reset()
    preemption.reset()


def _build(seed=7):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[6], dtype="float32")
        y = layers.data("y", shape=[1], dtype="float32")
        h = layers.fc(x, size=8, act="relu")
        pred = layers.fc(h, size=1)
        loss = layers.reduce_mean(layers.square(pred - y))
        optimizer.SGD(0.05).minimize(loss)
    return main, startup, loss


def _feed(step=0, batch=8):
    rs = np.random.RandomState(100 + step)
    return {"x": rs.rand(batch, 6).astype(np.float32),
            "y": rs.rand(batch, 1).astype(np.float32)}


# -- plan_next_world (the pure sizing decision) -----------------------------

def test_plan_next_world_shrinks_to_survivors():
    assert plan_next_world(3, {2}, 3) == 2
    assert plan_next_world(4, {1, 3}, 4) == 2
    assert plan_next_world(2, {0, 1}, 4) == 1  # never below 1


def test_plan_next_world_honors_floor_and_cap():
    assert plan_next_world(3, {2}, 3, min_world=3) == 3
    assert plan_next_world(2, {1}, 4, returned=5) == 4  # capped at orig
    assert plan_next_world(3, set(), 3, returned=2) == 3


def test_plan_next_world_ignores_out_of_range_slots():
    assert plan_next_world(2, {9, -1}, 4) == 2


# -- rendezvous dir ---------------------------------------------------------

def test_rendezvous_world_and_slot_roundtrip(tmp_path):
    rdzv = Rendezvous(str(tmp_path))
    rdzv.record_world(3, generation=5)
    w = rdzv.world()
    assert w["world_size"] == 3 and w["generation"] == 5
    assert w["slots"] == [0, 1, 2]
    assert rdzv.generation() == 5

    rdzv.offer_slot(2)
    rdzv.offer_slot(1)
    assert rdzv.returned_slots() == [1, 2]
    assert rdzv.consume_slots() == [1, 2]
    assert rdzv.returned_slots() == []

    rdzv.announce(rank=1, step=9)
    assert rdzv.members()[1]["step"] == 9
    rdzv.clear_members()
    assert rdzv.members() == {}


def test_rendezvous_requires_a_directory():
    with pytest.raises(ValueError):
        Rendezvous()


def test_rendezvous_tolerates_garbage_files(tmp_path):
    rdzv = Rendezvous(str(tmp_path))
    (tmp_path / "world.json").write_text("{torn")
    (tmp_path / "slot.bogus").write_text("x")
    (tmp_path / "member.3").write_text("not json")
    assert rdzv.world() is None and rdzv.generation() == 0
    assert rdzv.returned_slots() == []
    assert rdzv.members() == {}


# -- preemption drain -------------------------------------------------------

def test_request_drain_sets_flag_once():
    assert not preemption.draining()
    preemption.request_drain("evict-notice")
    assert preemption.draining()
    assert preemption.drain_reason() == "evict-notice"
    preemption.request_drain("second")  # first reason wins
    assert preemption.drain_reason() == "evict-notice"
    preemption.reset()
    assert not preemption.draining()


def test_maybe_install_from_env_is_memoized(monkeypatch):
    monkeypatch.setenv(preemption.ENV_DRAIN, "0")
    assert preemption.maybe_install_from_env() is False
    monkeypatch.setenv(preemption.ENV_DRAIN, "1")
    assert preemption.maybe_install_from_env() is False  # answer cached
    preemption.reset()  # forgets the env check
    assert preemption.maybe_install_from_env() is True
    assert preemption.installed()


def test_check_drain_noop_until_flagged_then_exits_zero(tmp_path,
                                                        monkeypatch):
    monkeypatch.setenv("PADDLE_HEARTBEAT_DIR", str(tmp_path))
    monkeypatch.setenv("PADDLE_TRAINER_ID", "2")
    preemption.check_drain()  # not draining: no-op
    preemption.request_drain("test")
    with pytest.raises(SystemExit) as e:
        preemption.check_drain()
    assert e.value.code == 0
    marker = preemption.preempt_marker_path(str(tmp_path), 2)
    with open(marker) as f:
        assert json.load(f)["reason"] == "test"


def test_executor_run_drains_between_steps(tmp_path, monkeypatch):
    """The acceptance path in-process: a drain request arriving between
    steps makes the NEXT Executor.run force-checkpoint, write the
    marker, and exit 0 — the in-flight step is never torn."""
    monkeypatch.setenv("PADDLE_HEARTBEAT_DIR", str(tmp_path))
    main_p, startup, loss = _build()
    exe = fluid.Executor()
    exe.run(startup)
    mgr = fluid.io.CheckpointManager(str(tmp_path / "ckpt"))
    exe.run(main_p, feed=_feed(0), fetch_list=[loss],
            checkpoint=(mgr, 1))
    assert mgr.latest() == 1
    preemption.request_drain("test-evict")
    with pytest.raises(SystemExit) as e:
        exe.run(main_p, feed=_feed(1), fetch_list=[loss],
                checkpoint=(mgr, 1))
    assert e.value.code == 0
    assert os.path.exists(preemption.preempt_marker_path(str(tmp_path), 0))
    assert mgr.latest() == 1  # force-saved (re-saved step 1), intact


def test_batched_run_drains_between_windows(tmp_path, monkeypatch):
    """Same contract under iters=k: the drain check also guards the
    step-batched window path."""
    monkeypatch.setenv("PADDLE_HEARTBEAT_DIR", str(tmp_path))
    main_p, startup, loss = _build()
    exe = fluid.Executor()
    exe.run(startup)
    mgr = fluid.io.CheckpointManager(str(tmp_path / "ckpt"))
    feed = {"x": np.stack([_feed(s)["x"] for s in range(2)]),
            "y": np.stack([_feed(s)["y"] for s in range(2)])}
    exe.run(main_p, feed=feed, fetch_list=[loss], iters=2,
            checkpoint=(mgr, 2))
    assert mgr.latest() == 2
    preemption.request_drain("test-evict")
    with pytest.raises(SystemExit) as e:
        exe.run(main_p, feed=feed, fetch_list=[loss], iters=2,
                checkpoint=(mgr, 2))
    assert e.value.code == 0
    assert os.path.exists(preemption.preempt_marker_path(str(tmp_path), 0))


# -- hung-step watchdog -----------------------------------------------------

def _stamp(dirname, rank, step):
    with open(os.path.join(str(dirname), "hb.%d" % rank), "w") as f:
        json.dump({"ts": time.time(), "step": step, "pid": 1}, f)


def test_watchdog_flags_fresh_heartbeat_frozen_step(tmp_path):
    wd = Watchdog(str(tmp_path), nproc=1, timeout=None,
                  step_deadline=0.05)
    _stamp(tmp_path, 0, 3)
    assert wd.hung_workers() == []  # first sighting only starts the clock
    time.sleep(0.1)
    _stamp(tmp_path, 0, 3)  # stamp fresh, step frozen past the deadline
    before = monitor.counter("watchdog_hung_steps_total").value
    assert wd.hung_workers() == [0]
    assert monitor.counter("watchdog_hung_steps_total").value > before
    _stamp(tmp_path, 0, 4)  # progress clears the flag
    assert wd.hung_workers() == []


def test_watchdog_stale_is_not_hung(tmp_path):
    wd = Watchdog(str(tmp_path), nproc=1, timeout=0.05,
                  startup_grace=10.0, step_deadline=0.05)
    _stamp(tmp_path, 0, 3)
    wd.hung_workers()
    time.sleep(0.15)  # the stamp itself went stale: worker is DEAD,
    assert wd.hung_workers() == []  # which is stale_workers' business
    assert wd.stale_workers() == [0]


def test_watchdog_skips_drained_and_exited_ranks(tmp_path):
    wd = Watchdog(str(tmp_path), nproc=2, timeout=None,
                  step_deadline=0.05)
    _stamp(tmp_path, 0, 3)
    _stamp(tmp_path, 1, 3)
    wd.hung_workers()
    time.sleep(0.1)
    _stamp(tmp_path, 0, 3)
    _stamp(tmp_path, 1, 3)
    (tmp_path / "hb.1.preempted").write_text("{}")
    assert wd.hung_workers() == [0]


def test_exit_marker_beats_stale_stamp_race(tmp_path):
    """Regression (satellite): a worker killed between writing its
    ``.exit`` marker and removing its stamp must read as cleanly
    exited, never as stale/hung."""
    _stamp(tmp_path, 0, 5)
    old = time.time() - 100
    os.utime(os.path.join(str(tmp_path), "hb.0"), (old, old))
    (tmp_path / "hb.0.exit").write_text("clean")
    wd = Watchdog(str(tmp_path), nproc=1, timeout=0.05,
                  startup_grace=0.0, step_deadline=0.05)
    assert wd.stale_workers() == []
    assert wd.hung_workers() == []


# -- restart backoff reset (satellite) --------------------------------------

def test_restart_backoff_resets_after_healthy_run():
    bo = RestartBackoff(base=0.5, factor=2.0, max_delay=30.0,
                        jitter=0.0, reset_after=10.0)
    assert bo.next_delay(0.0) == pytest.approx(0.5)
    assert bo.next_delay(1.0) == pytest.approx(1.0)
    assert bo.next_delay(2.0) == pytest.approx(2.0)
    before = monitor.counter("restart_backoff_resets_total").value
    # the gang ran healthy past reset_after: series starts over
    assert bo.next_delay(11.0) == pytest.approx(0.5)
    assert monitor.counter("restart_backoff_resets_total").value > before


# -- checkpoint rotation guard + latest fallback (satellites) ---------------

def test_rotation_guard_protects_version_being_read(tmp_path):
    main_p, startup, _ = _build()
    exe = fluid.Executor()
    exe.run(startup)
    mgr = fluid.io.CheckpointManager(str(tmp_path), max_to_keep=1)
    mgr.save(main_p, step=1)
    mgr.save(main_p, step=2)
    assert mgr.steps() == [2]
    with open(mgr._guard_path(2), "w") as f:  # a concurrent restore()
        f.write(str(time.time()))
    mgr.save(main_p, step=3)
    assert 2 in mgr.steps()  # guarded: rotation must not delete it
    os.remove(mgr._guard_path(2))
    mgr.save(main_p, step=4)
    assert mgr.steps() == [4]


def test_rotation_guard_ttl_sweeps_crashed_readers(tmp_path):
    main_p, startup, _ = _build()
    exe = fluid.Executor()
    exe.run(startup)
    mgr = fluid.io.CheckpointManager(str(tmp_path), max_to_keep=1)
    mgr.save(main_p, step=1)
    guard = mgr._guard_path(1)
    with open(guard, "w") as f:
        f.write("dead reader")
    old = time.time() - 1000  # well past _GUARD_TTL
    os.utime(guard, (old, old))
    assert mgr._guarded_steps() == set()
    assert not os.path.exists(guard)  # swept


def test_latest_falls_back_past_torn_version_and_counts(tmp_path):
    main_p, startup, _ = _build()
    exe = fluid.Executor()
    exe.run(startup)
    mgr = fluid.io.CheckpointManager(str(tmp_path), max_to_keep=5)
    mgr.save(main_p, step=1)
    mgr.save(main_p, step=2)
    # tear the newest version (truncate a payload file)
    with open(os.path.join(mgr._path(2), "params.pdparams"), "w") as f:
        f.write("torn")
    before = monitor.counter("checkpoint_latest_fallback_total").value
    assert mgr.latest() == 1
    assert monitor.counter(
        "checkpoint_latest_fallback_total").value > before


def test_restore_on_restart_cold_starts_on_empty_or_garbage(tmp_path,
                                                            monkeypatch):
    monkeypatch.setenv("PADDLE_RESTART_ATTEMPT", "1")
    main_p, startup, _ = _build()
    exe = fluid.Executor()
    exe.run(startup)
    mgr = fluid.io.CheckpointManager(str(tmp_path / "empty"))
    assert mgr.restore_on_restart(exe, main_p) is None
    gdir = tmp_path / "garbage"
    mgr2 = fluid.io.CheckpointManager(str(gdir))
    (gdir / "ckpt-notanumber").write_text("junk")
    os.makedirs(str(gdir / "ckpt-00000007"))
    (gdir / "ckpt-00000007" / "manifest.json").write_text("{torn")
    assert mgr2.restore_on_restart(exe, main_p) is None


def test_manifest_records_world_size(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "3")
    main_p, startup, _ = _build()
    exe = fluid.Executor()
    exe.run(startup)
    mgr = fluid.io.CheckpointManager(str(tmp_path))
    mgr.save(main_p, step=1)
    assert mgr.manifest(1)["world_size"] == 3


# -- reshard-on-restore -----------------------------------------------------

def _build_sharded(seed=11):
    """A model whose first fc weight carries a ParamAttr shard spec over
    the 'dp' axis (8x8 weight: divides the 8-device virtual mesh)."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        h = layers.fc(x, size=8, act="relu",
                      param_attr=fluid.ParamAttr(shard=("dp", None)))
        loss = layers.reduce_mean(h)
        optimizer.SGD(0.05).minimize(loss)
    return main, startup, loss


def test_restore_reshards_through_compiled_program(tmp_path):
    from jax import Array
    from jax.sharding import PartitionSpec as P

    main_p, startup, loss = _build_sharded()
    exe = fluid.Executor()
    exe.run(startup)
    mgr = fluid.io.CheckpointManager(str(tmp_path))
    mgr.save(main_p, step=1)

    cp = fluid.CompiledProgram(main_p).with_data_parallel(
        loss_name=loss.name)
    sharded = [v.name for v in main_p.list_vars()
               if getattr(v, "shard_spec", None)]
    assert sharded
    before = monitor.counter("checkpoint_reshards_total").value
    # the CompiledProgram handed straight in IS the reshard strategy
    assert mgr.restore(exe, cp) == 1
    assert monitor.counter("checkpoint_reshards_total").value > before
    scope = fluid.global_scope()
    w = scope.find_var(sharded[0])
    assert isinstance(w, Array)
    assert w.sharding.spec == P("dp", None)
    # an unspecced persistable restores replicated
    repl = [v.name for v in main_p.list_vars()
            if v.persistable and not getattr(v, "shard_spec", None)]
    r = scope.find_var(repl[0])
    assert isinstance(r, Array) and r.sharding.spec == P()


def test_state_sharding_degrades_when_dim_no_longer_divides(tmp_path):
    main_p, _, loss = _build_sharded()
    cp = fluid.CompiledProgram(main_p).with_data_parallel(
        loss_name=loss.name)
    block = main_p.global_block()
    name = [v.name for v in main_p.list_vars()
            if getattr(v, "shard_spec", None)][0]
    before = monitor.counter("state_reshard_replicated_total").value
    # a checkpoint written before the mesh changed: 7 does not divide 8
    sh = cp.state_sharding(block, name, value=np.zeros((7, 8), "f"))
    from jax.sharding import PartitionSpec as P

    assert sh.spec == P()
    assert monitor.counter(
        "state_reshard_replicated_total").value > before


def test_state_sharding_missing_axis_replicates_with_value_only():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        h = layers.fc(x, size=8,
                      param_attr=fluid.ParamAttr(shard=("tp", None)))
        loss = layers.reduce_mean(h)
    cp = fluid.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name)  # mesh has only 'dp' — 'tp' is gone
    block = main.global_block()
    name = [v.name for v in main.list_vars()
            if getattr(v, "shard_spec", None)][0]
    from jax.sharding import PartitionSpec as P

    sh = cp.state_sharding(block, name, value=np.zeros((8, 8), "f"))
    assert sh.spec == P()  # restore path: degrade, don't die
    with pytest.raises(ValueError):
        cp.state_sharding(block, name)  # compile path stays strict


# -- trainer env derivation -------------------------------------------------

def test_trainer_env_rederives_world_from_endpoints():
    e = trainer_env(1, ["h:1", "h:2"], attempt=3, base_env={"KEEP": "1"})
    assert e["PADDLE_TRAINER_ID"] == "1"
    assert e["PADDLE_TRAINERS_NUM"] == "2"
    assert e["PADDLE_CURRENT_ENDPOINT"] == "h:2"
    assert e["PADDLE_RESTART_ATTEMPT"] == "3"
    assert e["KEEP"] == "1"
    with pytest.raises(ValueError):
        trainer_env(2, ["h:1", "h:2"])


# -- resilience lint: raw signal.signal / os._exit (satellite) --------------

def _lint():
    sys.path.insert(0, os.path.join(os.path.dirname(HERE), "tools"))
    import check_resilience
    return check_resilience


def test_lint_flags_raw_signal_and_exit_calls(tmp_path):
    cr = _lint()
    bad = tmp_path / "bad.py"
    bad.write_text("import os\nimport signal\n"
                   "signal.signal(2, None)\nos._exit(1)\n")
    assert len(cr.check_file(str(bad))) == 2
    ok = tmp_path / "ok.py"
    ok.write_text(
        '"""docstring mentioning os._exit(1) is prose, not a call"""\n'
        "import signal\n"
        "signal.signal(2, None)  # test-only handler, restored in teardown\n")
    assert cr.check_file(str(ok)) == []


def test_lint_exempts_the_preemption_module(tmp_path):
    cr = _lint()
    d = tmp_path / "distributed"
    os.makedirs(str(d))
    p = d / "preemption.py"
    p.write_text("import signal\nsignal.signal(2, None)\n")
    assert cr.check_file(str(p)) == []


# -- acceptance: the three elastic scenarios end-to-end ---------------------

def _launch_elastic(tmp_path, tag, nproc, extra_env=None, **kw):
    env = dict(os.environ)
    env.pop(faults.ENV, None)
    env.update(extra_env or {})
    log_dir = str(tmp_path / ("logs_" + tag))
    kw.setdefault("restart_backoff", 0.05)
    kw.setdefault("checkpoint_dir", str(tmp_path / ("ckpt_" + tag)))
    codes = launch(nproc, [sys.executable, "-u", RUNNER], env=env,
                   log_dir=log_dir, **kw)
    logs = []
    for r in range(nproc):
        try:
            with open(os.path.join(log_dir, "worker.%d.log" % r)) as f:
                logs.append(f.read())
        except OSError:
            logs.append("")
    return codes, logs


@pytest.mark.elastic
@pytest.mark.faults
@pytest.mark.parametrize("backend", ["file", "tcp"])
def test_preempt_drain_checkpoints_and_resumes_bit_identical(tmp_path,
                                                            backend):
    """SIGTERM mid-run: the worker finishes its step, force-saves,
    exits 0 — and the respawn (NO restart budget: max_restarts=0)
    resumes to final weights bit-identical to an uninterrupted run.
    Runs against BOTH rendezvous backends: shared-FS file and the TCP
    coordination service."""
    base_codes, base_logs = _launch_elastic(tmp_path, "base", 1,
                                            rendezvous_backend=backend)
    assert base_codes == [0]
    base_w = re.findall(r"WEIGHTS (\w+)", base_logs[0])
    assert base_w

    before = monitor.counter("launch_preemptions_total").value
    codes, logs = _launch_elastic(
        tmp_path, "pre", 1, {"PADDLE_TEST_PREEMPT_AT": "3"},
        max_restarts=0, rendezvous_backend=backend)
    assert codes == [0]
    log = logs[0]
    assert "drained cleanly" in log
    resumed = [int(x) for x in re.findall(r"RESUMED (-?\d+)", log)]
    assert resumed[0] == -1
    assert len(resumed) == 2 and resumed[1] >= 1  # respawn resumed
    assert re.findall(r"WEIGHTS (\w+)", log)[-1] == base_w[-1]
    assert monitor.counter("launch_preemptions_total").value > before


@pytest.mark.elastic
@pytest.mark.faults
@pytest.mark.parametrize("backend", ["file", "tcp"])
def test_gang_shrinks_to_survivors_and_reshards(tmp_path, backend):
    """Rank 2 hard-crashes whenever the gang runs at size 3; after the
    size-3 budget (max_restarts_at_size=1) is exhausted the launcher
    re-forms at 2, and rank 0 restores the size-3 checkpoint THROUGH
    its CompiledProgram — reshard-on-restore onto the current mesh.
    The reformation plumbing (offer/consume slots, generation bumps)
    must behave identically over the file and TCP rendezvous."""
    before = monitor.counter("launch_reformations_total").value
    codes, logs = _launch_elastic(
        tmp_path, "shrink", 3,
        {"PADDLE_TEST_CRASH_RANK": "2", "PADDLE_TEST_CRASH_WORLD": "3",
         "PADDLE_TEST_CRASH_AT": "2", "PADDLE_TEST_COMPILED": "1"},
        max_restarts=4, max_restarts_at_size=1, min_world_size=2,
        rendezvous_backend=backend)
    assert len(codes) == 2  # the reformed gang IS the final attempt
    assert codes == [0, 0]
    assert monitor.counter("launch_reformations_total").value > before
    log0 = logs[0]
    assert "WORLD 3 RANK 0" in log0 and "WORLD 2 RANK 0" in log0
    resumed = [int(x) for x in re.findall(r"RESUMED (-?\d+)", log0)]
    assert resumed[0] == -1 and resumed[-1] >= 1  # shrunk gang resumed
    reshards = [int(x) for x in re.findall(r"RESHARD (\d+)", log0)]
    assert reshards and reshards[-1] > 0  # state really went through
    assert re.findall(r"WEIGHTS (\w+)", log0)  # ... and training finished


@pytest.mark.elastic
@pytest.mark.faults
@pytest.mark.parametrize("backend", ["file", "tcp"])
def test_hung_step_watchdog_dumps_stacks_and_recovers(tmp_path, backend):
    """A worker wedges mid-step while its heartbeat daemon keeps
    stamping: only the step-deadline watchdog can see it. It SIGUSR1s
    the worker (faulthandler stack dump into the log), kills the gang,
    and the respawn resumes from the checkpoint — on either rendezvous
    backend."""
    before = monitor.counter("watchdog_hung_steps_total").value
    codes, logs = _launch_elastic(
        tmp_path, "hang", 1,
        {"PADDLE_TEST_HANG_AT": "2", "PADDLE_FAULT_HANG_SECONDS": "3600"},
        max_restarts=1, step_deadline=3.0, rendezvous_backend=backend)
    assert codes == [0]
    assert monitor.counter("watchdog_hung_steps_total").value > before
    log = logs[0]
    # faulthandler's dump: thread headers + the wedged frame in faults.py
    assert "Current thread" in log or "Thread 0x" in log
    assert "faults.py" in log
    resumed = [int(x) for x in re.findall(r"RESUMED (-?\d+)", log)]
    assert resumed[0] == -1 and resumed[-1] >= 1
    assert re.findall(r"WEIGHTS (\w+)", log)
