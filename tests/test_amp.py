"""AMP (mixed precision) tests — reference contrib/mixed_precision
(test_mixed_precision_decorate / test_image_classification_fp16 analogues)."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, optimizer
from paddle_tpu.fluid.contrib import mixed_precision
from paddle_tpu.models import bert, lenet


def test_rewrite_program_inserts_casts():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[16], dtype="float32")
        y = layers.fc(x, 8)
        loss = layers.mean(y)
    n_casts_before = sum(1 for op in main.global_block().ops
                         if op.type == "cast")
    mixed_precision.rewrite_program(
        main, mixed_precision.AutoMixedPrecisionLists(), "bfloat16")
    casts = [op for op in main.global_block().ops if op.type == "cast"]
    assert len(casts) > n_casts_before
    # the matmul (white) now consumes bf16-cast inputs
    mm = next(op for op in main.global_block().ops
              if op.type in ("mul", "matmul"))
    assert any(n.endswith(".cast_bfloat16") for n in mm.input_arg_names())


def test_amp_lenet_trains():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 7
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[1, 28, 28], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        _, loss, acc = lenet.lenet_forward(img, label)
        opt = mixed_precision.decorate(optimizer.Adam(learning_rate=1e-3))
        opt.minimize(loss)
    exe = fluid.Executor()
    rng = np.random.RandomState(0)
    feed = {"img": rng.rand(16, 1, 28, 28).astype(np.float32),
            "label": rng.randint(0, 10, (16, 1)).astype(np.int64)}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        losses = [float(np.asarray(exe.run(main, feed=feed,
                                           fetch_list=[loss])[0]))
                  for _ in range(6)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_amp_dynamic_loss_scaling_updates():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.fc(x, 4)
        loss = layers.mean(y)
        opt = mixed_precision.decorate(
            optimizer.SGD(learning_rate=0.1), init_loss_scaling=32.0,
            use_dynamic_loss_scaling=True, incr_every_n_steps=2)
        opt.minimize(loss)
    scale_var = opt.get_loss_scaling()
    exe = fluid.Executor()
    feed = {"x": np.ones((4, 4), np.float32)}
    with fluid.scope_guard(fluid.Scope()) as _:
        exe.run(startup)
        scales = []
        for _ in range(4):
            out = exe.run(main, feed=feed, fetch_list=[loss, scale_var])
            scales.append(float(np.asarray(out[1])))
    # finite grads throughout; fetch sees the post-step value: good-step
    # counter hits incr_every_n=2 at steps 1 and 3 -> scale doubles there
    assert scales == [32.0, 64.0, 64.0, 128.0]


def test_amp_overflow_halves_scale_and_protects_params():
    """fp16 overflow: inf grads must be gated with a select (inf*0 = nan
    would poison params) and the dynamic scale must halve."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.fc(x, 4)
        loss = layers.mean(y)
        opt = mixed_precision.decorate(
            optimizer.SGD(learning_rate=0.1), init_loss_scaling=256.0,
            use_dynamic_loss_scaling=True, decr_every_n_nan_or_inf=1,
            dest_dtype="float16")
        opt.minimize(loss)
    sv = opt.get_loss_scaling()
    w = main.global_block().all_parameters()[0]
    exe = fluid.Executor()
    feed = {"x": np.full((4, 4), 6e4, np.float32)}  # overflows fp16 matmul
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        scales = []
        for _ in range(3):
            out = exe.run(main, feed=feed, fetch_list=[loss, sv, w])
            scales.append(float(np.asarray(out[1]).ravel()[0]))
            assert np.isfinite(np.asarray(out[2])).all(), "params poisoned"
    assert scales == [128.0, 64.0, 32.0]


def test_amp_decr_counter_gates_halving():
    """decr_every_n_nan_or_inf=2: the scale halves only after two
    consecutive overflow steps (reference update_loss_scaling's
    num_bad_steps counter)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.fc(x, 4)
        loss = layers.mean(y)
        opt = mixed_precision.decorate(
            optimizer.SGD(learning_rate=0.1), init_loss_scaling=256.0,
            use_dynamic_loss_scaling=True, decr_every_n_nan_or_inf=2,
            dest_dtype="float16")
        opt.minimize(loss)
    sv = opt.get_loss_scaling()
    exe = fluid.Executor()
    feed = {"x": np.full((4, 4), 6e4, np.float32)}  # overflows fp16 matmul
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        scales = [float(np.asarray(exe.run(main, feed=feed,
                                           fetch_list=[sv])[0]).ravel()[0])
                  for _ in range(4)]
    # bad counter: 1 (no decr), 2 (decr, reset), 1, 2 (decr)
    assert scales == [256.0, 128.0, 128.0, 64.0]


def test_amp_applied_scale_recovers_grads():
    """The *applied* scale must track the variable: an init scale big enough
    to overflow the fp16 backward produces inf grads (zeroed step); once the
    dynamic scale halves below the fp16 max, grads become finite and params
    actually move — impossible if the compile-time init scale kept applying."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.fc(x, 4)
        loss = layers.mean(y)
        opt = mixed_precision.decorate(
            optimizer.SGD(learning_rate=0.1),
            init_loss_scaling=float(2 ** 21),  # dLoss/dy = 2^21/16 > fp16 max
            use_dynamic_loss_scaling=True, decr_every_n_nan_or_inf=1,
            dest_dtype="float16")
        opt.minimize(loss)
    sv = opt.get_loss_scaling()
    w = main.global_block().all_parameters()[0]
    exe = fluid.Executor()
    feed = {"x": np.ones((4, 4), np.float32)}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        # Until the scale decays enough, grads are inf -> gated to zero and
        # params hold still (w stays at its init value); once the applied
        # scale is low enough for the whole fp16 backward (incl. the x^T@dy
        # weight-grad accumulation) the params move.
        w_first = None
        moved = []
        for _ in range(8):
            out = exe.run(main, feed=feed, fetch_list=[sv, w])
            wn = np.asarray(out[1])
            assert np.isfinite(wn).all()
            if w_first is None:
                w_first = wn.copy()
            moved.append(bool(np.abs(wn - w_first).max() > 0))
    assert moved[-1], "params never moved: dynamic scale not applied in-graph"


def test_amp_bert_tiny_trains():
    cfg = bert.BertConfig.tiny()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 7
    with fluid.program_guard(main, startup):
        src = layers.data("src_ids", shape=[32], dtype="int64")
        pos = layers.data("pos_ids", shape=[32], dtype="int64")
        sent = layers.data("sent_ids", shape=[32], dtype="int64")
        imask = layers.data("input_mask", shape=[32, 1], dtype="float32")
        mlabel = layers.data("mask_label", shape=[32, 1], dtype="int64")
        mweight = layers.data("mask_weight", shape=[32, 1], dtype="float32")
        enc = bert.bert_encoder(src, pos, sent, imask, cfg)
        loss = bert.mlm_loss(enc, mlabel, mweight, cfg)
        opt = mixed_precision.decorate(optimizer.Adam(learning_rate=1e-3))
        opt.minimize(loss)
    batch = bert.synthetic_batch(cfg, 4, 32, masked_gather=False)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        losses = [float(np.asarray(exe.run(main, feed=batch,
                                           fetch_list=[loss])[0]))
                  for _ in range(6)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_amp_dynamic_preserves_selected_rows_grads():
    """Dynamic scaling rewrites grads into '.unscaled'/'.gated' vars; for a
    SelectedRows grad those must keep the type marker and the @ROWS binding
    (else the (n, dim) values array would be applied as a dense [vocab, dim]
    grad). Trains must match the non-AMP sparse baseline at scale 1.0."""
    vocab, dim, lr = 25, 4, 0.5
    feed = {"ids": np.array([[1, 3, 3], [9, 1, 1]], np.int64)}

    def run(with_amp):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 13
        with fluid.program_guard(main, startup):
            ids = layers.data("ids", shape=[3], dtype="int64")
            emb = layers.embedding(ids, size=[vocab, dim], is_sparse=True,
                                   param_attr=fluid.ParamAttr(name="amp_emb"))
            loss = layers.mean(layers.reduce_sum(emb * emb, dim=-1))
            opt = optimizer.SGD(learning_rate=lr)
            if with_amp:
                opt = mixed_precision.decorate(
                    opt, init_loss_scaling=1.0,
                    use_dynamic_loss_scaling=True,
                    amp_lists=mixed_precision.AutoMixedPrecisionLists(
                        custom_black_list={"lookup_table"}))
            opt.minimize(loss)
        if with_amp:
            block = main.global_block()
            gated = block.var("amp_emb@GRAD.gated")
            assert gated.type == "selected_rows"
            assert block.var("amp_emb@GRAD.gated@ROWS") is not None
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            w0 = np.asarray(exe.run(main, feed=feed,
                                    fetch_list=["amp_emb"])[0]).copy()
            w1 = np.asarray(exe.run(main, feed=feed,
                                    fetch_list=["amp_emb"])[0]).copy()
        return w0, w1

    base0, base1 = run(False)
    amp0, amp1 = run(True)
    np.testing.assert_allclose(amp0, base0, rtol=1e-4)
    np.testing.assert_allclose(amp1, base1, rtol=1e-4)
    # untouched rows frozen (sparse update semantics survived AMP)
    untouched = np.setdiff1d(np.arange(vocab), [1, 3, 9])
    np.testing.assert_array_equal(amp1[untouched], amp0[untouched])


def test_amp_batch_norm_bf16_io_f32_stats():
    """batch_norm is AMP-gray on TPU: the activation X follows the bf16
    chain but the running Mean/Variance and Scale/Bias inputs must stay
    f32 (momentum deltas below the bf16 ulp would vanish), and only Y
    propagates as low precision — MeanOut aliases the f32 stat var."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[3, 8, 8], dtype="float32")
        c = layers.conv2d(img, 4, 3)         # white -> bf16 output
        bn = layers.batch_norm(c)
        pred = layers.fc(layers.flatten(bn), 2)
        loss = layers.mean(pred)
    mixed_precision.rewrite_program(
        main, mixed_precision.AutoMixedPrecisionLists(), "bfloat16")
    bn_op = next(op for op in main.global_block().ops
                 if op.type == "batch_norm")
    blk = main.global_block()
    # X rides the low chain; state inputs stay on the f32 vars
    for slot in ("Scale", "Bias", "Mean", "Variance"):
        for n in bn_op.inputs[slot]:
            assert "cast_bfloat16" not in n, (slot, n)
            assert str(blk._find_var_recursive(n).dtype) == "float32"
    # Y follows the low chain: the downstream (white) matmul consumes it
    # directly, with no fresh .cast_bfloat16 inserted for it
    (yname,) = bn_op.outputs["Y"]
    consumers = [op for op in blk.ops
                 if any(yname == n or n.startswith(yname + ".")
                        for n in op.input_arg_names())]
    assert consumers and all(op.type != "cast" for op in consumers), (
        [op.type for op in consumers])
    # and the program executes with the running stats committed as f32
    exe = fluid.Executor()
    rng = np.random.RandomState(0)
    feed = {"img": rng.rand(4, 3, 8, 8).astype(np.float32)}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        stat = next(v.name for v in main.list_vars()
                    if v.persistable and v.name.endswith(".stat_0"))
        (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
        assert np.isfinite(np.asarray(lv)).all()
        got = fluid.global_scope().find_var(stat)
        assert np.asarray(got).dtype == np.float32


def test_amp_layer_norm_bf16_io_f32_stats():
    """layer_norm mirrors batch_norm's AMP-gray contract: X rides the
    bf16 chain, Scale/Bias inputs stay on the f32 vars (no cast
    inserted), Y feeds downstream ops directly (no re-cast), and the
    lowering computes stats in f32 internally."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8, 16], dtype="float32")
        h = layers.fc(x, 16, num_flatten_dims=2)   # white -> bf16
        ln = layers.layer_norm(h, begin_norm_axis=2)
        out = layers.fc(ln, 4, num_flatten_dims=2)
        loss = layers.mean(out)
    mixed_precision.rewrite_program(
        main, mixed_precision.AutoMixedPrecisionLists(), "bfloat16")
    blk = main.global_block()
    ln_op = next(op for op in blk.ops if op.type == "layer_norm")
    for slot in ("Scale", "Bias"):
        for n in ln_op.inputs.get(slot, []):
            assert "cast_bfloat16" not in n, (slot, n)
            assert str(blk._find_var_recursive(n).dtype) == "float32"
    # X arrives low (produced by the white matmul chain), uncast
    (xname,) = ln_op.inputs["X"]
    assert "cast" not in xname.split(".")[-1] or "bfloat16" in xname
    # Y flows into the next white matmul without a fresh bf16 cast
    (yname,) = ln_op.outputs["Y"]
    consumers = [op for op in blk.ops
                 if yname in op.input_arg_names()]
    assert consumers and all(op.type != "cast" for op in consumers), (
        [op.type for op in consumers])
    # executes and trains
    exe = fluid.Executor()
    feed = {"x": np.random.RandomState(0).rand(2, 8, 16)
            .astype(np.float32)}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
        assert np.isfinite(np.asarray(lv)).all()
