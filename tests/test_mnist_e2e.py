"""End-to-end LeNet/MNIST training — the reference "book" suite milestone
(``tests/book/test_recognize_digits.py``), config 1 of BASELINE.md.

Uses synthetic class-separable data (zero-egress environment)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, optimizer


def lenet(img, label):
    conv1 = layers.conv2d(img, num_filters=6, filter_size=5, padding=2, act="relu")
    pool1 = layers.pool2d(conv1, pool_size=2, pool_stride=2)
    conv2 = layers.conv2d(pool1, num_filters=16, filter_size=5, act="relu")
    pool2 = layers.pool2d(conv2, pool_size=2, pool_stride=2)
    fc1 = layers.fc(pool2, size=120, act="relu")
    fc2 = layers.fc(fc1, size=84, act="relu")
    logits = layers.fc(fc2, size=10)
    loss = layers.mean(
        layers.softmax_with_cross_entropy(logits, label)
    )
    acc = layers.accuracy(layers.softmax(logits), label)
    return loss, acc


def synthetic_digits(rng, n):
    """Class-separable 28x28 images: digit k = bright kth row band."""
    labels = rng.randint(0, 10, (n, 1)).astype(np.int64)
    imgs = rng.rand(n, 1, 28, 28).astype(np.float32) * 0.1
    for i, k in enumerate(labels.ravel()):
        imgs[i, 0, k * 2 : k * 2 + 3, :] += 1.0
    return imgs, labels


def test_mnist_lenet_train():
    main = fluid.Program()
    startup = fluid.Program()
    main.random_seed = 7
    with fluid.program_guard(main, startup):
        img = layers.data(name="img", shape=[1, 28, 28], dtype="float32")
        label = layers.data(name="label", shape=[1], dtype="int64")
        loss, acc = lenet(img, label)
        opt = optimizer.Adam(learning_rate=1e-3)
        opt.minimize(loss)

    exe = fluid.Executor()
    rng = np.random.RandomState(0)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        first = last = None
        for step in range(40):
            imgs, labels = synthetic_digits(rng, 32)
            lv, av = exe.run(main, feed={"img": imgs, "label": labels},
                             fetch_list=[loss, acc])
            if first is None:
                first = float(lv)
            last, last_acc = float(lv), float(av)
        assert last < first * 0.5, (first, last)
        assert last_acc > 0.8, last_acc

    # inference program path
    test_prog = main.clone(for_test=True)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        imgs, labels = synthetic_digits(rng, 16)
        (lv,) = exe.run(test_prog, feed={"img": imgs, "label": labels},
                        fetch_list=[loss.name])
        assert np.isfinite(lv)


def test_save_load_roundtrip(tmp_path):
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        out = layers.fc(x, size=2)
    exe = fluid.Executor()
    xv = np.random.rand(3, 4).astype(np.float32)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (r1,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
        fluid.io.save_persistables(exe, str(tmp_path), main)
    with fluid.scope_guard(fluid.Scope()):
        fluid.io.load_persistables(exe, str(tmp_path), main)
        (r2,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
    np.testing.assert_allclose(r1, r2, rtol=1e-6)


def test_save_load_inference_model(tmp_path):
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        hidden = layers.fc(x, size=8, act="relu")
        out = layers.fc(hidden, size=2, act="softmax")
        label = layers.data(name="label", shape=[1], dtype="int64")
        loss = layers.mean(layers.cross_entropy(out, label))
        # clone for eval BEFORE adding optimizer ops (reference idiom)
        test_prog = main.clone(for_test=True)
        optimizer.SGD(0.01).minimize(loss)
    exe = fluid.Executor()
    xv = np.random.rand(3, 4).astype(np.float32)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        exe.run(main, feed={"x": xv, "label": np.zeros((3, 1), np.int64)},
                fetch_list=[loss])  # one train step
        (r1,) = exe.run(test_prog,
                        feed={"x": xv, "label": np.zeros((3, 1), np.int64)},
                        fetch_list=[out.name])
        fluid.io.save_inference_model(str(tmp_path), ["x"], [out], exe, main)
    with fluid.scope_guard(fluid.Scope()):
        prog, feed_names, fetch_vars = fluid.io.load_inference_model(str(tmp_path), exe)
        assert feed_names == ["x"]
        # pruned program has no optimizer/loss ops
        types = [op.type for op in prog.global_block().ops]
        assert "sgd" not in types and "autodiff" not in types
        (r2,) = exe.run(prog, feed={"x": xv}, fetch_list=fetch_vars)
    np.testing.assert_allclose(r1, r2, rtol=1e-5)
