"""Numpy-referenced op tests (the reference's ~400 test_*_op.py workhorse
pattern, SURVEY §4)."""

import numpy as np
import pytest

from op_test import OpTest


def _softmax_np(x, axis=-1):
    e = np.exp(x - np.max(x, axis=axis, keepdims=True))
    return e / np.sum(e, axis=axis, keepdims=True)


class TestElementwiseAdd(OpTest):
    op_type = "elementwise_add"

    def setup_method(self, _):
        x = np.random.rand(3, 4).astype(np.float32)
        y = np.random.rand(3, 4).astype(np.float32)
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x + y}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["X", "Y"], "Out")


class TestElementwiseAddBroadcastAxis(OpTest):
    op_type = "elementwise_add"

    def setup_method(self, _):
        x = np.random.rand(2, 3, 4).astype(np.float32)
        y = np.random.rand(3).astype(np.float32)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": x + y.reshape(1, 3, 1)}

    def test_output(self):
        self.check_output()


class TestMul(OpTest):
    op_type = "mul"

    def setup_method(self, _):
        x = np.random.rand(4, 6).astype(np.float32)
        y = np.random.rand(6, 3).astype(np.float32)
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x @ y}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["X", "Y"], "Out")


class TestMulColDims(OpTest):
    op_type = "mul"

    def setup_method(self, _):
        x = np.random.rand(2, 3, 4).astype(np.float32)
        y = np.random.rand(4, 5).astype(np.float32)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"x_num_col_dims": 2, "y_num_col_dims": 1}
        self.outputs = {"Out": (x.reshape(6, 4) @ y).reshape(2, 3, 5)}

    def test_output(self):
        self.check_output()


class TestMatmulTranspose(OpTest):
    op_type = "matmul"

    def setup_method(self, _):
        x = np.random.rand(5, 3).astype(np.float32)
        y = np.random.rand(5, 4).astype(np.float32)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"transpose_X": True, "alpha": 2.0}
        self.outputs = {"Out": 2.0 * (x.T @ y)}

    def test_output(self):
        self.check_output()


class TestSoftmax(OpTest):
    op_type = "softmax"

    def setup_method(self, _):
        x = np.random.rand(3, 7).astype(np.float32)
        self.inputs = {"X": x}
        self.outputs = {"Out": _softmax_np(x)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["X"], "Out")


class TestSoftmaxWithCrossEntropy(OpTest):
    op_type = "softmax_with_cross_entropy"

    def setup_method(self, _):
        logits = np.random.rand(5, 4).astype(np.float32)
        labels = np.random.randint(0, 4, (5, 1)).astype(np.int64)
        sm = _softmax_np(logits)
        loss = -np.log(sm[np.arange(5), labels.ravel()]).reshape(5, 1)
        self.inputs = {"Logits": logits, "Label": labels}
        self.outputs = {"Softmax": sm, "Loss": loss}

    def test_output(self):
        self.check_output(atol=1e-5)


class TestCrossEntropySoft(OpTest):
    op_type = "cross_entropy"

    def setup_method(self, _):
        probs = _softmax_np(np.random.rand(4, 5).astype(np.float32))
        soft = _softmax_np(np.random.rand(4, 5).astype(np.float32))
        self.inputs = {"X": probs, "Label": soft}
        self.attrs = {"soft_label": True}
        self.outputs = {"Y": -np.sum(soft * np.log(probs), axis=1, keepdims=True)}

    def test_output(self):
        self.check_output()


class TestConv2d(OpTest):
    op_type = "conv2d"

    def setup_method(self, _):
        import jax  # reference conv via scipy-free numpy loop

        x = np.random.rand(2, 3, 5, 5).astype(np.float32)
        w = np.random.rand(4, 3, 3, 3).astype(np.float32)
        out = np.zeros((2, 4, 3, 3), np.float32)
        for n in range(2):
            for o in range(4):
                for i in range(3):
                    for j in range(3):
                        patch = x[n, :, i:i + 3, j:j + 3]
                        out[n, o, i, j] = np.sum(patch * w[o])
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = {"strides": [1, 1], "paddings": [0, 0], "dilations": [1, 1]}
        self.outputs = {"Output": out}

    def test_output(self):
        self.check_output(atol=1e-4)

    def test_grad(self):
        self.check_grad(["Filter"], "Output", atol=2e-2, rtol=2e-2, delta=1e-2)


class TestPool2dMax(OpTest):
    op_type = "pool2d"

    def setup_method(self, _):
        x = np.random.rand(2, 3, 4, 4).astype(np.float32)
        out = x.reshape(2, 3, 2, 2, 2, 2).max(axis=(3, 5))
        self.inputs = {"X": x}
        self.attrs = {"pooling_type": "max", "ksize": [2, 2], "strides": [2, 2],
                      "paddings": [0, 0]}
        self.outputs = {"Out": out}

    def test_output(self):
        self.check_output()


class TestPool2dAvg(OpTest):
    op_type = "pool2d"

    def setup_method(self, _):
        x = np.random.rand(2, 3, 4, 4).astype(np.float32)
        out = x.reshape(2, 3, 2, 2, 2, 2).mean(axis=(3, 5))
        self.inputs = {"X": x}
        self.attrs = {"pooling_type": "avg", "ksize": [2, 2], "strides": [2, 2],
                      "paddings": [0, 0]}
        self.outputs = {"Out": out}

    def test_output(self):
        self.check_output()


class TestLayerNorm(OpTest):
    op_type = "layer_norm"

    def setup_method(self, _):
        x = np.random.rand(3, 6).astype(np.float32)
        scale = np.random.rand(6).astype(np.float32)
        bias = np.random.rand(6).astype(np.float32)
        mean = x.mean(axis=1, keepdims=True)
        var = x.var(axis=1, keepdims=True)
        y = (x - mean) / np.sqrt(var + 1e-5) * scale + bias
        self.inputs = {"X": x, "Scale": scale, "Bias": bias}
        self.attrs = {"epsilon": 1e-5, "begin_norm_axis": 1}
        self.outputs = {"Y": y}

    def test_output(self):
        self.check_output(atol=1e-4)


class TestBatchNormInference(OpTest):
    op_type = "batch_norm"

    def setup_method(self, _):
        x = np.random.rand(2, 3, 4, 4).astype(np.float32)
        scale = np.random.rand(3).astype(np.float32)
        bias = np.random.rand(3).astype(np.float32)
        mean = np.random.rand(3).astype(np.float32)
        var = np.random.rand(3).astype(np.float32) + 0.5
        y = (x - mean.reshape(1, 3, 1, 1)) / np.sqrt(
            var.reshape(1, 3, 1, 1) + 1e-5
        ) * scale.reshape(1, 3, 1, 1) + bias.reshape(1, 3, 1, 1)
        self.inputs = {"X": x, "Scale": scale, "Bias": bias, "Mean": mean,
                       "Variance": var}
        self.attrs = {"is_test": True, "epsilon": 1e-5}
        self.outputs = {"Y": y}

    def test_output(self):
        self.check_output(atol=1e-4)


class TestReduceSum(OpTest):
    op_type = "reduce_sum"

    def setup_method(self, _):
        x = np.random.rand(3, 4, 5).astype(np.float32)
        self.inputs = {"X": x}
        self.attrs = {"dim": [1], "keep_dim": False, "reduce_all": False}
        self.outputs = {"Out": x.sum(axis=1)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["X"], "Out")


class TestReshape(OpTest):
    op_type = "reshape"

    def setup_method(self, _):
        x = np.random.rand(2, 6).astype(np.float32)
        self.inputs = {"X": x}
        self.attrs = {"shape": [0, 3, 2]}  # 0 = copy dim
        self.outputs = {"Out": x.reshape(2, 3, 2)}

    def test_output(self):
        self.check_output()


class TestConcat(OpTest):
    op_type = "concat"

    def setup_method(self, _):
        xs = [np.random.rand(2, i + 1).astype(np.float32) for i in range(3)]
        self.inputs = {"X": xs}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": np.concatenate(xs, axis=1)}

    def test_output(self):
        self.check_output()


class TestSplit(OpTest):
    op_type = "split"

    def setup_method(self, _):
        x = np.random.rand(2, 9).astype(np.float32)
        self.inputs = {"X": x}
        self.attrs = {"axis": 1, "num": 3, "sections": []}
        self.outputs = {"Out": np.split(x, 3, axis=1)}

    def test_output(self):
        self.check_output()


class TestTranspose(OpTest):
    op_type = "transpose"

    def setup_method(self, _):
        x = np.random.rand(2, 3, 4).astype(np.float32)
        self.inputs = {"X": x}
        self.attrs = {"axis": [2, 0, 1]}
        self.outputs = {"Out": x.transpose(2, 0, 1)}

    def test_output(self):
        self.check_output()


class TestTopK(OpTest):
    op_type = "top_k"

    def setup_method(self, _):
        x = np.random.rand(3, 8).astype(np.float32)
        idx = np.argsort(-x, axis=1)[:, :2]
        vals = np.take_along_axis(x, idx, axis=1)
        self.inputs = {"X": x}
        self.attrs = {"k": 2}
        self.outputs = {"Out": vals, "Indices": idx.astype(np.int64)}

    def test_output(self):
        self.check_output()


class TestGather(OpTest):
    op_type = "gather"

    def setup_method(self, _):
        x = np.random.rand(5, 3).astype(np.float32)
        idx = np.array([0, 2, 4], np.int64)
        self.inputs = {"X": x, "Index": idx}
        self.outputs = {"Out": x[idx]}

    def test_output(self):
        self.check_output()


class TestLookupTable(OpTest):
    op_type = "lookup_table"

    def setup_method(self, _):
        w = np.random.rand(10, 4).astype(np.float32)
        ids = np.array([[1], [3], [1], [9]], np.int64)
        self.inputs = {"W": w, "Ids": ids}
        self.outputs = {"Out": w[ids.ravel()]}

    def test_output(self):
        self.check_output()


class TestSigmoid(OpTest):
    op_type = "sigmoid"

    def setup_method(self, _):
        x = np.random.randn(4, 5).astype(np.float32)
        self.inputs = {"X": x}
        self.outputs = {"Out": 1.0 / (1.0 + np.exp(-x))}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["X"], "Out")


class TestScale(OpTest):
    op_type = "scale"

    def setup_method(self, _):
        x = np.random.rand(3, 4).astype(np.float32)
        self.inputs = {"X": x}
        self.attrs = {"scale": 2.5, "bias": 1.0}
        self.outputs = {"Out": 2.5 * x + 1.0}

    def test_output(self):
        self.check_output()


class TestClip(OpTest):
    op_type = "clip"

    def setup_method(self, _):
        x = np.random.randn(4, 4).astype(np.float32)
        self.inputs = {"X": x}
        self.attrs = {"min": -0.5, "max": 0.5}
        self.outputs = {"Out": np.clip(x, -0.5, 0.5)}

    def test_output(self):
        self.check_output()


class TestOneHot(OpTest):
    op_type = "one_hot"

    def setup_method(self, _):
        ids = np.array([[1], [0], [3]], np.int64)
        out = np.zeros((3, 4), np.float32)
        out[np.arange(3), ids.ravel()] = 1.0
        self.inputs = {"X": ids}
        self.attrs = {"depth": 4}
        self.outputs = {"Out": out}

    def test_output(self):
        self.check_output()


class TestCast(OpTest):
    op_type = "cast"

    def setup_method(self, _):
        x = np.random.rand(3, 4).astype(np.float32) * 10
        self.inputs = {"X": x}
        self.attrs = {"out_dtype": "int32"}
        self.outputs = {"Out": x.astype(np.int32)}

    def test_output(self):
        self.check_output()


class TestAccuracyOp(OpTest):
    op_type = "accuracy"

    def setup_method(self, _):
        idx = np.array([[1, 2], [0, 3], [4, 0]], np.int64)
        label = np.array([[2], [1], [4]], np.int64)
        self.inputs = {"Out": np.zeros((3, 2), np.float32), "Indices": idx,
                       "Label": label}
        self.outputs = {"Accuracy": np.float32(2.0 / 3.0)}

    def test_output(self):
        self.check_output()


class TestDropoutGradReplay(OpTest):
    """Gradient through dropout must reuse the SAME mask in replay —
    verifies the recorded-PRNG-key replay mechanism."""

    op_type = "dropout"

    def test_mask_consistency(self):
        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid import layers

        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            x = layers.data(name="x", shape=[64], dtype="float32")
            x.stop_gradient = False
            d = layers.dropout(x, dropout_prob=0.5,
                               dropout_implementation="upscale_in_train")
            loss = layers.reduce_sum(d)
            (gx,) = fluid.gradients(loss, x)
        exe = fluid.Executor()
        xv = np.random.rand(2, 64).astype(np.float32) + 1.0
        with fluid.scope_guard(fluid.Scope()):
            out, g = exe.run(main, feed={"x": xv}, fetch_list=[d, gx])
        # gradient must be 2.0 exactly where output non-zero, 0 where dropped
        np.testing.assert_allclose((out != 0), (g != 0))
        assert set(np.unique(g)).issubset({0.0, 2.0})


def test_py_func_forward_and_custom_backward():
    """py_func (reference operators/py_func_op.cc): host numpy forward +
    user backward; grad checked against the analytic value."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers

    def fwd(a):
        return np.tanh(a)

    def bwd(a, out, dout):
        return dout * (1.0 - out ** 2)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[2, 3], dtype="float32",
                        append_batch_size=False)
        out_var = main.current_block().create_var(
            name="pyf_out", shape=(2, 3), dtype="float32")
        o = layers.py_func(fwd, x, out_var, backward_func=bwd)
        loss = layers.reduce_sum(o * o)
        (gx,) = fluid.gradients(loss, x)
    exe = fluid.Executor()
    xv = np.random.RandomState(0).randn(2, 3).astype(np.float32)
    with fluid.scope_guard(fluid.Scope()):
        ov, gv = exe.run(main, feed={"x": xv}, fetch_list=[o, gx])
    ref = np.tanh(xv)
    np.testing.assert_allclose(ov, ref, atol=1e-6)
    np.testing.assert_allclose(gv, 2 * ref * (1 - ref ** 2), atol=1e-5)
    # finite-difference cross-check of the registered backward
    eps = 1e-3
    num = np.zeros_like(xv)
    for idx in np.ndindex(*xv.shape):
        p = xv.copy(); p[idx] += eps
        m = xv.copy(); m[idx] -= eps
        num[idx] = ((np.tanh(p) ** 2).sum() - (np.tanh(m) ** 2).sum()) \
            / (2 * eps)
    np.testing.assert_allclose(gv, num, atol=1e-2, rtol=1e-2)


def test_py_func_multiple_outputs_no_backward():
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers

    def fwd(a, b):
        return a + b, a * b

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32",
                        append_batch_size=False)
        y = layers.data("y", shape=[4], dtype="float32",
                        append_batch_size=False)
        o1 = main.current_block().create_var(name="pyf_o1", shape=(4,),
                                             dtype="float32")
        o2 = main.current_block().create_var(name="pyf_o2", shape=(4,),
                                             dtype="float32")
        outs = layers.py_func(fwd, [x, y], [o1, o2])
    exe = fluid.Executor()
    xv = np.arange(4, dtype=np.float32)
    yv = np.full(4, 2.0, np.float32)
    with fluid.scope_guard(fluid.Scope()):
        a, b = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=list(outs))
    np.testing.assert_allclose(a, xv + yv)
    np.testing.assert_allclose(b, xv * yv)


def test_einsum_op():
    """einsum (paddle.einsum capability): contraction by equation,
    checked against numpy on the attention-score pattern."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers

    rng = np.random.RandomState(3)
    q = rng.rand(2, 5, 3, 4).astype(np.float32)
    k = rng.rand(2, 6, 3, 4).astype(np.float32)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        qv = layers.data("eq", list(q.shape), append_batch_size=False)
        kv = layers.data("ek", list(k.shape), append_batch_size=False)
        out = layers.einsum("bqhd,bkhd->bhqk", qv, kv)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        (r,) = exe.run(main, feed={"eq": q, "ek": k}, fetch_list=[out])
    np.testing.assert_allclose(np.asarray(r),
                               np.einsum("bqhd,bkhd->bhqk", q, k),
                               rtol=1e-4, atol=1e-5)


def test_batch_norm_single_pass_stats_anchored():
    """BN computes batch stats in ONE sweep (shifted by the running
    mean): must stay accurate even for channels with |mean| >> std,
    where the naive E[x^2]-E[x]^2 form catastrophically cancels."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers

    rng = np.random.RandomState(0)
    x = (1000.0 + 0.1 * rng.randn(64, 8, 4, 4)).astype(np.float32)
    main, st = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, st):
        xv = layers.data("bn_x", list(x.shape), append_batch_size=False)
        y = layers.batch_norm(xv)
    exe = fluid.Executor()
    scope = fluid.Scope()
    mname = [v.name for v in main.list_vars()
             if v.persistable and v.name.endswith(".stat_0")][0]
    with fluid.scope_guard(scope):
        exe.run(st)
        # settled-training regime: running mean near the true mean
        scope.set_var(mname, np.full(8, 1000.0, np.float32))
        (yv,) = exe.run(main, feed={"bn_x": x}, fetch_list=[y])
    ref = (x - x.mean((0, 2, 3), keepdims=True)) / np.sqrt(
        x.var((0, 2, 3), keepdims=True) + 1e-5)
    assert np.abs(np.asarray(yv) - ref).max() < 0.05
    # Y keeps the input dtype (no silent promotion in bf16 programs)
    import jax.numpy as jnp

    from paddle_tpu.fluid.ops.nn import _batch_norm  # noqa: F401
    assert np.asarray(yv).dtype == np.float32


def test_batch_norm_far_anchor_stats():
    """Early-training regime for the single-pass anchored BN stats: the
    anchor is the FRESH running mean (0) while activations sit at
    |mean| = 50*sigma. The shifted-moment correction loses ~mc^2/var *
    2^-24 relative precision (~1e-4 here) — normalization must still be
    accurate. Pins the bound documented at ops/nn.py _batch_norm."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers

    rng = np.random.RandomState(7)
    x = (50.0 + rng.randn(64, 8, 4, 4)).astype(np.float32)
    main, st = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, st):
        xv = layers.data("bnf_x", list(x.shape), append_batch_size=False)
        y = layers.batch_norm(xv)
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(st)  # running mean stays at its 0.0 init — worst anchor
        (yv,) = exe.run(main, feed={"bnf_x": x}, fetch_list=[y])
    ref = (x - x.mean((0, 2, 3), keepdims=True)) / np.sqrt(
        x.var((0, 2, 3), keepdims=True) + 1e-5)
    assert np.abs(np.asarray(yv) - ref).max() < 0.05
