"""Detection op family — reference ``operators/detection/`` +
``layers/detection.py`` (27 fns), numpy-referenced per SURVEY §4.

Static-shape deviations under test: NMS/proposal outputs are fixed top-N
padded with label -1 / zero boxes (see ops/detection_ops.py docstring).
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers


def _run(build, feed):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        fetch = build()
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        res = exe.run(main, feed=feed, fetch_list=list(fetch))
    return [np.asarray(r) for r in res]


def _np_iou(a, b):
    area_a = np.maximum(a[:, 2] - a[:, 0], 0) * np.maximum(
        a[:, 3] - a[:, 1], 0)
    area_b = np.maximum(b[:, 2] - b[:, 0], 0) * np.maximum(
        b[:, 3] - b[:, 1], 0)
    lt = np.maximum(a[:, None, :2], b[None, :, :2])
    rb = np.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = np.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / np.maximum(area_a[:, None] + area_b[None, :] - inter,
                              1e-10)


BOXES_A = np.array([[0, 0, 10, 10], [5, 5, 15, 15], [20, 20, 30, 30]],
                   np.float32)
BOXES_B = np.array([[0, 0, 10, 10], [6, 6, 14, 14]], np.float32)


def test_iou_similarity():
    (out,) = _run(
        lambda: [layers.iou_similarity(
            layers.data("a", [4], append_batch_size=False, dtype="float32"),
            layers.data("b", [4], append_batch_size=False,
                        dtype="float32"))],
        {"a": BOXES_A, "b": BOXES_B})
    np.testing.assert_allclose(out, _np_iou(BOXES_A, BOXES_B), rtol=1e-5)


def test_prior_box_shapes_and_values():
    feat = np.zeros((1, 8, 2, 2), np.float32)
    img = np.zeros((1, 3, 32, 32), np.float32)

    def build():
        f = layers.data("f", feat.shape, append_batch_size=False)
        im = layers.data("im", img.shape, append_batch_size=False)
        b, v = layers.prior_box(f, im, min_sizes=[4.0], max_sizes=[8.0],
                                aspect_ratios=[2.0], flip=True, clip=True)
        return [b, v]

    b, v = _run(build, {"f": feat, "im": img})
    # priors per cell: ar=1 + ar=2 + ar=1/2 + max-size = 4
    assert b.shape == (2, 2, 4, 4)
    assert v.shape == (2, 2, 4, 4)
    # cell (0,0): center at offset 0.5 * step 16 = (8, 8); ar=1 min_size 4
    np.testing.assert_allclose(
        b[0, 0, 0], [(8 - 2) / 32, (8 - 2) / 32, (8 + 2) / 32, (8 + 2) / 32],
        rtol=1e-5)
    assert (b >= 0).all() and (b <= 1).all()
    np.testing.assert_allclose(v[0, 0, 0], [0.1, 0.1, 0.2, 0.2], rtol=1e-6)


def test_density_prior_box():
    feat = np.zeros((1, 8, 2, 2), np.float32)
    img = np.zeros((1, 3, 32, 32), np.float32)

    def build():
        f = layers.data("f", feat.shape, append_batch_size=False)
        im = layers.data("im", img.shape, append_batch_size=False)
        b, v = layers.density_prior_box(
            f, im, densities=[2], fixed_sizes=[4.0], fixed_ratios=[1.0])
        return [b, v]

    b, v = _run(build, {"f": feat, "im": img})
    assert b.shape == (2, 2, 4, 4)  # density^2 = 4 priors


def test_anchor_generator():
    feat = np.zeros((1, 8, 2, 3), np.float32)

    def build():
        f = layers.data("f", feat.shape, append_batch_size=False)
        a, v = layers.anchor_generator(f, anchor_sizes=[32.0, 64.0],
                                       aspect_ratios=[1.0],
                                       stride=[16.0, 16.0])
        return [a, v]

    a, v = _run(build, {"f": feat})
    assert a.shape == (2, 3, 2, 4)
    # first cell center (0.5*16, 0.5*16) = (8, 8), size 32 -> [-8,-8,24,24]
    np.testing.assert_allclose(a[0, 0, 0], [-8, -8, 24, 24], rtol=1e-5)


def test_box_coder_decode_matches_numpy():
    prior = np.array([[0, 0, 10, 10], [10, 10, 30, 30]], np.float32)
    pvar = np.tile(np.array([[0.1, 0.1, 0.2, 0.2]], np.float32), (2, 1))
    target = np.array([[[0.5, 0.5, 0.1, 0.1], [-0.2, 0.3, 0.0, -0.1]]],
                      np.float32)  # [1, 2, 4]

    def build():
        p = layers.data("p", prior.shape, append_batch_size=False)
        v = layers.data("v", pvar.shape, append_batch_size=False)
        t = layers.data("t", target.shape, append_batch_size=False)
        return [layers.box_coder(p, v, t, code_type="decode_center_size")]

    (out,) = _run(build, {"p": prior, "v": pvar, "t": target})
    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph / 2
    t = target[0]
    cx = pvar[:, 0] * t[:, 0] * pw + pcx
    cy = pvar[:, 1] * t[:, 1] * ph + pcy
    w = np.exp(pvar[:, 2] * t[:, 2]) * pw
    h = np.exp(pvar[:, 3] * t[:, 3]) * ph
    ref = np.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=1)
    np.testing.assert_allclose(out[0], ref, rtol=1e-4)


def test_box_coder_encode_roundtrip():
    """decode(encode(gt)) == gt."""
    prior = np.array([[0, 0, 10, 10]], np.float32)
    gt = np.array([[2, 2, 8, 9]], np.float32)

    def build():
        p = layers.data("p", prior.shape, append_batch_size=False)
        g = layers.data("g", gt.shape, append_batch_size=False)
        enc = layers.box_coder(p, None, g, code_type="encode_center_size")
        dec = layers.box_coder(p, None, enc,
                               code_type="decode_center_size")
        return [enc, dec]

    enc, dec = _run(build, {"p": prior, "g": gt})
    np.testing.assert_allclose(dec.reshape(-1, 4), gt, rtol=1e-4, atol=1e-4)


def test_box_clip():
    x = np.array([[[-5, -5, 40, 40], [2, 2, 8, 8]]], np.float32)
    im_info = np.array([[20, 30, 1.0]], np.float32)

    def build():
        b = layers.data("b", x.shape, append_batch_size=False)
        info = layers.data("i", im_info.shape, append_batch_size=False)
        return [layers.box_clip(b, info)]

    (out,) = _run(build, {"b": x, "i": im_info})
    np.testing.assert_allclose(out[0, 0], [0, 0, 29, 19], rtol=1e-6)
    np.testing.assert_allclose(out[0, 1], [2, 2, 8, 8], rtol=1e-6)


def test_bipartite_match():
    dist = np.array([[0.9, 0.1, 0.3],
                     [0.2, 0.8, 0.4]], np.float32)  # 2 gt x 3 priors

    def build():
        d = layers.data("d", dist.shape, append_batch_size=False)
        idx, dv = layers.bipartite_match(d)
        return [idx, dv]

    idx, dv = _run(build, {"d": dist})
    np.testing.assert_array_equal(idx[0], [0, 1, -1])
    np.testing.assert_allclose(dv[0], [0.9, 0.8, 0.0], rtol=1e-6)


def test_bipartite_match_per_prediction():
    dist = np.array([[0.9, 0.1, 0.7],
                     [0.2, 0.8, 0.6]], np.float32)

    def build():
        d = layers.data("d", dist.shape, append_batch_size=False)
        idx, dv = layers.bipartite_match(d, "per_prediction", 0.5)
        return [idx, dv]

    idx, dv = _run(build, {"d": dist})
    # col 2 unmatched by greedy but best gt 0 has 0.7 > 0.5
    np.testing.assert_array_equal(idx[0], [0, 1, 0])


def test_target_assign():
    gt = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    match = np.array([[0, -1, 1]], np.int32)

    def build():
        g = layers.data("g", gt.shape, append_batch_size=False)
        m = layers.data("m", match.shape, append_batch_size=False,
                        dtype="int32")
        out, w = layers.target_assign(g, m, mismatch_value=0)
        return [out, w]

    out, w = _run(build, {"g": gt, "m": match})
    np.testing.assert_allclose(out[0], [[1, 2], [0, 0], [3, 4]], rtol=1e-6)
    np.testing.assert_allclose(w[0], [[1], [0], [1]], rtol=1e-6)


def test_sigmoid_focal_loss_matches_numpy():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 3).astype(np.float32)
    label = np.array([[1], [0], [3], [2]], np.int32)  # 1-based; 0 = bg
    fg = np.array([2], np.int32)

    def build():
        xv = layers.data("x", x.shape, append_batch_size=False)
        lv = layers.data("l", label.shape, append_batch_size=False,
                         dtype="int32")
        fv = layers.data("f", fg.shape, append_batch_size=False,
                         dtype="int32")
        return [layers.sigmoid_focal_loss(xv, lv, fv)]

    (out,) = _run(build, {"x": x, "l": label, "f": fg})
    p = 1 / (1 + np.exp(-x))
    pos = (label.reshape(-1, 1) == np.arange(1, 4)[None, :])
    gamma, alpha = 2.0, 0.25
    loss = np.where(pos, alpha * (1 - p) ** gamma * -np.log(p),
                    (1 - alpha) * p ** gamma * -np.log(1 - p)) / 2.0
    np.testing.assert_allclose(out, loss, rtol=1e-4, atol=1e-6)


def test_yolo_box_decodes_centers():
    N, A, C, H, W = 1, 1, 2, 2, 2
    x = np.zeros((N, A * (5 + C), H, W), np.float32)
    x[:, 4] = 10.0  # conf sigmoid ~1
    img_size = np.array([[64, 64]], np.int32)

    def build():
        xv = layers.data("x", x.shape, append_batch_size=False)
        sv = layers.data("s", img_size.shape, append_batch_size=False,
                         dtype="int32")
        b, s = layers.yolo_box(xv, sv, anchors=[16, 16], class_num=C,
                               conf_thresh=0.5, downsample_ratio=32)
        return [b, s]

    b, s = _run(build, {"x": x, "s": img_size})
    assert b.shape == (1, A * H * W, 4)
    # tx=ty=0 -> sigmoid 0.5; cell (0,0) center = 0.5/2*64 = 16
    # bw = exp(0)*16/64*64 = 16
    np.testing.assert_allclose(b[0, 0], [8, 8, 24, 24], rtol=1e-5)
    assert s.shape == (1, A * H * W, C)


def test_multiclass_nms_suppresses_and_pads():
    # 3 boxes, 2 heavily overlap; 2 classes (class 0 = background)
    boxes = np.array([[[0, 0, 10, 10], [1, 1, 10.5, 10.5],
                       [20, 20, 30, 30]]], np.float32)
    scores = np.array([[[0.0, 0.0, 0.0],      # background scores
                        [0.9, 0.8, 0.6]]], np.float32)  # class 1 scores

    def build():
        b = layers.data("b", boxes.shape, append_batch_size=False)
        s = layers.data("s", scores.shape, append_batch_size=False)
        return [layers.multiclass_nms(b, s, score_threshold=0.1,
                                      nms_top_k=3, keep_top_k=3,
                                      nms_threshold=0.5)]

    (out,) = _run(build, {"b": boxes, "s": scores})
    assert out.shape == (1, 3, 6)
    labels = out[0, :, 0]
    kept = labels >= 0
    assert kept.sum() == 2  # the overlapping pair collapsed
    np.testing.assert_allclose(out[0, 0, 1], 0.9, rtol=1e-5)
    np.testing.assert_allclose(out[0, 0, 2:], [0, 0, 10, 10], rtol=1e-5)
    assert (out[0, ~kept, 0] == -1).all()  # pad rows


def test_detection_output_runs():
    P = 4
    prior = np.array([[0, 0, .2, .2], [.2, .2, .4, .4],
                      [.4, .4, .6, .6], [.6, .6, .8, .8]], np.float32)
    pvar = np.tile(np.array([[.1, .1, .2, .2]], np.float32), (P, 1))
    loc = np.zeros((1, P, 4), np.float32)
    scores = np.random.RandomState(1).rand(1, 2, P).astype(np.float32)

    def build():
        p = layers.data("p", prior.shape, append_batch_size=False)
        v = layers.data("v", pvar.shape, append_batch_size=False)
        lo = layers.data("lo", loc.shape, append_batch_size=False)
        s = layers.data("s", scores.shape, append_batch_size=False)
        return [layers.detection_output(lo, s, p, v, keep_top_k=4)]

    (out,) = _run(build, {"p": prior, "v": pvar, "lo": loc, "s": scores})
    assert out.shape == (1, 4, 6)


def test_roi_align_uniform_map():
    """On a constant feature map every RoI bin pools that constant."""
    x = np.full((1, 3, 8, 8), 7.0, np.float32)
    rois = np.array([[0, 0, 4, 4], [2, 2, 6, 6]], np.float32)

    def build():
        xv = layers.data("x", x.shape, append_batch_size=False)
        r = layers.data("r", rois.shape, append_batch_size=False)
        return [layers.roi_align(xv, r, pooled_height=2, pooled_width=2,
                                 spatial_scale=1.0, sampling_ratio=2)]

    (out,) = _run(build, {"x": x, "r": rois})
    assert out.shape == (2, 3, 2, 2)
    np.testing.assert_allclose(out, 7.0, rtol=1e-6)


def test_roi_align_gradient_flows():
    """RoIAlign backprops through the bilinear gather into a parameter."""
    x = np.arange(64, dtype=np.float32).reshape(1, 1, 8, 8)
    rois = np.array([[1, 1, 5, 5]], np.float32)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = layers.data("x", x.shape, append_batch_size=False)
        w = layers.create_parameter([1], "float32", name="w_roi",
                                    default_initializer=fluid.initializer.
                                    ConstantInitializer(1.0))
        r = layers.data("r", rois.shape, append_batch_size=False)
        out = layers.roi_align(xv * w, r, 2, 2, 1.0, 2)
        loss = layers.reduce_sum(out)
        grads = fluid.backward.append_backward(loss)
    gmap = {p.name: g for p, g in grads}
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (g,) = exe.run(main, feed={"x": x, "r": rois},
                       fetch_list=[gmap["w_roi"]])
    # d(sum(roi_align(w*x)))/dw = sum(roi_align(x)) -- nonzero on this map
    assert np.asarray(g)[0] > 0

def test_roi_pool_max():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    rois = np.array([[0, 0, 3, 3]], np.float32)

    def build():
        xv = layers.data("x", x.shape, append_batch_size=False)
        r = layers.data("r", rois.shape, append_batch_size=False)
        return [layers.roi_pool(xv, r, pooled_height=2, pooled_width=2)]

    (out,) = _run(build, {"x": x, "r": rois})
    np.testing.assert_allclose(out[0, 0], [[5, 7], [13, 15]], rtol=1e-6)


def test_generate_proposals_shapes():
    N, A, H, W = 1, 2, 4, 4
    rng = np.random.RandomState(2)
    scores = rng.rand(N, A, H, W).astype(np.float32)
    deltas = (rng.randn(N, A * 4, H, W) * 0.1).astype(np.float32)
    im_info = np.array([[64, 64, 1.0]], np.float32)
    anchors = rng.rand(H, W, A, 4).astype(np.float32) * 32
    anchors[..., 2:] += 33  # ensure x1>x0, y1>y0 and min-size pass
    variances = np.full((H, W, A, 4), 1.0, np.float32)

    def build():
        s = layers.data("s", scores.shape, append_batch_size=False)
        d = layers.data("d", deltas.shape, append_batch_size=False)
        i = layers.data("i", im_info.shape, append_batch_size=False)
        a = layers.data("a", anchors.shape, append_batch_size=False)
        v = layers.data("v", variances.shape, append_batch_size=False)
        rois, probs = layers.generate_proposals(
            s, d, i, a, v, pre_nms_top_n=16, post_nms_top_n=8,
            nms_thresh=0.7, min_size=1.0)
        return [rois, probs]

    rois, probs = _run(build, {"s": scores, "d": deltas, "i": im_info,
                               "a": anchors, "v": variances})
    assert rois.shape == (8, 4)
    assert probs.shape == (8, 1)
    assert (rois[:, 2] >= rois[:, 0]).all()


def test_rpn_target_assign_labels():
    anchors = np.array([[0, 0, 10, 10], [0, 0, 1, 1], [20, 20, 30, 30]],
                       np.float32)
    gt = np.array([[0, 0, 10, 10]], np.float32)

    def build():
        a = layers.data("a", anchors.shape, append_batch_size=False)
        g = layers.data("g", gt.shape, append_batch_size=False)
        res = layers.rpn_target_assign(None, None, a, None, g)
        return [res[2], res[3]]

    lbl, tgt = _run(build, {"a": anchors, "g": gt})
    assert lbl[0] == 1          # perfect overlap -> positive
    assert lbl[1] in (0, -1)    # tiny overlap -> negative/ignored
    assert lbl[2] == 0          # no overlap -> negative
    np.testing.assert_allclose(tgt[0], gt[0], rtol=1e-6)


def test_ssd_loss_positive_matching_lowers_loss():
    """Perfect localization must have lower loss than bad localization."""
    P = 2
    prior = np.array([[0, 0, .5, .5], [.5, .5, 1, 1]], np.float32)
    gt = np.array([[[0, 0, .5, .5]]], np.float32)  # matches prior 0
    lab = np.array([[1]], np.int32)
    conf_good = np.array([[[0., 5.], [5., 0.]]], np.float32)
    loc_zero = np.zeros((1, P, 4), np.float32)  # encoded target is 0 here

    def build():
        lo = layers.data("lo", loc_zero.shape, append_batch_size=False)
        c = layers.data("c", conf_good.shape, append_batch_size=False)
        g = layers.data("g", gt.shape, append_batch_size=False)
        lv = layers.data("lv", lab.shape, append_batch_size=False,
                         dtype="int32")
        p = layers.data("p", prior.shape, append_batch_size=False)
        return [layers.ssd_loss(lo, c, g, lv, p)]

    (good,) = _run(build, {"lo": loc_zero, "c": conf_good, "g": gt,
                           "lv": lab, "p": prior})
    bad_loc = np.full((1, P, 4), 3.0, np.float32)
    (bad,) = _run(build, {"lo": bad_loc, "c": conf_good, "g": gt,
                          "lv": lab, "p": prior})
    assert good[0] < bad[0]


def test_yolov3_loss_zero_gt_ignored():
    """All-padding gt must give a loss driven only by objectness negatives,
    and a confident empty prediction should beat a confident full one."""
    N, A, C, H, W = 1, 3, 2, 2, 2
    x_quiet = np.zeros((N, A * (5 + C), H, W), np.float32)
    x_quiet.reshape(N, A, 5 + C, H, W)[:, :, 4] = -10.0  # low objectness
    x_loud = x_quiet.copy()
    x_loud.reshape(N, A, 5 + C, H, W)[:, :, 4] = 10.0
    gt = np.zeros((N, 2, 4), np.float32)
    lab = np.zeros((N, 2), np.int32)

    def build():
        xv = layers.data("x", x_quiet.shape, append_batch_size=False)
        g = layers.data("g", gt.shape, append_batch_size=False)
        lv = layers.data("l", lab.shape, append_batch_size=False,
                         dtype="int32")
        return [layers.yolov3_loss(xv, g, lv,
                                   anchors=[10, 13, 16, 30, 33, 23],
                                   anchor_mask=[0, 1, 2], class_num=C,
                                   ignore_thresh=0.7, downsample_ratio=32)]

    (quiet,) = _run(build, {"x": x_quiet, "g": gt, "l": lab})
    (loud,) = _run(build, {"x": x_loud, "g": gt, "l": lab})
    assert quiet[0] < loud[0]


def test_distribute_and_collect_fpn_proposals():
    rois = np.array([[0, 0, 20, 20],      # small -> low level
                     [0, 0, 500, 500]], np.float32)  # large -> high level
    scores = np.array([0.9, 0.8], np.float32)

    def build():
        r = layers.data("r", rois.shape, append_batch_size=False)
        s = layers.data("s", scores.shape, append_batch_size=False)
        outs, restore = layers.distribute_fpn_proposals(r, 2, 5, 4, 224)
        merged = layers.collect_fpn_proposals(
            list(outs), [s, s, s, s], 2, 5, post_nms_top_n=2)
        return list(outs) + [restore, merged]

    res = _run(build, {"r": rois, "s": scores})
    lv2, lv3, lv4, lv5, restore, merged = res
    np.testing.assert_allclose(lv2[0], rois[0], rtol=1e-6)  # small at lvl2
    np.testing.assert_allclose(lv2[1], 0.0)                  # zeroed slot
    np.testing.assert_allclose(lv5[1], rois[1], rtol=1e-6)  # big at lvl5
    assert merged.shape == (2, 4)


def test_box_decoder_and_assign():
    prior = np.array([[0, 0, 10, 10]], np.float32)
    pvar = np.array([[1, 1, 1, 1]], np.float32)
    target = np.zeros((1, 8), np.float32)  # 2 classes x 4
    score = np.array([[0.2, 0.8]], np.float32)

    def build():
        p = layers.data("p", prior.shape, append_batch_size=False)
        v = layers.data("v", pvar.shape, append_batch_size=False)
        t = layers.data("t", target.shape, append_batch_size=False)
        s = layers.data("s", score.shape, append_batch_size=False)
        d, a = layers.box_decoder_and_assign(p, v, t, s)
        return [d, a]

    d, a = _run(build, {"p": prior, "v": pvar, "t": target, "s": score})
    assert d.shape == (1, 8)
    # zero deltas decode back to the prior (pixel convention: pw = 11,
    # cx = 5.5, x1 = cx + pw/2 - 1 = 10)
    np.testing.assert_allclose(a[0], [0, 0, 10, 10], rtol=1e-5)


def test_polygon_box_transform():
    x = np.ones((1, 8, 2, 2), np.float32)

    def build():
        xv = layers.data("x", x.shape, append_batch_size=False)
        return [layers.polygon_box_transform(xv)]

    (out,) = _run(build, {"x": x})
    # channel 0 is an x-coordinate: out = 4*j - x
    np.testing.assert_allclose(out[0, 0], [[-1, 3], [-1, 3]], rtol=1e-6)
    # channel 1 is a y-coordinate: out = 4*i - x
    np.testing.assert_allclose(out[0, 1], [[-1, -1], [3, 3]], rtol=1e-6)


def test_multi_box_head_shapes():
    img = np.zeros((2, 3, 32, 32), np.float32)
    f1 = np.zeros((2, 8, 4, 4), np.float32)
    f2 = np.zeros((2, 8, 2, 2), np.float32)

    def build():
        im = layers.data("im", img.shape, append_batch_size=False)
        a = layers.data("f1", f1.shape, append_batch_size=False)
        b = layers.data("f2", f2.shape, append_batch_size=False)
        locs, confs, boxes, vars_ = layers.multi_box_head(
            [a, b], im, base_size=32, num_classes=3,
            aspect_ratios=[[2.0], [2.0]], min_ratio=20, max_ratio=90,
            flip=True)
        return [locs, confs, boxes, vars_]

    locs, confs, boxes, vars_ = _run(build, {"im": img, "f1": f1, "f2": f2})
    n_priors_per_cell = 1 + 2 + 1  # ar1 + (ar2, flip) + max size
    total = (16 + 4) * n_priors_per_cell
    assert locs.shape == (2, total, 4)
    assert confs.shape == (2, total, 3)
    assert boxes.shape == (total, 4)
    assert vars_.shape == (total, 4)


def test_retinanet_detection_output_runs():
    b1 = np.random.RandomState(3).rand(1, 4, 4).astype(np.float32) * 10
    b1[..., 2:] += 10
    s1 = np.random.RandomState(4).rand(1, 4, 3).astype(np.float32)

    def build():
        b = layers.data("b", b1.shape, append_batch_size=False)
        s = layers.data("s", s1.shape, append_batch_size=False)
        im = layers.data("im", [1, 3], append_batch_size=False)
        return [layers.retinanet_detection_output(
            [b], [s], im, keep_top_k=4)]

    (out,) = _run(build, {"b": b1, "s": s1,
                          "im": np.array([[32, 32, 1]], np.float32)})
    assert out.shape == (1, 4, 6)


def test_box_clip_batched():
    """Per-image bounds must broadcast over the box axis (N=2, M=3)."""
    x = np.tile(np.array([[[-5, -5, 40, 40], [2, 2, 8, 8],
                           [0, 0, 100, 100]]], np.float32), (2, 1, 1))
    im_info = np.array([[20, 30, 1.0], [50, 60, 1.0]], np.float32)

    def build():
        b = layers.data("b", x.shape, append_batch_size=False)
        info = layers.data("i", im_info.shape, append_batch_size=False)
        return [layers.box_clip(b, info)]

    (out,) = _run(build, {"b": x, "i": im_info})
    np.testing.assert_allclose(out[0, 0], [0, 0, 29, 19], rtol=1e-6)
    np.testing.assert_allclose(out[1, 0], [0, 0, 40, 40], rtol=1e-6)
    np.testing.assert_allclose(out[1, 2], [0, 0, 59, 49], rtol=1e-6)


def test_roi_align_rois_num_is_per_image_count():
    """RoisNum [N] holds counts; roi r maps to the covering image."""
    x = np.stack([np.full((1, 4, 4), 1.0, np.float32),
                  np.full((1, 4, 4), 9.0, np.float32)])  # [2, 1, 4, 4]
    rois = np.array([[0, 0, 3, 3], [0, 0, 2, 2], [1, 1, 3, 3]], np.float32)
    counts = np.array([2, 1], np.int32)  # rois 0-1 -> img 0, roi 2 -> img 1

    def build():
        xv = layers.data("x", x.shape, append_batch_size=False)
        r = layers.data("r", rois.shape, append_batch_size=False)
        n = layers.data("n", counts.shape, append_batch_size=False,
                        dtype="int32")
        return [layers.roi_align(xv, r, 1, 1, 1.0, 2, rois_num=n)]

    (out,) = _run(build, {"x": x, "r": rois, "n": counts})
    np.testing.assert_allclose(out[:, 0, 0, 0], [1.0, 1.0, 9.0], rtol=1e-6)


def test_rpn_target_assign_positive_weight_survives_bg_fill():
    """When there are fewer negatives than the bg quota, top_k filler
    indices must not zero out a positive anchor's sampling weight."""
    anchors = np.array([[0, 0, 10, 10], [0, 0, 1, 1], [20, 20, 30, 30]],
                       np.float32)
    gt = np.array([[0, 0, 10, 10]], np.float32)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = layers.data("a", anchors.shape, append_batch_size=False)
        g = layers.data("g", gt.shape, append_batch_size=False)
        helper = fluid.layer_helper.LayerHelper("rpn_target_assign")
        outs = {k: helper.create_variable_for_type_inference(
            "int32" if k in ("LocationIndex", "ScoreIndex", "TargetLabel")
            else "float32") for k in
            ("LocationIndex", "ScoreIndex", "TargetLabel", "TargetBBox",
             "BBoxInsideWeight", "ScoreWeight")}
        helper.append_op(
            type="rpn_target_assign",
            inputs={"Anchor": [a], "GtBoxes": [g]},
            outputs={k: [v] for k, v in outs.items()},
            attrs={"rpn_batch_size_per_im": 256, "rpn_fg_fraction": 0.5,
                   "rpn_positive_overlap": 0.7,
                   "rpn_negative_overlap": 0.3})
        fetch = [outs["ScoreWeight"], outs["BBoxInsideWeight"]]
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        sw, bw = [np.asarray(r) for r in
                  exe.run(main, feed={"a": anchors, "g": gt},
                          fetch_list=fetch)]
    assert sw[0] == 1.0          # the positive anchor stays sampled
    np.testing.assert_allclose(bw[0], 1.0, rtol=1e-6)


def test_box_coder_list_variance():
    """A 4-float list prior_box_var must ride through as the variance."""
    prior = np.array([[0, 0, 10, 10]], np.float32)
    target = np.array([[[1.0, 0.0, 0.0, 0.0]]], np.float32)

    def build():
        p = layers.data("p", prior.shape, append_batch_size=False)
        t = layers.data("t", target.shape, append_batch_size=False)
        return [layers.box_coder(p, [0.1, 0.1, 0.2, 0.2], t,
                                 code_type="decode_center_size")]

    (out,) = _run(build, {"p": prior, "t": target})
    # cx = 0.1 * 1.0 * 10 + 5 = 6 (not 15 as with variance 1.0)
    np.testing.assert_allclose(out[0, 0, 0], 6 - 5, rtol=1e-5)  # x0 = cx-w/2


def test_box_coder_axis1():
    """axis=1: priors align with target dim 0 (one prior per row)."""
    prior = np.array([[0, 0, 10, 10], [0, 0, 20, 20]], np.float32)
    target = np.zeros((2, 3, 4), np.float32)  # M=3 != N=2

    def build():
        p = layers.data("p", prior.shape, append_batch_size=False)
        t = layers.data("t", target.shape, append_batch_size=False)
        return [layers.box_coder(p, None, t,
                                 code_type="decode_center_size", axis=1)]

    (out,) = _run(build, {"p": prior, "t": target})
    assert out.shape == (2, 3, 4)
    np.testing.assert_allclose(out[0, 0], [0, 0, 10, 10], rtol=1e-5)
    np.testing.assert_allclose(out[1, 0], [0, 0, 20, 20], rtol=1e-5)


def test_multiclass_nms_return_index():
    boxes = np.array([[[0, 0, 10, 10], [20, 20, 30, 30]]], np.float32)
    scores = np.array([[[0.0, 0.0], [0.3, 0.9]]], np.float32)

    def build():
        b = layers.data("b", boxes.shape, append_batch_size=False)
        s = layers.data("s", scores.shape, append_batch_size=False)
        out, idx = layers.multiclass_nms(b, s, 0.1, 2, 2,
                                         return_index=True)
        return [out, idx]

    out, idx = _run(build, {"b": boxes, "s": scores})
    assert idx.shape == (1, 2)
    assert idx[0, 0] == 1  # highest score is box 1
    assert idx[0, 1] == 0


def test_generate_proposal_labels_per_roi():
    """Labels/targets must be per-ROI (not per-gt)."""
    rois = np.array([[0, 0, 10, 10], [0, 0, 2, 2], [20, 20, 30, 30],
                     [21, 21, 29, 29]], np.float32)
    gt_boxes = np.array([[0, 0, 10, 10], [20, 20, 30, 30]], np.float32)
    gt_classes = np.array([[3.0], [5.0]], np.float32)
    im_info = np.array([[40, 40, 1.0]], np.float32)

    def build():
        r = layers.data("r", rois.shape, append_batch_size=False)
        g = layers.data("g", gt_boxes.shape, append_batch_size=False)
        c = layers.data("c", gt_classes.shape, append_batch_size=False)
        i = layers.data("i", im_info.shape, append_batch_size=False)
        res = layers.generate_proposal_labels(r, c, None, g, i)
        return [res[1], res[2]]

    labels, tgts = _run(build, {"r": rois, "g": gt_boxes, "c": gt_classes,
                                "i": im_info})
    assert labels.shape[1] == rois.shape[0]   # one label per roi
    assert labels[0, 0, 0] == 3.0             # roi 0 matches gt 0
    assert labels[0, 2, 0] == 5.0             # roi 2 matches gt 1
