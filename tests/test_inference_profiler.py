"""Inference predictor + profiler + op-attributed errors — reference
``inference/api/analysis_predictor.h:47``, ``fluid/profiler.py:228``,
``framework/op_call_stack.cc``.
"""

import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import inference
from paddle_tpu.fluid import layers, optimizer, profiler


def _train_and_save(tmpdir, seed=5):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[6], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(x, size=16, act="relu")
        logits = layers.fc(h, size=3)
        prob = layers.softmax(logits)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor()
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(8, 6).astype(np.float32),
            "label": rng.randint(0, 3, (8, 1)).astype(np.int64)}
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(3):
            exe.run(main, feed=feed, fetch_list=[loss])
        fluid.io.save_inference_model(str(tmpdir), ["x"], [prob], exe,
                                      main_program=main)
        # reference output from the full program (needs both feeds; the
        # pruned inference program needs only x)
        expect, _ = exe.run(main, feed=feed, fetch_list=[prob, loss])
    return np.asarray(expect), feed["x"]


def test_predictor_serves_saved_model(tmp_path):
    expect, xv = _train_and_save(tmp_path)
    config = inference.Config(str(tmp_path))
    predictor = inference.create_predictor(config)
    assert predictor.get_input_names() == ["x"]
    outs = predictor.run({"x": xv})
    np.testing.assert_allclose(np.asarray(outs[0]), expect, rtol=1e-5)
    # handle-style API
    h = predictor.get_input_handle("x")
    h.copy_from_cpu(xv)
    predictor.run()
    out_name = predictor.get_output_names()[0]
    np.testing.assert_allclose(
        predictor.get_output_handle(out_name).copy_to_cpu(), expect,
        rtol=1e-5)


def test_predictor_clone_shares_weights(tmp_path):
    expect, xv = _train_and_save(tmp_path, seed=6)
    p1 = inference.Predictor(inference.Config(str(tmp_path)))
    p2 = p1.clone()
    assert p2._scope is p1._scope
    np.testing.assert_allclose(np.asarray(p2.run({"x": xv})[0]),
                               np.asarray(p1.run({"x": xv})[0]), rtol=1e-6)


def test_predictor_bf16_mode(tmp_path):
    expect, xv = _train_and_save(tmp_path, seed=7)
    config = inference.Config(str(tmp_path))
    config.enable_bf16()
    p = inference.create_predictor(config)
    out = np.asarray(p.run({"x": xv})[0], np.float32)
    # bf16 weights: close but not bit-equal
    np.testing.assert_allclose(out, expect, rtol=0.05, atol=0.02)


def test_predictor_pool(tmp_path):
    _train_and_save(tmp_path, seed=8)
    pool = inference.PredictorPool(inference.Config(str(tmp_path)), size=3)
    assert pool.retrieve(0)._scope is pool.retrieve(2)._scope


def test_predictor_missing_feed_raises(tmp_path):
    _train_and_save(tmp_path, seed=9)
    p = inference.create_predictor(inference.Config(str(tmp_path)))
    with pytest.raises(ValueError, match="missing inference feeds"):
        p.run({})


def test_profiler_table_and_events(tmp_path, capsys):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 1
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        loss = layers.mean(layers.fc(x, size=8))
        optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor()
    feed = {"x": np.ones((4, 4), np.float32)}
    path = str(tmp_path / "profile.txt")
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        with profiler.profiler(sorted_key="total", profile_path=path):
            for _ in range(3):
                exe.run(main, feed=feed, fetch_list=[loss])
            with profiler.RecordEvent("my_section"):
                pass
    report = open(path).read()
    assert "Profiling Report" in report
    assert "executor_run" in report and "my_section" in report
    # 3 (+1 startup? startup ran outside) executor_run calls recorded
    line = next(l for l in report.splitlines() if "executor_run" in l)
    assert " 3 " in line


def test_op_attributed_error_names_call_site():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.data("y", shape=[5], dtype="float32")
        # shape-incompatible add -> the lowering must fail WITH attribution
        bad = layers.elementwise_add(x, y)   # <-- creation site
        loss = layers.mean(bad)
    exe = fluid.Executor()
    from paddle_tpu.fluid.registry import EnforceError

    with fluid.scope_guard(fluid.Scope()):
        with pytest.raises(EnforceError) as ei:
            exe.run(main, feed={"x": np.ones((2, 4), np.float32),
                                "y": np.ones((2, 5), np.float32)},
                    fetch_list=[loss])
    msg = str(ei.value)
    assert "elementwise_add" in msg
    assert "test_inference_profiler.py" in msg  # the user call site
    assert "created at" in msg


def test_callstack_recording_can_be_disabled():
    fluid.record_op_callstacks(False)
    try:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[4], dtype="float32")
            y = layers.fc(x, size=2)
        op = main.global_block().ops[-1]
        assert op.callstack is None
    finally:
        fluid.record_op_callstacks(True)


def test_predictor_clone_keeps_bf16(tmp_path):
    """clone() must not reload fp32 weights over the bf16-cast scope."""
    _train_and_save(tmp_path, seed=10)
    config = inference.Config(str(tmp_path))
    config.enable_bf16()
    p1 = inference.create_predictor(config)
    p2 = p1.clone()
    import jax.numpy as jnp

    dtypes = {np.dtype(getattr(v, "dtype", np.float32))
              for v in p2._scope.vars.values()
              if hasattr(v, "dtype")}
    assert np.dtype(jnp.bfloat16) in dtypes, dtypes
    assert np.float32 not in dtypes


def test_sub_block_op_error_attributed():
    """A failure INSIDE a cond sub-block must name the inner op."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[3], dtype="float32")
        y = layers.data("y", shape=[4], dtype="float32")
        pred = layers.less_than(layers.reduce_sum(x), layers.reduce_sum(y))

        def bad_branch():
            return layers.elementwise_add(x, y)  # shape mismatch

        def ok_branch():
            return layers.scale(x, scale=2.0)

        out = layers.cond(pred, bad_branch, ok_branch)
    exe = fluid.Executor()
    from paddle_tpu.fluid.registry import EnforceError

    with fluid.scope_guard(fluid.Scope()):
        with pytest.raises(EnforceError) as ei:
            exe.run(main, feed={"x": np.ones((2, 3), np.float32),
                                "y": np.ones((2, 4), np.float32)},
                    fetch_list=[out])
    assert "elementwise_add" in str(ei.value)


def test_chrome_timeline_export(tmp_path):
    """stop_profiler(timeline_path=...) writes chrome://tracing JSON with
    the host spans (reference tools/timeline.py output shape)."""
    import json

    from paddle_tpu.fluid import profiler

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("tl_x", [4, 4], append_batch_size=False)
        y = layers.reduce_sum(layers.square(x))
    exe = fluid.Executor()
    path = str(tmp_path / "timeline.json")
    with fluid.scope_guard(fluid.Scope()):
        with profiler.profiler(timeline_path=path):
            for _ in range(2):
                with profiler.record_event("tl_section"):
                    exe.run(main, feed={"tl_x": np.ones((4, 4), np.float32)},
                            fetch_list=[y])
    doc = json.load(open(path))
    evts = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    names = {e["name"] for e in evts}
    assert any("tl_section" in n for n in names)
    assert any("executor_run" in n for n in names)
    assert all(e["dur"] >= 0 and e["ts"] >= 0 for e in evts)


def test_profiler_counts_dropped_spans(tmp_path, monkeypatch):
    """Past _MAX_SPANS the span buffer stops recording — the drop count
    must be surfaced (monitor counter + chrome-trace meta event), not
    silently truncated."""
    import json

    from paddle_tpu.fluid import monitor

    monkeypatch.setattr(profiler, "_MAX_SPANS", 2)
    monitor.reset()
    profiler.reset_profiler()
    profiler.start_profiler()
    for i in range(5):
        with profiler.RecordEvent("burst"):
            pass
    profiler.stop_profiler(silent=True)
    assert profiler.dropped_span_count() == 3
    assert monitor.counter("profiler_dropped_spans_total").value == 3
    path = str(tmp_path / "trunc.json")
    profiler.export_chrome_tracing(path)
    doc = json.load(open(path))
    (meta,) = [e for e in doc["traceEvents"]
               if e.get("name") == "dropped_spans"]
    assert meta["args"]["count"] == 3
    # the summary still aggregates ALL 5 calls (only the timeline drops)
    assert profiler._events["burst"][0] == 5
    profiler.reset_profiler()
    assert profiler.dropped_span_count() == 0


def test_record_events_visible_as_monitor_histograms():
    """RecordEvent totals are unified into the monitor registry: one
    profiler_event_seconds series per event name."""
    from paddle_tpu.fluid import monitor

    monitor.reset()
    profiler.reset_profiler()
    profiler.start_profiler()
    for _ in range(4):
        with profiler.RecordEvent("mon_unified"):
            pass
    profiler.stop_profiler(silent=True)
    h = monitor.get_metric("profiler_event_seconds",
                           labels={"event": "mon_unified"})
    assert h is not None and h.count == 4
    assert 'event="mon_unified"' in monitor.dump_prometheus()


def test_run_event_names_distinguish_programs():
    """Two programs with IDENTICAL fetch names must not collide in the
    profiler table (the event name carries a #p<uid> suffix)."""

    def build():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup), fluid.unique_name.guard():
            x = layers.data("px", shape=[4], dtype="float32")
            y = layers.mean(layers.scale(x, scale=2.0))
        return main, startup, y

    main_a, startup_a, ya = build()
    main_b, startup_b, yb = build()
    assert ya.name == yb.name  # the old fetch_names[:3] key collided
    exe = fluid.Executor()
    feed = {"px": np.ones((2, 4), np.float32)}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup_a)
        exe.run(startup_b)
        profiler.reset_profiler()
        profiler.start_profiler()
        exe.run(main_a, feed=feed, fetch_list=[ya])
        exe.run(main_b, feed=feed, fetch_list=[yb])
        profiler.stop_profiler(silent=True)
    run_events = [n for n in profiler._events if n.startswith("executor_run")]
    assert len(run_events) == 2
    assert all("#p" in n for n in run_events)
    profiler.reset_profiler()


def test_predictor_monitor_latency_and_shape_recompiles(tmp_path):
    """Every Predictor.run lands in the latency histogram; a NEW input
    shape signature counts as a recompile."""
    from paddle_tpu.fluid import monitor

    try:
        _, xv = _train_and_save(tmp_path, seed=11)
        p = inference.create_predictor(inference.Config(str(tmp_path)))
    except OSError as e:  # pre-existing: native tensor_io .so unloadable
        pytest.skip("native lib unavailable: %s" % e)
    monitor.reset()
    p.run({"x": xv})
    p.run({"x": xv})            # same signature: no recompile
    p.run({"x": xv[:4]})        # new batch shape: recompile
    assert monitor.counter("predictor_runs_total").value == 3
    assert monitor.get_metric("predictor_run_seconds").count == 3
    assert monitor.counter("predictor_shape_recompile_total").value == 1


def test_dygraph_gperf_routes_through_shared_profiler(tmp_path,
                                                      monkeypatch):
    """dygraph start/stop_gperf_profiler is no longer a stub: it drives
    the shared fluid profiler (host spans + monitor counters)."""
    from paddle_tpu.fluid import monitor
    from paddle_tpu.fluid.dygraph import profiler as dyprof

    monkeypatch.setenv("PADDLE_TPU_GPERF_DIR", str(tmp_path / "gp"))
    monitor.reset()
    profiler.reset_profiler()
    dyprof.start_gperf_profiler()
    assert profiler.is_profiler_enabled()
    with profiler.RecordEvent("dy_section"):
        pass
    dyprof.stop_gperf_profiler()
    assert not profiler.is_profiler_enabled()
    assert "dy_section" in profiler._events
    assert monitor.counter("dygraph_profiler_sessions_total").value == 1
    dyprof.stop_gperf_profiler()  # idempotent
    assert monitor.counter("dygraph_profiler_sessions_total").value == 1
    profiler.reset_profiler()


def test_predictor_pool_validates_size(tmp_path):
    _train_and_save(tmp_path, seed=12)
    config = inference.Config(str(tmp_path))
    with pytest.raises(ValueError, match="size must be >= 1"):
        inference.PredictorPool(config, size=0)
    pool = inference.PredictorPool(config, size=2)
    assert len(pool) == 2
    with pytest.raises(IndexError, match="holds 2 predictor"):
        pool.retrieve(2)


def test_predictor_clone_compile_cache_independent(tmp_path):
    """clone() shares weights but NOT the seen-signature set: the clone
    serving a brand-new shape must not count as a recompile against the
    source (each predictor's first signature is its initial compile)."""
    from paddle_tpu.fluid import monitor

    _, xv = _train_and_save(tmp_path, seed=13)
    p1 = inference.Predictor(inference.Config(str(tmp_path)))
    p2 = p1.clone()
    monitor.reset()
    p1.run({"x": xv})        # p1's first signature: initial compile
    p2.run({"x": xv[:2]})    # p2's first signature — NOT a recompile
    p1.run({"x": xv})        # repeat signature on p1
    assert monitor.counter("predictor_shape_recompile_total").value == 0
    p1.run({"x": xv[:2]})    # new shape for p1 (even though p2 saw it)
    assert monitor.counter("predictor_shape_recompile_total").value == 1


def test_tensor_handle_roundtrip_and_unrun_error(tmp_path):
    expect, xv = _train_and_save(tmp_path, seed=14)
    p = inference.create_predictor(inference.Config(str(tmp_path)))
    out_name = p.get_output_names()[0]
    with pytest.raises(RuntimeError, match="run\\(\\) has not been called"):
        p.get_output_handle(out_name).copy_to_cpu()
    h = p.get_input_handle("x")
    h.copy_from_cpu(xv)
    p.run()
    np.testing.assert_allclose(
        p.get_output_handle(out_name).copy_to_cpu(), expect, rtol=1e-5)
    # staged inputs are consumed by the run: a second handle-fed run
    # must demand fresh copy_from_cpu instead of silently reusing them
    with pytest.raises(ValueError, match="missing inference feeds"):
        p.run()


def test_bf16_cast_counter(tmp_path):
    """enable_bf16 is observable: one counter tick per f32 param cast
    (two fc layers -> 2 weights + 2 biases)."""
    from paddle_tpu.fluid import monitor

    _train_and_save(tmp_path, seed=15)
    config = inference.Config(str(tmp_path))
    config.enable_bf16()
    monitor.reset()
    p = inference.create_predictor(config)
    assert monitor.counter("predictor_bf16_cast_total").value == 4
    import jax.numpy as jnp

    assert all(np.dtype(v.dtype) == np.dtype(jnp.bfloat16)
               for v in p._scope.vars.values() if hasattr(v, "dtype"))


def test_dropout_inference_scales_by_exact_keep():
    """downgrade_in_infer inference multiplies by EXACT 1-p (reference
    checkpoint parity) while training folds the realized-keep correction
    in, so E[train] == E[test] stays true (ADVICE r3 #3)."""
    p = 0.37   # keep=0.63 -> thresh 161/256 = 0.62890625 != keep
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("do_x", [512, 64], append_batch_size=False)
        te = layers.dropout(x, p, is_test=True,
                            dropout_implementation="downgrade_in_infer")
        tr = layers.dropout(x, p, is_test=False,
                            dropout_implementation="downgrade_in_infer")
    exe = fluid.Executor()
    xv = np.ones((512, 64), np.float32)
    with fluid.scope_guard(fluid.Scope()):
        tev, trv = exe.run(main, feed={"do_x": xv}, fetch_list=[te, tr])
    np.testing.assert_allclose(np.asarray(tev), xv * (1 - p), rtol=1e-6)
    # train-mode kept cells carry keep/realized, so the mean matches the
    # inference scale despite the 1/256 mask grid
    np.testing.assert_allclose(np.asarray(trv).mean(), 1 - p, rtol=0.02)
    kept = np.asarray(trv)[np.asarray(trv) > 0]
    np.testing.assert_allclose(kept, kept[0], rtol=1e-6)  # uniform scale
    np.testing.assert_allclose(kept[0], (1 - p) / (161 / 256.0), rtol=1e-5)
