"""RNN / beam-search family — reference ``layers/rnn.py`` (15 fns),
``lstm_op.cc`` / ``gru_op.cc`` gate equations, ``beam_search_op.cc``,
``gather_tree_op.cc``. Numpy-referenced per SURVEY §4.
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, optimizer


def _np_lstm(gates, lens, w, b, H, peep=True):
    """Reference LSTM recurrence (lstm_kernel.h): gates order c~, i, f, o."""
    sig = lambda v: 1 / (1 + np.exp(-v))
    flat = b.reshape(-1)
    bias = flat[:4 * H]
    cI, cF, cO = ((flat[4 * H:5 * H], flat[5 * H:6 * H], flat[6 * H:7 * H])
                  if peep and flat.shape[0] >= 7 * H
                  else (np.zeros(H),) * 3)
    outs = np.zeros((gates.shape[0], H), np.float32)
    cells = np.zeros_like(outs)
    start = 0
    for L in lens:
        h = np.zeros(H, np.float32)
        c = np.zeros(H, np.float32)
        for t in range(L):
            g = gates[start + t] + bias + h @ w
            cand = np.tanh(g[:H])
            i = sig(g[H:2 * H] + c * cI)
            f = sig(g[2 * H:3 * H] + c * cF)
            c = cand * i + c * f
            o = sig(g[3 * H:] + c * cO)
            h = o * np.tanh(c)
            outs[start + t] = h
            cells[start + t] = c
        start += L
    return outs, cells


def test_dynamic_lstm_matches_numpy():
    H, lens = 4, [3, 2]
    total = sum(lens)
    rng = np.random.RandomState(0)
    gates_in = rng.randn(total, 4 * H).astype(np.float32) * 0.5
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 1
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4 * H], dtype="float32", lod_level=1)
        hidden, cell = layers.dynamic_lstm(x, size=4 * H, use_peepholes=True)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        hv, cv = exe.run(main, feed={
            "x": fluid.create_lod_tensor(gates_in, [lens])},
            fetch_list=[hidden, cell])
        scope = fluid.global_scope()
        w = np.asarray(scope.find_var(
            main.global_block().ops[0].input("Weight")[0]))
        b = np.asarray(scope.find_var(
            main.global_block().ops[0].input("Bias")[0]))
    ref_h, ref_c = _np_lstm(gates_in, lens, w, b, H)
    np.testing.assert_allclose(np.asarray(hv), ref_h, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(cv), ref_c, rtol=1e-4, atol=1e-5)


def test_dynamic_lstm_reverse_runs():
    H = 3
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4 * H], dtype="float32", lod_level=1)
        hidden, _ = layers.dynamic_lstm(x, size=4 * H, is_reverse=True)
    v = np.random.RandomState(1).randn(5, 4 * H).astype(np.float32)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (hv,) = exe.run(main, feed={
            "x": fluid.create_lod_tensor(v, [[3, 2]])}, fetch_list=[hidden])
    assert np.asarray(hv).shape == (5, H)
    assert np.isfinite(np.asarray(hv)).all()


def test_dynamic_gru_matches_numpy():
    H, lens = 3, [2, 3]
    total = sum(lens)
    rng = np.random.RandomState(2)
    gin = rng.randn(total, 3 * H).astype(np.float32) * 0.5
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[3 * H], dtype="float32", lod_level=1)
        hidden = layers.dynamic_gru(x, size=H)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (hv,) = exe.run(main, feed={
            "x": fluid.create_lod_tensor(gin, [lens])}, fetch_list=[hidden])
        scope = fluid.global_scope()
        op = main.global_block().ops[0]
        w = np.asarray(scope.find_var(op.input("Weight")[0]))
        b = np.asarray(scope.find_var(op.input("Bias")[0])).reshape(-1)
    sig = lambda v: 1 / (1 + np.exp(-v))
    ref = np.zeros((total, H), np.float32)
    start = 0
    for L in lens:
        h = np.zeros(H, np.float32)
        for t in range(L):
            g = gin[start + t] + b
            ur = sig(g[:2 * H] + h @ w[:, :2 * H])
            u, r = ur[:H], ur[H:]
            cand = np.tanh(g[2 * H:] + (r * h) @ w[:, 2 * H:])
            h = (1 - u) * h + u * cand
            ref[start + t] = h
        start += L
    np.testing.assert_allclose(np.asarray(hv), ref, rtol=1e-4, atol=1e-5)


def test_lstm_unit_and_gru_unit_step():
    B, H = 2, 3
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 4
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[5], dtype="float32")
        h0 = layers.data("h0", shape=[H], dtype="float32")
        c0 = layers.data("c0", shape=[H], dtype="float32")
        h1, c1 = layers.lstm_unit(x, h0, c0, forget_bias=1.0)
        g = layers.fc(x, size=3 * H, bias_attr=False)
        h2, _, _ = layers.gru_unit(g, h0, 3 * H)
    rng = np.random.RandomState(5)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        rh, rc, rg = exe.run(main, feed={
            "x": rng.randn(B, 5).astype(np.float32),
            "h0": rng.randn(B, H).astype(np.float32),
            "c0": rng.randn(B, H).astype(np.float32)},
            fetch_list=[h1, c1, h2])
    for r in (rh, rc, rg):
        assert np.asarray(r).shape == (B, H)
        assert np.isfinite(np.asarray(r)).all()


def test_cudnn_style_lstm():
    T, B, I, H, L = 4, 2, 3, 5, 2
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 6
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[T, B, I], dtype="float32",
                        append_batch_size=False)
        ih = layers.data("ih", shape=[L, B, H], dtype="float32",
                         append_batch_size=False)
        ic = layers.data("ic", shape=[L, B, H], dtype="float32",
                         append_batch_size=False)
        out, lh, lc = layers.lstm(x, ih, ic, T, H, L)
    rng = np.random.RandomState(7)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        ov, hv, cv = exe.run(main, feed={
            "x": rng.randn(T, B, I).astype(np.float32),
            "ih": np.zeros((L, B, H), np.float32),
            "ic": np.zeros((L, B, H), np.float32)},
            fetch_list=[out, lh, lc])
    assert np.asarray(ov).shape == (T, B, H)
    assert np.asarray(hv).shape == (L, B, H)
    # last output of top layer == last hidden of top layer
    np.testing.assert_allclose(np.asarray(ov)[-1], np.asarray(hv)[-1],
                               rtol=1e-5)


def test_rnn_cell_unroll_with_mask():
    """rnn() over GRUCell: states freeze past sequence_length."""
    B, T, I, H = 3, 4, 2, 5
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 8
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[T, I], dtype="float32")
        sl = layers.data("sl", shape=[], dtype="int64")
        cell = layers.GRUCell(hidden_size=H)
        outs, final = layers.rnn(cell, x, sequence_length=sl)
    rng = np.random.RandomState(9)
    xv = rng.randn(B, T, I).astype(np.float32)
    slv = np.array([4, 2, 1], np.int64)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        ov, fv = exe.run(main, feed={"x": xv, "sl": slv},
                         fetch_list=[outs, final])
    ov, fv = np.asarray(ov), np.asarray(fv)
    assert ov.shape == (B, T, H)
    # row 1 finished at t=2: final state equals output at t=1
    np.testing.assert_allclose(fv[1], ov[1, 1], rtol=1e-5)
    np.testing.assert_allclose(fv[2], ov[2, 0], rtol=1e-5)
    np.testing.assert_allclose(fv[0], ov[0, 3], rtol=1e-5)


def test_lstm_cell_rnn_trains():
    B, T, I, H = 2, 3, 4, 6
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 10
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[T, I], dtype="float32")
        label = layers.data("label", shape=[1], dtype="float32")
        cell = layers.LSTMCell(hidden_size=H)
        outs, (h, c) = layers.rnn(cell, x)
        pred = layers.fc(h, size=1)
        loss = layers.mean(layers.square_error_cost(pred, label))
        optimizer.Adam(0.01).minimize(loss)
    rng = np.random.RandomState(11)
    feed = {"x": rng.randn(B, T, I).astype(np.float32),
            "label": rng.rand(B, 1).astype(np.float32)}
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        losses = [float(np.asarray(exe.run(main, feed=feed,
                                           fetch_list=[loss])[0]))
                  for _ in range(6)]
    assert losses[-1] < losses[0]


def test_beam_search_op_dense():
    """2 batches x beam 2, V=4: hand-checked candidate selection incl. a
    finished beam that must keep its (end_id, score) slot."""
    beam, V, end_id = 2, 4, 3
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        pre_ids = layers.data("pre_ids", shape=[1], dtype="int64")
        pre_scores = layers.data("pre_scores", shape=[1], dtype="float32")
        scores = layers.data("scores", shape=[V], dtype="float32")
        sid, ssc, par = layers.beam_search(
            pre_ids, pre_scores, None, scores, beam_size=beam,
            end_id=end_id, is_accumulated=True)
    # batch 0: beams alive; batch 1: beam 0 finished (pre_id == end)
    pid = np.array([[0], [1], [end_id], [2]], np.int64)
    psc = np.array([[0.5], [0.1], [9.0], [0.2]], np.float32)
    sc = np.array([
        [1.0, 2.0, 3.0, 0.1],    # b0 beam0
        [0.2, 4.0, 0.1, 0.1],    # b0 beam1
        [5.0, 5.0, 5.0, 5.0],    # b1 beam0 (finished -> only end_id @ 9.0)
        [1.5, 0.3, 0.1, 0.2],    # b1 beam1
    ], np.float32)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        ids, scs, parents = exe.run(
            main, feed={"pre_ids": pid, "pre_scores": psc, "scores": sc},
            fetch_list=[sid, ssc, par])
    ids = np.asarray(ids).ravel().tolist()
    scs = np.asarray(scs).ravel().tolist()
    parents = np.asarray(parents).ravel().tolist()
    # batch 0 top2 over [row0, row1]: 4.0 (row1,tok1), 3.0 (row0,tok2)
    assert ids[:2] == [1, 2] and parents[:2] == [1, 0]
    np.testing.assert_allclose(scs[:2], [4.0, 3.0], rtol=1e-6)
    # batch 1: finished beam keeps end_id@9.0; next best 1.5 (row3,tok0)
    assert ids[2:] == [end_id, 0] and parents[2:] == [2, 3]
    np.testing.assert_allclose(scs[2:], [9.0, 1.5], rtol=1e-6)


def test_gather_tree():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[2, 4], dtype="int64",
                          append_batch_size=False)
        parents = layers.data("par", shape=[2, 4], dtype="int64",
                              append_batch_size=False)
        out = layers.gather_tree(ids, parents)
    # T=2, BW=4 (2 batches x beam 2)
    idv = np.array([[10, 11, 20, 21],
                    [12, 13, 22, 23]], np.int64)
    # step1 winners came from: row0<-1, row1<-0, row2<-3, row3<-2
    pav = np.array([[0, 1, 2, 3],
                    [1, 0, 3, 2]], np.int64)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (r,) = exe.run(main, feed={"ids": idv, "par": pav},
                       fetch_list=[out])
    r = np.asarray(r)
    # final tokens keep their place; step-0 tokens re-gathered via parents
    np.testing.assert_array_equal(r[1], idv[1])
    np.testing.assert_array_equal(r[0], [11, 10, 21, 20])


def test_beam_search_decoder_e2e():
    """Greedy-equivalent sanity: a rigged output layer that always scores
    token 2 highest must decode sequences of 2s ending at end token."""
    B, H, V, beam, end_id, T = 2, 4, 5, 2, 4, 3
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 12
    with fluid.program_guard(main, startup):
        enc = layers.data("enc", shape=[H], dtype="float32")
        cell = layers.GRUCell(hidden_size=H)

        def embed(ids):
            return layers.cast(
                layers.one_hot(layers.reshape(ids, [-1, 1]), V), "float32")

        bias = np.zeros(V, np.float32)
        bias[2] = 5.0
        bias_var = main.global_block().create_var(
            name="rig_bias", shape=(V,), dtype="float32", persistable=True)
        sb = startup.global_block()
        sv0 = sb.create_var(name="rig_bias", shape=(V,), dtype="float32",
                            persistable=True)
        from paddle_tpu.fluid.initializer import NumpyArrayInitializer

        NumpyArrayInitializer(bias)(sv0, sb)

        def output_fn(h):
            logits = layers.fc(h, size=V, bias_attr=False)
            return layers.elementwise_add(
                layers.scale(logits, scale=0.01), bias_var, axis=-1)

        decoder = layers.BeamSearchDecoder(
            cell, start_token=0, end_token=end_id, beam_size=beam,
            embedding_fn=embed, output_fn=output_fn)
        init_states = cell.get_initial_states(enc)
        final, _ = layers.dynamic_decode(decoder, inits=init_states,
                                         max_step_num=T)
        seqs = final["sequences"]
    exe = fluid.Executor()
    rng = np.random.RandomState(13)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (sv,) = exe.run(main, feed={
            "enc": rng.randn(B, H).astype(np.float32)}, fetch_list=[seqs])
    sv = np.asarray(sv)  # [T, B*beam]
    assert sv.shape == (T, B * beam)
    # the top beam of each batch decodes token 2 at every step
    assert (sv[:, 0] == 2).all() and (sv[:, beam] == 2).all()


def test_rnn_cell_params_shared_across_timesteps():
    """The unrolled rnn() must train ONE recurrent weight set, not one per
    timestep (reference: cells hold their params)."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 20
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[3, 2], dtype="float32")
        cell = layers.GRUCell(hidden_size=4)
        outs, final = layers.rnn(cell, x)
    params = [p.name for p in main.all_parameters()]
    # exactly 3 params: input proj, recurrent weight, bias
    assert len(params) == 3, params
    # and a second cell build adds nothing
    with fluid.program_guard(main, startup):
        x2 = layers.data("x2", shape=[3, 2], dtype="float32")
        layers.rnn(cell, x2)
    assert len(main.all_parameters()) == 3


def test_rnn_time_major_initial_state_shape():
    T, B, I, H = 5, 2, 3, 4  # T != B would break the old batch inference
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 21
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[T, B, I], dtype="float32",
                        append_batch_size=False)
        cell = layers.GRUCell(hidden_size=H)
        outs, final = layers.rnn(cell, x, time_major=True)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        ov, fv = exe.run(main, feed={
            "x": np.random.RandomState(22).randn(T, B, I).astype(
                np.float32)}, fetch_list=[outs, final])
    assert np.asarray(ov).shape == (T, B, H)
    assert np.asarray(fv).shape == (B, H)
