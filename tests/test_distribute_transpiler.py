"""DistributeTranspiler + TCP parameter-server tier: pserver programs
serve over loopback sockets (the reference's fake-cluster discipline,
``test_dist_base.py:500``), trainers pull/push through ShardedRemoteTable,
and the result matches single-process local-table training exactly."""

import threading

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.distributed import ps, wait_server_ready
from paddle_tpu.distributed.ps_server import (RemoteTable,
                                              ShardedRemoteTable,
                                              TableServer, shard_vocab)
from paddle_tpu.fluid import layers, optimizer


@pytest.fixture(autouse=True)
def _clean_tables():
    ps.reset_tables()
    yield
    ps.reset_tables()


def _start_server(tables):
    srv = TableServer(tables=tables).start()
    return srv


def test_remote_table_roundtrip():
    local = ps.EmbeddingTable(vocab=12, dim=3, init_scale=0.0)
    srv = _start_server({"t": local})
    try:
        wait_server_ready([srv.endpoint])
        rt = RemoteTable(srv.endpoint, "t")
        assert (rt.vocab, rt.dim) == (12, 3)
        ids = np.array([1, 5, 1], np.int64)
        np.testing.assert_allclose(rt.pull(ids), np.zeros((3, 3)))
        rt.push(np.array([2], np.int64), np.ones((1, 3), np.float32),
                lr=0.5)
        np.testing.assert_allclose(rt.pull(np.array([2], np.int64)),
                                   [[-0.5, -0.5, -0.5]])
        # dump/load round trip
        arr = rt.dump()
        arr[7] = 9.0
        rt.load(arr)
        np.testing.assert_allclose(rt.pull(np.array([7], np.int64)),
                                   [[9.0, 9.0, 9.0]])
        rt.close()
    finally:
        srv.stop()


def test_wait_touched_barrier():
    """Joining trainers block on wait_touched until trainer 0's init
    push lands (ADVICE r4 #3): before any push the flag times out False;
    after a push/load it flips True without re-constructing the proxy."""
    from paddle_tpu.distributed.ps_server import ShardedRemoteTable

    local = ps.EmbeddingTable(vocab=8, dim=2, init_scale=0.0)
    srv = _start_server({"t": local})
    try:
        wait_server_ready([srv.endpoint])
        rt = ShardedRemoteTable([srv.endpoint], "t", 8, 2)
        assert not rt.touched
        assert not rt.wait_touched(timeout=0.3, interval=0.05)
        # trainer 0's init arrives concurrently with the waiter
        def _init():
            other = RemoteTable(srv.endpoint, "t")
            other.load(np.full((8, 2), 3.0, np.float32))
            other.close()

        t = threading.Timer(0.2, _init)
        t.start()
        assert rt.wait_touched(timeout=10.0, interval=0.05)
        assert rt.touched
        t.join()
        rt.close()
    finally:
        srv.stop()


def test_sharded_remote_matches_local_table():
    vocab, dim, n = 17, 4, 3
    servers = []
    try:
        for k in range(n):
            rows = shard_vocab(vocab, n, k)
            servers.append(_start_server(
                {"s": ps.EmbeddingTable(rows, dim, init_scale=0.0)}))
        wait_server_ready([s.endpoint for s in servers])
        sharded = ShardedRemoteTable([s.endpoint for s in servers], "s",
                                     vocab, dim)
        local = ps.EmbeddingTable(vocab, dim, init_scale=0.0)
        rng = np.random.RandomState(0)
        for _ in range(5):
            ids = rng.randint(0, vocab, 9).astype(np.int64)
            grads = rng.randn(9, dim).astype(np.float32)
            sharded.push(ids, grads, lr=0.1)
            local.push(ids, grads, lr=0.1)
        np.testing.assert_allclose(sharded.dump(), local.dump(), rtol=1e-5,
                                   atol=1e-6)
        probe = rng.randint(0, vocab, 6).astype(np.int64)
        np.testing.assert_allclose(sharded.pull(probe), local.pull(probe),
                                   rtol=1e-5, atol=1e-6)
        sharded.close()
    finally:
        for s in servers:
            s.stop()


def _build_ctr_program(vocab, dim):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 3
    with fluid.program_guard(main, startup):
        ids = layers.data("dt_ids", [1], dtype="int64")
        label = layers.data("dt_label", [1], dtype="float32")
        emb = layers.embedding(ids, size=[vocab, dim],
                               is_distributed=True,
                               param_attr=fluid.ParamAttr(name="dt_emb"))
        emb = layers.reshape(emb, [-1, dim])
        pred = layers.fc(emb, 1, param_attr=fluid.ParamAttr(name="dt_w"),
                         bias_attr=fluid.ParamAttr(name="dt_b"))
        loss = layers.reduce_mean(layers.square(pred - label))
        optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def test_distribute_transpiler_e2e_matches_local():
    """2 'pserver processes' (threads serving exe.run(pserver_program)) +
    1 trainer; final embedding table equals local-table training."""
    vocab, dim = 10, 4
    rng = np.random.RandomState(1)
    batches = [(rng.randint(0, vocab, (8, 1)).astype(np.int64),
                rng.rand(8, 1).astype(np.float32)) for _ in range(6)]

    def train(main, startup, loss, preload=None):
        """Run startup (which re-inits the table), then optionally load
        known rows so runs compare exactly, then train."""
        exe = fluid.Executor()
        init = None
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            if preload is not None:
                ps.get_table("dt_emb").load(preload)
            else:
                init = ps.get_table("dt_emb").dump().copy()
            for ids, lab in batches:
                exe.run(main, feed={"dt_ids": ids, "dt_label": lab},
                        fetch_list=[loss])
            return ps.get_table("dt_emb").dump(), init

    # ---- local baseline ----
    main, startup, loss = _build_ctr_program(vocab, dim)
    local_final, baseline_init = train(main, startup, loss)
    ps.reset_tables()

    # ---- transpiled: 2 pservers on loopback ----
    main, startup, loss = _build_ctr_program(vocab, dim)
    # reserve two free ports
    probes = [TableServer() for _ in range(2)]
    eps = [s.endpoint for s in probes]
    for s in probes:
        s.stop()

    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id=0, program=main, pservers=",".join(eps),
                trainers=1)

    server_threads = []
    for ep in eps:
        prog = t.get_pserver_program(ep)
        types = [op.type for op in prog.global_block().ops]
        assert types == ["listen_and_serv"]
        th = threading.Thread(
            target=lambda p=prog: fluid.Executor().run(p), daemon=True)
        th.start()
        server_threads.append(th)
    wait_server_ready(eps)

    trainer_prog = t.get_trainer_program()
    remote_final, _ = train(trainer_prog, startup, loss,
                            preload=baseline_init)
    np.testing.assert_allclose(remote_final, local_final, rtol=1e-5,
                               atol=1e-6)


def test_fleet_parameter_server_mode():
    """The reference recipe through the Fleet facade: server role runs
    run_server() (blocking, in a thread), worker role transpiles via
    distributed_optimizer + init_worker and trains."""
    from paddle_tpu.fluid.incubate.fleet.base.role_maker import (
        Role, UserDefinedRoleMaker)
    from paddle_tpu.fluid.incubate.fleet.parameter_server import (
        ParameterServerFleet)

    vocab, dim = 12, 3
    probes = [TableServer() for _ in range(2)]
    eps = [s.endpoint for s in probes]
    for s in probes:
        s.stop()

    rng = np.random.RandomState(5)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = layers.data("fl_ids", [1], dtype="int64")
        label = layers.data("fl_label", [1], dtype="float32")
        emb = layers.reshape(layers.embedding(
            ids, size=[vocab, dim], is_distributed=True,
            param_attr=fluid.ParamAttr(name="fl_emb")), [-1, dim])
        loss = layers.reduce_mean(
            layers.square(layers.fc(emb, 1) - label))

        # worker-side fleet
        worker = ParameterServerFleet()
        worker.init(UserDefinedRoleMaker(current_id=0, role=Role.WORKER,
                                         worker_num=1,
                                         server_endpoints=eps))
        opt = worker.distributed_optimizer(optimizer.SGD(learning_rate=0.1))
        opt.minimize(loss)

    # server-side fleets (threads standing in for pserver processes)
    for k, ep in enumerate(eps):
        server = ParameterServerFleet()
        server.init(UserDefinedRoleMaker(current_id=k, role=Role.SERVER,
                                         worker_num=1,
                                         server_endpoints=eps))
        sopt = server.distributed_optimizer(
            optimizer.SGD(learning_rate=0.1))
        # servers see the same graph; transpile records the table specs
        sopt._fleet._transpiler = worker._transpiler
        server.init_server()
        threading.Thread(target=server.run_server, daemon=True).start()
    wait_server_ready(eps)

    trainer_prog = worker.init_worker()
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        losses = []
        for _ in range(25):
            b = rng.randint(0, vocab, (8, 1)).astype(np.int64)
            y = (b % 2).astype(np.float32)
            (lv,) = exe.run(trainer_prog,
                            feed={"fl_ids": b, "fl_label": y},
                            fetch_list=[loss])
            losses.append(float(np.asarray(lv).ravel()[0]))
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])
    worker.stop_worker()


# -- transport hardening (VERDICT r3 #7 / ADVICE r3) --------------------------

def test_ps_auth_token_rejects_mismatch():
    from paddle_tpu.distributed.ps import EmbeddingTable
    from paddle_tpu.distributed.ps_server import RemoteTable, TableServer

    srv = TableServer(tables={"t": EmbeddingTable(8, 4, seed=0)},
                      token="secret").start()
    try:
        with pytest.raises((ConnectionError, RuntimeError)):
            RemoteTable(srv.endpoint, "t", token="wrong")
        rt = RemoteTable(srv.endpoint, "t", token="secret")
        assert rt.vocab == 8
        rt.close()
    finally:
        srv.stop()


def test_ps_frame_cap_and_magic():
    """A raw peer without the magic gets dropped; an oversized frame
    poisons the stream instead of allocating."""
    import socket
    import struct as st

    from paddle_tpu.distributed.ps import EmbeddingTable
    from paddle_tpu.distributed import ps_server as M

    srv = M.TableServer(tables={"t": EmbeddingTable(8, 4, seed=0)}).start()
    try:
        # no magic: server closes without serving
        s = socket.create_connection((srv.host, srv.port), timeout=5)  # deliberately raw: garbage-bytes handshake-rejection test
        s.sendall(b"GARBAGE-" + b"x" * 20)
        s.settimeout(2)
        try:
            assert s.recv(64) == b""  # clean close, no response
        except (ConnectionResetError, OSError):
            pass                      # RST is an equally firm rejection
        s.close()
        # client-side cap: a frame header demanding > cap raises before
        # any allocation happens
        a, b = socket.socketpair()
        try:
            a.sendall(st.pack("<I", M._MAX_FRAME + 1))
            with pytest.raises(ConnectionError):
                M._read_frame(b)
        finally:
            a.close()
            b.close()
    finally:
        srv.stop()


def test_ps_push_retry_applies_once():
    """The (client, seq) dedup: re-sending the same push frame (what the
    reconnect path does when a response is lost) must not apply the
    gradient twice."""
    from paddle_tpu.distributed.ps import EmbeddingTable
    from paddle_tpu.distributed.ps_server import RemoteTable, TableServer

    table = EmbeddingTable(8, 4, seed=0)
    srv = TableServer(tables={"t": table}).start()
    try:
        rt = RemoteTable(srv.endpoint, "t")
        before = table.pull(np.arange(8)).copy()
        ids = np.array([1, 3])
        g = np.ones((2, 4), np.float32)
        rt.push(ids, g, lr=0.5)  # seq=1
        after_once = table.pull(np.arange(8)).copy()
        # replay the exact same seq through a second connection (the
        # retry path): server must ack without applying
        import struct as st
        from paddle_tpu.distributed import ps_server as M

        body = (st.pack("<16sQ", rt._client_id, rt._push_seq) +
                M._pack_arr(ids.astype(np.int64)) + M._pack_arr(g) +
                st.pack("<dBd", 0.5, 0, 1e-6))
        conn = M._Conn(srv.endpoint)
        conn.request(M._req(M._PUSH, "t", body))
        conn.close()
        np.testing.assert_array_equal(table.pull(np.arange(8)), after_once)
        assert not np.allclose(before, after_once)
        rt.close()
    finally:
        srv.stop()


def test_ps_id_bounds_rejected():
    from paddle_tpu.distributed.ps import EmbeddingTable
    from paddle_tpu.distributed.ps_server import (RemoteTable,
                                                  ShardedRemoteTable,
                                                  TableServer, shard_vocab)

    srvs = [TableServer(tables={"s": EmbeddingTable(
        shard_vocab(10, 2, k), 4, seed=k)}).start() for k in range(2)]
    try:
        sh = ShardedRemoteTable([s.endpoint for s in srvs], "s", 10, 4)
        with pytest.raises(ValueError):
            sh.pull(np.array([-1, 2]))
        with pytest.raises(ValueError):
            sh.push(np.array([10]), np.ones((1, 4), np.float32))
        # server side too (direct shard access past the shard vocab)
        rt = RemoteTable(srvs[0].endpoint, "s")
        with pytest.raises(RuntimeError):
            rt.pull(np.array([99]))
        sh.close()
        rt.close()
    finally:
        for s in srvs:
            s.stop()


def test_ps_server_crash_restart_resume():
    """Fault injection (VERDICT r3 #7): kill the TableServer mid-train,
    restart it on the same port from a dump, and the SAME client object
    resumes via reconnect-with-backoff — no corruption."""
    from paddle_tpu.distributed.ps import EmbeddingTable
    from paddle_tpu.distributed.ps_server import RemoteTable, TableServer

    table = EmbeddingTable(8, 4, seed=0)
    srv = TableServer(tables={"t": table}).start()
    port = srv.port
    rt = RemoteTable(srv.endpoint, "t")
    ids = np.array([0, 5])
    rt.push(ids, np.ones((2, 4), np.float32), lr=0.1)
    snapshot = rt.dump()

    srv.stop()  # crash
    # while down: requests fail after the retry budget
    rt2 = None
    with pytest.raises((ConnectionError, OSError)):
        rt.pull(ids)

    # restart on the same port from the dump
    table2 = EmbeddingTable(8, 4, seed=99)   # different init...
    table2.load_rows(0, snapshot)            # ...restored from the dump
    srv2 = TableServer(port=port, tables={"t": table2}).start()
    try:
        rows = rt.pull(ids)                  # same client, auto-reconnect
        np.testing.assert_allclose(rows, snapshot[ids])
        rt.push(ids, np.ones((2, 4), np.float32), lr=0.1)  # train resumes
        assert not np.allclose(rt.pull(ids), snapshot[ids])
        rt.close()
    finally:
        srv2.stop()


def test_transpiler_fresh_init_matches_local():
    """get_trainer_program (trainer 0) ships the local tables' initial
    values to the pservers — fresh-start PS training begins from exactly
    the single-process init, no explicit load() (ADVICE r3 #2)."""
    vocab, dim = 10, 4
    main, startup, loss = _build_ctr_program(vocab, dim)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        local_init = ps.get_table("dt_emb").dump().copy()

        probes = [TableServer() for _ in range(2)]
        eps = [s.endpoint for s in probes]
        for s in probes:
            s.stop()
        t = fluid.DistributeTranspiler()
        t.transpile(trainer_id=0, program=main, pservers=",".join(eps),
                    trainers=1)
        threads = []
        for ep in eps:
            prog = t.get_pserver_program(ep)
            th = threading.Thread(
                target=lambda p=prog: fluid.Executor().run(p), daemon=True)
            th.start()
            threads.append(th)
        wait_server_ready(eps)
        t.get_trainer_program()
        remote = ps.get_table("dt_emb")
        np.testing.assert_allclose(remote.pull(np.arange(vocab)),
                                   local_init, rtol=1e-6)
    ps.reset_tables()
