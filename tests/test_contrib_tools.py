"""Contrib analysis tools + legacy/geo transpilers.

References: contrib/memory_usage_calc.py:46, op_frequence.py:23,
model_stat.py:40, extend_optimizer_with_weight_decay.py:102,
reader/distributed_reader.py:21, utils/hdfs_utils.py:29,
transpiler/memory_optimization_transpiler.py:18, geo_sgd_transpiler.py:48.
"""

import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, optimizer
from paddle_tpu.fluid.contrib import (memory_usage, model_stat,
                                      op_freq_statistic)
from paddle_tpu.fluid.contrib.extend_optimizer import (
    extend_with_decoupled_weight_decay)
from paddle_tpu.fluid.contrib.reader import distributed_batch_reader
from paddle_tpu.distributed import ps


def _conv_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[1, 8, 8])
        c = layers.conv2d(img, num_filters=4, filter_size=3, padding=1,
                          act="relu")
        p = layers.pool2d(c, pool_size=2, pool_stride=2)
        y = layers.fc(p, size=10)
        loss = layers.mean(y)
    return main, startup, loss


def test_memory_usage():
    main, _, _ = _conv_program()
    lo, hi, unit = memory_usage(main, batch_size=32)
    assert 0 < lo < hi
    assert unit in ("B", "KB", "MB")
    # scales with batch size
    lo2, hi2, unit2 = memory_usage(main, batch_size=64)
    def to_b(v, u):
        return v * {"B": 1, "KB": 1024, "MB": 1024**2}[u]
    assert to_b(lo2, unit2) > to_b(lo, unit)
    with pytest.raises(ValueError):
        memory_usage(main, batch_size=0)
    with pytest.raises(TypeError):
        memory_usage("not a program", 8)


def test_op_freq_statistic():
    main, _, _ = _conv_program()
    uni, adj = op_freq_statistic(main)
    assert uni["conv2d"] == 1 and uni["pool2d"] == 1
    # producer->consumer adjacency captured (conv feeds relu)
    assert any(k.startswith("conv2d,") for k in adj)
    # sorted descending
    counts = list(uni.values())
    assert counts == sorted(counts, reverse=True)


def test_model_stat_summary(capsys):
    main, _, _ = _conv_program()
    rows, total_params, total_flops = model_stat.summary(main)
    types = [r["type"] for r in rows]
    assert "conv2d" in types and "pool2d" in types and "relu" in types
    conv = next(r for r in rows if r["type"] == "conv2d")
    assert conv["PARAMs"] == 4 * 1 * 3 * 3
    assert conv["FLOPs"] == 2 * 8 * 8 * 4 * 9
    assert total_params > 0 and total_flops > 0
    assert "Total PARAMs" in capsys.readouterr().out


def test_decoupled_weight_decay_static():
    """AdamW-style: param shrinks by coeff*param BEFORE the grad step —
    compare one step against the hand computation with SGD."""
    AdamW = extend_with_decoupled_weight_decay(optimizer.SGD)
    coeff, lr = 0.1, 0.5
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 5
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4])
        y = layers.fc(x, size=1,
                      param_attr=fluid.ParamAttr(name="wd_w"),
                      bias_attr=False)
        loss = layers.mean(y)
        opt = AdamW(weight_decay=coeff, learning_rate=lr)
        opt.minimize(loss)
    exe = fluid.Executor()
    xv = np.ones((2, 4), np.float32)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        w0 = np.asarray(fluid.global_scope().find_var("wd_w"))
        exe.run(main, feed={"x": xv}, fetch_list=[loss])
        w1 = np.asarray(fluid.global_scope().find_var("wd_w"))
    # d(mean(x@w))/dw = mean over batch of x = ones/1 -> grad = 0.5*... :
    # grad_ij = mean_b x_bi / 1 (single output) = 1.0 / 2 * 2 = 1? compute:
    # loss = mean(x @ w) over 2 rows -> dloss/dw_i = mean_b(x_bi) = 1.0
    expect = w0 * (1 - coeff) - lr * 1.0
    np.testing.assert_allclose(w1, expect, rtol=1e-5, atol=1e-6)


def test_decoupled_weight_decay_filter_and_dygraph():
    from paddle_tpu.fluid import dygraph
    from paddle_tpu.fluid.dygraph import nn, to_variable

    AdamW = extend_with_decoupled_weight_decay(optimizer.SGD)
    with dygraph.guard():
        model = nn.Linear(4, 1)
        w = model.parameters()[0]
        b = model.parameters()[1]
        w0 = np.asarray(w.numpy()).copy()
        b0 = np.asarray(b.numpy()).copy()
        opt = AdamW(weight_decay=0.5, learning_rate=0.0,
                    apply_decay_param_fun=lambda n: n == w.name)
        out = model(to_variable(np.ones((2, 4), np.float32)))
        tracer = fluid.framework._dygraph_tracer()
        (loss,) = tracer.trace_op("mean", {"X": [out]}, ["Out"], {})
        opt.minimize(loss, parameter_list=model.parameters())
        # lr=0: the ONLY change is the decay, applied to w but not b
        np.testing.assert_allclose(np.asarray(w.numpy()), w0 * 0.5,
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(b.numpy()), b0, rtol=1e-6)
    with pytest.raises(TypeError):
        extend_with_decoupled_weight_decay(object)


def test_distributed_batch_reader(monkeypatch):
    def reader():
        for i in range(10):
            yield [i]

    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "3")
    monkeypatch.setenv("PADDLE_TRAINER_ID", "1")
    got = [b[0] for b in distributed_batch_reader(reader)()]
    assert got == [1, 4, 7]
    monkeypatch.setenv("PADDLE_TRAINER_ID", "5")
    with pytest.raises(AssertionError):
        distributed_batch_reader(reader)


def test_contrib_multi_transfer(tmp_path):
    from paddle_tpu.fluid.contrib.utils import multi_download, multi_upload
    from paddle_tpu.fs import LocalFS

    src = tmp_path / "src"
    src.mkdir()
    for i in range(5):
        (src / ("f%d.txt" % i)).write_text(str(i))
    client = LocalFS()
    up = multi_upload(client, str(tmp_path / "store"), str(src),
                      overwrite=True)
    assert up == 5
    got = multi_download(client, str(tmp_path / "store"),
                         str(tmp_path / "dl"), trainer_id=1, trainers=2)
    assert [os.path.basename(p) for p in got] == ["f1.txt", "f3.txt"]


def test_memory_optimize_noop_warns(caplog):
    import logging

    main, _, _ = _conv_program()
    with caplog.at_level(logging.WARNING):
        assert fluid.memory_optimize(main, print_log=True) is None
        assert fluid.release_memory(main) is None
    assert any("deprecated" in r.message for r in caplog.records)


def test_geo_sgd_transpiler_end_to_end():
    """GeoSgdTranspiler trains against the local mirror; the pserver-side
    table only moves on the k-th push / final sync."""
    vocab, dim, k = 16, 4, 3
    cfg = fluid.DistributeTranspilerConfig()
    cfg.geo_sgd_need_push_nums = k
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        ids = layers.data("gt_ids", shape=[2], dtype="int64")
        layers.embedding(ids, size=[vocab, dim], is_distributed=True,
                         param_attr=fluid.ParamAttr(name="geo_t"))
    t = fluid.transpiler.GeoSgdTranspiler(cfg)
    t.transpile(trainer_id=0, program=main, pservers="local://0",
                trainers=1)
    # single-process: skip the real PS tier by keeping the local table;
    # interpose the geo proxy exactly as get_trainer_program would
    from paddle_tpu.fluid.communicator import _GeoTableProxy

    table = ps.get_table("geo_t")
    comm = ps.GeoCommunicator(table, k_steps=k)
    t._geo_comms["geo_t"] = comm
    ps.register_table("geo_t", _GeoTableProxy(table, comm))
    try:
        proxy = ps.get_table("geo_t")
        base = table.dump()
        g = np.ones((2, dim), np.float32)
        idv = np.array([2, 5], np.int64)
        proxy.push(idv, g, lr=1.0)
        proxy.push(idv, g, lr=1.0)
        np.testing.assert_array_equal(table.dump(), base)  # not shipped yet
        proxy.push(idv, g, lr=1.0)                         # k-th: ships
        assert np.abs(table.dump()[idv] - base[idv]).max() > 0
        # pending deltas force-ship through the transpiler-level sync
        proxy.push(idv, g, lr=1.0)
        before = table.dump().copy()
        t.sync()
        assert np.abs(table.dump()[idv] - before[idv]).max() > 0
    finally:
        ps.register_table("geo_t", table)


def test_model_stat_depthwise_conv():
    """Grouped/depthwise conv params counted once (the filter shape
    already carries the per-group channel division)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[4, 8, 8])
        layers.conv2d(img, num_filters=4, filter_size=3, padding=1,
                      groups=4)
    rows, total_params, _ = model_stat.summary(main, print_table=False)
    conv = next(r for r in rows if r["type"] == "conv2d")
    assert conv["PARAMs"] == 4 * 1 * 3 * 3  # 36, not 0


def test_ctr_metric_bundle():
    from paddle_tpu.fluid.contrib.layers import ctr_metric_bundle

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        p = layers.data("p", shape=[1])
        lbl = layers.data("lbl", shape=[1])
        bundle = ctr_metric_bundle(p, lbl)
    exe = fluid.Executor()
    rng = np.random.RandomState(0)
    pv = rng.rand(8, 1).astype(np.float32)
    lv = (rng.rand(8, 1) < 0.5).astype(np.float32)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(2):  # accumulates across runs
            vals = exe.run(main, feed={"p": pv, "lbl": lv},
                           fetch_list=list(bundle))
        sqrerr, abserr, prob, q, pos, ins = [np.asarray(v).item() for v in vals]
    np.testing.assert_allclose(sqrerr, 2 * np.square(pv - lv).sum(), rtol=1e-5)
    np.testing.assert_allclose(abserr, 2 * np.abs(pv - lv).sum(), rtol=1e-5)
    np.testing.assert_allclose(prob, 2 * pv.sum(), rtol=1e-5)
    np.testing.assert_allclose(q, 2 * (1 / (1 + np.exp(-pv))).sum(), rtol=1e-5)
    np.testing.assert_allclose(pos, 2 * lv.sum(), rtol=1e-5)
    np.testing.assert_allclose(ins, 16.0, rtol=1e-6)


def test_legacy_quantize_transpiler_e2e():
    """The pre-slim QuantizeTranspilerthree-phase flow end-to-end: QAT
    trains, freeze integerizes weights, int8 storage keeps outputs."""
    from paddle_tpu.fluid.contrib.quantize import QuantizeTranspiler

    rng = np.random.RandomState(0)
    X = rng.rand(32, 8).astype(np.float32)
    Y = (X @ rng.rand(8, 1)).astype(np.float32)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 9
    with fluid.program_guard(main, startup):
        x = layers.data("qx", shape=[8])
        yl = layers.data("qy", shape=[1])
        h = layers.fc(x, 16, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square_error_cost(pred, yl))
    scope = fluid.Scope()
    qt = QuantizeTranspiler(
        activation_quantize_type="moving_average_abs_max",
        quantizable_op_type=("mul",))
    with fluid.scope_guard(scope):
        qt.training_transpile(main, startup, scope=scope)
        with fluid.program_guard(main, startup):
            optimizer.SGD(learning_rate=0.05).minimize(loss)
        types = [op.type for op in main.global_block().ops]
        assert any(t.startswith("fake_quantize") for t in types)
        exe = fluid.Executor()
        exe.run(startup)
        losses = []
        for _ in range(12):
            (lv,) = exe.run(main, feed={"qx": X, "qy": Y},
                            fetch_list=[loss])
            losses.append(float(np.asarray(lv)))
        assert losses[-1] < losses[0]
        infer = main._prune([pred])
        qt.freeze_program(infer, scope=scope)
        types = [op.type for op in infer.global_block().ops]
        assert not any(t.startswith("fake_quantize") for t in types)
        (frozen,) = exe.run(infer, feed={"qx": X}, fetch_list=[pred])
        wname = next(iter(qt._freeze_pass._weight_scales))
        w = np.asarray(scope.find_var(wname))
        np.testing.assert_allclose(w, np.round(w), atol=1e-5)
        qt.convert_to_int8(infer, scope=scope)
        assert np.asarray(scope.find_var(wname)).dtype == np.int8
        (int8_out,) = exe.run(infer, feed={"qx": X}, fetch_list=[pred])
        np.testing.assert_allclose(np.asarray(int8_out),
                                   np.asarray(frozen), rtol=1e-4,
                                   atol=1e-5)
