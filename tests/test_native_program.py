"""Native ProgramDesc IR library (native/program_graph.cc).

Pins the C++ tier against the authoritative Python implementations it
mirrors: wire parse/serialize round-trip through proto_io, prune vs
Program._prune (including the control-flow sub-block walk), lint on
well-formed and deliberately broken programs, the last-use plan, and
graphviz export. Reference analogues: program_desc.h, prune.h,
ir/graph_helper, reference_count_pass, graph_viz_pass (SURVEY §2.1/2.3).
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, optimizer
from paddle_tpu.fluid.core import proto_io
from paddle_tpu.fluid.native_program import NativeProgram, check_program_native
from paddle_tpu import native

pytestmark = pytest.mark.skipif(native.load_program_graph() is None,
                                reason="no native toolchain")


def _simple_program():
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = layers.data(name="x", shape=[4])
        h = layers.fc(x, size=3, act="relu")
        out1 = layers.mean(h)
        out2 = layers.reduce_sum(h)
    return main, out1, out2


def _control_flow_program():
    """fc read only inside a cond branch + a While mutating a parent var
    + a Switch with list-valued "blocks" attr — the same shapes
    test_prune_keeps_subblock_dependencies exercises."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4])
        label = layers.data("label", shape=[1])
        h = layers.fc(x, size=3, act="relu")
        pred = layers.reduce_mean(x) > 0.0
        branched = layers.cond(pred, lambda: h * 2.0, lambda: h + 1.0)
        acc = layers.fill_constant([1], "float32", 0.0)
        i = layers.fill_constant([1], "int64", 0)
        n = layers.fill_constant([1], "int64", 3)
        w_cond = layers.less_than(i, n)
        w = layers.While(w_cond)
        with w.block():
            layers.assign(acc + 1.0, acc)
            layers.increment(i)
            layers.less_than(i, n, cond=w_cond)
        lr = layers.fill_constant([1], "float32", 0.0)
        with layers.Switch() as sw:
            with sw.case(layers.reduce_mean(x) > -1000.0):
                layers.assign(layers.fill_constant([1], "float32", 10.0), lr)
            with sw.default():
                layers.assign(layers.fill_constant([1], "float32", 20.0), lr)
        out = branched + acc + lr
        loss = layers.reduce_mean(
            layers.square_error_cost(layers.reduce_sum(out, keep_dim=True),
                                     label))
        optimizer.SGD(learning_rate=0.01).minimize(loss)
    return main, out, loss


def test_parse_structure_and_roundtrip():
    main, out1, _ = _simple_program()
    data = main.serialize_to_string()
    np_ = NativeProgram.from_bytes(data)
    assert np_.num_blocks == len(main.blocks)
    assert np_.num_ops(0) == len(main.global_block().ops)
    assert np_.num_vars(0) == len(main.global_block().vars)
    assert np_.op_types(0) == [op.type for op in main.global_block().ops]
    # canonical re-serialization parses back to the identical desc
    desc_orig = proto_io.program_from_bytes(data, check=False)
    desc_rt = proto_io.program_from_bytes(np_.serialize(), check=False)
    assert desc_rt == desc_orig


def test_roundtrip_preserves_attr_types():
    """One op of every attr flavour survives C++ parse -> serialize."""
    main = fluid.Program()
    blk = main.global_block()
    blk.create_var(name="z", shape=[2], dtype="float32")
    blk.append_op(
        type="fill_constant",
        inputs={},
        outputs={"Out": ["z"]},
        attrs={
            "i_attr": 7,
            "neg_attr": -3,
            "f_attr": 0.125,
            "s_attr": "hello",
            "b_true": True,
            "b_false": False,
            "ints": [1, -2, 3],
            "floats": [0.5, -1.5],
            "strings": ["a", "b"],
            "empty_ints": [],
            "none_attr": None,
        },
    )
    data = main.serialize_to_string()
    np_ = NativeProgram.from_bytes(data)
    desc_rt = proto_io.program_from_bytes(np_.serialize(), check=False)
    attrs = desc_rt["blocks"][0]["ops"][0]["attrs"]
    assert attrs["i_attr"] == 7 and attrs["neg_attr"] == -3
    assert attrs["f_attr"] == 0.125
    assert attrs["s_attr"] == "hello"
    assert attrs["b_true"] is True and attrs["b_false"] is False
    assert attrs["ints"] == [1, -2, 3]
    assert attrs["floats"] == [0.5, -1.5]
    assert attrs["strings"] == ["a", "b"]
    assert attrs["empty_ints"] == []
    assert attrs["none_attr"] is None


def test_native_prune_matches_python_simple():
    main, out1, out2 = _simple_program()
    py = main._prune([out1])
    np_ = NativeProgram.from_program(main).prune(out1.name)
    assert np_.op_types(0) == [op.type for op in py.global_block().ops]
    assert "reduce_sum" not in np_.op_types(0)


def test_native_prune_matches_python_control_flow():
    main, out, loss = _control_flow_program()
    py = main._prune([out])
    np_ = NativeProgram.from_program(main).prune(out.name)
    assert np_.op_types(0) == [op.type for op in py.global_block().ops]
    # the training tail is gone, the sub-block chains survive
    kept = np_.op_types(0)
    assert "while" in kept and "cond" in kept and "switch" in kept
    assert "sgd" not in kept and "square_error_cost" not in kept
    # sub-blocks ride along untouched
    assert np_.num_blocks == len(main.blocks)


def test_lint_clean_on_real_programs():
    for prog in (_simple_program()[0], _control_flow_program()[0]):
        issues = [i for i in NativeProgram.from_program(prog).lint()
                  if i.startswith("E: ")]
        assert issues == []
    assert check_program_native(_simple_program()[0]) == []


def test_lint_catches_undefined_var_and_bad_subblock():
    main, _, _ = _simple_program()
    desc = proto_io.program_from_bytes(main.serialize_to_string(),
                                       check=False)
    desc["blocks"][0]["ops"][0]["inputs"]["X"] = ["no_such_var"]
    desc["blocks"][0]["ops"][1]["attrs"]["sub_block"] = 99
    np_ = NativeProgram.from_bytes(proto_io.program_to_bytes(desc))
    issues = np_.lint()
    assert any("undefined var 'no_such_var'" in i for i in issues)
    assert any("sub-block 99 out of range" in i for i in issues)


def test_lint_catches_duplicate_var():
    main, _, _ = _simple_program()
    desc = proto_io.program_from_bytes(main.serialize_to_string(),
                                       check=False)
    desc["blocks"][0]["vars"].append(dict(desc["blocks"][0]["vars"][0]))
    np_ = NativeProgram.from_bytes(proto_io.program_to_bytes(desc))
    assert any("duplicate var" in i for i in np_.lint())


def test_last_use_plan():
    main, out1, out2 = _simple_program()
    np_ = NativeProgram.from_program(main)
    plan = np_.last_use(0)
    blk = main.global_block()
    # recompute expectation in Python
    last = {}
    for oi, op in enumerate(blk.ops):
        for name in list(op.input_arg_names()) + list(op.output_arg_names()):
            last[name] = oi
    expect = {}
    for name, var in blk.vars.items():
        if var.persistable or getattr(var, "is_data", False):
            continue
        if name in last:
            expect.setdefault(last[name], []).append(name)
    assert {k: sorted(v) for k, v in plan.items()} == {
        k: sorted(v) for k, v in expect.items()
    }


def test_to_dot():
    main, out1, _ = _simple_program()
    dot = NativeProgram.from_program(main).to_dot(0)
    assert dot.startswith("digraph")
    assert '"op_0"' in dot and "shape=box" in dot
    assert "mean" in dot


def test_malformed_bytes_raise():
    with pytest.raises(ValueError):
        NativeProgram.from_bytes(b"\xff\xff\xff\xff\x02")


def test_prune_flips_is_test():
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = layers.data(name="x", shape=[4])
        d = layers.dropout(x, dropout_prob=0.5)
        out = layers.mean(d)
    np_ = NativeProgram.from_program(main).prune(out.name)
    pruned_bytes = np_.serialize()
    desc = proto_io.program_from_bytes(pruned_bytes, check=False)
    drop = [o for o in desc["blocks"][0]["ops"] if o["type"] == "dropout"]
    assert drop and drop[0]["attrs"]["is_test"] is True
