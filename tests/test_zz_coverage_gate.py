"""EXECUTION-based op-coverage gate (VERDICT r3 #4): every registered
lowering must actually RUN during the suite — ``registry.lower_op`` (and
the dygraph tracer) record executed types into ``EXECUTED_OP_TYPES``, and
this file (alphabetically last, so it runs after every other module)
asserts registry ⊆ executed ∪ EXEMPT. Unlike the old textual-mention
check (an op named in a comment passed), a lowering that silently stops
being exercised now fails the build. Reference analogue: the op-test
discipline of ``unittests/op_test.py:135``."""

import pytest


# Genuinely-unexecutable-in-process lowerings, each with its reason.
EXEMPT = {
    # spawned trainer SUBPROCESSES execute these (test_multiprocess /
    # launch gang tests); the recorder is per-process
    "c_comm_init", "c_comm_init_all",
    # identity boot markers for rendezvous the transpiler emits for
    # reference parity; real bootstrap is jax.distributed (env.py) and
    # the lowering is shared with `barrier` (asserted registered)
    "c_gen_nccl_id", "gen_nccl_id",
}


def test_every_registered_lowering_executed(request):
    from paddle_tpu.fluid.registry import EXECUTED_OP_TYPES, registry

    if len(request.session.items) < 400:
        pytest.skip("partial run: the execution gate needs the full suite")
    missing = sorted(t for t in registry.types()
                     if t not in EXECUTED_OP_TYPES and t not in EXEMPT)
    assert not missing, (
        "registered op lowerings never executed by the suite "
        "(add a real execution test or an EXEMPT entry with a reason): %s"
        % missing)
