"""Native tier: combined tensor serde (tensor_io.cc) + bounded channel
(channel.cc), and their Python fallbacks/product wiring."""

import os
import threading
import time

import numpy as np
import pytest

from paddle_tpu import native
from paddle_tpu.fluid.core import tensor_io


def _sample_arrays():
    rng = np.random.RandomState(0)
    out = {
        "w": rng.rand(4, 3).astype(np.float32),
        "ids": np.arange(7, dtype=np.int64),
        "flag": np.array([True, False]),
        "scalar": np.float32(3.5).reshape(()),
        "bytes8": np.arange(5, dtype=np.uint8),
    }
    try:
        import ml_dtypes

        out["bf"] = rng.rand(3, 2).astype(ml_dtypes.bfloat16)
    except ImportError:
        pass
    return out


def test_tensor_io_roundtrip(tmp_path):
    path = str(tmp_path / "combined.ptc")
    arrays = _sample_arrays()
    tensor_io.save_combine(path, arrays)
    out = tensor_io.load_combine(path)
    assert list(out) == list(arrays)
    for k in arrays:
        assert out[k].dtype == np.asarray(arrays[k]).dtype
        np.testing.assert_array_equal(np.asarray(out[k]),
                                      np.asarray(arrays[k]))


def test_tensor_io_python_and_native_formats_interchange(tmp_path):
    if native.load_tensor_io() is None:
        pytest.skip("no toolchain")
    arrays = _sample_arrays()
    p_native = str(tmp_path / "n.ptc")
    p_py = str(tmp_path / "p.ptc")
    tensor_io._save_native(native.load_tensor_io(), p_native,
                           [(k, np.ascontiguousarray(v))
                            for k, v in arrays.items()])
    tensor_io._save_py(p_py, [(k, np.ascontiguousarray(v))
                              for k, v in arrays.items()])
    assert open(p_native, "rb").read() == open(p_py, "rb").read()
    # each loader reads the other's file
    a = tensor_io._load_py(p_native)
    b = tensor_io._load_native(native.load_tensor_io(), p_py)
    for k in arrays:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]))


def test_save_load_persistables_combined_file(tmp_path):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers, optimizer

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("nio_x", [4])
            y = layers.data("nio_y", [1])
            loss = layers.reduce_mean(
                layers.square(layers.fc(x, 1, param_attr=fluid.ParamAttr(
                    name="nio_w")) - y))
            optimizer.Adam(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        exe.run(main, feed={"nio_x": np.ones((4, 4), np.float32),
                            "nio_y": np.ones((4, 1), np.float32)},
                fetch_list=[])
        w = np.asarray(scope.find_var("nio_w")).copy()
        fluid.io.save_persistables(exe, str(tmp_path), main,
                                   filename="all_params")
        assert (tmp_path / "all_params").exists()
        # magic says PTC1
        assert open(tmp_path / "all_params", "rb").read(4) == b"PTC1"
        scope.set_var("nio_w", np.zeros_like(w))
        fluid.io.load_persistables(exe, str(tmp_path), main,
                                   filename="all_params")
        np.testing.assert_array_equal(np.asarray(scope.find_var("nio_w")), w)


def test_channel_fifo_and_close():
    if native.load_channel() is None:
        pytest.skip("no toolchain")
    ch = native.Channel(capacity=4)
    ch.put(b"a")
    ch.put(b"b")
    assert ch.size() == 2
    assert ch.get() == b"a"
    assert ch.get() == b"b"
    ch.close()
    assert ch.get() is None  # closed and drained
    with pytest.raises(RuntimeError):
        ch.put(b"c")
    ch.destroy()


def test_channel_blocking_producer_consumer():
    if native.load_channel() is None:
        pytest.skip("no toolchain")
    ch = native.Channel(capacity=2)
    n = 50
    got = []

    def produce():
        for i in range(n):
            ch.put(b"item%04d" % i)
        ch.close()

    t = threading.Thread(target=produce)
    t.start()
    while True:
        b = ch.get()
        if b is None:
            break
        got.append(b)
    t.join()
    ch.destroy()
    assert got == [b"item%04d" % i for i in range(n)]


def test_channel_bounded_blocks_when_full():
    if native.load_channel() is None:
        pytest.skip("no toolchain")
    ch = native.Channel(capacity=1)
    ch.put(b"x")
    state = {"done": False}

    def produce():
        ch.put(b"y")  # must block until consumer pops
        state["done"] = True

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    time.sleep(0.2)
    assert not state["done"]
    assert ch.get() == b"x"
    t.join(timeout=5)
    assert state["done"]
    assert ch.get() == b"y"
    ch.close()
    ch.destroy()


def test_queue_dataset_streams_over_channel(tmp_path, monkeypatch):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers

    monkeypatch.setenv("PADDLE_TPU_NATIVE_CHANNEL", "1")

    fn = str(tmp_path / "part-0")
    with open(fn, "w") as f:
        for i in range(10):
            f.write("3 %d %d %d 1 %d\n" % (i, i + 1, i + 2, i % 2))
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = layers.data("qc_ids", [1], dtype="int64", lod_level=1)
        lab = layers.data("qc_lab", [1], dtype="int64")
    ds = fluid.DatasetFactory().create_dataset("QueueDataset")
    ds.set_use_var([ids, lab])
    ds.set_batch_size(4)
    ds.set_filelist([fn])
    batches = list(ds.batch_reader()())
    assert len(batches) == 3  # 4+4+2
    assert set(batches[0]) == {"qc_ids", "qc_lab"}
