"""Multi-process execution — reference ``test_dist_base.py:500``: spawn a
localhost fake cluster (2 trainer subprocesses, virtual CPU devices + gloo
collectives), assert losses match the single-process baseline.
"""

import json
import os
import re
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RUNNER = os.path.join(REPO, "tests", "dist_runner_mlp.py")


def _single_process_baseline():
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers, optimizer

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 17
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(x, size=16, act="relu")
        logits = layers.fc(h, size=4)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor()
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(16, 8).astype(np.float32),
            "label": rng.randint(0, 4, (16, 1)).astype(np.int64)}
    out = []
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(4):
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
            out.append(float(np.asarray(lv).ravel()[0]))
    return out


@pytest.mark.slow
def test_two_process_dp_matches_single_process(tmp_path):
    base = _single_process_baseline()

    env = dict(os.environ)
    # children must NOT inherit the parent's single-backend pins
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    log_dir = str(tmp_path / "logs")
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--nproc_per_node", "2", "--backend", "cpu",
           "--log_dir", log_dir, RUNNER]
    r = subprocess.run(cmd, env=env, cwd=REPO, capture_output=True,
                       timeout=600)
    logs = ""
    for i in range(2):
        with open(os.path.join(log_dir, "worker.%d.log" % i)) as f:
            logs += "--- worker %d ---\n%s\n" % (i, f.read())
    assert r.returncode == 0, logs

    per_rank = re.findall(r"LOSSES (\[.*\])", logs)
    assert len(per_rank) == 2, logs
    l0, l1 = json.loads(per_rank[0]), json.loads(per_rank[1])
    # both ranks observe the same global loss, equal to the baseline
    np.testing.assert_allclose(l0, l1, rtol=1e-5)
    np.testing.assert_allclose(l0, base, rtol=1e-4)


def test_launch_module_help():
    r = subprocess.run([sys.executable, "-m",
                        "paddle_tpu.distributed.launch", "--help"],
                       capture_output=True, cwd=REPO, timeout=120)
    assert r.returncode == 0
    assert b"nproc_per_node" in r.stdout


def test_launch_restarts_failed_gang(tmp_path):
    """A worker that crashes on its first attempt succeeds after the
    launcher's gang restart (SURVEY §5.3 failure detection)."""
    from paddle_tpu.distributed.launch import launch

    script = tmp_path / "flaky.py"
    marker = tmp_path / "attempt1"
    script.write_text(
        "import os, sys\n"
        "rank = os.environ['PADDLE_TRAINER_ID']\n"
        "attempt = int(os.environ['PADDLE_RESTART_ATTEMPT'])\n"
        "print('rank', rank, 'attempt', attempt)\n"
        "if attempt == 0 and rank == '1':\n"
        "    sys.exit(3)  # crash once\n"
        "print('DONE', rank)\n")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    log_dir = str(tmp_path / "logs")
    codes = launch(2, [sys.executable, "-u", str(script)], env=env,
                   log_dir=log_dir, max_restarts=1)
    assert codes == [0, 0]
    logs = ""
    for i in range(2):
        logs += open(os.path.join(log_dir, "worker.%d.log" % i)).read()
    assert "attempt 1" in logs and "DONE 1" in logs


def test_launch_watchdog_kills_hung_worker(tmp_path):
    """A worker that stops heartbeating is detected and the gang killed
    (no restart budget -> nonzero exit)."""
    from paddle_tpu.distributed.launch import launch

    script = tmp_path / "hang.py"
    script.write_text(
        "import os, sys, time\n"
        "sys.path.insert(0, %r)\n"
        "from paddle_tpu.distributed import Heartbeat\n"
        "rank = os.environ['PADDLE_TRAINER_ID']\n"
        "if rank == '0':\n"
        "    hb = Heartbeat(interval=0.2).start()\n"
        "    time.sleep(30)\n"  # healthy worker, parked
        "else:\n"
        "    time.sleep(30)\n"  # never heartbeats -> stale
        % REPO)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    import time
    t0 = time.time()
    codes = launch(2, [sys.executable, "-u", str(script)], env=env,
                   heartbeat_timeout=3.0)
    assert time.time() - t0 < 25  # killed well before the 30s sleep
    assert any(c != 0 for c in codes)


def test_heartbeat_watchdog_unit(tmp_path):
    from paddle_tpu.distributed import Heartbeat, Watchdog

    hb = Heartbeat(rank=0, dirname=str(tmp_path), interval=10.0).start()
    hb.beat(step=7)
    wd = Watchdog(str(tmp_path), nproc=2, timeout=0.5,
                  startup_grace=0.5)
    assert wd.read(0)["step"] == 7
    import time
    time.sleep(0.7)
    hb.beat()  # rank 0 stays fresh; rank 1 never stamped
    assert wd.stale_workers() == [1]
    hb.stop()
