"""DGC (deep gradient compression) and Program-level pipeline parallelism —
the round-1 phantom capabilities, now real. Reference:
``optimizer.py:870`` (DGCMomentum), ``operators/dgc_op.cc``,
``optimizer.py:3048`` (Pipeline), ``trainer.h:114`` (PipelineTrainer)."""

import numpy as np
import pytest

import jax

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, optimizer
from paddle_tpu.parallel import dgc as dgc_lib


def test_dgc_compress_semantics():
    u = np.zeros(8, np.float32)
    v = np.zeros(8, np.float32)
    g = np.array([0.1, -3.0, 0.2, 2.0, -0.1, 0.05, 1.0, -0.2], np.float32)
    u1, v1, send = dgc_lib.dgc_compress(u, v, g, momentum=0.9, ratio=0.25)
    send = np.asarray(send)
    # top-2 of |v+g| survive; the rest accumulate as error feedback
    assert (send != 0).sum() == 2
    assert send[1] == -3.0 and send[3] == 2.0
    np.testing.assert_allclose(np.asarray(v1)[1], 0.0)
    np.testing.assert_allclose(np.asarray(v1)[0], 0.1)  # residual kept
    np.testing.assert_allclose(np.asarray(u1)[1], 0.0)  # masked out of u too


def test_dgc_momentum_trains_and_is_sparse():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 5
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[16], dtype="float32")
        y = layers.fc(x, 16, act="tanh")
        loss = layers.mean(layers.fc(y, 1))
        opt = optimizer.DGCMomentumOptimizer(
            learning_rate=0.05, momentum=0.9, sparsity=(0.75,))
        opt.minimize(loss)
    # a dgc op exists and feeds a plain sgd update
    types = [op.type for op in main.global_block().ops]
    assert "dgc" in types and "sgd" in types
    exe = fluid.Executor()
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(8, 16).astype(np.float32)}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        losses = [float(np.asarray(exe.run(main, feed=feed,
                                           fetch_list=[loss])[0]).ravel()[0])
                  for _ in range(20)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_dgc_rampup_behaves_like_momentum_first():
    """Before rampup_begin_step, DGC must match plain momentum exactly."""

    def build(use_dgc):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 9
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[8], dtype="float32")
            loss = layers.mean(layers.fc(x, 4))
            if use_dgc:
                opt = optimizer.DGCMomentumOptimizer(
                    learning_rate=0.1, momentum=0.9, rampup_begin_step=1000,
                    sparsity=(0.9,))
            else:
                opt = optimizer.MomentumOptimizer(learning_rate=0.1,
                                                  momentum=0.9)
            opt.minimize(loss)
        return main, startup, loss

    rng = np.random.RandomState(1)
    feed = {"x": rng.rand(4, 8).astype(np.float32)}
    out = {}
    for use in (False, True):
        main, startup, loss = build(use)
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            out[use] = [float(np.asarray(
                exe.run(main, feed=feed, fetch_list=[loss])[0]).ravel()[0])
                for _ in range(5)]
    np.testing.assert_allclose(out[False], out[True], rtol=1e-6)


def test_dgc_gradallreduce_moves_allreduce_to_compressed():
    from paddle_tpu.fluid.transpiler import collective as coll

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        loss = layers.mean(layers.fc(x, 4))
        opt = optimizer.DGCMomentumOptimizer(learning_rate=0.1, momentum=0.9)
        opt.minimize(loss)
    coll.GradAllReduce(nranks=2).transpile(startup, main)
    block = main.global_block()
    dgc_ops = [op for op in block.ops if op.type == "dgc"]
    assert dgc_ops
    dense_grads = set()
    for op in block.ops:
        if op.type == "autodiff":
            dense_grads.update(op.attr("grad_names"))
    compressed = {n for op in dgc_ops for n in op.output("GradOut")}
    ar_targets = {op.input("X")[0] for op in block.ops
                  if op.type == "c_allreduce_sum"}
    assert ar_targets & compressed, "no allreduce on compressed grads"
    assert not (ar_targets & dense_grads), "dense DGC grad allreduced"


def test_dgc_sparsity_ramp():
    """Multi-entry sparsity warms up: early steps keep more entries than
    late steps (reference rampup_step semantics)."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 2
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[64], dtype="float32")
        loss = layers.mean(layers.fc(x, 64))
        opt = optimizer.DGCMomentumOptimizer(
            learning_rate=0.01, momentum=0.9, rampup_begin_step=0,
            rampup_step=4, sparsity=(0.5, 0.9375))
        opt.minimize(loss)
    block = main.global_block()
    gout = next(op for op in block.ops if op.type == "dgc").output("GradOut")[0]
    exe = fluid.Executor()
    feed = {"x": np.random.RandomState(0).rand(4, 64).astype(np.float32)}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        nnz = []
        for _ in range(5):
            g = np.asarray(exe.run(main, feed=feed, fetch_list=[gout])[0])
            nnz.append(int((g != 0).sum()))
    # steps 0-1 run sparsity 0.5 (keep ~2048), steps >=2 run 0.9375 (~256)
    assert nnz[0] > nnz[-1], nnz


# ---------------------------------------------------------------------------
# Program-level pipeline


def _build_mlp(seed=13, use_pipeline=False):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[32], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        h1 = layers.fc(x, 32, act="tanh")
        h2 = layers.fc(h1, 32, act="tanh")
        logits = layers.fc(h2, 10)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        opt = optimizer.SGD(learning_rate=0.1)
        if use_pipeline:
            opt = optimizer.PipelineOptimizer(opt, cut_list=[h1])
        opt.minimize(loss)
    return main, startup, loss


@pytest.mark.slow
def test_pipeline_program_matches_single_device():
    rng = np.random.RandomState(7)
    feed = {"x": rng.rand(8, 32).astype(np.float32),
            "label": rng.randint(0, 10, (8, 1)).astype(np.int64)}

    main, startup, loss = _build_mlp(use_pipeline=False)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        base = [float(np.asarray(exe.run(main, feed=feed,
                                         fetch_list=[loss])[0]).ravel()[0])
                for _ in range(4)]

    main, startup, loss = _build_mlp(use_pipeline=True)
    compiled = fluid.CompiledProgram(main).with_pipeline(
        loss_name=loss.name, places=jax.devices()[:2], num_microbatches=2)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        piped = [float(np.asarray(exe.run(compiled, feed=feed,
                                          fetch_list=[loss])[0]).ravel()[0])
                 for _ in range(4)]
    # GPipe with M microbatches == gradient accumulation: same losses
    np.testing.assert_allclose(base, piped, rtol=2e-4)


def test_pipeline_requires_matching_cuts():
    main, startup, loss = _build_mlp(use_pipeline=True)
    compiled = fluid.CompiledProgram(main).with_pipeline(
        loss_name=loss.name, places=jax.devices()[:4], num_microbatches=2)
    exe = fluid.Executor()
    feed = {"x": np.zeros((8, 32), np.float32),
            "label": np.zeros((8, 1), np.int64)}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        with pytest.raises(ValueError, match="cut vars"):
            exe.run(compiled, feed=feed, fetch_list=[loss])
