/* C embedder smoke driver: serves a saved model through the prd_* ABI
 * (libpredictor.so) with no Python code in this translation unit.
 * Usage: c_predict_main <model_dir> <input_name> <C> <H> <W>
 * Feeds a deterministic [1, C, H, W] ramp image and prints output 0. */

#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>

#include "../paddle_tpu/native/c_api.h"

int main(int argc, char** argv) {
  if (argc < 6) {
    fprintf(stderr, "usage: %s model_dir input_name C H W\n", argv[0]);
    return 2;
  }
  const char* model_dir = argv[1];
  const char* input_name = argv[2];
  int64_t c = atoll(argv[3]), h = atoll(argv[4]), w = atoll(argv[5]);

  int64_t handle = prd_create(model_dir, /*use_bf16=*/0);
  if (!handle) {
    fprintf(stderr, "prd_create failed\n");
    return 3;
  }

  int64_t n = c * h * w;
  float* img = (float*)malloc(n * sizeof(float));
  for (int64_t i = 0; i < n; ++i) img[i] = (float)(i % 17) / 17.0f;

  const char* names[1] = {input_name};
  const float* bufs[1] = {img};
  int64_t shape[4] = {1, c, h, w};
  int64_t ranks[1] = {4};

  float out[4096];
  int64_t out_shape[8];
  int64_t out_rank = 0;
  int rc = prd_run(handle, names, bufs, shape, ranks, 1,
                   /*out_index=*/0, out, 4096, out_shape, &out_rank);
  if (rc != 0) {
    fprintf(stderr, "prd_run rc=%d\n", rc);
    return 4;
  }
  int64_t total = 1;
  printf("shape:");
  for (int64_t i = 0; i < out_rank; ++i) {
    printf(" %lld", (long long)out_shape[i]);
    total *= out_shape[i];
  }
  printf("\nvalues:");
  for (int64_t i = 0; i < total && i < 64; ++i) printf(" %.6f", out[i]);
  printf("\n");
  free(img);
  return prd_destroy(handle) == 0 ? 0 : 5;
}
