"""Dataset engine + multiprocess DataLoader + train_from_dataset —
reference ``dataset.py``/``data_feed.cc``/``executor.py:920`` per
SURVEY §2 (Dataset/DataFeed engine, Trainer stack rows)."""

import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.fs import LocalFS, split_files


def _write_multislot(path, n_lines, seed, dense_dim=3, ragged=False):
    """Lines: dense float slot [dense_dim] + int64 id slot (1 or ragged
    1-3 ids) + float label slot [1]."""
    rng = np.random.RandomState(seed)
    rows = []
    for _ in range(n_lines):
        dense = rng.rand(dense_dim)
        n_ids = rng.randint(1, 4) if ragged else 1
        ids = rng.randint(0, 50, size=n_ids)
        label = [float(rng.randint(0, 2))]
        parts = [str(dense_dim)] + ["%.6f" % v for v in dense]
        parts += [str(n_ids)] + [str(i) for i in ids]
        parts += ["1"] + ["%.1f" % label[0]]
        rows.append(" ".join(parts))
    with open(path, "w") as f:
        f.write("\n".join(rows) + "\n")
    return rows


def _use_vars(ragged=False):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        dense = layers.data("dense", [3])
        ids = layers.data("ids", [1], dtype="int64",
                          lod_level=1 if ragged else 0)
        label = layers.data("label", [1])
    return main, startup, [dense, ids, label]


def test_in_memory_dataset_load_and_batch(tmp_path):
    f1 = str(tmp_path / "a.txt")
    f2 = str(tmp_path / "b.txt")
    _write_multislot(f1, 5, seed=1)
    _write_multislot(f2, 3, seed=2)
    _, _, use_vars = _use_vars()
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(4)
    ds.set_use_var(use_vars)
    ds.set_filelist([f1, f2])
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 8
    batches = list(ds.batch_reader()())
    assert len(batches) == 2
    assert batches[0]["dense"].shape == (4, 3)
    assert batches[0]["ids"].dtype == np.int64
    assert batches[1]["dense"].shape == (4, 3)
    # drop_last drops the trailing partial batch
    ds.set_batch_size(3)
    assert len(list(ds.batch_reader(drop_last=True)())) == 2


def test_native_and_numpy_parsers_agree(tmp_path):
    from paddle_tpu import native
    from paddle_tpu.fluid.dataset import _native_parse, _numpy_parse

    lib = native.load_data_feed()
    assert lib is not None, "native toolchain expected in this image"
    f = str(tmp_path / "c.txt")
    _write_multislot(f, 7, seed=3, ragged=True)
    raw = open(f, "rb").read()
    nat = _native_parse(lib, raw, ["f", "u", "f"])
    ref = _numpy_parse(raw.decode(), ["f", "u", "f"])
    for (nv, no), (rv, ro) in zip(nat, ref):
        np.testing.assert_allclose(nv, rv, rtol=1e-6)
        np.testing.assert_array_equal(no, ro)


def test_local_shuffle_deterministic(tmp_path):
    f = str(tmp_path / "d.txt")
    _write_multislot(f, 20, seed=4)
    _, _, use_vars = _use_vars()

    def mk():
        ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
        ds.set_batch_size(20)
        ds.set_use_var(use_vars)
        ds.set_filelist([f])
        ds.set_seed(123)
        ds.load_into_memory()
        ds.local_shuffle()
        return next(ds.batch_reader()())["dense"]

    a, b = mk(), mk()
    np.testing.assert_allclose(a, b)  # same seed -> same order
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(20)
    ds.set_use_var(use_vars)
    ds.set_filelist([f])
    ds.load_into_memory()
    unshuffled = next(ds.batch_reader()())["dense"]
    assert not np.allclose(a, unshuffled)  # shuffle moved something


def test_global_shuffle_partitions(tmp_path):
    f = str(tmp_path / "e.txt")
    _write_multislot(f, 10, seed=5)
    _, _, use_vars = _use_vars()

    class FakeFleet:
        def __init__(self, idx, num):
            self._i, self._n = idx, num

        def worker_index(self):
            return self._i

        def worker_num(self):
            return self._n

    seen = []
    for r in range(2):
        ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
        ds.set_batch_size(100)
        ds.set_use_var(use_vars)
        ds.set_filelist([f])
        ds.set_seed(7)
        ds.load_into_memory()
        ds.global_shuffle(FakeFleet(r, 2))
        assert ds.get_shuffle_data_size() == 5
        seen.append(next(ds.batch_reader()())["dense"])
    # the two trainers' shards are disjoint and cover everything
    allrows = np.concatenate(seen)
    assert allrows.shape == (10, 3)
    assert len({tuple(np.round(r, 5)) for r in allrows}) == 10


def test_queue_dataset_streams(tmp_path):
    f = str(tmp_path / "f.txt")
    _write_multislot(f, 6, seed=6)
    _, _, use_vars = _use_vars()
    ds = fluid.DatasetFactory().create_dataset("QueueDataset")
    ds.set_batch_size(2)
    ds.set_use_var(use_vars)
    ds.set_filelist([f])
    assert len(list(ds.batch_reader()())) == 3
    with pytest.raises(NotImplementedError):
        ds.local_shuffle()


def test_ragged_slot_feeds_lod(tmp_path):
    f = str(tmp_path / "g.txt")
    _write_multislot(f, 4, seed=8, ragged=True)
    _, _, use_vars = _use_vars(ragged=True)
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(4)
    ds.set_use_var(use_vars)
    ds.set_filelist([f])
    ds.load_into_memory()
    feed = next(ds.batch_reader()())
    ids = feed["ids"]
    assert hasattr(ids, "recursive_sequence_lengths")
    lens = ids.recursive_sequence_lengths()[-1]
    assert len(lens) == 4 and all(1 <= n <= 3 for n in lens)


def test_pipe_command_filters(tmp_path):
    f = str(tmp_path / "h.txt")
    _write_multislot(f, 6, seed=9)
    _, _, use_vars = _use_vars()
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(100)
    ds.set_use_var(use_vars)
    ds.set_filelist([f])
    ds.set_pipe_command("head -n 2")
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 2


def test_train_from_dataset_e2e(tmp_path):
    """Executor.train_from_dataset: a linear model fits multislot data."""
    f = str(tmp_path / "train.txt")
    rng = np.random.RandomState(11)
    w_true = np.array([1.5, -2.0, 0.5])
    with open(f, "w") as fh:
        for _ in range(64):
            x = rng.rand(3)
            y = float(x @ w_true)
            fh.write("3 %f %f %f 1 0 1 %f\n" % (x[0], x[1], x[2], y))
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        dense = layers.data("dense", [3])
        ids = layers.data("ids", [1], dtype="int64")
        label = layers.data("label", [1])
        pred = layers.fc(dense, 1)
        loss = layers.reduce_mean(layers.square(pred - label))
        fluid.optimizer.SGD(learning_rate=0.5).minimize(loss)
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(16)
    ds.set_use_var([dense, ids, label])
    ds.set_filelist([f])
    ds.load_into_memory()
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        first = None
        for epoch in range(15):
            ds.local_shuffle()
            n = exe.train_from_dataset(main, ds, fetch_list=[loss])
            assert n == 4
        feed = next(ds.batch_reader()())
        (final_loss,) = exe.run(main, feed=feed, fetch_list=[loss])
    assert float(np.asarray(final_loss)) < 0.01


def test_dataloader_from_dataset(tmp_path):
    f = str(tmp_path / "i.txt")
    _write_multislot(f, 8, seed=12)
    _, _, use_vars = _use_vars()
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(4)
    ds.set_use_var(use_vars)
    ds.set_filelist([f])
    ds.load_into_memory()
    loader = fluid.DataLoader.from_dataset(ds)
    batches = list(loader)
    assert len(batches) == 2
    assert np.asarray(batches[0]["dense"]).shape == (4, 3)


def test_multiprocess_dataloader_covers_stream():
    """mp workers split the batch stream round-robin with no loss."""
    data = np.arange(40, dtype=np.float32).reshape(10, 4)

    def gen():
        for i in range(10):
            yield [data[i:i + 1]]

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [4])
    loader = fluid.DataLoader.from_generator(
        feed_list=[x], use_multiprocess=True, num_workers=3,
        stage_on_device=False)
    loader.set_batch_generator(gen)
    rows = sorted(float(np.asarray(b["x"])[0, 0]) for b in loader)
    assert rows == [float(v) for v in data[:, 0]]


def test_local_fs_and_split_files(tmp_path):
    fs = LocalFS()
    d = str(tmp_path / "sub")
    fs.makedirs(d)
    p = os.path.join(d, "x.bin")
    with open(p, "wb") as f:
        f.write(b"hello")
    assert fs.is_file(p) and fs.is_dir(d) and fs.is_exist(p)
    assert fs.cat(p) == b"hello"
    assert fs.ls_dir(d) == ["x.bin"]
    p2 = os.path.join(d, "y.bin")
    fs.rename(p, p2)
    assert fs.is_exist(p2) and not fs.is_exist(p)
    fs.delete(d)
    assert not fs.is_exist(d)
    files = ["f%d" % i for i in range(7)]
    s0 = split_files(files, 0, 3)
    s1 = split_files(files, 1, 3)
    s2 = split_files(files, 2, 3)
    assert sorted(s0 + s1 + s2) == sorted(files)
    assert not (set(s0) & set(s1))


def test_hdfs_client_without_hadoop_errors():
    from paddle_tpu.fs import ExecuteError, HDFSClient

    client = HDFSClient("hdfs://nowhere:9000", "user,passwd")
    client._hadoop = "definitely_not_a_real_binary"
    with pytest.raises(ExecuteError):
        client.cat("hdfs://nowhere:9000/x")


def test_native_parser_rejects_truncated_line(tmp_path):
    from paddle_tpu import native
    from paddle_tpu.fluid.dataset import _native_parse

    lib = native.load_data_feed()
    assert lib is not None
    # slot 0 claims 2 floats but only has 1; next line must NOT be merged
    bad = b"2 1.0\n2 3.0 4.0\n"
    with pytest.raises(ValueError):
        _native_parse(lib, bad, ["f"])


def test_ragged_batches_share_feed_signature(tmp_path):
    """Different token totals pad to the same power-of-two bound, so the
    executor compiles once, not per batch."""
    f = str(tmp_path / "sig.txt")
    _write_multislot(f, 8, seed=20, ragged=True)
    _, _, use_vars = _use_vars(ragged=True)
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(4)
    ds.set_use_var(use_vars)
    ds.set_filelist([f])
    ds.load_into_memory()
    shapes = {np.asarray(feed["ids"]).shape
              for feed in ds.batch_reader()()}
    assert len(shapes) == 1, shapes  # both batches hit the same bucket


def test_multiprocess_worker_error_propagates():
    def bad_gen():
        yield [np.zeros((1, 4), np.float32)]
        raise RuntimeError("boom in worker")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [4])
    loader = fluid.DataLoader.from_generator(
        feed_list=[x], use_multiprocess=True, num_workers=1,
        stage_on_device=False)
    loader.set_batch_generator(bad_gen)
    with pytest.raises(RuntimeError, match="worker 0 died"):
        list(loader)


def test_multiprocess_preserves_lod():
    from paddle_tpu.fluid.lod import LoDTensor

    def gen():
        yield [LoDTensor(np.arange(6, dtype=np.float32)[:, None],
                         [[4, 2]])]

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [1], lod_level=1)
    loader = fluid.DataLoader.from_generator(
        feed_list=[x], use_multiprocess=True, num_workers=1,
        stage_on_device=False)
    loader.set_batch_generator(gen)
    (batch,) = list(loader)
    assert hasattr(batch["x"], "recursive_sequence_lengths")
    assert batch["x"].recursive_sequence_lengths() == [[4, 2]]


def test_worker_info_sharding():
    """A shard-aware generator keeps every batch it yields."""
    from paddle_tpu.fluid.reader import get_worker_info

    def gen():
        info = get_worker_info()
        assert info is not None
        info.mark_sharded()
        for i in range(info.id, 6, info.num_workers):
            yield [np.full((1, 2), float(i), np.float32)]

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [2])
    loader = fluid.DataLoader.from_generator(
        feed_list=[x], use_multiprocess=True, num_workers=2,
        stage_on_device=False)
    loader.set_batch_generator(gen)
    vals = sorted(float(np.asarray(b["x"])[0, 0]) for b in loader)
    assert vals == [0.0, 1.0, 2.0, 3.0, 4.0, 5.0]


def test_dataset_library_modules():
    """Every reference dataset module exists and yields the documented
    sample shapes (synthetic fallback in this sealed image)."""
    from paddle_tpu import dataset as D

    wd = D.imikolov.build_dict(min_word_freq=1)
    grams = list(D.imikolov.train(wd, 4)())[:5]
    assert all(len(g) == 4 for g in grams)
    seqs = list(D.imikolov.train(wd, 4, D.imikolov.SEQ)())[:2]
    assert len(seqs[0]) == 2

    rows = list(D.movielens.train()())[:3]
    assert len(rows[0]) == 8  # 4 user + 3 movie + score
    assert D.movielens.max_user_id() >= 1

    s, t_in, t_out = next(iter(D.wmt14.train(100)()))
    assert t_in[0] == 0 and t_out[-1] == 1  # <s> prefix, <e> suffix
    s16 = next(iter(D.wmt16.train(100, 100)()))
    assert len(s16) == 3

    sample = next(iter(D.conll05.test()()))
    assert len(sample) == 9
    n = len(sample[0])
    assert all(len(col) == n for col in sample[1:])

    wd2 = D.sentiment.get_word_dict()
    ids, label = next(iter(D.sentiment.train()()))
    assert label in (0, 1) and max(ids) < len(wd2)

    img, lbl = next(iter(D.flowers.train()()))
    assert img.shape == (3, 64, 64)
    img2, mask = next(iter(D.voc2012.train()()))
    assert mask.shape == (64, 64) and mask.max() > 0


def test_image_transforms():
    from paddle_tpu.dataset import image as I

    img = np.arange(3 * 40 * 60, dtype=np.float32).reshape(3, 40, 60)
    r = I.resize_short(img, 20)
    assert r.shape == (3, 20, 30)  # short side 20, aspect kept
    c = I.center_crop(r, 16)
    assert c.shape == (3, 16, 16)
    f = I.left_right_flip(c)
    np.testing.assert_allclose(f[:, :, 0], c[:, :, -1])
    t = I.simple_transform(img, 24, 16, is_train=True,
                           rng=np.random.RandomState(0))
    assert t.shape == (3, 16, 16)


def test_mq2007_formats():
    from paddle_tpu.dataset import mq2007

    a, b = next(iter(mq2007.train("pairwise")()))
    assert a.shape == (46,) and b.shape == (46,)
    f, l = next(iter(mq2007.train("pointwise")()))
    assert f.shape == (46,) and isinstance(l, float)
    labels, feats = next(iter(mq2007.train("listwise")()))
    assert feats.shape == (len(labels), 46)


def test_core_memory_stats_surface():
    import paddle_tpu.fluid.core as core

    stats = core.memory_stats()
    assert isinstance(stats, dict)
    assert core.memory_allocated() >= 0
    assert core.max_memory_allocated() >= 0


def test_mq2007_rejects_bad_format_and_reads_cached(tmp_path,
                                                    monkeypatch):
    from paddle_tpu.dataset import common, mq2007

    with pytest.raises(ValueError):
        mq2007.train("list_wise")
    # a cached LETOR split is parsed as real data (no synthetic warning)
    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path))
    d = tmp_path / "mq2007"
    d.mkdir()
    (d / "train.txt").write_text(
        "2 qid:1 1:0.5 2:0.25 #doc\n0 qid:1 1:0.1 2:0.9\n"
        "1 qid:2 1:0.7 2:0.3\n")
    labels, feats = next(iter(mq2007.train("listwise")()))
    assert feats.shape == (2, 46)
    np.testing.assert_allclose(feats[0, :2], [0.5, 0.25])
    np.testing.assert_array_equal(sorted(labels), [0, 2])


def test_global_shuffle_exchange_nprocess(tmp_path):
    """Exchange-based global shuffle (reference GlobalShuffle,
    data_set.h:100): 3 PROCESSES each load only 1/3 of the files, the
    samples exchange over TCP, and the union of the post-shuffle sets is
    exactly the global sample set with pairwise-disjoint shares."""
    import json
    import socket
    import subprocess
    import sys

    n = 3
    files, expected = [], set()
    for k in range(n):
        f = str(tmp_path / ("part%d.txt" % k))
        _write_multislot(f, 6 + k, seed=10 + k)
        files.append(f)
        # key = first dense value of each sample (distinct w.h.p.)
        for line in open(f):
            expected.add("%.6f" % float(line.split()[1]))
    outs = [str(tmp_path / ("out%d.json" % k)) for k in range(n)]
    rdv = [str(tmp_path / ("port%d" % k)) for k in range(n)]
    cfg = {"files": files, "rdv": rdv, "out": outs}
    here = os.path.dirname(os.path.abspath(__file__))
    procs = []
    for k in range(n):
        c = dict(cfg)
        c["trainer_id"] = k
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(here, "dist_runner_exchange.py"),
             json.dumps(c)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    for p in procs:
        out, err = p.communicate(timeout=180)
        assert p.returncode == 0, err.decode()[-2000:]
    shares, shares2 = [], []
    for k in range(n):
        with open(outs[k]) as f:
            r = json.load(f)
        assert r["loaded"] == 6 + k       # only its own file was loaded
        shares.append(set(r["keys"]))
        assert len(r["keys"]) == len(shares[-1])  # no dup within a share
        shares2.append(set(r["keys2"]))
        assert len(r["keys2"]) == len(shares2[-1])
    # both back-to-back rounds must partition the global set exactly —
    # round ids keep a fast peer's second-round frames out of a slow
    # peer's first-round collection
    for sh in (shares, shares2):
        assert set().union(*sh) == expected
        for a in range(n):
            for b in range(a + 1, n):
                assert not (sh[a] & sh[b])


def test_exchange_round_isolation():
    """A fast peer's round-(r+1) SEND/DONE frames arriving BEFORE the
    slow peer drains round r must queue, not bleed: wait() for round r
    returns only round-r samples, and the queued round-(r+1) frames are
    returned by the next wait() (ADVICE r4 #4)."""
    from paddle_tpu.distributed.sample_exchange import (ExchangeServer,
                                                        _Sender)

    server = ExchangeServer(port=0, token="xchg")
    try:
        ep = "127.0.0.1:%d" % server.port
        r0 = [(np.array([0.5], np.float32),)]
        r1 = [(np.array([1.5], np.float32),),
              (np.array([2.5], np.float32),)]
        # the fast peer finishes round 0 AND round 1 before the slow
        # peer's server owner ever calls wait()
        s = _Sender(ep, "xchg")
        s.send(r0, rnd=0)
        s.done(rnd=0)
        s2 = _Sender(ep, "xchg")
        s2.send(r1, rnd=1)
        s2.done(rnd=1)

        got0 = server.wait(n_senders=1, timeout=30)
        assert [float(x[0][0]) for x in got0] == [0.5]
        got1 = server.wait(n_senders=1, timeout=30)
        assert sorted(float(x[0][0]) for x in got1) == [1.5, 2.5]
        # stale frames (round already drained) are NACKed so a desynced
        # sender raises instead of silently losing its share
        s3 = _Sender(ep, "xchg")
        with pytest.raises(RuntimeError, match="stale round"):
            s3.send(r0, rnd=0)
        s4 = _Sender(ep, "xchg")
        s4.send([(np.array([9.5], np.float32),)], rnd=2)
        s4.done(rnd=2)
        got2 = server.wait(n_senders=1, timeout=30)
        assert [float(x[0][0]) for x in got2] == [9.5]
    finally:
        server.stop()


def test_train_from_dataset_double_buffer_loss_identical(tmp_path):
    """The ahead-dispatch double buffer must not change the math: the
    same dataset driven through train_from_dataset and through a manual
    run() loop lands on bit-identical parameters."""
    f = str(tmp_path / "d.txt")
    _write_multislot(f, 12, seed=21)

    def build():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            dense = layers.data("dense", [3])
            ids = layers.data("ids", [1], dtype="int64")
            label = layers.data("label", [1])
            pred = layers.fc(dense, 1, name="w")
            loss = layers.reduce_mean(
                layers.square_error_cost(pred, label))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        return main, startup, [dense, ids, label], loss

    def make_ds(use_vars):
        ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
        ds.set_batch_size(4)
        ds.set_use_var(use_vars)
        ds.set_filelist([f])
        ds.load_into_memory()
        return ds

    results = []
    for mode in ("tfd", "manual"):
        main, startup, use_vars, loss = build()
        ds = make_ds(use_vars)
        exe = fluid.Executor()
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            if mode == "tfd":
                n = exe.train_from_dataset(main, ds, fetch_list=[loss])
                assert n == 3
            else:
                for feed in ds.batch_reader()():
                    exe.run(main, feed=feed, fetch_list=[loss])
            wname = [v.name for v in main.list_vars()
                     if v.persistable and ".w_" in v.name][0]
            results.append(np.asarray(scope.find_var(wname)))
    np.testing.assert_array_equal(results[0], results[1])


def test_train_from_dataset_ragged_lod_feed(tmp_path):
    """The double-buffer staging must pass LoDTensor (ragged slot) feeds
    through to run()'s decomposition untouched."""
    f = str(tmp_path / "r.txt")
    _write_multislot(f, 8, seed=31, ragged=True)
    main, startup, use_vars = _use_vars(ragged=True)
    with fluid.program_guard(main, startup):
        emb = layers.embedding(use_vars[1], size=[50, 4], is_sparse=False)
        pooled = layers.sequence_pool(emb, "sum")
        pred = layers.fc(pooled, 1)
        loss = layers.reduce_mean(
            layers.square_error_cost(pred, use_vars[2]))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(4)
    ds.set_use_var(use_vars)
    ds.set_filelist([f])
    ds.load_into_memory()
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        n = exe.train_from_dataset(main, ds, fetch_list=[loss])
    assert n == 2


def test_global_shuffle_deterministic_under_set_seed(tmp_path):
    """Two global_shuffles from the same set_seed produce the same order
    (no fleet: the shuffle itself is the only reordering)."""
    f = str(tmp_path / "gs.txt")
    _write_multislot(f, 30, seed=6)
    _, _, use_vars = _use_vars()

    def shuffled():
        ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
        ds.set_batch_size(30)
        ds.set_use_var(use_vars)
        ds.set_filelist([f])
        ds.set_seed(321)
        ds.load_into_memory()
        ds.global_shuffle()
        return next(ds.batch_reader()())["dense"]

    a, b = shuffled(), shuffled()
    np.testing.assert_allclose(a, b)
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(30)
    ds.set_use_var(use_vars)
    ds.set_filelist([f])
    ds.load_into_memory()
    unshuffled = next(ds.batch_reader()())["dense"]
    assert not np.allclose(a, unshuffled)  # it did reorder something


def test_queue_dataset_global_shuffle_error_names_alternative():
    ds = fluid.DatasetFactory().create_dataset("QueueDataset")
    with pytest.raises(NotImplementedError, match="InMemoryDataset"):
        ds.global_shuffle()
