"""Shared resilience primitives (fluid/resilience.py), the fault
harness (fluid/faults.py), the catch-all lint (tools/check_resilience),
and the background-thread exception-surfacing contracts of the four
``except BaseException`` sites (reader stager, mp worker, async pusher,
window prefetch)."""

import os
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.fluid import faults, monitor, resilience  # noqa: E402
from paddle_tpu.fluid.resilience import (  # noqa: E402
    CircuitBreaker, CircuitOpenError, Retry, TransientError, backoff_delay)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


# -- backoff_delay ----------------------------------------------------------

def test_backoff_grows_exponentially_and_caps():
    ds = [backoff_delay(a, base=0.1, factor=2.0, max_delay=1.0, jitter=0)
          for a in range(6)]
    assert ds[:4] == [pytest.approx(0.1), pytest.approx(0.2),
                      pytest.approx(0.4), pytest.approx(0.8)]
    assert ds[4] == ds[5] == pytest.approx(1.0)  # capped


def test_backoff_jitter_bounded():
    d = backoff_delay(0, base=1.0, jitter=0.5, rand=lambda: 1.0)
    assert d == pytest.approx(1.5)
    d = backoff_delay(0, base=1.0, jitter=0.5, rand=lambda: 0.0)
    assert d == pytest.approx(1.0)


# -- Retry ------------------------------------------------------------------

def _no_sleep_retry(**kw):
    kw.setdefault("jitter", 0)
    return Retry(sleep=lambda s: None, **kw)


def test_retry_succeeds_after_transient_failures():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TransientError("blip")
        return "ok"

    assert _no_sleep_retry(max_attempts=5).call(flaky) == "ok"
    assert len(calls) == 3


def test_retry_exhaustion_reraises_last_exception():
    r = _no_sleep_retry(max_attempts=3)
    calls = []

    def always(n=[0]):
        calls.append(1)
        raise TransientError("attempt %d" % len(calls))

    with pytest.raises(TransientError, match="attempt 3"):
        r.call(always)
    assert len(calls) == 3


def test_retry_nonretryable_surfaces_immediately():
    calls = []

    def bad():
        calls.append(1)
        raise ValueError("programming error")

    with pytest.raises(ValueError):
        _no_sleep_retry(max_attempts=5).call(bad)
    assert len(calls) == 1


def test_retry_deadline_stops_early():
    clock = [0.0]
    sleeps = []

    def fake_sleep(s):
        sleeps.append(s)
        clock[0] += s

    r = Retry(max_attempts=100, base_delay=1.0, factor=1.0, jitter=0,
              deadline=2.5, sleep=fake_sleep, clock=lambda: clock[0])
    calls = []

    def always():
        calls.append(1)
        raise TransientError

    with pytest.raises(TransientError):
        r.call(always)
    # attempt 1 (t=0), sleep 1, attempt 2 (t=1), sleep 1, attempt 3
    # (t=2): next sleep would land past the 2.5s deadline -> give up
    assert len(calls) == 3


def test_retry_custom_predicate_and_decorator():
    pred = lambda e: isinstance(e, KeyError)  # noqa: E731
    calls = []

    @Retry(max_attempts=2, jitter=0, retryable=pred,
           sleep=lambda s: None, name="test.pred")
    def fn():
        calls.append(1)
        raise KeyError("x")

    with pytest.raises(KeyError):
        fn()
    assert len(calls) == 2


def test_retry_counts_in_monitor():
    before_a = monitor.counter(
        "resilience_retry_attempts_total",
        labels={"site": "test.count"}).value
    before_e = monitor.counter(
        "resilience_retry_exhausted_total",
        labels={"site": "test.count"}).value
    r = _no_sleep_retry(max_attempts=3, name="test.count")
    with pytest.raises(TransientError):
        r.call(lambda: (_ for _ in ()).throw(TransientError()))
    a = monitor.counter("resilience_retry_attempts_total",
                        labels={"site": "test.count"}).value
    e = monitor.counter("resilience_retry_exhausted_total",
                        labels={"site": "test.count"}).value
    assert a - before_a == 2  # two retried failures, the third exhausts
    assert e - before_e == 1


def test_retry_validates_args():
    with pytest.raises(ValueError):
        Retry(max_attempts=0)
    with pytest.raises(TypeError):
        Retry(retryable=42)


# -- CircuitBreaker ---------------------------------------------------------

def test_breaker_trips_after_consecutive_failures():
    clock = [0.0]
    b = CircuitBreaker(failure_threshold=3, reset_timeout=10.0,
                       name="test.trip", clock=lambda: clock[0])

    def boom():
        raise TransientError

    for _ in range(3):
        with pytest.raises(TransientError):
            b.call(boom)
    assert b.state == CircuitBreaker.OPEN
    with pytest.raises(CircuitOpenError):
        b.call(lambda: "never runs")
    # success resets the consecutive count while closed
    clock[0] += 11.0  # half-open: one probe allowed
    assert b.state == CircuitBreaker.HALF_OPEN
    assert b.call(lambda: "probe ok") == "probe ok"
    assert b.state == CircuitBreaker.CLOSED


def test_breaker_halfopen_probe_failure_reopens():
    clock = [0.0]
    b = CircuitBreaker(failure_threshold=1, reset_timeout=5.0,
                       name="test.reopen", clock=lambda: clock[0])
    with pytest.raises(TransientError):
        b.call(lambda: (_ for _ in ()).throw(TransientError()))
    assert b.state == CircuitBreaker.OPEN
    clock[0] += 6.0
    with pytest.raises(TransientError):
        b.call(lambda: (_ for _ in ()).throw(TransientError()))
    assert b.state == CircuitBreaker.OPEN  # probe failed -> re-open


def test_breaker_halfopen_single_probe():
    clock = [0.0]
    b = CircuitBreaker(failure_threshold=1, reset_timeout=5.0,
                       name="test.probe", clock=lambda: clock[0])
    b.record_failure()
    clock[0] += 6.0
    assert b.allow() is True    # the probe
    assert b.allow() is False   # concurrent second caller rejected
    b.record_success()
    assert b.allow() is True    # closed again


# -- faults harness ---------------------------------------------------------

def test_faults_arm_check_fire_window():
    faults.arm("io.write", after_n=2, times=1)
    faults.check("io.write")        # hit 1: passes
    faults.check("io.write")        # hit 2: passes
    with pytest.raises(faults.FaultInjected):
        faults.check("io.write")    # hit 3: fires
    faults.check("io.write")        # hit 4: window over, passes again
    assert faults.hits("io.write") == 4


def test_faults_unknown_point_rejected():
    with pytest.raises(ValueError, match="unknown fault point"):
        faults.arm("no.such.point")


def test_faults_custom_exception_class():
    faults.arm("reader.stage", exc=RuntimeError)
    with pytest.raises(RuntimeError):
        faults.check("reader.stage")


def test_faults_take_returns_bool():
    faults.arm("step.nonfinite", after_n=0, times=1)
    assert faults.take("step.nonfinite") is True
    assert faults.take("step.nonfinite") is False


def test_faults_env_parsing():
    assert faults._parse_env("io.write:3,ps.rpc:0:2") == [
        ("io.write", 3, 1), ("ps.rpc", 0, 2)]
    with pytest.raises(ValueError):
        faults._parse_env("io.write")
    faults.arm_from_env({"PADDLE_FAULTS": "worker.exit:5"})
    assert faults.is_armed("worker.exit")
    faults.reset()


def test_faults_injected_is_transient():
    # the default injected class MUST be retryable by default-config
    # Retry layers, or the absorb tests test nothing
    assert issubclass(faults.FaultInjected, TransientError)


# -- the catch-all lint -----------------------------------------------------

def _repo_root():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_repo_has_no_unjustified_catchalls():
    sys.path.insert(0, os.path.join(_repo_root(), "tools"))
    try:
        import check_resilience
    finally:
        sys.path.pop(0)
    violations = check_resilience.check_tree(_repo_root())
    assert violations == [], (
        "unjustified bare-except/BaseException sites:\n%s"
        % "\n".join("%s:%d: %s" % v for v in violations))


def test_lint_catches_violations(tmp_path):
    sys.path.insert(0, os.path.join(_repo_root(), "tools"))
    try:
        import check_resilience
    finally:
        sys.path.pop(0)
    bad = tmp_path / "bad.py"
    bad.write_text(
        "try:\n    pass\nexcept:\n    pass\n"
        "try:\n    pass\nexcept BaseException as e:\n    raise\n")
    assert len(check_resilience.check_file(str(bad))) == 2
    ok = tmp_path / "ok.py"
    ok.write_text(
        "try:\n    pass\n"
        "except BaseException:  # re-raised on the consumer thread\n"
        "    raise\n"
        "try:\n    pass\nexcept ValueError:\n    pass\n")
    assert check_resilience.check_file(str(ok)) == []
    # a '#' inside a string is not a justification
    sneaky = tmp_path / "sneaky.py"
    sneaky.write_text(
        "try:\n    pass\nexcept BaseException:\n    x = '# not a comment'\n")
    assert len(check_resilience.check_file(str(sneaky))) == 1


def test_lint_wal_discipline(tmp_path):
    """Every mutating CoordServer._do_* handler must journal to the
    WAL; read-only ones must say so on their def line."""
    sys.path.insert(0, os.path.join(_repo_root(), "tools"))
    try:
        import check_resilience
    finally:
        sys.path.pop(0)
    src = (
        "class CoordServer:\n"
        "    def _do_put(self, k, v):\n"
        "        self._journal({'o': 'put'})\n"
        "    def _do_list(self, p):  # wal: read-only (enumeration)\n"
        "        return []\n"
        "    def _do_sneaky(self, k):\n"
        "        return 1\n"
        "class Other:\n"
        "    def _do_elsewhere(self):\n"
        "        return 2\n")
    viol = check_resilience._wal_violations(src)
    assert len(viol) == 1 and "_do_sneaky" in viol[0][1], viol
    # the rule only fires on the coordination module itself
    other = tmp_path / "not_coordination.py"
    other.write_text(src)
    assert check_resilience.check_file(str(other)) == []


# -- background-exception surfacing contracts -------------------------------
# The runtime's four long-lived catch-all sites must deliver the
# ORIGINAL exception to the consumer, not swallow it.

def test_device_stager_surfaces_transform_error():
    from paddle_tpu.fluid.reader import DeviceStager

    def transform(item):
        raise ValueError("original message %d" % item)

    stager = DeviceStager(iter([7]), transform=transform, name="t")
    try:
        with pytest.raises(ValueError, match="original message 7"):
            for _ in stager:
                pass
    finally:
        stager.close()


def test_device_stager_surfaces_source_error():
    from paddle_tpu.fluid.reader import DeviceStager

    def gen():
        yield 1
        raise KeyError("source died")

    stager = DeviceStager(gen(), name="t")
    got = []
    try:
        with pytest.raises(KeyError, match="source died"):
            for item in stager:
                got.append(item)
    finally:
        stager.close()
    assert got == [1]


def test_mp_worker_surfaces_error_with_traceback():
    from paddle_tpu.fluid import reader as fr

    loader = fr.GeneratorLoader(["x"], use_multiprocess=True,
                                num_workers=1)

    def gen():
        yield [np.zeros((2, 3), np.float32)]
        raise RuntimeError("worker exploded here")

    loader.set_batch_generator(gen)
    with pytest.raises(RuntimeError) as ei:
        for _ in loader:
            pass
    # the original traceback text must ride along for debuggability
    assert "worker exploded here" in str(ei.value)
    assert "Traceback" in str(ei.value)


def test_async_pusher_surfaces_push_error_on_flush():
    from paddle_tpu.distributed.ps import AsyncPusher, EmbeddingTable

    table = EmbeddingTable(vocab=8, dim=2)
    pusher = AsyncPusher(table)
    try:
        pusher.push(np.array([999], np.int64),  # out of range
                    np.ones((1, 2), np.float32))
        with pytest.raises(Exception) as ei:
            pusher.flush()
            # the deferred error re-raises from flush() or the next push
            pusher.push(np.array([0], np.int64),
                        np.ones((1, 2), np.float32))
            pusher.flush()
        assert ei.value is not None
    finally:
        pusher.stop()


def test_window_prefetch_surfaces_reader_error():
    from paddle_tpu.fluid.executor import _WindowPrefetch

    class FakeReader:
        names = ["slot0"]

        def _next(self):
            raise OSError("reader pipe broke")

    pf = _WindowPrefetch([FakeReader()], iters=3)
    status = pf.consume()
    assert status[0] == "error"
    assert isinstance(status[1], OSError)
    assert "reader pipe broke" in str(status[1])


# -- retry wiring at the call sites -----------------------------------------

def test_stager_absorbs_transient_stage_fault():
    from paddle_tpu.fluid.reader import DeviceStager, stage_feed

    faults.arm("reader.stage", after_n=0, times=1)  # first batch blips
    stager = DeviceStager(
        iter([{"x": np.ones((2, 2), np.float32)} for _ in range(3)]),
        transform=lambda feed: stage_feed(feed), name="t")
    got = list(stager)
    stager.close()
    assert len(got) == 3  # the injected fault was retried, not fatal
    assert faults.hits("reader.stage") >= 2


def test_stager_nontransient_stage_error_surfaces():
    from paddle_tpu.fluid.reader import DeviceStager, stage_feed

    faults.arm("reader.stage", exc=TypeError)  # not retryable
    stager = DeviceStager(
        iter([{"x": np.ones((2, 2), np.float32)}]),
        transform=lambda feed: stage_feed(feed), name="t")
    try:
        with pytest.raises(TypeError):
            list(stager)
    finally:
        stager.close()


def test_async_pusher_retries_transient_push():
    from paddle_tpu.distributed.ps import AsyncPusher, EmbeddingTable

    table = EmbeddingTable(vocab=8, dim=2)
    fails = [2]
    orig_push = table.push

    def flaky_push(*a, **kw):
        if fails[0] > 0:
            fails[0] -= 1
            raise ConnectionError("push blip")
        return orig_push(*a, **kw)

    table.push = flaky_push
    pusher = AsyncPusher(table)
    try:
        pusher.push(np.array([1], np.int64),
                    np.full((1, 2), 2.0, np.float32))
        pusher.flush()  # transient failures absorbed by the retry
    finally:
        pusher.stop()
    assert fails[0] == 0
    row = table.pull(np.array([1], np.int64))
    assert np.any(row != 0)  # the push landed despite the blips
