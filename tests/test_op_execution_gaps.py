"""Real-execution coverage for op types the rest of the suite exercises
only under other names (aliases, optimizer classes, shard_map-only
collectives): each runs through a Program so the EXECUTION-based gate
(test_zz_coverage_gate.py) sees its lowering fire, with numerics checked
where single-rank semantics are defined."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers

RNG = np.random.RandomState(77)
A = (RNG.rand(3, 4).astype(np.float32) * 2 - 1) * 2


def _run_ops(build, feed):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        fetch = build(main.global_block())
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        return [np.asarray(r) for r in
                exe.run(main, feed=feed, fetch_list=fetch)]


def _raw(blk, op_type, inputs, n_out=1, attrs=None, out_slots=None):
    outs = [blk.create_var(name="%s_o%d" % (op_type, i), shape=(-1,),
                           dtype="float32") for i in range(n_out)]
    slots = out_slots or ["Out"]
    out_map = {s: [o] for s, o in zip(slots, outs)}
    blk.append_op(op_type, inputs=inputs, outputs=out_map,
                  attrs=dict(attrs or {}))
    return outs if n_out > 1 else outs[0]


def test_unary_tensor_gap_ops():
    def build(blk):
        x = layers.data("x", list(A.shape), append_batch_size=False)
        pos = layers.data("p", list(A.shape), append_batch_size=False)
        return [
            _raw(blk, "abs", {"X": [x]}),
            _raw(blk, "exp", {"X": [x]}),
            _raw(blk, "sqrt", {"X": [pos]}),
            _raw(blk, "sign", {"X": [x]}),
            _raw(blk, "cumsum", {"X": [x]}, attrs={"axis": -1}),
            _raw(blk, "argsort", {"X": [x]}, n_out=2,
                 out_slots=["Out", "Indices"])[0],
            _raw(blk, "shape", {"Input": [x]}),
            _raw(blk, "reduce_all", {"X": [layers.cast(x > -10, "bool")]},
                 attrs={"dim": [1]}),
            _raw(blk, "pow_scalar", {"X": [x]}, attrs={"factor": 3.0}),
            _raw(blk, "share_data", {"X": [x]}),
        ]

    feed = {"x": A, "p": np.abs(A) + 0.1}
    (ab, ex, sq, sg, cs, srt, shp, ra, pw, sd) = _run_ops(build, feed)
    np.testing.assert_allclose(ab, np.abs(A), rtol=1e-6)
    np.testing.assert_allclose(ex, np.exp(A), rtol=1e-5)
    np.testing.assert_allclose(sq, np.sqrt(np.abs(A) + 0.1), rtol=1e-6)
    np.testing.assert_allclose(sg, np.sign(A))
    np.testing.assert_allclose(cs, np.cumsum(A, -1), rtol=1e-5)
    np.testing.assert_allclose(srt, np.sort(A, -1), rtol=1e-6)
    np.testing.assert_array_equal(shp, A.shape)
    np.testing.assert_array_equal(ra, np.ones(3, bool))
    np.testing.assert_allclose(pw, A ** 3, rtol=1e-5)
    np.testing.assert_allclose(sd, A)


def test_alias_shape_ops_execute():
    """The reference's *2 op variants (reshape2/flatten2/...) must lower
    under their own registered names."""
    def build(blk):
        x = layers.data("x", list(A.shape), append_batch_size=False)
        return [
            _raw(blk, "reshape2", {"X": [x]}, attrs={"shape": [4, 3]}),
            _raw(blk, "flatten2", {"X": [x]}, attrs={"axis": 1}),
            _raw(blk, "squeeze2", {"X": [layers.unsqueeze(x, [0])]},
                 attrs={"axes": [0]}),
            _raw(blk, "unsqueeze2", {"X": [x]}, attrs={"axes": [0]}),
            _raw(blk, "transpose2", {"X": [x]}, attrs={"axis": [1, 0]}),
        ]

    rs, fl, sq, us, tr = _run_ops(build, {"x": A})
    np.testing.assert_allclose(rs, A.reshape(4, 3))
    np.testing.assert_allclose(fl, A)
    np.testing.assert_allclose(sq, A)
    np.testing.assert_allclose(us, A[None])
    np.testing.assert_allclose(tr, A.T)


def test_lookup_table_v2_and_depthwise_conv():
    ids = np.array([1, 0, 2], np.int64)
    img = RNG.rand(1, 3, 6, 6).astype(np.float32)

    def build(blk):
        w = layers.create_parameter(
            [4, 5], "float32",
            default_initializer=fluid.initializer.Constant(0.5))
        iv = layers.data("ids", [3], dtype="int64",
                         append_batch_size=False)
        emb = _raw(blk, "lookup_table_v2", {"W": [w], "Ids": [iv]})
        x = layers.data("img", list(img.shape), append_batch_size=False)
        f = layers.create_parameter(
            [3, 1, 3, 3], "float32",
            default_initializer=fluid.initializer.Constant(1.0 / 9))
        dw = blk.create_var(name="dw_out", shape=(-1,), dtype="float32")
        blk.append_op("depthwise_conv2d",
                      inputs={"Input": [x], "Filter": [f]},
                      outputs={"Output": [dw]},
                      attrs={"strides": [1, 1], "paddings": [1, 1],
                             "groups": 3})
        return [emb, dw]

    emb, dw = _run_ops(build, {"ids": ids, "img": img})
    assert emb.shape == (3, 5) and (emb == 0.5).all()
    assert dw.shape == (1, 3, 6, 6)


def test_collectives_single_rank_identity():
    """Outside any mesh context collectives are single-rank identities
    (their real multi-rank semantics run under shard_map in
    test_parallel/test_tp_fluid); this executes every registered
    collective lowering under its own op type."""
    def build(blk):
        x = layers.data("x", list(A.shape), append_batch_size=False)
        outs = []
        for t in ("c_allreduce_max", "c_allreduce_min", "c_allreduce_avg",
                  "c_broadcast", "c_concat", "c_reducescatter",
                  "collective_permute", "allreduce", "barrier"):
            outs.append(_raw(blk, t, {"X": [x]}, attrs={"ring_id": 0}))
        outs.append(_raw(blk, "c_sync_calc_stream", {"X": [x]}))
        outs.append(_raw(blk, "c_sync_comm_stream", {"X": [x]}))
        return outs

    for r in _run_ops(build, {"x": A}):
        np.testing.assert_allclose(r, A)


def test_switch_and_print_execute():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        step = layers.fill_constant([1], "float32", 7.0)
        thresh = layers.fill_constant([1], "float32", 5.0)
        lr = layers.create_global_var([1], 0.0, "float32",
                                      persistable=True, name="sw_lr")
        with layers.Switch() as sw:
            with sw.case(layers.greater_than(step, thresh)):
                layers.assign(layers.fill_constant([1], "float32", 0.1),
                              lr)
            with sw.default():
                layers.assign(layers.fill_constant([1], "float32", 0.01),
                              lr)
        shown = layers.Print(lr, message="lr=")
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (v,) = exe.run(main, feed={}, fetch_list=[shown])
    np.testing.assert_allclose(np.asarray(v), [0.1])


def test_cudnn_style_lstm_op_executes():
    T, B, I, H = 4, 2, 3, 5
    x = RNG.rand(T, B, I).astype(np.float32)
    nparam = I * 4 * H + H * 4 * H + 4 * H

    def build(blk):
        xv = layers.data("x", [T, B, I], append_batch_size=False)
        w = layers.create_parameter(
            [nparam], "float32",
            default_initializer=fluid.initializer.NormalInitializer(
                scale=0.1))
        h0 = layers.fill_constant([1, B, H], "float32", 0.0)
        c0 = layers.fill_constant([1, B, H], "float32", 0.0)
        out = blk.create_var(name="lstm_out", shape=(-1,),
                             dtype="float32")
        lh = blk.create_var(name="lstm_lh", shape=(-1,), dtype="float32")
        lc = blk.create_var(name="lstm_lc", shape=(-1,), dtype="float32")
        blk.append_op("lstm",
                      inputs={"Input": [xv], "InitH": [h0], "InitC": [c0],
                              "W": [w]},
                      outputs={"Out": [out], "LastH": [lh],
                               "LastC": [lc]},
                      attrs={"hidden_size": H, "num_layers": 1,
                             "is_test": True})
        return [out, lh]

    out, lh = _run_ops(build, {"x": x})
    assert out.shape == (T, B, H) and lh.shape == (1, B, H)
    np.testing.assert_allclose(out[-1], lh[0], rtol=1e-6)


def test_randint_unique_sample_logits():
    def build(blk):
        r = blk.create_var(name="ri_out", shape=(-1,), dtype="int64")
        blk.append_op("randint", inputs={}, outputs={"Out": [r]},
                      attrs={"shape": [64], "low": 3, "high": 9,
                             "dtype": "int64"})
        x = layers.data("u", [6], dtype="float32",
                        append_batch_size=False)
        uq = _raw(blk, "unique", {"X": [x]}, n_out=2,
                  out_slots=["Out", "Index"])
        logits = layers.data("lg", [4, 50], append_batch_size=False)
        lbl = layers.data("lb", [4, 1], dtype="int64",
                          append_batch_size=False)
        loss = blk.create_var(name="sl_loss", shape=(-1,),
                              dtype="float32")
        samples = blk.create_var(name="sl_samp", shape=(-1,),
                                 dtype="int64")
        blk.append_op("sample_logits",
                      inputs={"Logits": [logits], "Label": [lbl]},
                      outputs={"Loss": [loss], "Samples": [samples]},
                      attrs={"num_samples": 8})
        return [r, uq[0], loss]

    r, uq, loss = _run_ops(build, {
        "u": np.array([3, 1, 3, 2, 1, 9], np.float32),
        "lg": RNG.randn(4, 50).astype(np.float32),
        "lb": RNG.randint(0, 50, (4, 1)).astype(np.int64)})
    assert r.shape == (64,) and (r >= 3).all() and (r < 9).all()
    assert set(np.unique(uq)) >= {1.0, 2.0, 3.0, 9.0}
    assert loss.shape[0] == 4 and np.isfinite(loss).all()


def test_adagrad_optimizer_steps():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        w = layers.create_parameter(
            [4], "float32",
            default_initializer=fluid.initializer.Constant(3.0))
        loss = layers.reduce_sum(layers.square(w))
        fluid.optimizer.Adagrad(learning_rate=0.5).minimize(loss)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        l0 = None
        for _ in range(5):
            (l,) = exe.run(main, feed={}, fetch_list=[loss])
            l0 = l0 if l0 is not None else float(np.asarray(l))
        assert float(np.asarray(l)) < l0
