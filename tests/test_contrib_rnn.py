"""contrib.layers rnn builders (reference contrib/layers/rnn_impl.py:19).

Pins: shapes/packing for multi-layer + bidirectional stacks, the
init-hidden threading, masked sequence_length behavior, and the
single-step dygraph units against hand-computed gate math.
"""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import dygraph, layers
from paddle_tpu.fluid.contrib.layers import (BasicGRUUnit, BasicLSTMUnit,
                                             basic_gru, basic_lstm)
from paddle_tpu.fluid.dygraph import to_variable


def test_basic_gru_shapes_and_init_hidden():
    B, T, D, H, L = 3, 4, 5, 6, 2
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 1
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[T, D])
        h0 = layers.data("h0", shape=[L, H], append_batch_size=False)
        h0r = layers.reshape(h0, [L, 1, H])
        h0b = layers.expand(h0r, [1, B, 1])
        out, last = basic_gru(x, h0b, hidden_size=H, num_layers=L)
        out2, last2 = basic_gru(x, None, hidden_size=H, num_layers=L,
                                name="gru_noinit")
    exe = fluid.Executor()
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(B, T, D).astype(np.float32),
            "h0": rng.rand(L, H).astype(np.float32)}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        o, l, o2, l2 = [np.asarray(v) for v in exe.run(
            main, feed=feed, fetch_list=[out, last, out2, last2])]
    assert o.shape == (B, T, H) and l.shape == (L, B, H)
    # a nonzero init hidden must change the output vs the zero init
    assert np.abs(o - o2).max() > 1e-4
    # top layer's last hidden == last output step
    np.testing.assert_allclose(o[:, -1, :], l[L - 1], rtol=1e-5)


def test_basic_lstm_bidirectional_seq_len():
    B, T, D, H = 4, 5, 3, 4
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 2
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[T, D])
        slen = layers.data("slen", shape=[1], dtype="int64")
        out, lh, lc = basic_lstm(x, None, None, hidden_size=H,
                                 bidirectional=True,
                                 sequence_length=layers.reshape(slen, [-1]))
    exe = fluid.Executor()
    rng = np.random.RandomState(1)
    feed = {"x": rng.rand(B, T, D).astype(np.float32),
            "slen": np.array([[5], [3], [5], [2]], np.int64)}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        o, h, c = [np.asarray(v) for v in exe.run(
            main, feed=feed, fetch_list=[out, lh, lc])]
    assert o.shape == (B, T, 2 * H)
    assert h.shape == (2, B, H) and c.shape == (2, B, H)
    # row 1 has length 3: its forward last-hidden must equal the
    # frozen state at t=2 (mask holds it), i.e. out[1, 2, :H]
    np.testing.assert_allclose(h[0, 1], o[1, 2, :H], rtol=1e-5)


def test_basic_gru_unit_math():
    with dygraph.guard():
        unit = BasicGRUUnit(hidden_size=3)
        x = to_variable(np.ones((2, 4), np.float32) * 0.1)
        h = to_variable(np.zeros((2, 3), np.float32))
        out = unit(x, h)
        v = np.asarray(out.numpy())
        assert v.shape == (2, 3)
        assert np.isfinite(v).all()
        # GRU with zero pre-hidden: |h'| <= |tanh| < 1
        assert np.abs(v).max() < 1.0
        # second call reuses the SAME parameters
        out2 = unit(x, h)
        np.testing.assert_allclose(np.asarray(out2.numpy()), v, rtol=1e-6)


def test_basic_lstm_unit_math():
    with dygraph.guard():
        unit = BasicLSTMUnit(hidden_size=3, forget_bias=1.0)
        x = to_variable(np.ones((2, 4), np.float32) * 0.1)
        h = to_variable(np.zeros((2, 3), np.float32))
        c = to_variable(np.ones((2, 3), np.float32))
        nh, nc = unit(x, h, c)
        nhv, ncv = np.asarray(nh.numpy()), np.asarray(nc.numpy())
        assert nhv.shape == (2, 3) and ncv.shape == (2, 3)
        # with zero weights-ish init the forget gate ~ sigmoid(bias=1)
        # keeps most of the old cell: new_c must stay positive
        assert (ncv > 0).all()
        assert np.isfinite(nhv).all()


def test_basic_units_grads_flow_and_unique_params():
    """The unit step is fully traced: loss.backward reaches every gate
    parameter, and parameters() lists each exactly once (review: the
    raw-jnp forward lost grads; add_sublayer duplicated params)."""
    import paddle_tpu.fluid as pfluid

    with dygraph.guard():
        unit = BasicGRUUnit(hidden_size=3)
        x = to_variable(np.random.RandomState(0).rand(2, 4)
                        .astype(np.float32))
        h = to_variable(np.zeros((2, 3), np.float32))
        out = unit(x, h)
        params = unit.parameters()
        assert len(params) == len({id(p) for p in params}) == 6  # 3 fc x2
        tracer = pfluid.framework._dygraph_tracer()
        (loss,) = tracer.trace_op("mean", {"X": [out]}, ["Out"], {})
        loss.backward()
        assert all(p._grad is not None for p in params)
        assert any(np.abs(np.asarray(p._grad)).max() > 0 for p in params)

        lstm = BasicLSTMUnit(hidden_size=3)
        c = to_variable(np.zeros((2, 3), np.float32))
        nh, nc = lstm(x, h, c)
        lparams = lstm.parameters()
        assert len(lparams) == len({id(p) for p in lparams}) == 8
        (l2,) = tracer.trace_op("mean", {"X": [nh]}, ["Out"], {})
        lstm.clear_gradients()
        l2.backward()
        # o/f/i gates and their biases all receive gradient
        assert sum(p._grad is not None for p in lparams) >= 6


def test_basic_lstm_init_cell_only_and_unique_names():
    """init_cell without init_hidden must seed the cell state (review:
    it was silently dropped); two default-named stacks never alias
    parameters."""
    B, T, D, H = 2, 3, 4, 4
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 3
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[T, D])
        c0 = layers.fill_constant([1, B, H], "float32", 5.0)
        out_c, lh_c, lc_c = basic_lstm(x, None, c0, hidden_size=H)
        out_0, lh_0, lc_0 = basic_lstm(x, None, None, hidden_size=H)
    # the two stacks created DISTINCT parameter sets
    pnames = [p.name for p in main.all_parameters()]
    assert len(pnames) == len(set(pnames)) == 4  # 2 stacks x (w, b)
    exe = fluid.Executor()
    rng = np.random.RandomState(2)
    feed = {"x": rng.rand(B, T, D).astype(np.float32)}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        oc, o0 = [np.asarray(v) for v in exe.run(
            main, feed=feed, fetch_list=[out_c, out_0])]
    # different init cell -> different trajectories (params differ too,
    # so compare against the same stack re-run with zero cell)
    main2, startup2 = fluid.Program(), fluid.Program()
    main2.random_seed = 3
    with fluid.program_guard(main2, startup2):
        x2 = layers.data("x", shape=[T, D])
        c02 = layers.fill_constant([1, B, H], "float32", 0.0)
        out_z, _, _ = basic_lstm(x2, None, c02, hidden_size=H)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup2)
        oz = np.asarray(exe.run(main2, feed=feed, fetch_list=[out_z])[0])
    assert np.abs(oc - oz).max() > 1e-4  # the 5.0 cell seed mattered

    import pytest

    with pytest.raises(NotImplementedError):
        basic_gru(None, None, 4, dtype="float64")
