"""Chaos: the coordination service dies (kill -9, no drain, no final
snapshot) and comes back on the same port + WAL dir while the systems
built on top keep running — the serving fleet in-process, and (slow) a
2-process training gang whose lockstep barriers ride the outage."""

import os
import re
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, monitor
from paddle_tpu.distributed.coordination import CoordClient, CoordServer
from paddle_tpu.serving import FleetClient, Replica, Router

pytestmark = pytest.mark.chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RUNNER = os.path.join(REPO, "tests", "dist_runner_chaos.py")


def _restart(port, wal_dir):
    deadline = time.time() + 10
    while True:
        try:
            return CoordServer(port=port, wal_dir=wal_dir).start()
        except OSError:
            if time.time() > deadline:
                raise
            time.sleep(0.1)


# -- in-process fleet -------------------------------------------------------

@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("chaos_model")
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 33
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[6], dtype="float32")
        h = layers.fc(x, size=16, act="relu")
        prob = layers.softmax(layers.fc(h, size=3))
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        fluid.io.save_inference_model(str(d), ["x"], [prob], exe,
                                      main_program=main)
    return str(d)


def _spec(model_dir):
    return {"prefix": "fleet/",
            "models": [{"name": "fc", "model_dir": model_dir,
                        "warmup": {"x": {"shape": [1, 6],
                                         "dtype": "float32"}},
                        "config": {"max_batch_size": 8,
                                   "max_queue_delay_ms": 2.0}}]}


def test_fleet_rides_out_coordinator_crash(model_dir, tmp_path):
    """Acceptance: coordinator kill -9 + same-WAL restart under a
    2-replica fleet. The data path never touches the coordinator, so
    EVERY request is served (100% accounted, zero shed): healthy,
    degraded (stale routing table, ``fleet_stale_routing_total``
    grows), and recovered phases all included. The restarted server
    replays replica leases from its WAL at a bumped epoch and the
    router's refresh goes fresh again."""
    wal = str(tmp_path / "wal")
    srv = CoordServer(wal_dir=wal).start()
    addr, port, epoch0 = srv.endpoint, srv.port, srv.epoch
    dbg = CoordClient(addr, grace=10.0)
    reps, router, cli = [], None, None
    try:
        reps = [Replica(_spec(model_dir), coord_addr=addr,
                        replica_id="cx%d" % i, lease_ttl=5.0,
                        stats_interval=0.05).start()
                for i in range(2)]
        deadline = time.time() + 120
        while len(dbg.live_members("fleet/replicas/")) < 2:
            assert time.time() < deadline, "replicas never registered"
            time.sleep(0.05)
        router = Router(coord_addr=addr, refresh_interval=0.05).start()
        cli = FleetClient("%s:%d" % (router.host, router.port))
        rng = np.random.RandomState(3)
        shed0 = monitor.sum_labeled("fleet_shed_total")
        stale0 = monitor.counter("fleet_stale_routing_total").value

        def burst(n):
            for _ in range(n):
                x = rng.rand(rng.randint(1, 5), 6).astype(np.float32)
                out = cli.submit("fc", {"x": x}, deadline_ms=10000)
                assert out[0].shape == (x.shape[0], 3)

        burst(6)                       # healthy
        srv.crash()
        deadline = time.time() + 30    # detection = router's fail-fast
        while True:                    # coordination client (~1 s)
            with router._table_mu:
                stale = router._stale_since is not None
            if stale:
                break
            assert time.time() < deadline, "router never marked stale"
            time.sleep(0.05)
        burst(6)                       # degraded: last-known table
        assert monitor.counter(
            "fleet_stale_routing_total").value > stale0
        srv = _restart(port, wal)
        assert srv.epoch == epoch0 + 1
        deadline = time.time() + 60
        while True:
            with router._table_mu:
                fresh = router._stale_since is None \
                    and len(router._table) == 2
            if fresh:
                break
            assert time.time() < deadline, "router never recovered"
            time.sleep(0.05)
        burst(6)                       # recovered
        # 18/18 served above; shed-by-reason totals unchanged — the
        # outage never cost a request, typed or otherwise
        assert monitor.sum_labeled("fleet_shed_total") == shed0
    finally:
        if cli is not None:
            cli.close()
        if router is not None:
            router.close()
        for r in reps:
            r.drain(timeout=10)
        dbg.close()
        srv.stop()


def test_fleet_sheds_typed_after_grace_expires(model_dir, tmp_path):
    """Past the degraded-mode grace window the stale view is too old to
    trust: the table drops and requests shed typed ``no_replica`` —
    never an untyped error, never a hang."""
    from paddle_tpu import inference

    wal = str(tmp_path / "wal")
    srv = CoordServer(wal_dir=wal).start()
    addr = srv.endpoint
    dbg = CoordClient(addr, grace=10.0)
    reps, router, cli = [], None, None
    try:
        reps = [Replica(_spec(model_dir), coord_addr=addr,
                        replica_id="gx0", lease_ttl=5.0,
                        stats_interval=0.05).start()]
        deadline = time.time() + 120
        while len(dbg.live_members("fleet/replicas/")) < 1:
            assert time.time() < deadline, "replica never registered"
            time.sleep(0.05)
        # grace=0: the first failed refresh already exceeds the window
        router = Router(coord_addr=addr, refresh_interval=0.05,
                        grace=0.0).start()
        cli = FleetClient("%s:%d" % (router.host, router.port))
        x = np.ones((1, 6), np.float32)
        assert cli.submit("fc", {"x": x}, deadline_ms=10000)[0].shape \
            == (1, 3)
        srv.crash()
        deadline = time.time() + 30
        while router.members():
            assert time.time() < deadline, "stale table never dropped"
            time.sleep(0.05)
        with pytest.raises(inference.Overloaded):
            cli.submit("fc", {"x": x}, deadline_ms=500)
    finally:
        if cli is not None:
            cli.close()
        if router is not None:
            router.close()
        # coordinator stays dead: deregistration RPCs can't land, so
        # tear the replicas down hard instead of drain()
        for r in reps:
            r.stop()
        dbg.close()
        srv.stop()


# -- 2-process training gang (slow) -----------------------------------------

def _worker_env(rank, addr):
    env = dict(os.environ)
    for k in ("XLA_FLAGS", "JAX_PLATFORMS", "PADDLE_RENDEZVOUS_DIR"):
        env.pop(k, None)
    env.update({"PADDLE_COORD_ADDR": addr,
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": "2",
                "PADDLE_DIST_BACKEND": "cpu",
                "PADDLE_COORD_GRACE_S": "240"})
    return env


def _read(paths):
    out = ""
    for i, p in enumerate(paths):
        with open(p) as f:
            out += "--- worker %d ---\n%s\n" % (i, f.read())
    return out


@pytest.mark.slow
def test_gang_training_survives_coordinator_kill9(tmp_path):
    """Acceptance: SIGKILL the standalone durable coordinator mid-run,
    restart it on the same port + WAL dir — the 2-process gang's
    barriers and leases resume (journaled arrivals, reconnecting
    clients) and both ranks finish with BIT-IDENTICAL weights."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import chaos
    finally:
        sys.path.pop(0)

    wal = str(tmp_path / "wal")
    proc, addr, port, epoch0 = chaos._spawn(wal)
    paths = [str(tmp_path / ("worker.%d.log" % r)) for r in range(2)]
    workers = []
    try:
        for r in range(2):
            f = open(paths[r], "w")
            try:
                workers.append(subprocess.Popen(
                    [sys.executable, RUNNER], env=_worker_env(r, addr),
                    cwd=REPO, stdout=f, stderr=subprocess.STDOUT))
            finally:
                f.close()
        deadline = time.time() + 300
        while not all("STEP 1 " in open(p).read() for p in paths):
            assert all(w.poll() is None for w in workers), _read(paths)
            assert time.time() < deadline, _read(paths)
            time.sleep(0.2)
        chaos._kill9(proc)
        time.sleep(1.0)                # a real outage, not a blip
        proc, _, _, epoch1 = chaos._spawn(wal, port=port)
        assert epoch1 == epoch0 + 1
        for w in workers:
            assert w.wait(timeout=600) == 0, _read(paths)
        text = _read(paths)
        assert text.count("STEP 7 ") == 2, text       # every step ran
        digests = re.findall(r"WDIGEST (\S+)", text)
        assert len(digests) == 2, text
        assert digests[0] == digests[1], text         # bit-identical
        epochs = [int(e) for e in re.findall(r"EPOCH (\d+)", text)]
        assert epochs == [epoch1, epoch1], text       # rode the restart
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
                w.wait()
        chaos._kill9(proc)
