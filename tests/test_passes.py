"""Pass registry / PassBuilder / IrGraph (reference ir/pass infrastructure,
SURVEY §2.3)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, optimizer, passes


def _mlp_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("pp_x", [4])
        y = layers.data("pp_y", [1])
        h = layers.fc(x, 8, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.reduce_mean(layers.square(pred - y))
    return main, startup, loss


def test_registry_lookup_and_errors():
    assert passes.get_pass("prune").name == "prune"
    assert "amp_rewrite" in passes._registry.names()
    with pytest.raises(KeyError):
        passes.get_pass("not_a_pass")


def test_register_custom_pass_decorator():
    @passes.register_pass("test_count_ops")
    def count_ops(program):
        program._test_op_count = len(program.global_block().ops)
        return program

    main, _, _ = _mlp_program()
    out = passes.apply_pass(main, "test_count_ops")
    assert out._test_op_count == len(main.global_block().ops)


def test_prune_pass_drops_loss_ops():
    main, startup, loss = _mlp_program()
    with fluid.program_guard(main, startup):
        optimizer.SGD(learning_rate=0.1).minimize(loss)
    n_full = len(main.global_block().ops)
    fc_out = None
    for op in main.global_block().ops:
        if op.type == "relu":
            fc_out = op.outputs["Out"][0]
    pruned = passes.apply_pass(main, "prune",
                               targets=[main.global_block().var(fc_out)])
    assert len(pruned.global_block().ops) < n_full
    types = [op.type for op in pruned.global_block().ops]
    assert "relu" in types and "autodiff" not in types


def test_amp_rewrite_pass_inserts_casts():
    main, startup, loss = _mlp_program()
    before = [op.type for op in main.global_block().ops]
    passes.apply_pass(main, "amp_rewrite")
    after = [op.type for op in main.global_block().ops]
    assert after.count("cast") > before.count("cast")


def test_collective_pass_inserts_allreduce():
    main, startup, loss = _mlp_program()
    with fluid.program_guard(main, startup):
        optimizer.SGD(learning_rate=0.1).minimize(loss)
    passes.apply_pass(main, "collective_grad_allreduce",
                      startup_program=startup, nranks=2)
    types = [op.type for op in main.global_block().ops]
    assert "c_allreduce_sum" in types


def test_pass_builder_pipeline_order():
    calls = []

    @passes.register_pass("test_first")
    def first(program):
        calls.append("first")

    @passes.register_pass("test_second")
    def second(program):
        calls.append("second")

    b = passes.PassBuilder(["test_first"])
    b.append_pass("test_second")
    assert [p.name for p in b.all_passes()] == ["test_first", "test_second"]
    main, _, _ = _mlp_program()
    b.apply(main)
    assert calls == ["first", "second"]
    b.remove_pass(0)
    assert [p.name for p in b.all_passes()] == ["test_second"]


def test_ir_graph_structure():
    main, _, loss = _mlp_program()
    g = passes.IrGraph(main)
    assert "relu" in g.op_types()
    relu = next(op for op in g.all_op_nodes() if op.type == "relu")
    (relu_out,) = g.outputs_of(relu)
    consumers = g.consumers_of(relu_out)
    assert consumers and all(relu_out in g.inputs_of(c) for c in consumers)
    assert g.producer_of(relu_out) is relu
    dot = g.draw()
    assert dot.startswith("digraph")


def test_program_check_pass():
    main, startup, loss = _mlp_program()
    passes.apply_pass(main, "program_check", startup_program=startup,
                      feed_names=["pp_x", "pp_y"])

    broken = fluid.Program()
    blk = broken.global_block()
    blk.create_var(name="pc_ghost", shape=(2,), dtype="float32")
    blk.create_var(name="pc_out", shape=(2,), dtype="float32")
    blk.append_op("relu", inputs={"X": ["pc_ghost"]},
                  outputs={"Out": ["pc_out"]})
    blk.append_op("not_an_op", inputs={"X": ["pc_out"]},
                  outputs={"Out": ["pc_out"]})
    with pytest.raises(ValueError) as ei:
        passes.apply_pass(broken, "program_check")
    msg = str(ei.value)
    assert "never produced" in msg and "no lowering rule" in msg


def test_net_drawer_draw_graph(tmp_path):
    main, startup, _ = _mlp_program()
    dot = fluid.net_drawer.draw_graph(startup, main,
                                      path=str(tmp_path / "nd.dot"))
    assert dot.startswith("digraph") and (tmp_path / "nd.dot").exists()


def test_flags_check_program_in_executor():
    import numpy as np

    fluid.set_flags({"FLAGS_check_program": True})
    try:
        main, startup, loss = _mlp_program()
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            exe.run(main, feed={
                "pp_x": np.ones((2, 4), np.float32),
                "pp_y": np.ones((2, 1), np.float32)}, fetch_list=[loss])

        broken = fluid.Program()
        blk = broken.global_block()
        blk.create_var(name="fc_ghost", shape=(2,), dtype="float32")
        blk.create_var(name="fc_out", shape=(2,), dtype="float32")
        blk.append_op("relu", inputs={"X": ["fc_ghost"]},
                      outputs={"Out": ["fc_out"]})
        with fluid.scope_guard(fluid.Scope()):
            with pytest.raises(ValueError, match="program_check"):
                exe.run(broken, feed={}, fetch_list=["fc_out"])
    finally:
        fluid.set_flags({"FLAGS_check_program": False})
