"""Mechanical op coverage (VERDICT round-1 item 8): every registered
lowering rule must be executed by at least one test. The table below
numpy-references the op families no other suite touches; the final gate
test fails the build if a registered op type is referenced nowhere under
``tests/``."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers

RNG = np.random.RandomState(42)
A = (RNG.rand(3, 4).astype(np.float32) * 2 - 1) * 2   # [-2, 2]
B = (RNG.rand(3, 4).astype(np.float32) * 2 - 1) * 2
POS = RNG.rand(3, 4).astype(np.float32) + 0.5          # strictly positive
IMG = RNG.rand(2, 4, 6, 6).astype(np.float32)


def _run(build, feed):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        fetch = build()
        if not isinstance(fetch, (list, tuple)):
            fetch = [fetch]
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        res = exe.run(main, feed=feed, fetch_list=list(fetch))
    return [np.asarray(r) for r in res]


def _x(shape=None, name="x", dtype="float32"):
    if isinstance(shape, str):  # allow _x("name", shape) call order too
        shape, name = name, shape
        if not isinstance(shape, (list, tuple)):
            shape = None
    shape = list(shape if shape is not None else A.shape)
    return layers.data(name, shape, append_batch_size=False, dtype=dtype)


def _sigmoid(v):
    return 1 / (1 + np.exp(-v))


# (id, build_fn, feed, numpy_ref) — one Program execution per case
UNARY = [
    ("acos", lambda: layers.acos(_x()), {"x": A * 0.45},
     lambda: np.arccos(A * 0.45)),
    ("asin", lambda: layers.asin(_x()), {"x": A * 0.45},
     lambda: np.arcsin(A * 0.45)),
    ("atan", lambda: layers.atan(_x()), {"x": A}, lambda: np.arctan(A)),
    ("cos", lambda: layers.cos(_x()), {"x": A}, lambda: np.cos(A)),
    ("sin", lambda: layers.sin(_x()), {"x": A}, lambda: np.sin(A)),
    ("ceil", lambda: layers.ceil(_x()), {"x": A}, lambda: np.ceil(A)),
    ("floor", lambda: layers.floor(_x()), {"x": A}, lambda: np.floor(A)),
    ("round", lambda: layers.round(_x()), {"x": A}, lambda: np.round(A)),
    ("erf", lambda: layers.erf(_x()), {"x": A},
     lambda: __import__("scipy.special", fromlist=["erf"]).erf(A)),
    ("gelu", lambda: layers.gelu(_x()), {"x": A},
     lambda: A * 0.5 * (1 + __import__("scipy.special",
                                       fromlist=["erf"]).erf(A / np.sqrt(2)))),
    ("elu", lambda: layers.elu(_x(), alpha=0.5), {"x": A},
     lambda: np.where(A > 0, A, 0.5 * (np.exp(A) - 1))),
    ("selu", lambda: layers.selu(_x()), {"x": A},
     lambda: 1.0507009873554805 * np.where(
         A > 0, A, 1.6732632423543772 * (np.exp(A) - 1))),
    ("brelu", lambda: layers.brelu(_x(), t_min=-0.5, t_max=0.5), {"x": A},
     lambda: np.clip(A, -0.5, 0.5)),
    ("relu6", lambda: layers.relu6(_x()), {"x": A * 4},
     lambda: np.clip(A * 4, 0, 6)),
    ("leaky_relu", lambda: layers.leaky_relu(_x(), alpha=0.1), {"x": A},
     lambda: np.where(A > 0, A, 0.1 * A)),
    ("hard_shrink", lambda: layers.hard_shrink(_x(), threshold=0.5),
     {"x": A}, lambda: np.where(np.abs(A) > 0.5, A, 0)),
    ("hard_sigmoid", lambda: layers.hard_sigmoid(_x()), {"x": A},
     lambda: np.clip(0.2 * A + 0.5, 0, 1)),
    ("hard_swish", lambda: layers.hard_swish(_x()), {"x": A},
     lambda: A * np.clip(A + 3, 0, 6) / 6),
    ("softplus", lambda: layers.softplus(_x()), {"x": A},
     lambda: np.log1p(np.exp(A))),
    ("softshrink", lambda: layers.softshrink(_x(), alpha=0.3), {"x": A},
     lambda: np.where(A > 0.3, A - 0.3, np.where(A < -0.3, A + 0.3, 0))),
    ("softsign", lambda: layers.softsign(_x()), {"x": A},
     lambda: A / (1 + np.abs(A))),
    ("stanh", lambda: layers.stanh(_x()), {"x": A},
     lambda: 1.7159 * np.tanh(0.67 * A)),
    ("swish", lambda: layers.swish(_x()), {"x": A},
     lambda: A * _sigmoid(A)),
    ("tanh_shrink", lambda: layers.tanh_shrink(_x()), {"x": A},
     lambda: A - np.tanh(A)),
    ("thresholded_relu",
     lambda: layers.thresholded_relu(_x(), threshold=0.3),
     {"x": A}, lambda: np.where(A > 0.3, A, 0)),
    ("logsigmoid", lambda: layers.logsigmoid(_x()), {"x": A},
     lambda: np.log(_sigmoid(A))),
    ("soft_relu", lambda: layers.soft_relu(_x(), threshold=3.0), {"x": A},
     lambda: np.log1p(np.exp(np.clip(A, -3, 3)))),
    ("reciprocal", lambda: layers.reciprocal(_x()), {"x": POS},
     lambda: 1 / POS),
    ("rsqrt", lambda: layers.rsqrt(_x()), {"x": POS},
     lambda: 1 / np.sqrt(POS)),
    ("pow", lambda: layers.pow(_x(), factor=3.0), {"x": A}, lambda: A ** 3),
    ("log_softmax", lambda: layers.log_softmax(_x()), {"x": A},
     lambda: A - A.max(-1, keepdims=True) -
     np.log(np.exp(A - A.max(-1, keepdims=True)).sum(-1, keepdims=True))),
]

BINARY = [
    ("elementwise_sub", lambda: layers.elementwise_sub(_x(), _y()),
     lambda: A - B),
    ("elementwise_div", lambda: layers.elementwise_div(_x(), _y()),
     lambda: A / B),
    ("elementwise_max", lambda: layers.elementwise_max(_x(), _y()),
     lambda: np.maximum(A, B)),
    ("elementwise_min", lambda: layers.elementwise_min(_x(), _y()),
     lambda: np.minimum(A, B)),
    ("elementwise_pow", lambda: layers.elementwise_pow(_x(), _y()),
     lambda: np.abs(A) ** B, {"x": np.abs(A)}),
    ("elementwise_mod", lambda: layers.elementwise_mod(_x(), _y()),
     lambda: np.mod(np.abs(A), np.abs(B)),
     {"x": np.abs(A), "y": np.abs(B)}),
    ("elementwise_floordiv",
     lambda: layers.elementwise_floordiv(_x(), _y()),
     lambda: np.floor_divide(np.abs(A) * 4, np.abs(B) + 0.5),
     {"x": np.abs(A) * 4, "y": np.abs(B) + 0.5}),
    ("greater_than", lambda: _x() > _y(), lambda: A > B),
    ("greater_equal", lambda: _x() >= _y(), lambda: A >= B),
    ("less_equal", lambda: _x() <= _y(), lambda: A <= B),
    ("not_equal", lambda: layers.not_equal(_x(), _y()), lambda: A != B),
    ("logical_and",
     lambda: layers.logical_and(_x(dtype="bool"), _y(dtype="bool")),
     lambda: (A > 0) & (B > 0), {"x": A > 0, "y": B > 0}),
    ("logical_or",
     lambda: layers.logical_or(_x(dtype="bool"), _y(dtype="bool")),
     lambda: (A > 0) | (B > 0), {"x": A > 0, "y": B > 0}),
    ("logical_xor",
     lambda: layers.logical_xor(_x(dtype="bool"), _y(dtype="bool")),
     lambda: (A > 0) ^ (B > 0), {"x": A > 0, "y": B > 0}),
    ("logical_not", lambda: layers.logical_not(_x(dtype="bool")),
     lambda: ~(A > 0), {"x": A > 0}),
]


def _y(shape=None, dtype="float32"):
    return _x(shape, "y", dtype)


@pytest.mark.parametrize("name,build,feed,ref", UNARY,
                         ids=[u[0] for u in UNARY])
def test_unary(name, build, feed, ref):
    (out,) = _run(build, feed)
    np.testing.assert_allclose(out, ref(), rtol=2e-5, atol=1e-6)


@pytest.mark.parametrize("case", BINARY, ids=[b[0] for b in BINARY])
def test_binary(case):
    name, build, ref = case[0], case[1], case[2]
    feed = {"x": A, "y": B}
    if len(case) > 3:
        feed.update(case[3])
    (out,) = _run(build, feed)
    np.testing.assert_allclose(out, ref(), rtol=2e-5, atol=1e-6)


def test_reductions():
    outs = _run(lambda: [layers.reduce_max(_x(), dim=[1]),
                         layers.reduce_min(_x(), dim=[1]),
                         layers.reduce_prod(_x(), dim=[1]),
                         layers.reduce_any(_x("b", dtype="bool"),
                                           dim=[1])],
                {"x": A, "b": A > 0.5})
    np.testing.assert_allclose(outs[0], A.max(1), rtol=1e-6)
    np.testing.assert_allclose(outs[1], A.min(1), rtol=1e-6)
    np.testing.assert_allclose(outs[2], A.prod(1), rtol=1e-5)
    np.testing.assert_array_equal(outs[3], (A > 0.5).any(1))


def test_shape_ops():
    idx_nd = np.array([[0, 1], [2, 3]], np.int64)
    outs = _run(lambda: [
        layers.flatten(_x((2, 3, 4), "f"), axis=2),
        layers.squeeze(_x((3, 1, 4), "s"), axes=[1]),
        layers.unsqueeze(_x(), axes=[0, 2]),
        layers.expand(_x((1, 4), "e"), [3, 2]),
        layers.expand_as(_x((1, 4), "e2"), _x((3, 4), "t")),
        layers.stack([_x(), _y()], axis=1),
        layers.reverse(_x(), axis=[1]),
        layers.pad(_x(), [1, 0, 0, 2], pad_value=9.0),
        layers.pad_constant_like(_x((5, 6), "big"), _x(), 7.0),
        layers.strided_slice(_x(), axes=[1], starts=[0], ends=[4],
                             strides=[2]),
        layers.gather_nd(_x(), _x((2, 2), "ind", "int64")),
    ], {"x": A, "y": B, "f": np.arange(24, dtype=np.float32).reshape(
        2, 3, 4), "s": A.reshape(3, 1, 4), "e": A[:1], "e2": A[:1], "t": A,
        "big": np.zeros((5, 6), np.float32), "ind": idx_nd})
    np.testing.assert_allclose(outs[0],
                               np.arange(24, dtype=np.float32).reshape(6, 4))
    np.testing.assert_allclose(outs[1], A)
    assert outs[2].shape == (1, 3, 1, 4)
    np.testing.assert_allclose(outs[3], np.tile(A[:1], (3, 2)))
    np.testing.assert_allclose(outs[4], np.tile(A[:1], (3, 1)))
    np.testing.assert_allclose(outs[5], np.stack([A, B], axis=1))
    np.testing.assert_allclose(outs[6], A[:, ::-1])
    np.testing.assert_allclose(
        outs[7], np.pad(A, [(1, 0), (0, 2)], constant_values=9.0))
    ref8 = np.full((5, 6), 7.0, np.float32)
    ref8[:3, :4] = A
    np.testing.assert_allclose(outs[8], ref8)
    np.testing.assert_allclose(outs[9], A[:, 0:4:2])
    np.testing.assert_allclose(outs[10], A[idx_nd[:, 0], idx_nd[:, 1]])


def test_unstack_and_scatter():
    idx = np.array([2, 0], np.int64)
    upd = np.ones((2, 4), np.float32)
    outs = _run(lambda: layers.unstack(_x(), axis=0) + [
        layers.scatter(_x("r1"), _x((2,), "i", "int64"),
                       _x((2, 4), "u")),
        layers.scatter_nd_add(_x("r2"), _x((2, 1), "i2", "int64"),
                              _x((2, 4), "u2")),
    ], {"x": A, "r1": A, "r2": A, "i": idx, "u": upd,
        "i2": idx[:, None], "u2": upd})
    for i in range(3):
        np.testing.assert_allclose(outs[i], A[i])
    ref = A.copy()
    ref[idx] = upd
    np.testing.assert_allclose(outs[3], ref)
    ref2 = A.copy()
    np.add.at(ref2, idx, upd)
    np.testing.assert_allclose(outs[4], ref2)


def test_creation_ops():
    outs = _run(lambda: [
        layers.eye(3, 4),
        layers.ones_like(_x()),
        layers.zeros_like(_x()),
        layers.fill_constant_batch_size_like(_x(), [0, 7], "float32", 2.5),
        layers.linspace(0.0, 1.0, 5, "float32"),
        layers.range(0, 10, 3, "int64"),
        layers.diag(np.array([1.0, 2.0, 3.0], np.float32)),
        layers.assign(np.array([[1.0, 2.0]], np.float32)),
    ], {"x": A})
    np.testing.assert_allclose(outs[0], np.eye(3, 4))
    np.testing.assert_allclose(outs[1], np.ones_like(A))
    np.testing.assert_allclose(outs[2], np.zeros_like(A))
    assert outs[3].shape == (3, 7) and (outs[3] == 2.5).all()
    np.testing.assert_allclose(outs[4], np.linspace(0, 1, 5), rtol=1e-6)
    np.testing.assert_array_equal(outs[5], np.arange(0, 10, 3))
    np.testing.assert_allclose(outs[6], np.diag([1.0, 2.0, 3.0]))
    np.testing.assert_allclose(outs[7], [[1.0, 2.0]])


def test_random_ops_statistics():
    outs = _run(lambda: [
        layers.uniform_random([2000], min=-2.0, max=2.0),
        layers.gaussian_random([2000], mean=1.0, std=0.5),
        layers.uniform_random_batch_size_like(_x(), [0, 500]),
        layers.gaussian_random_batch_size_like(_x(), [0, 500]),
        layers.sampling_id(layers.softmax(_x("p", (64, 4)))),
        layers.random_crop(_x("c", (4, 8, 8)), shape=[4, 4]),
    ], {"x": A, "p": RNG.randn(64, 4).astype(np.float32),
        "c": RNG.rand(4, 8, 8).astype(np.float32)})
    u, g = outs[0], outs[1]
    assert -2 <= u.min() and u.max() <= 2 and abs(u.mean()) < 0.15
    assert abs(g.mean() - 1.0) < 0.1 and abs(g.std() - 0.5) < 0.1
    assert outs[2].shape == (3, 500)
    assert outs[3].shape == (3, 500)
    assert outs[4].shape[0] == 64 and (0 <= outs[4]).all() \
        and (outs[4] <= 3).all()
    assert outs[5].shape == (4, 4, 4)


def test_truncated_gaussian_random():
    (out,) = _run(
        lambda: [layers.create_parameter(
            [4000], "float32", name="tg",
            default_initializer=fluid.initializer.TruncatedNormal(
                scale=1.0))], {})
    assert np.abs(out).max() <= 2.0 + 1e-5  # truncated at 2 std
    assert out.std() > 0.5


def test_nn_extras():
    theta = np.tile(np.array([[[1, 0, 0], [0, 1, 0]]], np.float32),
                    (2, 1, 1))
    outs = _run(lambda: [
        layers.maxout(_x("m", IMG.shape), groups=2),
        layers.space_to_depth(_x("m2", (2, 4, 6, 6)), 3),
        layers.shuffle_channel(_x("m3", (2, 4, 2, 2)), group=2),
        layers.pixel_shuffle(_x("m4", (2, 4, 3, 3)), 2),
        layers.temporal_shift(_x("m5", (4, 4, 2, 2)), seg_num=2),
        layers.affine_channel(
            _x("m6", IMG.shape),
            layers.assign(np.full((4,), 2.0, np.float32)),
            layers.assign(np.full((4,), 1.0, np.float32))),
        layers.affine_grid(_x("th", theta.shape), [2, 1, 4, 4]),
        layers.l2_normalize(_x(), axis=1),
        layers.label_smooth(_x("oh", (3, 4)), epsilon=0.1),
        layers.add_position_encoding(_x("pe", (2, 5, 8)), 1.0, 1.0),
    ], {"m": IMG, "m2": IMG, "m3": IMG[:, :, :2, :2],
        "m4": IMG[:, :, :3, :3], "m5": RNG.rand(4, 4, 2, 2).astype(
            np.float32), "m6": IMG, "th": theta, "x": A,
        "oh": np.eye(3, 4, dtype=np.float32),
        "pe": RNG.rand(2, 5, 8).astype(np.float32)})
    # maxout groups are CONSECUTIVE channels (reference maxout_op)
    np.testing.assert_allclose(
        outs[0], IMG.reshape(2, 2, 2, 6, 6).max(axis=2), rtol=1e-6)
    assert outs[1].shape == (2, 36, 2, 2)
    # shuffle_channel: [g, c/g] -> transposed
    ref = IMG[:, :, :2, :2].reshape(2, 2, 2, 2, 2).transpose(
        0, 2, 1, 3, 4).reshape(2, 4, 2, 2)
    np.testing.assert_allclose(outs[2], ref, rtol=1e-6)
    assert outs[3].shape == (2, 1, 6, 6)
    assert outs[4].shape == (4, 4, 2, 2)
    np.testing.assert_allclose(outs[5], IMG * 2 + 1, rtol=1e-6)
    assert outs[6].shape == (2, 4, 4, 2)
    np.testing.assert_allclose(
        outs[7], A / np.sqrt((A * A).sum(1, keepdims=True)), rtol=1e-5)
    np.testing.assert_allclose(
        outs[8], np.eye(3, 4, dtype=np.float32) * 0.9 + 0.1 / 4, rtol=1e-5)
    assert outs[9].shape == (2, 5, 8)


def test_norm_layers():
    x = RNG.rand(2, 4, 3, 3).astype(np.float32)
    outs = _run(lambda: [
        layers.instance_norm(_x("x", x.shape)),
        layers.group_norm(_x("x2", x.shape), groups=2),
        layers.data_norm(_x("x3", (8, 5))),
        layers.lrn(_x("x4", x.shape), n=3),
        layers.spectral_norm(_x("w", (6, 4)), power_iters=20),
    ], {"x": x, "x2": x, "x3": RNG.rand(8, 5).astype(np.float32),
        "x4": x, "w": RNG.randn(6, 4).astype(np.float32)})
    inorm = outs[0].reshape(2, 4, -1)
    np.testing.assert_allclose(inorm.mean(-1), 0, atol=1e-5)
    np.testing.assert_allclose(inorm.std(-1), 1, atol=1e-2)
    gview = outs[1].reshape(2, 2, -1)
    np.testing.assert_allclose(gview.mean(-1), 0, atol=1e-5)
    assert outs[2].shape == (8, 5)
    assert outs[3].shape == x.shape
    # spectral norm: largest singular value ~1
    s = np.linalg.svd(outs[4], compute_uv=False)
    np.testing.assert_allclose(s[0], 1.0, rtol=0.05)


def test_conv_pool_3d_and_transpose():
    vol = RNG.rand(1, 2, 4, 4, 4).astype(np.float32)
    outs = _run(lambda: [
        layers.conv3d(_x("v", vol.shape), 3, 3, padding=1),
        layers.pool3d(_x("v2", vol.shape), 2, "avg", pool_stride=2),
        layers.conv2d_transpose(_x("i", (1, 2, 4, 4)), 3, filter_size=2,
                                stride=2),
        layers.conv3d_transpose(_x("v3", vol.shape), 2, filter_size=2,
                                stride=2),
        layers.conv2d(_x("i2", (1, 4, 6, 6)), 4, 3, groups=4, padding=1),
    ], {"v": vol, "v2": vol, "i": RNG.rand(1, 2, 4, 4).astype(np.float32),
        "v3": vol, "i2": IMG[:1]})
    assert outs[0].shape == (1, 3, 4, 4, 4)
    np.testing.assert_allclose(
        outs[1][0, 0, 0, 0, 0], vol[0, 0, :2, :2, :2].mean(), rtol=1e-5)
    assert outs[2].shape == (1, 3, 8, 8)
    assert outs[3].shape == (1, 2, 8, 8, 8)
    assert outs[4].shape == (1, 4, 6, 6)  # depthwise via groups


def test_grid_sampler_identity():
    """An identity grid reproduces the input (bilinear sampling)."""
    x = RNG.rand(1, 2, 5, 5).astype(np.float32)
    ys, xs = np.meshgrid(np.linspace(-1, 1, 5), np.linspace(-1, 1, 5),
                         indexing="ij")
    grid = np.stack([xs, ys], axis=-1)[None].astype(np.float32)
    (out,) = _run(lambda: layers.grid_sampler(
        _x("x", x.shape), _x("g", grid.shape)), {"x": x, "g": grid})
    np.testing.assert_allclose(out, x, rtol=1e-4, atol=1e-5)


def test_losses():
    lbl01 = (RNG.rand(3, 4) > 0.5).astype(np.float32)
    left = RNG.rand(4, 1).astype(np.float32)
    right = RNG.rand(4, 1).astype(np.float32)
    lbl_lr = (RNG.rand(4, 1) > 0.5).astype(np.float32)
    prob = RNG.rand(4, 1).astype(np.float32) * 0.8 + 0.1
    outs = _run(lambda: [
        layers.huber_loss(_x("p", (3, 4)), _x("l", (3, 4)), delta=0.5),
        layers.hinge_loss(_x("p"), _x("l")),
        layers.log_loss(_x("pr", (4, 1)), _x("ll", (4, 1))),
        layers.kldiv_loss(_x("p"), _x("t"), reduction="none"),
        layers.mse_loss(_x("p"), _x("l")),
        layers.rank_loss(_x("rl", (4, 1)), _x("le", (4, 1)),
                         _x("ri", (4, 1))),
        layers.margin_rank_loss(_x("rl"), _x("le"), _x("ri"),
                                margin=0.1),
        layers.sigmoid_cross_entropy_with_logits(_x("p"), _x("l")),
        layers.teacher_student_sigmoid_loss(_x("ts", (4, 1)),
                                            _x("tl", (4, 1))),
        layers.square_error_cost(_x("p"), _x("l")),
        layers.bpr_loss(layers.softmax(_x("bp", (4, 3))),
                        _x("bl", (4, 1), "int64")),
    ], {"p": A, "l": lbl01, "pr": prob, "ll": lbl_lr, "t": np.abs(B) + .1,
        "rl": lbl_lr, "le": left, "ri": right, "ts": left * 4,
        "tl": lbl_lr, "bp": RNG.randn(4, 3).astype(np.float32),
        "bl": RNG.randint(0, 3, (4, 1)).astype(np.int64)})
    d = A - lbl01
    hub = np.where(np.abs(d) <= 0.5, 0.5 * d * d, 0.5 * (np.abs(d) - 0.25))
    np.testing.assert_allclose(outs[0], hub, rtol=1e-5)
    np.testing.assert_allclose(
        outs[1], np.maximum(0, 1 - (2 * lbl01 - 1) * A), rtol=1e-5)
    np.testing.assert_allclose(
        outs[2], -lbl_lr * np.log(prob + 1e-4) -
        (1 - lbl_lr) * np.log(1 - prob + 1e-4), rtol=1e-4)
    tgt = np.abs(B) + .1
    np.testing.assert_allclose(outs[3], tgt * (np.log(tgt) - A), rtol=1e-4)
    np.testing.assert_allclose(outs[4], ((A - lbl01) ** 2).mean(),
                               rtol=1e-5)
    np.testing.assert_allclose(
        outs[5], np.log1p(np.exp(left - right)) -
        lbl_lr * (left - right), rtol=1e-4)
    # label rides through as-is (the reference uses +-1 labels)
    np.testing.assert_allclose(
        outs[6], np.maximum(0, -lbl_lr * (left - right) + 0.1), rtol=1e-4)
    np.testing.assert_allclose(
        outs[7], np.maximum(A, 0) - A * lbl01 + np.log1p(
            np.exp(-np.abs(A))), rtol=1e-4)
    assert outs[8].shape == (4, 1)
    np.testing.assert_allclose(outs[9], (A - lbl01) ** 2, rtol=1e-5)
    assert outs[10].shape == (4, 1) and (outs[10] >= 0).all()


def test_center_npair_losses():
    feat = RNG.rand(6, 8).astype(np.float32)
    lbl = np.array([0, 1, 0, 2, 1, 2], np.int64)[:, None]
    anchor = RNG.rand(3, 8).astype(np.float32)
    positive = RNG.rand(3, 8).astype(np.float32)
    plbl = np.array([0, 1, 2], np.int64)
    outs = _run(lambda: [
        layers.center_loss(_x("f", feat.shape),
                           _x("l", lbl.shape, "int64"), 3, alpha=0.1,
                           update_center=False),
        layers.npair_loss(_x("a", anchor.shape), _x("p", anchor.shape),
                          _x("pl", (3,), "int64")),
    ], {"f": feat, "l": lbl, "a": anchor, "p": positive, "pl": plbl})
    assert outs[0].shape[0] == 6 and (outs[0] >= 0).all()
    assert np.isfinite(outs[1]).all()


def test_misc_ops():
    idx = np.array([0, 2, 1], np.int32)
    t1 = RNG.rand(3, 4).astype(np.float32)
    t2 = RNG.rand(3, 4).astype(np.float32)
    t3 = RNG.rand(3, 4).astype(np.float32)
    bx = RNG.rand(2, 3, 4).astype(np.float32)
    by = RNG.rand(2, 4, 5).astype(np.float32)
    outs = _run(lambda: [
        layers.multiplex([_x("t1"), _x("t2"), _x("t3")],
                         _x("ix", (3, 1), "int32")),
        layers.bmm(_x("bx", bx.shape), _x("by", by.shape)),
        layers.cos_sim(_x("t1"), _x("t2")),
        layers.hash(_x("h", (4, 1), "int64"), hash_size=97),
        layers.mean_iou(_x("mi", (6,), "int32"),
                        _x("ml", (6,), "int32"), 3)[0],
        layers.clip_by_norm(_x("t1"), max_norm=1.0),
        layers.shard_index(_x("si", (4, 1), "int64"), index_num=20,
                           nshards=2, shard_id=0),
    ], {"t1": t1, "t2": t2, "t3": t3, "ix": idx[:, None],
        "bx": bx, "by": by, "h": np.array([[1], [5], [9], [1]], np.int64),
        "mi": np.array([0, 1, 2, 0, 1, 2], np.int32),
        "ml": np.array([0, 1, 1, 0, 2, 2], np.int32),
        "si": np.array([[0], [7], [11], [19]], np.int64)})
    np.testing.assert_allclose(outs[0], np.stack([t1[0], t3[1], t2[2]]))
    np.testing.assert_allclose(outs[1], bx @ by, rtol=1e-5)
    ref_cs = (t1 * t2).sum(1) / (np.linalg.norm(t1, axis=1) *
                                 np.linalg.norm(t2, axis=1))
    np.testing.assert_allclose(outs[2].ravel(), ref_cs, rtol=1e-5)
    assert outs[3].shape[0] == 4 and (outs[3] < 97).all()
    assert outs[3][0, 0] == outs[3][3, 0]  # same input -> same hash
    assert 0 < outs[4] <= 1
    assert np.linalg.norm(outs[5]) <= 1.0 + 1e-5
    np.testing.assert_array_equal(outs[6].ravel(),
                                  [0, 7, -1, -1])  # shard 0 owns [0, 10)


def test_auc_metric():
    pred = np.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7], [0.6, 0.4]],
                    np.float32)
    lbl = np.array([[1], [0], [1], [0]], np.int64)
    (auc_val,) = _run(
        lambda: [layers.auc(_x("p", pred.shape),
                            _x("l", lbl.shape, "int64"))[0]],
        {"p": pred, "l": lbl})
    np.testing.assert_allclose(auc_val, 1.0, rtol=1e-3)  # perfect ranking


def test_optimizer_ops_single_step():
    """Each optimizer takes one step on a quadratic; param must move
    toward the minimum (value decreases)."""
    opts = [
        fluid.optimizer.Adadelta(learning_rate=1.0),
        fluid.optimizer.Adamax(learning_rate=0.1),
        fluid.optimizer.DecayedAdagrad(learning_rate=0.5),
        fluid.optimizer.Ftrl(learning_rate=0.5),
        fluid.optimizer.RMSProp(learning_rate=0.1),
        fluid.optimizer.Lamb(learning_rate=0.1),
        fluid.optimizer.LarsMomentum(learning_rate=0.1, momentum=0.9),
        fluid.optimizer.Dpsgd(learning_rate=0.1),
    ]
    for opt in opts:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            w = layers.create_parameter(
                [4], "float32", name="w",
                default_initializer=fluid.initializer.ConstantInitializer(
                    3.0))
            loss = layers.reduce_sum(layers.square(w))
            opt.minimize(loss)
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            l0 = None
            for _ in range(5):
                (l,) = exe.run(main, feed={}, fetch_list=[loss])
                l0 = l0 if l0 is not None else float(np.asarray(l))
            assert float(np.asarray(l)) < l0, type(opt).__name__


def test_collective_lowerings_on_mesh():
    """max/min/broadcast/concat/reducescatter/permute over an 8-dev mesh
    via the shard_map path (sum/avg are covered by test_parallel)."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    import jax.numpy as jnp

    devs = np.array(jax.devices()[:8]).reshape(8)
    mesh = Mesh(devs, ("dp",))
    x = np.arange(8, dtype=np.float32)[:, None]

    def body(v):
        vmax = jax.lax.pmax(v, "dp")
        vmin = jax.lax.pmin(v, "dp")
        return v * 0 + vmax + vmin

    f = shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    out = np.asarray(f(x))
    np.testing.assert_allclose(out, np.full((8, 1), 7.0))

    from paddle_tpu.fluid.registry import registry
    for t in ("c_allreduce_max", "c_allreduce_min", "c_broadcast",
              "c_concat", "c_reducescatter", "collective_permute",
              "c_sync_calc_stream", "c_sync_comm_stream"):
        assert t in registry.types()


def test_argminmax_and_interp():
    x4 = RNG.rand(1, 2, 4, 4).astype(np.float32)
    vol = RNG.rand(1, 1, 2, 4, 4).astype(np.float32)
    outs = _run(lambda: [
        layers.argmax(_x(), axis=1),        # arg_max
        layers.argmin(_x(), axis=0),        # arg_min
        layers.resize_bilinear(_x("i", x4.shape), out_shape=[8, 8]),
        layers.resize_nearest(_x("i2", x4.shape), out_shape=[8, 8]),
        layers.resize_trilinear(_x("v", vol.shape), out_shape=[4, 8, 8]),
    ], {"x": A, "i": x4, "i2": x4, "v": vol})
    np.testing.assert_array_equal(outs[0], A.argmax(1))
    np.testing.assert_array_equal(outs[1], A.argmin(0))
    assert outs[2].shape == (1, 2, 8, 8)
    # nearest: exact 2x upsample replicates pixels
    np.testing.assert_allclose(outs[3][:, :, ::2, ::2], x4, rtol=1e-6)
    assert outs[4].shape == (1, 1, 4, 8, 8)


def test_pad2d_prelu_unfold_smooth_l1():
    x4 = RNG.rand(1, 2, 3, 3).astype(np.float32) * 2 - 1
    outs = _run(lambda: [
        layers.pad2d(_x("i", x4.shape), paddings=[1, 1, 0, 2],
                     pad_value=5.0),
        layers.pad2d(_x("i", x4.shape), paddings=[1, 1, 1, 1],
                     mode="reflect"),
        layers.prelu(_x("i", x4.shape), mode="all"),
        layers.unfold(_x("i", x4.shape), kernel_sizes=[2, 2]),
        layers.smooth_l1(_x(), _y()),
        layers.has_inf(_x()),
        layers.has_nan(_x()),
    ], {"i": x4, "x": A, "y": B})
    # paddings order is [top, bottom, left, right]
    assert outs[0].shape == (1, 2, 5, 5)
    assert (outs[0][:, :, 0, :] == 5.0).all()
    np.testing.assert_allclose(outs[1][:, :, 0, 1:-1], x4[:, :, 1, :],
                               rtol=1e-6)  # reflect row
    # default prelu alpha 0.25
    np.testing.assert_allclose(
        outs[2], np.where(x4 > 0, x4, 0.25 * x4), rtol=1e-5)
    assert outs[3].shape == (1, 2 * 4, 4)  # C*k*k x L
    d = A - B
    sl1 = np.where(np.abs(d) < 1, 0.5 * d * d, np.abs(d) - 0.5).sum(
        1, keepdims=True)
    np.testing.assert_allclose(outs[4], sl1, rtol=1e-5)
    assert outs[5] == False and outs[6] == False  # noqa: E712


def test_bilinear_tensor_product_and_beam_decode():
    xv = RNG.rand(2, 3).astype(np.float32)
    yv = RNG.rand(2, 4).astype(np.float32)
    (btp,) = _run(lambda: [layers.bilinear_tensor_product(
        _x("bx", xv.shape), _x("by", yv.shape), size=5)],
        {"bx": xv, "by": yv})
    assert btp.shape == (2, 5)
    # beam_search_decode: backtrack a 2-step beam via parents
    ids = np.array([[0, 1], [1, 0]], np.int64)       # [T, beam]
    parents = np.array([[0, 0], [1, 0]], np.int64)
    scores = np.array([[0.5, 0.4], [0.9, 0.8]], np.float32)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        i = layers.data("i", ids.shape, append_batch_size=False,
                        dtype="int64")
        p = layers.data("p", parents.shape, append_batch_size=False,
                        dtype="int64")
        s = layers.data("s", scores.shape, append_batch_size=False)
        out_ids, out_scores = layers.beam_search_decode(
            i, s, beam_size=2, end_id=99, parents=p)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        got = exe.run(main, feed={"i": ids, "p": parents, "s": scores},
                      fetch_list=[out_ids])
    assert np.asarray(got[0]).shape[0] == 2  # one path per beam slot


def test_calc_gradient_api():
    """fluid.backward.calc_gradient: d(sum(w*x^2))/dx = 2*w*x."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = _x()
        x.stop_gradient = False
        y = layers.reduce_sum(3.0 * layers.square(x))
        (gx,) = fluid.backward.calc_gradient(y, [x])
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (g,) = exe.run(main, feed={"x": A}, fetch_list=[gx])
    np.testing.assert_allclose(np.asarray(g), 6.0 * A, rtol=1e-5)


def test_dynamic_lstmp():
    x = RNG.rand(6, 4 * 4).astype(np.float32)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = layers.data("x", [4 * 4], dtype="float32", lod_level=1)
        h, c = layers.dynamic_lstmp(xv, size=4 * 4, proj_size=3)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        hv, cv = exe.run(main, feed={
            "x": fluid.create_lod_tensor(x, [[4, 2]])},
            fetch_list=[h, c])
    assert np.asarray(hv).shape == (6, 3)   # projected hidden
    assert np.asarray(cv).shape == (6, 4)


def test_quant_freeze_path_ops():
    """fake_quantize_abs_max / fake_dequantize_max_abs /
    fake_quantize_range_abs_max / moving_average_abs_max_scale run as
    standalone ops (the freeze-path kernels)."""
    helper_types = [
        ("fake_quantize_abs_max", {"X": "x"},
         {"Out": "o", "OutScale": "s"}, {"bit_length": 8}),
    ]
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = _x()
        h = fluid.layer_helper.LayerHelper("q")
        q = h.create_variable_for_type_inference("float32")
        sc = h.create_variable_for_type_inference("float32")
        h.append_op(type="fake_quantize_abs_max", inputs={"X": [x]},
                    outputs={"Out": [q], "OutScale": [sc]},
                    attrs={"bit_length": 8})
        dq = h.create_variable_for_type_inference("float32")
        h.append_op(type="fake_dequantize_max_abs",
                    inputs={"X": [q], "Scale": [sc]},
                    outputs={"Out": [dq]}, attrs={"max_range": 127.0})
        iters = h.main_program.global_block().create_var(
            name="qiter", shape=[1], dtype="int32", persistable=True)
        insc = h.main_program.global_block().create_var(
            name="qinsc", shape=[1], dtype="float32", persistable=True)
        rq = h.create_variable_for_type_inference("float32")
        h.append_op(type="fake_quantize_range_abs_max",
                    inputs={"X": [x], "InScale": [insc], "Iter": [iters]},
                    outputs={"Out": [rq], "OutScale": [insc],
                             "OutIter": [iters]},
                    attrs={"bit_length": 8, "window_size": 4})
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        scope.set_var("qiter", np.zeros(1, np.int32))
        scope.set_var("qinsc", np.asarray([0.001], np.float32))
        exe.run(startup)
        o_dq, o_rq = exe.run(main, feed={"x": A}, fetch_list=[dq, rq])
    # quant->dequant round trip stays within one quantum
    np.testing.assert_allclose(np.asarray(o_dq), A,
                               atol=np.abs(A).max() / 127 + 1e-6)
    assert np.isfinite(np.asarray(o_rq)).all()


def test_detection_aliases_execute():
    """locality_aware_nms / retinanet_target_assign run through their own
    registered type names."""
    boxes = np.array([[[0, 0, 10, 10], [20, 20, 30, 30]]], np.float32)
    scores = np.array([[[0.9, 0.6]]], np.float32)
    anchors = np.array([[0, 0, 10, 10], [20, 20, 30, 30]], np.float32)
    gt = np.array([[0, 0, 10, 10]], np.float32)

    def build():
        b = _x("b", boxes.shape)
        s = _x("s", scores.shape)
        out = layers.locality_aware_nms(b, s, 0.1, 2, 2)
        a = _x("a", anchors.shape)
        g = _x("g", gt.shape)
        res = layers.retinanet_target_assign(None, None, a, None, g, None)
        return [out, res[2]]

    out, lbl = _run(build, {"b": boxes, "s": scores, "a": anchors,
                            "g": gt})
    assert out.shape == (1, 2, 6)
    assert lbl[0] == 1 and lbl[1] == 0


# The former textual-mention gate (grep for op-type strings in test
# sources) lived here; it is superseded by the EXECUTION-based gate in
# test_zz_coverage_gate.py (VERDICT r3 #4): every registered lowering
# must actually RUN during the suite.


def test_range_with_constant_variable_bounds():
    """Input-slot bounds backed by constants (assign_value) must lower —
    only live tracers are runtime-variable."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        start = layers.assign(np.asarray([0.0], np.float32))
        end = layers.assign(np.asarray([10.0], np.float32))
        step = layers.assign(np.asarray([3.0], np.float32))
        out = layers.range(start, end, step, "float32")
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (r,) = exe.run(main, feed={}, fetch_list=[out])
    np.testing.assert_allclose(np.asarray(r), [0, 3, 6, 9])


def test_conv3d_transpose_grouped_dilated():
    vol = RNG.rand(1, 4, 3, 3, 3).astype(np.float32)
    (out,) = _run(lambda: [layers.conv3d_transpose(
        _x("v", vol.shape), num_filters=4, filter_size=2, stride=2,
        groups=2, dilation=1)], {"v": vol})
    assert out.shape == (1, 4, 6, 6, 6)
