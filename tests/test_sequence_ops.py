"""Sequence (LoD) op family — reference ``operators/sequence_ops/`` +
``layers/sequence_lod.py`` (16 fns), numpy-referenced per SURVEY §4.

The TPU encoding under test: flattened [total_bound, D] data + @LOD lengths
("bounded LoD", fluid/lod.py) — every op must mask physical padding rows.
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers


X = np.arange(12, dtype=np.float32).reshape(6, 2)  # two seqs: 4 + 2
LENS = [4, 2]


def test_sequence_pool_all_types():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[2], dtype="float32", lod_level=1)
        fetch = [layers.sequence_pool(x, t)
                 for t in ("sum", "average", "sqrt", "max", "first", "last")]
    exe = fluid.Executor()
    feed = {"x": fluid.create_lod_tensor(X, [LENS])}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        s, a, q, m, f, l = [np.asarray(r) for r in
                            exe.run(main, feed=feed, fetch_list=fetch)]
    seqs = [X[:4], X[4:6]]
    np.testing.assert_allclose(s, [sq.sum(0) for sq in seqs], rtol=1e-6)
    np.testing.assert_allclose(a, [sq.mean(0) for sq in seqs], rtol=1e-6)
    np.testing.assert_allclose(
        q, [sq.sum(0) / np.sqrt(len(sq)) for sq in seqs], rtol=1e-6)
    np.testing.assert_allclose(m, [sq.max(0) for sq in seqs], rtol=1e-6)
    np.testing.assert_allclose(f, [sq[0] for sq in seqs], rtol=1e-6)
    np.testing.assert_allclose(l, [sq[-1] for sq in seqs], rtol=1e-6)


def test_sequence_pool_ignores_physical_padding():
    """Rows past sum(lengths) must not leak into the pool."""
    data = np.vstack([X, np.full((2, 2), 99.0, np.float32)])  # 2 pad rows
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[2], dtype="float32", lod_level=1)
        out = layers.sequence_pool(x, "sum")
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (r,) = exe.run(main, feed={
            "x": fluid.create_lod_tensor(data, [LENS])}, fetch_list=[out])
    np.testing.assert_allclose(np.asarray(r), [X[:4].sum(0), X[4:6].sum(0)],
                               rtol=1e-6)


def test_sequence_softmax():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[1], dtype="float32", lod_level=1)
        out = layers.sequence_softmax(x)
    v = np.array([[1.0], [2.0], [3.0], [0.5], [1.5], [0.0]], np.float32)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (r,) = exe.run(main, feed={
            "x": fluid.create_lod_tensor(v, [[3, 2, 1]])}, fetch_list=[out])
    r = np.asarray(r).ravel()

    def sm(a):
        e = np.exp(a - a.max())
        return e / e.sum()

    np.testing.assert_allclose(r[:3], sm(v.ravel()[:3]), rtol=1e-5)
    np.testing.assert_allclose(r[3:5], sm(v.ravel()[3:5]), rtol=1e-5)
    np.testing.assert_allclose(r[5], 1.0, rtol=1e-5)


def test_sequence_reverse():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[2], dtype="float32", lod_level=1)
        out = layers.sequence_reverse(x)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (r,) = exe.run(main, feed={
            "x": fluid.create_lod_tensor(X, [LENS])}, fetch_list=[out])
    expect = np.vstack([X[:4][::-1], X[4:6][::-1]])
    np.testing.assert_allclose(np.asarray(r), expect, rtol=1e-6)


def test_sequence_expand_dense_x():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[3], dtype="float32")
        y = layers.data("y", shape=[1], dtype="float32", lod_level=1)
        out = layers.sequence_expand(x, y)
    xv = np.array([[1, 2, 3], [4, 5, 6]], np.float32)
    yv = np.zeros((5, 1), np.float32)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (r,) = exe.run(main, feed={
            "x": xv, "y": fluid.create_lod_tensor(yv, [[3, 2]])},
            fetch_list=[out])
    expect = np.vstack([np.tile(xv[0], (3, 1)), np.tile(xv[1], (2, 1))])
    np.testing.assert_allclose(np.asarray(r), expect, rtol=1e-6)


def test_sequence_expand_as():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[2], dtype="float32")
        y = layers.data("y", shape=[1], dtype="float32", lod_level=1)
        out = layers.sequence_expand_as(x, y)
    xv = np.array([[1, 2], [3, 4]], np.float32)
    yv = np.zeros((6, 1), np.float32)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (r,) = exe.run(main, feed={
            "x": xv, "y": fluid.create_lod_tensor(yv, [[4, 2]])},
            fetch_list=[out])
    expect = np.vstack([np.tile(xv[0], (4, 1)), np.tile(xv[1], (2, 1))])
    np.testing.assert_allclose(np.asarray(r), expect, rtol=1e-6)


def test_sequence_pad_unpad_roundtrip():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[2], dtype="float32", lod_level=1)
        pad_v = layers.fill_constant([1], "float32", -1.0)
        padded, length = layers.sequence_pad(x, pad_v, maxlen=5)
        back = layers.sequence_unpad(padded, length)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        p, ln, b = exe.run(main, feed={
            "x": fluid.create_lod_tensor(X, [LENS])},
            fetch_list=[padded, length, back])
    p, ln, b = np.asarray(p), np.asarray(ln), np.asarray(b)
    assert p.shape == (2, 5, 2)
    np.testing.assert_allclose(p[0, :4], X[:4], rtol=1e-6)
    np.testing.assert_allclose(p[0, 4:], -1.0)
    np.testing.assert_allclose(p[1, :2], X[4:6], rtol=1e-6)
    np.testing.assert_array_equal(ln, [4, 2])
    np.testing.assert_allclose(b[:6], X, rtol=1e-6)


def test_sequence_mask():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[], dtype="int64")
        out = layers.sequence_mask(x, maxlen=5, dtype="float32")
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (r,) = exe.run(main, feed={"x": np.array([3, 1, 5], np.int64)},
                       fetch_list=[out])
    expect = np.array([[1, 1, 1, 0, 0], [1, 0, 0, 0, 0], [1, 1, 1, 1, 1]],
                      np.float32)
    np.testing.assert_array_equal(np.asarray(r), expect)


def test_sequence_reshape():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32", lod_level=1)
        out = layers.sequence_reshape(x, new_dim=2)
        pooled = layers.sequence_pool(out, "sum")
    v = np.arange(16, dtype=np.float32).reshape(4, 4)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        r, p = exe.run(main, feed={
            "x": fluid.create_lod_tensor(v, [[1, 3]])},
            fetch_list=[out, pooled])
    np.testing.assert_allclose(np.asarray(r), v.reshape(8, 2), rtol=1e-6)
    # new lengths are [2, 6]
    np.testing.assert_allclose(
        np.asarray(p),
        [v.reshape(8, 2)[:2].sum(0), v.reshape(8, 2)[2:].sum(0)], rtol=1e-6)


def test_sequence_concat():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = layers.data("a", shape=[1], dtype="float32", lod_level=1)
        b = layers.data("b", shape=[1], dtype="float32", lod_level=1)
        out = layers.sequence_concat([a, b])
    av = np.array([[1], [2], [3]], np.float32)       # lens [2,1]
    bv = np.array([[10], [20], [30]], np.float32)    # lens [1,2]
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (r,) = exe.run(main, feed={
            "a": fluid.create_lod_tensor(av, [[2, 1]]),
            "b": fluid.create_lod_tensor(bv, [[1, 2]])}, fetch_list=[out])
    # out seq0 = [1,2,10]; seq1 = [3,20,30]
    np.testing.assert_allclose(np.asarray(r).ravel(),
                               [1, 2, 10, 3, 20, 30], rtol=1e-6)


def test_sequence_slice():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[2], dtype="float32", lod_level=1)
        off = layers.data("off", shape=[1], dtype="int64")
        ln = layers.data("ln", shape=[1], dtype="int64")
        out = layers.sequence_slice(x, off, ln)
        pooled = layers.sequence_pool(out, "sum")
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        r, p = exe.run(main, feed={
            "x": fluid.create_lod_tensor(X, [LENS]),
            "off": np.array([[1], [0]], np.int64),
            "ln": np.array([[2], [1]], np.int64)}, fetch_list=[out, pooled])
    r = np.asarray(r)
    # seq0 slice = X[1:3], seq1 slice = X[4:5]
    np.testing.assert_allclose(r[0], X[1], rtol=1e-6)
    np.testing.assert_allclose(r[1], X[2], rtol=1e-6)
    np.testing.assert_allclose(r[2], X[4], rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(p), [X[1:3].sum(0), X[4:5].sum(0)], rtol=1e-6)


def test_sequence_enumerate():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[1], dtype="int64", lod_level=1)
        out = layers.sequence_enumerate(x, win_size=2, pad_value=0)
    v = np.array([[1], [2], [3], [7], [8]], np.int64)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (r,) = exe.run(main, feed={
            "x": fluid.create_lod_tensor(v, [[3, 2]])}, fetch_list=[out])
    expect = np.array([[1, 2], [2, 3], [3, 0], [7, 8], [8, 0]])
    np.testing.assert_array_equal(np.asarray(r), expect)


def test_sequence_erase():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[1], dtype="int64", lod_level=1)
        out = layers.sequence_erase(x, tokens=[2, 8])
        pooled = layers.sequence_pool(out.astype("float32"), "sum")
    v = np.array([[1], [2], [3], [7], [8]], np.int64)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        r, p = exe.run(main, feed={
            "x": fluid.create_lod_tensor(v, [[3, 2]])},
            fetch_list=[out, pooled])
    r = np.asarray(r).ravel()
    # seq0 keeps [1,3], seq1 keeps [7]; front-packed
    assert r[0] == 1 and r[1] == 3
    np.testing.assert_allclose(np.asarray(p).ravel(), [4.0, 7.0], rtol=1e-6)


def test_sequence_scatter():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        ids = layers.data("ids", shape=[1], dtype="int64", lod_level=1)
        upd = layers.data("upd", shape=[1], dtype="float32", lod_level=1)
        out = layers.sequence_scatter(x, ids, upd)
    xv = np.zeros((2, 4), np.float32)
    idv = np.array([[0], [2], [1]], np.int64)       # lens [2, 1]
    uv = np.array([[5.0], [6.0], [7.0]], np.float32)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (r,) = exe.run(main, feed={
            "x": xv,
            "ids": fluid.create_lod_tensor(idv, [[2, 1]]),
            "upd": fluid.create_lod_tensor(uv, [[2, 1]])}, fetch_list=[out])
    expect = np.array([[5, 0, 6, 0], [0, 7, 0, 0]], np.float32)
    np.testing.assert_allclose(np.asarray(r), expect, rtol=1e-6)


def test_sequence_conv_trains():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 3
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32", lod_level=1)
        h = layers.sequence_conv(x, num_filters=8, filter_size=3, act="relu")
        pooled = layers.sequence_pool(h, "max")
        loss = layers.mean(pooled)
        from paddle_tpu.fluid import optimizer

        optimizer.SGD(0.1).minimize(loss)
    v = np.random.RandomState(0).rand(7, 4).astype(np.float32)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        vals = [float(np.asarray(exe.run(main, feed={
            "x": fluid.create_lod_tensor(v, [[4, 3]])},
            fetch_list=[loss])[0])) for _ in range(4)]
    assert np.isfinite(vals).all()
    assert vals[-1] != vals[0]  # sequence_conv grads flow


def test_row_conv():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[2], dtype="float32", lod_level=1)
        out = layers.row_conv(x, future_context_size=1)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (r,) = exe.run(main, feed={
            "x": fluid.create_lod_tensor(X, [LENS])}, fetch_list=[out])
    assert np.asarray(r).shape == X.shape


def test_im2sequence():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[1, 4, 4], dtype="float32")
        out = layers.im2sequence(x, filter_size=[2, 2], stride=[2, 2])
        pooled = layers.sequence_pool(out, "sum")
    v = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        r, p = exe.run(main, feed={"x": v}, fetch_list=[out, pooled])
    r = np.asarray(r)
    assert r.shape == (4, 4)
    np.testing.assert_allclose(r[0], [0, 1, 4, 5], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(p)[0], r.sum(axis=0), rtol=1e-6)


def test_lod_propagates_through_embedding_and_fc():
    """The generic ShareLoD rule: token-aligned ops carry @LOD forward so
    sequence ops compose with embedding/fc like reference LoD propagation."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 1
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[1], dtype="int64", lod_level=1)
        emb = layers.embedding(ids, size=[20, 4])
        emb = layers.reshape(emb, [-1, 4])
        pooled = layers.sequence_pool(emb, "average")
        loss = layers.mean(pooled)
    idv = np.array([[1], [2], [3], [4], [5]], np.int64)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (p,) = exe.run(main, feed={
            "ids": fluid.create_lod_tensor(idv, [[3, 2]])},
            fetch_list=[pooled])
    assert np.asarray(p).shape == (2, 4)
