"""Misc SURVEY §2.10 modules: evaluator, average, debugger, install_check,
data_generator, compat, wait_server_ready."""

import threading

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import average, compat, debugger, evaluator, layers


def test_weighted_average():
    w = average.WeightedAverage()
    with pytest.raises(ValueError):
        w.eval()
    w.add(2.0, 1.0)
    w.add(4.0, 3.0)
    assert abs(w.eval() - (2.0 + 12.0) / 4.0) < 1e-9
    w.reset()
    w.add(np.array([1.0, 3.0]), 2.0)  # mean 2.0
    assert abs(w.eval() - 2.0) < 1e-9


def test_edit_distance_evaluator():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        hyp = layers.data("ed_hyp", [4], dtype="int64",
                          append_batch_size=False)
        ref = layers.data("ed_ref", [4], dtype="int64",
                          append_batch_size=False)
        hlen = layers.data("ed_hlen", [1], dtype="int64",
                           append_batch_size=False)
        rlen = layers.data("ed_rlen", [1], dtype="int64",
                           append_batch_size=False)
        dist, _ = layers.edit_distance(hyp, ref, normalized=False,
                                       input_length=hlen, label_length=rlen)
    exe = fluid.Executor()
    exe.run(startup)
    (d,) = exe.run(main, feed={
        "ed_hyp": np.array([[1, 2, 3, 0]], np.int64),
        "ed_ref": np.array([[1, 3, 3, 0]], np.int64),
        "ed_hlen": np.array([3], np.int64),
        "ed_rlen": np.array([3], np.int64)}, fetch_list=[dist])
    assert float(np.asarray(d).reshape(-1)[0]) == 1.0


def test_chunk_evaluator_accumulates_and_resets():
    # IOB scheme, 1 chunk type: ids 0=B,1=I,2=O.
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        inf = layers.data("ce_inf", [6], dtype="int64",
                          append_batch_size=False)
        lab = layers.data("ce_lab", [6], dtype="int64",
                          append_batch_size=False)
        ev = evaluator.ChunkEvaluator(inf, lab, chunk_scheme="IOB",
                                      num_chunk_types=1)
    exe = fluid.Executor()
    exe.run(startup)
    seq = np.array([[0, 1, 2, 0, 1, 2]], np.int64)
    exe.run(main, feed={"ce_inf": seq, "ce_lab": seq}, fetch_list=[])
    p, r, f1 = ev.eval(exe)
    assert p == 1.0 and r == 1.0 and f1 == 1.0
    ev.reset(exe)
    p, r, f1 = ev.eval(exe)
    assert p == 0.0 and r == 0.0 and f1 == 0.0


def test_detection_map_evaluator():
    m = evaluator.DetectionMAP(class_num=2, overlap_threshold=0.5)
    gt_boxes = np.array([[0, 0, 10, 10], [20, 20, 30, 30]], np.float64)
    gt_labels = np.array([0, 1], np.int64)
    dets = np.array([
        [0, 0.9, 0, 0, 10, 10],      # TP class 0
        [1, 0.8, 20, 20, 30, 30],    # TP class 1
        [1, 0.7, 50, 50, 60, 60],    # FP class 1
    ], np.float64)
    m.update(dets, gt_boxes, gt_labels)
    val = m.eval()
    assert 0.5 < val <= 1.0
    m.reset()
    assert m.eval() == 0.0


def test_debugger_dump_and_dot(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("dbg_x", [3])
        y = layers.fc(x, 2)
    text = debugger.pprint_program_codes(main)
    assert "matmul" in text or "mul" in text
    dot = debugger.draw_block_graphviz(main.global_block(),
                                       highlights=["dbg_x"],
                                       path=str(tmp_path / "g.dot"))
    assert dot.startswith("digraph G {") and "dbg_x" in dot
    assert (tmp_path / "g.dot").exists()


def test_install_check_runs():
    fluid.install_check.run_check()


def test_data_generator_multislot():
    from paddle_tpu.fluid.incubate.data_generator import (
        MultiSlotDataGenerator, MultiSlotStringDataGenerator)

    class G(MultiSlotDataGenerator):
        def generate_sample(self, line):
            def gen():
                a, b = line.split("|")
                yield [("ids", [int(t) for t in a.split()]),
                       ("label", [float(b)])]
            return gen

    g = G()
    lines = g.run_from_memory(["1 2 3|0", "4|1"])
    assert lines == ["3 1 2 3 1 0.0\n", "1 4 1 1.0\n"]

    class S(MultiSlotStringDataGenerator):
        def generate_sample(self, line):
            def gen():
                yield [("tok", line.split())]
            return gen

    assert S().run_from_memory(["a b"]) == ["2 a b\n"]


def test_compat_check():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("cm_x", [3])
        layers.fc(x, 2)
    assert compat.check_program_compatible(main)
    # desc with an unknown op fails
    desc = {"version": 1,
            "blocks": [{"ops": [{"type": "totally_unknown_op_xyz"}]}]}
    info = compat.check_program_compatible(desc)
    assert not info and info.status == compat.CompatibleInfo.UNDEFINED_OP
    info = compat.check_program_compatible({"version": 999, "blocks": []})
    assert info.status == compat.CompatibleInfo.UNSUPPORTED_VERSION


def test_wait_server_ready():
    import socket

    from paddle_tpu.distributed import wait_server_ready

    srv = socket.socket()  # accept-only stub for the readiness poller
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    t = threading.Thread(target=srv.accept, daemon=True)
    t.start()
    wait_server_ready(["127.0.0.1:%d" % port], timeout=10)
    t.join(timeout=5)  # accept completes once the poller connected
    srv.close()
    with pytest.raises(TimeoutError):
        wait_server_ready(["127.0.0.1:1"], timeout=0.5, interval=0.1)


def test_fluid_top_level_parity_attrs():
    """1.6 top-level surface: name_scope annotates ops, require_version
    gates, places/device_guard/memory_optimize accept the reference
    calls, save/load and embedding/one_hot are reachable."""
    assert callable(fluid.save) and callable(fluid.load)
    assert fluid.embedding is fluid.layers.embedding
    assert len(fluid.cpu_places(3)) == 3
    fluid.memory_optimize(None)      # deprecated no-op
    fluid.release_memory(None)
    with fluid.device_guard("gpu:0"):
        pass
    fluid.require_version("1.5.0")
    with pytest.raises(Exception, match="tracks"):
        fluid.require_version("9.9.9")
    with pytest.raises(NotImplementedError, match="registry"):
        fluid.load_op_library("/tmp/libfoo.so")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("ns_x", [4])
        with fluid.name_scope("outer"):
            with fluid.name_scope("inner"):
                fluid.layers.relu(x)
    op = main.global_block().ops[-1]
    assert op.attrs.get("op_namescope") == "outer/inner"
    # the annotated program still runs and round-trips
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        exe.run(main, feed={"ns_x": np.ones((2, 4), np.float32)},
                fetch_list=[main.global_block().ops[-1].output("Out")[0]])
    from paddle_tpu.fluid.core import proto_io

    proto_io.program_from_bytes(proto_io.program_to_bytes(main.to_desc()))


def test_image_util_resize_crop_flip(tmp_path):
    """paddle.utils.image_util tier (reference utils/image_util.py:20):
    short-edge resize, center/random crop with padding, deterministic
    rng, jpeg decode round trip."""
    from PIL import Image

    from paddle_tpu.utils import image_util as iu

    img = Image.fromarray(
        (np.random.RandomState(0).rand(40, 60, 3) * 255).astype(np.uint8))
    small = iu.resize_image(img, 20)
    assert min(small.size) == 20 and max(small.size) == 30

    chw = np.transpose(np.asarray(img, np.float32), (2, 0, 1))
    center = iu.crop_img(chw, 24, color=True, test=True)
    assert center.shape == (3, 24, 24)
    np.testing.assert_allclose(center, chw[:, 8:32, 18:42])
    # gray path + padding when the image is smaller than the crop
    gray = np.ones((10, 12), np.float32)
    padded = iu.crop_img(gray, 16, color=False, test=True)
    assert padded.shape == (16, 16) and padded.sum() == gray.sum()
    # train mode: same rng seed -> same crop
    a = iu.crop_img(chw, 24, test=False, rng=np.random.RandomState(3))
    b = iu.crop_img(chw, 24, test=False, rng=np.random.RandomState(3))
    np.testing.assert_array_equal(a, b)

    # flip is width-axis for both layouts
    np.testing.assert_array_equal(iu.flip(chw)[:, :, 0], chw[:, :, -1])
    np.testing.assert_array_equal(iu.flip(gray)[:, 0], gray[:, -1])

    # decode_jpeg: CHW out, content approximately survives the codec
    buf = tmp_path / "x.jpg"
    img.save(str(buf), quality=95)
    dec = iu.decode_jpeg(open(str(buf), "rb").read())
    assert dec.shape == (3, 40, 60)
    loaded = iu.load_image(str(buf))
    assert loaded.size == (60, 40)

    # preprocess = crop + mean-subtract + flatten; the INPUT must not be
    # mutated even when the crop is a view (cached-image pipelines)
    mean = np.full((3, 24, 24), 5.0, np.float32)
    before = chw.copy()
    flat = iu.preprocess_img(chw, mean, 24, is_train=False)
    assert flat.shape == (3 * 24 * 24,)
    np.testing.assert_allclose(flat, center.flatten() - 5.0)
    np.testing.assert_array_equal(chw, before)
    # non-3-channel padding path
    two_ch = np.ones((2, 10, 10), np.float32)
    assert iu.crop_img(two_ch, 16, color=True, test=True).shape == (2, 16, 16)


def test_image_util_oversample_meta_transformer(tmp_path):
    from paddle_tpu.utils import image_util as iu

    im = np.arange(32 * 32 * 3, dtype=np.float32).reshape(32, 32, 3)
    crops = iu.oversample([im], (24, 24))
    assert crops.shape == (10, 24, 24, 3)
    # crop 0 is the top-left corner; crop 5 is its mirror
    np.testing.assert_array_equal(crops[0], im[:24, :24, :])
    np.testing.assert_array_equal(crops[5], crops[0][:, ::-1, :])
    # center crop present
    np.testing.assert_array_equal(crops[4], im[4:28, 4:28, :])

    # load_meta: mean image center-cropped
    mean_flat = np.arange(3 * 32 * 32, dtype=np.float64)
    np.savez(str(tmp_path / "meta.npz"), data_mean=mean_flat)
    m = iu.load_meta(str(tmp_path / "meta.npz"), 32, 24)
    assert m.shape == (3, 24, 24) and m.dtype == np.float32
    np.testing.assert_allclose(
        m, mean_flat.reshape(3, 32, 32)[:, 4:28, 4:28])

    # transformer chain: transpose -> swap -> mean
    t = iu.ImageTransformer(transpose=(2, 0, 1), channel_swap=(2, 1, 0),
                            mean=np.array([1.0, 2.0, 3.0]))
    hwc = np.random.RandomState(1).rand(8, 8, 3).astype(np.float32)
    out = t.transformer(hwc)
    ref = hwc.transpose(2, 0, 1)[[2, 1, 0]] - np.array(
        [1.0, 2.0, 3.0], np.float32)[:, None, None]
    np.testing.assert_allclose(out, ref, rtol=1e-6)


def test_data_feed_desc_roundtrip(tmp_path):
    """fluid.DataFeedDesc (reference data_feed_desc.py:21): parse the
    MultiSlotDataFeed textproto, mutate via the reference API, re-emit."""
    proto = tmp_path / "data.proto"
    proto.write_text(
        'name: "MultiSlotDataFeed"\n'
        "batch_size: 2\n"
        "multi_slot_desc {\n"
        "    slots {\n"
        '         name: "words"\n'
        '         type: "uint64"\n'
        "         is_dense: false\n"
        "         is_used: false\n"
        "     }\n"
        "     slots {\n"
        '         name: "label"\n'
        '         type: "uint64"\n'
        "         is_dense: false\n"
        "         is_used: false\n"
        "    }\n"
        "}\n")
    d = fluid.DataFeedDesc(str(proto))
    assert d.name == "MultiSlotDataFeed" and d.batch_size == 2
    d.set_batch_size(128)
    d.set_dense_slots(["words"])
    d.set_use_slots(["words", "label"])
    text = d.desc()
    assert "batch_size: 128" in text
    assert text.count("is_used: true") == 2
    assert text.count("is_dense: true") == 1
    with pytest.raises(ValueError, match="not found"):
        d.set_use_slots(["bogus"])
    # re-parse what we emitted
    proto2 = tmp_path / "rt.proto"
    proto2.write_text(text)
    d2 = fluid.DataFeedDesc(str(proto2))
    assert d2.batch_size == 128
    assert [s.is_dense for s in d2.slots] == [True, False]


def test_lod_tensor_array():
    import numpy as np

    arr = fluid.LoDTensorArray()
    arr.append(fluid.create_lod_tensor(
        np.ones((3, 2), np.float32), [[2, 1]]))
    arr.append(np.zeros((2, 2), np.float32))   # coerced
    assert len(arr) == 2
    assert all(isinstance(t, fluid.LoDTensor) for t in arr)
    from paddle_tpu.fluid import core
    assert core.LoDTensorArray is fluid.LoDTensorArray


def test_data_feed_desc_preserves_unknown_fields(tmp_path):
    p = tmp_path / "d2.proto"
    p.write_text('name: "MultiSlotDataFeed"\nbatch_size: 4\n'
                 'thread_num: 7\nfs_name: "hdfs://x"\n'
                 'multi_slot_desc {\n  slots {\n    name: "a"\n'
                 '    type: "float"\n    is_dense: true\n'
                 '    is_used: true\n  }\n}\n')
    d = fluid.DataFeedDesc(str(p))
    text = d.desc()
    assert "thread_num: 7" in text and 'fs_name: "hdfs://x"' in text


def test_lod_tensor_array_coerces_every_path():
    import numpy as np

    a = fluid.LoDTensorArray([np.zeros((2, 2), np.float32)])
    a.extend([np.ones((1, 2), np.float32)])
    a.insert(0, np.ones((3, 2), np.float32))
    a[0] = np.zeros((1, 2), np.float32)
    a[0:1] = [np.ones((2, 2), np.float32)]
    assert all(isinstance(t, fluid.LoDTensor) for t in a)


def test_paddle_top_level_surface():
    """Reference ``python/paddle/__init__.py`` top-level exports:
    batch/compat/dataset/distributed/reader/sysconfig/version are
    importable attributes with working behavior."""
    import os

    import paddle_tpu as paddle

    for m in ("batch", "compat", "dataset", "distributed", "reader",
              "sysconfig", "version"):
        assert hasattr(paddle, m), m
    assert paddle.compat.to_text(b"ab") == "ab"
    assert paddle.compat.to_bytes({"x"}) == {b"x"}
    s = ["a", b"c"]
    paddle.compat.to_text(s, inplace=True)
    assert s == ["a", "c"]
    # py2-style half-away-from-zero rounding
    assert paddle.compat.round(0.5) == 1.0
    assert paddle.compat.round(-0.5) == -1.0
    assert paddle.compat.floor_division(7, 2) == 3
    assert os.path.exists(os.path.join(paddle.sysconfig.get_include(),
                                       "c_api.h"))
    assert paddle.sysconfig.get_lib() == paddle.sysconfig.get_include()
    assert paddle.version.full_version.startswith("1.6")
    batches = list(paddle.batch(lambda: iter(range(5)), 2)())
    assert batches[0] == [0, 1]
    assert paddle.check_import_scipy()
