"""Elastic-launcher runner for tests/test_elastic.py: trains a small
MLP with periodic crash-consistent checkpoints under the launcher's
heartbeat + preemption contract, optionally injuring itself through the
``faults`` points so the parent test can watch ``distributed.launch``
drain, reform, or watchdog-kill the gang:

- ``worker.preempt`` — self-SIGTERM partway through the first attempt;
  the ``distributed.preemption`` drain handlers (installed by
  ``Executor.run`` because the launcher exports PADDLE_PREEMPT_DRAIN=1)
  finish the step, force-checkpoint, and exit 0 with a ``.preempted``
  marker.
- ``worker.exit`` — hard ``os._exit`` whenever this rank runs at a
  specific world size, so the launcher exhausts its same-size budget
  and shrinks the gang to the survivors.
- ``worker.hang`` — wedge the training thread while the Heartbeat's
  daemon stamper keeps beating: invisible to the staleness check, only
  the hung-step deadline watchdog catches it (and SIGUSR1s this process
  so faulthandler dumps every thread's stack into this log).

Determinism contract is dist_runner_ckpt's: step ``i``'s feed derives
from ``RandomState(1234 + i)`` and the rng is checkpointed, so a
drained-and-resumed run must reach final weights BIT-IDENTICAL to an
uninterrupted one.

Env knobs (all set by tests/test_elastic.py):
  PADDLE_TEST_TOTAL        total training steps (default 8)
  PADDLE_TEST_EVERY        checkpoint every n steps (default 2)
  PADDLE_TEST_PREEMPT_AT   arm worker.preempt after N steps (rank 0,
                           first attempt only)
  PADDLE_TEST_CRASH_RANK   arm worker.exit on this rank ...
  PADDLE_TEST_CRASH_WORLD  ... whenever the gang runs at this size
  PADDLE_TEST_CRASH_AT     ... after this many completed steps (def. 2)
  PADDLE_TEST_HANG_AT      arm worker.hang after N steps (first attempt)
  PADDLE_TEST_HANG_RANK    which rank hangs (default 0)
  PADDLE_TEST_COMPILED     "1": rank 0 trains a data-parallel
                           CompiledProgram over the local virtual-CPU
                           mesh and restores THROUGH it, exercising
                           reshard-on-restore

Prints ``WORLD <n> RANK <r> ATTEMPT <a>``, ``RESUMED <step>``,
``RESHARD <n>`` (compiled mode) and ``WEIGHTS <sha256>`` lines the
parent parses from the worker log.
"""

import hashlib
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import paddle_tpu.fluid as fluid  # noqa: E402
from paddle_tpu.fluid import faults, layers, monitor, optimizer  # noqa: E402
from paddle_tpu.distributed.heartbeat import Heartbeat  # noqa: E402

TOTAL = int(os.environ.get("PADDLE_TEST_TOTAL", "8"))
EVERY = int(os.environ.get("PADDLE_TEST_EVERY", "2"))
ATTEMPT = int(os.environ.get("PADDLE_RESTART_ATTEMPT", "0") or 0)
RANK = int(os.environ.get("PADDLE_TRAINER_ID", "0") or 0)
WORLD = int(os.environ.get("PADDLE_TRAINERS_NUM", "1") or 1)
COMPILED = os.environ.get("PADDLE_TEST_COMPILED") == "1"


def arm_faults():
    """Programmatic arming (PADDLE_FAULTS would re-arm on every respawn;
    these knobs gate on attempt/rank/world so the launcher's recovery
    path actually gets exercised instead of re-injured forever)."""
    preempt_at = os.environ.get("PADDLE_TEST_PREEMPT_AT")
    if preempt_at is not None and ATTEMPT == 0 and RANK == 0:
        faults.arm("worker.preempt", after_n=int(preempt_at))
    crash_rank = os.environ.get("PADDLE_TEST_CRASH_RANK")
    if crash_rank is not None and RANK == int(crash_rank) and \
            WORLD == int(os.environ.get("PADDLE_TEST_CRASH_WORLD", "-1")):
        faults.arm("worker.exit",
                   after_n=int(os.environ.get("PADDLE_TEST_CRASH_AT",
                                              "2")))
    hang_at = os.environ.get("PADDLE_TEST_HANG_AT")
    if hang_at is not None and ATTEMPT == 0 and \
            RANK == int(os.environ.get("PADDLE_TEST_HANG_RANK", "0")):
        faults.arm("worker.hang", after_n=int(hang_at))


def build(seed=29):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[6], dtype="float32")
        y = layers.data("y", shape=[1], dtype="float32")
        h = layers.fc(x, size=8, act="relu")
        pred = layers.fc(h, size=1)
        loss = layers.reduce_mean(layers.square(pred - y))
        optimizer.SGD(0.05).minimize(loss)
    return main, startup, loss


def feed_for(step):
    # batch 8: divides the 8-device virtual mesh in compiled mode
    rs = np.random.RandomState(1234 + step)
    return {"x": rs.rand(8, 6).astype(np.float32),
            "y": rs.rand(8, 1).astype(np.float32)}


def weight_digest(program, scope):
    h = hashlib.sha256()
    for v in sorted(program.list_vars(), key=lambda v: v.name):
        if not v.persistable:
            continue
        val = scope.find_var(v.name)
        if val is not None:
            h.update(v.name.encode())
            h.update(np.ascontiguousarray(np.asarray(val)).tobytes())
    return h.hexdigest()


def main():
    arm_faults()
    print("WORLD %d RANK %d ATTEMPT %d" % (WORLD, RANK, ATTEMPT),
          flush=True)
    main_p, startup, loss = build()
    exe = fluid.Executor()
    exe.run(startup)
    train_p = main_p
    if COMPILED and RANK == 0:
        train_p = fluid.CompiledProgram(main_p).with_data_parallel(
            loss_name=loss.name)
    hb = Heartbeat(interval=0.2).start()

    # rank 0 owns the shared checkpoint dir; the other ranks train
    # checkpoint-free (they still drain + leave markers on preemption)
    mgr = None
    resumed = None
    if RANK == 0:
        mgr = fluid.io.CheckpointManager(max_to_keep=2)
        reshards = monitor.counter("checkpoint_reshards_total")
        before = reshards.value
        resumed = mgr.restore_on_restart(
            exe, train_p, strategy=train_p if COMPILED else None)
        if COMPILED:
            print("RESHARD %d" % (reshards.value - before), flush=True)
    start = resumed if resumed is not None else 0
    print("RESUMED %s" % (resumed if resumed is not None else -1),
          flush=True)

    for step in range(start, TOTAL):
        if mgr is not None:
            exe.run(train_p, feed=feed_for(step), fetch_list=[loss],
                    checkpoint=(mgr, EVERY))
        else:
            exe.run(train_p, feed=feed_for(step), fetch_list=[loss])
        hb.beat(step + 1)
        faults.check("worker.exit")
        faults.check("worker.hang")
        faults.check("worker.preempt")
    if mgr is not None:
        mgr.wait()
    print("WEIGHTS %s" % weight_digest(main_p, fluid.global_scope()),
          flush=True)
    hb.stop()


if __name__ == "__main__":
    main()
