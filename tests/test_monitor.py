"""Framework-wide metrics registry — reference
``paddle/fluid/platform/monitor.h`` (StatRegistry / STAT macros), grown
into counter/gauge/histogram series with Prometheus + JSON exposition
(``fluid/monitor.py``) and the executor run-hook API.
"""

import json
import re
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, monitor


@pytest.fixture(autouse=True)
def _fresh_values():
    """Zero every process-wide series so each test asserts exact deltas."""
    monitor.reset()
    yield
    monitor.reset()


# -- metric semantics ---------------------------------------------------------

def test_counter_semantics():
    c = monitor.counter("t_requests_total", help="test counter")
    assert c.value == 0
    c.inc()
    c.inc(4)
    assert c.value == 5
    with pytest.raises(ValueError, match="only go up"):
        c.inc(-1)
    # get-or-create returns the SAME instance
    assert monitor.counter("t_requests_total") is c


def test_gauge_semantics():
    g = monitor.gauge("t_inflight")
    g.set(10)
    g.inc(2)
    g.dec(5)
    assert g.value == 7


def test_histogram_buckets_are_log_scale_and_cumulative():
    h = monitor.histogram("t_latency", buckets=(0.001, 0.01, 0.1, 1.0))
    for v in (0.0005, 0.005, 0.005, 0.5, 50.0):
        h.observe(v)
    assert h.count == 5
    assert abs(h.sum - 50.5105) < 1e-9
    cum = h.cumulative_buckets()
    assert [c for _, c in cum] == [1, 3, 3, 4, 5]
    assert cum[-1][0] == float("inf")
    # cumulative counts are monotone
    counts = [c for _, c in cum]
    assert counts == sorted(counts)
    d = h.to_dict()
    assert d["min"] == 0.0005 and d["max"] == 50.0


def test_histogram_default_buckets_log_scale():
    b = monitor.default_buckets()
    ratios = {round(b[i + 1] / b[i], 6) for i in range(len(b) - 1)}
    assert ratios == {4.0}  # fixed log-scale factor
    assert b[0] <= 1e-6 and b[-1] > 10  # spans us..tens of seconds


def test_histogram_observe_on_bound_is_inclusive():
    h = monitor.histogram("t_edge", buckets=(1.0, 10.0))
    h.observe(1.0)  # le="1.0" is inclusive (Prometheus semantics)
    assert h.cumulative_buckets()[0] == (1.0, 1)


def test_histogram_timer():
    h = monitor.histogram("t_timed")
    with h.time():
        time.sleep(0.01)
    assert h.count == 1 and h.sum >= 0.005


def test_labels_make_separate_series():
    a = monitor.counter("t_labeled", labels={"method": "get"})
    b = monitor.counter("t_labeled", labels={"method": "put"})
    assert a is not b
    a.inc(2)
    assert b.value == 0
    # label order is irrelevant to identity
    c = monitor.counter("t_two", labels={"x": 1, "y": 2})
    assert monitor.counter("t_two", labels={"y": 2, "x": 1}) is c


def test_kind_conflict_raises():
    monitor.counter("t_conflict")
    with pytest.raises(ValueError, match="already registered"):
        monitor.gauge("t_conflict")
    with pytest.raises(ValueError, match="already registered"):
        monitor.histogram("t_conflict", labels={"a": "b"})


def test_reset_zeroes_in_place():
    c = monitor.counter("t_reset_me")
    h = monitor.histogram("t_reset_hist")
    c.inc(3)
    h.observe(1.0)
    monitor.reset()
    assert c.value == 0 and h.count == 0 and h.sum == 0.0
    assert monitor.counter("t_reset_me") is c  # instance survives
    c.inc()
    assert c.value == 1


# -- exposition ---------------------------------------------------------------

def test_dump_json_shape():
    monitor.counter("t_json_c", labels={"k": "v"}).inc(2)
    monitor.histogram("t_json_h", buckets=(1.0,)).observe(0.5)
    d = monitor.dump_json()
    json.dumps(d)  # must be JSON-serializable as-is
    assert d["t_json_c"] == [{"kind": "counter", "value": 2,
                              "labels": {"k": "v"}}]
    (h,) = d["t_json_h"]
    assert h["kind"] == "histogram" and h["count"] == 1
    assert h["buckets"] == [[1.0, 1], [float("inf"), 1]]


def test_prometheus_golden():
    """Exact text for a known set of series (format 0.0.4)."""
    monitor.counter("zz_golden_total", help="served requests",
                    labels={"method": "get"}).inc(3)
    monitor.counter("zz_golden_total", labels={"method": "put"}).inc(1)
    monitor.gauge("zz_golden_inflight").set(2)
    h = monitor.histogram("zz_golden_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = monitor.dump_prometheus()
    block = "\n".join(l for l in text.splitlines() if "zz_golden" in l)
    assert block == "\n".join([
        '# TYPE zz_golden_inflight gauge',
        'zz_golden_inflight 2',
        '# TYPE zz_golden_seconds histogram',
        'zz_golden_seconds_bucket{le="0.1"} 1',
        'zz_golden_seconds_bucket{le="1.0"} 2',
        'zz_golden_seconds_bucket{le="+Inf"} 3',
        'zz_golden_seconds_sum 5.55',
        'zz_golden_seconds_count 3',
        '# HELP zz_golden_total served requests',
        '# TYPE zz_golden_total counter',
        'zz_golden_total{method="get"} 3',
        'zz_golden_total{method="put"} 1',
    ])


_PROM_LINE = re.compile(
    r'^(# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]*( .*)?'
    r'|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? (NaN|[+-]Inf|-?[0-9.eE+-]+))$')


def test_prometheus_full_output_parses():
    """EVERY line of the full process dump must be valid exposition
    text — this sweeps the names the framework modules registered at
    import (executor, reader, heartbeat, predictor...)."""
    monitor.histogram("t_parse_h", labels={"event": 'odd"name\nx'}) \
        .observe(0.1)
    text = monitor.dump_prometheus()
    assert text.endswith("\n")
    for line in text.splitlines():
        assert _PROM_LINE.match(line), "bad exposition line: %r" % line


def test_dump_prometheus_to_path_and_stream(tmp_path):
    import io

    monitor.counter("t_dst").inc()
    p = str(tmp_path / "m.prom")
    text = monitor.dump_prometheus(p)
    assert open(p).read() == text
    buf = io.StringIO()
    monitor.dump_prometheus(buf)
    assert buf.getvalue() == text


def test_env_dump_at_exit(tmp_path, monkeypatch):
    monitor.counter("t_atexit").inc(7)
    # JSON by extension
    jpath = str(tmp_path / "dump.json")
    monkeypatch.setenv(monitor.ENV_DUMP, jpath)
    monitor._atexit_dump()
    assert json.load(open(jpath))["t_atexit"][0]["value"] == 7
    # Prometheus otherwise
    ppath = str(tmp_path / "dump.prom")
    monkeypatch.setenv(monitor.ENV_DUMP, ppath)
    monitor._atexit_dump()
    assert "t_atexit 7" in open(ppath).read()
    monkeypatch.delenv(monitor.ENV_DUMP)
    monitor._atexit_dump()  # unset env: no-op


# -- executor wiring ----------------------------------------------------------

def _tiny_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("mx", shape=[4], dtype="float32")
        y = layers.mean(layers.fc(x, size=2))
    return main, startup, y


def test_executor_run_histogram_and_cache_counters():
    main, startup, y = _tiny_program()
    exe = fluid.Executor()
    feed = {"mx": np.ones((2, 4), np.float32)}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(3):
            exe.run(main, feed=feed, fetch_list=[y])
    d = monitor.dump_json()
    (h,) = d["executor_run_seconds"]
    assert h["count"] == 4 and h["sum"] > 0
    assert monitor.counter("executor_run_total").value == 4
    # startup + first main run compile; runs 2-3 hit the cache
    assert monitor.counter("executor_compile_cache_miss_total").value == 2
    assert monitor.counter("executor_compile_cache_hit_total").value == 2
    # prometheus exposition of the histogram is non-zero
    text = monitor.dump_prometheus()
    assert "executor_run_seconds_count 4" in text


def test_run_hooks_fire_exactly_once_per_run():
    main, startup, y = _tiny_program()
    exe = fluid.Executor()
    feed = {"mx": np.ones((2, 4), np.float32)}
    records = []
    fluid.register_run_hook(records.append)
    try:
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            exe.run(main, feed=feed, fetch_list=[y])
            exe.run(main, feed=feed, fetch_list=[y])
    finally:
        fluid.unregister_run_hook(records.append)
    assert len(records) == 3
    rec = records[-1]
    assert rec["program_id"] == main._uid
    assert rec["fetch_names"] == [y.name]
    assert rec["wall_time"] > 0
    assert rec["cache_hit"] is True and records[1]["cache_hit"] is False
    assert rec["profiler_enabled"] is False
    # unregistered: no further firing
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
    assert len(records) == 3
    fluid.unregister_run_hook(records.append)  # absent: no-op


def test_run_hook_errors_are_swallowed():
    main, startup, y = _tiny_program()
    exe = fluid.Executor()

    def bad_hook(record):
        raise RuntimeError("observability must not fail training")

    fluid.register_run_hook(bad_hook)
    try:
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)  # must not raise
    finally:
        fluid.unregister_run_hook(bad_hook)


# -- reader wiring ------------------------------------------------------------

def test_reader_batch_and_feed_latency_counters():
    from paddle_tpu.fluid.reader import DataLoader

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("rx", shape=[2], dtype="float32")
    loader = DataLoader.from_generator(feed_list=[x], capacity=2,
                                       stage_on_device=False)

    def gen():
        for i in range(5):
            yield [np.full((3, 2), i, np.float32)]

    loader.set_batch_generator(gen)
    n = sum(1 for _ in loader)
    assert n == 5
    assert monitor.counter("reader_batches_total").value == 5
    assert monitor.get_metric("reader_feed_seconds").count == 5


def test_reader_queue_full_stall_counter():
    from paddle_tpu.fluid.reader import DataLoader

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("sx", shape=[1], dtype="float32")
    loader = DataLoader.from_generator(feed_list=[x], capacity=1,
                                       stage_on_device=False)

    def gen():
        for i in range(6):
            yield [np.zeros((1, 1), np.float32)]

    loader.set_batch_generator(gen)
    for _ in loader:
        time.sleep(0.02)  # slow consumer: the producer must stall
    assert monitor.counter("reader_queue_full_total").value > 0


# -- heartbeat / watchdog wiring ---------------------------------------------

def test_heartbeat_and_watchdog_counters(tmp_path):
    from paddle_tpu.distributed.heartbeat import Heartbeat, Watchdog

    hb = Heartbeat(rank=0, dirname=str(tmp_path), interval=60)
    hb.beat(step=5)
    hb.beat(step=9)
    assert monitor.counter("heartbeat_beats_total").value == 2
    assert monitor.gauge("heartbeat_last_step").value == 9

    # rank 0 stamped just now; rank 1 never did and grace has passed
    wd = Watchdog(str(tmp_path), nproc=2, timeout=60.0, startup_grace=0.0)
    time.sleep(0.01)
    assert wd.stale_workers() == [1]
    assert monitor.counter("watchdog_stale_detections_total").value == 1
    # a detached (no-dir) heartbeat never stamps or counts
    Heartbeat(rank=7, dirname=None).beat(step=1)
    assert monitor.counter("heartbeat_beats_total").value == 2


# -- hostile label values (Prometheus escaping regression) --------------------

def test_prometheus_escapes_hostile_label_values():
    """Quotes, backslashes, and newlines in label VALUES must come out
    escaped per the text exposition format — an attacker-shaped model
    name must not be able to inject extra series lines."""
    hostile = 'a"b\\c\nd'
    monitor.counter("t_hostile_total", labels={"path": hostile},
                    help="hostile").inc(2)
    text = monitor.dump_prometheus()
    # exactly one physical line carries the series; the newline is \n
    assert 't_hostile_total{path="a\\"b\\\\c\\nd"} 2' in text.splitlines()
    # every non-comment line still parses as  name{labels} value
    for line in text.splitlines():
        if line.startswith("#") or not line:
            continue
        assert re.match(
            r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
            r'(\{([a-zA-Z_:][a-zA-Z0-9_:]*="(\\.|[^"\\])*",?)*\})? '
            r'\S+$', line), line


def test_prometheus_sanitizes_hostile_names():
    """Metric and label NAMES with invalid characters are rewritten to
    the legal charset (values get escaped; names get sanitized)."""
    monitor.counter("2bad-name.total",
                    labels={"bad key!": "v"}, help="h").inc()
    text = monitor.dump_prometheus()
    assert '_2bad_name_total{bad_key_="v"} 1' in text
    assert "2bad-name.total" not in text


def test_prometheus_hostile_help_and_histogram_suffixes():
    h = monitor.histogram("t_host_seconds", labels={"m": 'x"y'},
                          help="line1\nline2 \\ backslash",
                          buckets=(1.0,))
    h.observe(0.5)
    text = monitor.dump_prometheus()
    assert "# HELP t_host_seconds line1\\nline2 \\\\ backslash" \
        in text.splitlines()
    # the _sum/_count suffixes keep the escaped labels
    assert 't_host_seconds_sum{m="x\\"y"} 0.5' in text
    assert 't_host_seconds_count{m="x\\"y"} 1' in text
    assert 't_host_seconds_bucket{m="x\\"y",le="+Inf"} 1' in text


# -- Histogram.quantile edge cases (pinned values) ----------------------------

def test_quantile_empty_histogram_is_none():
    h = monitor.histogram("t_q_empty")
    for q in (0.0, 0.5, 1.0):
        assert h.quantile(q) is None


def test_quantile_single_observation_is_exact():
    """One observation: every quantile is that value — the min/max
    clamp must defeat the bucket's width."""
    h = monitor.histogram("t_q_one")
    h.observe(5.0)
    for q in (0.0, 0.25, 0.5, 0.99, 1.0):
        assert h.quantile(q) == 5.0


def test_quantile_q0_is_min_q1_is_max():
    h = monitor.histogram("t_q_ends")
    h.observe(0.2)
    h.observe(0.9)
    assert h.quantile(0.0) == 0.2
    # lo + (hi - lo) interpolation re-associates the float ops, so the
    # max clamp is hit only to within one ulp
    assert h.quantile(1.0) == pytest.approx(0.9, rel=1e-12)


def test_quantile_overflow_bucket_reports_max():
    """Observations past the last bound land in +Inf; the only bounded
    answer is the observed max — never inf, never None."""
    h = monitor.histogram("t_q_over", buckets=(1.0, 10.0))
    h.observe(1e6)
    h.observe(2e6)
    for q in (0.25, 0.5, 1.0):
        assert h.quantile(q) == 2e6
    assert h.quantile(0.0) == 1e6


def test_quantile_rejects_out_of_range_q():
    h = monitor.histogram("t_q_bad")
    h.observe(1.0)
    for q in (-0.1, 1.1, 2):
        with pytest.raises(ValueError, match="q must be in"):
            h.quantile(q)


def test_quantile_of_merged_equals_union():
    """Two processes' bucket vectors added element-wise give EXACTLY the
    union's quantiles (the telemetry aggregation contract, pinned here
    at the Histogram level)."""
    buckets = monitor.default_buckets()
    a = monitor.Histogram("a", buckets=buckets)
    b = monitor.Histogram("b", buckets=buckets)
    union = monitor.Histogram("u", buckets=buckets)
    rng = np.random.RandomState(3)
    for h, vals in ((a, rng.lognormal(-3, 1, 100)),
                    (b, rng.lognormal(-1, 2, 50))):
        for v in vals:
            h.observe(v)
            union.observe(v)
    merged = monitor.Histogram("m", buckets=buckets)
    for src in (a, b):
        for i, c in enumerate(src.bucket_counts()):
            merged._counts[i] += c
        merged._sum += src.sum
        merged._count += src.count
        merged._min = src._min if merged._min is None \
            else min(merged._min, src._min)
        merged._max = src._max if merged._max is None \
            else max(merged._max, src._max)
    for q in (0.0, 0.1, 0.5, 0.9, 0.99, 1.0):
        assert merged.quantile(q) == union.quantile(q)
