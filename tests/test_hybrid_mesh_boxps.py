"""make_hybrid_mesh (ICI x DCN layout, single-slice collapse) and
BoxPSDataset pass hooks."""

import numpy as np

from paddle_tpu import parallel


def test_hybrid_mesh_single_slice_collapse():
    # CPU-virtual devices report one slice -> collapse to a plain mesh of
    # the combined sizes, with DCN axes outermost
    mesh = parallel.make_hybrid_mesh(ici_axes={"tp": 2, "dp": 2},
                                     dcn_axes={"dp": 2})
    assert dict(mesh.shape) == {"dp": 4, "tp": 2}
    assert mesh.axis_names == ("dp", "tp")


def test_hybrid_mesh_runs_collective():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = parallel.make_hybrid_mesh(ici_axes={"dp": 4}, dcn_axes={"dp": 2})
    x = np.arange(8, dtype=np.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P("dp")))

    @jax.jit
    def total(v):
        return v.sum()

    assert float(total(xs)) == x.sum()


def test_boxps_dataset_pass_hooks(tmp_path):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.distributed import ps
    from paddle_tpu.fluid import layers

    table = ps.EmbeddingTable(vocab=16, dim=2, nshards=2, init_scale=0.0)
    pusher = ps.AsyncPusher(table)
    assert pusher in ps.registered_pushers()
    comm = ps.GeoCommunicator(table, k_steps=100)
    assert comm in ps.registered_communicators()

    fn = str(tmp_path / "p0")
    with open(fn, "w") as f:
        for i in range(6):
            f.write("1 %d 1 0.5\n" % (i % 4))
    ds = fluid.DatasetFactory().create_dataset("BoxPSDataset")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = layers.data("bp_ids", [1], dtype="int64")
        val = layers.data("bp_val", [1], dtype="float32")
    ds.set_use_var([ids, val])
    ds.set_batch_size(3)
    ds.set_filelist([fn])

    # a pending async push must be applied by begin_pass's flush
    pusher.push(np.array([1], np.int64), np.full((1, 2), 1.0, np.float32),
                lr=1.0)
    ds.begin_pass()
    np.testing.assert_allclose(table.pull(np.array([1], np.int64)),
                               [[-1.0, -1.0]])

    ds.load_into_memory()
    ds.local_shuffle()
    n = sum(1 for _ in ds.batch_reader()())
    assert n == 2

    # end_pass forces the geo communicator to sync its mirror
    comm.local[2] += 5.0
    ds.end_pass()
    np.testing.assert_allclose(table.pull(np.array([2], np.int64)),
                               [[5.0, 5.0]])
    pusher.stop()
