"""Fleet telemetry plane: distributed request tracing across the wire
(client -> router -> replica -> executor, one trace per submit),
cross-process metrics aggregation with exact merged quantiles, and the
crash flight recorder (SIGKILL/SIGUSR1/kill postmortems)."""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time
from threading import Thread

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import telemetry
from paddle_tpu.fluid import layers, monitor
from paddle_tpu.distributed import wire as dwire
from paddle_tpu.distributed.coordination import CoordClient, CoordServer
from paddle_tpu.serving import FleetClient, Replica, Router
from paddle_tpu.serving import protocol as fp
from paddle_tpu.telemetry import aggregate, flight, pusher

pytestmark = pytest.mark.telemetry


@pytest.fixture(autouse=True)
def _telemetry_clean(monkeypatch):
    """Every test starts with the plane off, an empty ring, and no
    leftover pusher/flight state — and leaves it that way."""
    monkeypatch.delenv("PADDLE_TELEMETRY_SERVICE", raising=False)
    monkeypatch.delenv("PADDLE_TELEMETRY_SAMPLE", raising=False)
    telemetry.disable()
    telemetry.clear()
    yield
    pusher.stop_pusher()
    flight.stop(final_dump=False)
    telemetry.disable()
    telemetry.clear()
    telemetry.set_max_spans(int(os.environ.get(
        telemetry.spans.ENV_MAX_SPANS, 65536) or 65536))


# -- trace context ----------------------------------------------------------


def test_header_roundtrip_and_malformed():
    ctx = telemetry.new_trace(baggage={"model": "fc"})
    d = telemetry.encode_header(ctx)
    back = telemetry.decode_header(json.loads(json.dumps(d)))
    assert back.trace_id == ctx.trace_id
    assert back.span_id == ctx.span_id
    assert back.baggage == {"model": "fc"}
    assert back.sampled is True
    # a foreign/garbled header must decode to None, never raise
    for junk in (None, "x", 7, [], {}, {"t": "a"}, {"s": "b"},
                 {"t": 1, "s": 2}, {"t": "", "s": ""}):
        assert telemetry.decode_header(junk) is None
    assert telemetry.encode_header(None) is None


def test_child_keeps_trace_and_sampling_verdict():
    root = telemetry.new_trace(sampled=False)
    child = telemetry.child_of(root)
    assert child.trace_id == root.trace_id
    assert child.parent_id == root.span_id
    assert child.span_id != root.span_id
    # the sampling verdict survives the wire: a child decoded on a far
    # host must never resurrect a dropped trace
    wired = telemetry.decode_header(telemetry.encode_header(child))
    assert wired.sampled is False
    telemetry.enable()
    with telemetry.span("dropped", parent=wired):
        pass
    assert telemetry.snapshot() == []
    n0 = len(telemetry.snapshot())
    assert telemetry.record_span("x", time.perf_counter(), 0.0,
                                 wired) is None
    assert len(telemetry.snapshot()) == n0


def test_span_ring_keeps_newest_and_counts_drops():
    telemetry.enable()
    telemetry.set_max_spans(4)
    for i in range(10):
        with telemetry.span("s%d" % i):
            pass
    recs = telemetry.snapshot()
    assert [r["name"] for r in recs] == ["s6", "s7", "s8", "s9"]
    assert telemetry.dropped_span_count() == 6


def test_ambient_nesting_and_chrome_lanes(tmp_path):
    telemetry.enable()
    with telemetry.span("outer", service="router") as outer:
        with telemetry.span("inner") as inner:
            assert inner.ctx.trace_id == outer.ctx.trace_id
            assert inner.ctx.parent_id == outer.ctx.span_id
    recs = telemetry.snapshot()
    by_name = {r["name"]: r for r in recs}
    # the nested span inherits the ambient service (chrome lane)
    assert by_name["inner"]["service"] == "router"
    assert by_name["inner"]["parent_id"] == by_name["outer"]["span_id"]
    # one pid lane per distinct (pid, service); an OPEN span (no dur —
    # the crash-in-flight shape) still exports, with zero width
    open_rec = dict(by_name["outer"], service="replica:r0", dur=None)
    meta, events = telemetry.merge_chrome_events([recs, [open_rec]])
    lanes = {m["args"]["name"] for m in meta if m["name"] == "process_name"}
    assert any(n.startswith("router") for n in lanes)
    assert any(n.startswith("replica:r0") for n in lanes)
    assert [e for e in events if e["dur"] == 0.0]
    path = telemetry.export_trace(str(tmp_path / "t.json"),
                                  trace_id=recs[0]["trace_id"])
    doc = json.load(open(path))
    assert any(e.get("cat") == "trace" for e in doc["traceEvents"])


# -- wire compatibility -----------------------------------------------------


def test_telemetry_off_frames_are_byte_identical():
    """The off-path acceptance: no trace key, ZERO new wire bytes — the
    frame matches a byte-for-byte reconstruction of the pre-telemetry
    encoding."""
    assert not telemetry.enabled()
    feed = {"x": np.arange(12, dtype=np.float32).reshape(2, 6)}
    frame = fp.pack_request(fp.OP_SUBMIT, "fc", feed, deadline_ms=250.0,
                            priority=1)
    assert frame == fp.pack_request(fp.OP_SUBMIT, "fc", feed,
                                    deadline_ms=250.0, priority=1,
                                    trace=None)
    import struct
    meta = json.dumps({"model": "fc", "deadline_ms": 250.0,
                       "priority": 1},
                      separators=(",", ":")).encode()
    legacy = (struct.pack("<BI", fp.OP_SUBMIT, len(meta)) + meta
              + fp.pack_arrays([feed["x"]], names=["x"]))
    assert frame == legacy
    assert b"trace" not in frame
    model, dl, prio, out, trace = fp.unpack_request(frame)
    assert (model, dl, prio, trace) == ("fc", 250.0, 1, None)
    np.testing.assert_array_equal(out["x"], feed["x"])


def test_traced_frame_roundtrip_adds_only_the_meta_key():
    ctx = telemetry.new_trace()
    feed = {"x": np.zeros((1, 6), np.float32)}
    frame = fp.pack_request(fp.OP_SUBMIT, "fc", feed,
                            trace=telemetry.encode_header(ctx))
    *_, trace = fp.unpack_request(frame)
    assert telemetry.decode_header(trace).trace_id == ctx.trace_id
    # old-format frame through the NEW decoder: trace is simply None
    *_, no_trace = fp.unpack_request(
        fp.pack_request(fp.OP_SUBMIT, "fc", feed))
    assert no_trace is None


# -- fleet fixtures (mirrors tests/test_fleet.py) ---------------------------


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("telemetry_model")
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 21
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[6], dtype="float32")
        h = layers.fc(x, size=16, act="relu")
        prob = layers.softmax(layers.fc(h, size=3))
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        fluid.io.save_inference_model(str(d), ["x"], [prob], exe,
                                      main_program=main)
    return str(d)


def _spec(model_dir, model="fc", delay_ms=2.0):
    return {"prefix": "fleet/",
            "models": [{"name": model, "model_dir": model_dir,
                        "warmup": {"x": {"shape": [1, 6],
                                         "dtype": "float32"}},
                        "config": {"max_batch_size": 8,
                                   "max_queue_delay_ms": delay_ms}}]}


class _Fleet:
    def __init__(self, model_dir, n, model="fc", rid_prefix="rep",
                 delay_ms=2.0):
        self.coord = CoordServer().start()
        self.addr = "%s:%d" % (self.coord.host, self.coord.port)
        spec = _spec(model_dir, model=model, delay_ms=delay_ms)
        self.replicas = [
            Replica(spec, coord_addr=self.addr,
                    replica_id="%s%d" % (rid_prefix, i),
                    lease_ttl=2.0, stats_interval=0.05).start()
            for i in range(n)]
        self.router = Router(coord_addr=self.addr,
                             refresh_interval=0.05).start()
        self.endpoint = "%s:%d" % (self.router.host, self.router.port)
        self.client = FleetClient(self.endpoint)

    def close(self):
        self.client.close()
        self.router.close()
        for r in self.replicas:
            r.drain(timeout=5)
        self.coord.stop()


# -- the e2e acceptance trace -----------------------------------------------


def test_one_submit_is_one_trace_across_the_fleet(model_dir, tmp_path):
    """FleetClient.submit through a live router + 2 replicas yields ONE
    trace: client.submit -> router.route -> router.dispatch ->
    replica.infer -> serving.queue_wait / serving.batch ->
    predictor.run -> executor.run, all under one trace_id, correctly
    parented, with the batch span LINKING >= 2 concurrent request
    spans, exported to chrome with client/router/replica lanes."""
    telemetry.enable()
    f = _Fleet(model_dir, 2, model="tr", rid_prefix="tr",
               delay_ms=40.0)
    try:
        telemetry.clear()  # drop warmup spans; keep only the submits
        n_clients = 6
        clients = [FleetClient(f.endpoint) for _ in range(n_clients)]
        outs, errs = [None] * n_clients, []

        def _one(i):
            try:
                x = np.full((1, 6), float(i), np.float32)
                outs[i] = clients[i].submit("tr", {"x": x},
                                            deadline_ms=10000)
            except Exception as e:  # surfaced below; thread must not die silently
                errs.append(e)
        threads = [Thread(target=_one, args=(i,))
                   for i in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        for c in clients:
            c.close()
        assert not errs, errs
        assert all(o is not None and o[0].shape == (1, 3) for o in outs)

        recs = telemetry.snapshot()
        submits = [r for r in recs if r["name"] == "client.submit"]
        assert len(submits) == n_clients
        # one trace per submit — ids never collide across requests
        assert len({r["trace_id"] for r in submits}) == n_clients

        # walk one full trace
        tid = submits[0]["trace_id"]
        tr = telemetry.trace_spans(tid)
        names = {r["name"] for r in tr}
        assert {"client.submit", "router.route", "router.dispatch",
                "replica.infer", "serving.queue_wait"} <= names, names
        by = {r["name"]: r for r in tr}
        assert by["router.route"]["parent_id"] == \
            by["client.submit"]["span_id"]
        assert by["router.dispatch"]["parent_id"] == \
            by["router.route"]["span_id"]
        assert by["replica.infer"]["parent_id"] == \
            by["router.dispatch"]["span_id"]
        assert by["serving.queue_wait"]["parent_id"] == \
            by["replica.infer"]["span_id"]
        # every span is closed (dur filled) and service-labelled
        assert by["client.submit"]["service"] == "client"
        assert by["router.route"]["service"] == "router"
        assert by["replica.infer"]["service"].startswith("replica:tr")
        assert all(r["dur"] is not None for r in tr)

        # batch fan-in: with 6 concurrent submits inside a 40 ms window
        # over 2 replicas, some batch carried >= 2 requests, and its
        # links point at real replica.infer request spans of DIFFERENT
        # traces
        batches = [r for r in recs if r["name"] == "serving.batch"]
        assert batches
        linked = max(batches, key=lambda r: len(r.get("links", [])))
        assert len(linked["links"]) >= 2
        infer_ids = {(r["trace_id"], r["span_id"])
                     for r in recs if r["name"] == "replica.infer"}
        for link in linked["links"]:
            assert (link["trace_id"], link["span_id"]) in infer_ids
        assert len({l["trace_id"] for l in linked["links"]}) >= 2
        # the executor ran INSIDE a batch span's trace
        exec_spans = [r for r in recs if r["name"] == "executor.run"]
        batch_tids = {r["trace_id"] for r in batches}
        assert exec_spans and \
            {r["trace_id"] for r in exec_spans} <= batch_tids
        assert {r["trace_id"] for r in recs
                if r["name"] == "predictor.run"} <= batch_tids

        # merged chrome export: one lane per service
        path = telemetry.export_trace(str(tmp_path / "fleet.json"))
        doc = json.load(open(path))
        lanes = {e["args"]["name"].split(" (")[0]
                 for e in doc["traceEvents"]
                 if e.get("name") == "process_name"}
        assert "client" in lanes and "router" in lanes
        assert any(n.startswith("replica:tr") for n in lanes)
    finally:
        f.close()


def test_disabled_fleet_serves_with_zero_spans(model_dir):
    """The whole fleet path with telemetry OFF: requests serve, nothing
    is recorded, nothing rides the wire."""
    assert not telemetry.enabled()
    f = _Fleet(model_dir, 1, model="off", rid_prefix="off")
    try:
        telemetry.clear()
        out = f.client.submit("off", {"x": np.zeros((1, 6), np.float32)},
                              deadline_ms=10000)
        assert out[0].shape == (1, 3)
        assert telemetry.snapshot() == []
    finally:
        f.close()


class _DirectReplicaConn(dwire.Conn):
    MAGIC = fp.MAGIC_REPLICA
    TOKEN_ENV = fp.ENV_TOKEN
    RETRIES = 0


def test_traced_frame_against_telemetry_off_replica(model_dir):
    """Forward-compat: a NEW (traced) frame served by a replica with
    telemetry off — the header is ignored, the request serves."""
    assert not telemetry.enabled()
    r = Replica(_spec(model_dir, model="bc"), replica_id="bc0").start()
    try:
        conn = _DirectReplicaConn(r.endpoint)
        try:
            ctx = telemetry.new_trace()
            req = fp.pack_request(
                fp.OP_INFER, "bc", {"x": np.zeros((1, 6), np.float32)},
                10000.0, 0, trace=telemetry.encode_header(ctx))
            out = fp.raise_for_status(conn.request(req))
            assert out[0].shape == (1, 3)
            assert telemetry.snapshot() == []
            # backward-compat: an OLD (traceless) frame against the same
            # replica with telemetry ON serves untraced
            telemetry.enable()
            old = fp.pack_request(
                fp.OP_INFER, "bc", {"x": np.zeros((1, 6), np.float32)},
                10000.0, 0)
            out = fp.raise_for_status(conn.request(old))
            assert out[0].shape == (1, 3)
            assert [s for s in telemetry.snapshot()
                    if s["name"] == "replica.infer"] == []
        finally:
            conn.close()
    finally:
        r.drain(timeout=5)


# -- coordination RPC tracing -----------------------------------------------


def test_coord_rpc_spans_join_the_callers_trace():
    telemetry.enable()
    srv = CoordServer().start()
    cli = CoordClient("%s:%d" % (srv.host, srv.port))
    try:
        with telemetry.span("op", service="ctl") as sp:
            cli.put("k", b"v")
            assert cli.get("k") == b"v"
            tid = sp.ctx.trace_id
        rpc = [r for r in telemetry.trace_spans(tid)
               if r["name"] == "coord.rpc"]
        assert len(rpc) == 2
        assert {r["service"] for r in rpc} == {"coord"}
        assert all(r["parent_id"] == sp.ctx.span_id for r in rpc)
    finally:
        cli.close()
        srv.stop()


def test_coord_client_downgrades_against_old_server():
    """A pre-telemetry server answers 'unknown opcode' to the _TRACED
    envelope: the client resends unwrapped, remembers the downgrade,
    and every later RPC works untraced."""
    from paddle_tpu.distributed import coordination as dcoord

    class _OldServer(CoordServer):
        def _handle(self, req):
            if req and req[0] == dcoord._TRACED:  # trace: simulating a peer too old to know the envelope
                return b"\x01decode error: unknown opcode 13"
            return CoordServer._handle(self, req)

    telemetry.enable()
    srv = _OldServer().start()
    cli = CoordClient("%s:%d" % (srv.host, srv.port))
    try:
        with telemetry.span("op"):
            cli.put("k", b"v")      # first RPC triggers the downgrade
            assert cli.get("k") == b"v"
        assert cli._trace_ok is False
        assert [r for r in telemetry.snapshot()
                if r["name"] == "coord.rpc"] == []
    finally:
        cli.close()
        srv.stop()


def test_coord_client_reprobes_trace_after_server_restart():
    """The _TRACED downgrade must not outlive the server that caused
    it: when the client reconnects (old server replaced by a modern
    build on the same endpoint), it re-probes the envelope and traces
    flow again."""
    from paddle_tpu.distributed import coordination as dcoord

    class _OldServer(CoordServer):
        def _handle(self, req):
            if req and req[0] == dcoord._TRACED:  # trace: simulating a peer too old to know the envelope
                return b"\x01decode error: unknown opcode 13"
            return CoordServer._handle(self, req)

    telemetry.enable()
    srv = _OldServer().start()
    port = srv.port
    cli = CoordClient("%s:%d" % (srv.host, srv.port), grace=30.0)
    try:
        with telemetry.span("op"):
            cli.put("k", b"v")
        assert cli._trace_ok is False      # downgraded, stays down...
        srv.crash()
        deadline = time.time() + 10
        while True:                        # modern build, same endpoint
            try:
                srv = CoordServer(port=port).start()
                break
            except OSError:
                if time.time() > deadline:
                    raise
                time.sleep(0.1)
        with telemetry.span("op2") as sp:
            cli.put("k", b"v2")            # rides the reconnect (sent
            #                                unwrapped); probe re-arms
            cli.put("k", b"v3")            # ...and this one re-probes
            tid = sp.ctx.trace_id
        assert cli._trace_ok is not False  # downgrade forgotten
        assert [r for r in telemetry.trace_spans(tid)
                if r["name"] == "coord.rpc"]
    finally:
        cli.close()
        srv.stop()


# -- metrics aggregation ----------------------------------------------------


def _hist_snapshot_entry(name, values, buckets):
    h = monitor.Histogram(name, buckets=buckets)
    for v in values:
        h.observe(v)
    return {"name": name, "kind": "histogram", "labels": {}, "help": "",
            "bounds": list(h.buckets), "counts": h.bucket_counts(),
            "sum": h.sum, "count": h.count, "min": h._min, "max": h._max}


def test_merged_quantiles_equal_union_quantiles():
    """The exactness acceptance: two processes' histogram snapshots
    merge to EXACTLY what one process observing the union would
    report — every quantile, min/max clamps included."""
    buckets = monitor.default_buckets()
    rng = np.random.RandomState(11)
    a = list(rng.lognormal(-3, 2, 400))
    b = list(rng.lognormal(-1, 1, 300))
    snaps = [
        {"proc": "a", "ts": 1.0, "metrics": [
            _hist_snapshot_entry("lat_seconds", a, buckets),
            {"name": "req_total", "kind": "counter", "labels": {},
             "help": "", "value": 7},
            {"name": "depth", "kind": "gauge", "labels": {},
             "help": "", "value": 3}]},
        {"proc": "b", "ts": 2.0, "metrics": [
            _hist_snapshot_entry("lat_seconds", b, buckets),
            {"name": "req_total", "kind": "counter", "labels": {},
             "help": "", "value": 5},
            {"name": "depth", "kind": "gauge", "labels": {},
             "help": "", "value": 9}]},
    ]
    union = monitor.Histogram("union", buckets=buckets)
    for v in a + b:
        union.observe(v)
    for q in (0.0, 0.1, 0.5, 0.9, 0.99, 1.0):
        got = aggregate.merged_quantile(snaps, "lat_seconds", q)
        want = union.quantile(q)
        assert got == pytest.approx(want, rel=1e-12), (q, got, want)
    metrics, kinds = aggregate.merge(snaps)
    by = {m.name: m for m in metrics}
    assert by["req_total"].value == 12          # counters SUM
    assert by["depth"].value == 9               # gauges last-write-wins
    assert by["lat_seconds"].count == 700
    assert kinds["lat_seconds"][0] == "histogram"
    text = aggregate.merged_prometheus(snaps)
    assert "req_total 12" in text
    assert "lat_seconds_count 700" in text


def test_merge_rejects_bucket_bound_skew():
    snaps = [
        {"proc": "a", "ts": 1.0, "metrics": [
            _hist_snapshot_entry("h", [0.1], (0.1, 1.0))]},
        {"proc": "b", "ts": 2.0, "metrics": [
            _hist_snapshot_entry("h", [0.1], (0.5, 1.0))]},
    ]
    with pytest.raises(ValueError, match="bucket bounds differ"):
        aggregate.merge(snaps)


def test_pusher_publishes_leased_snapshots_to_the_kv():
    """push -> collect round trip through a real coordination server:
    two publishers, both collected, counters merge as a sum; a lapsed
    lease ages the publisher out of the view."""
    srv = CoordServer().start()
    addr = "%s:%d" % (srv.host, srv.port)
    cli = CoordClient(addr)
    c = monitor.counter("tele_test_total", help="x")
    c.inc(4)
    try:
        pusher.push_once(cli, "p1", ttl=30.0)
        pusher.push_once(cli, "p2", ttl=0.4)
        snaps = pusher.collect_metrics(addr)
        assert {s["proc"] for s in snaps} == {"p1", "p2"}
        metrics, _ = aggregate.merge(snaps)
        by = {(m.name, tuple(m.labels.items())): m for m in metrics}
        assert by[("tele_test_total", ())].value == 8  # 4 from each
        spans_lists = pusher.collect_spans(addr)
        assert len(spans_lists) == 2
        time.sleep(0.6)  # p2's lease lapses: dead publisher ages out
        snaps = pusher.collect_metrics(addr)
        assert {s["proc"] for s in snaps} == {"p1"}
    finally:
        cli.close()
        srv.stop()


def test_pusher_oversized_snapshot_counted_and_dropped():
    """A snapshot bigger than the frame cap is refused CLIENT-side
    (FrameTooLarge before a byte hits the socket): the one-shot caller
    sees the raise, the pusher loop counts+drops it without touching
    the error counter, and the connection is NOT wedged — the same
    client keeps serving normal-sized requests."""
    srv = CoordServer().start()
    addr = "%s:%d" % (srv.host, srv.port)
    # tiny cap: the global monitor registry's JSON blob cannot fit
    cli = CoordClient(addr, grace=5.0, max_frame=512)
    over0 = monitor.counter("telemetry_push_oversize_total").value
    errs0 = monitor.counter("telemetry_push_errors_total").value
    try:
        with pytest.raises(dwire.FrameTooLarge):
            pusher.push_once(cli, "pbig", ttl=30.0)
        # the loop path: counted as oversize, NOT as a transport error
        pusher.start_pusher(cli, "pbig", interval=60.0)
        assert monitor.counter(
            "telemetry_push_oversize_total").value >= over0 + 1
        assert monitor.counter(
            "telemetry_push_errors_total").value == errs0
        cli.put("k", b"small")          # connection still usable
        assert cli.get("k") == b"small"
    finally:
        pusher.stop_pusher()
        cli.close()
        srv.stop()


# -- flight recorder --------------------------------------------------------


def test_flight_dump_and_collect(tmp_path):
    telemetry.enable()
    d = str(tmp_path / "fl")
    assert flight.start(dirname=d, rank="7", interval=30.0) == d
    with telemetry.span("request", service="replica:7"):
        monitor.counter("flight_t_total", help="x").inc(3)
        path = flight.dump(reason="test")  # mid-span: the span is OPEN
    assert path and os.path.exists(path)
    images = flight.collect(d)
    assert set(images) == {"7"}
    img = images["7"]
    assert img["schema"] == 1 and img["reason"] == "test"
    assert img["rank"] == "7" and img["pid"] == os.getpid()
    last = img["spans"][-1]
    assert last["name"] == "request" and last["dur"] is None
    assert img["monitor_delta"].get("flight_t_total") == 3
    # deltas are per-flush: an immediate second dump shows no new work
    flight.dump(reason="again")
    assert "flight_t_total" not in flight.collect(d)["7"]["monitor_delta"]
    # corrupt sibling files are skipped, not fatal
    (tmp_path / "fl" / "flight.bad.json").write_text("{truncated")
    assert set(flight.collect(d)) == {"7"}
    flight.stop(final_dump=False)
    assert not flight.is_active()


def test_flight_records_wire_ops(tmp_path):
    d = str(tmp_path / "fw")
    flight.start(dirname=d, rank="w", interval=30.0)
    srv = CoordServer().start()
    cli = CoordClient("%s:%d" % (srv.host, srv.port))
    try:
        cli.put("k", b"v")
        assert cli.get("k") == b"v"
    finally:
        cli.close()
        srv.stop()
    flight.dump(reason="wire")
    ops = flight.collect(d)["w"]["wire_ops"]
    assert ops, "framed coordination traffic must land in the ring"
    assert {o["dir"] for o in ops} <= {"send", "recv"}
    assert all(o["bytes"] > 0 for o in ops)
    flight.stop(final_dump=False)


_WORKER = textwrap.dedent("""
    import os, sys, time
    from paddle_tpu import telemetry
    from paddle_tpu.distributed import preemption

    telemetry.enable("worker")
    telemetry.flight.start(dirname=sys.argv[1], rank=sys.argv[2],
                           interval=float(sys.argv[3]))
    preemption.install()
    scope = telemetry.span("inflight.request",
                           attrs={"step": 42})
    scope.__enter__()           # stays OPEN: the in-flight work at death
    print("READY", flush=True)
    time.sleep(30)
""")


def _spawn_worker(tmp_path, rank, interval=0.05):
    proc = subprocess.Popen(
        [sys.executable, "-c", _WORKER, str(tmp_path), rank,
         str(interval)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.stdout.readline().strip() == b"READY"
    return proc


def test_flight_survives_sigkill_with_open_span(tmp_path):
    """The supervisor-kill acceptance shape: SIGKILL (uncatchable, like
    FleetSupervisor.kill) still leaves a flight image — the periodic
    flusher's last write — whose newest span is the OPEN in-flight
    request."""
    proc = _spawn_worker(tmp_path, "k0")
    try:
        time.sleep(0.5)          # a few flush intervals
        proc.kill()              # SIGKILL: no handler can run
        proc.wait(timeout=10)
        images = flight.collect(str(tmp_path))
        assert "k0" in images
        img = images["k0"]
        assert img["reason"] == "periodic"
        last = img["spans"][-1]
        assert last["name"] == "inflight.request"
        assert last["dur"] is None and last["attrs"]["step"] == 42
    finally:
        if proc.poll() is None:
            proc.kill()


def test_flight_dumps_on_watchdog_stack_signal(tmp_path):
    """The watchdog-hang acceptance shape: SIGUSR1 (what the hung-step
    watchdog sends) triggers an IMMEDIATE dump through the preemption
    chain, tagged stack_signal, in-flight span included."""
    # long flush interval: the triggered dump must not be overwritten
    # by a periodic flush before the test reads it
    proc = _spawn_worker(tmp_path, "h0", interval=30.0)
    try:
        deadline = time.time() + 10
        os.kill(proc.pid, signal.SIGUSR1)
        while time.time() < deadline:
            img = flight.collect(str(tmp_path)).get("h0")
            if img and img["reason"] == "stack_signal":
                break
            time.sleep(0.05)
        assert img and img["reason"] == "stack_signal", img
        assert img["spans"][-1]["name"] == "inflight.request"
    finally:
        proc.kill()
        proc.wait(timeout=10)


def test_supervisor_exports_flight_dir_and_collects(tmp_path):
    """FleetSupervisor plumbs PADDLE_FLIGHT_DIR to every child and
    collects survivors' rings for the postmortem."""
    from paddle_tpu.serving.supervisor import FleetSupervisor

    sup = FleetSupervisor({}, 1, "127.0.0.1:1", log_dir=str(tmp_path))
    sup._spec_path = "unused"
    env = sup._child_env("rep0")
    assert env["PADDLE_FLIGHT_DIR"] == sup.flight_dir
    assert os.path.isdir(sup.flight_dir)
    image = {"schema": 1, "rank": "rep0", "reason": "kill",
             "spans": [{"name": "replica.infer", "dur": None}]}
    with open(os.path.join(sup.flight_dir, "flight.rep0.json"), "w") as f:
        json.dump(image, f)
    assert sup.collect_flight()["rep0"]["reason"] == "kill"
    assert sup.collect_flight("rep0")["spans"][-1]["dur"] is None
    assert sup.collect_flight("missing") is None


def test_launcher_postmortem_summarizes_survivor_rings(tmp_path, capsys):
    from paddle_tpu.distributed import launch as dlaunch

    for rank in ("0", "1"):
        with open(str(tmp_path / ("flight.%s.json" % rank)), "w") as f:
            json.dump({"pid": 100 + int(rank), "reason": "periodic",
                       "spans": [{"name": "executor.run", "dur": None}],
                       "wire_ops": [{"ts": 0, "dir": "send", "op": 1,
                                     "bytes": 9}]}, f)
    dlaunch._flight_postmortem(str(tmp_path))
    err = capsys.readouterr().err
    assert "flight-recorder postmortem" in err
    assert "rank 0" in err and "rank 1" in err
    assert "last_span=executor.run" in err
    # an empty dir prints nothing (no noise on traceless gangs)
    dlaunch._flight_postmortem(str(tmp_path / "nope"))
    assert capsys.readouterr().err == ""


def test_replica_kill_dumps_flight_ring(model_dir, tmp_path):
    """The in-process Replica.kill() path (the crash-shape used by the
    no-loss fleet test) writes a final flight image tagged 'kill'."""
    telemetry.enable()
    d = str(tmp_path / "rk")
    flight.start(dirname=d, rank="kr0", interval=30.0)
    r = Replica(_spec(model_dir, model="kr"), replica_id="kr0").start()
    r.kill()
    images = flight.collect(d)
    assert "kr0" in images and images["kr0"]["reason"] == "kill"


@pytest.mark.slow
def test_supervisor_kill_leaves_flight_postmortem(model_dir, tmp_path):
    """Full acceptance: a SIGKILLed replica SUBPROCESS leaves
    flight.<rid>.json in the supervisor's flight dir; collect_flight
    reads it back after the fact."""
    from paddle_tpu.serving.supervisor import FleetSupervisor

    coord = CoordServer().start()
    addr = "%s:%d" % (coord.host, coord.port)
    sup = FleetSupervisor(
        _spec(model_dir), 1, addr,
        env={"PADDLE_TELEMETRY": "1", "PADDLE_FLIGHT_FLUSH_MS": "100",
             "PADDLE_FLEET_LEASE_TTL": "2.0"},
        log_dir=str(tmp_path))
    dbg = CoordClient(addr)
    try:
        sup.start()
        deadline = time.time() + 180
        while ("fleet/replicas/rep0" not in
               dbg.live_members("fleet/replicas/")
               and time.time() < deadline):
            time.sleep(0.2)
        time.sleep(0.5)           # let a couple of flushes land
        sup.kill("rep0")
        deadline = time.time() + 30
        while time.time() < deadline:
            img = sup.collect_flight("rep0")
            if img is not None:
                break
            time.sleep(0.2)
        assert img is not None, "no flight image after SIGKILL"
        assert img["rank"] == "rep0"
        assert img["service"].startswith("replica")
    finally:
        dbg.close()
        sup.stop(timeout=30)
        coord.stop()
