"""Quantization (slim) — reference ``contrib/slim/quantization`` per
SURVEY §2 contrib row: QAT transform/freeze/int8 passes + post-training
quantization."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.fluid.contrib.slim.quantization import (
    AddQuantDequantPass, ConvertToInt8Pass, PostTrainingQuantization,
    QuantizationFreezePass, QuantizationTransformPass, ScaleForInferencePass,
    ScaleForTrainingPass)

RNG = np.random.RandomState(7)
X = RNG.randn(16, 8).astype(np.float32)
W_TRUE = RNG.randn(8, 1).astype(np.float32)
Y = X @ W_TRUE + 0.1


def _fc_net():
    """fc (mul+add) regression net; returns (main, startup, loss, pred)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [8])
        y = layers.data("y", [1])
        h = layers.fc(x, 8, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.reduce_mean(layers.square(pred - y))
    return main, startup, loss, pred


def test_fake_quant_dequant_abs_max_numerics():
    """Round-trip error bounded by scale/127; scale recorded."""
    x = RNG.randn(4, 5).astype(np.float32) * 3
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = layers.data("x", x.shape, append_batch_size=False)
        helper = fluid.layer_helper.LayerHelper("q")
        out = helper.create_variable_for_type_inference("float32")
        scale = helper.create_variable_for_type_inference("float32")
        helper.append_op(type="fake_quantize_dequantize_abs_max",
                         inputs={"X": [xv]},
                         outputs={"Out": [out], "OutScale": [scale]},
                         attrs={"bit_length": 8})
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        o, s = [np.asarray(r) for r in
                exe.run(main, feed={"x": x}, fetch_list=[out, scale])]
    expected_scale = np.abs(x).max()
    np.testing.assert_allclose(s[0], expected_scale, rtol=1e-5)
    assert np.abs(o - x).max() <= expected_scale / 127.0 + 1e-6
    # outputs land exactly on the quant grid
    grid = np.round(o / expected_scale * 127)
    np.testing.assert_allclose(o, grid * expected_scale / 127, rtol=1e-5,
                               atol=1e-6)


def test_channel_wise_quant_per_channel_scales():
    x = np.stack([np.full((3,), 1.0, np.float32),
                  np.full((3,), 100.0, np.float32)])
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = layers.data("x", x.shape, append_batch_size=False)
        helper = fluid.layer_helper.LayerHelper("q")
        out = helper.create_variable_for_type_inference("float32")
        scale = helper.create_variable_for_type_inference("float32")
        helper.append_op(
            type="fake_channel_wise_quantize_dequantize_abs_max",
            inputs={"X": [xv]},
            outputs={"Out": [out], "OutScale": [scale]},
            attrs={"bit_length": 8})
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        o, s = [np.asarray(r) for r in
                exe.run(main, feed={"x": x}, fetch_list=[out, scale])]
    np.testing.assert_allclose(s, [1.0, 100.0], rtol=1e-5)
    # channel 0 is NOT crushed by channel 1's range (per-tensor would be)
    assert np.abs(o[0] - x[0]).max() < 1.0 / 127 + 1e-6


def test_qat_transform_trains_and_quantizes():
    """TransformPass before minimize: fake ops inserted, loss decreases
    (straight-through gradients flow), scale vars update."""
    main, startup, loss, _ = _fc_net()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        pass_ = QuantizationTransformPass(
            scope=scope,
            activation_quantize_type="moving_average_abs_max",
            weight_quantize_type="channel_wise_abs_max",
            quantizable_op_type=("mul",))
        pass_.apply(main)
        with fluid.program_guard(main, startup):
            fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
        types = [op.type for op in main.global_block().ops]
        assert "fake_quantize_dequantize_moving_average_abs_max" in types
        assert "fake_channel_wise_quantize_dequantize_abs_max" in types
        exe = fluid.Executor()
        exe.run(startup)
        losses = []
        for _ in range(15):
            l, = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
            losses.append(float(np.asarray(l)))
        assert losses[-1] < losses[0] * 0.7, losses
        # the activation scale observer moved off its 0.001 seed
        sv = np.asarray(scope.find_var("x.quant_scale"))
        assert sv[0] > 0.5  # ~abs max of X


def test_qat_freeze_roundtrip_and_int8():
    """Freeze after QAT: weights become integer-valued, inference output
    stays close to the QAT output; ConvertToInt8Pass stores int8."""
    main, startup, loss, pred = _fc_net()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        QuantizationTransformPass(
            scope=scope, activation_quantize_type="moving_average_abs_max",
            weight_quantize_type="abs_max",
            quantizable_op_type=("mul",)).apply(main)
        with fluid.program_guard(main, startup):
            fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        for _ in range(10):
            exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
        infer = main._prune([pred])
        (qat_out,) = exe.run(infer, feed={"x": X}, fetch_list=[pred])
        qat_out = np.asarray(qat_out)

        # numpy reference of the frozen semantics: quant-dequant weights,
        # exact activations (freeze drops input quantization)
        def qd(w):
            s = np.abs(w).max()
            return np.round(w / s * 127) * s / 127

        params = {n: np.asarray(scope.find_var(n))
                  for n in main.global_block().vars
                  if getattr(main.global_block().vars[n], "persistable",
                             False) and scope.find_var(n) is not None}
        wnames = sorted(n for n in params if n.endswith(".w_0"))
        bnames = sorted(n for n in params if n.endswith(".b_0"))
        h = np.maximum(X @ qd(params[wnames[0]]) + params[bnames[0]], 0)
        ref = h @ qd(params[wnames[1]]) + params[bnames[1]]

        freeze = QuantizationFreezePass(scope=scope,
                                        weight_quantize_type="abs_max",
                                        quantizable_op_type=("mul",))
        freeze.apply(infer)
        types = [op.type for op in infer.global_block().ops]
        assert not any(t.startswith("fake_quantize") for t in types)
        assert "fake_channel_wise_dequantize_max_abs" in types
        # weights in scope are now integers on the int8 grid
        wname = next(n for n in freeze._weight_scales)
        w = np.asarray(scope.find_var(wname))
        np.testing.assert_allclose(w, np.round(w), atol=1e-5)
        assert np.abs(w).max() <= 127
        (frozen_out,) = exe.run(infer, feed={"x": X}, fetch_list=[pred])
        frozen_out = np.asarray(frozen_out)
        # exact vs the numpy frozen model ...
        np.testing.assert_allclose(frozen_out, ref, rtol=1e-3, atol=1e-4)
        # ... and in the neighborhood of the QAT output (which carries
        # activation-quant noise the frozen graph no longer has).
        # Quantization closeness is distributional: a single int8 grid
        # flip on a near-zero activation legitimately produces one
        # outlier row, so bound the relative RMS over the batch rather
        # than the worst single element.
        rel_rms = (np.linalg.norm(frozen_out - qat_out)
                   / max(np.linalg.norm(qat_out), 1e-6))
        assert rel_rms < 0.25, rel_rms

        ConvertToInt8Pass(scope=scope,
                          quantizable_op_type=("mul",)).apply(infer)
        assert np.asarray(scope.find_var(wname)).dtype == np.int8
        (int8_out,) = exe.run(infer, feed={"x": X}, fetch_list=[pred])
        np.testing.assert_allclose(np.asarray(int8_out), frozen_out,
                                   rtol=1e-4, atol=1e-5)


def test_add_quant_dequant_pass():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [4])
        y = layers.data("y", [4])
        out = layers.elementwise_add(x, y)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        AddQuantDequantPass(
            scope=scope,
            quantizable_op_type=("elementwise_add",)).apply(main)
        types = [op.type for op in main.global_block().ops]
        assert types.count(
            "fake_quantize_dequantize_moving_average_abs_max") == 2
        exe = fluid.Executor()
        exe.run(startup)
        a = RNG.randn(3, 4).astype(np.float32)
        # EMA scale needs a few steps to converge from its 0.001 seed
        for _ in range(40):
            (r,) = exe.run(main, feed={"x": a, "y": a}, fetch_list=[out])
    np.testing.assert_allclose(np.asarray(r), a + a, rtol=0.05, atol=0.05)


def test_scale_passes_record_out_threshold():
    main, startup, loss, pred = _fc_net()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        ScaleForTrainingPass(scope=scope).apply(main)
        with fluid.program_guard(main, startup):
            fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        for _ in range(3):
            exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
        ScaleForInferencePass(scope=scope).apply(main)
        muls = [op for op in main.global_block().ops if op.type == "mul"]
        assert muls and all(op.attr("out_threshold", 0.0) > 0 for op in muls)


@pytest.mark.parametrize("algo", ["abs_max", "avg", "min_max", "KL"])
def test_post_training_quantization(algo):
    """PTQ calibrates scales and produces a quantized program whose
    output tracks the float program."""
    main, startup, loss, pred = _fc_net()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        # train the float model a little so weights are meaningful
        with fluid.program_guard(main, startup):
            fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        for _ in range(10):
            exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
        infer = main._prune([pred])
        (float_out,) = exe.run(infer, feed={"x": X}, fetch_list=[pred])
        float_out = np.asarray(float_out)

        def samples():
            for i in range(len(X)):
                yield (X[i],)

        ptq = PostTrainingQuantization(
            executor=exe, sample_generator=samples, program=infer,
            feed_list=["x"], fetch_list=[pred], batch_size=8,
            batch_nums=2, scope=scope, algo=algo,
            quantizable_op_type=("mul",))
        qprog = ptq.quantize()
        types = [op.type for op in qprog.global_block().ops]
        assert "fake_channel_wise_dequantize_max_abs" in types
        (q_out,) = exe.run(qprog, feed={"x": X}, fetch_list=[pred])
        q_out = np.asarray(q_out)
        denom = max(np.abs(float_out).max(), 1e-6)
        assert np.abs(q_out - float_out).max() / denom < 0.15, algo


def test_ptq_save_quantized_model(tmp_path):
    main, startup, loss, pred = _fc_net()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        infer = main._prune([pred])

        def samples():
            for i in range(len(X)):
                yield (X[i],)

        ptq = PostTrainingQuantization(
            executor=exe, sample_generator=samples, program=infer,
            feed_list=["x"], fetch_list=[pred], batch_size=8, batch_nums=1,
            scope=scope, algo="abs_max", quantizable_op_type=("mul",))
        ptq.quantize()
        path = str(tmp_path / "quant_model")
        ptq.save_quantized_model(path)
        prog2, feeds, fetches = fluid.io.load_inference_model(path, exe)
        (out2,) = exe.run(prog2, feed={"x": X}, fetch_list=fetches)
        assert np.asarray(out2).shape == (16, 1)


def test_qat_conv2d_channel_wise_freeze():
    """conv2d QAT with per-output-channel weight quant, then freeze:
    channels with very different ranges keep independent precision."""
    img = RNG.randn(4, 3, 8, 8).astype(np.float32)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("img", img.shape[1:])
        y = layers.conv2d(x, num_filters=4, filter_size=3, padding=1)
        out = layers.reduce_mean(y, dim=[1, 2, 3])
        loss = layers.reduce_mean(layers.square(out))
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        QuantizationTransformPass(
            scope=scope, activation_quantize_type="abs_max",
            weight_quantize_type="channel_wise_abs_max",
            quantizable_op_type=("conv2d",)).apply(main)
        with fluid.program_guard(main, startup):
            fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        for _ in range(3):
            exe.run(main, feed={"img": img}, fetch_list=[loss])
        infer = main._prune([y])
        (qat_out,) = exe.run(infer, feed={"img": img}, fetch_list=[y])
        freeze = QuantizationFreezePass(
            scope=scope, weight_quantize_type="channel_wise_abs_max",
            quantizable_op_type=("conv2d",))
        freeze.apply(infer)
        wname = next(n for n in freeze._weight_scales)
        assert freeze._weight_scales[wname].shape == (4,)  # per out-channel
        (frozen_out,) = exe.run(infer, feed={"img": img}, fetch_list=[y])
    qat_out, frozen_out = np.asarray(qat_out), np.asarray(frozen_out)
    denom = max(np.abs(qat_out).max(), 1e-6)
    assert np.abs(frozen_out - qat_out).max() / denom < 0.1


def test_freeze_dequantizes_direct_fetch_target():
    """A bias-free fc output IS the quantized op's output; fetching it
    must return real-scale values, not the integer-scaled product."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [8])
        pred = layers.fc(x, 2, bias_attr=False)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        (float_out,) = exe.run(main, feed={"x": X}, fetch_list=[pred])
        float_out = np.asarray(float_out)
        QuantizationTransformPass(
            scope=scope, activation_quantize_type="abs_max",
            weight_quantize_type="abs_max",
            quantizable_op_type=("mul",), is_test=True).apply(main)
        QuantizationFreezePass(
            scope=scope, weight_quantize_type="abs_max",
            quantizable_op_type=("mul",)).apply(main)
        (frozen_out,) = exe.run(main, feed={"x": X}, fetch_list=[pred])
    frozen_out = np.asarray(frozen_out)
    denom = max(np.abs(float_out).max(), 1e-6)
    assert np.abs(frozen_out - float_out).max() / denom < 0.05


def test_convert_to_int8_refuses_unfrozen_floats():
    """Float (unfrozen) weights must not be truncated to int8 zeros."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [8])
        pred = layers.fc(x, 2)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        (before,) = exe.run(main, feed={"x": X}, fetch_list=[pred])
        ConvertToInt8Pass(scope=scope,
                          quantizable_op_type=("mul",)).apply(main)
        (after,) = exe.run(main, feed={"x": X}, fetch_list=[pred])
    np.testing.assert_allclose(np.asarray(after), np.asarray(before),
                               rtol=1e-5, atol=1e-6)


def test_ptq_partial_final_batch_counts():
    """batch_nums with fewer samples than batch_size still calibrates."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [8])
        pred = layers.fc(x, 2)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)

        def few_samples():
            for i in range(4):  # < batch_size
                yield (X[i],)

        ptq = PostTrainingQuantization(
            executor=exe, sample_generator=few_samples, program=main,
            feed_list=["x"], fetch_list=[pred], batch_size=10,
            batch_nums=1, scope=scope, algo="avg",
            quantizable_op_type=("mul",))
        ptq.quantize()
        (out,) = exe.run(main, feed={"x": X}, fetch_list=[pred])
    assert np.isfinite(np.asarray(out)).all()


def test_int8_model_served_by_predictor(tmp_path):
    """The full serve proof (VERDICT r3 #9): QAT train -> freeze ->
    ConvertToInt8 -> save_inference_model -> Predictor serves the int8
    model and matches the fp32 predictor within quantization tolerance."""
    from paddle_tpu.inference import Config, Predictor

    main, startup, loss, pred = _fc_net()
    fp32_dir = str(tmp_path / "fp32")
    int8_dir = str(tmp_path / "int8")
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        QuantizationTransformPass(
            scope=scope, activation_quantize_type="moving_average_abs_max",
            weight_quantize_type="abs_max",
            quantizable_op_type=("mul",)).apply(main)
        with fluid.program_guard(main, startup):
            fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
        exe.run(startup)
        for _ in range(10):
            exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
        infer = main._prune([pred])
        # fp32 reference model BEFORE freezing (QAT graph serves fp32)
        fluid.io.save_inference_model(fp32_dir, ["x"], [pred], exe,
                                      main_program=infer)
        QuantizationFreezePass(scope=scope, weight_quantize_type="abs_max",
                               quantizable_op_type=("mul",)).apply(infer)
        ConvertToInt8Pass(scope=scope,
                          quantizable_op_type=("mul",)).apply(infer)
        fluid.io.save_inference_model(int8_dir, ["x"], [pred], exe,
                                      main_program=infer)

    p32 = Predictor(Config(model_dir=fp32_dir))
    p8 = Predictor(Config(model_dir=int8_dir))
    (o32,) = p32.run({"x": X})
    (o8,) = p8.run({"x": X})
    # int8-vs-fp32 closeness is distributional (see the freeze test):
    # one grid flip on a small activation makes a single outlier row,
    # so bound the relative RMS, not the max pointwise error
    o32, o8 = np.asarray(o32), np.asarray(o8)
    rel_rms = np.linalg.norm(o8 - o32) / max(np.linalg.norm(o32), 1e-6)
    assert rel_rms < 0.25, rel_rms
