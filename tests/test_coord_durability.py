"""Durable coordination service: WAL + snapshot crash recovery, the
epoch handshake, transparently reconnecting clients, and the monotonic
lease clock (a wall-clock step must never mass-expire leases)."""

import json
import os
import threading
import time

import pytest

from paddle_tpu.distributed import coordination, wire
from paddle_tpu.distributed.coordination import (CoordClient, CoordServer,
                                                 SNAPSHOT_FILE, WAL_FILE)
from paddle_tpu.fluid import monitor

pytestmark = pytest.mark.chaos


def _restart(port, wal_dir, **kw):
    """Rebind the coordinator on the SAME port right after a crash —
    SO_REUSEADDR makes this safe, but give the kernel a beat if the
    listener teardown races the rebind."""
    deadline = time.time() + 10
    while True:
        try:
            return CoordServer(port=port, wal_dir=wal_dir, **kw).start()
        except OSError:
            if time.time() > deadline:
                raise
            time.sleep(0.1)


# -- crash recovery ---------------------------------------------------------

def test_crash_recovery_restores_kv_and_counters(tmp_path):
    """kill -9 (``crash()``: no final snapshot) + restart on the same
    WAL dir: every acknowledged mutation survives, the epoch bumps,
    and the SAME client object re-dials transparently."""
    wal = str(tmp_path / "wal")
    srv = CoordServer(wal_dir=wal).start()
    port, epoch0 = srv.port, srv.epoch
    cli = CoordClient(srv.endpoint, grace=30.0)
    try:
        cli.put("k1", b"v1")
        cli.put("k2", b"v2")
        assert cli.delete("k2") is True
        assert cli.add("ctr", 3) == 3
        srv.crash()
        srv = _restart(port, wal)
        assert srv.epoch == epoch0 + 1
        assert cli.get("k1") == b"v1"
        assert cli.get("k2") is None
        # journaled as the RESULT: replay cannot double-count the add
        assert cli.add("ctr", 2) == 5
        assert cli.server_epoch == srv.epoch
    finally:
        cli.close()
        srv.stop()


def test_clean_stop_compacts_into_snapshot(tmp_path):
    """A clean ``stop()`` snapshots and truncates the WAL, so the next
    start replays nothing."""
    wal = str(tmp_path / "wal")
    srv = CoordServer(wal_dir=wal).start()
    cli = CoordClient(srv.endpoint)
    try:
        cli.put("k", b"v")
    finally:
        cli.close()
        srv.stop()
    assert os.path.getsize(os.path.join(wal, WAL_FILE)) == 0
    snap = json.loads(open(os.path.join(wal, SNAPSHOT_FILE), "rb").read())
    assert "k" in snap["kv"]
    srv2 = CoordServer(wal_dir=wal)
    try:
        assert srv2._kv == {"k": b"v"}
        assert srv2.epoch == snap["epoch"] + 1
    finally:
        srv2.stop()


def test_periodic_snapshot_compacts_wal(tmp_path):
    """Every ``snapshot_every`` records the WAL is folded into an
    atomic snapshot and truncated; recovery still sees everything."""
    wal = str(tmp_path / "wal")
    snaps0 = monitor.counter("coord_snapshots_total").value
    srv = CoordServer(wal_dir=wal, snapshot_every=4).start()
    port = srv.port
    cli = CoordClient(srv.endpoint, grace=30.0)
    try:
        for i in range(10):
            cli.put("k%d" % i, b"v%d" % i)
        assert monitor.counter("coord_snapshots_total").value - snaps0 >= 2
        # only the records since the last snapshot remain in the log
        with open(os.path.join(wal, WAL_FILE), "rb") as f:
            assert len(f.read().splitlines()) < 4
        srv.crash()
        srv = _restart(port, wal)
        for i in range(10):
            assert cli.get("k%d" % i) == b"v%d" % i
    finally:
        cli.close()
        srv.stop()


def test_torn_wal_tail_is_tolerated(tmp_path):
    """A crash mid-append tears only the unacknowledged tail: replay
    keeps every record before it and stops at the torn line."""
    wal = str(tmp_path / "wal")
    srv = CoordServer(wal_dir=wal).start()
    port = srv.port
    cli = CoordClient(srv.endpoint, grace=30.0)
    try:
        for i in range(3):
            cli.put("k%d" % i, b"v")
        srv.crash()
        with open(os.path.join(wal, WAL_FILE), "ab") as f:
            f.write(b'{"o":"put","k":"torn","v":"A')  # no newline, no seq
        srv = _restart(port, wal)
        for i in range(3):
            assert cli.get("k%d" % i) == b"v"
        assert cli.get("torn") is None
    finally:
        cli.close()
        srv.stop()


def test_corrupt_snapshot_refuses_loudly(tmp_path):
    """Snapshots are written atomically, so garbage means operator
    error — the server must refuse to serve empty state over it."""
    wal = tmp_path / "wal"
    wal.mkdir()
    (wal / SNAPSHOT_FILE).write_bytes(b"\x00not json at all")
    with pytest.raises(RuntimeError, match="corrupt"):
        CoordServer(wal_dir=str(wal))


# -- barriers and watches across a restart ----------------------------------

def test_barrier_blocked_across_crash_releases_both(tmp_path):
    """The journaled arrival survives the crash; the blocked waiter
    re-dials and both ranks release with the SAME generation."""
    wal = str(tmp_path / "wal")
    srv = CoordServer(wal_dir=wal).start()
    port = srv.port
    a = CoordClient(srv.endpoint, grace=30.0)
    b = CoordClient(srv.endpoint, grace=30.0)
    got = {}
    try:
        t = threading.Thread(
            target=lambda: got.__setitem__(
                "a", a.barrier("bar", 2, "ra", timeout=60.0)))
        t.start()
        time.sleep(0.4)           # ra's arrival journaled; ra blocked
        srv.crash()
        srv = _restart(port, wal)
        got["b"] = b.barrier("bar", 2, "rb", timeout=60.0)
        t.join(60)
        assert not t.is_alive(), "blocked rank never released"
        assert got["a"] == got["b"]
    finally:
        a.close()
        b.close()
        srv.stop()


def test_blocked_wait_get_survives_restart(tmp_path):
    """A ``get(wait=True)`` watch blocked through the crash re-arms on
    the restarted server and still wakes on the put."""
    wal = str(tmp_path / "wal")
    srv = CoordServer(wal_dir=wal).start()
    port = srv.port
    a = CoordClient(srv.endpoint, grace=30.0)
    b = CoordClient(srv.endpoint, grace=30.0)
    got = {}
    try:
        t = threading.Thread(
            target=lambda: got.__setitem__(
                "v", a.get("late", wait=True, timeout=60.0)))
        t.start()
        time.sleep(0.3)
        srv.crash()
        srv = _restart(port, wal)
        b.put("late", b"ok")
        t.join(60)
        assert not t.is_alive(), "watcher never woke"
        assert got["v"] == b"ok"
    finally:
        a.close()
        b.close()
        srv.stop()


# -- leases: monotonic in memory, wall-clock on disk ------------------------

def test_lease_immune_to_wall_clock_step():
    """Satellite regression: in-memory lease deadlines live on the
    MONOTONIC clock — an NTP step (even a huge one) must not expire a
    live lease; only monotonic time passing may."""
    mono, wall = [100.0], [1.0e9]
    srv = CoordServer(clock=lambda: mono[0], wall=lambda: wall[0])
    try:
        srv._do_lease("c", 5.0)
        wall[0] += 3600.0         # one-hour NTP step forward
        assert json.loads(srv._do_live()[1:]) == ["c"]
        wall[0] -= 7200.0         # and a step backward
        assert json.loads(srv._do_live()[1:]) == ["c"]
        mono[0] += 6.0            # real time actually passing
        assert json.loads(srv._do_live()[1:]) == []
    finally:
        srv.stop()


def test_lease_wall_deadline_survives_restart(tmp_path):
    """Across a restart only the wall clock survives: the journaled
    absolute wall deadline converts back to a monotonic one, so the
    REMAINING ttl (minus the outage) is what the new server enforces."""
    wal = str(tmp_path / "wal")
    mono1, wall1 = [0.0], [1000.0]
    srv = CoordServer(wal_dir=wal, clock=lambda: mono1[0],
                      wall=lambda: wall1[0]).start()
    port = srv.port
    cli = CoordClient(srv.endpoint, grace=30.0)
    try:
        cli.lease("c", ttl=100.0)       # wall deadline 1100 journaled
        cli.forget_lease("c")           # no client-side replay: the
        srv.crash()                     # WAL alone must carry it
        # restart 60 wall-seconds into the outage: 40 s must remain
        mono2, wall2 = [500.0], [1060.0]
        srv = _restart(port, wal, clock=lambda: mono2[0],
                       wall=lambda: wall2[0])
        assert cli.live() == ["c"]
        mono2[0] += 50.0                # past the remaining 40 s
        assert cli.live() == []
    finally:
        cli.close()
        srv.stop()


def test_client_replays_leases_onto_amnesiac_server():
    """An EPHEMERAL coordinator restart loses all state — the client's
    post-reconnect lease replay re-establishes every lease it holds."""
    srv = CoordServer().start()
    port = srv.port
    cli = CoordClient(srv.endpoint, grace=30.0)
    try:
        cli.lease("member/x", ttl=60.0)
        srv.crash()
        deadline = time.time() + 10
        while True:                     # ephemeral rebind, same port
            try:
                srv = CoordServer(port=port).start()
                break
            except OSError:
                if time.time() > deadline:
                    raise
                time.sleep(0.1)
        cli.ping()                      # rides the reconnect; replay
        assert "member/x" in cli.live()  # runs after it completes
    finally:
        cli.close()
        srv.stop()


# -- epoch handshake + reconnect accounting ---------------------------------

def test_epoch_handshake_and_restart_counter(tmp_path):
    """The hello advertises the server epoch; a reconnect that lands on
    a bumped epoch is counted as kind=restart (vs resume)."""
    wal = str(tmp_path / "wal")
    srv = CoordServer(wal_dir=wal).start()
    port = srv.port
    cli = CoordClient(srv.endpoint, grace=30.0)
    restarts0 = coordination._m_reconnects("restart").value
    try:
        cli.ping()
        assert cli.server_epoch == srv.epoch
        srv.crash()
        srv = _restart(port, wal)
        cli.ping()
        assert cli.server_epoch == srv.epoch
        assert coordination._m_reconnects("restart").value \
            == restarts0 + 1
    finally:
        cli.close()
        srv.stop()


# -- oversized frames refused before the socket -----------------------------

def test_oversized_request_refused_client_side():
    """A request bigger than the frame cap raises FrameTooLarge BEFORE
    any byte hits the socket: no retry budget burned, and the very same
    connection keeps working for the next (smaller) request."""
    srv = CoordServer().start()
    cli = CoordClient(srv.endpoint, max_frame=256)
    try:
        with pytest.raises(wire.FrameTooLarge):
            cli.put("k", b"x" * 1024)
        cli.put("k", b"small")
        assert cli.get("k") == b"small"
    finally:
        cli.close()
        srv.stop()
