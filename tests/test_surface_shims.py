"""v1.6 surface parity shims: fluid.communicator, fluid.dygraph_grad_clip,
fluid.lod_tensor.create_random_int_lodtensor, fluid.input.

References: fluid/communicator.py:26 (Communicator over the async
communicator, communicator.h:175/:332), fluid/dygraph_grad_clip.py:34-258,
fluid/lod_tensor.py:114, fluid/input.py:21.
"""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import dygraph, layers, optimizer
from paddle_tpu.fluid.communicator import Communicator
from paddle_tpu.fluid.dygraph_grad_clip import (
    GradClipByGlobalNorm, GradClipByNorm, GradClipByValue)
from paddle_tpu.fluid.dygraph import nn, to_variable
from paddle_tpu.distributed import ps


def _ps_program(table_name, vocab=30, dim=8):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 11
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[3], dtype="int64")
        label = layers.data("label", shape=[1], dtype="float32")
        emb = layers.embedding(
            ids, size=[vocab, dim], is_distributed=True, table_lr=0.1,
            param_attr=fluid.ParamAttr(name=table_name))
        pooled = layers.reduce_sum(emb, dim=1)
        pred = layers.fc(pooled, size=1)
        loss = layers.mean(layers.square_error_cost(pred, label))
        optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def test_communicator_async_mode_trains():
    vocab = 30
    main, startup, loss = _ps_program("comm_emb", vocab=vocab)
    table = ps.get_table("comm_emb")
    base = table.dump()
    comm = Communicator(main)
    assert not comm.is_running()
    comm.start()
    assert comm.is_running()
    # pushes now route through the async proxy
    assert type(ps.get_table("comm_emb")).__name__ == "_AsyncTableProxy"
    rng = np.random.RandomState(0)
    feed = {"ids": rng.randint(0, vocab, (16, 3)).astype(np.int64),
            "label": rng.rand(16, 1).astype(np.float32)}
    exe = fluid.Executor()
    losses = []
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(6):
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(lv).ravel()[0]))
        comm.stop()  # drains the queue: ALL 6 pushes are applied now
        assert not comm.is_running()
        # direct table restored, queued pushes drained and applied
        assert ps.get_table("comm_emb") is table
        # deterministic post-drain check (the async worker may lag the
        # loop arbitrarily): an eval step after the drain must beat the
        # first step — it sees every push plus the trained dense head
        (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
        assert float(np.asarray(lv).ravel()[0]) < losses[0]
    touched = np.unique(feed["ids"])
    assert np.abs(table.dump()[touched] - base[touched]).max() > 0
    # start/stop again is clean (idempotency)
    comm.start()
    comm.stop()


def test_communicator_geo_mode_syncs_every_k():
    vocab, dim, k = 12, 4, 3
    table = ps.register_table("geo_comm_t", ps.EmbeddingTable(vocab, dim))
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        ids = layers.data("gids", shape=[2], dtype="int64")
        layers.embedding(ids, size=[vocab, dim], is_distributed=True,
                         param_attr=fluid.ParamAttr(name="geo_comm_t"))
    comm = Communicator(main, vars_info={"geo_comm_t": {}}, trainers=2,
                        geo_sgd_need_push_nums=k)
    comm.start()
    proxy = ps.get_table("geo_comm_t")
    assert type(proxy).__name__ == "_GeoTableProxy"
    base = table.dump()
    g = np.ones((2, dim), np.float32)
    ids2 = np.array([1, 3], np.int64)
    for i in range(k - 1):
        proxy.push(ids2, g, lr=0.5)
        np.testing.assert_array_equal(table.dump(), base)  # not yet shipped
    # local mirror moved though — pulls see it
    assert np.abs(proxy.pull(ids2) - base[ids2]).max() > 0
    proxy.push(ids2, g, lr=0.5)  # k-th push ships the delta
    shipped = table.dump()
    assert np.abs(shipped[ids2] - base[ids2]).max() > 0
    # geo is SGD-by-construction: other optimizers must refuse loudly
    import pytest

    with pytest.raises(ValueError, match="sgd"):
        proxy.push(ids2, g, lr=0.5, optimizer="adagrad")
    with pytest.raises(IndexError):
        proxy.pull(np.array([vocab + 1], np.int64))
    # pull contract matches EmbeddingTable.pull: 2-D ids flatten to (N, dim)
    assert proxy.pull(np.array([[1], [3]], np.int64)).shape == (2, dim)
    comm.stop()
    assert ps.get_table("geo_comm_t") is table


def _grads_from_model(seed=0):
    rng = np.random.RandomState(seed)
    model = nn.Linear(4, 3)
    x = to_variable(rng.rand(8, 4).astype(np.float32) * 10.0)
    out = model(x)
    sq = out * out
    tracer = fluid.framework._dygraph_tracer()
    (loss,) = tracer.trace_op("mean", {"X": [sq]}, ["Out"], {})
    loss.backward()
    params = [p for p in model.parameters() if p._grad is not None]
    return model, loss, [(p, p._grad) for p in params]


def test_dygraph_grad_clip_by_value():
    with dygraph.guard():
        _, _, pg = _grads_from_model()
        clipped = GradClipByValue(0.01)(pg)
        for (_, g0), (_, g1) in zip(pg, clipped):
            assert float(np.abs(np.asarray(g1)).max()) <= 0.01 + 1e-7
            np.testing.assert_allclose(
                np.asarray(g1), np.clip(np.asarray(g0), -0.01, 0.01))


def test_dygraph_grad_clip_by_norm():
    with dygraph.guard():
        _, _, pg = _grads_from_model()
        clip_norm = 0.05
        clipped = GradClipByNorm(clip_norm)(pg)
        for (_, g0), (_, g1) in zip(pg, clipped):
            n0 = np.linalg.norm(np.asarray(g0))
            n1 = np.linalg.norm(np.asarray(g1))
            if n0 > clip_norm:
                np.testing.assert_allclose(n1, clip_norm, rtol=1e-4)
            else:
                np.testing.assert_allclose(np.asarray(g1), np.asarray(g0))


def test_dygraph_grad_clip_by_global_norm():
    with dygraph.guard():
        _, _, pg = _grads_from_model()
        max_norm = 0.02
        gn = np.sqrt(sum(np.sum(np.square(np.asarray(g))) for _, g in pg))
        assert gn > max_norm  # the test must exercise the clipping branch
        clipped = GradClipByGlobalNorm(max_norm)(pg)
        gn1 = np.sqrt(sum(np.sum(np.square(np.asarray(g)))
                          for _, g in clipped))
        np.testing.assert_allclose(gn1, max_norm, rtol=1e-4)
        # direction preserved per tensor
        for (_, g0), (_, g1) in zip(pg, clipped):
            np.testing.assert_allclose(np.asarray(g1),
                                       np.asarray(g0) * (max_norm / gn),
                                       rtol=1e-4)


def test_dygraph_minimize_applies_grad_clip():
    """minimize(grad_clip=...) must update with the CLIPPED grads
    (reference optimizer.py:680-682)."""
    with dygraph.guard():
        model, loss, pg = _grads_from_model(seed=1)
        w = model.parameters()[0]
        w_before = np.asarray(w.numpy()).copy()
        g_raw = np.asarray(w._grad).copy()
        clip = GradClipByGlobalNorm(0.01)
        # the expectation uses the global norm over ALL params, matching
        # what minimize hands the clip
        all_pairs = [(p, p._grad) for p in model.parameters()
                     if p._grad is not None]
        gn = np.sqrt(sum(np.sum(np.square(np.asarray(g)))
                         for _, g in all_pairs))
        scale = 0.01 / max(gn, 0.01)
        opt = optimizer.SGD(learning_rate=1.0)
        opt.minimize(loss, parameter_list=model.parameters(),
                     grad_clip=clip)
        w_after = np.asarray(w.numpy())
        np.testing.assert_allclose(w_after, w_before - g_raw * scale,
                                   rtol=1e-4, atol=1e-6)


def test_create_random_int_lodtensor():
    t = fluid.create_random_int_lodtensor(
        recursive_seq_lens=[[2, 3]], base_shape=[30], place=None,
        low=0, high=9)
    data = np.asarray(t)
    assert data.shape == (5, 30)
    assert data.dtype == np.int64
    assert data.min() >= 0 and data.max() <= 9
    assert t.recursive_sequence_lengths() == [[2, 3]]


def test_input_module_and_module_paths():
    assert fluid.input.embedding is layers.embedding
    assert fluid.input.one_hot is layers.one_hot
    assert fluid.lod_tensor.create_lod_tensor is fluid.create_lod_tensor
    assert hasattr(fluid.communicator, "Communicator")
    assert hasattr(fluid.dygraph_grad_clip, "GradClipByGlobalNorm")


def test_distribute_lookup_table_helpers():
    from paddle_tpu.fluid import distribute_lookup_table as dlt

    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        ids = layers.data("dlt_ids", shape=[2], dtype="int64")
        layers.embedding(ids, size=[8, 4], is_distributed=True,
                         param_attr=fluid.ParamAttr(name="dlt_t"))
    assert dlt.find_distributed_lookup_table(main) == "dlt_t"
    ins = dlt.find_distributed_lookup_table_inputs(main, "dlt_t")
    outs = dlt.find_distributed_lookup_table_outputs(main, "dlt_t")
    assert [v.name for v in ins] == ["dlt_ids"]
    assert len(outs) == 1
    # no distributed table -> None
    empty = fluid.Program()
    with fluid.program_guard(empty, fluid.Program()):
        x = layers.data("dlt_x", shape=[2], dtype="int64")
        layers.embedding(x, size=[8, 4])
    assert dlt.find_distributed_lookup_table(empty) is None


def test_dygraph_traced_layer_exported():
    assert dygraph.TracedLayer is fluid.dygraph.jit.TracedLayer


def test_dygraph_gperf_profiler_smoke(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_GPERF_DIR", str(tmp_path / "prof"))
    from paddle_tpu.fluid.dygraph import profiler as dyprof

    dyprof.start_gperf_profiler()
    with dygraph.guard():
        v = to_variable(np.ones((2, 2), np.float32))
        (v * v).numpy()
    dyprof.stop_gperf_profiler()
    import os

    assert os.path.isdir(str(tmp_path / "prof"))
    # idempotent stop
    dyprof.stop_gperf_profiler()
