"""Test harness: force a virtual 8-device CPU mesh so multi-chip sharding
paths compile and execute without TPU hardware (the analogue of the
reference's spawn-local-subprocess fake cluster, SURVEY §4)."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

# The environment may pre-set JAX_PLATFORMS to a TPU tunnel backend; the env
# var alone does not always win, so force it through the config API too.
jax.config.update("jax_platforms", "cpu")
assert all(d.platform == "cpu" for d in jax.devices())
assert len(jax.devices()) == 8, "expected 8 virtual CPU devices for mesh tests"


import threading  # noqa: E402

import pytest  # noqa: E402


def pytest_configure(config):
    """Register the suite's markers here (no pytest.ini — an extra
    config file would change pytest's rootdir resolution for callers
    that run a subset of the tree)."""
    config.addinivalue_line(
        "markers", "slow: long-running test, excluded from tier-1 "
                   "(-m 'not slow')")
    config.addinivalue_line(
        "markers", "faults: exercises the fluid.faults injection "
                   "harness (kills subprocesses, arms global fault "
                   "points)")
    config.addinivalue_line(
        "markers", "elastic: exercises the elastic launcher path "
                   "(preemption drain, gang reformation, hung-step "
                   "watchdog) — spawns worker subprocesses")
    config.addinivalue_line(
        "markers", "decode: exercises the autoregressive KV-cache "
                   "decode fast path (prefill/decode program pair, "
                   "cache-aware attention)")
    config.addinivalue_line(
        "markers", "serving: exercises the in-process serving tier "
                   "(dynamic request batching, bucket ladder, "
                   "admission control, continuous decode batching)")
    config.addinivalue_line(
        "markers", "embedding: exercises the sparse embedding engine "
                   "(mesh-sharded dedup-gather tier, host-offloaded "
                   "resident-cache tier, fused sparse optimizer updates)")
    config.addinivalue_line(
        "markers", "compile_cache: exercises the persistent on-disk "
                   "compile cache (AOT serialize/deserialize, "
                   "quarantine, eviction, prelowered models)")
    config.addinivalue_line(
        "markers", "multihost: exercises the multi-host SPMD runtime "
                   "(TCP coordination service, hierarchical DCN "
                   "data-parallelism, cross-host DGC/LocalSGD) — "
                   "spawns worker subprocesses")
    config.addinivalue_line(
        "markers", "fleet: exercises the serving fleet (SLO-aware "
                   "router, coordinated replicas, warm respawn, "
                   "deadline-aware batching)")
    config.addinivalue_line(
        "markers", "telemetry: exercises the fleet telemetry plane "
                   "(distributed tracing, cross-process metrics "
                   "aggregation, crash flight recorder)")
    config.addinivalue_line(
        "markers", "chaos: kills and restarts the coordination "
                   "service mid-run (WAL recovery, reconnecting "
                   "clients, degraded-mode fleet routing)")
    config.addinivalue_line(
        "markers", "longctx: exercises the long-context tier (ring / "
                   "Ulysses sequence-parallel attention over the 'sp' "
                   "mesh axis, recompute, sequence-sharded decode); "
                   "heavy S>=1024 cases additionally carry 'slow'")
    config.addinivalue_line(
        "markers", "pipeline3d: exercises 3D parallelism (GPipe "
                   "pipeline schedule over 'stage', Megatron tensor "
                   "parallelism over 'model', hierarchical DP over "
                   "'host'/'data' — loss-trajectory equivalence, "
                   "iters=k windows, checkpoint resharding); the "
                   "compile-heavy equivalence/report cases additionally "
                   "carry 'slow' — run -m pipeline3d for full coverage")


@pytest.fixture(autouse=True)
def _no_leaked_nondaemon_threads():
    """Fail any test that leaves NEW non-daemon threads alive — a hung
    DeviceStager / window-prefetch thread would otherwise hang the whole
    suite at interpreter exit. Pre-existing threads (dataset channel
    workers from earlier tests, jax internals) are exempt via the
    before-snapshot; a short grace join absorbs threads that are mid-
    shutdown when the test body returns."""
    before = set(threading.enumerate())
    yield
    leaked = [t for t in threading.enumerate()
              if t not in before and t.is_alive() and not t.daemon]
    deadline = 2.0
    for t in leaked:
        t.join(timeout=deadline)
    leaked = [t for t in leaked if t.is_alive()]
    if leaked:
        pytest.fail(
            "test leaked non-daemon thread(s): %s — close() your "
            "DeviceStager/Executor/loader" % [t.name for t in leaked])


def pytest_sessionfinish(session, exitstatus):
    """Dump the executed-op-type set so the execution-coverage gate's
    EXEMPT list can be audited: tests/.executed_op_types.txt. Only
    full-suite sessions write it (partial runs would clobber the
    meaningful dump with a tiny one)."""
    try:
        if len(getattr(session, "items", [])) < 400:
            return
        from paddle_tpu.fluid.registry import EXECUTED_OP_TYPES, registry

        here = os.path.dirname(os.path.abspath(__file__))
        with open(os.path.join(here, ".executed_op_types.txt"), "w") as f:
            f.write("\n".join(sorted(EXECUTED_OP_TYPES)) + "\n")
            f.write("# missing:\n")
            for t in sorted(set(registry.types()) - EXECUTED_OP_TYPES):
                f.write("# %s\n" % t)
    except Exception:
        pass
