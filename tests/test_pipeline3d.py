"""3D parallelism: GPipe pipeline x Megatron tensor parallel x
hierarchical data parallel, composed on one mesh.

The oracle is loss-trajectory equivalence: the SAME initial parameters
stepped by plain SGD on one device must reproduce (CPU fp32,
rtol <= 1e-6) under every composition — pipeline-only (2 stages),
TP-only (GSPMD over a 'model' axis), and pipeline x TP x DP on the full
8-device mesh. Microbatch loss averaging, the stage psum, the Megatron
region collectives and the DP pmean must all telescope back to the
single-device math or the trajectory drifts in step one.

Also here: the ``iters=k`` window bit-identity contract for pipelined
programs, the typed ``UnsupportedStrategyError`` refusal, reserved
mesh-axis validation, checkpoint resharding across a mesh-shape change
that adds 'stage', and the ``tools/stagebalance.py`` cut audit."""

import os
import sys

import numpy as np
import pytest

import jax

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, monitor, optimizer
from paddle_tpu.fluid.compiler import (RESERVED_AXES,
                                       UnsupportedStrategyError)
from paddle_tpu.fluid import dygraph
from paddle_tpu.fluid.executor import scope_guard
from paddle_tpu.models import transformer

V, SEQ = 64, 8
M = 2                 # microbatches
B_SHARD = 2           # per-shard microbatch rows (pipeline trace batch)
B_FULL = M * B_SHARD  # global batch
STEPS = 3


def _build_tiny(trace_batch, pipeline, model_axis=None):
    """Trace the tiny NMT transformer at ``trace_batch`` rows, append
    CE loss + SGD (wrapped in PipelineOptimizer cutting at the final
    encoder output when ``pipeline``), and materialize the eager params
    into a scope."""
    with dygraph.guard():
        model = transformer.Transformer.tiny(V, V, dropout_rate=0.0,
                                             model_axis=model_axis)
        src, tgt, labels, pos = transformer.synthetic_batch(
            V, V, trace_batch, SEQ)
        bias = transformer.make_causal_bias(SEQ)
        args = [dygraph.to_variable(v) for v in (src, tgt, pos, pos, bias)]
        _, traced = dygraph.jit.trace(model, args)
    startup = fluid.Program()
    with fluid.program_guard(traced.program, startup):
        blk = traced.program.global_block()
        logits = blk.var(traced._fetch_names[0])
        label = layers.data("lbl", [SEQ, 1], dtype="int64")
        ce = layers.softmax_with_cross_entropy(
            layers.reshape(logits, [-1, V]),
            layers.reshape(label, [-1, 1]))
        loss = layers.mean(ce)
        opt = optimizer.SGD(learning_rate=0.1)
        if pipeline:
            cut = blk.var(model.last_checkpoints[1])  # final encoder out
            opt = optimizer.PipelineOptimizer(opt, cut_list=[cut])
        opt.minimize(loss)
    traced._materialize_scope()
    return model, traced, startup, loss


def _copy_params(ref_values, model, traced):
    """Same init across traces: eager params pair up by construction
    order (dygraph names are globally counted, so name equality can't).
    ``ref_values`` are numpy snapshots — the reference run donates its
    scope buffers, so the eager arrays themselves don't survive it."""
    ps = model.parameters()
    assert len(ref_values) == len(ps)
    for rv, pp in zip(ref_values, ps):
        assert tuple(rv.shape) == tuple(pp.shape), (rv.shape, pp.name)
        traced._scope.set_var(pp.name, rv)


def _run_steps(exe, program, traced, loss, feed, n=STEPS):
    with scope_guard(traced._scope):
        return [float(np.asarray(exe.run(program, feed=feed,
                                         fetch_list=[loss])[0]).ravel()[0])
                for _ in range(n)]


@pytest.fixture(scope="module")
def oracle():
    """Single-device reference trajectory + the eager params and batch
    every composition must reproduce."""
    model, traced, startup, loss = _build_tiny(B_FULL, pipeline=False)
    src, tgt, labels, pos = transformer.synthetic_batch(V, V, B_FULL, SEQ,
                                                        seed=3)
    bias = transformer.make_causal_bias(SEQ)
    feed = dict(zip(traced._feed_names, (src, tgt, pos, pos, bias)))
    feed["lbl"] = labels
    exe = fluid.Executor()
    with scope_guard(traced._scope):
        exe.run(startup)
    init = [np.asarray(p._ivar).copy() for p in model.parameters()]
    losses = _run_steps(exe, traced.program, traced, loss, feed)
    return {"params": init, "losses": losses,
            "arrays": (src, tgt, pos, pos, bias), "labels": labels}


def _feed_for(traced, arrays, labels):
    feed = dict(zip(traced._feed_names, arrays))
    feed["lbl"] = labels
    return feed


@pytest.mark.slow
@pytest.mark.pipeline3d
def test_pipeline_matches_single_device(oracle):
    model, traced, startup, loss = _build_tiny(B_SHARD, pipeline=True)
    _copy_params(oracle["params"], model, traced)
    cp = fluid.CompiledProgram(traced.program).with_pipeline(
        loss_name=loss.name, places=jax.devices()[:2], num_microbatches=M)
    exe = fluid.Executor()
    with scope_guard(traced._scope):
        exe.run(startup)
    losses = _run_steps(exe, cp, traced, loss,
                        _feed_for(traced, oracle["arrays"],
                                  oracle["labels"]))
    np.testing.assert_allclose(oracle["losses"], losses, rtol=1e-6)


@pytest.mark.slow
@pytest.mark.pipeline3d
def test_tensor_parallel_matches_single_device(oracle):
    model, traced, startup, loss = _build_tiny(B_FULL, pipeline=False,
                                               model_axis="model")
    _copy_params(oracle["params"], model, traced)
    cp = fluid.CompiledProgram(traced.program).with_data_parallel(
        loss_name=loss.name, mesh_axes=("dp", "model"),
        mesh_shape={"dp": 2, "model": 4})
    exe = fluid.Executor()
    with scope_guard(traced._scope):
        exe.run(startup)
    losses = _run_steps(exe, cp, traced, loss,
                        _feed_for(traced, oracle["arrays"],
                                  oracle["labels"]))
    np.testing.assert_allclose(oracle["losses"], losses, rtol=1e-6)


@pytest.mark.slow
@pytest.mark.pipeline3d
def test_pipeline_tp_dp_composed_matches_single_device(oracle):
    """The full 3D mesh: stage=2 x model=2 x data=2 over all 8 CPU
    devices, per-shard microbatch of ONE row."""
    model, traced, startup, loss = _build_tiny(1, pipeline=True,
                                               model_axis="model")
    _copy_params(oracle["params"], model, traced)
    cp = fluid.CompiledProgram(traced.program).with_pipeline(
        loss_name=loss.name, num_microbatches=M,
        mesh_axes=("stage", "model", "data"),
        mesh_shape={"stage": 2, "model": 2, "data": 2})
    exe = fluid.Executor()
    with scope_guard(traced._scope):
        exe.run(startup)
    losses = _run_steps(exe, cp, traced, loss,
                        _feed_for(traced, oracle["arrays"],
                                  oracle["labels"]))
    np.testing.assert_allclose(oracle["losses"], losses, rtol=1e-6)


# -- iters=k window ----------------------------------------------------------

def _build_mlp_pipeline(seed=13):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[32], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        h1 = layers.fc(x, 32, act="tanh")
        h2 = layers.fc(h1, 32, act="tanh")
        logits = layers.fc(h2, 10)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        opt = optimizer.PipelineOptimizer(optimizer.SGD(learning_rate=0.1),
                                          cut_list=[h1])
        opt.minimize(loss)
    return main, startup, loss


def _snapshot(scope):
    return {n: np.asarray(scope.find_var(n)).copy()
            for n in scope.var_names()}


@pytest.mark.pipeline3d
def test_pipeline_iters_window_bit_identical():
    """A k-step device-side window through the pipelined program must be
    BIT-identical to k single steps: the window scans the same GPipe
    kernel, so not even float reassociation may differ."""
    k = 3
    rng = np.random.RandomState(7)
    xs = rng.rand(k, 8, 32).astype(np.float32)
    ys = rng.randint(0, 10, (k, 8, 1)).astype(np.int64)

    main, startup, loss = _build_mlp_pipeline()
    cp = fluid.CompiledProgram(main).with_pipeline(
        loss_name=loss.name, places=jax.devices()[:2], num_microbatches=2)
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        snap = _snapshot(scope)
        single = [np.asarray(exe.run(cp, feed={"x": xs[i], "label": ys[i]},
                                     fetch_list=[loss])[0])
                  for i in range(k)]
        end_single = _snapshot(scope)

    # same Program, fresh strategy/scope, identical initial state
    cp2 = fluid.CompiledProgram(main).with_pipeline(
        loss_name=loss.name, places=jax.devices()[:2], num_microbatches=2)
    exe2 = fluid.Executor()
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe2.run(startup)
        for n, v in snap.items():
            scope2.set_var(n, v)
        (traj,) = exe2.run(cp2, feed={"x": xs, "label": ys},
                           fetch_list=[loss], iters=k)
        traj = np.asarray(traj)
        end_window = _snapshot(scope2)

    np.testing.assert_array_equal(
        traj.ravel(), np.asarray(single).ravel())
    for n in end_single:
        if end_single[n].dtype == np.float32:
            np.testing.assert_array_equal(end_single[n], end_window[n],
                                          err_msg=n)


@pytest.mark.pipeline3d
def test_iters_refuses_shard_map_strategy_with_typed_error():
    """shard_map mode schedules its own device loop; asking it to batch
    steps must raise the TYPED error naming the strategy and the
    supported set — not a silent fallback, not a bare RuntimeError."""
    main, startup, loss = _build_mlp_pipeline()
    cp = fluid.CompiledProgram(main).with_explicit_collectives(
        loss_name=loss.name)
    rng = np.random.RandomState(0)
    xs = rng.rand(2, 8, 32).astype(np.float32)
    ys = rng.randint(0, 10, (2, 8, 1)).astype(np.int64)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        with pytest.raises(UnsupportedStrategyError) as ei:
            exe.run(cp, feed={"x": xs, "label": ys}, fetch_list=[loss],
                    iters=2)
    msg = str(ei.value)
    assert "shard_map" in msg
    assert "with_data_parallel" in msg and "with_pipeline" in msg
    assert isinstance(ei.value, RuntimeError)  # back-compat contract


# -- mesh-axis validation ----------------------------------------------------

@pytest.mark.pipeline3d
def test_reserved_axes_rejected_outside_owning_strategy():
    main, _, loss = _build_mlp_pipeline()

    def fresh():
        return fluid.CompiledProgram(main)

    # 'stage' belongs to the pipeline schedule, not GSPMD
    with pytest.raises(ValueError, match="reserved"):
        fresh().with_data_parallel(loss_name=loss.name,
                                   mesh_axes=("stage", "dp"))
    # 'model'/'sp' have no meaning under explicit collectives
    with pytest.raises(ValueError, match="reserved"):
        fresh().with_explicit_collectives(loss_name=loss.name,
                                          mesh_axes=("model",))
    # the pipeline cannot run without its own axis
    with pytest.raises(ValueError, match="requires mesh axes"):
        fresh().with_pipeline(loss_name=loss.name, mesh_axes=("data",))
    # and accepts only axes with a role in the schedule
    with pytest.raises(ValueError, match="no role"):
        fresh().with_pipeline(loss_name=loss.name,
                              mesh_axes=("stage", "foo"))
    with pytest.raises(ValueError, match="duplicates"):
        fresh().with_data_parallel(loss_name=loss.name,
                                   mesh_axes=("dp", "dp"))
    # free (non-reserved) names stay legal where they always were
    fresh().with_data_parallel(loss_name=loss.name, mesh_axes=("dp", "tp"))
    assert RESERVED_AXES == {"host", "stage", "model", "data", "sp"}


# -- checkpoint resharding across a mesh-shape change ------------------------

def _sharded_fc_program(seed=11):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        h = layers.fc(x, size=8, act="relu",
                      param_attr=fluid.ParamAttr(shard=("model", None)))
        loss = layers.reduce_mean(h)
        optimizer.SGD(0.05).minimize(loss)
    return main, startup, loss


@pytest.mark.pipeline3d
def test_checkpoint_reshards_onto_pipeline_mesh(tmp_path):
    """A 'model'-sharded checkpoint saved under a 1x4 GSPMD mesh restores
    onto a 2x2 stage-x-model pipeline mesh (the spec's axis survived, so
    it reshards) and onto a stage-only mesh (axis gone: the degradation
    path replicates and counts it) — mesh-shape changes across the
    pipeline axes go through the same single source of truth."""
    from jax.sharding import PartitionSpec as P

    main, startup, loss = _sharded_fc_program()
    name = [v.name for v in main.list_vars()
            if getattr(v, "shard_spec", None)][0]
    exe = fluid.Executor()
    save_cp = fluid.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name, mesh_axes=("dp", "model"),
        mesh_shape={"dp": 1, "model": 4}, places=jax.devices()[:4])
    exe.run(startup)
    mgr = fluid.io.CheckpointManager(str(tmp_path))
    mgr.save(main, step=1)

    restore_cp = fluid.CompiledProgram(main).with_pipeline(
        loss_name=loss.name, mesh_axes=("stage", "model"),
        mesh_shape={"stage": 2, "model": 2}, places=jax.devices()[:4])
    assert mgr.restore(exe, restore_cp) == 1
    w = fluid.global_scope().find_var(name)
    assert w.sharding.spec == P("model", None)
    assert w.sharding.mesh.shape["model"] == 2  # re-laid-out, not 4

    before = monitor.counter("state_reshard_replicated_total").value
    stage_only = fluid.CompiledProgram(main).with_pipeline(
        loss_name=loss.name, mesh_axes=("stage",),
        mesh_shape={"stage": 4}, places=jax.devices()[:4])
    assert mgr.restore(exe, stage_only) == 1
    w2 = fluid.global_scope().find_var(name)
    assert w2.sharding.spec == P()
    assert monitor.counter(
        "state_reshard_replicated_total").value > before
    del save_cp


# -- stagebalance cut audit --------------------------------------------------

@pytest.mark.slow
@pytest.mark.pipeline3d
def test_stagebalance_reports_per_stage_bytes():
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    import stagebalance

    program, feed = stagebalance._build_demo(
        n_layers=2, n_stages=2, mb_rows=2, seq_len=SEQ, vocab=V)
    rows = stagebalance.stage_report(program, feed)
    assert [r["stage"] for r in rows] == [0, 1]
    assert all(r["param_bytes"] > 0 for r in rows)
    assert all(r["peak_act_bytes"] > 0 for r in rows)
    # exactly one boundary, carried by stage 0, per-microbatch sized
    assert rows[0]["boundary_bytes"] > 0
    assert rows[1]["boundary_bytes"] == 0
    # the audited segmentation covers every forward op exactly once
    from paddle_tpu.fluid.compiler import pipeline_segments

    segs, cuts, ad_idx = pipeline_segments(program,
                                           program.global_block())
    assert len(segs) == 2 and len(cuts) == 1
    assert sum(r["ops"] for r in rows) == sum(len(s) for s in segs)
