"""Federated pserver variant (reference fl_listen_and_serv_op.cc
RunSyncLoop): round-synchronous FedAvg — trainers pull params, train
locally, push weighted copies; the server merges when all arrive."""

import threading

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.distributed.fl_server import (FLServer, FLTrainerClient,
                                              build_fl_server_program)


def test_fl_fedavg_rounds():
    srv = FLServer({"w": np.zeros(4, np.float32)}, n_trainers=2)
    try:
        results = {}

        def trainer(tid, delta, weight):
            c = FLTrainerClient(srv.endpoint, token=srv.token)
            traj = []
            for _ in range(3):
                p = c.pull()["w"]
                local = p + delta          # "train locally"
                c.push({"w": local}, weight=weight)
                traj.append(p.copy())
            results[tid] = traj
            c.close()

        t0 = threading.Thread(target=trainer, args=(0, 1.0, 1.0))
        t1 = threading.Thread(target=trainer, args=(1, 4.0, 3.0))
        t0.start(), t1.start()
        t0.join(30), t1.join(30)
        assert not t0.is_alive() and not t1.is_alive()
        # weighted FedAvg per round: merged delta = (1*1 + 3*4)/4 = 3.25
        for traj in results.values():
            np.testing.assert_allclose(
                [t[0] for t in traj], [0.0, 3.25, 6.5], rtol=1e-6)
        np.testing.assert_allclose(srv.params["w"],
                                   np.full(4, 9.75, np.float32))
        assert srv.round == 3
    finally:
        srv.stop()


def test_fl_stale_round_nacks():
    srv = FLServer({"w": np.zeros(2, np.float32)}, n_trainers=1)
    try:
        a = FLTrainerClient(srv.endpoint, token=srv.token)
        a.pull()
        a.push({"w": np.ones(2, np.float32)})       # round 0 done
        b = FLTrainerClient(srv.endpoint, token=srv.token)
        b.round = 0                                  # desynced trainer
        try:
            b.push({"w": np.zeros(2, np.float32)})
            raise AssertionError("stale push must NACK")
        except RuntimeError as e:
            assert "stale round" in str(e)
        a.close(), b.close()
    finally:
        srv.stop()


def test_fl_malformed_and_duplicate_pushes():
    """A malformed PUT (missing/mis-sized param) NACKs without touching
    round state, and a retried push from the SAME client replaces its
    contribution instead of completing the round alone."""
    srv = FLServer({"w": np.zeros(3, np.float32)}, n_trainers=2)
    try:
        a = FLTrainerClient(srv.endpoint, token=srv.token)
        a.pull()
        try:
            a.push({"bogus": np.ones(3, np.float32)})
            raise AssertionError("malformed push must NACK")
        except RuntimeError as e:
            assert "missing param" in str(e)
        try:
            a.push({"w": np.ones(7, np.float32)})
            raise AssertionError("mis-sized push must NACK")
        except RuntimeError as e:
            assert "size" in str(e)
        assert srv.round == 0 and not srv._pending

        # same-client retry must REPLACE, not double-count: a pushes
        # 2.0, then a RETRY on a fresh connection with the SAME client
        # id pushes 6.0 — the round must still wait for a second
        # trainer, and the merge must use the replaced value
        done = {}

        def push_as(key, client, val):
            client.push({"w": np.full(3, val, np.float32)})
            done[key] = True

        t1 = threading.Thread(target=push_as, args=("a1", a, 2.0),
                              daemon=True)
        t1.start()
        t1.join(0.5)
        assert t1.is_alive(), "single client completed a 2-trainer round"
        a_retry = FLTrainerClient(srv.endpoint, token=srv.token)
        a_retry._client_id = a._client_id
        a_retry.round = 0
        t2 = threading.Thread(target=push_as, args=("a2", a_retry, 6.0),
                              daemon=True)
        t2.start()
        t2.join(0.5)
        assert t2.is_alive(), "same-client retry was double-counted"
        b = FLTrainerClient(srv.endpoint, token=srv.token)
        b.round = 0
        b.push({"w": np.full(3, 4.0, np.float32)})
        t1.join(10), t2.join(10)
        assert done.get("a1") and done.get("a2") and srv.round == 1
        # merge of {a: 6.0 (replaced), b: 4.0} — 3.0 would mean the
        # stale 2.0 survived, 4.0 would mean a double-counted round
        np.testing.assert_allclose(srv.params["w"],
                                   np.full(3, 5.0, np.float32))
        a.close(), a_retry.close(), b.close()
    finally:
        srv.stop()


def test_fl_listen_and_serv_program():
    """An Executor serving an fl_listen_and_serv program behaves like
    the reference pserver: blocks, serves rounds from scope-held
    params, and stops when the server is severed."""
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        scope.set_var("fc_w", np.full(3, 2.0, np.float32))
    # bind the port ourselves via a probe server to avoid TOCTOU
    probe = FLServer({"x": np.zeros(1, np.float32)}, 1)
    ep, tok = probe.endpoint, probe.token
    probe.stop()
    prog = build_fl_server_program(ep, 1, ["fc_w"])
    assert any(op.type == "fl_listen_and_serv"
               for op in prog.global_block().ops)

    holder = {}

    def serve():
        exe = fluid.Executor()
        with fluid.scope_guard(scope):
            exe.run(prog)
        holder["done"] = True

    th = threading.Thread(target=serve, daemon=True)
    th.start()
    from paddle_tpu.distributed import wait_server_ready

    wait_server_ready([ep])
    import os

    c = FLTrainerClient(ep, token=os.environ.get("PADDLE_PS_TOKEN"))
    p = c.pull()
    np.testing.assert_allclose(p["fc_w"], np.full(3, 2.0))
    c.push({"fc_w": p["fc_w"] * 2})
    np.testing.assert_allclose(c.pull()["fc_w"], np.full(3, 4.0))
    c.close()
    # stopping the served instance unblocks the Executor promptly
    from paddle_tpu.distributed import fl_server as fl_mod

    fl_mod.SERVING[ep].stop()
    th.join(10)
    assert holder.get("done"), "exe.run(fl program) did not return"
