"""C API surface: a C program compiled against native/c_api.h must link and
run against the shipped shared objects (the reference's framework/c/c_api
capability + ABI regression guard for the ctypes bindings)."""

import os

import numpy as np
import subprocess

import pytest

from paddle_tpu import native

_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "paddle_tpu", "native")

_C_PROGRAM = r"""
#include <stdio.h>
#include <string.h>
#include "c_api.h"

int main(int argc, char** argv) {
  /* ps_store */
  int64_t t = pts_create(100, 4, 2, 0.0, 7);
  if (t < 0) return 1;
  int64_t ids[2] = {3, 42};
  float rows[8];
  if (pts_pull(t, ids, 2, rows) != 0) return 2;
  float grads[8] = {1, 1, 1, 1, 2, 2, 2, 2};
  if (pts_push_sgd(t, ids, 2, grads, 0.5) != 0) return 3;
  if (pts_pull(t, ids, 2, rows) != 0) return 4;
  if (rows[0] != -0.5f || rows[4] != -1.0f) return 5;

  /* channel */
  long long ch = chn_create(2);
  if (chn_put(ch, "hello", 5) != 0) return 6;
  char* out; long long n;
  if (chn_get(ch, &out, &n) != 0 || n != 5 || memcmp(out, "hello", 5))
    return 7;
  chn_free(out);
  chn_close(ch);
  if (chn_get(ch, &out, &n) != 1) return 8; /* closed + drained */
  chn_destroy(ch);

  /* tensor_io (scratch path from argv: parallel runs must not collide) */
  if (argc < 2) return 9;
  long long w = tio_open_write(argv[1]);
  if (!w) return 9;
  long long dims[2] = {2, 2};
  float data[4] = {1, 2, 3, 4};
  if (tio_write_tensor(w, "m", 0, 2, dims, data, 16) != 0) return 10;
  if (tio_close_write(w) != 0) return 11;
  long long r = tio_open_read(argv[1]);
  if (!r || tio_count(r) != 1) return 12;
  char name[64]; int dt; long long d2[16], nb;
  if (tio_entry_meta(r, 0, name, 64, &dt, d2, &nb) != 2) return 13;
  if (strcmp(name, "m") || dt != 0 || d2[0] != 2 || nb != 16) return 14;
  float back[4];
  if (tio_read_data(r, 0, back, 16) != 0 || back[3] != 4.0f) return 15;
  tio_close_read(r);

  /* data_feed */
  const char* text = "2 1 2 1 3\n";
  int64_t counts[2];
  long long lines = dfd_count(text, (long long)strlen(text), 2, counts);
  if (lines != 1 || counts[0] != 2 || counts[1] != 1) return 16;

  printf("C_API_OK\n");
  return 0;
}
"""


def test_c_program_against_header(tmp_path):
    import shutil

    # prebuilt .so files can exist without a compiler — need both here
    if shutil.which("g++") is None:
        pytest.skip("no g++")
    libs = [native.load_ps_store(), native.load_channel(),
            native.load_tensor_io(), native.load_data_feed()]
    if any(l is None for l in libs):
        pytest.skip("no toolchain")
    src = tmp_path / "capi_test.c"
    src.write_text(_C_PROGRAM)
    exe = tmp_path / "capi_test"
    sos = [os.path.join(_DIR, "lib%s.so" % n)
           for n in ("ps_store", "channel", "tensor_io", "data_feed")]
    subprocess.run(
        ["g++", "-x", "c", str(src), "-x", "none", "-o", str(exe),
         "-I", _DIR] + sos + ["-Wl,-rpath," + _DIR],
        check=True, capture_output=True)
    out = subprocess.run([str(exe), str(tmp_path / "capi_test.ptc")],
                         capture_output=True, text=True)
    assert out.returncode == 0, (out.returncode, out.stdout, out.stderr)
    assert "C_API_OK" in out.stdout


def _build_embedder(tmp_path, driver_c, exe_name):
    """Shared C-embedder harness: compile a driver against
    libpredictor.so and build the env its embedded interpreter needs
    (PYTHONHOME = the BASE stdlib — a venv has none — plus the venv's
    site-packages and this repo on the path). Returns (exe, env) or
    skips when the toolchain/library is unavailable."""
    import shutil
    import site
    import sys

    if shutil.which("g++") is None:
        pytest.skip("no g++")
    so = native.build_predictor_lib()
    if so is None:
        pytest.skip("libpredictor build unavailable (no python headers?)")
    drv_src = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           driver_c)
    drv = str(tmp_path / exe_name)
    subprocess.run(
        ["g++", "-x", "c", drv_src, "-x", "none", "-o", drv, so,
         "-Wl,-rpath," + os.path.dirname(so),
         "-Wl,-rpath," + "/usr/local/lib"],
        check=True, capture_output=True)
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONHOME"] = sys.base_prefix
    env["PYTHONPATH"] = os.pathsep.join(
        [repo] + [p for p in site.getsitepackages() if "site-packages" in p])
    env["JAX_PLATFORMS"] = "cpu"
    return drv, env


def test_c_predictor_serves_lenet(tmp_path):
    """A pure-C embedder (tests/c_predict_main.c) serves a saved conv
    model through the prd_* ABI: libpredictor.so hosts an embedded
    interpreter over the XLA serve path (reference inference/capi/)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers

    drv, env = _build_embedder(tmp_path, "c_predict_main.c", "c_predict")

    # tiny LeNet-ish model, saved as an inference model
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("img", shape=[1, 12, 12], dtype="float32")
        c1 = layers.conv2d(x, 4, 3, padding=1, act="relu")
        p1 = layers.pool2d(c1, 2, "max", pool_stride=2)
        prob = layers.softmax(layers.fc(p1, 10))
    exe = fluid.Executor()
    scope = fluid.Scope()
    model_dir = str(tmp_path / "lenet_model")
    with fluid.scope_guard(scope):
        exe.run(startup)
        fluid.io.save_inference_model(model_dir, ["img"], [prob], exe,
                                      main_program=main)
        # python-side reference on the SAME deterministic ramp the C
        # driver feeds: img[i] = (i % 17) / 17
        n = 1 * 12 * 12
        img = (np.arange(n) % 17 / 17.0).astype(np.float32).reshape(
            1, 1, 12, 12)
        (expect,) = exe.run(main, feed={"img": img}, fetch_list=[prob])
    expect = np.asarray(expect)

    out = subprocess.run([drv, model_dir, "img", "1", "12", "12"],
                         capture_output=True, text=True, env=env,
                         timeout=300)
    assert out.returncode == 0, (out.returncode, out.stdout[-500:],
                                 out.stderr[-2000:])
    lines = out.stdout.strip().splitlines()
    shape = [int(v) for v in lines[0].split(":")[1].split()]
    vals = np.array([float(v) for v in lines[1].split(":")[1].split()],
                    np.float32)
    assert shape == [1, 10]
    np.testing.assert_allclose(vals, expect.ravel(), rtol=1e-4, atol=1e-5)


def test_c_trainer_trains_and_checkpoints(tmp_path):
    """A pure-C embedder (tests/c_train_main.c) TRAINS through the
    trn_* ABI: loads a fluid.save'd train program (backward + optimizer
    ops included), steps it with float32 features + int64 labels, sees
    the loss decrease, and checkpoints back out; python then reloads
    the C-written checkpoint and the trained loss is preserved
    (reference fluid/train/demo/demo_trainer.cc capability)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers, optimizer

    drv, env = _build_embedder(tmp_path, "c_train_main.c", "c_train")

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 11
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        logits = layers.fc(x, 3)
        raw = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        loss = main.current_block().create_var(
            name="loss", shape=(1,), dtype="float32")
        layers.assign(raw, loss)
        optimizer.SGD(learning_rate=0.5).minimize(raw)
    exe = fluid.Executor()
    scope = fluid.Scope()
    model_path = str(tmp_path / "trainable" / "model")
    with fluid.scope_guard(scope):
        exe.run(startup)
        fluid.io.save(main, model_path)

    out_path = str(tmp_path / "trained" / "model")
    out = subprocess.run([drv, model_path, out_path, "40"],
                         capture_output=True, text=True, env=env,
                         timeout=300)
    assert out.returncode == 0, (out.returncode, out.stdout[-500:],
                                 out.stderr[-2000:])
    toks = out.stdout.split()
    first, last = float(toks[1]), float(toks[3])
    assert last < first * 0.9, (first, last)

    # the C-written checkpoint reloads in python with the trained state
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        fluid.io.load(main, out_path)
        # same deterministic batch as the C driver
        xv = np.zeros((16, 4), np.float32)
        for i in range(16):
            for j in range(4):
                xv[i, j] = ((i * 7 + j * 3) % 11) / 11.0
        lv = np.array([[int(np.argmax(xv[i]) % 3)] for i in range(16)],
                      np.int64)
        (l2,) = exe.run(main, feed={"x": xv, "label": lv},
                        fetch_list=["loss"])
    assert float(np.asarray(l2).ravel()[0]) <= last * 1.05 + 1e-3


def test_c_program_graph_driver(tmp_path):
    """A pure-C driver (tests/c_program_main.c) parses, lints, prunes,
    and round-trips a REAL serialized program through the prg_* ABI —
    the reference exercises its desc/prune tier from native tests the
    same way (framework/prune_test.cc)."""
    import shutil

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers

    if shutil.which("g++") is None:
        pytest.skip("no g++")
    if native.load_program_graph() is None:
        pytest.skip("no toolchain")

    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = layers.data("x", shape=[4])
        h = layers.fc(x, size=3, act="relu")
        out = layers.mean(h)
        layers.reduce_sum(h)  # prunable tail
    bytes_path = tmp_path / "prog.bin"
    bytes_path.write_bytes(main.serialize_to_string())

    drv_src = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "c_program_main.c")
    so = os.path.join(_DIR, "libprogram_graph.so")
    drv = str(tmp_path / "c_program")
    subprocess.run(
        ["g++", "-x", "c", drv_src, "-x", "none", "-o", drv, so,
         "-Wl,-rpath," + _DIR],
        check=True, capture_output=True)
    r = subprocess.run([drv, str(bytes_path), out.name],
                       capture_output=True, text=True)
    assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
    assert "C_PROGRAM_OK" in r.stdout
    # the C-side prune agrees with the Python prune it mirrors
    py_pruned = len(main._prune([out]).global_block().ops)
    assert ("pruned_ops=%d" % py_pruned) in r.stdout
