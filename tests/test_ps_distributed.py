"""PS tier (host-resident tables) + round-3 fix coverage.

Reference analogues: pslib pull/push (``framework/fleet/fleet_wrapper.h``),
async Communicator (``operators/distributed/communicator.h:285``), GeoSGD
(``:332``), distributed_lookup_table op
(``operators/distributed_ops/distributed_lookup_table_op.cc``).
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, optimizer
from paddle_tpu.distributed import ps


@pytest.fixture(autouse=True)
def _clean_tables():
    ps.reset_tables()
    yield
    ps.reset_tables()


@pytest.mark.parametrize("force_numpy", [True, False])
def test_embedding_table_pull_push(force_numpy):
    t = ps.EmbeddingTable(10, 4, seed=1, force_numpy=force_numpy)
    base = t.dump()
    out = t.pull([2, 5, 2])
    np.testing.assert_allclose(out[0], base[2], rtol=1e-6)
    np.testing.assert_allclose(out[1], base[5], rtol=1e-6)
    # duplicate ids in one push must accumulate
    g = np.ones((3, 4), np.float32)
    t.push([2, 5, 2], g, lr=0.1)
    now = t.dump()
    np.testing.assert_allclose(now[2], base[2] - 0.2, rtol=1e-5)
    np.testing.assert_allclose(now[5], base[5] - 0.1, rtol=1e-5)
    untouched = [i for i in range(10) if i not in (2, 5)]
    np.testing.assert_array_equal(now[untouched], base[untouched])


@pytest.mark.parametrize("force_numpy", [True, False])
def test_embedding_table_adagrad(force_numpy):
    t = ps.EmbeddingTable(6, 2, seed=2, force_numpy=force_numpy)
    base = t.dump()
    g = np.full((1, 2), 2.0, np.float32)
    t.push([3], g, lr=0.5, optimizer="adagrad", eps=1e-6)
    # accum = g^2 = 4 -> step = lr * g / (sqrt(4)+eps) = 0.5
    np.testing.assert_allclose(t.dump()[3], base[3] - 0.5, rtol=1e-4)


def test_embedding_table_dump_load_roundtrip():
    t = ps.EmbeddingTable(8, 3, seed=3)
    snap = t.dump()
    t.push([0, 1], np.ones((2, 3), np.float32), lr=1.0)
    assert np.abs(t.dump() - snap).max() > 0
    t.load(snap)
    np.testing.assert_array_equal(t.dump(), snap)


def test_async_pusher_applies_and_flushes():
    t = ps.EmbeddingTable(10, 2, seed=4, force_numpy=True)
    base = t.dump()
    p = ps.AsyncPusher(t)
    for _ in range(5):
        p.push(np.array([1], np.int64), np.ones((1, 2), np.float32), lr=0.1)
    p.flush()
    np.testing.assert_allclose(t.dump()[1], base[1] - 0.5, rtol=1e-5)
    p.stop()


def test_async_pusher_error_surfaces_no_deadlock():
    """A failing push (out-of-range id) must not kill the worker silently:
    flush() must return (no deadlock) and re-raise the recorded error."""
    t = ps.EmbeddingTable(4, 2, seed=5, force_numpy=True)
    p = ps.AsyncPusher(t)
    p.push(np.array([99], np.int64), np.ones((1, 2), np.float32))  # bad id
    with pytest.raises(IndexError):
        p.flush()
    # worker survived: subsequent pushes still work
    base = t.dump()
    p.push(np.array([0], np.int64), np.ones((1, 2), np.float32), lr=0.1)
    p.flush()
    np.testing.assert_allclose(t.dump()[0], base[0] - 0.1, rtol=1e-5)
    p.stop()


def test_geo_communicator_syncs_every_k():
    t = ps.EmbeddingTable(5, 2, seed=6, force_numpy=True)
    geo = ps.GeoCommunicator(t, k_steps=3)
    base = t.dump()
    geo.local[1] += 1.0
    assert not geo.maybe_sync() and not geo.maybe_sync()
    np.testing.assert_array_equal(t.dump(), base)  # not yet pushed
    assert geo.maybe_sync()  # step 3: delta pushed
    np.testing.assert_allclose(t.dump()[1], base[1] + 1.0, rtol=1e-5)
    np.testing.assert_array_equal(t.dump()[0], base[0])


def test_distributed_lookup_table_e2e():
    """BASELINE config 4 substrate: a model whose embedding lives in the
    host PS table trains end-to-end — forward pulls via host callback,
    backward pushes the SelectedRows cotangent, rows move, loss falls."""
    vocab, dim = 30, 8
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 7
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[3], dtype="int64")
        label = layers.data("label", shape=[1], dtype="float32")
        emb = layers.embedding(
            ids, size=[vocab, dim], is_distributed=True, table_lr=0.1,
            param_attr=fluid.ParamAttr(name="ps_emb"))
        pooled = layers.reduce_sum(emb, dim=1)
        pred = layers.fc(pooled, size=1)
        loss = layers.mean(layers.square_error_cost(pred, label))
        optimizer.SGD(learning_rate=0.1).minimize(loss)

    assert ps.has_table("ps_emb")
    table = ps.get_table("ps_emb")
    base = table.dump()
    ad = next(op for op in main.global_block().ops if op.type == "autodiff")
    assert ad.attr("dist_push"), "autodiff lost the PS push marker"

    rng = np.random.RandomState(0)
    feed = {"ids": rng.randint(0, vocab, (16, 3)).astype(np.int64),
            "label": rng.rand(16, 1).astype(np.float32)}
    exe = fluid.Executor()
    losses = []
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(8):
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(lv).ravel()[0]))
    assert losses[-1] < losses[0]
    now = table.dump()
    touched = np.unique(feed["ids"])
    assert np.abs(now[touched] - base[touched]).max() > 0
    untouched = np.setdiff1d(np.arange(vocab), touched)
    np.testing.assert_array_equal(now[untouched], base[untouched])


def test_sparse_param_demoted_on_use_before_lookup():
    """A param consumed by another op BEFORE the is_sparse lookup in program
    order must still get a DENSE gradient (order-independent demotion)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[2], dtype="int64")
        emb = layers.embedding(ids, size=[12, 4], is_sparse=True,
                               param_attr=fluid.ParamAttr(name="w_pre"))
        wvar = main.global_block().var("w_pre")
        wsum = layers.reduce_sum(wvar)
        loss = layers.mean(layers.reduce_sum(emb, dim=-1)) + wsum
    block = main.global_block()
    # move the reduce_sum(w_pre) op BEFORE the lookup op
    from paddle_tpu.embedding.lookup import SPARSE_LOOKUP_TYPES

    lookup_i = next(i for i, o in enumerate(block.ops)
                    if o.type in SPARSE_LOOKUP_TYPES)
    red_i = next(i for i, o in enumerate(block.ops)
                 if o.type.startswith("reduce_sum")
                 and "w_pre" in o.input_arg_names())
    op = block.ops.pop(red_i)
    block.ops.insert(lookup_i, op)
    main._bump()
    with fluid.program_guard(main, startup):
        optimizer.SGD(learning_rate=0.1).minimize(loss)
    gvar = block.var("w_pre@GRAD")
    assert gvar.type != "selected_rows", (
        "param with a pre-lookup consumer must take the dense grad path")
    # and it still trains correctly
    exe = fluid.Executor()
    feed = {"ids": np.array([[1, 2]], np.int64)}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=[loss])


def test_sparse_grad_dp_allgather_matches_dense_baseline():
    """GradAllReduce over a SelectedRows grad must NOT positionally sum
    values across ranks (ranks hold different rows); the allgather path
    must reproduce the single-device dense result exactly."""
    vocab, dim, lr = 40, 4, 0.5
    feed = {"ids": np.arange(16, dtype=np.int64).reshape(16, 1) % 11,
            "w8": np.linspace(0.5, 1.5, 16).astype(np.float32).reshape(16, 1)}

    def build(seed, sparse):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = seed
        with fluid.program_guard(main, startup):
            ids = layers.data("ids", shape=[1], dtype="int64")
            w8 = layers.data("w8", shape=[1], dtype="float32")
            emb = layers.embedding(ids, size=[vocab, dim], is_sparse=sparse,
                                   param_attr=fluid.ParamAttr(name="emb_dp"))
            emb = layers.reshape(emb, [-1, dim])
            loss = layers.mean(
                layers.reduce_sum(emb * emb, dim=-1, keep_dim=True) * w8)
        return main, startup, loss

    # single-device dense baseline on the full batch
    main, startup, loss = build(21, sparse=False)
    with fluid.program_guard(main, startup):
        optimizer.SGD(lr).minimize(loss)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(3):
            exe.run(main, feed=feed, fetch_list=[loss])
        w_base = np.asarray(exe.run(main, feed=feed,
                                    fetch_list=["emb_dp"])[0])

    # 8-rank explicit-collective mode with sparse grads
    from paddle_tpu.fluid.transpiler.collective import GradAllReduce

    main2, startup2, loss2 = build(21, sparse=True)
    with fluid.program_guard(main2, startup2):
        optimizer.SGD(lr).minimize(loss2)
    GradAllReduce(nranks=8).transpile(startup2, main2)
    types = [op.type for op in main2.global_block().ops]
    assert "c_allgather" in types, "sparse grad must ride allgather"
    compiled = fluid.CompiledProgram(main2).with_explicit_collectives(
        loss_name=loss2.name)
    exe2 = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe2.run(startup2)
        for _ in range(3):
            exe2.run(compiled, feed=feed, fetch_list=[loss2])
        w_dp = np.asarray(exe2.run(compiled, feed=feed,
                                   fetch_list=["emb_dp"])[0])
    np.testing.assert_allclose(w_dp, w_base, rtol=1e-5, atol=1e-6)


def test_c_allreduce_prod_zeros_and_negatives():
    """Product all-reduce must be exact for zero and negative entries (the
    old exp(psum(log)) lowering NaN'd on them)."""
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = layers.data("x", shape=[2], dtype="float32")
        out = main.global_block().create_var(name="prod_out", shape=(-1, 2),
                                             dtype="float32")
        main.global_block().append_op(
            "c_allreduce_prod", inputs={"X": [x]}, outputs={"Out": [out]},
            attrs={"ring_id": 0})
    xv = np.array([[-1.0, 2.0], [3.0, 0.0], [1.0, 1.0], [2.0, -2.0],
                   [1.0, 1.0], [1.0, 1.0], [-1.0, 1.0], [1.0, 1.0]],
                  np.float32)
    compiled = fluid.CompiledProgram(main).with_explicit_collectives()
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        (r,) = exe.run(compiled, feed={"x": xv}, fetch_list=["prod_out"])
    r = np.asarray(r)
    expect = np.prod(xv, axis=0)  # elementwise product across the 8 ranks
    np.testing.assert_allclose(r[0], expect, rtol=1e-5)


def test_model_average_windowed():
    """ModelAverage must honor its window: the served average covers the
    current + previous windows only, restarting every W steps."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 5
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[2], dtype="float32")
        y = layers.fc(x, size=1, param_attr=fluid.ParamAttr(name="ma_w"),
                      bias_attr=False)
        loss = layers.mean(y)
        optimizer.SGD(learning_rate=0.1).minimize(loss)
        ma = optimizer.ModelAverage(1.0, min_average_window=3,
                                    max_average_window=3)
    exe = fluid.Executor()
    feed = {"x": np.ones((4, 2), np.float32)}
    history = []
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(7):
            # fetch the post-update param in the SAME run (no extra steps)
            _, w = exe.run(main, feed=feed, fetch_list=[loss, "ma_w"])
            history.append(np.asarray(w).copy())
        with ma.apply(exe):
            from paddle_tpu.fluid.executor import global_scope

            served = np.asarray(global_scope().find_var("ma_w"))
    # emulate the gated recurrence exactly: W = clip(1.0*t, 3, 3) = 3
    s = sp = np.zeros_like(history[0])
    n = on = 0.0
    for p in history:
        s1, n1 = s + p, n + 1
        if n1 >= 3:
            sp, on, s, n = s1, n1, np.zeros_like(s1), 0.0
        else:
            s, n = s1, n1
    expect = (s + sp) / (n + on)
    np.testing.assert_allclose(served, expect, rtol=1e-5)
    # the running sum is windowed: the served value is NOT the mean of the
    # whole history (the unbounded-sum bug)
    assert np.abs(served - np.mean(history, axis=0)).max() > 1e-7


def test_distributed_embedding_amp_scale_unwound():
    """The PS push must be divided by the AMP loss scale (static and
    dynamic): table rows after one step must match the scale-1.0 baseline."""
    from paddle_tpu.fluid.contrib import mixed_precision

    vocab, dim = 20, 4
    feed = {"ids": np.array([[1, 2], [3, 1]], np.int64),
            "label": np.array([[0.5], [1.0]], np.float32)}

    def run(mode):
        ps.reset_tables()
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 11
        with fluid.program_guard(main, startup):
            ids = layers.data("ids", shape=[2], dtype="int64")
            label = layers.data("label", shape=[1], dtype="float32")
            emb = layers.embedding(ids, size=[vocab, dim],
                                   is_distributed=True, table_lr=0.2,
                                   param_attr=fluid.ParamAttr(name="amp_ps"))
            pooled = layers.reduce_sum(emb, dim=1)
            pred = layers.fc(pooled, size=1,
                             param_attr=fluid.ParamAttr(name="amp_ps_fc"))
            loss = layers.mean(layers.square_error_cost(pred, label))
            opt = optimizer.SGD(learning_rate=0.1)
            if mode == "static":
                opt = mixed_precision.decorate(opt, init_loss_scaling=128.0)
            elif mode == "dynamic":
                opt = mixed_precision.decorate(
                    opt, init_loss_scaling=64.0,
                    use_dynamic_loss_scaling=True)
            opt.minimize(loss)
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            exe.run(main, feed=feed, fetch_list=[loss])
        return ps.get_table("amp_ps").dump()

    # AMP computes in bfloat16, so expect rounding-level differences only —
    # a missed unscale would be off by 64x/128x, far outside this tolerance
    base = run("none")
    np.testing.assert_allclose(run("static"), base, rtol=0.05, atol=2e-3)
    np.testing.assert_allclose(run("dynamic"), base, rtol=0.05, atol=2e-3)


def test_distributed_embedding_padding_and_startup_reset():
    vocab, dim, pad = 15, 4, 0
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 9
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[3], dtype="int64")
        emb = layers.embedding(ids, size=[vocab, dim], is_distributed=True,
                               padding_idx=pad, table_lr=0.5,
                               param_attr=fluid.ParamAttr(name="pad_ps"))
        loss = layers.mean(layers.reduce_sum(emb * emb, dim=-1))
        optimizer.SGD(learning_rate=0.5).minimize(loss)
    table = ps.get_table("pad_ps")
    base = table.dump()
    exe = fluid.Executor()
    feed = {"ids": np.array([[0, 2, 0]], np.int64)}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (ev,) = exe.run(main, feed=feed, fetch_list=[emb])
        # padded positions read zeros
        ev = np.asarray(ev)
        assert np.abs(ev[0, 0]).max() == 0 and np.abs(ev[0, 2]).max() == 0
        assert np.abs(ev[0, 1]).max() > 0
    after = table.dump()
    # the padding row received NO push; row 2 did
    np.testing.assert_array_equal(after[pad], base[pad])
    assert np.abs(after[2] - base[2]).max() > 0
    # running startup again resets the table to its init distribution
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
    np.testing.assert_array_equal(table.dump(), base)


def test_table_shape_mismatch_raises():
    ps.ensure_table("shape_t", 10, 4)
    with pytest.raises(ValueError):
        ps.ensure_table("shape_t", 20, 4)
    with pytest.raises(ValueError):
        ps.register_table("shape_t", ps.EmbeddingTable(10, 8))
    # same shape is fine (table reused)
    t = ps.ensure_table("shape_t", 10, 4)
    assert t is ps.get_table("shape_t")


def test_table_reinit_resets_adagrad_state():
    for force_numpy in (True, False):
        t = ps.EmbeddingTable(6, 2, seed=7, force_numpy=force_numpy)
        base = t.dump()
        t.push([1], np.full((1, 2), 2.0, np.float32), lr=0.5,
               optimizer="adagrad")
        t.reinit()
        np.testing.assert_array_equal(t.dump(), base)
        # accumulator was cleared: identical push gives the identical step
        t.push([1], np.full((1, 2), 2.0, np.float32), lr=0.5,
               optimizer="adagrad")
        np.testing.assert_allclose(t.dump()[1], base[1] - 0.5, rtol=1e-4)
