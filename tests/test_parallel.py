"""Data-parallel execution over the virtual 8-device mesh.

The reference's analogue: ``test_parallel_executor_mnist.py`` — run the same
model with/without ParallelExecutor and compare losses (SURVEY §4)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, optimizer


def _build(seed):
    main = fluid.Program()
    startup = fluid.Program()
    main.random_seed = seed
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[16], dtype="float32")
        label = layers.data(name="label", shape=[1], dtype="int64")
        h = layers.fc(x, size=32, act="relu")
        logits = layers.fc(h, size=4)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def test_dp_matches_single_device():
    rng = np.random.RandomState(0)
    xv = rng.rand(16, 16).astype(np.float32)
    yv = rng.randint(0, 4, (16, 1)).astype(np.int64)

    losses_single, losses_dp = [], []

    main, startup, loss = _build(3)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(5):
            (lv,) = exe.run(main, feed={"x": xv, "label": yv}, fetch_list=[loss])
            losses_single.append(float(lv))

    main2, startup2, loss2 = _build(3)
    compiled = fluid.CompiledProgram(main2).with_data_parallel(loss_name=loss2.name)
    exe2 = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe2.run(startup2)
        for _ in range(5):
            (lv,) = exe2.run(compiled, feed={"x": xv, "label": yv}, fetch_list=[loss2])
            losses_dp.append(float(lv))

    # same seed, same data => same loss trajectory (GSPMD DP is exact for
    # mean-reduced losses)
    np.testing.assert_allclose(losses_single, losses_dp, rtol=1e-4)
    assert losses_single[-1] < losses_single[0]


def test_dp_uses_all_devices():
    import jax

    main, startup, loss = _build(5)
    compiled = fluid.CompiledProgram(main).with_data_parallel(loss_name=loss.name)
    assert compiled.mesh.shape["dp"] == len(jax.devices())


def test_build_strategy_enable_inplace_gates_donation(monkeypatch):
    """enable_inplace must gate donate_argnums in the compiled step (CPU
    ignores donation at runtime, so assert the jit wiring directly) and
    the no-donation path must still train."""
    import numpy as np

    import jax

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers, optimizer

    recorded = []
    real_jit = jax.jit

    def spy_jit(*args, **kwargs):
        if "donate_argnums" in kwargs:
            recorded.append(kwargs["donate_argnums"])
        return real_jit(*args, **kwargs)

    def build():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("ip_x", [4])
            y = layers.data("ip_y", [1])
            loss = layers.reduce_mean(layers.square(layers.fc(x, 1) - y))
            optimizer.SGD(learning_rate=0.1).minimize(loss)
        return main, startup, loss

    # compiler.py imports jax inside functions, so patching the module
    # attribute is enough
    monkeypatch.setattr(jax, "jit", spy_jit)

    rng = np.random.RandomState(0)
    w = rng.rand(4, 1).astype(np.float32)
    for inplace, expect in ((False, ()), (True, (0,))):
        main, startup, loss = build()
        bs = fluid.BuildStrategy()
        bs.enable_inplace = inplace
        prog = fluid.CompiledProgram(main, build_strategy=bs) \
            .with_data_parallel(loss_name=loss.name)
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            # clear AFTER startup: the single-device startup jit always
            # donates and would satisfy the True assertion vacuously
            recorded.clear()
            losses = []
            for _ in range(10):
                xb = rng.rand(8, 4).astype(np.float32)
                (lv,) = exe.run(prog, feed={"ip_x": xb, "ip_y": xb @ w},
                                fetch_list=[loss])
                losses.append(float(np.asarray(lv).ravel()[0]))
            assert losses[-1] < losses[0]
        assert expect in recorded, (inplace, recorded)
