"""Remaining Appendix-A layers (layers/extras.py + ops/misc_ops.py):
LoD rebinding, SelectedRows utilities, CVM, PSRoI pooling, chunk_eval,
adaptive_pool3d, resize-short, scatter_nd, crop_tensor, fsp_matrix."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers

RNG = np.random.RandomState(5)


def _run(build, feed):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        fetch = build()
        if not isinstance(fetch, (list, tuple)):
            fetch = [fetch]
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        res = exe.run(main, feed=feed, fetch_list=list(fetch))
    return [np.asarray(r) for r in res]


def test_lod_reset_rebinds_lengths():
    x = np.arange(12, dtype=np.float32).reshape(6, 2)

    def build():
        xv = layers.data("x", [2], dtype="float32", lod_level=1)
        y = layers.lod_reset(xv, target_lod=[0, 2, 6])
        return [layers.sequence_pool(y, "sum")]

    (out,) = _run(build, {"x": fluid.create_lod_tensor(x, [[3, 3]])})
    # pools follow the NEW lod [2, 4], not the fed [3, 3]
    np.testing.assert_allclose(out, [x[:2].sum(0), x[2:6].sum(0)],
                               rtol=1e-6)


def test_unique_with_counts():
    x = np.array([2, 3, 3, 1, 5, 3], np.int64)

    def build():
        xv = layers.data("x", x.shape, append_batch_size=False,
                         dtype="int64")
        return list(layers.unique_with_counts(xv))

    out, index, count = _run(build, {"x": x})
    assert out.shape == (6,)
    np.testing.assert_array_equal(out[index], x)  # inverse reconstructs
    assert count[list(out).index(3)] == 3


def test_merge_and_densify_selected_rows():
    """An is_sparse embedding grad merges duplicates and densifies to
    the dense-path gradient."""
    ids = np.array([[1], [3], [1]], np.int64)

    def build(sparse):
        xv = layers.data("ids", ids.shape, append_batch_size=False,
                         dtype="int64")
        emb = layers.embedding(xv, size=[6, 2], is_sparse=sparse,
                               param_attr=fluid.ParamAttr(
                                   name="emb_w_%d" % sparse))
        loss = layers.reduce_sum(layers.square(emb))
        grads = fluid.backward.append_backward(loss)
        gvar = dict((p.name, g) for p, g in grads)["emb_w_%d" % sparse]
        if sparse:
            merged = layers.merge_selected_rows(gvar)
            return [layers.get_tensor_from_selected_rows(merged, height=6)]
        return [gvar]

    (dense_grad,) = _run(lambda: build(False), {"ids": ids})
    (sparse_dense,) = _run(lambda: build(True), {"ids": ids})
    np.testing.assert_allclose(sparse_dense, dense_grad, rtol=1e-5)


def test_cvm():
    x = np.array([[3.0, 1.0, 0.5, 0.6]], np.float32)

    def build(use):
        xv = layers.data("x", x.shape, append_batch_size=False)
        return [layers.cvm(xv, use_cvm=use)]

    (kept,) = _run(lambda: build(True), {"x": x})
    np.testing.assert_allclose(
        kept[0, :2], [np.log(4.0), np.log(2.0) - np.log(4.0)], rtol=1e-5)
    np.testing.assert_allclose(kept[0, 2:], x[0, 2:])
    (stripped,) = _run(lambda: build(False), {"x": x})
    np.testing.assert_allclose(stripped, x[:, 2:])


def test_psroi_pool_position_sensitivity():
    """Each output channel/bin reads its OWN input channel: constant
    per-channel planes come back exactly."""
    out_c, ph, pw = 2, 2, 2
    C = out_c * ph * pw
    x = np.zeros((1, C, 4, 4), np.float32)
    for c in range(C):
        x[0, c] = c + 1.0
    rois = np.array([[0, 0, 4, 4]], np.float32)

    def build():
        xv = layers.data("x", x.shape, append_batch_size=False)
        r = layers.data("r", rois.shape, append_batch_size=False)
        return [layers.psroi_pool(xv, r, out_c, 1.0, ph, pw)]

    (out,) = _run(build, {"x": x, "r": rois})
    assert out.shape == (1, out_c, ph, pw)
    for c in range(out_c):
        for i in range(ph):
            for j in range(pw):
                assert out[0, c, i, j] == (c * ph + i) * pw + j + 1.0


def test_chunk_eval_iob():
    """2 types, IOB: tags B0=0 I0=1 B1=2 I1=3 O=4."""
    label = np.array([0, 1, 4, 2, 3, 4], np.int64)
    inf = np.array([0, 1, 4, 2, 4, 4], np.int64)  # 2nd chunk cut short

    def build():
        iv = layers.data("i", label.shape, append_batch_size=False,
                         dtype="int64")
        lv = layers.data("l", label.shape, append_batch_size=False,
                         dtype="int64")
        return list(layers.chunk_eval(iv, lv, "IOB", 2))

    p, r, f1, ni, nl, nc = _run(build, {"i": inf, "l": label})
    assert ni == 2 and nl == 2 and nc == 1
    np.testing.assert_allclose(p, 0.5)
    np.testing.assert_allclose(r, 0.5)
    np.testing.assert_allclose(f1, 0.5)


def test_adaptive_pool3d():
    x = RNG.rand(1, 2, 4, 4, 4).astype(np.float32)

    def build():
        xv = layers.data("x", x.shape, append_batch_size=False)
        return [layers.adaptive_pool3d(xv, 2, pool_type="avg")]

    (out,) = _run(build, {"x": x})
    assert out.shape == (1, 2, 2, 2, 2)
    np.testing.assert_allclose(out[0, 0, 0, 0, 0],
                               x[0, 0, :2, :2, :2].mean(), rtol=1e-5)


def test_image_resize_short_and_crop_tensor_and_scatter_nd():
    x = RNG.rand(1, 3, 4, 8).astype(np.float32)
    idx = np.array([[1], [3]], np.int64)
    upd = np.ones((2, 2), np.float32)

    def build():
        xv = layers.data("x", x.shape, append_batch_size=False)
        short = layers.image_resize_short(xv, 8)
        cropped = layers.crop_tensor(xv, shape=[-1, 2, 2, -1],
                                     offsets=[0, 1, 1, 2])
        iv = layers.data("i", idx.shape, append_batch_size=False,
                         dtype="int64")
        uv = layers.data("u", upd.shape, append_batch_size=False)
        sc = layers.scatter_nd(iv, uv, [5, 2])
        return [short, cropped, sc]

    short, cropped, sc = _run(build, {"x": x, "i": idx, "u": upd})
    assert short.shape == (1, 3, 8, 16)  # short side 4 -> 8, aspect kept
    np.testing.assert_allclose(cropped, x[:, 1:3, 1:3, 2:], rtol=1e-6)
    ref = np.zeros((5, 2), np.float32)
    ref[[1, 3]] = 1.0
    np.testing.assert_allclose(sc, ref)


def test_fsp_matrix():
    a = RNG.rand(2, 3, 4, 4).astype(np.float32)
    b = RNG.rand(2, 5, 4, 4).astype(np.float32)

    def build():
        av = layers.data("a", a.shape, append_batch_size=False)
        bv = layers.data("b", b.shape, append_batch_size=False)
        return [layers.fsp_matrix(av, bv)]

    (out,) = _run(build, {"a": a, "b": b})
    ref = np.einsum("nchw,ndhw->ncd", a, b) / 16.0
    np.testing.assert_allclose(out, ref, rtol=1e-4)


def test_unsupported_apis_raise_with_alternatives():
    for fn, kw in ((layers.similarity_focus, {}),
                   (layers.prroi_pool, {}),
                   (layers.deformable_conv, {}),
                   (layers.filter_by_instag, {})):
        with pytest.raises(NotImplementedError):
            fn()
    # IfElse / DynamicRNN are real since round 4 (test_control_flow.py);
    # constructing them must NOT raise anymore
    assert layers.IfElse(None) is not None
    assert layers.DynamicRNN(maxlen=4) is not None


def test_lod_append_sets_innermost_level():
    x = np.arange(8, dtype=np.float32).reshape(4, 2)

    def build():
        xv = layers.data("x", [2], dtype="float32", lod_level=1)
        y = layers.lod_append(xv, level=[0, 1, 4])
        return [layers.sequence_pool(y, "sum")]

    (out,) = _run(build, {"x": fluid.create_lod_tensor(x, [[4]])})
    np.testing.assert_allclose(out, [x[:1].sum(0), x[1:4].sum(0)],
                               rtol=1e-6)


def test_chunk_eval_excluded_types():
    label = np.array([0, 1, 2, 3], np.int64)  # one type-0 + one type-1
    inf = np.array([0, 1, 2, 3], np.int64)

    def build():
        iv = layers.data("i", label.shape, append_batch_size=False,
                         dtype="int64")
        lv = layers.data("l", label.shape, append_batch_size=False,
                         dtype="int64")
        return list(layers.chunk_eval(iv, lv, "IOB", 2,
                                      excluded_chunk_types=[0]))

    p, r, f1, ni, nl, nc = _run(build, {"i": inf, "l": label})
    assert ni == 1 and nl == 1 and nc == 1  # type-0 chunk not counted
