"""Numeric gradient checks (finite differences vs the autodiff replay)
for the newer differentiable lowerings — the reference's per-op
``check_grad`` discipline (``unittests/op_test.py:135``) extended to the
round-3 op families. Tensors stay tiny: every perturbation re-runs the
program."""

import numpy as np
import pytest

from op_test import OpTest

RNG = np.random.RandomState(7)


class TestRoiAlignGrad(OpTest):
    op_type = "roi_align"

    def setup_method(self, _):
        self.inputs = {"X": RNG.rand(1, 1, 4, 4).astype(np.float32),
                       "ROIs": np.array([[0.5, 0.5, 3.0, 3.0]],
                                        np.float32)}
        self.attrs = {"pooled_height": 2, "pooled_width": 2,
                      "spatial_scale": 1.0, "sampling_ratio": 2}
        self.outputs = {"Out": np.zeros((1, 1, 2, 2), np.float32)}

    def test_grad(self):
        self.check_grad(["X"], "Out")


class TestGridSamplerGrad(OpTest):
    op_type = "grid_sampler"

    def setup_method(self, _):
        ys, xs = np.meshgrid(np.linspace(-0.7, 0.7, 3),
                             np.linspace(-0.7, 0.7, 3), indexing="ij")
        grid = np.stack([xs, ys], -1)[None].astype(np.float32)
        self.inputs = {"X": RNG.rand(1, 1, 4, 4).astype(np.float32),
                       "Grid": grid}
        self.outputs = {"Output": np.zeros((1, 1, 3, 3), np.float32)}

    def test_grad(self):
        self.check_grad(["X", "Grid"], "Output")


class TestConv2dTransposeGrad(OpTest):
    op_type = "conv2d_transpose"

    def setup_method(self, _):
        self.inputs = {"Input": RNG.rand(1, 2, 3, 3).astype(np.float32),
                       "Filter": RNG.rand(2, 2, 2, 2).astype(np.float32)}
        self.attrs = {"strides": [2, 2], "paddings": [0, 0],
                      "dilations": [1, 1], "groups": 1}
        self.outputs = {"Output": np.zeros((1, 2, 6, 6), np.float32)}

    def test_grad(self):
        self.check_grad(["Input", "Filter"], "Output")


class TestMaxoutGrad(OpTest):
    op_type = "maxout"

    def setup_method(self, _):
        self.inputs = {"X": RNG.rand(1, 4, 2, 2).astype(np.float32)}
        self.attrs = {"groups": 2}
        self.outputs = {"Out": np.zeros((1, 2, 2, 2), np.float32)}

    def test_grad(self):
        self.check_grad(["X"], "Out")


class TestPixelShuffleGrad(OpTest):
    op_type = "pixel_shuffle"

    def setup_method(self, _):
        self.inputs = {"X": RNG.rand(1, 4, 2, 2).astype(np.float32)}
        self.attrs = {"upscale_factor": 2}
        self.outputs = {"Out": np.zeros((1, 1, 4, 4), np.float32)}

    def test_grad(self):
        self.check_grad(["X"], "Out")


class TestTemporalShiftGrad(OpTest):
    op_type = "temporal_shift"

    def setup_method(self, _):
        self.inputs = {"X": RNG.rand(4, 4, 2, 2).astype(np.float32)}
        self.attrs = {"seg_num": 2, "shift_ratio": 0.25}
        self.outputs = {"Out": np.zeros((4, 4, 2, 2), np.float32)}

    def test_grad(self):
        self.check_grad(["X"], "Out")


class TestHuberLossGrad(OpTest):
    op_type = "huber_loss"

    def setup_method(self, _):
        self.inputs = {"X": RNG.rand(4, 3).astype(np.float32),
                       "Y": RNG.rand(4, 3).astype(np.float32)}
        self.attrs = {"delta": 0.4}
        self.outputs = {"Out": np.zeros((4, 3), np.float32)}

    def test_grad(self):
        self.check_grad(["X"], "Out")


class TestKLDivLossGrad(OpTest):
    op_type = "kldiv_loss"

    def setup_method(self, _):
        self.inputs = {"X": RNG.rand(3, 4).astype(np.float32),
                       "Target": (RNG.rand(3, 4) + 0.2).astype(
                           np.float32)}
        self.attrs = {"reduction": "none"}
        self.outputs = {"Loss": np.zeros((3, 4), np.float32)}

    def test_grad(self):
        self.check_grad(["X"], "Loss")


class TestLogSoftmaxGrad(OpTest):
    op_type = "log_softmax"

    def setup_method(self, _):
        self.inputs = {"X": RNG.randn(3, 5).astype(np.float32)}
        self.attrs = {"axis": -1}
        self.outputs = {"Out": np.zeros((3, 5), np.float32)}

    def test_grad(self):
        self.check_grad(["X"], "Out")


class TestBmmGrad(OpTest):
    op_type = "bmm"

    def setup_method(self, _):
        self.inputs = {"X": RNG.rand(2, 2, 3).astype(np.float32),
                       "Y": RNG.rand(2, 3, 2).astype(np.float32)}
        self.outputs = {"Out": np.zeros((2, 2, 2), np.float32)}

    def test_grad(self):
        self.check_grad(["X", "Y"], "Out")


class TestSigmoidFocalLossGrad(OpTest):
    op_type = "sigmoid_focal_loss"

    def setup_method(self, _):
        self.inputs = {"X": RNG.randn(4, 3).astype(np.float32),
                       "Label": np.array([[1], [0], [3], [2]], np.int64),
                       "FgNum": np.array([2], np.int32)}
        self.attrs = {"gamma": 2.0, "alpha": 0.25}
        self.outputs = {"Out": np.zeros((4, 3), np.float32)}

    def test_grad(self):
        self.check_grad(["X"], "Out")


@pytest.mark.slow
class TestFusedAttentionGrad(OpTest):
    """Finite differences through the full custom-VJP path of the fused
    attention op (jnp fallback on CPU — same formula as the kernel)."""

    op_type = "fused_multihead_attention"

    def setup_method(self, _):
        B, H, S, d = 1, 2, 4, 3
        self.inputs = {
            "Q": (RNG.randn(B, H, S, d) * 0.4).astype(np.float32),
            "K": (RNG.randn(B, H, S, d) * 0.4).astype(np.float32),
            "V": (RNG.randn(B, H, S, d) * 0.4).astype(np.float32),
            "Bias": np.zeros((B, 1, 1, S), np.float32),
        }
        self.attrs = {"dropout_prob": 0.0, "is_test": False}
        self.outputs = {"Out": np.zeros((B, H, S, d), np.float32)}

    def test_grad(self):
        self.check_grad(["Q", "K", "V"], "Out", atol=8e-3, rtol=8e-3)


class TestLabelSmoothGrad(OpTest):
    op_type = "label_smooth"

    def setup_method(self, _):
        self.inputs = {"X": RNG.rand(3, 4).astype(np.float32)}
        self.attrs = {"epsilon": 0.1}
        self.outputs = {"Out": np.zeros((3, 4), np.float32)}

    def test_grad(self):
        self.check_grad(["X"], "Out")
