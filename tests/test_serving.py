"""Serving tier: dynamic request batching (bucket ladder, admission
control, multi-client coalescing) + continuous decode batching."""

import threading
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import inference
from paddle_tpu.fluid import layers, monitor
from paddle_tpu.inference import Overloaded, ServeConfig, Server
from paddle_tpu.models.transformer import Transformer, build_decode_session

pytestmark = pytest.mark.serving


def _save_fc(tmpdir, seed=21):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[6], dtype="float32")
        h = layers.fc(x, size=16, act="relu")
        prob = layers.softmax(layers.fc(h, size=3))
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        fluid.io.save_inference_model(str(tmpdir), ["x"], [prob], exe,
                                      main_program=main)


def _predictor(tmpdir, **kw):
    return inference.create_predictor(inference.Config(str(tmpdir)))


def test_server_batches_match_direct(tmp_path):
    """Coalesced+padded batches resolve each future to exactly what a
    direct per-request Predictor.run would return."""
    _save_fc(tmp_path)
    pred = _predictor(tmp_path)
    direct = _predictor(tmp_path)
    rng = np.random.RandomState(3)
    with Server() as srv:
        srv.register("fc", pred,
                     config=ServeConfig(max_batch_size=8,
                                        max_queue_delay_ms=2.0),
                     warmup_feed={"x": rng.rand(1, 6).astype(np.float32)})
        feeds = [rng.rand(rng.randint(1, 5), 6).astype(np.float32)
                 for _ in range(24)]
        futs = [srv.submit("fc", {"x": f}) for f in feeds]
        for f, fut in zip(feeds, futs):
            out = fut.result(timeout=60)
            assert out[0].shape == (f.shape[0], 3)
            np.testing.assert_allclose(out[0], direct.run({"x": f})[0],
                                       atol=1e-5)
    m = monitor.get_metric("serving_batches_total", labels={"model": "fc"})
    assert m is not None and m.value >= 1


def test_mixed_size_stream_compiles_once_per_bucket(tmp_path):
    """After warm-up pre-compiles the ladder, the recompile counter must
    NEVER grow with request count — every request size maps onto an
    already-compiled bucket."""
    _save_fc(tmp_path, seed=22)
    pred = _predictor(tmp_path)
    rng = np.random.RandomState(4)
    with Server() as srv:
        ladder = srv.register(
            "fc", pred,
            config=ServeConfig(max_batch_size=8, max_queue_delay_ms=1.0,
                               max_queue_depth=512),
            warmup_feed={"x": rng.rand(1, 6).astype(np.float32)})
        assert ladder == [1, 2, 4, 8]
        # warm-up = ladder-many signatures; the first is the initial
        # compile, so the counter sits at len(ladder) - 1
        assert len(pred._seen_sigs) == len(ladder)
        before = monitor.counter("predictor_shape_recompile_total").value
        futs = [srv.submit("fc", {"x": rng.rand(rng.randint(1, 9), 6)
                                  .astype(np.float32)})
                for _ in range(40)]
        for fut in futs:
            fut.result(timeout=60)
        assert len(pred._seen_sigs) == len(ladder)
        assert monitor.counter(
            "predictor_shape_recompile_total").value == before


def test_overload_sheds_with_typed_error(tmp_path):
    """Beyond max_queue_depth rows, submit sheds instantly with
    Overloaded; consecutive sheds trip the admission breaker so a
    saturated server rejects without inspecting the queue."""
    _save_fc(tmp_path, seed=23)
    pred = _predictor(tmp_path)
    rng = np.random.RandomState(5)
    row = {"x": rng.rand(1, 6).astype(np.float32)}
    srv = Server()
    try:
        # huge delay + batch: the worker holds back, so the queue fills
        srv.register("fc", pred,
                     config=ServeConfig(max_batch_size=8,
                                        max_queue_delay_ms=500.0,
                                        max_queue_depth=4,
                                        breaker_threshold=2,
                                        breaker_reset_s=30.0),
                     warmup_feed=row)
        futs = [srv.submit("fc", row) for _ in range(4)]
        with pytest.raises(Overloaded, match="depth bound"):
            srv.submit("fc", row)
        with pytest.raises(Overloaded):
            srv.submit("fc", row)
        # breaker tripped by 2 consecutive over-bound submissions
        with pytest.raises(Overloaded, match="breaker is open"):
            srv.submit("fc", row)
        shed = monitor.get_metric("serving_shed_total",
                                  labels={"model": "fc"})
        assert shed.value >= 3
        for fut in futs:  # queued work still completes after the delay
            fut.result(timeout=60)
    finally:
        srv.close()


def test_closed_loop_64_clients(tmp_path):
    """>= 64 concurrent client threads: every future resolves, requests
    coalesce (strictly fewer batches than requests), queue depth stays
    bounded, and the latency histograms can answer p50/p99."""
    _save_fc(tmp_path, seed=24)
    pred = _predictor(tmp_path)
    rng = np.random.RandomState(6)
    xs = [rng.rand(1, 6).astype(np.float32) for _ in range(8)]
    expect = {i: _predictor(tmp_path).run({"x": x})[0]
              for i, x in enumerate(xs)}
    n_clients, per_client = 64, 3
    errors = []
    with Server() as srv:
        srv.register("load", pred,
                     config=ServeConfig(max_batch_size=16,
                                        max_queue_delay_ms=4.0,
                                        max_queue_depth=256),
                     warmup_feed={"x": xs[0]})

        def client(cid):
            try:
                for r in range(per_client):
                    i = (cid + r) % len(xs)
                    out = srv.submit("load", {"x": xs[i]}).result(timeout=60)
                    np.testing.assert_allclose(out[0], expect[i], atol=1e-5)
            except BaseException as e:  # collected and asserted empty after join
                errors.append(e)

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
    assert not errors, errors[:3]
    lbl = {"model": "load"}
    reqs = monitor.get_metric("serving_requests_total", labels=lbl).value
    batches = monitor.get_metric("serving_batches_total", labels=lbl).value
    assert reqs == n_clients * per_client
    assert 1 <= batches < reqs  # coalescing actually happened
    assert monitor.get_metric("serving_queue_depth", labels=lbl).value == 0
    e2e = monitor.get_metric("serving_request_seconds", labels=lbl)
    assert e2e.count == reqs
    p50, p99 = e2e.quantile(0.5), e2e.quantile(0.99)
    assert 0 < p50 <= p99


def test_server_lifecycle_and_validation(tmp_path):
    _save_fc(tmp_path, seed=25)
    pred = _predictor(tmp_path)
    srv = Server()
    srv.register("fc", pred, config=ServeConfig(max_batch_size=4))
    with pytest.raises(ValueError, match="already registered"):
        srv.register("fc", pred)
    with pytest.raises(ValueError, match="max_batch_size"):
        srv.submit("fc", {"x": np.zeros((5, 6), np.float32)})
    with pytest.raises(ValueError, match="leading"):
        srv.submit("fc", {"x": np.zeros((2, 6), np.float32),
                          "y": np.zeros((3, 1), np.float32)})
    srv.close()
    with pytest.raises(RuntimeError, match="closed"):
        srv.submit("fc", {"x": np.zeros((1, 6), np.float32)})
    srv.close()  # idempotent


def test_close_is_typed_flushes_and_idempotent(tmp_path):
    """close() contract: queued futures FLUSH through the normal
    dispatch path (never abandoned), post-close submit/register raise
    the dedicated ``Closed`` (a RuntimeError subclass, NOT retryable),
    and double-close is a no-op."""
    from paddle_tpu.inference import Closed

    _save_fc(tmp_path, seed=26)
    pred = _predictor(tmp_path)
    direct = _predictor(tmp_path)
    rng = np.random.RandomState(8)
    srv = Server()
    srv.register("fc", pred,
                 config=ServeConfig(max_batch_size=8,
                                    max_queue_delay_ms=5000.0),
                 warmup_feed={"x": rng.rand(1, 6).astype(np.float32)})
    # park requests behind the huge delay, then close underneath them
    xs = [rng.rand(1, 6).astype(np.float32) for _ in range(3)]
    futs = [srv.submit("fc", {"x": x}) for x in xs]
    srv.close()
    for x, fut in zip(xs, futs):        # flushed, not abandoned
        np.testing.assert_allclose(fut.result(timeout=10)[0],
                                   direct.run({"x": x})[0], atol=1e-5)
    with pytest.raises(Closed):
        srv.submit("fc", {"x": xs[0]})
    with pytest.raises(Closed):
        srv.register("fc2", pred)
    assert issubclass(Closed, RuntimeError)
    assert not issubclass(Closed, Overloaded)
    srv.close()                         # second close: no-op, no raise
    srv.close()


def test_deadline_aware_batch_close(tmp_path):
    """SLO batcher: a tight-deadline request forces an EARLY partial
    batch (well before max_queue_delay_ms) while deadline-less requests
    still coalesce to full buckets — and neither path grows the
    recompile counter past the warm-up ladder."""
    _save_fc(tmp_path, seed=27)
    pred = _predictor(tmp_path)
    rng = np.random.RandomState(9)
    row = lambda: {"x": rng.rand(1, 6).astype(np.float32)}
    with Server() as srv:
        srv.register("fc", pred,
                     config=ServeConfig(max_batch_size=8,
                                        max_queue_delay_ms=2000.0),
                     warmup_feed=row())
        before = monitor.counter("predictor_shape_recompile_total").value
        # lazy requests would sit out the full 2 s delay; one request
        # with a 100 ms deadline closes the batch for all of them
        t0 = time.perf_counter()
        lazy = [srv.submit("fc", row()) for _ in range(2)]
        tight = srv.submit("fc", row(), deadline_ms=100.0)
        for fut in lazy + [tight]:
            fut.result(timeout=10)
        assert time.perf_counter() - t0 < 1.0
        # a full bucket still closes immediately without any deadline
        t1 = time.perf_counter()
        full = [srv.submit("fc", row()) for _ in range(8)]
        for fut in full:
            fut.result(timeout=10)
        assert time.perf_counter() - t1 < 1.0
        # an already-expired deadline is shed typed, before dispatch
        with pytest.raises(Overloaded, match="deadline"):
            srv.submit("fc", row(), deadline_ms=0.0)
        assert monitor.counter(
            "predictor_shape_recompile_total").value == before


# -- continuous decode batching -------------------------------------------


def _decode_fixture(n_req=6, B=4, V=32, S=6, P=4, C=24, seed=0):
    np.random.seed(seed)
    with fluid.dygraph.guard():
        model = Transformer(V, V, d_model=16, n_heads=2, d_inner=32,
                            n_layers=1, max_len=C + 8, dropout_rate=0.0)
        sess = build_decode_session(model, B, S, P, C, end_id=1,
                                    slot_prefill=True)
    srcs = [np.random.randint(2, V, (S,)).astype(np.int64)
            for _ in range(n_req)]
    prompts = [np.random.randint(2, V, (P,)).astype(np.int64)
               for _ in range(n_req)]
    return sess, srcs, prompts


def _run_solo(sess, src, prompt, budget):
    st = sess.open_stream()
    slot, done = st.join(src, prompt, max_new_tokens=budget)
    if done is not None:
        return done[0]
    while True:
        for s, toks, _fin in st.step():
            if s == slot:
                return toks


def test_continuous_batching_token_identical():
    """Requests joining mid-stream into vacant slots of a live decode
    batch produce TOKEN-IDENTICAL output to running each alone — slot
    rows never interact inside the decode program."""
    sess, srcs, prompts = _decode_fixture()
    budget = 6
    solo = [_run_solo(sess, s, p, budget) for s, p in zip(srcs, prompts)]

    occ = monitor.histogram("decode_slot_occupancy")
    joins0 = monitor.counter("decode_slot_join_total").value
    retires0 = monitor.counter("decode_slot_retire_total").value
    sum0, count0 = occ.sum, occ.count

    st = sess.open_stream()
    results, slot_of = {}, {}
    pending = list(range(len(srcs)))

    def join_next():
        i = pending.pop(0)
        slot, done = st.join(srcs[i], prompts[i], max_new_tokens=budget)
        if done is not None:
            results[i] = done[0]
        else:
            slot_of[slot] = i

    while pending and st.vacant_slots():
        join_next()
    steps = 0
    while len(results) < len(srcs):
        for slot, toks, _fin in st.step():
            results[slot_of.pop(slot)] = toks
            if pending:
                join_next()       # mid-stream join into the freed slot
        steps += 1
        assert steps < 200
    for i, want in enumerate(solo):
        np.testing.assert_array_equal(results[i], want)

    n = len(srcs)
    assert monitor.counter("decode_slot_join_total").value - joins0 == n
    assert monitor.counter("decode_slot_retire_total").value - retires0 == n
    # occupancy stayed above drained batch-1 decoding (1/width)
    d_count = occ.count - count0
    assert d_count > 0
    mean_occ = (occ.sum - sum0) / d_count
    assert mean_occ > 1.0 / st.width


def test_stream_requires_slot_prefill():
    sess, _, _ = _decode_fixture(n_req=0, seed=1)
    np.random.seed(1)
    with fluid.dygraph.guard():
        model = Transformer(32, 32, d_model=16, n_heads=2, d_inner=32,
                            n_layers=1, max_len=32, dropout_rate=0.0)
        plain = build_decode_session(model, 2, 6, 4, 24, end_id=1)
    with pytest.raises(ValueError, match="slot_prefill=True"):
        plain.open_stream()
    # the slot_prefill session costs exactly ONE extra trace/compile,
    # amortized over every later join
    assert sess.prefill1_program is not None


def test_stream_join_validation():
    sess, srcs, prompts = _decode_fixture(n_req=12, B=2, seed=2)
    st = sess.open_stream()
    with pytest.raises(RuntimeError, match="no active slot"):
        st.step()
    with pytest.raises(ValueError, match="max_new_tokens"):
        st.join(srcs[0], prompts[0], max_new_tokens=0)
    # occupy both slots (a join may legitimately complete at prefill
    # when the first greedy token is end_id — those leave the slot free)
    i = 0
    while st.vacant_slots():
        assert i < len(srcs), "every request finished at prefill"
        st.join(srcs[i], prompts[i], max_new_tokens=50)
        i += 1
    with pytest.raises(RuntimeError, match="no vacant slot"):
        st.join(srcs[i], prompts[i], max_new_tokens=50)


def test_generative_server_continuous(tmp_path):
    """GenerativeServer: concurrent clients' generations resolve with
    the same tokens as solo runs, through one live decode batch."""
    from paddle_tpu.inference import GenerativeServer

    sess, srcs, prompts = _decode_fixture(n_req=8, seed=3)
    budget = 6
    solo = [_run_solo(sess, s, p, budget) for s, p in zip(srcs, prompts)]
    results, errors = {}, []
    with GenerativeServer(sess.open_stream(), model="gen-test") as srv:

        def client(i):
            try:
                toks, _fin = srv.submit(
                    srcs[i], prompts[i],
                    max_new_tokens=budget).result(timeout=120)
                results[i] = toks
            except BaseException as e:  # collected and asserted empty after join
                errors.append(e)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(srcs))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(180)
    assert not errors, errors[:3]
    for i, want in enumerate(solo):
        np.testing.assert_array_equal(results[i], want)
    lbl = {"model": "gen-test"}
    assert monitor.get_metric("serving_requests_total",
                              labels=lbl).value == len(srcs)
    e2e = monitor.get_metric("serving_request_seconds", labels=lbl)
    assert e2e.count == len(srcs) and e2e.quantile(0.99) > 0
