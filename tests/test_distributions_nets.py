"""Distributions + nets composites — reference ``layers/distributions.py``
and ``python/paddle/fluid/nets.py``."""

import math

import numpy as np
import pytest
from scipy import stats

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, nets, optimizer
from paddle_tpu.fluid.layers.distributions import (
    Categorical, MultivariateNormalDiag, Normal, Uniform)


def _run(fetches, feed=None, seed=0):
    main = fluid.default_main_program()
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(fluid.default_startup_program())
        return [np.asarray(r) for r in
                exe.run(main, feed=feed or {}, fetch_list=fetches)]


def test_normal_log_prob_entropy_kl():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        n1 = Normal(0.0, 1.0)
        n2 = Normal(1.0, 2.0)
        v = layers.data("v", shape=[1], dtype="float32")
        lp = n1.log_prob(v)
        ent = n2.entropy()
        kl = n1.kl_divergence(n2)
        samp = n1.sample([500])
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        lpv, entv, klv, sv = exe.run(
            main, feed={"v": np.array([[0.5]], np.float32)},
            fetch_list=[lp, ent, kl, samp])
    np.testing.assert_allclose(np.asarray(lpv).ravel()[0],
                               stats.norm.logpdf(0.5), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(entv).ravel()[0],
                               stats.norm(1, 2).entropy(), rtol=1e-5)
    # KL(N(0,1) || N(1,2)) closed form
    expect_kl = math.log(2.0) + (1 + 1) / (2 * 4) - 0.5
    np.testing.assert_allclose(np.asarray(klv).ravel()[0], expect_kl,
                               rtol=1e-5)
    s = np.asarray(sv)
    assert abs(s.mean()) < 0.2 and abs(s.std() - 1.0) < 0.2


def test_uniform_sample_and_log_prob():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        u = Uniform(-2.0, 3.0)
        samp = u.sample([400])
        v = layers.data("v", shape=[1], dtype="float32")
        lp = u.log_prob(v)
        ent = u.entropy()
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        sv, lpv, entv = exe.run(
            main, feed={"v": np.array([[0.0]], np.float32)},
            fetch_list=[samp, lp, ent])
    s = np.asarray(sv)
    assert s.min() >= -2.0 and s.max() <= 3.0
    np.testing.assert_allclose(np.asarray(lpv).ravel()[0],
                               -math.log(5.0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(entv).ravel()[0],
                               math.log(5.0), rtol=1e-5)


def test_categorical_entropy_kl_sample():
    logits = np.log(np.array([[0.2, 0.3, 0.5]], np.float32))
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        lv = layers.data("lv", shape=[3], dtype="float32")
        c1 = Categorical(lv)
        c2 = Categorical(layers.scale(lv, scale=0.5))
        ent = c1.entropy()
        kl = c1.kl_divergence(c2)
        v = layers.data("v", shape=[1], dtype="int64")
        lp = c1.log_prob(v)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        entv, klv, lpv = exe.run(
            main, feed={"lv": logits, "v": np.array([[2]], np.int64)},
            fetch_list=[ent, kl, lp])
    p = np.array([0.2, 0.3, 0.5])
    np.testing.assert_allclose(np.asarray(entv).ravel()[0],
                               -(p * np.log(p)).sum(), rtol=1e-5)
    assert np.asarray(klv).ravel()[0] > 0
    np.testing.assert_allclose(np.asarray(lpv).ravel()[0], np.log(0.5),
                               rtol=1e-5)


def test_multivariate_normal_diag():
    loc = np.array([0.0, 1.0], np.float32)
    scale = np.diag([1.0, 2.0]).astype(np.float32)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        d = MultivariateNormalDiag(loc, scale)
        ent = d.entropy()
        v = layers.data("v", shape=[2], dtype="float32")
        lp = d.log_prob(v)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        entv, lpv = exe.run(main, feed={
            "v": np.array([[0.5, 0.0]], np.float32)},
            fetch_list=[ent, lp])
    ref = stats.multivariate_normal(loc, np.diag([1.0, 2.0]))  # scale = cov
    np.testing.assert_allclose(np.asarray(entv).ravel()[0], ref.entropy(),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(lpv).ravel()[0],
                               ref.logpdf([0.5, 0.0]), rtol=1e-4)


def test_simple_img_conv_pool_and_group():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 1
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[1, 8, 8], dtype="float32")
        a = nets.simple_img_conv_pool(img, num_filters=4, filter_size=3,
                                      pool_size=2, pool_stride=2, act="relu")
        b = nets.img_conv_group(img, conv_num_filter=[4, 4], pool_size=2,
                                conv_act="relu", conv_with_batchnorm=True)
    exe = fluid.Executor()
    v = np.random.RandomState(0).rand(2, 1, 8, 8).astype(np.float32)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        av, bv = exe.run(main, feed={"img": v}, fetch_list=[a, b])
    # conv 3x3 (no pad) on 8x8 -> 6x6; pool 2/2 -> 3x3
    assert np.asarray(av).shape == (2, 4, 3, 3)
    # group: pad-1 convs keep 8x8; pool 2 stride 1 -> 7x7
    assert np.asarray(bv).shape == (2, 4, 7, 7)


def test_sequence_conv_pool_and_glu():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 2
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[6], dtype="float32", lod_level=1)
        sp = nets.sequence_conv_pool(x, num_filters=5, filter_size=3)
        g = layers.data("g", shape=[8], dtype="float32")
        gl = nets.glu(g, dim=-1)
    exe = fluid.Executor()
    xv = np.random.RandomState(1).rand(5, 6).astype(np.float32)
    gv = np.random.RandomState(2).rand(3, 8).astype(np.float32)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        spv, glv = exe.run(main, feed={
            "x": fluid.create_lod_tensor(xv, [[3, 2]]), "g": gv},
            fetch_list=[sp, gl])
    assert np.asarray(spv).shape == (2, 5)
    expect = gv[:, :4] * (1 / (1 + np.exp(-gv[:, 4:])))
    np.testing.assert_allclose(np.asarray(glv), expect, rtol=1e-5)


def test_scaled_dot_product_attention():
    B, T, D, heads = 2, 4, 8, 2
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 3
    with fluid.program_guard(main, startup):
        q = layers.data("q", shape=[T, D], dtype="float32")
        k = layers.data("k", shape=[T, D], dtype="float32")
        v = layers.data("v", shape=[T, D], dtype="float32")
        out = nets.scaled_dot_product_attention(q, k, v, num_heads=heads)
    rng = np.random.RandomState(4)
    qv, kv, vv = [rng.rand(B, T, D).astype(np.float32) for _ in range(3)]
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (r,) = exe.run(main, feed={"q": qv, "k": kv, "v": vv},
                       fetch_list=[out])
    r = np.asarray(r)
    assert r.shape == (B, T, D)
    # numpy reference
    dk = D // heads
    qh = qv.reshape(B, T, heads, dk).transpose(0, 2, 1, 3)
    kh = kv.reshape(B, T, heads, dk).transpose(0, 2, 1, 3)
    vh = vv.reshape(B, T, heads, dk).transpose(0, 2, 1, 3)
    logits = (qh / np.sqrt(dk)) @ kh.transpose(0, 1, 3, 2)
    w = np.exp(logits - logits.max(-1, keepdims=True))
    w = w / w.sum(-1, keepdims=True)
    ref = (w @ vh).transpose(0, 2, 1, 3).reshape(B, T, D)
    np.testing.assert_allclose(r, ref, rtol=1e-4, atol=1e-5)
