"""Fault-tolerant training runtime: crash-consistent checkpoints
(atomic writes, versioned CheckpointManager, torn-write fallback),
auto-resume under the gang launcher (kill-resume bit-equivalence),
anomaly policies (skip_step / rollback), pserver RPC retry, heartbeat
clean-stop, and the launcher's port-race handling."""

import hashlib
import json
import os
import re
import subprocess
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import paddle_tpu.fluid as fluid  # noqa: E402
from paddle_tpu.fluid import faults, flags, layers, optimizer  # noqa: E402
from paddle_tpu.fluid.core import tensor_io  # noqa: E402
from paddle_tpu.fluid.executor import RNG_STATE_VAR  # noqa: E402
from paddle_tpu.fluid.io import CheckpointManager  # noqa: E402

HERE = os.path.dirname(os.path.abspath(__file__))


@pytest.fixture(autouse=True)
def _clean_faults_and_flags():
    faults.reset()
    yield
    faults.reset()
    flags.set_flags({"FLAGS_anomaly_policy": "raise",
                     "FLAGS_anomaly_skip_budget": 3})


def _mlp(seed=11):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.data("y", shape=[1], dtype="float32")
        h = layers.fc(x, size=6, act="relu")
        pred = layers.fc(h, size=1)
        loss = layers.reduce_mean(layers.square(pred - y))
        optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def _feed(step):
    rs = np.random.RandomState(77 + step)
    return {"x": rs.rand(3, 4).astype(np.float32),
            "y": rs.rand(3, 1).astype(np.float32)}


def _params(program, scope):
    out = {}
    for v in program.list_vars():
        if v.persistable and scope.find_var(v.name) is not None:
            out[v.name] = np.asarray(scope.find_var(v.name))
    return out


# -- atomic tensor_io writes ------------------------------------------------

def test_save_combine_atomic_survives_injected_crash(tmp_path):
    path = str(tmp_path / "w.pdparams")
    old = {"a": np.arange(6, dtype=np.float32).reshape(2, 3)}
    tensor_io.save_combine(path, old)
    # crash between the tmp write and the rename: destination untouched
    faults.arm("io.write")
    with pytest.raises(faults.FaultInjected):
        tensor_io.save_combine(path, {"a": np.zeros((2, 3), np.float32)})
    got = tensor_io.load_combine(path)
    np.testing.assert_array_equal(got["a"], old["a"])
    # and no tmp litter left behind
    assert [n for n in os.listdir(str(tmp_path)) if ".tmp-" in n] == []


def test_save_combine_atomic_replaces_on_success(tmp_path):
    path = str(tmp_path / "w.pdparams")
    tensor_io.save_combine(path, {"a": np.zeros(3, np.float32)})
    new = {"a": np.ones(3, np.float32)}
    tensor_io.save_combine(path, new)
    np.testing.assert_array_equal(tensor_io.load_combine(path)["a"],
                                  new["a"])


# -- io.load strict (satellite) ---------------------------------------------

def test_io_load_missing_raises_and_strict_false_tolerates(tmp_path):
    prog, _, _ = _mlp()
    missing = str(tmp_path / "nope" / "model")
    with pytest.raises(FileNotFoundError, match="strict=False"):
        fluid.io.load(prog, missing)
    assert fluid.io.load(prog, missing, strict=False) is False


# -- CheckpointManager ------------------------------------------------------

def test_checkpoint_roundtrip_restores_exact_state(tmp_path):
    prog, startup, loss = _mlp()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        for i in range(3):
            exe.run(prog, feed=_feed(i), fetch_list=[loss])
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(prog, step=3)
        saved = _params(prog, scope)
        rng_saved = np.asarray(scope.find_var(RNG_STATE_VAR))
    fresh = fluid.Scope()
    with fluid.scope_guard(fresh):
        exe2 = fluid.Executor()
        exe2.run(startup)
        mgr2 = CheckpointManager(str(tmp_path))
        assert mgr2.restore(exe2, prog) == 3
        got = _params(prog, fresh)
        for name, arr in saved.items():
            np.testing.assert_array_equal(got[name], arr)
        np.testing.assert_array_equal(
            np.asarray(fresh.find_var(RNG_STATE_VAR)), rng_saved)


def test_checkpoint_rotation_keeps_max_to_keep(tmp_path):
    prog, startup, _ = _mlp()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor()
        exe.run(startup)
        mgr = CheckpointManager(str(tmp_path), max_to_keep=2)
        for s in (1, 2, 3, 4):
            mgr.save(prog, step=s)
        assert mgr.steps() == [3, 4]


def test_torn_checkpoint_detected_and_falls_back(tmp_path):
    prog, startup, loss = _mlp()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor()
        exe.run(startup)
        mgr = CheckpointManager(str(tmp_path), max_to_keep=3)
        mgr.save(prog, step=5)
        exe.run(prog, feed=_feed(0), fetch_list=[loss])
        mgr.save(prog, step=10)
        # truncate the newest version's params file: checksum mismatch
        p = os.path.join(mgr._path(10), "params.pdparams")
        with open(p, "r+b") as f:
            f.truncate(os.path.getsize(p) // 2)
        assert mgr.validate(10) is False
        assert mgr.validate(5) is True
        assert mgr.latest() == 5  # silent fallback to the intact version
        assert mgr.restore(exe, prog) == 5


def test_crash_during_version_write_leaves_previous_intact(tmp_path):
    prog, startup, _ = _mlp()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor()
        exe.run(startup)
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(prog, step=1)
        # crash after the data files, before the manifest+rename commit.
        # times=3 outlasts the io retry's 3 attempts, so the save fails.
        faults.arm("io.write", times=3)
        with pytest.raises(faults.FaultInjected):
            mgr.save(prog, step=2)
        faults.reset()
        assert mgr.latest() == 1  # the committed version is untouched
        assert mgr.steps() == [1]  # no half-written ckpt-2 dir


def test_background_save_lands_after_wait(tmp_path):
    prog, startup, _ = _mlp()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor()
        exe.run(startup)
        mgr = CheckpointManager(str(tmp_path), background=True)
        mgr.save(prog, step=7)
        mgr.wait()
        assert mgr.latest() == 7
        assert mgr.validate(7)


def test_background_save_failure_surfaces_on_wait(tmp_path):
    prog, startup, _ = _mlp()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor()
        exe.run(startup)
        mgr = CheckpointManager(str(tmp_path), background=True)
        faults.arm("io.write", times=3)
        mgr.save(prog, step=1)
        with pytest.raises(faults.FaultInjected):
            mgr.wait()


def test_restore_on_restart_env_contract(tmp_path, monkeypatch):
    prog, startup, _ = _mlp()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor()
        exe.run(startup)
        mgr = CheckpointManager(str(tmp_path))
        monkeypatch.setenv("PADDLE_RESTART_ATTEMPT", "1")
        # restarted but nothing saved yet: fresh start, not an error
        assert mgr.restore_on_restart(exe, prog) is None
        mgr.save(prog, step=4)
        assert mgr.restore_on_restart(exe, prog) == 4
        monkeypatch.setenv("PADDLE_RESTART_ATTEMPT", "0")
        assert mgr.restore_on_restart(exe, prog) is None  # first spawn


def test_checkpoint_dir_from_env(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_CHECKPOINT_DIR", str(tmp_path / "cp"))
    mgr = CheckpointManager()
    assert mgr.dirname == str(tmp_path / "cp")
    monkeypatch.delenv("PADDLE_CHECKPOINT_DIR")
    with pytest.raises(ValueError, match="PADDLE_CHECKPOINT_DIR"):
        CheckpointManager()


def test_executor_checkpoint_every_n_steps(tmp_path):
    prog, startup, loss = _mlp()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor()
        exe.run(startup)
        mgr = CheckpointManager(str(tmp_path), max_to_keep=10)
        for i in range(7):
            exe.run(prog, feed=_feed(i), fetch_list=[loss],
                    checkpoint=(mgr, 3))
        mgr.wait()
        assert mgr.steps() == [3, 6]
        # iters=k advances the counter by k and saves on the crossing
        feed = {"x": np.stack([_feed(7)["x"], _feed(8)["x"]]),
                "y": np.stack([_feed(7)["y"], _feed(8)["y"]])}
        exe.run(prog, feed=feed, fetch_list=[loss], iters=2,
                checkpoint=(mgr, 3))
        mgr.wait()
        assert mgr.steps() == [3, 6, 9]


def test_executor_checkpoint_arg_validated():
    exe = fluid.Executor()
    with pytest.raises(ValueError, match="checkpoint"):
        exe.run(fluid.Program(), checkpoint=("not a manager",))
    with pytest.raises(ValueError, match="checkpoint"):
        exe.run(fluid.Program(), checkpoint=(object(), 0))


# -- py_reader position (checkpointed epoch cursor) -------------------------

def test_py_reader_position_and_resume():
    from paddle_tpu.fluid.layers.py_reader import _PyReader

    r = _PyReader(["s0"], [(2, 2)], ["float32"])
    batches = [np.full((2, 2), i, np.float32) for i in range(6)]
    r.decorate_tensor_provider(lambda: iter([(b,) for b in batches]))
    r.start()
    r._next(); r._next(); r._next()
    assert r.position == 3
    r.reset()
    r.resume_at(3)
    r.start()  # fast-forwards past the 3 consumed batches
    (nxt,) = r._next()
    np.testing.assert_array_equal(nxt, batches[3])
    assert r.position == 4
    r.reset()


# -- anomaly policies -------------------------------------------------------

def test_anomaly_skip_step_discards_and_budget_raises(tmp_path):
    prog, startup, loss = _mlp()
    with fluid.scope_guard(fluid.Scope()) as _:
        exe = fluid.Executor()
        exe.run(startup)
        flags.set_flags({"FLAGS_anomaly_policy": "skip_step",
                         "FLAGS_anomaly_skip_budget": 2})
        before = _params(prog, fluid.global_scope())
        faults.arm("step.nonfinite", after_n=0, times=1)
        exe.run(prog, feed=_feed(0), fetch_list=[loss])
        after = _params(prog, fluid.global_scope())
        for name in before:  # discarded: nothing committed
            np.testing.assert_array_equal(after[name], before[name])
        # a clean step commits and resets the consecutive counter
        exe.run(prog, feed=_feed(1), fetch_list=[loss])
        changed = any(not np.array_equal(
            _params(prog, fluid.global_scope())[n], before[n])
            for n in before)
        assert changed
        # budget: 2 consecutive skips tolerated, the third raises
        faults.arm("step.nonfinite", after_n=0, times=5)
        exe.run(prog, feed=_feed(2), fetch_list=[loss])
        exe.run(prog, feed=_feed(3), fetch_list=[loss])
        with pytest.raises(FloatingPointError, match="skip_budget"):
            exe.run(prog, feed=_feed(4), fetch_list=[loss])


def test_anomaly_rollback_restores_checkpoint(tmp_path):
    prog, startup, loss = _mlp()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor()
        exe.run(startup)
        mgr = CheckpointManager(str(tmp_path))
        for i in range(3):
            exe.run(prog, feed=_feed(i), fetch_list=[loss],
                    checkpoint=(mgr, 3))
        mgr.wait()
        at_ckpt = _params(prog, fluid.global_scope())
        exe.run(prog, feed=_feed(3), fetch_list=[loss],
                checkpoint=(mgr, 3))
        drifted = _params(prog, fluid.global_scope())
        assert any(not np.array_equal(at_ckpt[n], drifted[n])
                   for n in at_ckpt)
        flags.set_flags({"FLAGS_anomaly_policy": "rollback"})
        faults.arm("step.nonfinite", after_n=0, times=1)
        exe.run(prog, feed=_feed(4), fetch_list=[loss],
                checkpoint=(mgr, 3))
        rolled = _params(prog, fluid.global_scope())
        for name in at_ckpt:  # back to the step-3 checkpoint exactly
            np.testing.assert_array_equal(rolled[name], at_ckpt[name])


def test_anomaly_rollback_without_checkpoint_is_an_error():
    prog, startup, loss = _mlp()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor()
        exe.run(startup)
        flags.set_flags({"FLAGS_anomaly_policy": "rollback"})
        faults.arm("step.nonfinite", after_n=0, times=1)
        with pytest.raises(RuntimeError, match="rollback"):
            exe.run(prog, feed=_feed(0), fetch_list=[loss])


def test_real_nonfinite_feed_still_raises_by_default():
    prog, startup, loss = _mlp()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor()
        exe.run(startup)
        flags.set_flags({"FLAGS_check_nan_inf": True})
        try:
            bad = _feed(0)
            bad["x"] = np.full_like(bad["x"], np.nan)
            with pytest.raises(FloatingPointError, match="check_nan_inf"):
                exe.run(prog, feed=bad, fetch_list=[loss])
        finally:
            flags.set_flags({"FLAGS_check_nan_inf": False})


def test_bad_anomaly_policy_rejected():
    flags.set_flags({"FLAGS_anomaly_policy": "explode"})
    with pytest.raises(ValueError, match="anomaly_policy"):
        flags.anomaly_policy()


# -- pserver RPC retry ------------------------------------------------------

def test_ps_rpc_retry_absorbs_injected_fault():
    from paddle_tpu.distributed import ps
    from paddle_tpu.distributed.ps_server import RemoteTable, TableServer

    srv = TableServer(tables={"t": ps.EmbeddingTable(
        vocab=8, dim=2, init_scale=0.0)}).start()
    try:
        rt = RemoteTable(srv.endpoint, "t")
        # next two RPC round-trips blip; the shared Retry absorbs them
        faults.arm("ps.rpc", after_n=0, times=2)
        rows = rt.pull(np.array([1, 2], np.int64))
        assert rows.shape == (2, 2)
        assert faults.hits("ps.rpc") >= 3
        rt.close()
    finally:
        srv.stop()


def test_ps_rpc_retry_exhaustion_surfaces():
    from paddle_tpu.distributed import ps
    from paddle_tpu.distributed.ps_server import RemoteTable, TableServer

    srv = TableServer(tables={"t": ps.EmbeddingTable(
        vocab=8, dim=2, init_scale=0.0)}).start()
    try:
        rt = RemoteTable(srv.endpoint, "t")
        faults.arm("ps.rpc", after_n=0, times=99)  # outlasts the budget
        with pytest.raises(faults.FaultInjected):
            rt.pull(np.array([1], np.int64))
        faults.reset()
        rows = rt.pull(np.array([1], np.int64))  # recovers afterwards
        assert rows.shape == (1, 2)
        rt.close()
    finally:
        srv.stop()


# -- heartbeat clean stop (satellite) ---------------------------------------

def test_heartbeat_stop_is_clean_and_idempotent(tmp_path):
    from paddle_tpu.distributed.heartbeat import Heartbeat, Watchdog

    hb = Heartbeat(rank=0, dirname=str(tmp_path), interval=0.1).start()
    time.sleep(0.05)
    assert os.path.exists(hb.path)
    hb.stop()
    hb.stop()  # idempotent
    assert not os.path.exists(hb.path)          # stamp removed
    assert os.path.exists(hb.path + ".exit")    # clean-exit marker
    # the watchdog no longer needs skip= for cleanly-stopped ranks
    wd = Watchdog(str(tmp_path), nproc=1, timeout=0.01,
                  startup_grace=0.01)
    time.sleep(0.05)
    assert wd.stale_workers() == []


def test_watchdog_still_flags_hung_worker(tmp_path):
    from paddle_tpu.distributed.heartbeat import Heartbeat, Watchdog

    hb = Heartbeat(rank=0, dirname=str(tmp_path), interval=30).start()
    try:
        wd = Watchdog(str(tmp_path), nproc=1, timeout=0.05)
        time.sleep(0.15)  # stamp goes stale, no exit marker
        assert wd.stale_workers() == [0]
    finally:
        hb.stop()


# -- launcher port handling (satellite) -------------------------------------

def test_reserve_port_range_is_fully_bindable():
    import socket

    from paddle_tpu.distributed.launch import _reserve_port_range

    base = _reserve_port_range(4)
    for i in range(4):
        s = socket.socket()  # bind probe only, no protocol spoken
        s.bind(("127.0.0.1", base + i))
        s.close()


def test_bind_failure_detected_in_worker_logs(tmp_path):
    from paddle_tpu.distributed.launch import _bind_failure

    log_dir = str(tmp_path)
    with open(os.path.join(log_dir, "worker.0.log"), "w") as f:
        f.write("Traceback ...\nOSError: [Errno 98] "
                "Address already in use\n")
    assert _bind_failure(log_dir, 1) is True
    with open(os.path.join(log_dir, "worker.0.log"), "w") as f:
        f.write("clean run\n")
    assert _bind_failure(log_dir, 1) is False
    assert _bind_failure(None, 1) is False


# -- kill-resume equivalence (the acceptance test) --------------------------

def _run_gang(tmp_path, tag, extra_env, max_restarts):
    from paddle_tpu.distributed.launch import launch

    log_dir = str(tmp_path / ("logs_" + tag))
    env = dict(os.environ)
    env.pop("PADDLE_FAULTS", None)
    env.update(extra_env)
    codes = launch(
        1, [sys.executable, "-u", os.path.join(HERE, "dist_runner_ckpt.py")],
        env=env, log_dir=log_dir, max_restarts=max_restarts,
        restart_backoff=0.05,
        checkpoint_dir=str(tmp_path / ("ckpt_" + tag)))
    with open(os.path.join(log_dir, "worker.0.log")) as f:
        log = f.read()
    return codes, log


@pytest.mark.faults
def test_kill_resume_matches_uninterrupted_run(tmp_path):
    """A worker hard-killed mid-run (os._exit via the worker.exit fault)
    is respawned by launch(max_restarts=1), auto-resumes from the last
    intact checkpoint, and finishes with weights BIT-IDENTICAL to a
    run that was never interrupted."""
    codes, log = _run_gang(tmp_path, "base", {}, max_restarts=0)
    assert codes == [0], log
    base_weights = re.findall(r"WEIGHTS (\w+)", log)[-1]

    codes, log = _run_gang(
        tmp_path, "kill", {"PADDLE_TEST_KILL_AT": "7"}, max_restarts=1)
    assert codes == [0], log
    # two attempts wrote the (append-mode) log: fresh start then resume
    resumed = [int(m) for m in re.findall(r"RESUMED (-?\d+)", log)]
    assert len(resumed) == 2, log
    assert resumed[0] == -1        # attempt 0: fresh start
    assert resumed[-1] == 6        # attempt 1: resumed at the last ckpt
    kill_weights = re.findall(r"WEIGHTS (\w+)", log)[-1]
    assert kill_weights == base_weights  # bit-identical final state
