"""Expert-parallel MoE (parallel/moe.py) — the ``ep`` mesh axis.

New capability (no 2019-reference analogue, like ring attention):
Switch/GShard dispatch-combine MoE with capacity-bounded static-shape
routing. Pins: identical-experts equivalence to a dense FFN, capacity
drop behavior, top-2 renormalization, load-balance aux, and the
GSPMD-sharded (dp x ep) train step matching the single-device step.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.parallel import (MoEConfig, init_moe_params,
                                 make_moe_train_step, moe_ffn,
                                 moe_param_specs)
from paddle_tpu.parallel import make_mesh, shard_moe_params


def _dense_ffn(x, w1, b1, w2, b2):
    h = jax.nn.gelu(x @ w1 + b1)
    return h @ w2 + b2


def test_identical_experts_match_dense():
    """With every expert holding the SAME weights and ample capacity, the
    MoE output must equal the dense FFN regardless of routing."""
    cfg = MoEConfig(hidden=16, ffn=32, n_experts=4, k=1,
                    capacity_factor=4.0)
    p = init_moe_params(cfg, seed=0)
    # overwrite experts with copies of expert 0
    for k in ("w1", "b1", "w2", "b2"):
        p[k] = jnp.broadcast_to(p[k][:1], p[k].shape)
    x = jax.random.normal(jax.random.PRNGKey(1), (6, 5, cfg.hidden))
    y, aux = moe_ffn(p, x, cfg)
    # top-1 gate scales the output by the winning probability; recover the
    # dense output by dividing it out per token
    logits = x.reshape(-1, cfg.hidden) @ p["wg"]
    gates = jax.nn.softmax(logits.astype(jnp.float32), -1)
    top = jnp.max(gates, -1).reshape(6, 5, 1)
    dense = _dense_ffn(x, p["w1"][0], p["b1"][0], p["w2"][0], p["b2"][0])
    np.testing.assert_allclose(np.asarray(y / top), np.asarray(dense),
                               rtol=2e-3, atol=2e-3)
    assert np.isfinite(float(aux))


def test_topk_validation():
    with pytest.raises(ValueError, match="k must be"):
        MoEConfig(k=3)
    # k > n_experts would dispatch a token to one expert twice
    with pytest.raises(ValueError, match="exceeds n_experts"):
        MoEConfig(n_experts=1, k=2)


def test_capacity_drops_pass_zero():
    """capacity_factor so small that most tokens drop: dropped tokens
    contribute ZERO (they ride the residual path outside this fn)."""
    cfg = MoEConfig(hidden=8, ffn=16, n_experts=2, k=1,
                    capacity_factor=0.01)  # capacity = 1 token/expert
    assert cfg.capacity(64) == 1
    p = init_moe_params(cfg, seed=0)
    x = jax.random.normal(jax.random.PRNGKey(2), (64, cfg.hidden))
    y, _ = moe_ffn(p, x, cfg)
    nonzero = np.abs(np.asarray(y)).sum(axis=-1) > 1e-9
    assert nonzero.sum() <= 2 * cfg.capacity(64)  # at most E*C tokens kept


def test_top2_identical_experts_match_dense_exactly():
    """k=2 with renormalized gates sums to weight 1 per token, so with
    identical experts and ample capacity the output must EQUAL the dense
    FFN — this pins the GShard slot-offset (without it, round-1 and
    round-2 tokens collide in the same (expert, slot) buffer entry and
    the outputs mix)."""
    cfg = MoEConfig(hidden=16, ffn=32, n_experts=4, k=2,
                    capacity_factor=4.0)
    p = init_moe_params(cfg, seed=3)
    for k in ("w1", "b1", "w2", "b2"):
        p[k] = jnp.broadcast_to(p[k][:1], p[k].shape)
    x = jax.random.normal(jax.random.PRNGKey(4), (32, 16))
    y2, _ = moe_ffn(p, x, cfg)
    dense = _dense_ffn(x, p["w1"][0], p["b1"][0], p["w2"][0], p["b2"][0])
    np.testing.assert_allclose(np.asarray(y2), np.asarray(dense),
                               rtol=2e-3, atol=2e-3)


def test_top2_differs_from_top1():
    cfg1 = MoEConfig(hidden=16, ffn=32, n_experts=4, k=1,
                     capacity_factor=2.0)
    cfg2 = MoEConfig(hidden=16, ffn=32, n_experts=4, k=2,
                     capacity_factor=2.0)
    p = init_moe_params(cfg1, seed=3)
    x = jax.random.normal(jax.random.PRNGKey(4), (32, 16))
    y1, _ = moe_ffn(p, x, cfg1)
    y2, _ = moe_ffn(p, x, cfg2)
    # top-2 output differs (second expert contributes) and stays finite
    assert np.isfinite(np.asarray(y2)).all()
    assert np.abs(np.asarray(y2 - y1)).max() > 1e-6


def test_load_balance_aux_prefers_uniform():
    """The aux loss is minimized (=1) at a perfectly uniform router and
    larger for a collapsed router."""
    cfg = MoEConfig(hidden=8, ffn=16, n_experts=4, k=1)
    p = init_moe_params(cfg, seed=0)
    x = jax.random.normal(jax.random.PRNGKey(5), (256, 8))
    # collapsed router: all tokens to expert 0
    p_collapsed = dict(p)
    wg = np.zeros((8, 4), np.float32)
    wg[:, 0] = 10.0
    p_collapsed["wg"] = jnp.asarray(wg)
    _, aux_c = moe_ffn(p_collapsed, x, cfg)
    _, aux_r = moe_ffn(p, x, cfg)
    assert float(aux_c) > float(aux_r) >= 0.9  # collapsed ~= E, uniform ~= 1


def test_sharded_train_step_matches_single_device():
    """(dp=2, ep=4) GSPMD step == single-device step, and loss falls."""
    n = len(jax.devices())
    if n < 8:
        pytest.skip("needs the virtual 8-device mesh")
    cfg = MoEConfig(hidden=16, ffn=32, n_experts=4, k=1,
                    capacity_factor=2.0)
    params = init_moe_params(cfg, seed=0)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(8, 4, cfg.hidden).astype(np.float32))
    tgt = jnp.asarray(rng.rand(8, 4, cfg.hidden).astype(np.float32))

    mesh = make_mesh({"dp": 2, "ep": 4})
    step = make_moe_train_step(cfg, mesh, lr=0.05)
    p_sh = shard_moe_params(params, mesh)
    losses = []
    for _ in range(5):
        p_sh, loss = step(p_sh, x, tgt)
        losses.append(float(loss))
    assert losses[-1] < losses[0]

    # single-device reference: same math, no mesh
    def loss_fn(p):
        y, aux = moe_ffn(p, x, cfg)
        return jnp.mean(jnp.square(y - tgt).astype(jnp.float32)) + 0.01 * aux

    p_ref = init_moe_params(cfg, seed=0)
    ref_losses = []
    for _ in range(5):
        loss, grads = jax.value_and_grad(loss_fn)(p_ref)
        p_ref = jax.tree_util.tree_map(lambda a, g: a - 0.05 * g,
                                       p_ref, grads)
        ref_losses.append(float(loss))
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4, atol=1e-5)


def test_param_specs_cover_params():
    cfg = MoEConfig()
    assert set(moe_param_specs()) == set(init_moe_params(cfg))


def test_top2_overflow_keeps_gshard_weight():
    """When a token's FIRST choice overflows capacity, its second-choice
    output keeps weight g2/(g1+g2) — normalized BEFORE the drop (GShard),
    never amplified to 1.0.

    Construction (E=3, capacity=1, 2 tokens): both tokens pick e0 first;
    t0 wins the slot, t1's first pick drops. Second round: t0 -> e1,
    t1 -> e2 (distinct experts, both slots free), so t1's surviving
    output is EXACTLY its second choice at the normalized share."""
    cfg = MoEConfig(hidden=16, ffn=32, n_experts=3, k=2,
                    capacity_factor=0.01)
    assert cfg.capacity(2) == 1
    p = init_moe_params(cfg, seed=7)
    for k in ("w1", "b1", "w2", "b2"):
        p[k] = jnp.broadcast_to(p[k][:1], p[k].shape)
    wg = np.zeros((16, 3), np.float32)
    wg[0, 0] = wg[1, 1] = wg[2, 2] = 1.0  # logits = x[:, :3]
    p["wg"] = jnp.asarray(wg)
    x = np.zeros((2, 16), np.float32)
    x[0, :3] = [3.0, 2.0, 0.0]   # t0: e0 then e1
    x[1, :3] = [3.0, 0.0, 2.0]   # t1: e0 then e2
    x[:, 3:] = np.random.RandomState(0).rand(2, 13)
    x = jnp.asarray(x)
    y, _ = moe_ffn(p, x, cfg)
    dense = _dense_ffn(x, p["w1"][0], p["b1"][0], p["w2"][0], p["b2"][0])
    gates = np.asarray(jax.nn.softmax((x @ p["wg"]).astype(jnp.float32), -1))
    # t0 kept both choices: weights sum to 1 -> dense exactly
    np.testing.assert_allclose(np.asarray(y[0]), np.asarray(dense[0]),
                               rtol=2e-3, atol=2e-3)
    # t1: first choice dropped; second survives at g2/(g1+g2) ~ 0.27,
    # clearly distinguishable from the buggy amplified 1.0
    w2nd = gates[1, 2] / (gates[1, 0] + gates[1, 2] + 1e-9)
    assert 0.1 < w2nd < 0.5
    np.testing.assert_allclose(np.asarray(y[1]),
                               np.asarray(dense[1]) * w2nd,
                               rtol=2e-3, atol=2e-3)
