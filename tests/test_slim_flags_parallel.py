"""slim prune/distillation, global flags (check_nan_inf), and dygraph
DataParallel — reference ``contrib/slim/prune``, ``slim/distillation``,
``platform/flags``, ``dygraph/parallel.py`` per SURVEY §2."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.fluid.contrib.slim.distillation import (FSPDistiller,
                                                        L2Distiller,
                                                        SoftLabelDistiller,
                                                        merge)
from paddle_tpu.fluid.contrib.slim.prune import (StructurePruner,
                                                 sensitivity)

RNG = np.random.RandomState(0)


# ---------------------------------------------------------------- prune
def test_structure_pruner_masks_lowest_channels():
    w = np.stack([np.full((3, 3), 0.01, np.float32),
                  np.full((3, 3), 1.0, np.float32),
                  np.full((3, 3), 0.5, np.float32),
                  np.full((3, 3), 2.0, np.float32)])  # [4, 3, 3]
    scope = fluid.Scope()
    scope.set_var("w", w)
    pruner = StructurePruner()
    pruned = pruner.prune(None, scope, ["w"], [0.5])
    np.testing.assert_array_equal(sorted(pruned["w"]), [0, 2])
    out = np.asarray(scope.find_var("w"))
    assert (out[0] == 0).all() and (out[2] == 0).all()
    assert (out[1] == 1.0).all() and (out[3] == 2.0).all()
    # masks survive optimizer-style updates
    scope.set_var("w", np.asarray(scope.find_var("w")) + 0.3)
    pruner.apply_masks(scope)
    out = np.asarray(scope.find_var("w"))
    assert (out[0] == 0).all() and (out[3] == 2.3).all()
    assert pruner.flops_ratio("w") == 0.5


def test_pruned_conv_trains_with_dead_channels():
    """End to end: prune half a conv's filters, keep training, masked
    channels stay silent."""
    img = RNG.rand(4, 1, 8, 8).astype(np.float32)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [1, 8, 8])
        c = layers.conv2d(x, 4, 3, padding=1, name="pconv",
                          bias_attr=False)
        loss = layers.reduce_mean(layers.square(c))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        pruner = StructurePruner()
        pruner.prune(main, scope, ["pconv.w_0"], [0.5])
        for _ in range(3):
            exe.run(main, feed={"x": img}, fetch_list=[loss])
            pruner.apply_masks(scope)
        w = np.asarray(scope.find_var("pconv.w_0"))
        axis, mask = pruner._masks["pconv.w_0"]
        assert (w[mask == 0] == 0).all()
        assert np.abs(w[mask == 1]).sum() > 0


def test_sensitivity_analysis():
    scope = fluid.Scope()
    w = RNG.rand(8, 4).astype(np.float32)
    scope.set_var("w", w)

    def eval_fn():
        return float(np.abs(np.asarray(scope.find_var("w"))).sum())

    sens = sensitivity(None, scope, "w", [0.25, 0.5], eval_fn)
    assert sens[0.5] < sens[0.25] < 0  # pruning more loses more mass
    np.testing.assert_allclose(np.asarray(scope.find_var("w")), w)


# --------------------------------------------------------- distillation
def _student_teacher():
    teacher, t_startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(teacher, t_startup):
        x = layers.data("x", [4])
        t_logits = layers.fc(x, 3, name="t_fc")
    student, s_startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(student, s_startup):
        x = layers.data("x", [4])
        s_logits = layers.fc(x, 3, name="s_fc")
    return (teacher, t_startup, t_logits), (student, s_startup, s_logits)


def test_merge_and_soft_label_distillation():
    (teacher, t_startup, t_logits), (student, s_startup, s_logits) = \
        _student_teacher()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(t_startup)   # teacher params in scope
        merge(teacher, student, data_name_map={"x": "x"}, scope=scope)
        with fluid.program_guard(student, s_startup):
            dist = SoftLabelDistiller(s_logits.name,
                                      "teacher_" + t_logits.name,
                                      distillation_loss_weight=1.0)
            dloss = dist.distiller_loss(student)
            fluid.optimizer.SGD(learning_rate=0.5).minimize(dloss)
        exe.run(s_startup)
        x = RNG.rand(8, 4).astype(np.float32)
        t_w0 = np.asarray(scope.find_var("teacher_t_fc.w_0")).copy()
        losses = []
        for _ in range(20):
            (l,) = exe.run(student, feed={"x": x}, fetch_list=[dloss])
            losses.append(float(np.asarray(l)))
        assert losses[-1] < losses[0]  # student moves toward teacher
        # teacher stayed frozen
        np.testing.assert_allclose(
            np.asarray(scope.find_var("teacher_t_fc.w_0")), t_w0)


def test_l2_and_fsp_distillers_build():
    (teacher, t_startup, t_logits), (student, s_startup, s_logits) = \
        _student_teacher()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(t_startup)
        exe.run(s_startup)
        merge(teacher, student, data_name_map={"x": "x"}, scope=scope)
        with fluid.program_guard(student):
            l2 = L2Distiller(s_logits.name, "teacher_" + t_logits.name)
            loss = l2.distiller_loss(student)
        x = RNG.rand(8, 4).astype(np.float32)
        (lv,) = exe.run(student, feed={"x": x}, fetch_list=[loss])
        assert float(np.asarray(lv)) >= 0

    # FSP over two conv feature maps
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("img", [2, 8, 8])
        a = layers.conv2d(x, 4, 3, padding=1, name="fa")
        b = layers.conv2d(a, 4, 3, padding=1, name="fb")
        ta = layers.conv2d(x, 4, 3, padding=1, name="ta")
        tb = layers.conv2d(ta, 4, 3, padding=1, name="tb")
        fsp = FSPDistiller([(a.name, b.name)], [(ta.name, tb.name)])
        floss = fsp.distiller_loss(main)
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe = fluid.Executor()
        exe.run(startup)
        (fv,) = exe.run(main,
                        feed={"img": RNG.rand(2, 2, 8, 8).astype(
                            np.float32)},
                        fetch_list=[floss])
    assert float(np.asarray(fv)) >= 0


# ---------------------------------------------------------------- flags
def test_flags_roundtrip_and_check_nan_inf():
    fluid.set_flags({"FLAGS_check_nan_inf": True})
    assert fluid.get_flags("FLAGS_check_nan_inf")[
        "FLAGS_check_nan_inf"] is True
    try:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", [2])
            out = layers.log(x)  # log(-1) -> nan
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            with pytest.raises(FloatingPointError, match="check_nan_inf"):
                exe.run(main, feed={"x": -np.ones((1, 2), np.float32)},
                        fetch_list=[out])
            # clean values pass
            (r,) = exe.run(main,
                           feed={"x": np.ones((1, 2), np.float32)},
                           fetch_list=[out])
            np.testing.assert_allclose(np.asarray(r), 0.0, atol=1e-6)
    finally:
        fluid.set_flags({"FLAGS_check_nan_inf": False})


# ----------------------------------------------------- dygraph parallel
def test_dygraph_data_parallel_single_process():
    """nranks=1: wrapper is transparent — loss unscaled, grads intact."""
    from paddle_tpu.fluid import dygraph

    with dygraph.guard():
        layer = dygraph.nn.Linear(4, 2)
        model = dygraph.DataParallel(layer)
        env = dygraph.ParallelEnv()
        assert env.nranks == 1 and env.local_rank == 0
        x = dygraph.to_variable(RNG.rand(3, 4).astype(np.float32))
        out = model(x)
        loss = out.mean() if hasattr(out, "mean") else out
        from paddle_tpu.fluid.layers import reduce_mean  # noqa: F401
        scaled = model.scale_loss(loss)
        assert scaled is loss  # no scaling at nranks == 1
        model.apply_collective_grads()  # no-op, must not raise
        assert model.state_dict()  # passthrough to the wrapped layer


def test_pruner_physical_prune():
    """lazy=False actually deletes channels (shapes shrink, no mask)."""
    scope = fluid.Scope()
    scope.set_var("w", RNG.rand(8, 3).astype(np.float32))
    pruner = StructurePruner()
    pruner.prune(None, scope, ["w"], [0.25], lazy=False)
    assert np.asarray(scope.find_var("w")).shape == (6, 3)
    assert "w" not in pruner._masks


def test_parallel_env_reads_launcher_vars(monkeypatch):
    from paddle_tpu.fluid import dygraph

    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "4")
    monkeypatch.setenv("PADDLE_TRAINER_ID", "2")
    env = dygraph.ParallelEnv()
    assert env.nranks == 4 and env.local_rank == 2


# ------------------------------------------------------------------ nas
def test_sa_controller_and_light_nas():
    """SA search over a toy space converges toward the known optimum."""
    from paddle_tpu.fluid.contrib.slim.nas import LightNAS, SearchSpace

    target = [3, 1, 4, 1]

    class ToySpace(SearchSpace):
        def init_tokens(self):
            return [0, 0, 0, 0]

        def range_table(self):
            return [5, 5, 5, 5]

        def create_net(self, tokens):
            return tokens

    nas = LightNAS(ToySpace(), max_steps=120)
    best, reward = nas.search(
        lambda net: -sum(abs(a - b) for a, b in zip(net, target)))
    assert reward > -3  # walked most of the way to the optimum


def test_controller_server_round_trip():
    from paddle_tpu.fluid.contrib.slim.nas import (ControllerServer,
                                                   SearchAgent)
    from paddle_tpu.fluid.contrib.slim.searcher import SAController

    ctrl = SAController(seed=1)
    ctrl.reset([4, 4], [0, 0])
    server = ControllerServer(ctrl).start()
    try:
        agent = SearchAgent(server.ip(), server.port())
        tokens = agent.next_tokens()
        assert len(tokens) == 2 and all(0 <= t < 4 for t in tokens)
        agent.update(tokens, 7.5)
        assert agent.best_tokens() == tokens
        assert ctrl.max_reward == 7.5
    finally:
        server.close()


def test_sa_controller_respects_constraint():
    from paddle_tpu.fluid.contrib.slim.searcher import SAController

    ctrl = SAController(seed=2)
    ctrl.reset([10, 10], [2, 2], constrain_func=lambda t: sum(t) <= 6)
    for _ in range(50):
        t = ctrl.next_tokens()
        assert sum(t) <= 6
        ctrl.update(t, float(sum(t)))


# ----------------------------------------------------------- compressor
def test_compressor_runs_strategies_and_checkpoints(tmp_path):
    """Compressor drives epochs with strategy hooks; a prune strategy
    re-applies masks each batch; checkpoint/resume round-trips."""
    from paddle_tpu.fluid.contrib.slim.core import Compressor, Strategy

    rng2 = np.random.RandomState(1)
    X = rng2.rand(32, 4).astype(np.float32)
    Yv = (X @ rng2.rand(4, 1)).astype(np.float32)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [4])
        y = layers.data("y", [1])
        h = layers.fc(x, 8, act="relu", name="cfc")
        pred = layers.fc(h, 1)
        loss = layers.reduce_mean(layers.square(pred - y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    eval_prog = main._prune([loss])

    def reader():
        for i in range(0, 32, 8):
            yield {"x": X[i:i + 8], "y": Yv[i:i + 8]}

    calls = []

    class PruneStrategy(Strategy):
        def __init__(self):
            super().__init__(start_epoch=0, end_epoch=0)
            self.pruner = StructurePruner(pruning_axis={"*": 1})

        def on_compression_begin(self, ctx):
            calls.append("begin")
            self.pruner.prune(ctx.train_program, ctx.scope,
                              ["cfc.w_0"], [0.25])

        def on_batch_end(self, ctx):
            self.pruner.apply_masks(ctx.scope)

        def on_epoch_end(self, ctx):
            calls.append("epoch_%d" % ctx.epoch_id)

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        fluid.Executor().run(startup)
        comp = Compressor(
            scope=scope, train_program=main, train_reader=reader,
            train_fetch_list=[loss], eval_program=eval_prog,
            eval_reader=reader, eval_fetch_list=[loss], epoch=2,
            checkpoint_path=str(tmp_path / "ckpt"))
        comp.add_strategy(PruneStrategy())
        ctx = comp.run()
    assert calls == ["begin", "epoch_0", "epoch_1"]
    assert len(ctx.eval_results[loss.name]) == 2
    assert ctx.eval_results[loss.name][1] <= ctx.eval_results[loss.name][0]
    # pruned output channels (columns of the [in, out] fc weight)
    # stayed dead through training
    w = np.asarray(scope.find_var("cfc.w_0"))
    assert (np.abs(w).sum(axis=0) == 0).sum() == 2  # 25% of 8 channels

    # resume: a fresh Compressor picks up after the last epoch
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        fluid.Executor().run(startup)
        comp2 = Compressor(
            scope=scope2, train_program=main, train_reader=reader,
            train_fetch_list=[loss], epoch=2,
            checkpoint_path=str(tmp_path / "ckpt"))
        ctx2 = comp2.run()
    assert ctx2.epoch_id == 2  # resumed past the checkpointed epochs


def test_compressor_positional_feed_and_eval_model(tmp_path):
    """feed_list maps positional reader tuples; eval model exported."""
    from paddle_tpu.fluid.contrib.slim.core import Compressor

    rng2 = np.random.RandomState(2)
    X = rng2.rand(16, 3).astype(np.float32)
    Yv = (X @ rng2.rand(3, 1)).astype(np.float32)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [3])
        y = layers.data("y", [1])
        pred = layers.fc(x, 1)
        loss = layers.reduce_mean(layers.square(pred - y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    eval_prog = main._prune([pred])

    def reader():  # positional tuples, reference-style
        for i in range(0, 16, 8):
            yield (X[i:i + 8], Yv[i:i + 8])

    def eval_reader():
        yield (X,)

    path = str(tmp_path / "eval_model")
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        fluid.Executor().run(startup)
        Compressor(scope=scope, train_program=main, train_reader=reader,
                   train_feed_list=["x", "y"], train_fetch_list=[loss],
                   eval_program=eval_prog, eval_reader=eval_reader,
                   eval_feed_list=["x"], eval_fetch_list=[pred],
                   epoch=1, eval_model_path=path).run()
        prog2, feeds, fetches = fluid.io.load_inference_model(
            path, fluid.Executor())
        assert feeds == ["x"]
