/* C driver for the native ProgramDesc IR (prg_* ABI, libprogram_graph.so)
 * — the reference proves its desc/prune tier from C++ gtest; this does
 * the same from plain C with no Python in the translation unit.
 * Usage: c_program_main <model_bytes_file> <target_var>
 * Parses the wire bytes, lints, prunes to the target, round-trips the
 * pruned program, and prints counts + "C_PROGRAM_OK". */

#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "../paddle_tpu/native/c_api.h"

static char* read_file(const char* path, int64_t* len) {
  FILE* f = fopen(path, "rb");
  if (!f) return NULL;
  fseek(f, 0, SEEK_END);
  *len = ftell(f);
  fseek(f, 0, SEEK_SET);
  char* buf = (char*)malloc(*len > 0 ? (size_t)*len : 1);
  if (fread(buf, 1, (size_t)*len, f) != (size_t)*len) {
    fclose(f);
    free(buf);
    return NULL;
  }
  fclose(f);
  return buf;
}

int main(int argc, char** argv) {
  if (argc < 3) {
    fprintf(stderr, "usage: %s model_bytes_file target_var\n", argv[0]);
    return 2;
  }
  int64_t len = 0;
  char* bytes = read_file(argv[1], &len);
  if (!bytes) {
    fprintf(stderr, "cannot read %s\n", argv[1]);
    return 3;
  }

  int64_t h = prg_parse(bytes, len);
  free(bytes);
  if (!h) {
    fprintf(stderr, "parse failed: %s\n", prg_last_error());
    return 4;
  }
  int64_t blocks = prg_num_blocks(h);
  int64_t ops = prg_num_ops(h, 0);
  int64_t vars = prg_num_vars(h, 0);
  printf("blocks=%lld ops=%lld vars=%lld version=%lld\n",
         (long long)blocks, (long long)ops, (long long)vars,
         (long long)prg_version(h));
  if (blocks < 1 || ops < 1 || vars < 1) return 5;

  char* report = NULL;
  int64_t issues = prg_lint(h, &report);
  int defects = 0;
  if (report) {
    defects = strstr(report, "E: ") != NULL;
    prg_free(report);
  }
  if (issues < 0 || defects) {
    fprintf(stderr, "lint found defects\n");
    return 6;
  }

  const char* targets[1] = {argv[2]};
  int64_t ph = prg_prune(h, targets, 1);
  if (!ph) {
    fprintf(stderr, "prune failed: %s\n", prg_last_error());
    return 7;
  }
  int64_t pruned_ops = prg_num_ops(ph, 0);
  printf("pruned_ops=%lld\n", (long long)pruned_ops);
  if (pruned_ops < 1 || pruned_ops > ops) return 8;

  /* round-trip the pruned program through serialize -> parse */
  char* out = NULL;
  int64_t out_len = 0;
  if (prg_serialize(ph, &out, &out_len) != 0) return 9;
  int64_t rt = prg_parse(out, out_len);
  prg_free(out);
  if (!rt || prg_num_ops(rt, 0) != pruned_ops) return 10;

  char type0[256];
  if (prg_op_type(rt, 0, 0, type0, sizeof(type0)) != 0) return 11;
  printf("first_pruned_op=%s\n", type0);

  char* dot = NULL;
  if (prg_to_dot(rt, 0, &dot) != 0) return 12;
  int has_digraph = strncmp(dot, "digraph", 7) == 0;
  prg_free(dot);
  if (!has_digraph) return 13;

  char* plan = NULL;
  if (prg_last_use(h, 0, &plan) != 0) return 14;
  prg_free(plan);

  prg_destroy(rt);
  prg_destroy(ph);
  prg_destroy(h);
  printf("C_PROGRAM_OK\n");
  return 0;
}
