"""Chaos gang runner: a 2-process data-parallel training gang whose
per-step lockstep goes through the DURABLE coordination service — a
generation-numbered barrier every step plus a held lease — while the
parent test SIGKILLs the coordinator mid-run and restarts it on the
same port against the same WAL dir. The gang must ride the outage
(reconnecting clients, journaled barrier state) and finish with
bit-identical weights on every rank.

Prints one ``STEP i gen g`` line per step, then ``EPOCH n`` (the
server incarnation the client ended on — proves the restart happened
under this run) and ``WDIGEST <sha256>`` of the final weights.

Run with PADDLE_COORD_ADDR pointing at a durable standalone
coordinator and PADDLE_TRAINER_ID/PADDLE_TRAINERS_NUM set.
"""

import hashlib
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

assert os.environ.get("PADDLE_COORD_ADDR"), \
    "runner requires a TCP coordination service (PADDLE_COORD_ADDR)"

from paddle_tpu.distributed import env as dist_env  # noqa: E402

rank, world = dist_env.init_parallel_env(ndev_per_proc=1)

import numpy as np  # noqa: E402

import paddle_tpu.fluid as fluid  # noqa: E402
from paddle_tpu.distributed import coordination  # noqa: E402
from paddle_tpu.fluid import layers, optimizer  # noqa: E402

STEPS = 8


def build(seed=17):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(x, size=16, act="relu",
                      param_attr=fluid.ParamAttr(name="cg_w1"))
        logits = layers.fc(h, size=4,
                           param_attr=fluid.ParamAttr(name="cg_w2"))
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def main():
    cli = coordination.CoordClient(
        coordination.current_coord_addr(), grace=240.0)
    cid = "gang/r%d" % rank
    cli.start_lease_keeper(cid, ttl=5.0, interval=0.5)
    main_p, startup, loss = build()
    compiled = fluid.CompiledProgram(main_p).with_data_parallel(
        loss_name=loss.name)
    exe = fluid.Executor()
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(16, 8).astype(np.float32),
            "label": rng.randint(0, 4, (16, 1)).astype(np.int64)}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for i in range(STEPS):
            (lv,) = exe.run(compiled, feed=feed, fetch_list=[loss])
            assert np.isfinite(np.asarray(lv)).all()
            # paced so the parent's kill window reliably lands mid-run
            time.sleep(0.4)
            gen = cli.barrier("chaos/step%d" % i, world,
                              "r%d" % rank, timeout=300.0)
            print("STEP %d gen %d" % (i, gen), flush=True)
        w = np.asarray(exe.run(compiled, feed=feed,
                               fetch_list=["cg_w1"])[0])
    # the keeper's lease survived the restart (replayed on reconnect)
    assert cid in cli.live(), cli.live()
    print("EPOCH %d" % cli.server_epoch, flush=True)
    print("WDIGEST %s"
          % hashlib.sha256(np.ascontiguousarray(w).tobytes()).hexdigest(),
          flush=True)
    cli.close()


if __name__ == "__main__":
    main()
