"""Sparse embedding engine (paddle_tpu.embedding) — the mesh-sharded
device tier (dedup-gather + fused row-sparse optimizer updates) and the
host-offloaded tier (host-RAM table behind a fixed HBM resident cache with
LRU/TTL eviction, write-back, and async prefetch)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import embedding
from paddle_tpu.fluid import layers, monitor, optimizer, unique_name
from paddle_tpu.models import deepfm

pytestmark = pytest.mark.embedding


@pytest.fixture(autouse=True)
def _clean_tables():
    embedding.reset_tables()
    yield
    embedding.reset_tables()


def _tiny_cfg():
    # vocab is 10x the host budget used below (64) and the model compiles
    # fast enough for tier-1
    return deepfm.DeepFMConfig(sparse_feature_dim=640, num_fields=4,
                               num_dense=3, embedding_size=4,
                               fc_sizes=(16,))


# -- device tier ------------------------------------------------------------


def test_dedup_gather_matches_naive_bit_identical():
    """The dedup path (unique -> gather unique rows -> index back) copies
    rows, never recomputes: bit-identical to the naive full gather."""
    vocab, dim = 30, 5
    rng = np.random.RandomState(0)
    w0 = rng.randn(vocab, dim).astype(np.float32)
    ids = np.array([[3, 3, 7, 29], [0, 7, 3, 0]], np.int64)  # duplicates
    outs = {}
    for sparse in (True, False):  # True -> embedding_lookup, False -> naive
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            iv = layers.data("ids", shape=[4], dtype="int64")
            emb = layers.embedding(iv, size=[vocab, dim], is_sparse=sparse,
                                   param_attr=fluid.ParamAttr(name="w"))
        exe = fluid.Executor()
        sc = fluid.Scope()
        with fluid.scope_guard(sc):
            exe.run(startup)
            sc.set_var("w", w0)
            outs[sparse], = exe.run(main, feed={"ids": ids},
                                    fetch_list=[emb.name])
    op_types = [op.type for op in main.global_block().ops]
    assert "lookup_table" in op_types  # the naive reference really is naive
    np.testing.assert_array_equal(np.asarray(outs[True]),
                                  np.asarray(outs[False]))
    np.testing.assert_array_equal(np.asarray(outs[False]),
                                  w0[ids])


def _build_emb_train(opt_factory, is_sparse, vocab=40, dim=3, seed=11):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[3], dtype="int64")
        emb = layers.embedding(ids, size=[vocab, dim], is_sparse=is_sparse,
                               param_attr=fluid.ParamAttr(name="w_t"))
        loss = layers.mean(layers.reduce_sum(emb * emb, dim=-1))
        opt_factory().minimize(loss)
    return main, startup, loss


@pytest.mark.parametrize("opt_factory", [
    lambda: optimizer.Momentum(learning_rate=0.2, momentum=0.9),
    lambda: optimizer.Momentum(learning_rate=0.2, momentum=0.9,
                               use_nesterov=True),
    lambda: optimizer.Adagrad(learning_rate=0.2),
], ids=["momentum", "nesterov", "adagrad"])
def test_fused_sparse_update_matches_dense(opt_factory):
    """The fused unique+segment-sum+scatter row update must reproduce the
    dense step on touched rows and freeze untouched rows (params AND
    slots). Duplicate ids in the batch must accumulate."""
    feed = {"ids": np.array([[2, 9, 9], [2, 2, 31]], np.int64)}
    res = {}
    for sparse in (False, True):
        main, startup, loss = _build_emb_train(opt_factory, sparse)
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            w0 = np.asarray(exe.run(main, feed=feed,
                                    fetch_list=["w_t"])[0])
            for _ in range(2):
                w1 = np.asarray(exe.run(main, feed=feed,
                                        fetch_list=["w_t"])[0])
        res[sparse] = (w0, w1)
    np.testing.assert_allclose(res[True][0], res[False][0], atol=1e-6)
    np.testing.assert_allclose(res[True][1], res[False][1], atol=1e-6)
    w0, w1 = res[True]
    untouched = np.setdiff1d(np.arange(40), [2, 9, 31])
    np.testing.assert_array_equal(w1[untouched], w0[untouched])
    assert np.abs(w1[[2, 9, 31]] - w0[[2, 9, 31]]).max() > 0


def test_sharded_table_on_mesh_matches_replicated():
    """ShardedEmbeddingTable rows sharded over a mesh axis: same loss
    trajectory as the single-device run (GSPMD partial gather +
    all-reduce is numerically a gather)."""
    vocab, dim = 64, 4  # 64 rows over 8 devices

    def build(mesh_axis):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 5
        with fluid.program_guard(main, startup):
            ids = layers.data("ids", shape=[6], dtype="int64")
            table = embedding.ShardedEmbeddingTable(
                "sh_emb", vocab, dim, mesh_axis=mesh_axis)
            emb = table.lookup(ids)
            loss = layers.mean(layers.reduce_sum(emb * emb, dim=-1))
            optimizer.SGD(learning_rate=0.5).minimize(loss)
        return main, startup, loss

    feed = {"ids": np.array([[1, 8, 17, 33, 63, 1],
                             [2, 9, 17, 40, 0, 2]], np.int64)}
    main, startup, loss = build(None)
    exe = fluid.Executor()
    base = []
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(3):
            lv, = exe.run(main, feed=feed, fetch_list=[loss])
            base.append(float(np.asarray(lv)))

    main2, startup2, loss2 = build("dp")
    w = main2.global_block().var("sh_emb")
    assert w.shard_spec == ("dp", None)
    compiled = fluid.CompiledProgram(main2).with_data_parallel(
        loss_name=loss2.name, mesh_axes=("dp",), mesh_shape={"dp": 8})
    got = []
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup2)
        for _ in range(3):
            lv, = exe.run(compiled, feed=feed, fetch_list=[loss2])
            got.append(float(np.asarray(lv)))
    np.testing.assert_allclose(base, got, rtol=1e-5)


# -- host tier: residency engine unit tests ---------------------------------


def _cache_scope(table, slot_map=()):
    """A scope holding the table's device cache (+ slot arrays), as the
    startup program would leave it."""
    import jax.numpy as jnp

    sc = fluid.Scope()
    sc.set_var(table.name + "@CACHE",
               jnp.zeros((table.budget + 1, table.dim), table.dtype))
    for dev in dict(slot_map):
        sc.set_var(dev, jnp.zeros((table.budget + 1, table.dim),
                                  table.dtype))
    return sc


def test_host_table_validation():
    with pytest.raises(ValueError, match="num_rows and dim"):
        embedding.HostEmbeddingTable("t0", 0, 4, resident_budget=2,
                                     register=False)
    with pytest.raises(ValueError, match="resident_budget"):
        embedding.HostEmbeddingTable("t0", 8, 4, resident_budget=0,
                                     register=False)
    with pytest.raises(ValueError, match="ttl_steps"):
        embedding.HostEmbeddingTable("t0", 8, 4, resident_budget=2,
                                     ttl_steps=0, register=False)
    t = embedding.HostEmbeddingTable("t0", 8, 4, resident_budget=2)
    with pytest.raises(ValueError, match="already registered"):
        embedding.HostEmbeddingTable("t0", 8, 4, resident_budget=2)
    with pytest.raises(ValueError, match="load expects shape"):
        t.load(np.zeros((3, 4), np.float32))
    with pytest.raises(ValueError, match="cannot shrink"):
        t.grow(4)
    with pytest.raises(KeyError, match="no host embedding table registered"):
        embedding.get_host_table("nope")


def test_lru_eviction_with_writeback():
    """Filling the cache past budget evicts the least-recently-used rows,
    writing their device values back to the host store first."""
    import jax.numpy as jnp

    t = embedding.HostEmbeddingTable("lru_t", 12, 2, resident_budget=4,
                                     register=False)
    sc = _cache_scope(t)
    cache = "lru_t@CACHE"
    s01 = t.prepare(np.array([0, 1]), sc, cache, {})
    t.prepare(np.array([2, 3]), sc, cache, {})
    assert t.resident_count == 4
    # mark rows 0/1 as device-updated, then touch 2/3 so 0/1 are the LRU
    marked = jnp.asarray(sc.find_var(cache))
    marked = marked.at[s01.reshape(-1)].set(7.0)
    sc.set_var(cache, marked)
    t.prepare(np.array([2, 3]), sc, cache, {})
    before = monitor.counter("embedding_evictions_total",
                             labels={"table": "lru_t"}).value
    t.prepare(np.array([4, 5]), sc, cache, {})  # needs 2 slots -> evict 0,1
    after = monitor.counter("embedding_evictions_total",
                            labels={"table": "lru_t"}).value
    assert after - before == 2
    assert t.resident_count == 4
    np.testing.assert_array_equal(t._values[[0, 1]],
                                  np.full((2, 2), 7.0, np.float32))
    # evicted rows come back with the written-back values
    s0 = t.prepare(np.array([0]), sc, cache, {})
    got = np.asarray(sc.find_var(cache))[int(s0.ravel()[0])]
    np.testing.assert_array_equal(got, np.full(2, 7.0, np.float32))


def test_ttl_eviction_expires_idle_rows():
    """ttl_steps evicts rows idle longer than the TTL even when slots are
    free — dynamic-vocabulary hygiene, not capacity pressure."""
    t = embedding.HostEmbeddingTable("ttl_t", 16, 2, resident_budget=8,
                                     ttl_steps=2, register=False)
    sc = _cache_scope(t)
    cache = "ttl_t@CACHE"
    t.prepare(np.array([0, 1]), sc, cache, {})        # tick 1
    t.prepare(np.array([2]), sc, cache, {})           # tick 2
    t.prepare(np.array([2]), sc, cache, {})           # tick 3
    assert t.resident_count == 3
    t.prepare(np.array([2]), sc, cache, {})           # tick 4: 0,1 idle 3 > 2
    assert t.resident_count == 1
    assert monitor.counter("embedding_evictions_total",
                           labels={"table": "ttl_t"}).value >= 2


def test_budget_too_small_for_batch_raises():
    t = embedding.HostEmbeddingTable("small_t", 32, 2, resident_budget=3,
                                     register=False)
    sc = _cache_scope(t)
    with pytest.raises(RuntimeError, match="cannot hold one batch"):
        t.prepare(np.array([0, 1, 2, 3]), sc, "small_t@CACHE", {})


def test_out_of_range_id_raises_clear_error():
    t = embedding.HostEmbeddingTable("rng_t", 10, 2, resident_budget=4,
                                     register=False)
    sc = _cache_scope(t)
    with pytest.raises(IndexError, match="id 10 out of range .* 10 rows"):
        t.prepare(np.array([0, 10]), sc, "rng_t@CACHE", {})
    with pytest.raises(IndexError, match="out of range"):
        t.prepare(np.array([-1]), sc, "rng_t@CACHE", {})


def test_prefetch_hit_and_miss_counters():
    t = embedding.HostEmbeddingTable("pf_t", 64, 2, resident_budget=16,
                                     register=False)
    sc = _cache_scope(t)
    cache = "pf_t@CACHE"
    t.prepare(np.array([0, 1]), sc, cache, {})  # cold: misses
    miss0 = monitor.counter("embedding_prefetch_miss_total",
                            labels={"table": "pf_t"}).value
    assert miss0 == 2
    t.prefetch(np.array([5, 6, 7]))
    t.prepare(np.array([5, 6, 7]), sc, cache, {})  # staged: all hits
    hit = monitor.counter("embedding_prefetch_hit_total",
                          labels={"table": "pf_t"}).value
    assert hit == 3
    assert monitor.counter("embedding_prefetch_miss_total",
                           labels={"table": "pf_t"}).value == miss0
    t.close()


# -- host tier: end-to-end through Executor.run -----------------------------


def _host_train(cfg, budget, steps, feeds, iters=None, table_seed=3,
                grow_to=None, grow_after=None):
    """Train DeepFM with fm_emb on a HostEmbeddingTable; returns
    (losses, table, initial fm_emb values)."""
    table = embedding.HostEmbeddingTable(
        "fm_emb", num_rows=cfg.sparse_feature_dim, dim=cfg.embedding_size,
        resident_budget=budget, seed=table_seed)
    init_vals = table.snapshot().copy()
    with unique_name.guard():
        main, startup, loss, _ = deepfm.build_train_program(
            cfg, residence="host")
    exe = fluid.Executor()
    losses = []
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for i, feed in enumerate(feeds[:steps]):
            if grow_after is not None and i == grow_after:
                table.grow(grow_to)
            if iters:
                out, = exe.run(main, feed=feed, fetch_list=[loss.name],
                               iters=iters)
                losses.extend(float(v) for v in np.asarray(out).ravel())
            else:
                out, = exe.run(main, feed=feed, fetch_list=[loss.name])
                losses.append(float(np.asarray(out).ravel()[0]))
    return losses, table, init_vals


def test_host_offload_matches_in_hbm_training():
    """Acceptance: DeepFM with a host table 10x the resident budget tracks
    the all-in-HBM loss trajectory exactly (fp32 CPU), with evictions
    actually happening along the way."""
    cfg = _tiny_cfg()
    feeds = [deepfm.synthetic_batch(cfg, 16, seed=i) for i in range(5)]
    assert cfg.sparse_feature_dim >= 10 * 64
    host_losses, table, init_vals = _host_train(cfg, budget=64, steps=5,
                                                feeds=feeds)
    assert monitor.counter("embedding_evictions_total",
                           labels={"table": "fm_emb"}).value > 0
    embedding.reset_tables()

    with unique_name.guard():
        main, startup, loss, _ = deepfm.build_train_program(cfg)
    exe = fluid.Executor()
    base_losses = []
    sc = fluid.Scope()
    with fluid.scope_guard(sc):
        exe.run(startup)
        sc.set_var("fm_emb", init_vals)
        for feed in feeds:
            out, = exe.run(main, feed=feed, fetch_list=[loss.name])
            base_losses.append(float(np.asarray(out).ravel()[0]))
    np.testing.assert_allclose(host_losses, base_losses, rtol=1e-6,
                               atol=1e-7)


def test_vocab_growth_never_retraces():
    """grow() extends the host store only; the compiled step is keyed on
    the budget, so feeding ids from the grown range adds ZERO compile
    cache misses."""
    vocab = 320
    table = embedding.HostEmbeddingTable("grow_w", vocab, 4,
                                         resident_budget=32, seed=3)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 9
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[4], dtype="int64")
        emb = layers.embedding(ids, size=[vocab, 4], is_sparse=True,
                               residence="host",
                               param_attr=fluid.ParamAttr(name="grow_w"))
        loss = layers.mean(layers.reduce_sum(emb * emb, dim=-1))
        optimizer.Adam(learning_rate=0.01).minimize(loss)
    rng = np.random.RandomState(2)
    misses = monitor.counter("executor_compile_cache_miss_total")
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(3):
            feed = {"ids": rng.randint(0, vocab, (8, 4)).astype(np.int64)}
            exe.run(main, feed=feed, fetch_list=[loss.name])
        warm = misses.value
        table.grow(2 * vocab)
        for _ in range(3):
            # ids exclusively from the grown range [vocab, 2*vocab)
            feed = {"ids": rng.randint(vocab, 2 * vocab,
                                       (8, 4)).astype(np.int64)}
            out, = exe.run(main, feed=feed, fetch_list=[loss.name])
            assert np.isfinite(float(np.asarray(out).ravel()[0]))
    assert misses.value == warm, "vocabulary growth retraced the program"
    assert table.num_rows == 2 * vocab


def test_host_iters_window_matches_single_steps():
    """iters=k windows route through one residency transaction covering
    the whole window; the k stacked losses match k single-step runs."""
    cfg = _tiny_cfg()
    singles = [deepfm.synthetic_batch(cfg, 8, seed=i) for i in range(4)]
    single_losses, _, init_vals = _host_train(cfg, budget=64, steps=4,
                                              feeds=singles)
    embedding.reset_tables()

    windows = []
    for w in range(2):
        pair = singles[2 * w:2 * w + 2]
        windows.append({k: np.stack([p[k] for p in pair])
                        for k in pair[0]})
    table = embedding.HostEmbeddingTable(
        "fm_emb", num_rows=cfg.sparse_feature_dim, dim=cfg.embedding_size,
        resident_budget=64, seed=3)
    table.load(init_vals)
    with unique_name.guard():
        main, startup, loss, _ = deepfm.build_train_program(
            cfg, residence="host")
    exe = fluid.Executor()
    window_losses = []
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for feed in windows:
            out, = exe.run(main, feed=feed, fetch_list=[loss.name],
                           iters=2)
            window_losses.extend(float(v) for v in np.asarray(out).ravel())
    np.testing.assert_allclose(window_losses, single_losses, rtol=1e-6,
                               atol=1e-7)


def test_prefetch_overlap_through_program_bindings():
    """embedding.prefetch(program, next_feed) stages the next batch's
    missing rows in the background; the next run consumes them as hits."""
    cfg = _tiny_cfg()
    embedding.HostEmbeddingTable(
        "fm_emb", num_rows=cfg.sparse_feature_dim, dim=cfg.embedding_size,
        resident_budget=64, seed=3)
    with unique_name.guard():
        main, startup, loss, _ = deepfm.build_train_program(
            cfg, residence="host")
    exe = fluid.Executor()
    feeds = [deepfm.synthetic_batch(cfg, 8, seed=i) for i in range(3)]
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for i, feed in enumerate(feeds):
            exe.run(main, feed=feed, fetch_list=[loss.name])
            if i + 1 < len(feeds):
                embedding.prefetch(main, feeds[i + 1])
    hits = monitor.counter("embedding_prefetch_hit_total",
                           labels={"table": "fm_emb"}).value
    assert hits > 0, "prefetched rows were never consumed as hits"
    ratio = monitor.gauge("embedding_unique_ratio",
                          labels={"table": "fm_emb"}).value
    assert 0 < ratio <= 1
    lookup_h = monitor.histogram("embedding_lookup_seconds",
                                 labels={"table": "fm_emb"})
    assert lookup_h.count >= 3 and lookup_h.quantile(0.5) is not None


def test_missing_ids_feed_raises():
    cfg = _tiny_cfg()
    embedding.HostEmbeddingTable(
        "fm_emb", num_rows=cfg.sparse_feature_dim, dim=cfg.embedding_size,
        resident_budget=64)
    with unique_name.guard():
        main, startup, loss, _ = deepfm.build_train_program(
            cfg, residence="host")
    exe = fluid.Executor()
    feed = deepfm.synthetic_batch(cfg, 4)
    feed.pop("sparse_ids")
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        with pytest.raises(KeyError, match="sparse_ids"):
            exe.run(main, feed=feed, fetch_list=[loss.name])


def test_deepfm_out_of_range_id_raises_at_lookup():
    """Satellite regression: a corrupt feed fails loudly at the lookup,
    not as a silent clamped gather."""
    cfg = _tiny_cfg()
    embedding.HostEmbeddingTable(
        "fm_emb", num_rows=cfg.sparse_feature_dim, dim=cfg.embedding_size,
        resident_budget=64)
    with unique_name.guard():
        main, startup, loss, _ = deepfm.build_train_program(
            cfg, residence="host")
    exe = fluid.Executor()
    feed = deepfm.synthetic_batch(cfg, 4)
    feed["sparse_ids"][0, 0] = cfg.sparse_feature_dim  # one past the end
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        with pytest.raises(IndexError, match="out of range for table"):
            exe.run(main, feed=feed, fetch_list=[loss.name])


# -- satellites -------------------------------------------------------------


def test_deepfm_config_validates_dimensions():
    for kwargs in ({"sparse_feature_dim": 0}, {"num_fields": 0},
                   {"embedding_size": -1}, {"num_dense": 0},
                   {"sparse_feature_dim": "100"}):
        with pytest.raises(ValueError, match="must be an int >= 1"):
            deepfm.DeepFMConfig(**kwargs)


def test_synthetic_batch_ids_in_vocab():
    cfg = deepfm.DeepFMConfig(sparse_feature_dim=17, num_fields=3,
                              num_dense=2, embedding_size=4)
    for seed in range(3):
        ids = deepfm.synthetic_batch(cfg, 64, seed=seed)["sparse_ids"]
        assert ids.min() >= 0 and ids.max() < 17


def test_distribute_lookup_table_is_deprecated_reexport():
    from paddle_tpu.embedding.lookup import find_distributed_lookup_table
    from paddle_tpu.fluid import distribute_lookup_table as legacy

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[2], dtype="int64")
        layers.embedding(ids, size=[32, 4], is_distributed=True,
                         param_attr=fluid.ParamAttr(name="dist_w"))
    assert find_distributed_lookup_table(main) == "dist_w"
    with pytest.warns(DeprecationWarning, match="paddle_tpu.embedding"):
        assert legacy.find_distributed_lookup_table(main) == "dist_w"


def test_find_sparse_lookup_ops_covers_all_tiers():
    from paddle_tpu.embedding.lookup import (find_host_lookup_ops,
                                             find_sparse_lookup_ops)

    embedding.HostEmbeddingTable("h_w", 32, 4, resident_budget=8)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[2], dtype="int64")
        layers.embedding(ids, size=[32, 4], is_sparse=True,
                         param_attr=fluid.ParamAttr(name="dev_w"))
        layers.embedding(ids, size=[32, 4], is_sparse=True,
                         residence="host",
                         param_attr=fluid.ParamAttr(name="h_w"))
        layers.embedding(ids, size=[32, 4], is_sparse=False,
                         param_attr=fluid.ParamAttr(name="dense_w"))
    sparse = find_sparse_lookup_ops(main)
    assert sorted(op.type for op in sparse) == ["embedding_lookup",
                                                "host_embedding_lookup"]
    assert [op.type for op in find_host_lookup_ops(main)] == \
        ["host_embedding_lookup"]
